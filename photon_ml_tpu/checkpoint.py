"""Iteration-level checkpointing of coordinate-descent training.

Reference parity-plus: the reference has NO optimizer-state checkpointing —
only model warm start from a saved directory (SURVEY.md §5.4, which notes
the TPU build "should exceed the reference here"). This module checkpoints
the full GAME model plus descent progress after every outer iteration, so a
preempted job resumes mid-descent instead of restarting (TPU preemption is
routine; Spark lineage recovery has no analog here).

Format: one ``.npz`` per checkpoint holding every coordinate's arrays +
a JSON sidecar with progress (outer iteration, task type, coordinate
metadata). Writes are atomic (tmp + rename), keeping the last checkpoint
valid under preemption mid-write.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.types import TaskType


@dataclass(frozen=True)
class DescentCheckpoint:
    """A resumable descent state: the model + the NEXT outer iteration.

    ``scores``/``total`` (when present) restore the residual-exchange state
    bit-exactly: recomputing scores from the model reproduces them only up
    to float re-association, and the per-entity solvers amplify that
    epsilon into visible coefficient drift. Storing the accumulated arrays
    makes an interrupted+resumed run bitwise identical to an uninterrupted
    one.

    ``next_coordinate`` refines the resume point to mid-outer-iteration
    granularity (the streamed GAME trainer checkpoints after every
    coordinate VISIT, not just every outer iteration — a visit can be hours
    at the 1B-row scale): resume restarts at coordinate index
    ``next_coordinate`` of outer iteration ``next_iteration``."""

    model: GameModel
    next_iteration: int
    scores: dict[str, np.ndarray] | None = None
    total: np.ndarray | None = None
    next_coordinate: int = 0
    # the fingerprint the checkpoint was WRITTEN under — callers that
    # accept a collection (peer-loss recovery) use it to tell whether
    # the resumed state comes from a foreign layout (and so whether the
    # stored global row ids need the pre-loss base for slicing)
    fingerprint: str | None = None


_SCORE_PREFIX = "__score__"
_TOTAL_KEY = "__total__"
_META_KEY = "__meta__"

_log = logging.getLogger(__name__)


def batch_digest(labels, weights) -> str:
    """Cheap value digest of a batch (head/tail label samples + moments),
    used to tie a checkpoint's residual-exchange ``scores``/``total`` to the
    data they were computed on. Avoids an O(n) host transfer of the
    device-resident arrays."""
    import hashlib

    import jax.numpy as jnp

    head = np.asarray(labels[:256])
    tail = np.asarray(labels[-256:])
    return hashlib.sha256(
        head.tobytes()
        + tail.tobytes()
        + np.float64(jnp.sum(labels)).tobytes()
        + np.float64(jnp.sum(weights)).tobytes()
    ).hexdigest()


def save_checkpoint(
    directory: str,
    model: GameModel,
    next_iteration: int,
    fingerprint: str | None = None,
    scores: dict[str, np.ndarray] | None = None,
    total: np.ndarray | None = None,
    data_digest: str | None = None,
    next_coordinate: int = 0,
) -> None:
    """``fingerprint`` identifies the training setup (configuration + data
    signature); ``load_checkpoint`` refuses checkpoints whose fingerprint
    differs, so rerunning into the same directory after changing the grid,
    hyperparameters, or data retrains instead of silently short-circuiting."""
    os.makedirs(directory, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    meta: dict = {
        "task_type": model.task_type.value,
        "next_iteration": next_iteration,
        "next_coordinate": next_coordinate,
        "fingerprint": fingerprint,
        "data_digest": data_digest,
        "coordinates": {},
    }
    for cid, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            arrays[f"{cid}__means"] = np.asarray(sub.model.coefficients.means)
            if sub.model.coefficients.variances is not None:
                arrays[f"{cid}__variances"] = np.asarray(
                    sub.model.coefficients.variances
                )
            meta["coordinates"][cid] = {
                "type": "fixed",
                "feature_shard_id": sub.feature_shard_id,
            }
        elif isinstance(sub, RandomEffectModel):
            arrays[f"{cid}__means"] = np.asarray(sub.coefficients)
            if sub.variances is not None:
                arrays[f"{cid}__variances"] = np.asarray(sub.variances)
            meta["coordinates"][cid] = {
                "type": "random",
                "feature_shard_id": sub.feature_shard_id,
                "random_effect_type": sub.random_effect_type,
            }
        else:  # pragma: no cover
            raise TypeError(f"unknown sub-model {type(sub)}")

    if scores is not None and total is not None:
        for cid, s in scores.items():
            arrays[f"{_SCORE_PREFIX}{cid}"] = np.asarray(s)
        arrays[_TOTAL_KEY] = np.asarray(total)
        meta["has_scores"] = True

    # The metadata lives INSIDE the npz so the checkpoint is one file and
    # one atomic rename — a sidecar json renamed separately would leave a
    # mixed-generation checkpoint if preempted between the two renames.
    arrays[_META_KEY] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)

    # durable commit (fsync → rename → dir fsync): os.replace alone is
    # atomic only in the namespace; a preemption between rename and
    # writeback could otherwise leave a truncated npz under the final name
    from photon_ml_tpu.utils.atomic_io import atomic_savez

    atomic_savez(directory, os.path.join(directory, "ckpt.npz"), arrays)
    # human-readable sidecar, informational only — never read back
    with open(os.path.join(directory, "ckpt.json"), "w") as f:
        json.dump(meta, f)


def peek_fingerprint(directory: str) -> str | None:
    """The fingerprint the stored checkpoint was written under, read
    from the npz's embedded metadata WITHOUT materializing any model
    arrays (``np.load`` is lazy per entry). None when there is no
    checkpoint or no metadata. This is what a degraded or rejoining
    restart feeds into its resume allow-list (``resume_fingerprints``)
    so a foreign layout's checkpoint is accepted instead of silently
    retraining — previously the drills scraped it from the
    human-readable ``ckpt.json`` sidecar, which is documented as
    informational-only and never read back."""
    npz_path = os.path.join(directory, "ckpt.npz")
    if not os.path.exists(npz_path):
        return None
    try:
        with np.load(npz_path) as z:
            if _META_KEY not in z.files:
                return None
            meta = json.loads(bytes(z[_META_KEY]).decode())
    except Exception:
        return None
    return meta.get("fingerprint")


def load_checkpoint(
    directory: str,
    fingerprint: str | Sequence[str] | None = None,
    data_digest: str | None = None,
) -> DescentCheckpoint | None:
    """The latest checkpoint in ``directory``, or None if there isn't one.

    When ``fingerprint`` is given and the stored checkpoint carries a
    different one, the checkpoint is ignored (returns None, with a warning)
    — it belongs to a different configuration or dataset and resuming from
    it would return a model trained under the old settings. A COLLECTION
    of fingerprints accepts any of them: peer-loss recovery resumes a
    degraded run from a checkpoint written under the pre-loss process
    layout, whose fingerprint legitimately differs from the survivor
    group's (the row layout is part of the fingerprint by design). When
    ``data_digest`` is given and differs from the stored one, only the
    residual-exchange ``scores``/``total`` are dropped (they embed the old
    data's per-sample values); the model itself still resumes."""
    npz_path = os.path.join(directory, "ckpt.npz")
    if not os.path.exists(npz_path):
        return None
    z = np.load(npz_path)
    if _META_KEY not in z.files:
        _log.warning(
            "ignoring %s: no embedded metadata (truncated or foreign npz); "
            "training restarts from iteration 0", npz_path,
        )
        return None
    meta = json.loads(bytes(z[_META_KEY]).decode())
    if isinstance(fingerprint, str):
        accepted = (fingerprint,)
    elif fingerprint is None:
        accepted = None
    else:
        accepted = tuple(fingerprint)
    if accepted is not None and meta.get("fingerprint") not in accepted:
        _log.warning(
            "ignoring %s: fingerprint mismatch (written under a different "
            "configuration/data); training restarts from iteration 0", npz_path,
        )
        return None
    task = TaskType(meta["task_type"])
    models: dict = {}
    for cid, info in meta["coordinates"].items():
        means = jnp.asarray(z[f"{cid}__means"])
        variances = (
            jnp.asarray(z[f"{cid}__variances"]) if f"{cid}__variances" in z else None
        )
        if info["type"] == "fixed":
            models[cid] = FixedEffectModel(
                model=GeneralizedLinearModel(Coefficients(means, variances), task),
                feature_shard_id=info["feature_shard_id"],
            )
        else:
            models[cid] = RandomEffectModel(
                coefficients=means,
                variances=variances,
                random_effect_type=info["random_effect_type"],
                feature_shard_id=info["feature_shard_id"],
                task_type=task,
            )
    scores = None
    total = None
    if meta.get("has_scores"):
        stored_digest = meta.get("data_digest")
        if data_digest is not None and stored_digest != data_digest:
            _log.warning(
                "checkpoint %s was written against different data; dropping "
                "its residual scores (model still resumes, scores recompute)",
                npz_path,
            )
        else:
            scores = {
                k[len(_SCORE_PREFIX):]: z[k]
                for k in z.files
                if k.startswith(_SCORE_PREFIX)
            }
            total = z[_TOTAL_KEY]
    return DescentCheckpoint(
        model=GameModel(models=models, task_type=task),
        next_iteration=int(meta["next_iteration"]),
        scores=scores,
        total=total,
        next_coordinate=int(meta.get("next_coordinate", 0)),
        fingerprint=meta.get("fingerprint"),
    )
