"""Iteration-level checkpointing of coordinate-descent training.

Reference parity-plus: the reference has NO optimizer-state checkpointing —
only model warm start from a saved directory (SURVEY.md §5.4, which notes
the TPU build "should exceed the reference here"). This module checkpoints
the full GAME model plus descent progress after every outer iteration, so a
preempted job resumes mid-descent instead of restarting (TPU preemption is
routine; Spark lineage recovery has no analog here).

Format: one ``.npz`` per checkpoint holding every coordinate's arrays +
a JSON sidecar with progress (outer iteration, task type, coordinate
metadata). Writes are atomic (tmp + rename), keeping the last checkpoint
valid under preemption mid-write.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.types import TaskType


@dataclass(frozen=True)
class DescentCheckpoint:
    """A resumable descent state: the model + the NEXT outer iteration."""

    model: GameModel
    next_iteration: int


def save_checkpoint(directory: str, model: GameModel, next_iteration: int) -> None:
    os.makedirs(directory, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    meta: dict = {
        "task_type": model.task_type.value,
        "next_iteration": next_iteration,
        "coordinates": {},
    }
    for cid, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            arrays[f"{cid}__means"] = np.asarray(sub.model.coefficients.means)
            if sub.model.coefficients.variances is not None:
                arrays[f"{cid}__variances"] = np.asarray(
                    sub.model.coefficients.variances
                )
            meta["coordinates"][cid] = {
                "type": "fixed",
                "feature_shard_id": sub.feature_shard_id,
            }
        elif isinstance(sub, RandomEffectModel):
            arrays[f"{cid}__means"] = np.asarray(sub.coefficients)
            if sub.variances is not None:
                arrays[f"{cid}__variances"] = np.asarray(sub.variances)
            meta["coordinates"][cid] = {
                "type": "random",
                "feature_shard_id": sub.feature_shard_id,
                "random_effect_type": sub.random_effect_type,
            }
        else:  # pragma: no cover
            raise TypeError(f"unknown sub-model {type(sub)}")

    tmp_npz = os.path.join(directory, ".ckpt.npz.tmp")
    np.savez(tmp_npz, **arrays)
    os.replace(tmp_npz, os.path.join(directory, "ckpt.npz"))
    tmp_meta = os.path.join(directory, ".ckpt.json.tmp")
    with open(tmp_meta, "w") as f:
        json.dump(meta, f)
    os.replace(tmp_meta, os.path.join(directory, "ckpt.json"))


def load_checkpoint(directory: str) -> DescentCheckpoint | None:
    """The latest checkpoint in ``directory``, or None if there isn't one."""
    meta_path = os.path.join(directory, "ckpt.json")
    npz_path = os.path.join(directory, "ckpt.npz")
    if not (os.path.exists(meta_path) and os.path.exists(npz_path)):
        return None
    with open(meta_path) as f:
        meta = json.load(f)
    z = np.load(npz_path)
    task = TaskType(meta["task_type"])
    models: dict = {}
    for cid, info in meta["coordinates"].items():
        means = jnp.asarray(z[f"{cid}__means"])
        variances = (
            jnp.asarray(z[f"{cid}__variances"]) if f"{cid}__variances" in z else None
        )
        if info["type"] == "fixed":
            models[cid] = FixedEffectModel(
                model=GeneralizedLinearModel(Coefficients(means, variances), task),
                feature_shard_id=info["feature_shard_id"],
            )
        else:
            models[cid] = RandomEffectModel(
                coefficients=means,
                variances=variances,
                random_effect_type=info["random_effect_type"],
                feature_shard_id=info["feature_shard_id"],
                task_type=task,
            )
    return DescentCheckpoint(
        model=GameModel(models=models, task_type=task),
        next_iteration=int(meta["next_iteration"]),
    )
