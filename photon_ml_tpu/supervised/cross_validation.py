"""K-fold cross-validation for the GLM sweep.

Reference parity: SURVEY.md checklist item 7 lists ``crossvalidation``
among the reference subsystems to cover; the reference's sweep otherwise
selects λ on a single held-out validation set (``ml.Driver`` stage
VALIDATED). K-fold selection is strictly more robust on small data and
reuses the exact training path (``train_glm``) per fold — same losses,
same optimizers, same warm-started λ sweep.

TPU note: fold training reuses the in-memory batch via device-side row
gathers (one ``take`` per fold), so the feature matrix is staged to HBM
once; each fold's sweep then runs the standard compiled solve.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

import jax
import numpy as np

from photon_ml_tpu.config import OptimizerConfig, RegularizationContext
from photon_ml_tpu.evaluation.evaluators import (
    DEFAULT_EVALUATOR_BY_TASK,
    make_evaluator,
)
from photon_ml_tpu.ops.batch import Batch
from photon_ml_tpu.supervised.training import GLMTrainingResult, train_glm
from photon_ml_tpu.types import TaskType, VarianceComputationType

__all__ = ["CrossValidationResult", "cross_validate_glm"]


@dataclass(frozen=True)
class CrossValidationResult:
    """Per-λ per-fold metrics + the CV-selected weight and final refit."""

    # metric_values[lam][fold] — the primary metric on that fold's held-out rows
    metric_values: Mapping[float, list[float]]
    metric_name: str
    best_weight: float
    # refit of the best λ on ALL rows (what you deploy)
    final: GLMTrainingResult

    def mean(self, lam: float) -> float:
        return float(np.mean(self.metric_values[lam]))

    def std(self, lam: float) -> float:
        return float(np.std(self.metric_values[lam]))

    def summary(self) -> dict:
        return {
            "metric": self.metric_name,
            "best_weight": self.best_weight,
            "per_weight": {
                str(lam): {
                    "mean": self.mean(lam),
                    "std": self.std(lam),
                    "folds": [float(v) for v in vals],
                }
                for lam, vals in self.metric_values.items()
            },
        }


def _row_select(batch: Batch, rows: np.ndarray) -> Batch:
    return jax.tree.map(lambda a: a[rows], batch)


def _ingest_training_batch(batch: Batch) -> Batch:
    """The fold/refit ingest decision — the framework's ONE standard rule
    (``optimize_batch_layout``: densify when the dense matrix fits,
    tile-COO for genuinely high-dimensional sparse, through the
    PROCESS-WIDE layout cache). A repeated ``cross_validate_glm`` over the
    same data (outer hyperparameter search, repeated experiments) re-packs
    no fold, and the final refit reuses any layout the caller's own ingest
    already built. Dense batches pass through unchanged."""
    from photon_ml_tpu.ops.batch import SparseBatch, optimize_batch_layout

    if isinstance(batch, SparseBatch):
        return optimize_batch_layout(batch)
    return batch


def cross_validate_glm(
    batch: Batch,
    task: TaskType,
    k: int = 5,
    regularization_weights: Sequence[float] = (0.0,),
    evaluator: str | None = None,
    seed: int = 0,
    optimizer_config: OptimizerConfig | None = None,
    regularization: RegularizationContext | None = None,
    normalization=None,
    intercept_index: int | None = None,
    variance_computation: VarianceComputationType = VarianceComputationType.NONE,
) -> CrossValidationResult:
    """Select λ by k-fold CV, then refit the winner on all rows.

    ``evaluator`` defaults per task (AUC for classification, RMSE for
    linear, POISSON_LOSS for counts). Each fold trains the full warm-started
    λ sweep on its k-1 training folds and scores every λ-model on the
    held-out fold; λ with the best MEAN metric wins.
    """
    if k < 2:
        raise ValueError(f"k-fold CV needs k >= 2, got {k}")
    n = batch.num_rows
    if n < k:
        raise ValueError(f"cannot split {n} rows into {k} folds")
    spec = evaluator or DEFAULT_EVALUATOR_BY_TASK[task]
    ev = make_evaluator(spec)

    perm = np.random.default_rng(seed).permutation(n)
    folds = np.array_split(perm, k)

    metric_values: dict[float, list[float]] = {
        float(lam): [] for lam in regularization_weights
    }
    from photon_ml_tpu.ops import prefetch

    from photon_ml_tpu.obs import span

    def ingest_fold(i):
        # fold INGEST (row gather + layout decision + tile-COO pack through
        # the process-wide cache) for fold i+k runs on prefetch workers
        # while fold i's sweep trains; training and evaluation stay on this
        # thread in fold order, so every metric and the refit are bitwise
        # identical to the synchronous schedule (depth 0 restores it).
        # The ingest span roots on the WORKER thread (spans are
        # thread-local by design — it must not adopt whatever fold the
        # consumer thread currently has open).
        with span("ingest/cv-fold", fold=i):
            train_rows = np.setdiff1d(perm, folds[i], assume_unique=True)
            return _ingest_training_batch(_row_select(batch, train_rows))

    # depth capped at 1 for THIS consumer: unlike the streaming paths
    # (whose items are bounded chunks), each prefetched item here is a
    # near-full ingested training batch — the default depth would hold
    # three of them live and triple peak memory. One fold ahead overlaps
    # the whole ingest with the previous fold's sweep already.
    from photon_ml_tpu.ops import stream_executor

    cv_depth = min(prefetch.prefetch_depth(), 1)
    if stream_executor.stream_executor_enabled():
        # scheduler-only port (ingest builds a fresh near-full training
        # batch per fold — nothing content-cacheable); the depth-1 cap
        # above still bounds peak memory on the executor path
        fold_iter = stream_executor.stream(
            "cv", len(folds), ingest_fold, depth=cv_depth
        )
    else:
        fold_iter = prefetch.prefetch_iter(
            len(folds), ingest_fold, depth=cv_depth
        )
    for i, train_batch in enumerate(fold_iter):
        held_out = folds[i]
        with span("cv/fold", fold=i, k=k):
            result = train_glm(
                train_batch,
                task,
                optimizer_config=optimizer_config,
                regularization=regularization,
                regularization_weights=regularization_weights,
                normalization=normalization,
                intercept_index=intercept_index,
            )
            val = _row_select(batch, held_out)
            for lam, model in result.models.items():
                scores = model.score(val)
                metric_values[float(lam)].append(
                    float(ev(scores, val.labels, val.weights))
                )

    best_weight = None
    best_mean = float("nan")
    for lam, vals in metric_values.items():
        m = float(np.mean(vals))
        if best_weight is None or ev.better(m, best_mean):
            best_weight, best_mean = lam, m

    with span("cv/refit", weight=float(best_weight), k=k):
        final = train_glm(
            _ingest_training_batch(batch),
            task,
            optimizer_config=optimizer_config,
            regularization=regularization,
            regularization_weights=[best_weight],
            normalization=normalization,
            intercept_index=intercept_index,
            variance_computation=variance_computation,
        )
    return CrossValidationResult(
        metric_values=metric_values,
        metric_name=ev.name,
        best_weight=best_weight,
        final=final,
    )
