"""Single-model supervised training (legacy GLM driver parity)."""

from photon_ml_tpu.supervised.cross_validation import (  # noqa: F401
    CrossValidationResult,
    cross_validate_glm,
)
from photon_ml_tpu.supervised.training import GLMTrainingResult, train_glm  # noqa: F401
