"""Single-GLM training: regularization sweep with warm start, validation,
model selection, optional coefficient variances.

Reference parity: ``photon-client::ml.ModelTraining.trainGeneralizedLinearModel``
+ the legacy ``Driver`` pipeline (SURVEY.md §3.2): for each λ in ascending
order, train (warm-starting from the previous λ's model), validate, select
best; optionally compute coefficient variances from the Hessian.

TPU-first: each λ's solve is one compiled device program (the optimizer
while-loop); the sweep is a short host loop that re-enters the same compiled
executable (shapes don't change with λ, and λ is a traced array, so there is
exactly ONE compilation for the whole sweep).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.config import OptimizerConfig, RegularizationContext
from photon_ml_tpu.evaluation import EvaluationResults, evaluate_all, make_evaluator
from photon_ml_tpu.models import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.normalization import (
    NormalizationContext,
    require_intercept_for_shifts,
)
from photon_ml_tpu.ops.batch import Batch
from photon_ml_tpu.ops.glm import GLMObjective, compute_variances, make_objective
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.optim.common import OptimizationResult, select_minimize_fn
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jnp.ndarray


@dataclass(frozen=True)
class GLMTrainingResult:
    """Per-λ models + diagnostics, and the selected best model."""

    models: Mapping[float, GeneralizedLinearModel]
    trackers: Mapping[float, OptimizationResult]
    validation: Mapping[float, EvaluationResults]
    best_weight: float | None

    @property
    def best_model(self) -> GeneralizedLinearModel:
        if self.best_weight is None:
            # no validation data: last λ (reference picks by validation;
            # without it the sweep's final — most regularized — model)
            return self.models[list(self.models)[-1]]
        return self.models[self.best_weight]


def train_glm(
    batch: Batch,
    task: TaskType,
    optimizer_config: OptimizerConfig | None = None,
    regularization: RegularizationContext | None = None,
    regularization_weights: Sequence[float] = (0.0,),
    normalization: NormalizationContext | None = None,
    intercept_index: int | None = None,
    validation_batch: Batch | None = None,
    evaluators: Sequence[str] = (),
    validation_group_ids: Mapping[str, np.ndarray] | None = None,
    variance_computation: VarianceComputationType = VarianceComputationType.NONE,
    initial_model: GeneralizedLinearModel | None = None,
    axis_name: str | None = None,
    incremental: bool = False,
) -> GLMTrainingResult:
    """Train one GLM per regularization weight (ascending, warm-started),
    validate each, and select the best by the first evaluator.

    ``incremental=True`` turns ``initial_model`` from a plain warm start
    into an informative Gaussian prior (MAP update): the regularizer pulls
    toward the prior model's means with strength 1/variance per coordinate
    (unit precision when the prior model carries no variances — train it
    with ``variance_computation`` to get per-coordinate strengths).

    When ``axis_name`` is set the caller is responsible for invoking this
    inside ``shard_map`` (the distributed layer wraps it); the code is
    identical either way.
    """
    optimizer_config = optimizer_config or OptimizerConfig()
    if regularization is None:
        # default: nonzero weights imply plain L2 (asking for λ>0 with type
        # NONE would silently train unregularized — an easy trap)
        from photon_ml_tpu.types import RegularizationType

        has_weights = any(w > 0 for w in regularization_weights)
        regularization = RegularizationContext(
            RegularizationType.L2 if has_weights else RegularizationType.NONE
        )
    elif regularization.regularization_type.value == "NONE" and any(
        w > 0 for w in regularization_weights
    ):
        raise ValueError(
            "regularization_weights > 0 with RegularizationType.NONE would be "
            "silently ignored; pass an L1/L2/ELASTIC_NET context or drop the weights"
        )
    loss = loss_for_task(task)
    d = batch.num_features
    dtype = batch.labels.dtype

    require_intercept_for_shifts(normalization)

    # The optimizer works in NORMALIZED coefficient space; models are kept in
    # ORIGINAL space (the reference un-applies factors on the final model).
    prior = None
    if initial_model is not None:
        w = jnp.asarray(initial_model.coefficients.means, dtype)
        if normalization is not None:
            w = normalization.model_from_original_space(w)
        if incremental:
            from photon_ml_tpu.ops.glm import GaussianPrior

            if not any(regularization.l2_weight(lam) > 0
                       for lam in regularization_weights):
                raise ValueError(
                    "incremental=True needs at least one sweep weight with a "
                    "positive L2 component: the prior's pull is "
                    "l2_weight * (1/prior_variance)"
                )
            prior = GaussianPrior.from_coefficients(
                initial_model.coefficients.means,
                initial_model.coefficients.variances,
                normalization,
            )
    else:
        if incremental:
            raise ValueError("incremental=True requires initial_model (the prior)")
        w = jnp.zeros((d,), dtype)

    specs = list(evaluators)
    if validation_batch is not None and not specs:
        from photon_ml_tpu.evaluation.evaluators import DEFAULT_EVALUATOR_BY_TASK

        specs = [DEFAULT_EVALUATOR_BY_TASK[task]]
    primary = make_evaluator(specs[0]) if specs else None

    models: dict[float, GeneralizedLinearModel] = {}
    trackers: dict[float, OptimizationResult] = {}
    validation: dict[float, EvaluationResults] = {}
    best_weight: float | None = None
    best_value = float("nan")

    from photon_ml_tpu.obs import emit_event, enabled, span

    # ascending λ with warm start (reference sweeps the same way)
    for lam in sorted(regularization_weights):
        l1 = regularization.l1_weight(lam)
        l2 = regularization.l2_weight(lam)
        with span("glm/lambda", weight=float(lam)):
            obj = make_objective(
                batch,
                loss,
                l2_weight=l2,
                norm=normalization,
                intercept_index=intercept_index,
                axis_name=axis_name,
                prior=prior,
            )
            minimize_fn, extra = select_minimize_fn(optimizer_config, l1)
            result = minimize_fn(obj, w, optimizer_config, **extra)
        w = result.w  # warm start the next λ (normalized space)
        if enabled():
            # device solvers return lazily; pull the record only when a
            # sink is live (a host sync per λ is fine, but not for free)
            emit_event(
                "optim_result", weight=float(lam), **result.telemetry_record()
            )

        variances = compute_variances(obj, result.w, variance_computation)
        w_model = result.w
        if normalization is not None:
            w_model, _ = normalization.model_to_original_space(result.w)
            if variances is not None:
                # linear map u = f⊙w ⇒ var scales by f² (diagonal approx.)
                variances = normalization.factors**2 * variances
        model = GeneralizedLinearModel(Coefficients(w_model, variances), task)
        models[lam] = model
        trackers[lam] = result

        if validation_batch is not None and specs:
            # evaluators consume RAW scores (margins + offsets), matching the
            # reference: loss evaluators re-apply the pointwise loss to the
            # margin; AUC is rank-invariant; RMSE on a linear task sees the
            # prediction (identity link). Feeding inverse-link predictions
            # here would evaluate e.g. the Poisson loss at exp(exp(m)).
            scores = model.score(validation_batch)
            res = evaluate_all(
                specs,
                scores,
                validation_batch.labels,
                validation_batch.weights,
                group_ids=validation_group_ids,
            )
            validation[lam] = res
            if primary is not None and (
                best_weight is None or primary.better(res.primary, best_value)
            ):
                best_weight, best_value = lam, res.primary

    return GLMTrainingResult(
        models=models, trackers=trackers, validation=validation, best_weight=best_weight
    )


class _StreamedSweepCheckpoint:
    """Resumable state for the streamed λ sweep: an atomic npz with the
    completed λs' coefficient vectors (rewritten only when a λ finishes)
    plus a separate small per-iteration file holding the in-progress λ's
    latest iterate. Both carry a fingerprint of the sweep setup (task,
    geometry, optimizer config, regularization, data digest), so a changed
    setup retrains instead of silently resuming; corrupt/foreign files are
    ignored, never fatal — a resume feature must not be able to brick runs.

    Multi-host: process 0 alone reads/writes the files (per-host data
    shards give other processes different digests, and shared storage must
    have exactly one writer); ``sync_across_processes`` broadcasts its
    state so every process branches identically.
    """

    def __init__(self, directory, task, chunks, num_features, opt_config, reg,
                 normalization=None, prior=None):
        import hashlib
        import os

        self.directory = directory
        self.done_path = os.path.join(directory, "sweep-done.npz")
        self.partial_path = os.path.join(directory, "sweep-partial.npz")
        first_labels = np.ascontiguousarray(chunks[0]["labels"]) if chunks else np.zeros(0)
        total_rows = sum(len(c["labels"]) for c in chunks)
        # normalization reshapes the optimization trajectory AND the saved
        # coefficient space — resuming under different factors/shifts must
        # be rejected like any other setup change
        norm_token = (
            None
            if normalization is None
            else hashlib.sha256(
                np.ascontiguousarray(
                    np.asarray(normalization.factors, np.float32)
                ).tobytes()
                + np.ascontiguousarray(
                    np.asarray(normalization.shifts, np.float32)
                ).tobytes()
                + repr(normalization.intercept_index).encode()
            ).hexdigest()
        )
        # NOTE: the λ list is deliberately NOT fingerprinted — completed
        # models are keyed by λ, so extending the sweep (the canonical
        # resume-and-extend workflow) reuses what finished and trains the
        # rest. The optimizer config IS fingerprinted: a λ "completed"
        # under a smaller iteration budget is not the model a bigger
        # budget's rerun asks for.
        self.fingerprint = hashlib.sha256(
            repr(
                (
                    task.value,
                    num_features,
                    total_rows,
                    len(chunks),
                    opt_config.optimizer_type.value,
                    opt_config.max_iterations,
                    opt_config.max_cg_iterations,
                    opt_config.history_length,
                    opt_config.max_line_search_steps,
                    opt_config.tolerance,
                    reg.regularization_type.value if reg is not None else None,
                    reg.alpha if reg is not None else None,
                    norm_token,
                    # an incremental prior reshapes the objective itself —
                    # resuming a plain sweep into a MAP sweep (or vice
                    # versa, or under a different prior) must retrain
                    None
                    if prior is None
                    else hashlib.sha256(
                        np.ascontiguousarray(
                            np.asarray(prior.means, np.float32)
                        ).tobytes()
                        + (
                            b""
                            if prior.variances is None
                            else np.ascontiguousarray(
                                np.asarray(prior.variances, np.float32)
                            ).tobytes()
                        )
                    ).hexdigest(),
                )
            ).encode()
            + first_labels.tobytes()
        ).hexdigest()
        self._completed: dict[str, np.ndarray] = {}
        self._partial: tuple[float, np.ndarray] | None = None
        import jax

        if jax.process_index() == 0:
            # only process 0 touches the files; in multi-host runs the
            # caller broadcasts this state via sync_across_processes()
            done = self._load(self.done_path)
            if done is not None:
                z, _ = done
                self._completed = {
                    k[len("done__"):]: z[k] for k in z.files if k.startswith("done__")
                }
            partial = self._load(self.partial_path)
            if partial is not None:
                z, meta = partial
                if "w" in z.files and meta.get("lam") is not None:
                    self._partial = (float(meta["lam"]), z["w"])

    def sync_across_processes(self) -> None:
        """Multi-host: replace every process's view of the checkpoint with
        PROCESS 0's (only process 0 reads/writes the files; per-host data
        shards would otherwise give each process a different fingerprint
        and desynchronize the λ-loop branches, deadlocking the gradient
        collectives). Two broadcast phases: sizes first, then arrays."""
        import jax

        if jax.process_count() <= 1:
            return
        from jax.experimental import multihost_utils as mhu

        d = None
        for v in self._completed.values():
            d = len(v)
            break
        if d is None and self._partial is not None:
            d = len(self._partial[1])
        counts = mhu.broadcast_one_to_all(
            np.asarray(
                [len(self._completed), 1 if self._partial is not None else 0,
                 d if d is not None else 0],
                np.int64,
            )
        )
        k, has_partial, d = int(counts[0]), int(counts[1]), int(counts[2])
        if k == 0 and not has_partial:
            self._completed, self._partial = {}, None
            return
        # every array broadcast in ONE canonical dtype — the stored
        # coefficient dtype varies (f32 from the solver, f64 from resume)
        # and a dtype mismatch between source and placeholder aborts gloo
        if jax.process_index() == 0:
            lams = np.asarray([float(key) for key in self._completed], np.float64)
            W = (
                np.stack(
                    [self._completed[key] for key in self._completed]
                ).astype(np.float64)
                if k
                else np.zeros((0, d))
            )
            plam = np.asarray(
                [self._partial[0] if self._partial is not None else 0.0],
                np.float64,
            )
            pw = (
                np.asarray(self._partial[1], np.float64)
                if self._partial is not None
                else np.zeros(d)
            )
        else:
            lams = np.zeros(k, np.float64)
            W = np.zeros((k, d))
            plam = np.zeros(1)
            pw = np.zeros(d)
        lams, W, plam, pw = mhu.broadcast_one_to_all((lams, W, plam, pw))
        self._completed = {
            repr(float(lams[i])): np.asarray(W[i]) for i in range(k)
        }
        self._partial = (
            (float(plam[0]), np.asarray(pw)) if has_partial else None
        )

    def _load(self, path):
        """(npz, meta) when ``path`` is a valid checkpoint matching this
        sweep's fingerprint; None otherwise (corrupt files included)."""
        import json as _json
        import os

        if not os.path.exists(path):
            return None
        try:
            z = np.load(path, allow_pickle=False)
            meta = _json.loads(bytes(z["__meta__"]).decode())
        except Exception:
            return None  # truncated/foreign file: retrain, don't crash
        if meta.get("fingerprint") != self.fingerprint:
            return None
        return z, meta

    def completed_model(self, lam: float) -> np.ndarray | None:
        got = self._completed.get(repr(float(lam)))
        return None if got is None else np.asarray(got, np.float64)

    def partial_iterate(self, lam: float) -> np.ndarray | None:
        if self._partial is not None and self._partial[0] == float(lam):
            return np.asarray(self._partial[1], np.float64)
        return None

    def save_partial(self, lam: float, w: np.ndarray) -> None:
        # small file, rewritten per accepted iteration — the completed
        # models are immutable and must not be re-serialized that often
        self._partial = (float(lam), np.asarray(w))
        self._write(
            self.partial_path, {"w": self._partial[1]}, {"lam": self._partial[0]}
        )

    def save_completed(self, lam: float, w: np.ndarray) -> None:
        import os

        self._completed[repr(float(lam))] = np.asarray(w)
        self._partial = None
        self._write(
            self.done_path,
            {f"done__{k}": v for k, v in self._completed.items()},
            {},
        )
        try:
            os.remove(self.partial_path)
        except OSError:
            pass

    def _write(self, path: str, arrays: dict, extra_meta: dict) -> None:
        import json as _json
        import os

        from photon_ml_tpu.parallel.multihost import is_output_process

        if not is_output_process():
            return  # multi-host: exactly one writer
        os.makedirs(self.directory, exist_ok=True)
        meta = {"fingerprint": self.fingerprint, **extra_meta}
        arrays = dict(arrays)
        arrays["__meta__"] = np.frombuffer(
            _json.dumps(meta).encode(), dtype=np.uint8
        )
        tmp = path + f".tmp-{os.getpid()}.npz"
        np.savez(tmp, **arrays)
        os.replace(tmp, path)


def train_glm_streamed(
    chunks: Sequence[dict],
    task: TaskType,
    num_features: int,
    optimizer_config: OptimizerConfig | None = None,
    regularization: RegularizationContext | None = None,
    regularization_weights: Sequence[float] = (0.0,),
    intercept_index: int | None = None,
    validation_chunks: Sequence[dict] | None = None,
    evaluators: Sequence[str] = (),
    initial_model: GeneralizedLinearModel | None = None,
    incremental: bool = False,
    cross_process: bool = False,
    checkpoint_dir: str | None = None,
    normalization: NormalizationContext | None = None,
    variance_computation: VarianceComputationType = VarianceComputationType.NONE,
) -> GLMTrainingResult:
    """Out-of-core twin of ``train_glm``: the same ascending-λ warm-started
    sweep, driven by host L-BFGS over a ``StreamingGLMObjective`` (one
    streamed pass per value+gradient evaluation — the reference's Spark
    aggregation pattern; SURVEY.md §7 "Streaming 1B rows").

    ``normalization`` applies inside every streamed objective evaluation
    (factor-folding — zero extra HBM traffic) and is un-applied on the
    saved models, exactly like the in-memory sweep; build the context from
    ``data.summary.summarize_chunks`` over the SAME chunks.
    ``variance_computation`` SIMPLE costs one extra streamed
    Hessian-diagonal pass per λ at its solution; FULL costs one extra
    streamed pass accumulating the d×d Hessian chunk-wise (host-inverted,
    bounded at ``StreamingGLMObjective.FULL_HESSIAN_MAX_D``).
    ``incremental=True`` turns ``initial_model`` into a Gaussian MAP prior
    (means + 1/variance precisions), folded into the streamed objective
    exactly like L2 — the same contract as the in-memory sweep.

    ``chunks`` are uniform host chunk dicts (``photon_ml_tpu.ops.streaming``
    builders or ``AvroDataReader.iter_batch_chunks``). Validation scores
    stream chunk-by-chunk; padded rows carry weight 0, which every
    evaluator treats as absent. The streamed optimizers are host-driven
    L-BFGS and TRON (selected by ``optimizer_config.optimizer_type``);
    a positive L1 weight routes through host OWL-QN, exactly like the
    in-memory path (L1 with TRON is rejected, as in the reference).

    ``checkpoint_dir`` makes the sweep resumable: completed λs' models and
    the in-progress λ's latest iterate are checkpointed (atomic npz with an
    embedded fingerprint of the sweep setup + a data digest); a rerun loads
    completed models and restarts the interrupted λ from its saved iterate
    with a fresh L-BFGS history. Multi-host safe: process 0 owns the files
    and its checkpoint view is broadcast to every process, so all λ-loop
    branches are taken identically and the gradient collectives stay
    matched.
    """
    from photon_ml_tpu.ops.streaming import StreamingGLMObjective, stream_scores
    from photon_ml_tpu.optim.common import select_minimize_fn
    from photon_ml_tpu.types import RegularizationType

    optimizer_config = optimizer_config or OptimizerConfig()
    has_weights = any(w > 0 for w in regularization_weights)
    if regularization is None:
        # same default as train_glm: nonzero weights imply plain L2
        regularization = RegularizationContext(
            RegularizationType.L2 if has_weights else RegularizationType.NONE
        )
    # fail fast on unsupported combinations BEFORE any data work: the
    # selection rule (and its rejections) is shared with the in-memory path
    select_minimize_fn(
        optimizer_config, regularization.l1_weight(1.0), host=True
    )
    if regularization.regularization_type is RegularizationType.NONE and has_weights:
        raise ValueError(
            "regularization_weights > 0 with RegularizationType.NONE would be "
            "silently ignored; pass an L2 context or drop the weights"
        )
    if variance_computation is VarianceComputationType.FULL:
        from photon_ml_tpu.ops.streaming import StreamingGLMObjective as _S

        if num_features > _S.FULL_HESSIAN_MAX_D:
            # fail BEFORE the first λ's full streamed solve, not after it
            raise ValueError(
                f"streamed FULL variance supports d <= {_S.FULL_HESSIAN_MAX_D} "
                f"(got {num_features}); use SIMPLE at this width"
            )
    require_intercept_for_shifts(normalization)
    loss = loss_for_task(task)
    # the optimizer works in NORMALIZED coefficient space (models are saved
    # in original space, same contract as the in-memory sweep)
    prior = None
    if initial_model is not None:
        w0 = jnp.asarray(initial_model.coefficients.means, jnp.float32)
        if normalization is not None:
            w0 = normalization.model_from_original_space(w0)
        w = np.asarray(w0, np.float32)
        if incremental:
            # same contract as the in-memory sweep: the loaded model
            # becomes a Gaussian MAP prior, which needs a positive L2
            # component somewhere in the sweep to have any pull
            from photon_ml_tpu.ops.glm import GaussianPrior

            if not any(
                regularization.l2_weight(lam) > 0
                for lam in regularization_weights
            ):
                raise ValueError(
                    "incremental=True needs at least one sweep weight with a "
                    "positive L2 component: the prior's pull is "
                    "l2_weight * (1/prior_variance)"
                )
            prior = GaussianPrior.from_coefficients(
                initial_model.coefficients.means,
                initial_model.coefficients.variances,
                normalization,
            )
    else:
        if incremental:
            raise ValueError("incremental=True requires initial_model (the prior)")
        w = np.zeros((num_features,), np.float32)

    specs = list(evaluators)
    if validation_chunks is not None and not specs:
        specs = {
            TaskType.LOGISTIC_REGRESSION: ["AUC"],
            TaskType.LINEAR_REGRESSION: ["RMSE"],
            TaskType.POISSON_REGRESSION: ["POISSON_LOSS"],
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: ["AUC"],
        }[task]
    primary = make_evaluator(specs[0]) if specs else None

    val_labels = val_weights = val_offsets = None
    if validation_chunks is not None:
        val_labels = np.concatenate([c["labels"] for c in validation_chunks])
        val_weights = np.concatenate([c["weights"] for c in validation_chunks])
        val_offsets = np.concatenate([c["offsets"] for c in validation_chunks])

    models: dict[float, GeneralizedLinearModel] = {}
    trackers: dict[float, OptimizationResult] = {}
    validation: dict[float, EvaluationResults] = {}
    best_weight: float | None = None
    best_value = float("nan")

    ckpt = (
        _StreamedSweepCheckpoint(
            checkpoint_dir, task, chunks, num_features, optimizer_config,
            regularization, normalization=normalization, prior=prior,
        )
        if checkpoint_dir is not None
        else None
    )
    if ckpt is not None and cross_process:
        # multi-host: all processes adopt process 0's checkpoint view, so
        # every λ-loop branch (load vs train vs resume-from-iterate) is
        # taken identically and the gradient collectives stay matched
        ckpt.sync_across_processes()

    # ONE objective for the whole sweep: its per-chunk kernels are built
    # λ-free (λ applied outside the jit), so mutating l2_weight between λs
    # re-enters the same compiled programs — no recompilation across the grid
    sobj = StreamingGLMObjective(
        chunks, loss, num_features=num_features, l2_weight=0.0,
        intercept_index=intercept_index, cross_process=cross_process,
        norm=normalization,
        prior_mean=None if prior is None else prior.means,
        prior_precision=None if prior is None else prior.precisions,
        # FULL needs the raw per-chunk indices for its densified Hessian
        # pass; the auto tile-COO layout drops them
        tile_sparse=(
            False
            if variance_computation is VarianceComputationType.FULL
            else None
        ),
    )
    fe = getattr(sobj, "fe_active", False)
    if fe:
        if ckpt is not None:
            # checkpoints store FULL-space iterates with a fingerprint
            # over the unsharded chunk set; a per-range resume contract
            # (and cross-P re-partitioned resume) is future work — fail
            # loudly rather than write shard-local iterates a later
            # unsharded run would load as full vectors
            raise NotImplementedError(
                "checkpoint_dir with PHOTON_FE_SHARD=1 is not supported; "
                "disable sharding or drop the checkpoint directory"
            )
        if variance_computation is VarianceComputationType.FULL:
            # the streamed FULL pass densifies a d x d Hessian from raw
            # chunk indices; the sharded objective only holds its range
            raise NotImplementedError(
                "FULL variances with PHOTON_FE_SHARD=1 are not supported; "
                "use SIMPLE (per-range diagonal, gathered exactly)"
            )
        # the optimizer iterates on this process's range shard; model
        # assembly gathers the full vector per λ below
        w = sobj.fe_slice(w)
    for lam in sorted(regularization_weights):
        done_w = ckpt.completed_model(lam) if ckpt is not None else None
        if done_w is not None:
            w = done_w
            result = None
            sobj.l2_weight = float(regularization.l2_weight(lam))
        else:
            sobj.l2_weight = float(regularization.l2_weight(lam))
            resume_w = ckpt.partial_iterate(lam) if ckpt is not None else None
            minimize, extra = select_minimize_fn(
                optimizer_config, regularization.l1_weight(lam), host=True
            )
            result = minimize(
                sobj,
                resume_w if resume_w is not None else w,
                optimizer_config,
                iteration_callback=(
                    None if ckpt is None else lambda it, wi, f: ckpt.save_partial(lam, wi)
                ),
                **extra,
            )
            w = np.asarray(result.w)  # warm start the next λ (normalized space)
            if ckpt is not None:
                ckpt.save_completed(lam, w)

        variances = None
        if variance_computation is not VarianceComputationType.NONE:
            from photon_ml_tpu.ops.glm import compute_variances

            # one extra streamed pass at the solution (checkpoint-loaded λs
            # included — variances are not checkpointed); the shared
            # implementation consumes the streaming objective's
            # hessian_diag (SIMPLE) or its chunk-accumulated d×d hessian
            # (FULL, host-inverted, d-bounded) directly
            variances = compute_variances(
                sobj, jnp.asarray(w, jnp.float32), variance_computation
            )
            if fe and variances is not None:
                # SIMPLE variances are elementwise in the Hessian
                # diagonal, and the sharded diagonal is this range's
                # DISJOINT segment — the gather is exact
                variances = jnp.asarray(sobj.fe_gather(np.asarray(variances)))
        # under PHOTON_FE_SHARD the iterate is this process's range
        # shard; the saved model (and validation scoring) need the full
        # vector — a fixed ascending-order gather, pure data movement
        w_model = jnp.asarray(sobj.fe_gather(w) if fe else w, jnp.float32)
        if normalization is not None:
            w_model, _ = normalization.model_to_original_space(w_model)
            if variances is not None:
                variances = normalization.factors**2 * variances
        model = GeneralizedLinearModel(
            Coefficients(w_model, variances), task
        )
        models[lam] = model
        if result is not None:
            trackers[lam] = result

        if validation_chunks is not None and specs:
            n_val = len(val_labels)
            # validation chunks carry RAW features — score with the
            # ORIGINAL-space coefficients
            margins = stream_scores(
                validation_chunks, np.asarray(w_model), num_rows=n_val,
                num_features=num_features,
            )
            res = evaluate_all(
                specs,
                jnp.asarray(margins + val_offsets),
                jnp.asarray(val_labels),
                jnp.asarray(val_weights),
            )
            validation[lam] = res
            if primary is not None and (
                best_weight is None or primary.better(res.primary, best_value)
            ):
                best_weight, best_value = lam, res.primary

    return GLMTrainingResult(
        models=models, trackers=trackers, validation=validation, best_weight=best_weight
    )
