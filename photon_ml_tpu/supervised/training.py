"""Single-GLM training: regularization sweep with warm start, validation,
model selection, optional coefficient variances.

Reference parity: ``photon-client::ml.ModelTraining.trainGeneralizedLinearModel``
+ the legacy ``Driver`` pipeline (SURVEY.md §3.2): for each λ in ascending
order, train (warm-starting from the previous λ's model), validate, select
best; optionally compute coefficient variances from the Hessian.

TPU-first: each λ's solve is one compiled device program (the optimizer
while-loop); the sweep is a short host loop that re-enters the same compiled
executable (shapes don't change with λ, and λ is a traced array, so there is
exactly ONE compilation for the whole sweep).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.config import OptimizerConfig, RegularizationContext
from photon_ml_tpu.evaluation import EvaluationResults, evaluate_all, make_evaluator
from photon_ml_tpu.models import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.normalization import (
    NormalizationContext,
    require_intercept_for_shifts,
)
from photon_ml_tpu.ops.batch import Batch
from photon_ml_tpu.ops.glm import GLMObjective, compute_variances, make_objective
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.optim.common import OptimizationResult, select_minimize_fn
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jnp.ndarray


@dataclass(frozen=True)
class GLMTrainingResult:
    """Per-λ models + diagnostics, and the selected best model."""

    models: Mapping[float, GeneralizedLinearModel]
    trackers: Mapping[float, OptimizationResult]
    validation: Mapping[float, EvaluationResults]
    best_weight: float | None

    @property
    def best_model(self) -> GeneralizedLinearModel:
        if self.best_weight is None:
            # no validation data: last λ (reference picks by validation;
            # without it the sweep's final — most regularized — model)
            return self.models[list(self.models)[-1]]
        return self.models[self.best_weight]


def train_glm(
    batch: Batch,
    task: TaskType,
    optimizer_config: OptimizerConfig | None = None,
    regularization: RegularizationContext | None = None,
    regularization_weights: Sequence[float] = (0.0,),
    normalization: NormalizationContext | None = None,
    intercept_index: int | None = None,
    validation_batch: Batch | None = None,
    evaluators: Sequence[str] = (),
    validation_group_ids: Mapping[str, np.ndarray] | None = None,
    variance_computation: VarianceComputationType = VarianceComputationType.NONE,
    initial_model: GeneralizedLinearModel | None = None,
    axis_name: str | None = None,
) -> GLMTrainingResult:
    """Train one GLM per regularization weight (ascending, warm-started),
    validate each, and select the best by the first evaluator.

    When ``axis_name`` is set the caller is responsible for invoking this
    inside ``shard_map`` (the distributed layer wraps it); the code is
    identical either way.
    """
    optimizer_config = optimizer_config or OptimizerConfig()
    if regularization is None:
        # default: nonzero weights imply plain L2 (asking for λ>0 with type
        # NONE would silently train unregularized — an easy trap)
        from photon_ml_tpu.types import RegularizationType

        has_weights = any(w > 0 for w in regularization_weights)
        regularization = RegularizationContext(
            RegularizationType.L2 if has_weights else RegularizationType.NONE
        )
    elif regularization.regularization_type.value == "NONE" and any(
        w > 0 for w in regularization_weights
    ):
        raise ValueError(
            "regularization_weights > 0 with RegularizationType.NONE would be "
            "silently ignored; pass an L1/L2/ELASTIC_NET context or drop the weights"
        )
    loss = loss_for_task(task)
    d = batch.num_features
    dtype = batch.labels.dtype

    require_intercept_for_shifts(normalization)

    # The optimizer works in NORMALIZED coefficient space; models are kept in
    # ORIGINAL space (the reference un-applies factors on the final model).
    if initial_model is not None:
        w = jnp.asarray(initial_model.coefficients.means, dtype)
        if normalization is not None:
            w = normalization.model_from_original_space(w)
    else:
        w = jnp.zeros((d,), dtype)

    specs = list(evaluators)
    if validation_batch is not None and not specs:
        specs = {
            TaskType.LOGISTIC_REGRESSION: ["AUC"],
            TaskType.LINEAR_REGRESSION: ["RMSE"],
            TaskType.POISSON_REGRESSION: ["POISSON_LOSS"],
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: ["AUC"],
        }[task]
    primary = make_evaluator(specs[0]) if specs else None

    models: dict[float, GeneralizedLinearModel] = {}
    trackers: dict[float, OptimizationResult] = {}
    validation: dict[float, EvaluationResults] = {}
    best_weight: float | None = None
    best_value = float("nan")

    # ascending λ with warm start (reference sweeps the same way)
    for lam in sorted(regularization_weights):
        l1 = regularization.l1_weight(lam)
        l2 = regularization.l2_weight(lam)
        obj = make_objective(
            batch,
            loss,
            l2_weight=l2,
            norm=normalization,
            intercept_index=intercept_index,
            axis_name=axis_name,
        )
        minimize_fn, extra = select_minimize_fn(optimizer_config, l1)
        result = minimize_fn(obj, w, optimizer_config, **extra)
        w = result.w  # warm start the next λ (normalized space)

        variances = compute_variances(obj, result.w, variance_computation)
        w_model = result.w
        if normalization is not None:
            w_model, _ = normalization.model_to_original_space(result.w)
            if variances is not None:
                # linear map u = f⊙w ⇒ var scales by f² (diagonal approx.)
                variances = normalization.factors**2 * variances
        model = GeneralizedLinearModel(Coefficients(w_model, variances), task)
        models[lam] = model
        trackers[lam] = result

        if validation_batch is not None and specs:
            # evaluators consume RAW scores (margins + offsets), matching the
            # reference: loss evaluators re-apply the pointwise loss to the
            # margin; AUC is rank-invariant; RMSE on a linear task sees the
            # prediction (identity link). Feeding inverse-link predictions
            # here would evaluate e.g. the Poisson loss at exp(exp(m)).
            scores = model.score(validation_batch)
            res = evaluate_all(
                specs,
                scores,
                validation_batch.labels,
                validation_batch.weights,
                group_ids=validation_group_ids,
            )
            validation[lam] = res
            if primary is not None and (
                best_weight is None or primary.better(res.primary, best_value)
            ):
                best_weight, best_value = lam, res.primary

    return GLMTrainingResult(
        models=models, trackers=trackers, validation=validation, best_weight=best_weight
    )


def train_glm_streamed(
    chunks: Sequence[dict],
    task: TaskType,
    num_features: int,
    optimizer_config: OptimizerConfig | None = None,
    regularization: RegularizationContext | None = None,
    regularization_weights: Sequence[float] = (0.0,),
    intercept_index: int | None = None,
    validation_chunks: Sequence[dict] | None = None,
    evaluators: Sequence[str] = (),
    initial_model: GeneralizedLinearModel | None = None,
    cross_process: bool = False,
) -> GLMTrainingResult:
    """Out-of-core twin of ``train_glm``: the same ascending-λ warm-started
    sweep, driven by host L-BFGS over a ``StreamingGLMObjective`` (one
    streamed pass per value+gradient evaluation — the reference's Spark
    aggregation pattern; SURVEY.md §7 "Streaming 1B rows").

    ``chunks`` are uniform host chunk dicts (``photon_ml_tpu.ops.streaming``
    builders or ``AvroDataReader.iter_batch_chunks``). Validation scores
    stream chunk-by-chunk; padded rows carry weight 0, which every
    evaluator treats as absent. L1 (OWL-QN) and TRON are not offered on
    this path — the streamed optimizer is L-BFGS.
    """
    from photon_ml_tpu.ops.streaming import StreamingGLMObjective, stream_scores
    from photon_ml_tpu.optim.host_lbfgs import host_lbfgs_minimize
    from photon_ml_tpu.types import RegularizationType

    optimizer_config = optimizer_config or OptimizerConfig()
    has_weights = any(w > 0 for w in regularization_weights)
    if regularization is None:
        # same default as train_glm: nonzero weights imply plain L2
        regularization = RegularizationContext(
            RegularizationType.L2 if has_weights else RegularizationType.NONE
        )
    if regularization.l1_weight(1.0) > 0:
        raise NotImplementedError(
            "L1/elastic-net is not supported on the streaming path (host "
            "L-BFGS only); use the in-memory trainer or L2"
        )
    if regularization.regularization_type is RegularizationType.NONE and has_weights:
        raise ValueError(
            "regularization_weights > 0 with RegularizationType.NONE would be "
            "silently ignored; pass an L2 context or drop the weights"
        )
    loss = loss_for_task(task)
    w = (
        np.asarray(initial_model.coefficients.means, np.float32)
        if initial_model is not None
        else np.zeros((num_features,), np.float32)
    )

    specs = list(evaluators)
    if validation_chunks is not None and not specs:
        specs = {
            TaskType.LOGISTIC_REGRESSION: ["AUC"],
            TaskType.LINEAR_REGRESSION: ["RMSE"],
            TaskType.POISSON_REGRESSION: ["POISSON_LOSS"],
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: ["AUC"],
        }[task]
    primary = make_evaluator(specs[0]) if specs else None

    val_labels = val_weights = val_offsets = None
    if validation_chunks is not None:
        val_labels = np.concatenate([c["labels"] for c in validation_chunks])
        val_weights = np.concatenate([c["weights"] for c in validation_chunks])
        val_offsets = np.concatenate([c["offsets"] for c in validation_chunks])

    models: dict[float, GeneralizedLinearModel] = {}
    trackers: dict[float, OptimizationResult] = {}
    validation: dict[float, EvaluationResults] = {}
    best_weight: float | None = None
    best_value = float("nan")

    # ONE objective for the whole sweep: its per-chunk kernels are built
    # λ-free (λ applied outside the jit), so mutating l2_weight between λs
    # re-enters the same compiled programs — no recompilation across the grid
    sobj = StreamingGLMObjective(
        chunks, loss, num_features=num_features, l2_weight=0.0,
        intercept_index=intercept_index, cross_process=cross_process,
    )
    for lam in sorted(regularization_weights):
        sobj.l2_weight = float(regularization.l2_weight(lam))
        result = host_lbfgs_minimize(sobj, w, optimizer_config)
        w = np.asarray(result.w)  # warm start the next λ
        model = GeneralizedLinearModel(Coefficients(result.w, None), task)
        models[lam] = model
        trackers[lam] = result

        if validation_chunks is not None and specs:
            n_val = len(val_labels)
            margins = stream_scores(
                validation_chunks, w, num_rows=n_val, num_features=num_features
            )
            res = evaluate_all(
                specs,
                jnp.asarray(margins + val_offsets),
                jnp.asarray(val_labels),
                jnp.asarray(val_weights),
            )
            validation[lam] = res
            if primary is not None and (
                best_weight is None or primary.better(res.primary, best_value)
            ):
                best_weight, best_value = lam, res.primary

    return GLMTrainingResult(
        models=models, trackers=trackers, validation=validation, best_weight=best_weight
    )
