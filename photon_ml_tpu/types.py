"""Core shared types.

Reference parity: ``photon-api::ml.Types`` (CoordinateId, FeatureShardId, REId,
UniqueSampleId type aliases) and ``photon-api::ml.TaskType`` (SURVEY.md §2.2).

In the TPU build, entity ids (``REId``) are *integer-encoded at ingest* (the
reference carries strings through the cluster and hashes them during the
group-by-entity shuffle; we build an entity index map once on the host so the
device only ever sees dense ``int32`` ids — see ``data.entity_index``).
"""

from __future__ import annotations

import enum

# Type aliases (host-side). On device, entity ids are int32 arrays.
CoordinateId = str
FeatureShardId = str
REType = str  # random-effect type, e.g. "userId" — the name of the id column
REId = str  # a single entity's id value (host side; int-encoded for device)
UniqueSampleId = int


class TaskType(enum.Enum):
    """Training task types.

    Parity: ``photon-api::ml.TaskType`` — LOGISTIC_REGRESSION,
    LINEAR_REGRESSION, POISSON_REGRESSION, SMOOTHED_HINGE_LOSS_LINEAR_SVM.
    """

    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"

    @property
    def is_classification(self) -> bool:
        return self in (
            TaskType.LOGISTIC_REGRESSION,
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )


class OptimizerType(enum.Enum):
    """Parity: ``photon-lib::ml.optimization.OptimizerType`` (LBFGS, TRON).

    OWLQN is selected implicitly when L1 regularization is active, matching
    the reference's behavior. NEWTON_CHOLESKY is a TPU-first EXTENSION
    beyond the reference: exact damped Newton for small-d problems (dense
    features), built for the per-entity random-effect solves where a
    batched (d, d) Cholesky converges in a few big fused kernels instead
    of many small sequential ones.
    """

    LBFGS = "LBFGS"
    TRON = "TRON"
    NEWTON_CHOLESKY = "NEWTON_CHOLESKY"


class RegularizationType(enum.Enum):
    """Parity: ``photon-lib::ml.optimization.RegularizationType``."""

    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


class NormalizationType(enum.Enum):
    """Parity: ``photon-api::ml.normalization.NormalizationType``."""

    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


class VarianceComputationType(enum.Enum):
    """Parity: ``photon-api::ml.optimization.VarianceComputationType``."""

    NONE = "NONE"
    SIMPLE = "SIMPLE"  # inverse of Hessian diagonal
    FULL = "FULL"  # diagonal of inverse full Hessian


class DataValidationType(enum.Enum):
    """Parity: ``photon-client::ml.data.DataValidators`` modes."""

    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"


class ModelOutputMode(enum.Enum):
    """Parity: ``photon-client::ml.io.ModelOutputMode``."""

    NONE = "NONE"
    BEST = "BEST"
    ALL = "ALL"
