"""Run-report rendering: load, validate, summarize and diff telemetry runs.

The ``photon-ml-tpu report`` CLI's engine. A summary answers the question
every on-chip sweep needs answered per run — where did the wall go
(per-phase span seconds), how much was XLA compile, how much was
host→device transfer, what did the optimizers do — and ``diff`` lines two
runs up so a knob sweep (``PHOTON_PREFETCH_DEPTH``,
``PHOTON_PIPELINE_SEGMENTS``, …) reads as a table instead of two log
greps. Phases are the first ``/`` segment of span names (``descent/iter``
→ ``descent``); a phase's wall is the UNION of its phase-entry spans'
time intervals (entry = parent outside the phase), so neither nesting
nor concurrent worker-thread spans double-count. Phases may still
overlap EACH OTHER in wall time — a prefetch worker's ``ingest`` span
running under a consumer's ``cv`` span is real pipelining, so the phase
column can legitimately sum past the run's wall.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any

from photon_ml_tpu.obs.sink import SCHEMA_VERSION

# fleet shard files: run-<id>.p<k>.jsonl (processes 1..N-1 of one run,
# next to process 0's canonical run-<id>.jsonl)
_SHARD_RE = re.compile(r"\.p(\d+)\.jsonl$")

_SPAN_REQUIRED = ("name", "span_id", "dur_s", "t")


def load_run(path: str) -> list[dict]:
    """Parse one run's JSONL into records (raises on unparseable lines —
    the atomic-rotate sink never commits a torn tail, so a parse failure
    means the file is not a telemetry run)."""
    records = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i + 1}: not JSONL: {e}") from e
    return records


def validate_run(records: list[dict]) -> list[str]:
    """Schema check; returns a list of problems (empty = valid)."""
    errors = []
    if not records:
        return ["empty run (no records)"]
    head = records[0]
    if head.get("event") != "run_start":
        errors.append("first record is not run_start")
    elif head.get("schema_version") != SCHEMA_VERSION:
        errors.append(
            f"schema_version {head.get('schema_version')!r} != "
            f"{SCHEMA_VERSION} (this reader)"
        )
    for i, r in enumerate(records):
        if "event" not in r or "t" not in r:
            errors.append(f"record {i}: missing 'event'/'t'")
            continue
        if r["event"] == "span":
            missing = [k for k in _SPAN_REQUIRED if k not in r]
            if missing:
                errors.append(f"record {i}: span missing {missing}")
    return errors


def _phase(name: str) -> str:
    return name.split("/", 1)[0]


def _union_seconds(intervals: list[tuple[float, float]]) -> float:
    """Total seconds covered by a set of (start, end) intervals."""
    total = 0.0
    end = -float("inf")
    for lo, hi in sorted(intervals):
        if hi <= end:
            continue
        total += hi - max(lo, end)
        end = hi
    return total


def summarize_run(path: str, records: list[dict] | None = None) -> dict:
    """One run's JSONL → a JSON-plain summary dict. ``records`` skips
    the re-read when the caller already parsed the file (the fleet
    summarizer loads each shard once for the P2P-event join)."""
    if records is None:
        records = load_run(path)
    errors = validate_run(records)
    if errors:
        raise ValueError(f"{path}: invalid telemetry run: {errors}")

    spans = [r for r in records if r["event"] == "span"]
    by_id = {r["span_id"]: r for r in spans}
    run_start = records[0]
    run_end = next(
        (r for r in records if r["event"] == "run_end"), None
    )
    t_last = max(float(r["t"]) for r in records)

    phases: dict[str, dict] = {}
    entry_intervals: dict[str, list[tuple[float, float]]] = {}
    for s in spans:
        ph = _phase(s["name"])
        agg = phases.setdefault(ph, {"wall_s": 0.0, "spans": 0})
        agg["spans"] += 1
        parent = by_id.get(s.get("parent_id"))
        # only phase-entry spans contribute wall (children re-cover the
        # same seconds), and entry intervals are UNIONED so concurrent
        # worker-thread spans of one phase don't double-count either
        if parent is None or _phase(parent["name"]) != ph:
            t0 = float(s["t"])
            entry_intervals.setdefault(ph, []).append(
                (t0, t0 + float(s["dur_s"]))
            )
    for ph, intervals in entry_intervals.items():
        phases[ph]["wall_s"] = _union_seconds(intervals)

    events: dict[str, int] = {}
    for r in records:
        events[r["event"]] = events.get(r["event"], 0) + 1

    # leaf XLA compiles only (jax nests backend_compile inside broader
    # "compile" events — summing every match would double-count)
    compile_s = sum(
        float(r.get("dur_s", 0.0))
        for r in records
        if r["event"] == "jax_event"
        and "backend_compile" in str(r.get("name", ""))
    )
    metrics = (run_end or {}).get("metrics", {})
    timers = metrics.get("timers", {})
    base_timers = run_start.get("metrics_baseline", {}).get("timers", {})

    def timer_s(name: str) -> float:
        # delta against the run_start baseline: the registry is process-
        # cumulative, and a second run in the same process must not
        # inherit the first run's seconds
        end = float(timers.get(name, {}).get("seconds", 0.0))
        base = float(base_timers.get(name, {}).get("seconds", 0.0))
        return max(end - base, 0.0)

    counters = metrics.get("counters", {})
    base_counters = run_start.get("metrics_baseline", {}).get("counters", {})

    def counter_v(name: str) -> float:
        # same run_start-baseline delta as timer_s: the registry is
        # process-cumulative, this run's share only
        end = float(counters.get(name, {}).get("value", 0.0))
        base = float(base_counters.get(name, {}).get("value", 0.0))
        return max(end - base, 0.0)

    # random-effect bucket-solve lane accounting (re_solve.* counters,
    # game/random_effect): executed = lane-iterations the launches ran,
    # useful = lane-iterations before each lane converged; their gap is
    # the wasted lockstep work the compaction knob exists to remove
    executed = counter_v("re_solve.executed_entity_iterations")
    useful = counter_v("re_solve.useful_entity_iterations")
    re_solve = {
        "launches": counter_v("re_solve.launches"),
        "executed_entity_iterations": executed,
        "useful_entity_iterations": useful,
        "wasted_lane_fraction": (
            1.0 - useful / executed if executed > 0 else None
        ),
    }

    # entity-sharded placement gauges (re_shard.*, parallel/placement +
    # the overlapped-exchange ratio from parallel/multihost): per-shard
    # load (Σ rows), max/mean balance, and the fraction of exchange wall
    # hidden behind other work — the scale-out counterpart of the
    # wasted-lane accounting below
    metrics_gauges = metrics.get("gauges", {})
    re_shard = {
        k[len("re_shard."):]: float(v)
        for k, v in metrics_gauges.items()
        if k.startswith("re_shard.") and isinstance(v, (int, float))
    } or None

    # fixed-effect feature-range sharding gauges (fe_shard.*,
    # ops/streaming under PHOTON_FE_SHARD): range count, this process's
    # range width and local nnz, and the planner's nnz balance ratio —
    # the FEATURE-axis counterpart of the re_shard row-placement block
    fe_shard = {
        k[len("fe_shard."):]: float(v)
        for k, v in metrics_gauges.items()
        if k.startswith("fe_shard.") and isinstance(v, (int, float))
    } or None

    optim = [r for r in records if r["event"] == "optim_result"]
    reasons: dict[str, int] = {}
    for r in optim:
        reasons[str(r.get("reason"))] = reasons.get(str(r.get("reason")), 0) + 1

    # precision-ladder quality parity (BASELINE protocol: speed is never
    # reported without a parity check): a reduced-precision bench run
    # emits a quality_parity event with its AUC/RMSE/loss deltas against
    # the f32 anchor — surfaced here so a dtype sweep reads its quality
    # gate from the same report as its wall numbers
    quality_parity = None
    for r in records:
        if r["event"] == "quality_parity":
            quality_parity = {
                k: v for k, v in r.items() if k not in ("event", "t")
            }

    # analytic device cost (obs/devcost executable_cost records): one
    # roofline row per (capture label, knob tuple) — flops,
    # bytes-accessed, arithmetic intensity, peak memory. Sums are over
    # fresh executables only (the capture layer dedups cache hits).
    # Aggregating across knob tuples would merge precision rungs (a
    # reduced-rung run can capture the same label under both rungs), so
    # a label that appears under several knob tuples gets one row per
    # tuple, suffixed with the knobs that differ.
    by_label_knobs: dict[tuple, dict] = {}
    for r in records:
        if r["event"] != "executable_cost":
            continue
        knobs = r.get("knobs") or {}
        k = (str(r.get("label")), tuple(sorted(knobs.items())))
        agg = by_label_knobs.setdefault(
            k,
            {
                "captures": 0, "flops": 0.0, "bytes_accessed": 0.0,
                "peak_bytes": 0, "peak_is_estimate": False,
                "capture_s": 0.0, "knobs": knobs,
            },
        )
        agg["captures"] += 1
        agg["flops"] += float(r.get("flops") or 0.0)
        agg["bytes_accessed"] += float(r.get("bytes_accessed") or 0.0)
        agg["peak_bytes"] = max(
            agg["peak_bytes"], int(r.get("peak_bytes") or 0)
        )
        agg["peak_is_estimate"] = agg["peak_is_estimate"] or bool(
            r.get("peak_is_estimate")
        )
        agg["capture_s"] += float(r.get("capture_s") or 0.0)
    label_variants: dict[str, list] = {}
    for (lab, _), agg in by_label_knobs.items():
        label_variants.setdefault(lab, []).append(agg)
    run_knobs = run_start.get("knobs", {})
    devcost: dict[str, dict] = {}
    for lab, variants in label_variants.items():
        if len(variants) == 1:
            devcost[lab] = variants[0]
            continue
        # naming must be STABLE for gating: the variant matching the
        # RUN'S OWN knobs keeps the bare label (the name a single-variant
        # baseline run produced), and off-run variants (e.g. the f32
        # quality-parity anchor captured inside a bf16 run) are suffixed
        # by their delta vs the run knobs — so adding an anchor capture
        # never renames the run's native metrics out from under a
        # committed baseline
        all_keys = set().union(*(v["knobs"] for v in variants))
        differing_between = sorted(
            kk for kk in all_keys
            if len({repr(v["knobs"].get(kk)) for v in variants}) > 1
        )
        for v in variants:
            diff_vs_run = sorted(
                kk for kk in v["knobs"]
                if repr(v["knobs"][kk]) != repr(run_knobs.get(kk))
            )
            if not diff_vs_run and lab not in devcost:
                # `lab not in devcost`: two variants can BOTH be
                # consistent with the run knobs (one captured with a
                # partial knob dict) — the second must fall through to a
                # suffixed name instead of overwriting the first
                devcost[lab] = v
                continue
            suffix = ",".join(f"{kk}={v['knobs'][kk]}" for kk in diff_vs_run)
            name = f"{lab}[{suffix}]" if diff_vs_run else lab
            if name in devcost:  # disambiguate fully
                suffix = ",".join(
                    f"{kk}={v['knobs'].get(kk)}" for kk in differing_between
                )
                name = f"{lab}[{suffix}]"
            devcost[name] = v
    for agg in devcost.values():
        b = agg["bytes_accessed"]
        agg["arith_intensity"] = (agg["flops"] / b) if b else None

    # runtime HBM axis: budget source (queried vs fallback) + watermark
    # samples from root-span exits; explicit unavailability on backends
    # without memory stats, so "no pressure" and "no instrument" read
    # differently
    gauges = metrics.get("gauges", {})
    budget_ev = [r for r in records if r["event"] == "hbm_budget"]
    wm = [r for r in records if r["event"] == "hbm_watermark"]
    wm_avail = [r for r in wm if r.get("available")]
    # source: the hbm_budget event when one landed, else the persistent
    # hbm.budget_queried gauge (the FIRST budget query of a run can
    # precede sink activation — run_start's own knob snapshot triggers
    # it — and later calls are memoized, so the gauge is the durable
    # record of which source won)
    if budget_ev:
        budget_source = budget_ev[-1].get("source")
    elif gauges.get("hbm.budget_bytes") is not None:
        budget_source = (
            "device_memory_stats"
            if gauges.get("hbm.budget_queried") else "fallback_default"
        )
    else:
        budget_source = None
    hbm = {
        "budget_bytes": (
            budget_ev[-1].get("budget_bytes") if budget_ev
            else gauges.get("hbm.budget_bytes")
        ),
        "budget_source": budget_source,
        "memory_stats_available": (
            bool(wm_avail) if wm else None  # None = never sampled
        ),
        "watermark_samples": len(wm_avail),
        "peak_bytes_in_use": (
            max(int(r.get("peak_bytes_in_use") or 0) for r in wm_avail)
            if wm_avail else None
        ),
    }

    out = {
        "path": os.path.abspath(path),
        "run_id": run_start.get("run_id"),
        "schema_version": run_start.get("schema_version"),
        "knobs": run_start.get("knobs", {}),
        "wall_s": t_last - float(run_start["t"]),
        "complete": run_end is not None,
        "phases": phases,
        "compile_s": compile_s or timer_s("jax.compile_s"),
        "transfer_s": timer_s("prefetch.device_put_s"),
        "host_pack_s": timer_s("prefetch.host_pack_s"),
        "consumer_wait_s": timer_s("prefetch.consumer_wait_s"),
        "events": events,
        "optim": {
            "solves": len(optim),
            "iterations": sum(int(r.get("iterations", 0)) for r in optim),
            "reasons": reasons,
        },
        "re_solve": re_solve,
        "re_shard": re_shard,
        "fe_shard": fe_shard,
        "quality_parity": quality_parity,
        "devcost": devcost,
        "hbm": hbm,
        "warnings": sum(
            1 for r in records
            if r["event"] == "log" and r.get("level") in ("WARN", "ERROR")
        ),
        "metrics": metrics,
    }
    # overlapped-exchange accounting — only on runs that recorded it, so
    # the summary of a fleet-off run stays key-for-key what it was
    if "re_exchange.exchange_s" in timers or \
            "re_exchange.exchange_s" in base_timers:
        out["exchange_s"] = timer_s("re_exchange.exchange_s")
        out["exchange_wait_s"] = timer_s("re_exchange.wait_s")
    # owned-result combine accounting (re_combine.*, game/random_effect):
    # bytes shipped per process by the cross-process combine — the
    # O(P·E·d)-vs-O(E·d) axis of the PHOTON_RE_COMBINE A/B — plus, on
    # the segments arm, the worker-side exchange wall vs the consumer's
    # blocked wait. Present only on runs that combined.
    if "re_combine.exchanges" in counters or \
            "re_combine.exchanges" in base_counters:
        out["re_combine"] = {
            "exchanges": counter_v("re_combine.exchanges"),
            "bytes_sent": counter_v("re_combine.bytes_sent"),
            "exchange_s": timer_s("re_combine.exchange_s"),
            "wait_s": timer_s("re_combine.wait_s"),
            "mode": run_start.get("knobs", {}).get("re_combine"),
        }
    # per-entity feature projection (re_project.*, game/projector): the
    # mean solved-width ratio and the per-lane bytes the subspace solves
    # shaved off the full-width schedule, plus the ladder narrative
    # (per-class support/hash widths) from the re_project event. Present
    # only on projected runs — an unprojected summary stays key-for-key
    # what it was.
    project_events = [r for r in records if r["event"] == "re_project"]
    if (
        metrics_gauges.get("re_project.mean_ratio") is not None
        or project_events
    ):
        out["re_project"] = {
            "mean_ratio": metrics_gauges.get("re_project.mean_ratio"),
            "dims_saved_bytes": metrics_gauges.get(
                "re_project.dims_saved_bytes"
            ),
            "mode": (
                project_events[-1].get("mode") if project_events else None
            ),
            "classes": (
                project_events[-1].get("classes")
                if project_events else None
            ),
        }
    # telemetry-driven re-planning (re_replan.*, game/streaming): checks
    # per iteration, re-plans fired, entities migrated — plus the event
    # narrative report fleet renders
    replan_events = [
        {
            k: r.get(k)
            for k in ("iteration", "coordinate", "imbalance",
                      "threshold", "migrated", "old_balance",
                      "new_balance")
        }
        for r in records if r["event"] == "re_replan"
    ]
    if (
        "re_replan.checks" in counters
        or "re_replan.checks" in base_counters
        or replan_events
    ):
        out["re_replan"] = {
            "checks": counter_v("re_replan.checks"),
            "replans": counter_v("re_replan.count"),
            "migrations": counter_v("re_replan.migrations"),
            "last_imbalance": metrics_gauges.get(
                "re_replan.last_imbalance"
            ),
            "events": replan_events,
        }
    # online serving (serve.*, photon_ml_tpu/serve): the latency section —
    # request/window counts, micro-window wall ("serve.window_s") and fill
    # ("serve.window.occupancy" histogram, mean gauge), the hot working
    # set's byte traffic ("serve.hot.hit_bytes" / "serve.hot.miss_bytes" /
    # "serve.hot.evictions") plus its request-count hit rate, cross-owner
    # forwards ("serve.forwarded"), incremental refreshes
    # ("serve.refresh.count" / "serve.refresh_s") and the loadgen's
    # open-loop percentile gauges. Present only on runs that served — a
    # non-serving summary stays key-for-key what it was.
    if "serve.requests" in counters or "serve.requests" in base_counters:
        out["serve"] = {
            "requests": counter_v("serve.requests"),
            "windows": counter_v("serve.windows"),
            "forwarded": counter_v("serve.forwarded"),
            "window_s": timer_s("serve.window_s"),
            "hot_hit_bytes": counter_v("serve.hot.hit_bytes"),
            "hot_miss_bytes": counter_v("serve.hot.miss_bytes"),
            "hot_evictions": counter_v("serve.hot.evictions"),
            "refreshes": counter_v("serve.refresh.count"),
            "refresh_s": timer_s("serve.refresh_s"),
            "latency_p50_ms": metrics_gauges.get("serve.latency_p50_ms"),
            "latency_p99_ms": metrics_gauges.get("serve.latency_p99_ms"),
            "hot_hit_rate": metrics_gauges.get("serve.hot.hit_rate"),
            "window_occupancy_mean": metrics_gauges.get(
                "serve.window.occupancy_mean"
            ),
        }
        # traffic-driven ownership migration (serve.replan.*): present
        # only when the router re-planned — pre-executor serve summaries
        # stay key-for-key what they were
        if "serve.replan.count" in counters or \
                "serve.replan.count" in base_counters:
            out["serve"]["replans"] = counter_v("serve.replan.count")
            out["serve"]["replan_migrations"] = counter_v(
                "serve.replan.migrations"
            )
    # streaming executor (stream.cache.* / stream.<consumer>.*,
    # ops/stream_executor): the multi-tenant arbiter's byte traffic —
    # "stream.cache.hit_bytes" / "stream.cache.miss_bytes" /
    # "stream.cache.shared_hit_bytes" (hits on entries ANOTHER consumer
    # admitted: the cross-stream dedup) / "stream.cache.evictions" — and
    # a per-consumer breakdown parsed from the wildcard counter family
    # ("stream.<name>.items" / ".hit_bytes" / ".miss_bytes" / ".yields",
    # timer "stream.<name>.wait_s", gauge "stream.<name>.charged_bytes").
    # Present only on executor-on runs — every committed executor-off
    # summary stays key-for-key what it was.
    if "stream.cache.hit_bytes" in counters \
            or "stream.cache.hit_bytes" in base_counters \
            or "stream.cache.miss_bytes" in counters \
            or "stream.cache.miss_bytes" in base_counters:
        consumers: dict = {}
        skip = {"passes", "chunks", "streams", "cache"}
        for cname in set(counters) | set(base_counters):
            parts = cname.split(".")
            if len(parts) != 3 or parts[0] != "stream":
                continue
            name = parts[1]
            if name in skip:
                continue
            c = consumers.setdefault(name, {
                "items": 0.0, "hit_bytes": 0.0, "miss_bytes": 0.0,
                "yields": 0.0,
            })
            if parts[2] in c:
                c[parts[2]] = counter_v(cname)
        for tname in set(timers) | set(base_timers):
            parts = tname.split(".")
            if len(parts) == 3 and parts[0] == "stream" \
                    and parts[2] == "wait_s" and parts[1] not in skip:
                consumers.setdefault(parts[1], {})["wait_s"] = timer_s(
                    tname
                )
        for gname, gval in metrics_gauges.items():
            parts = gname.split(".")
            if len(parts) == 3 and parts[0] == "stream" \
                    and parts[2] == "charged_bytes" and parts[1] not in skip:
                consumers.setdefault(parts[1], {})["charged_bytes"] = gval
        se_cache = (run_end or {}).get("stream_cache") or {}
        out["stream"] = {
            "streams": counter_v("stream.streams"),
            "cache_hit_bytes": counter_v("stream.cache.hit_bytes"),
            "cache_shared_hit_bytes": counter_v(
                "stream.cache.shared_hit_bytes"
            ),
            "cache_miss_bytes": counter_v("stream.cache.miss_bytes"),
            "cache_evictions": counter_v("stream.cache.evictions"),
            "cache_entries": se_cache.get("entries"),
            "cache_bytes": se_cache.get("bytes"),
            "charges": se_cache.get("charges"),
            "consumers": consumers,
        }
    if run_start.get("fleet"):
        out["fleet"] = run_start["fleet"]
    return out


# -- rendering --------------------------------------------------------------

_UNRECORDED = "(unrecorded)"


def _fmt_s(v: float) -> str:
    return f"{v:.3f}s"


def _fmt_qty(v: float | None) -> str:
    """Compact engineering format for flops/bytes (roofline cells)."""
    if v is None:
        return "-"
    v = float(v)
    for div, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(v) >= div:
            return f"{v / div:.2f}{suffix}"
    return f"{v:.0f}"


def _fmt_quality_parity(qp: dict) -> str:
    # every delta/RMSE metric renders — the gate's whole point is that a
    # bad number is impossible to miss next to the wall numbers
    parts = [f"kernel_dtype={qp.get('kernel_dtype')}"]
    for k in sorted(qp):
        if k.endswith("_delta") or "rmse" in k:
            v = qp[k]
            parts.append(f"{k}={v:+.6f}" if isinstance(v, float) else f"{k}={v}")
    return ", ".join(parts)


def format_summary(s: dict) -> str:
    lines = [
        f"run {s['run_id']}  (schema v{s['schema_version']}, "
        f"{'complete' if s['complete'] else 'NO run_end — truncated?'})",
        f"  wall {_fmt_s(s['wall_s'])}   compile {_fmt_s(s['compile_s'])}   "
        f"transfer {_fmt_s(s['transfer_s'])}   "
        f"host-pack {_fmt_s(s['host_pack_s'])}   "
        f"consumer-wait {_fmt_s(s['consumer_wait_s'])}",
        "",
        f"  {'phase':<16} {'wall':>10} {'spans':>7}",
    ]
    for ph, agg in sorted(
        s["phases"].items(), key=lambda kv: -kv[1]["wall_s"]
    ):
        lines.append(
            f"  {ph:<16} {_fmt_s(agg['wall_s']):>10} {agg['spans']:>7}"
        )
    o = s["optim"]
    if o["solves"]:
        reasons = ", ".join(f"{k}×{v}" for k, v in sorted(o["reasons"].items()))
        lines.append(
            f"  optimizer: {o['solves']} solves, {o['iterations']} "
            f"iterations ({reasons})"
        )
    rs = s.get("re_solve") or {}
    if rs.get("executed_entity_iterations"):
        lines.append(
            f"  re-solve: {int(rs['launches'])} launches, "
            f"{int(rs['executed_entity_iterations'])} executed entity-iters "
            f"({int(rs['useful_entity_iterations'])} useful), "
            f"wasted-lane {rs['wasted_lane_fraction']:.1%}"
        )
    rsh = s.get("re_shard") or {}
    if rsh.get("shards"):
        overlap = rsh.get("exchange_overlap_ratio")
        atoms = rsh.get("atoms")
        split_classes = int(rsh.get("split_classes") or 0)
        lines.append(
            f"  re-shard: {int(rsh['shards'])} shards, rows "
            f"{rsh.get('rows', 0):.0f} "
            f"(max {rsh.get('rows_max', 0):.0f} / mean "
            f"{rsh.get('rows_mean', 0):.1f}), "
            f"balance {rsh.get('balance', 1.0):.3f}x"
            + (
                # placement granularity (PHOTON_RE_SPLIT): how many
                # independently-placeable atoms the balance was achieved
                # over, and how many capacity classes the rule split
                f", atoms {int(atoms)}"
                + (f" ({split_classes} split)" if split_classes else "")
                if atoms is not None else ""
            )
            + (
                f", exchange-overlap {overlap:.1%}"
                if overlap is not None else ""
            )
        )
        # second placement level (PHOTON_RE_DEVICE_SPLIT): this
        # process's owned atoms spread over its LOCAL devices
        dbal = rsh.get("device_balance")
        if dbal is not None:
            lines.append(
                f"  re-shard devices: {int(rsh.get('devices') or 0)} local, "
                f"device balance {dbal:.3f}x"
            )
    fsh = s.get("fe_shard") or {}
    if fsh.get("ranges"):
        lines.append(
            f"  fe-shard: {int(fsh['ranges'])} ranges, width "
            f"{fsh.get('width', 0):.0f}, local nnz "
            f"{fsh.get('nnz_local', 0):.0f}, "
            f"nnz balance {fsh.get('nnz_balance', 1.0):.3f}x"
        )
    rc = s.get("re_combine") or {}
    if rc.get("exchanges"):
        seg = (
            f"  re-combine: {int(rc['exchanges'])} combines, "
            f"{_fmt_qty(rc['bytes_sent'])}B sent"
            + (f" (mode {rc['mode']})" if rc.get("mode") else "")
        )
        if rc.get("exchange_s"):
            seg += (
                f", exch {_fmt_s(rc['exchange_s'])} / wait "
                f"{_fmt_s(rc['wait_s'])}"
            )
        lines.append(seg)
    prj = s.get("re_project") or {}
    if prj.get("mean_ratio") is not None or prj.get("classes"):
        ratio = prj.get("mean_ratio")
        saved = prj.get("dims_saved_bytes")
        lines.append(
            "  re-project:"
            + (f" mode {prj['mode']}," if prj.get("mode") else "")
            + (
                f" mean width ratio {ratio:.3f}"
                if isinstance(ratio, (int, float)) else ""
            )
            + (
                f", {_fmt_qty(saved)}B/lane-row saved"
                if isinstance(saved, (int, float)) else ""
            )
        )
        for c in prj.get("classes") or []:
            lines.append(
                f"    class C={int(c.get('capacity', 0))}: "
                f"support {int(c.get('support_dim', 0))} -> "
                f"dim {int(c.get('dim', 0))}"
                + (" (hashed)" if c.get("hashed") else "")
            )
    rp = s.get("re_replan") or {}
    if rp.get("checks") or rp.get("migrations"):
        lines.append(
            f"  re-plan: {int(rp.get('checks') or 0)} checks, "
            f"{int(rp.get('replans') or 0)} re-plans, "
            f"{int(rp.get('migrations') or 0)} entities migrated"
            + (
                f" (last imbalance {rp['last_imbalance']:.2f}x)"
                if isinstance(rp.get("last_imbalance"), (int, float))
                else ""
            )
        )
    sv = s.get("serve") or {}
    if sv.get("requests"):
        p50, p99 = sv.get("latency_p50_ms"), sv.get("latency_p99_ms")
        lines.append(
            f"  serve: {int(sv['requests'])} requests in "
            f"{int(sv['windows'])} windows"
            + (
                f", p50 {p50:.2f} ms / p99 {p99:.2f} ms"
                if isinstance(p50, (int, float))
                and isinstance(p99, (int, float)) else ""
            )
            + (
                f", occupancy {sv['window_occupancy_mean']:.2f}"
                if isinstance(sv.get("window_occupancy_mean"),
                              (int, float)) else ""
            )
        )
        lines.append(
            f"    hot set: hit rate "
            + (
                f"{sv['hot_hit_rate']:.3f}"
                if isinstance(sv.get("hot_hit_rate"), (int, float))
                else _UNRECORDED
            )
            + f", {_fmt_qty(sv.get('hot_hit_bytes') or 0.0)}B hit / "
            f"{_fmt_qty(sv.get('hot_miss_bytes') or 0.0)}B miss, "
            f"{int(sv.get('hot_evictions') or 0)} evictions"
        )
        if sv.get("forwarded") or sv.get("refreshes"):
            lines.append(
                f"    {int(sv.get('forwarded') or 0)} cross-owner "
                f"forwards, {int(sv.get('refreshes') or 0)} refreshes"
                + (
                    f" ({_fmt_s(sv['refresh_s'])})"
                    if sv.get("refresh_s") else ""
                )
            )
        if sv.get("replans"):
            lines.append(
                f"    traffic re-plan: {int(sv['replans'])} re-plans, "
                f"{int(sv.get('replan_migrations') or 0)} entities "
                f"migrated"
            )
    stm = s.get("stream") or {}
    if stm.get("streams") or stm.get("consumers"):
        lines.append(
            f"  stream executor: {int(stm.get('streams') or 0)} streams, "
            f"{_fmt_qty(stm.get('cache_hit_bytes') or 0.0)}B hit "
            f"({_fmt_qty(stm.get('cache_shared_hit_bytes') or 0.0)}B "
            f"shared) / {_fmt_qty(stm.get('cache_miss_bytes') or 0.0)}B "
            f"miss, {int(stm.get('cache_evictions') or 0)} evictions"
        )
        for name, c in sorted((stm.get("consumers") or {}).items()):
            lines.append(
                f"    {name}: {int(c.get('items') or 0)} items, "
                f"{_fmt_qty(c.get('hit_bytes') or 0.0)}B hit / "
                f"{_fmt_qty(c.get('miss_bytes') or 0.0)}B miss, "
                f"wait {_fmt_s(c.get('wait_s') or 0.0)}, charged "
                f"{_fmt_qty(c.get('charged_bytes') or 0.0)}B, "
                f"{int(c.get('yields') or 0)} yields"
            )
    if s.get("quality_parity"):
        lines.append(
            f"  quality-parity: {_fmt_quality_parity(s['quality_parity'])}"
        )
    dc = s.get("devcost") or {}
    if dc:
        est = any(a.get("peak_is_estimate") for a in dc.values())
        lines.append("")
        lines.append(
            "  analytic device cost (XLA estimates"
            + ("; peak = arg+out+temp estimate" if est else "")
            + "):"
        )
        lines.append(
            f"  {'label':<34} {'flops':>9} {'bytes':>9} {'fl/B':>6} "
            f"{'peak':>9} {'caps':>5}"
        )
        for lab, a in sorted(
            dc.items(), key=lambda kv: -kv[1]["bytes_accessed"]
        ):
            ai = a.get("arith_intensity")
            lines.append(
                f"  {lab:<34} {_fmt_qty(a['flops']):>9} "
                f"{_fmt_qty(a['bytes_accessed']):>9} "
                f"{'-' if ai is None else f'{ai:.1f}':>6} "
                f"{_fmt_qty(a['peak_bytes']):>9} {a['captures']:>5}"
            )
        kd = next(
            (a["knobs"].get("kernel_dtype") for a in dc.values()
             if a.get("knobs", {}).get("kernel_dtype")), None,
        )
        if kd:
            lines.append(f"  (captured under kernel_dtype={kd})")
    hbm = s.get("hbm") or {}
    if hbm.get("budget_bytes") is not None or hbm.get(
        "memory_stats_available"
    ) is not None:
        avail = hbm.get("memory_stats_available")
        wm_txt = (
            f"peak in-use {_fmt_qty(hbm['peak_bytes_in_use'])}B over "
            f"{hbm['watermark_samples']} samples"
            if avail
            else "memory_stats unavailable on this backend"
            if avail is False
            else "no watermark samples"
        )
        src = hbm.get("budget_source")
        lines.append(
            f"  hbm: budget {_fmt_qty(hbm.get('budget_bytes'))}B"
            + (f" ({src})" if src else "")
            + f"; {wm_txt}"
        )
    if s["warnings"]:
        lines.append(f"  warnings: {s['warnings']}")
    if s["knobs"]:
        lines.append(f"  knobs: {json.dumps(s['knobs'], sort_keys=True)}")
    return "\n".join(lines)


def diff_summaries(a: dict, b: dict) -> str:
    """Two runs side by side: per-phase wall, compile/transfer split, knob
    deltas — the sweep-readout format."""
    lines = [
        f"A: {a['run_id']}  ({os.path.basename(a['path'])})",
        f"B: {b['run_id']}  ({os.path.basename(b['path'])})",
        "",
        f"  {'':<16} {'A':>10} {'B':>10} {'B/A':>7}",
    ]

    def row(label: str, va: float, vb: float):
        ratio = (vb / va) if va > 0 else float("inf") if vb > 0 else 1.0
        lines.append(
            f"  {label:<16} {_fmt_s(va):>10} {_fmt_s(vb):>10} {ratio:>7.2f}"
        )

    row("wall", a["wall_s"], b["wall_s"])
    for ph in sorted(set(a["phases"]) | set(b["phases"])):
        row(
            ph,
            a["phases"].get(ph, {}).get("wall_s", 0.0),
            b["phases"].get(ph, {}).get("wall_s", 0.0),
        )
    row("compile", a["compile_s"], b["compile_s"])
    row("transfer", a["transfer_s"], b["transfer_s"])
    row("host-pack", a["host_pack_s"], b["host_pack_s"])
    row("consumer-wait", a["consumer_wait_s"], b["consumer_wait_s"])
    ra, rb = a.get("re_solve") or {}, b.get("re_solve") or {}
    if ra.get("executed_entity_iterations") or rb.get("executed_entity_iterations"):
        # the wasted-lane column: the knob-sweep readout for
        # PHOTON_RE_COMPACT_EVERY / PHOTON_RE_FUSE_BUCKETS
        def pct(v):
            return "-" if v is None else f"{v:.1%}"

        lines.append(
            f"  {'wasted-lane':<16} "
            f"{pct(ra.get('wasted_lane_fraction')):>10} "
            f"{pct(rb.get('wasted_lane_fraction')):>10}"
        )
        lines.append(
            f"  {'exec-entity-it':<16} "
            f"{int(ra.get('executed_entity_iterations') or 0):>10} "
            f"{int(rb.get('executed_entity_iterations') or 0):>10}"
        )
    sha, shb = a.get("re_shard") or {}, b.get("re_shard") or {}
    if sha.get("shards") or shb.get("shards"):
        # the per-shard load-balance line, next to the wasted-lane
        # column: the placement-sweep readout for PHOTON_RE_SHARD
        def bal(v):
            return "-" if v is None else f"{v:.3f}x"

        def pct2(v):
            return "-" if v is None else f"{v:.1%}"

        lines.append(
            f"  {'shard-balance':<16} {bal(sha.get('balance')):>10} "
            f"{bal(shb.get('balance')):>10}"
        )
        lines.append(
            f"  {'shard-rows-max':<16} "
            f"{sha.get('rows_max', 0):>10.0f} "
            f"{shb.get('rows_max', 0):>10.0f}"
        )
        lines.append(
            f"  {'exch-overlap':<16} "
            f"{pct2(sha.get('exchange_overlap_ratio')):>10} "
            f"{pct2(shb.get('exchange_overlap_ratio')):>10}"
        )
    da, db = a.get("devcost") or {}, b.get("devcost") or {}
    if da or db:
        # the knob-keyed byte-delta readout: the dtype-ladder /
        # groups-per-run sweeps read their analytic traffic change here
        lines.append("  analytic bytes-accessed (per executable label):")
        for lab in sorted(set(da) | set(db)):
            va = (da.get(lab) or {}).get("bytes_accessed", 0.0)
            vb = (db.get(lab) or {}).get("bytes_accessed", 0.0)
            ratio = (
                f"{vb / va:.2f}" if va else ("inf" if vb else "1.00")
            )
            lines.append(
                f"    {lab:<32} {_fmt_qty(va):>9} {_fmt_qty(vb):>9} "
                f"{ratio:>7}"
            )
    qa, qb = a.get("quality_parity"), b.get("quality_parity")
    if qa or qb:
        lines.append("  quality-parity:")
        lines.append(
            f"    A: {_fmt_quality_parity(qa) if qa else _UNRECORDED}"
        )
        lines.append(
            f"    B: {_fmt_quality_parity(qb) if qb else _UNRECORDED}"
        )
    ka, kb = a.get("knobs", {}), b.get("knobs", {})
    # a knob only one run recorded (an older-schema run, or a pre-knob
    # baseline) renders as "(unrecorded)" instead of being dropped — an
    # asymmetric PHOTON_KERNEL_DTYPE is a real config delta, and the
    # `(k in ka) != (k in kb)` term keeps it even when .get() values
    # would coincide (e.g. a knob legitimately recorded as None)
    knob_keys = set(ka) | set(kb)
    knob_diffs = {
        k: (
            ka[k] if k in ka else _UNRECORDED,
            kb[k] if k in kb else _UNRECORDED,
        )
        for k in sorted(knob_keys)
        if (k in ka) != (k in kb) or ka.get(k) != kb.get(k)
    }
    if knob_diffs:
        lines.append("  knob deltas:")
        for k, (va, vb) in knob_diffs.items():
            lines.append(f"    {k}: {va!r} -> {vb!r}")
    return "\n".join(lines)


def latest_run(directory: str) -> str | None:
    """Newest CANONICAL ``run-*.jsonl`` in a telemetry directory (mtime
    order). ``.p<k>`` fleet shards are excluded — the newest run of a
    fleet directory is its process-0 file, exactly what every
    single-process consumer expects."""
    runs = [
        os.path.join(directory, f)
        for f in os.listdir(directory)
        if f.startswith("run-") and f.endswith(".jsonl")
        and not _SHARD_RE.search(f)
    ]
    return max(runs, key=os.path.getmtime) if runs else None


def fleet_run_paths(path: str, run_id: str | None = None) -> list[str]:
    """All files of one fleet run, canonical first: given a telemetry
    directory (newest canonical run, or ``run_id``), a canonical run
    file, or any one shard, return ``[run-<id>.jsonl,
    run-<id>.p1.jsonl, …]`` in ascending process order. A run with no
    shards returns just its canonical file, so every fleet entry point
    degrades to the single-process view."""
    if os.path.isdir(path):
        if run_id is not None:
            canonical = os.path.join(path, f"run-{run_id}.jsonl")
            if not os.path.exists(canonical):
                raise ValueError(
                    f"no run-{run_id}.jsonl in {path}"
                )
        else:
            canonical = latest_run(path)
            if canonical is None:
                raise ValueError(f"no run-*.jsonl files in {path}")
    else:
        canonical = path
        m = _SHARD_RE.search(canonical)
        if m:  # a shard was named: walk back to its canonical file
            canonical = canonical[: m.start()] + ".jsonl"
        if not canonical.endswith(".jsonl"):
            raise ValueError(
                f"not a telemetry run file (want *.jsonl): {canonical}"
            )
        if not os.path.exists(canonical):
            raise ValueError(f"canonical run file missing: {canonical}")
    base = os.path.basename(canonical)
    directory = os.path.dirname(canonical) or "."
    stem = base[: -len(".jsonl")]
    shard_re = re.compile(re.escape(stem) + r"\.p(\d+)\.jsonl$")
    shards: dict[int, str] = {}
    for f in os.listdir(directory):
        m = shard_re.fullmatch(f)
        if m:
            shards[int(m.group(1))] = os.path.join(directory, f)
    return [canonical] + [shards[k] for k in sorted(shards)]


# -- fleet view --------------------------------------------------------------
#
# ``photon-ml-tpu report fleet RUNDIR`` joins one run's canonical file
# and its per-process shards into the cross-process readout the on-chip
# multichip sweeps gate on: a per-process phase-wall table, a straggler
# summary (max/median/imbalance per phase, slowest process named), a
# per-link P2P table built by joining the correlated ``p2p_send`` /
# ``p2p_recv`` events the framed exchange emits on both ends of every
# link (one-sided wait = recv-start − send-start; same-host clocks on
# the loopback harness, NTP-disciplined hosts on a pod — cross-host
# skew shows up as negative waits, which clip to zero), and an
# unmatched-event count as a telemetry-health signal (a clean run joins
# every pair; unmatched events mean a torn mesh, a lost shard file, or
# a truncated run).


def _p2p_link_table(records_by_process: dict[int, list[dict]]) -> dict:
    """Join correlated send/recv events across all shards of one run."""
    sends: dict[str, dict] = {}
    recvs: dict[str, dict] = {}
    duplicates = 0
    heartbeats = 0
    for recs in records_by_process.values():
        for r in recs:
            ev = r.get("event")
            if ev == "p2p_heartbeat":
                heartbeats += 1
                continue
            if ev not in ("p2p_send", "p2p_recv"):
                continue
            corr = str(r.get("corr"))
            side = sends if ev == "p2p_send" else recvs
            if corr in side:
                duplicates += 1
            side[corr] = r
    links: dict[str, dict] = {}

    def link_agg(corr: str) -> dict | None:
        # corr = "p2p:<src>><dst>#<seq>"
        m = re.fullmatch(r"p2p:(\d+)>(\d+)#\d+", corr)
        if m is None:
            return None
        return links.setdefault(
            f"{m.group(1)}->{m.group(2)}",
            {
                "transfers": 0, "bytes": 0, "rows": 0,
                "send_s": 0.0, "recv_s": 0.0,
                "one_sided_wait_s": 0.0, "matched": 0,
                "tags": [],
            },
        )

    matched = 0
    for corr, s in sends.items():
        agg = link_agg(corr)
        if agg is None:
            continue
        agg["transfers"] += 1
        agg["bytes"] += int(s.get("bytes") or 0)
        agg["rows"] += int(s.get("rows") or 0)
        agg["send_s"] += float(s.get("dur_s") or 0.0)
        t = str(s.get("tag") or "")
        if t and t not in agg["tags"]:
            agg["tags"].append(t)
        r = recvs.get(corr)
        if r is None:
            continue
        matched += 1
        agg["matched"] += 1
        agg["recv_s"] += float(r.get("dur_s") or 0.0)
        agg["one_sided_wait_s"] += max(
            float(r.get("t_start") or 0.0) - float(s.get("t_start") or 0.0),
            0.0,
        )
    # recv-only correlations still surface on their link rows
    for corr, r in recvs.items():
        if corr in sends:
            continue
        agg = link_agg(corr)
        if agg is None:
            continue
        agg["transfers"] += 1
        agg["recv_s"] += float(r.get("dur_s") or 0.0)
    unmatched = (len(sends) - matched) + (len(recvs) - matched)
    for agg in links.values():
        agg["tags"] = sorted(agg["tags"])
    return {
        "links": {k: links[k] for k in sorted(links)},
        "sends": len(sends),
        "recvs": len(recvs),
        "matched": matched,
        "unmatched": unmatched,
        "duplicate_correlations": duplicates,
        "heartbeats": heartbeats,
    }


def summarize_fleet(paths: list[str]) -> dict:
    """All shards of one run → the merged fleet view (JSON-plain)."""
    if not paths:
        raise ValueError("no run files to summarize")
    processes: dict[str, dict] = {}
    records_by_process: dict[int, list[dict]] = {}
    expected = None
    for p in paths:
        records = load_run(p)
        errors = validate_run(records)
        if errors:
            raise ValueError(f"{p}: invalid telemetry run: {errors}")
        pidx = int(records[0].get("process_index", 0))
        if pidx in records_by_process:
            raise ValueError(
                f"{p}: duplicate process index {pidx} in fleet run"
            )
        records_by_process[pidx] = records
        s = summarize_run(p, records=records)
        s["process_index"] = pidx
        processes[str(pidx)] = s
        fleet_info = records[0].get("fleet") or {}
        if fleet_info.get("process_count"):
            expected = int(fleet_info["process_count"])
    pidxs = sorted(records_by_process)
    run_ids = {s["run_id"] for s in processes.values()}
    if len(run_ids) > 1:
        raise ValueError(f"shards disagree on run_id: {sorted(run_ids)}")

    # per-process phase walls + straggler summary. Imbalance is
    # max/median over ALL processes (absent phases count 0.0): a phase
    # only one process runs — ingest on the data-holding host, say — is
    # by definition maximally imbalanced, which is exactly what a
    # straggler table must say.
    from statistics import median

    phase_names = sorted(
        {ph for s in processes.values() for ph in s["phases"]}
    )
    phases: dict[str, dict] = {}
    for ph in phase_names:
        walls = {
            k: float(s["phases"].get(ph, {}).get("wall_s", 0.0))
            for k, s in processes.items()
        }
        mx = max(walls.values())
        med = median(list(walls.values()))
        slowest = max(walls, key=lambda k: walls[k])
        phases[ph] = {
            "per_process": walls,
            "max_s": mx,
            "median_s": med,
            "imbalance": (mx / med) if med > 0 else None,
            "slowest": int(slowest),
        }
    walls_total = {
        k: float(s["wall_s"]) for k, s in processes.items()
    }
    slowest_proc = max(walls_total, key=lambda k: walls_total[k]) \
        if walls_total else "0"

    overlap = {
        k: (s.get("re_shard") or {}).get("exchange_overlap_ratio")
        for k, s in processes.items()
        if (s.get("re_shard") or {}).get("exchange_overlap_ratio")
        is not None
    }
    # retry/recovery health (the PR-11 fault-tolerance tier): per-link
    # retries and corruption detections are transient absorption (the
    # run still completed); giveups, peer losses and recoveries mark a
    # degraded topology the reader must know about before trusting any
    # imbalance number in this table.
    recovery: dict = {
        "p2p_retries": 0, "p2p_giveups": 0, "drain_errors": 0,
        "faults_injected": 0, "peer_lost": [], "recoveries": [],
        "roll_calls": [], "degraded_descents": [], "rejoins": [],
    }
    replans: list[dict] = []
    retry_by_error: dict[str, int] = {}
    for pidx, recs in records_by_process.items():
        for r in recs:
            ev = r.get("event")
            if ev == "re_replan":
                # ONE fleet decision: every process emits the identical
                # event (the re-plan is computed from allgathered walls),
                # so dedup by (iteration, coordinate) and collect the
                # emitting processes — P copies rendered as P distinct
                # re-plans would read as P·migrated entities moved
                key = (r.get("iteration"), r.get("coordinate"))
                entry = next(
                    (
                        e for e in replans
                        if (e["iteration"], e["coordinate"]) == key
                    ),
                    None,
                )
                if entry is None:
                    replans.append(
                        {
                            "processes": [pidx],
                            "iteration": r.get("iteration"),
                            "coordinate": r.get("coordinate"),
                            "imbalance": r.get("imbalance"),
                            "migrated": r.get("migrated"),
                        }
                    )
                else:
                    entry["processes"].append(pidx)
            elif ev == "p2p_retry":
                recovery["p2p_retries"] += 1
                err = str(r.get("error") or "?")
                retry_by_error[err] = retry_by_error.get(err, 0) + 1
            elif ev == "p2p_giveup":
                recovery["p2p_giveups"] += 1
            elif ev == "exchange_drain_error":
                recovery["drain_errors"] += 1
            elif ev == "fault_injected":
                recovery["faults_injected"] += 1
            elif ev == "peer_lost":
                recovery["peer_lost"].append(
                    {"process": pidx, "peer": r.get("peer")}
                )
            elif ev == "recovery":
                recovery["recoveries"].append(
                    {
                        "process": pidx,
                        "survivors": r.get("survivors"),
                        "lost": r.get("lost"),
                    }
                )
            elif ev == "roll_call":
                recovery["roll_calls"].append(
                    {
                        "process": pidx,
                        "survivors": r.get("survivors"),
                        "lost": r.get("lost"),
                    }
                )
            elif ev == "degraded_descent":
                # the in-memory descent degraded IN PLACE (no restart,
                # no checkpoint re-entry): every survivor emits one
                recovery["degraded_descents"].append(
                    {
                        "process": pidx,
                        "iteration": r.get("iteration"),
                        "survivors": r.get("survivors"),
                        "lost": r.get("lost"),
                    }
                )
            elif ev == "rejoin":
                recovery["rejoins"].append(
                    {
                        "process": pidx,
                        "role": r.get("role"),
                        "rejoined": r.get("rejoined"),
                        "group": r.get("group"),
                        "migrated": r.get("migrated"),
                    }
                )
    recovery["retry_errors"] = dict(sorted(retry_by_error.items()))
    exchange = {
        k: {
            "exchange_s": s["exchange_s"],
            "wait_s": s["exchange_wait_s"],
        }
        for k, s in processes.items()
        if "exchange_s" in s
    }
    # owned-result combine traffic per process + fleet total (the
    # PHOTON_RE_COMBINE A/B axis at fleet granularity)
    combine_pp = {
        k: (s.get("re_combine") or {})
        for k, s in processes.items()
        if s.get("re_combine")
    }
    combine = None
    if combine_pp:
        combine = {
            "bytes_sent_total": float(
                sum(c.get("bytes_sent") or 0 for c in combine_pp.values())
            ),
            "per_process": {
                k: float(c.get("bytes_sent") or 0)
                for k, c in combine_pp.items()
            },
            "mode": next(
                (c.get("mode") for c in combine_pp.values()
                 if c.get("mode")), None,
            ),
        }
    # per-entity projection at fleet granularity: the ladder is
    # replicated (deterministic arithmetic on allreduced activity), so
    # any process's section speaks for the fleet; per-process ratios
    # are surfaced so a disagreeing shard is visible
    project_pp = {
        k: (s.get("re_project") or {})
        for k, s in processes.items()
        if s.get("re_project")
    }
    project = None
    if project_pp:
        first = next(iter(project_pp.values()))
        project = {
            "mode": first.get("mode"),
            "classes": first.get("classes"),
            "per_process_mean_ratio": {
                k: c.get("mean_ratio") for k, c in project_pp.items()
            },
            "mean_ratio": max(
                (
                    float(c["mean_ratio"]) for c in project_pp.values()
                    if isinstance(c.get("mean_ratio"), (int, float))
                ),
                default=None,
            ),
        }
    # online serving at fleet granularity: request/forward totals over
    # the processes that served, the WORST per-process tail (an SLO is a
    # max, not a mean) and the traffic-weighted hot-set hit rate
    serve_pp = {
        k: (s.get("serve") or {})
        for k, s in processes.items()
        if s.get("serve")
    }
    serve = None
    if serve_pp:
        reqs = {
            k: float(c.get("requests") or 0) for k, c in serve_pp.items()
        }
        total_req = sum(reqs.values())
        p99s = [
            float(c["latency_p99_ms"]) for c in serve_pp.values()
            if isinstance(c.get("latency_p99_ms"), (int, float))
        ]
        p50s = [
            float(c["latency_p50_ms"]) for c in serve_pp.values()
            if isinstance(c.get("latency_p50_ms"), (int, float))
        ]
        rates = [
            (reqs[k], float(c["hot_hit_rate"]))
            for k, c in serve_pp.items()
            if isinstance(c.get("hot_hit_rate"), (int, float))
        ]
        serve = {
            "requests_total": total_req,
            "forwarded_total": float(
                sum(c.get("forwarded") or 0 for c in serve_pp.values())
            ),
            "refreshes_total": float(
                sum(c.get("refreshes") or 0 for c in serve_pp.values())
            ),
            "latency_p50_ms_max": max(p50s) if p50s else None,
            "latency_p99_ms_max": max(p99s) if p99s else None,
            "hot_hit_rate": (
                sum(n * r for n, r in rates) / sum(n for n, r in rates)
                if rates and sum(n for n, r in rates) else None
            ),
            "per_process": serve_pp,
        }
    # streaming executor at fleet granularity: arbiter byte totals over
    # the processes that streamed through it (dedup is per-process — the
    # arbiter is process-wide — so totals just sum)
    stream_pp = {
        k: (s.get("stream") or {})
        for k, s in processes.items()
        if s.get("stream")
    }
    stream = None
    if stream_pp:
        stream = {
            "cache_hit_bytes_total": float(sum(
                c.get("cache_hit_bytes") or 0 for c in stream_pp.values()
            )),
            "cache_shared_hit_bytes_total": float(sum(
                c.get("cache_shared_hit_bytes") or 0
                for c in stream_pp.values()
            )),
            "cache_miss_bytes_total": float(sum(
                c.get("cache_miss_bytes") or 0 for c in stream_pp.values()
            )),
            "per_process": stream_pp,
        }
    head = processes[str(pidxs[0])]
    return {
        "run_id": head["run_id"],
        "schema_version": head["schema_version"],
        "knobs": head["knobs"],
        "paths": [os.path.abspath(p) for p in paths],
        "process_count": len(pidxs),
        "expected_process_count": expected,
        "missing_shards": (
            max(expected - len(pidxs), 0) if expected else 0
        ),
        "complete": all(s["complete"] for s in processes.values()),
        "wall_s": max(walls_total.values()) if walls_total else 0.0,
        "phases": phases,
        "straggler": {
            "slowest_process": int(slowest_proc),
            "per_process_wall_s": walls_total,
            "max_imbalance": max(
                (
                    agg["imbalance"]
                    for agg in phases.values()
                    if agg["imbalance"] is not None
                ),
                default=None,
            ),
        },
        "p2p": _p2p_link_table(records_by_process),
        "recovery": recovery,
        "overlap": overlap,
        "exchange": exchange,
        "re_combine": combine,
        "re_project": project,
        "serve": serve,
        "stream": stream,
        "replans": replans,
        "processes": processes,
    }


def _re_shard_fleet_max(fs: dict, name: str) -> float | None:
    """The fleet MAX of one per-process ``re_shard`` gauge — the
    readouts are identical on every process (deterministic planner on
    replicated inputs), so a disagreeing shard (itself a bug) can only
    look worse. ONE rule shared by the fleet render and the fleet
    gate, so the two can never diverge."""
    vals = [
        (s.get("re_shard") or {}).get(name)
        for s in (fs.get("processes") or {}).values()
    ]
    vals = [float(v) for v in vals if isinstance(v, (int, float))]
    return max(vals) if vals else None


def format_fleet(fs: dict) -> str:
    """The fleet-run tables (the human half of ``report fleet``)."""
    pidxs = sorted(int(k) for k in fs["processes"])
    cols = [str(k) for k in pidxs]
    expected = fs.get("expected_process_count")
    head = (
        f"fleet run {fs['run_id']}  (schema v{fs['schema_version']}, "
        f"{fs['process_count']} process"
        f"{'es' if fs['process_count'] != 1 else ''}"
    )
    if fs.get("missing_shards"):
        head += f", {fs['missing_shards']} of {expected} shards MISSING"
    head += ", complete)" if fs["complete"] else ", TRUNCATED?)"
    lines = [head, f"  fleet wall {_fmt_s(fs['wall_s'])}", ""]

    # per-process phase-wall table + straggler columns
    hdr = f"  {'phase':<16}" + "".join(f" {'p' + c:>9}" for c in cols)
    lines.append(hdr + f" {'max':>9} {'imbal':>6}  slowest")
    for ph, agg in sorted(
        fs["phases"].items(), key=lambda kv: -kv[1]["max_s"]
    ):
        row = f"  {ph:<16}" + "".join(
            f" {_fmt_s(agg['per_process'].get(c, 0.0)):>9}" for c in cols
        )
        imb = agg["imbalance"]
        row += (
            f" {_fmt_s(agg['max_s']):>9} "
            f"{'-' if imb is None else f'{imb:.2f}x':>6}  "
            f"p{agg['slowest']}"
        )
        lines.append(row)
    st = fs["straggler"]
    imb = st.get("max_imbalance")
    lines.append(
        f"  straggler: slowest process p{st['slowest_process']} "
        f"(wall {_fmt_s(st['per_process_wall_s'][str(st['slowest_process'])])})"
        + (
            f", worst phase imbalance {imb:.2f}x"
            if imb is not None else ""
        )
    )
    # placement balance + granularity (the fleet MAX of each per-process
    # gauge — same rule the fleet gate applies, one shared helper)
    bal = _re_shard_fleet_max(fs, "balance")
    if bal is not None:
        rows_max = _re_shard_fleet_max(fs, "rows_max")
        fatoms = _re_shard_fleet_max(fs, "atoms")
        fsplit = int(_re_shard_fleet_max(fs, "split_classes") or 0)
        lines.append(
            f"  re-shard: balance {bal:.3f}x"
            + (f", rows max {rows_max:.0f}" if rows_max is not None else "")
            + (
                f", atoms {int(fatoms)}"
                + (f" ({fsplit} split)" if fsplit else "")
                if fatoms is not None else ""
            )
        )
    # second placement level: per-device rows. Unlike the process-level
    # gauges (identical everywhere — deterministic planner on replicated
    # inputs), device loads are PROCESS-LOCAL: each process plans its
    # OWN owned atoms over its OWN local devices. So the table is
    # device x process, same column order as the phase table above.
    dbal = _re_shard_fleet_max(fs, "device_balance")
    if dbal is not None:
        ndev = int(_re_shard_fleet_max(fs, "devices") or 0)
        lines.append(
            f"  re-shard devices: {ndev}/process, "
            f"device balance {dbal:.3f}x (fleet max)"
        )
        for d in range(ndev):
            vals = []
            for c in cols:
                v = (fs["processes"][c].get("re_shard") or {}).get(
                    f"device_rows.{d}"
                )
                vals.append("-" if v is None else f"{v:.0f}")
            lines.append(
                f"  {'device ' + str(d):<16}"
                + "".join(f" {v:>9}" for v in vals)
            )

    if fs.get("overlap") or fs.get("exchange"):
        parts = []
        for c in cols:
            o = fs["overlap"].get(c)
            e = fs["exchange"].get(c) or {}
            seg = f"p{c}"
            if o is not None:
                seg += f" {o:.1%}"
            if e:
                seg += (
                    f" (exch {_fmt_s(e['exchange_s'])}, "
                    f"wait {_fmt_s(e['wait_s'])})"
                )
            parts.append(seg)
        lines.append("  exchange-overlap: " + "  ".join(parts))

    p2p = fs.get("p2p") or {}
    if p2p.get("links"):
        lines.append("")
        lines.append(
            f"  {'link':<8} {'xfers':>6} {'bytes':>9} {'rows':>8} "
            f"{'send':>9} {'wait(1-sided)':>14}  tags"
        )
        for link, a in p2p["links"].items():
            lines.append(
                f"  {link:<8} {a['transfers']:>6} "
                f"{_fmt_qty(a['bytes']):>9} {a['rows']:>8} "
                f"{_fmt_s(a['send_s']):>9} "
                f"{_fmt_s(a['one_sided_wait_s']):>14}  "
                + ",".join(a["tags"])
            )
    health = (
        f"  p2p health: {p2p.get('matched', 0)} correlated pairs, "
        f"{p2p.get('unmatched', 0)} unmatched"
    )
    if p2p.get("duplicate_correlations"):
        health += f", {p2p['duplicate_correlations']} DUPLICATE ids"
    if p2p.get("heartbeats"):
        health += f", {p2p['heartbeats']} blocked-recv heartbeats"
    lines.append(health)
    if p2p.get("unmatched"):
        lines.append(
            "  WARNING: unmatched correlated events — a torn exchange "
            "mesh, a missing shard file, or a truncated run"
        )
    rc = fs.get("re_combine") or {}
    if rc:
        lines.append(
            "  re-combine: "
            f"{_fmt_qty(rc['bytes_sent_total'])}B total"
            + (f" (mode {rc['mode']})" if rc.get("mode") else "")
            + "  "
            + "  ".join(
                f"p{k} {_fmt_qty(v)}B"
                for k, v in sorted(rc["per_process"].items())
            )
        )
    # feature-range sharding at fleet granularity: count/balance are
    # replicated, widths and local nnz are per-range — show the spread
    fe_pp = {
        k: (s.get("fe_shard") or {})
        for k, s in (fs.get("processes") or {}).items()
        if (s.get("fe_shard") or {}).get("ranges")
    }
    if fe_pp:
        first = next(iter(fe_pp.values()))
        widths = [
            v.get("width") for v in fe_pp.values()
            if isinstance(v.get("width"), (int, float))
        ]
        lines.append(
            f"  fe-shard: {int(first.get('ranges') or 0)} ranges, "
            f"nnz balance {float(first.get('nnz_balance') or 1.0):.3f}x"
            + (
                f", widths {min(widths):.0f}..{max(widths):.0f}"
                if widths else ""
            )
        )
    prj = fs.get("re_project") or {}
    if prj:
        ratio = prj.get("mean_ratio")
        lines.append(
            "  re-project:"
            + (f" mode {prj['mode']}," if prj.get("mode") else "")
            + (
                f" mean width ratio {ratio:.3f}"
                if isinstance(ratio, (int, float)) else ""
            )
        )
        for c in prj.get("classes") or []:
            lines.append(
                f"    class C={int(c.get('capacity', 0))}: "
                f"support {int(c.get('support_dim', 0))} -> "
                f"dim {int(c.get('dim', 0))}"
                + (" (hashed)" if c.get("hashed") else "")
            )
    sv = fs.get("serve") or {}
    if sv.get("requests_total"):
        p50m, p99m = sv.get("latency_p50_ms_max"), sv.get(
            "latency_p99_ms_max"
        )
        hr = sv.get("hot_hit_rate")
        lines.append(
            f"  serve: {int(sv['requests_total'])} requests, "
            f"{int(sv.get('forwarded_total') or 0)} cross-owner forwards, "
            f"{int(sv.get('refreshes_total') or 0)} refreshes"
        )
        lines.append(
            "    worst-process tail: "
            + (
                f"p50 {p50m:.2f} ms / p99 {p99m:.2f} ms"
                if isinstance(p50m, (int, float))
                and isinstance(p99m, (int, float)) else _UNRECORDED
            )
            + (
                f", traffic-weighted hot hit rate {hr:.3f}"
                if isinstance(hr, (int, float)) else ""
            )
        )
    stm = fs.get("stream") or {}
    if stm:
        lines.append(
            f"  stream executor: "
            f"{_fmt_qty(stm.get('cache_hit_bytes_total') or 0.0)}B hit "
            f"({_fmt_qty(stm.get('cache_shared_hit_bytes_total') or 0.0)}B "
            f"shared) / "
            f"{_fmt_qty(stm.get('cache_miss_bytes_total') or 0.0)}B miss "
            f"across {len(stm.get('per_process') or {})} process(es)"
        )
    for rp in fs.get("replans") or []:
        procs = rp.get("processes") or []
        lines.append(
            f"  re-plan: iter {rp['iteration']} {rp['coordinate']}: "
            "measured imbalance "
            + (
                f"{rp['imbalance']:.2f}x"
                if isinstance(rp.get("imbalance"), (int, float))
                else "?"
            )
            + f" → migrated {rp.get('migrated')} entities "
            + f"(observed by {len(procs)} process"
            + ("es)" if len(procs) != 1 else ")")
        )
    rec = fs.get("recovery") or {}
    if any(
        rec.get(k)
        for k in (
            "p2p_retries", "p2p_giveups", "drain_errors",
            "faults_injected", "peer_lost", "recoveries",
            "degraded_descents", "rejoins",
        )
    ):
        seg = (
            f"  retry/recovery: {rec.get('p2p_retries', 0)} retries"
        )
        errs = rec.get("retry_errors") or {}
        if errs:
            seg += (
                " ("
                + ", ".join(f"{k}×{v}" for k, v in errs.items())
                + ")"
            )
        seg += (
            f", {rec.get('p2p_giveups', 0)} giveups, "
            f"{rec.get('drain_errors', 0)} drain errors, "
            f"{rec.get('faults_injected', 0)} injected faults"
        )
        lines.append(seg)
        for pl in rec.get("peer_lost") or []:
            lines.append(
                f"    peer_lost: p{pl['process']} lost peer "
                f"{pl['peer']}"
            )
        for rv in rec.get("recoveries") or []:
            lines.append(
                f"    recovery: p{rv['process']} resumed with "
                f"survivors {rv['survivors']} (lost {rv['lost']})"
            )
        for dd in rec.get("degraded_descents") or []:
            lines.append(
                f"    degraded_descent: p{dd['process']} degraded IN "
                f"PLACE at iteration {dd['iteration']} — survivors "
                f"{dd['survivors']} (lost {dd['lost']}, no restart)"
            )
        for rj in rec.get("rejoins") or []:
            mig = rj.get("migrated")
            mig_s = (
                "" if not mig
                else " — migrated back: " + ", ".join(
                    f"{c}:{n}" for c, n in sorted(mig.items())
                )
            )
            lines.append(
                f"    rejoin: p{rj['process']} ({rj.get('role')}) — "
                f"{rj.get('rejoined')} rejoined, group {rj.get('group')}"
                + mig_s
            )
        if rec.get("recoveries") or rec.get("degraded_descents"):
            lines.append(
                "  WARNING: this run degraded mid-flight — wall/"
                "imbalance rows mix pre- and post-recovery topologies"
            )
    if fs["knobs"]:
        lines.append(f"  knobs: {json.dumps(fs['knobs'], sort_keys=True)}")
    return "\n".join(lines)


# -- regression gate --------------------------------------------------------
#
# ``photon-ml-tpu report gate RUN --baseline BASE`` turns the telemetry
# artifact from a passive record into an active tripwire: a flat metric
# dict is extracted from each side (telemetry run JSONL, bench JSON doc,
# or a saved gate-baseline file), every baseline metric is compared
# against the current run under a per-metric threshold, and any breach
# exits nonzero. Thresholds are tiered by what the metric IS: analytic
# cost numbers (devcost flops/bytes) are deterministic for a given
# compiler, so they gate TIGHT; wall-clock metrics are noisy, so they
# gate loose. Regressions are one-sided — fewer bytes/flops/seconds is
# never a failure.

GATE_SCHEMA_VERSION = 1

# pattern -> {"rel": fractional headroom, "abs": additive headroom};
# longest matching substring wins, "" is the default tier
DEFAULT_GATE_THRESHOLDS: dict[str, dict] = {
    "": {"rel": 0.25},
    # wall-clock tiers: real time on shared CI boxes jitters hard
    "wall_s": {"rel": 1.0, "abs": 10.0},
    "compile_s": {"rel": 2.0, "abs": 10.0},
    "transfer_s": {"rel": 1.0, "abs": 5.0},
    "host_pack_s": {"rel": 1.0, "abs": 5.0},
    "consumer_wait_s": {"rel": 2.0, "abs": 5.0},
    "capture_s": {"rel": 4.0, "abs": 10.0},
    # analytic tiers: byte/flop counts move only when code or knobs move
    "devcost/": {"rel": 0.02},
    "packed_stream_bytes": {"rel": 0.01},
    "hbm/": {"rel": 0.10},
    # placement tiers: every planner readout (balance ratios, rows_max)
    # is deterministic for a given planner + row distribution, so the
    # whole re_shard/ family gates TIGHT — a regression is a planner
    # change. The overlap ratio (longest-substring match wins over the
    # prefix tier) is bounded in [0, 1] and higher-is-better, so it
    # gates on PRESENCE only: abs 1.0 headroom can never fail on a
    # value, but a missing gauge still FAILs — losing the instrument
    # must trip the gate.
    "re_shard/": {"rel": 0.05},
    "re_shard/exchange_overlap_ratio": {"abs": 1.0},
    # sub-bucket placement tiers (PHOTON_RE_SPLIT runs only — unsplit
    # runs never emit these keys, so their thresholds are unchanged):
    # the atom ladder is exact deterministic arithmetic on the global
    # bincount, and at atom granularity the LPT balance has far less
    # excuse to drift than the whole-class plan — tight tier
    "re_shard/atoms": {"rel": 0.0, "abs": 0.0},
    "re_shard/balance_split": {"rel": 0.02},
    # device-granularity placement tiers (PHOTON_RE_DEVICE_SPLIT runs
    # only): the per-device LPT is deterministic on the owned-atom
    # weights, so the balance gates tight like balance_split; the
    # launch schedule is exact deterministic fusion-unit arithmetic —
    # one extra launch is a schedule regression, not noise
    "re_shard/device_balance": {"rel": 0.02},
    "re_solve/launches": {"rel": 0.0, "abs": 0.0},
    # combine-traffic tier: bytes per process are deterministic for a
    # given combine mode + placement, so near-tight — a 5% creep is a
    # packing/layout regression, and a mode accidentally falling back
    # to the dense arm shows up as a multiple, not a percent
    "re_combine/": {"rel": 0.05},
    # re-plan tier: exact headroom — like every gate this is ONE-SIDED
    # (cur > baseline fails), so a SPONTANEOUS migration against a
    # healthy baseline trips; the vanishing direction (a straggler
    # drill that stops migrating) is covered by the slow gloo drill's
    # own assertion, not the gate
    "re_replan/migrations": {"rel": 0.0, "abs": 0.0},
    # fleet tiers (the merged cross-process view from ``report fleet``):
    # telemetry-health counts gate EXACT — one unmatched correlated
    # event or one missing shard is a broken instrument, not noise —
    # while wall-derived imbalance gates loose (CPU scheduling jitter
    # moves a 2-process toy run's phase ratios hard). P2P bytes are
    # deterministic for a given router + row distribution: near-tight.
    "fleet/missing_shards": {"rel": 0.0, "abs": 0.0},
    "fleet/unmatched_p2p": {"rel": 0.0, "abs": 0.0},
    "fleet/p2p_bytes_total": {"rel": 0.05},
    # retry/recovery tiers (PR-11 fault-tolerance): a chaos baseline's
    # injected-fault retries may jitter up slightly (scheduler timing
    # can split one backoff into two attempts), but any NEW giveup,
    # drain error, peer loss or recovery is a new failure mode
    "fleet/p2p_retries": {"rel": 1.0, "abs": 2.0},
    "fleet/p2p_giveups": {"rel": 0.0, "abs": 0.0},
    "fleet/exchange_drain_errors": {"rel": 0.0, "abs": 0.0},
    "fleet/peer_lost": {"rel": 0.0, "abs": 0.0},
    "fleet/recoveries": {"rel": 0.0, "abs": 0.0},
    # elastic-fleet tiers: in-place descent degrades and rejoins are
    # deterministic for a committed fault plan — one extra of either is
    # a new failure mode (or a spontaneous rejoin against a healthy
    # baseline), never noise
    "fleet/degraded_descents": {"rel": 0.0, "abs": 0.0},
    "fleet/rejoins": {"rel": 0.0, "abs": 0.0},
    "/imbalance": {"rel": 1.0, "abs": 1.0},
    "exchange_wait_s": {"rel": 2.0, "abs": 5.0},
    "exchange_s": {"rel": 2.0, "abs": 5.0},
    # projection tier (PHOTON_RE_PROJECT runs only — unprojected runs
    # never emit these keys): the mean solved-width ratio is exact
    # deterministic arithmetic on the global activity bincount, so it
    # gates TIGHT — a >2% widening means the ladder (or the data's
    # sparsity structure) changed
    "re_project/": {"rel": 0.02},
    # feature-range sharding tiers (PHOTON_FE_SHARD runs only —
    # unsharded runs never emit these keys): the range count is exact
    # planner arithmetic (one extra range is a planner change, not
    # noise) and the nnz balance is deterministic on the histogram, so
    # it gates as tight as the placement balances above
    "fe_shard/": {"rel": 0.05},
    "fe_shard/ranges": {"rel": 0.0, "abs": 0.0},
    "fe_shard/nnz_balance": {"rel": 0.02},
    # serving tiers (bench --serve / serving runs only): wall-clock
    # latency percentiles jitter like every wall tier, so they gate
    # LOOSE; the hot-set hit rate and mean window occupancy are bounded
    # [0, 1] ratios that gate on PRESENCE (losing the instrument trips,
    # a value never does — the >= 0.8 acceptance floor is the bench
    # doc's own assertion, not the gate's); the two parity flags are
    # bitwise contracts, so they gate EXACT — a refresh that stops
    # matching its offline solve, or a serve path that stops matching
    # the batch score driver, is a correctness break, never noise
    "serve/latency": {"rel": 1.0, "abs": 10.0},
    "serve/hot_hit_rate": {"abs": 1.0},
    "serve/window_occupancy": {"abs": 1.0},
    "serve/refresh_parity": {"rel": 0.0, "abs": 0.0},
    "serve/score_parity": {"rel": 0.0, "abs": 0.0},
    # streaming-executor tiers (PHOTON_STREAM_EXECUTOR runs only —
    # executor-off runs never emit stream/* keys, so every committed
    # baseline stays valid unchanged): arbiter transfer bytes are
    # chunk-shape arithmetic but depend on eviction timing under
    # pressure, so they gate LOOSE; the stream parity flags (bench
    # X_stream) are bitwise contracts and gate EXACT
    "stream/": {"rel": 0.5},
    "stream/cache_evictions": {"rel": 1.0, "abs": 8.0},
    "stream/parity": {"rel": 0.0, "abs": 0.0},
    # quality tiers: deltas vs the f32 anchor, absolute headroom at the
    # parity-gate scale (|ΔAUC| ≤ 0.005 is the ladder's own bf16 gate)
    "quality/": {"rel": 0.0, "abs": 0.005},
    "optim/iterations": {"rel": 0.25, "abs": 2.0},
    "warnings": {"rel": 0.0, "abs": 0.0},
}


def _fmt_gate(v: float | None) -> str:
    """Gate-table cell format: engineering suffixes for big counts, but
    full precision below 1 — the quality/* tier lives at 1e-3..1e-6 and
    ``_fmt_qty`` would render every such value (and its limit) as '0',
    hiding by how much a parity gate was breached."""
    if v is None:
        return "-"
    v = float(v)
    if abs(v) >= 1000:
        return _fmt_qty(v)
    return f"{v:.6g}"


def resolve_threshold(metric: str, thresholds: dict) -> dict:
    """Longest substring-matching pattern wins; ``""`` is the default.
    An explicitly-empty rule (``{}``) is a valid exact gate (no
    headroom), so resolution checks for None, never truthiness."""
    best = ""
    for p in thresholds:
        if p and p in metric and len(p) > len(best):
            best = p
    rule = thresholds.get(best)
    if rule is None:
        rule = thresholds.get("")
    return rule if rule is not None else {"rel": 0.25}


def _qp_metrics(qp: dict, prefix: str = "") -> dict:
    m = {}
    if not qp:
        return m
    for k in ("auc_delta", "loss_rel_delta"):
        if isinstance(qp.get(k), (int, float)):
            m[f"{prefix}quality/{k}_abs"] = abs(float(qp[k]))
    if isinstance(qp.get("margins_rmse_vs_f32"), (int, float)):
        m[f"{prefix}quality/margins_rmse_vs_f32"] = float(
            qp["margins_rmse_vs_f32"]
        )
    return m


def gate_metrics_from_summary(s: dict) -> dict[str, float]:
    """Flatten one telemetry-run summary into gateable metrics."""
    m: dict[str, float] = {}
    for k in ("wall_s", "compile_s", "transfer_s", "host_pack_s",
              "consumer_wait_s", "exchange_s", "exchange_wait_s"):
        # exchange_s/exchange_wait_s exist only on runs that recorded
        # the overlapped-exchange timers; a pre-fleet baseline simply
        # never lists them, so old-vs-new gates stay comparable
        if isinstance(s.get(k), (int, float)):
            m[k] = float(s[k])
    for lab, agg in (s.get("devcost") or {}).items():
        m[f"devcost/{lab}/flops"] = float(agg.get("flops") or 0.0)
        m[f"devcost/{lab}/bytes_accessed"] = float(
            agg.get("bytes_accessed") or 0.0
        )
        if agg.get("peak_bytes"):
            m[f"devcost/{lab}/peak_bytes"] = float(agg["peak_bytes"])
    rsh = s.get("re_shard") or {}
    for k, v in rsh.items():
        if k in ("balance", "rows_max", "exchange_overlap_ratio",
                 "device_balance"):
            m[f"re_shard/{k}"] = float(v)
    if float(rsh.get("split_classes") or 0) > 0:
        # sub-bucket placement (PHOTON_RE_SPLIT) ran: gate the atom
        # count exactly and the balance on the TIGHT split tier — at
        # atom granularity the planner has no excuse for a worse ratio.
        # Unsplit runs never emit these keys, so their thresholds (and
        # committed baselines) are unchanged.
        m["re_shard/atoms"] = float(rsh.get("atoms") or 0)
        m["re_shard/balance_split"] = float(rsh.get("balance") or 1.0)
    fsh = s.get("fe_shard") or {}
    if float(fsh.get("ranges") or 0) > 0:
        # feature-range sharding ran: the range count is exact planner
        # arithmetic and the nnz balance is deterministic on the
        # histogram, so both gate tight. Unsharded runs never emit
        # these keys — their baselines are unchanged.
        m["fe_shard/ranges"] = float(fsh.get("ranges") or 0)
        m["fe_shard/nnz_balance"] = float(fsh.get("nnz_balance") or 1.0)
    rc = s.get("re_combine") or {}
    if isinstance(rc.get("bytes_sent"), (int, float)):
        m["re_combine/bytes_sent"] = float(rc["bytes_sent"])
    prj = s.get("re_project") or {}
    if isinstance(prj.get("mean_ratio"), (int, float)):
        # lower-is-better and deterministic: the tight re_project/ tier
        # catches any widening; dims_saved_bytes is higher-is-better so
        # it rides the report narrative, not the one-sided gate
        m["re_project/mean_ratio"] = float(prj["mean_ratio"])
    rp = s.get("re_replan") or {}
    if rp:
        # exact one-sided tier: a migration APPEARING against the
        # baseline is a planner-behavior change, not noise
        m["re_replan/migrations"] = float(rp.get("migrations") or 0)
    sv = s.get("serve") or {}
    if sv.get("requests"):
        # serving tiers: latency gates loose (wall), the bounded ratios
        # gate on presence — losing the instrument trips, a value never
        # does. Non-serving runs never emit these keys.
        if isinstance(sv.get("latency_p50_ms"), (int, float)):
            m["serve/latency_p50_ms"] = float(sv["latency_p50_ms"])
        if isinstance(sv.get("latency_p99_ms"), (int, float)):
            m["serve/latency_p99_ms"] = float(sv["latency_p99_ms"])
        if isinstance(sv.get("hot_hit_rate"), (int, float)):
            m["serve/hot_hit_rate"] = float(sv["hot_hit_rate"])
        if isinstance(sv.get("window_occupancy_mean"), (int, float)):
            m["serve/window_occupancy"] = float(
                sv["window_occupancy_mean"]
            )
    stm = s.get("stream") or {}
    if stm.get("streams") or stm.get("cache_miss_bytes"):
        # executor tiers: miss bytes (the actual transfer traffic the
        # arbiter paid) and evictions are lower-is-better and gate on
        # the loose stream/ tier; hit bytes are higher-is-better so
        # they ride the report narrative, not the one-sided gate.
        # Executor-off runs never emit these keys.
        m["stream/cache_miss_bytes"] = float(
            stm.get("cache_miss_bytes") or 0
        )
        m["stream/cache_evictions"] = float(
            stm.get("cache_evictions") or 0
        )
    m.update(_qp_metrics(s.get("quality_parity") or {}))
    o = s.get("optim") or {}
    if o.get("solves"):
        m["optim/iterations"] = float(o.get("iterations") or 0)
    m["warnings"] = float(s.get("warnings") or 0)
    hbm = s.get("hbm") or {}
    if hbm.get("peak_bytes_in_use"):
        m["hbm/peak_bytes_in_use"] = float(hbm["peak_bytes_in_use"])
    return m


def gate_metrics_from_bench(doc: dict) -> dict[str, float]:
    """Flatten a ``bench.py`` JSON document (the ``--quick`` single-line
    contract, or one ``--config`` child's result) into gateable metrics,
    namespaced per config. Reads the telemetry block's ``devcost.*`` /
    ``hbm.*`` gauges, the compile timer, the quality-parity gate and the
    per-rung packed-stream bytes — everything a dtype or schedule sweep
    would want tripwired."""
    configs = doc.get("configs")
    if configs is None:
        configs = {"config": doc}
    m: dict[str, float] = {}
    for cfg, r in configs.items():
        if not isinstance(r, dict) or "error" in r:
            continue  # its baseline metrics then read as MISSING -> fail
        tel = r.get("telemetry") or {}
        tmetrics = tel.get("metrics") or {}
        for g, v in (tmetrics.get("gauges") or {}).items():
            if g.startswith("devcost."):
                m[f"{cfg}/devcost/{g[len('devcost.'):]}"] = float(v)
            elif g.startswith("hbm.") and g != "hbm.budget_queried":
                m[f"{cfg}/hbm/{g[len('hbm.'):]}"] = float(v)
            elif g.startswith("re_shard.") and g in (
                "re_shard.balance",
                "re_shard.rows_max",
                "re_shard.round_robin_balance",
                "re_shard.exchange_overlap_ratio",
                "re_shard.device_balance",
            ):
                m[f"{cfg}/re_shard/{g[len('re_shard.'):]}"] = float(v)
            elif g in ("fe_shard.ranges", "fe_shard.nnz_balance"):
                # feature-range sharding readouts (the per-process width
                # and nnz ride the narrative, not the one-sided gate)
                m[f"{cfg}/{g.replace('.', '/', 1)}"] = float(v)
            elif g in ("serve.latency_p50_ms", "serve.latency_p99_ms"):
                # serving latency gauges (loose wall tier via the
                # serve/latency substring)
                m[f"{cfg}/serve/latency{g[len('serve.latency'):]}"] = (
                    float(v)
                )
            elif g == "serve.hot.hit_rate":
                m[f"{cfg}/serve/hot_hit_rate"] = float(v)
            elif g == "serve.window.occupancy_mean":
                m[f"{cfg}/serve/window_occupancy"] = float(v)
        gauges = tmetrics.get("gauges") or {}
        if float(gauges.get("re_shard.split_classes") or 0) > 0:
            # split-granularity tier (mirrors gate_metrics_from_summary)
            m[f"{cfg}/re_shard/atoms"] = float(
                gauges.get("re_shard.atoms") or 0
            )
            m[f"{cfg}/re_shard/balance_split"] = float(
                gauges.get("re_shard.balance") or 1.0
            )
        timers = tmetrics.get("timers") or {}
        if "jax.compile_s" in timers:
            m[f"{cfg}/compile_s"] = float(
                timers["jax.compile_s"].get("seconds") or 0.0
            )
        m.update(
            _qp_metrics(
                tel.get("quality_parity") or r.get("quality_parity") or {},
                prefix=f"{cfg}/",
            )
        )
        if isinstance(r.get("packed_stream_bytes_per_pass"), (int, float)):
            m[f"{cfg}/packed_stream_bytes_per_pass"] = float(
                r["packed_stream_bytes_per_pass"]
            )
        if isinstance(r.get("sec_per_solve"), (int, float)):
            m[f"{cfg}/wall_s"] = float(r["sec_per_solve"])
    return m


def gate_metrics_from_fleet(fs: dict) -> dict[str, float]:
    """Flatten a ``summarize_fleet`` view into gateable metrics — the
    whole-fleet gate the multichip sweeps use, so a balance/overlap
    regression on process 3 trips even though process 0's own summary
    looks fine. Telemetry-health counts (missing shards, unmatched
    correlated events) gate exact; per-phase imbalance and exchange wait
    gate loose (wall-derived); the overlap ratio gates on PRESENCE via
    the standard ``re_shard/exchange_overlap_ratio`` tier, taken as the
    fleet MINIMUM (the worst process is the one a regression hides in)."""
    m: dict[str, float] = {
        "fleet/processes": float(fs.get("process_count") or 0),
        "fleet/missing_shards": float(fs.get("missing_shards") or 0),
        "fleet/unmatched_p2p": float(
            (fs.get("p2p") or {}).get("unmatched") or 0
        ),
        "fleet/wall_s": float(fs.get("wall_s") or 0.0),
    }
    p2p = fs.get("p2p") or {}
    if p2p.get("links"):
        m["fleet/p2p_bytes_total"] = float(
            sum(a["bytes"] for a in p2p["links"].values())
        )
    rec = fs.get("recovery") or {}
    if rec:
        # retry/recovery tier: retries gate LOOSE against a chaos
        # baseline (the committed fault plan fixes the floor, scheduler
        # jitter can add a few); giveups, drain errors, peer losses and
        # recoveries gate EXACT — an extra one of any of these is a new
        # failure mode, not noise
        m["fleet/p2p_retries"] = float(rec.get("p2p_retries") or 0)
        m["fleet/p2p_giveups"] = float(rec.get("p2p_giveups") or 0)
        m["fleet/exchange_drain_errors"] = float(
            rec.get("drain_errors") or 0
        )
        m["fleet/peer_lost"] = float(len(rec.get("peer_lost") or []))
        m["fleet/recoveries"] = float(len(rec.get("recoveries") or []))
        m["fleet/degraded_descents"] = float(
            len(rec.get("degraded_descents") or [])
        )
        m["fleet/rejoins"] = float(len(rec.get("rejoins") or []))
    for ph, agg in (fs.get("phases") or {}).items():
        if agg.get("imbalance") is not None:
            m[f"fleet/phase/{ph}/imbalance"] = float(agg["imbalance"])
    if fs.get("overlap"):
        m["re_shard/exchange_overlap_ratio"] = float(
            min(fs["overlap"].values())
        )
    for k, e in (fs.get("exchange") or {}).items():
        m[f"fleet/p{k}/exchange_wait_s"] = float(e["wait_s"])
    # placement readouts are identical on every process; gate the fleet
    # MAX so one disagreeing shard (itself a bug) can only look worse
    for name in ("balance", "rows_max"):
        v = _re_shard_fleet_max(fs, name)
        if v is not None:
            m[f"re_shard/{name}"] = v
    # device-level sub-plan: loads are process-LOCAL, so the gateable
    # scalar is the fleet MAX of the per-process intra-host balance
    # (the worst host is the one a placement regression hides in)
    v = _re_shard_fleet_max(fs, "device_balance")
    if v is not None:
        m["re_shard/device_balance"] = v
    if (_re_shard_fleet_max(fs, "split_classes") or 0) > 0:
        # split-granularity tier, fleet-wide (mirrors the per-run gate)
        m["re_shard/atoms"] = float(_re_shard_fleet_max(fs, "atoms") or 0)
        m["re_shard/balance_split"] = float(
            _re_shard_fleet_max(fs, "balance") or 1.0
        )
    # combine traffic gates the fleet TOTAL (near-tight: deterministic
    # for a given mode + placement); migrations gate the fleet MAX of
    # the per-process counter — every process counts the same global
    # number, so one disagreeing shard can only look worse (exact tier)
    rc = fs.get("re_combine") or {}
    if isinstance(rc.get("bytes_sent_total"), (int, float)):
        m["re_combine/bytes_sent"] = float(rc["bytes_sent_total"])
    # feature-range sharding: range count and nnz balance are
    # replicated (deterministic planner on the allreduced histogram),
    # so gate the fleet MAX — a disagreeing shard can only look worse
    for name in ("ranges", "nnz_balance"):
        vals = [
            (s.get("fe_shard") or {}).get(name)
            for s in (fs.get("processes") or {}).values()
        ]
        vals = [float(v) for v in vals if isinstance(v, (int, float))]
        if vals:
            m[f"fe_shard/{name}"] = max(vals)
    # the projection ratio gates the fleet MAX of the per-process gauge
    # (replicated ladder: a disagreeing shard can only look worse)
    prj = fs.get("re_project") or {}
    if isinstance(prj.get("mean_ratio"), (int, float)):
        m["re_project/mean_ratio"] = float(prj["mean_ratio"])
    mig = [
        (s.get("re_replan") or {}).get("migrations")
        for s in (fs.get("processes") or {}).values()
    ]
    mig = [float(v) for v in mig if isinstance(v, (int, float))]
    if mig:
        m["re_replan/migrations"] = max(mig)
    # serving: the gateable tail is the WORST process's percentile (an
    # SLO is a max), the hit rate the traffic-weighted fleet value —
    # both on the per-run serve tiers; non-serving fleets emit nothing
    sv = fs.get("serve") or {}
    if sv:
        if isinstance(sv.get("latency_p50_ms_max"), (int, float)):
            m["serve/latency_p50_ms"] = float(sv["latency_p50_ms_max"])
        if isinstance(sv.get("latency_p99_ms_max"), (int, float)):
            m["serve/latency_p99_ms"] = float(sv["latency_p99_ms_max"])
        if isinstance(sv.get("hot_hit_rate"), (int, float)):
            m["serve/hot_hit_rate"] = float(sv["hot_hit_rate"])
    return m


def load_gate_metrics(
    path: str, fleet: bool = False
) -> tuple[str, dict[str, float]]:
    """(kind, metrics) from any gate-readable artifact: a telemetry run
    JSONL (or a telemetry DIR — newest run wins), a ``bench.py`` JSON
    document, or a gate-baseline file written by ``report gate
    --write-baseline``. ``fleet=True`` loads a telemetry run (file or
    dir) as the MERGED fleet view — canonical file plus every ``.p<k>``
    shard — instead of process 0's summary alone; saved gate-baseline
    files still load as baselines."""
    if fleet:
        doc = None
        if not os.path.isdir(path):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except json.JSONDecodeError:
                doc = None
        if isinstance(doc, dict) and doc.get("gate_baseline"):
            return "baseline", {
                k: float(v) for k, v in (doc.get("metrics") or {}).items()
                if isinstance(v, (int, float))
            }
        if isinstance(doc, dict) and (
            "configs" in doc or "telemetry" in doc
        ) and doc.get("event") != "run_start":
            # the EITHER-side contract holds under --fleet too: a
            # bench.py JSON document is a valid (non-fleet) side
            return "bench", gate_metrics_from_bench(doc)
        return "fleet", gate_metrics_from_fleet(
            summarize_fleet(fleet_run_paths(path))
        )
    if os.path.isdir(path):
        run = latest_run(path)
        if run is None:
            raise ValueError(f"no run-*.jsonl files in {path}")
        path = run
    doc = None
    try:
        with open(path) as f:
            doc = json.load(f)
    except json.JSONDecodeError:
        doc = None  # multi-record JSONL -> telemetry run
    if isinstance(doc, dict) and doc.get("gate_baseline"):
        return "baseline", {
            k: float(v) for k, v in (doc.get("metrics") or {}).items()
            if isinstance(v, (int, float))
        }
    if isinstance(doc, dict) and (
        "configs" in doc or "telemetry" in doc
    ) and doc.get("event") != "run_start":
        return "bench", gate_metrics_from_bench(doc)
    return "telemetry", gate_metrics_from_summary(summarize_run(path))


def gate_run(
    current: dict[str, float],
    baseline: dict[str, float],
    thresholds: dict | None = None,
    allow_missing: bool = False,
) -> tuple[list[dict], list[str]]:
    """Compare ``current`` against every ``baseline`` metric. Returns
    ``(failures, report_lines)``; empty failures = gate passes. A metric
    present in the baseline but absent from the run is itself a failure
    (lost instrumentation reads as "covered" otherwise) unless
    ``allow_missing``; metrics only the current run has are informational
    (new instrumentation is not a regression)."""
    th = dict(DEFAULT_GATE_THRESHOLDS)
    th.update(thresholds or {})
    if not baseline:
        raise ValueError("baseline contains no gateable metrics")
    failures: list[dict] = []
    lines = [
        f"  {'metric':<58} {'baseline':>11} {'current':>11} "
        f"{'limit':>11}  ok",
    ]
    for name in sorted(baseline):
        base = baseline[name]
        rule = resolve_threshold(name, th)
        limit = base * (1.0 + float(rule.get("rel", 0.0))) + float(
            rule.get("abs", 0.0)
        )
        cur = current.get(name)
        if cur is None:
            if not allow_missing:
                failures.append(
                    {"metric": name, "problem": "missing",
                     "baseline": base, "limit": limit}
                )
            lines.append(
                f"  {name:<58} {_fmt_gate(base):>11} {'(missing)':>11} "
                f"{_fmt_gate(limit):>11}  "
                + ("SKIP" if allow_missing else "FAIL")
            )
            continue
        ok = cur <= limit
        if not ok:
            failures.append(
                {"metric": name, "problem": "regression",
                 "baseline": base, "current": cur, "limit": limit}
            )
        lines.append(
            f"  {name:<58} {_fmt_gate(base):>11} {_fmt_gate(cur):>11} "
            f"{_fmt_gate(limit):>11}  " + ("ok" if ok else "FAIL")
        )
    new = sorted(set(current) - set(baseline))
    if new:
        lines.append(f"  (+{len(new)} metrics not in baseline — ignored)")
    return failures, lines
