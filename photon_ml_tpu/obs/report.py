"""Run-report rendering: load, validate, summarize and diff telemetry runs.

The ``photon-ml-tpu report`` CLI's engine. A summary answers the question
every on-chip sweep needs answered per run — where did the wall go
(per-phase span seconds), how much was XLA compile, how much was
host→device transfer, what did the optimizers do — and ``diff`` lines two
runs up so a knob sweep (``PHOTON_PREFETCH_DEPTH``,
``PHOTON_PIPELINE_SEGMENTS``, …) reads as a table instead of two log
greps. Phases are the first ``/`` segment of span names (``descent/iter``
→ ``descent``); a phase's wall is the UNION of its phase-entry spans'
time intervals (entry = parent outside the phase), so neither nesting
nor concurrent worker-thread spans double-count. Phases may still
overlap EACH OTHER in wall time — a prefetch worker's ``ingest`` span
running under a consumer's ``cv`` span is real pipelining, so the phase
column can legitimately sum past the run's wall.
"""

from __future__ import annotations

import json
import os
from typing import Any

from photon_ml_tpu.obs.sink import SCHEMA_VERSION

_SPAN_REQUIRED = ("name", "span_id", "dur_s", "t")


def load_run(path: str) -> list[dict]:
    """Parse one run's JSONL into records (raises on unparseable lines —
    the atomic-rotate sink never commits a torn tail, so a parse failure
    means the file is not a telemetry run)."""
    records = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i + 1}: not JSONL: {e}") from e
    return records


def validate_run(records: list[dict]) -> list[str]:
    """Schema check; returns a list of problems (empty = valid)."""
    errors = []
    if not records:
        return ["empty run (no records)"]
    head = records[0]
    if head.get("event") != "run_start":
        errors.append("first record is not run_start")
    elif head.get("schema_version") != SCHEMA_VERSION:
        errors.append(
            f"schema_version {head.get('schema_version')!r} != "
            f"{SCHEMA_VERSION} (this reader)"
        )
    for i, r in enumerate(records):
        if "event" not in r or "t" not in r:
            errors.append(f"record {i}: missing 'event'/'t'")
            continue
        if r["event"] == "span":
            missing = [k for k in _SPAN_REQUIRED if k not in r]
            if missing:
                errors.append(f"record {i}: span missing {missing}")
    return errors


def _phase(name: str) -> str:
    return name.split("/", 1)[0]


def _union_seconds(intervals: list[tuple[float, float]]) -> float:
    """Total seconds covered by a set of (start, end) intervals."""
    total = 0.0
    end = -float("inf")
    for lo, hi in sorted(intervals):
        if hi <= end:
            continue
        total += hi - max(lo, end)
        end = hi
    return total


def summarize_run(path: str) -> dict:
    """One run's JSONL → a JSON-plain summary dict."""
    records = load_run(path)
    errors = validate_run(records)
    if errors:
        raise ValueError(f"{path}: invalid telemetry run: {errors}")

    spans = [r for r in records if r["event"] == "span"]
    by_id = {r["span_id"]: r for r in spans}
    run_start = records[0]
    run_end = next(
        (r for r in records if r["event"] == "run_end"), None
    )
    t_last = max(float(r["t"]) for r in records)

    phases: dict[str, dict] = {}
    entry_intervals: dict[str, list[tuple[float, float]]] = {}
    for s in spans:
        ph = _phase(s["name"])
        agg = phases.setdefault(ph, {"wall_s": 0.0, "spans": 0})
        agg["spans"] += 1
        parent = by_id.get(s.get("parent_id"))
        # only phase-entry spans contribute wall (children re-cover the
        # same seconds), and entry intervals are UNIONED so concurrent
        # worker-thread spans of one phase don't double-count either
        if parent is None or _phase(parent["name"]) != ph:
            t0 = float(s["t"])
            entry_intervals.setdefault(ph, []).append(
                (t0, t0 + float(s["dur_s"]))
            )
    for ph, intervals in entry_intervals.items():
        phases[ph]["wall_s"] = _union_seconds(intervals)

    events: dict[str, int] = {}
    for r in records:
        events[r["event"]] = events.get(r["event"], 0) + 1

    # leaf XLA compiles only (jax nests backend_compile inside broader
    # "compile" events — summing every match would double-count)
    compile_s = sum(
        float(r.get("dur_s", 0.0))
        for r in records
        if r["event"] == "jax_event"
        and "backend_compile" in str(r.get("name", ""))
    )
    metrics = (run_end or {}).get("metrics", {})
    timers = metrics.get("timers", {})
    base_timers = run_start.get("metrics_baseline", {}).get("timers", {})

    def timer_s(name: str) -> float:
        # delta against the run_start baseline: the registry is process-
        # cumulative, and a second run in the same process must not
        # inherit the first run's seconds
        end = float(timers.get(name, {}).get("seconds", 0.0))
        base = float(base_timers.get(name, {}).get("seconds", 0.0))
        return max(end - base, 0.0)

    counters = metrics.get("counters", {})
    base_counters = run_start.get("metrics_baseline", {}).get("counters", {})

    def counter_v(name: str) -> float:
        # same run_start-baseline delta as timer_s: the registry is
        # process-cumulative, this run's share only
        end = float(counters.get(name, {}).get("value", 0.0))
        base = float(base_counters.get(name, {}).get("value", 0.0))
        return max(end - base, 0.0)

    # random-effect bucket-solve lane accounting (re_solve.* counters,
    # game/random_effect): executed = lane-iterations the launches ran,
    # useful = lane-iterations before each lane converged; their gap is
    # the wasted lockstep work the compaction knob exists to remove
    executed = counter_v("re_solve.executed_entity_iterations")
    useful = counter_v("re_solve.useful_entity_iterations")
    re_solve = {
        "launches": counter_v("re_solve.launches"),
        "executed_entity_iterations": executed,
        "useful_entity_iterations": useful,
        "wasted_lane_fraction": (
            1.0 - useful / executed if executed > 0 else None
        ),
    }

    optim = [r for r in records if r["event"] == "optim_result"]
    reasons: dict[str, int] = {}
    for r in optim:
        reasons[str(r.get("reason"))] = reasons.get(str(r.get("reason")), 0) + 1

    # precision-ladder quality parity (BASELINE protocol: speed is never
    # reported without a parity check): a reduced-precision bench run
    # emits a quality_parity event with its AUC/RMSE/loss deltas against
    # the f32 anchor — surfaced here so a dtype sweep reads its quality
    # gate from the same report as its wall numbers
    quality_parity = None
    for r in records:
        if r["event"] == "quality_parity":
            quality_parity = {
                k: v for k, v in r.items() if k not in ("event", "t")
            }

    return {
        "path": os.path.abspath(path),
        "run_id": run_start.get("run_id"),
        "schema_version": run_start.get("schema_version"),
        "knobs": run_start.get("knobs", {}),
        "wall_s": t_last - float(run_start["t"]),
        "complete": run_end is not None,
        "phases": phases,
        "compile_s": compile_s or timer_s("jax.compile_s"),
        "transfer_s": timer_s("prefetch.device_put_s"),
        "host_pack_s": timer_s("prefetch.host_pack_s"),
        "consumer_wait_s": timer_s("prefetch.consumer_wait_s"),
        "events": events,
        "optim": {
            "solves": len(optim),
            "iterations": sum(int(r.get("iterations", 0)) for r in optim),
            "reasons": reasons,
        },
        "re_solve": re_solve,
        "quality_parity": quality_parity,
        "warnings": sum(
            1 for r in records
            if r["event"] == "log" and r.get("level") in ("WARN", "ERROR")
        ),
        "metrics": metrics,
    }


# -- rendering --------------------------------------------------------------

_UNRECORDED = "(unrecorded)"


def _fmt_s(v: float) -> str:
    return f"{v:.3f}s"


def _fmt_quality_parity(qp: dict) -> str:
    # every delta/RMSE metric renders — the gate's whole point is that a
    # bad number is impossible to miss next to the wall numbers
    parts = [f"kernel_dtype={qp.get('kernel_dtype')}"]
    for k in sorted(qp):
        if k.endswith("_delta") or "rmse" in k:
            v = qp[k]
            parts.append(f"{k}={v:+.6f}" if isinstance(v, float) else f"{k}={v}")
    return ", ".join(parts)


def format_summary(s: dict) -> str:
    lines = [
        f"run {s['run_id']}  (schema v{s['schema_version']}, "
        f"{'complete' if s['complete'] else 'NO run_end — truncated?'})",
        f"  wall {_fmt_s(s['wall_s'])}   compile {_fmt_s(s['compile_s'])}   "
        f"transfer {_fmt_s(s['transfer_s'])}   "
        f"host-pack {_fmt_s(s['host_pack_s'])}   "
        f"consumer-wait {_fmt_s(s['consumer_wait_s'])}",
        "",
        f"  {'phase':<16} {'wall':>10} {'spans':>7}",
    ]
    for ph, agg in sorted(
        s["phases"].items(), key=lambda kv: -kv[1]["wall_s"]
    ):
        lines.append(
            f"  {ph:<16} {_fmt_s(agg['wall_s']):>10} {agg['spans']:>7}"
        )
    o = s["optim"]
    if o["solves"]:
        reasons = ", ".join(f"{k}×{v}" for k, v in sorted(o["reasons"].items()))
        lines.append(
            f"  optimizer: {o['solves']} solves, {o['iterations']} "
            f"iterations ({reasons})"
        )
    rs = s.get("re_solve") or {}
    if rs.get("executed_entity_iterations"):
        lines.append(
            f"  re-solve: {int(rs['launches'])} launches, "
            f"{int(rs['executed_entity_iterations'])} executed entity-iters "
            f"({int(rs['useful_entity_iterations'])} useful), "
            f"wasted-lane {rs['wasted_lane_fraction']:.1%}"
        )
    if s.get("quality_parity"):
        lines.append(
            f"  quality-parity: {_fmt_quality_parity(s['quality_parity'])}"
        )
    if s["warnings"]:
        lines.append(f"  warnings: {s['warnings']}")
    if s["knobs"]:
        lines.append(f"  knobs: {json.dumps(s['knobs'], sort_keys=True)}")
    return "\n".join(lines)


def diff_summaries(a: dict, b: dict) -> str:
    """Two runs side by side: per-phase wall, compile/transfer split, knob
    deltas — the sweep-readout format."""
    lines = [
        f"A: {a['run_id']}  ({os.path.basename(a['path'])})",
        f"B: {b['run_id']}  ({os.path.basename(b['path'])})",
        "",
        f"  {'':<16} {'A':>10} {'B':>10} {'B/A':>7}",
    ]

    def row(label: str, va: float, vb: float):
        ratio = (vb / va) if va > 0 else float("inf") if vb > 0 else 1.0
        lines.append(
            f"  {label:<16} {_fmt_s(va):>10} {_fmt_s(vb):>10} {ratio:>7.2f}"
        )

    row("wall", a["wall_s"], b["wall_s"])
    for ph in sorted(set(a["phases"]) | set(b["phases"])):
        row(
            ph,
            a["phases"].get(ph, {}).get("wall_s", 0.0),
            b["phases"].get(ph, {}).get("wall_s", 0.0),
        )
    row("compile", a["compile_s"], b["compile_s"])
    row("transfer", a["transfer_s"], b["transfer_s"])
    row("host-pack", a["host_pack_s"], b["host_pack_s"])
    row("consumer-wait", a["consumer_wait_s"], b["consumer_wait_s"])
    ra, rb = a.get("re_solve") or {}, b.get("re_solve") or {}
    if ra.get("executed_entity_iterations") or rb.get("executed_entity_iterations"):
        # the wasted-lane column: the knob-sweep readout for
        # PHOTON_RE_COMPACT_EVERY / PHOTON_RE_FUSE_BUCKETS
        def pct(v):
            return "-" if v is None else f"{v:.1%}"

        lines.append(
            f"  {'wasted-lane':<16} "
            f"{pct(ra.get('wasted_lane_fraction')):>10} "
            f"{pct(rb.get('wasted_lane_fraction')):>10}"
        )
        lines.append(
            f"  {'exec-entity-it':<16} "
            f"{int(ra.get('executed_entity_iterations') or 0):>10} "
            f"{int(rb.get('executed_entity_iterations') or 0):>10}"
        )
    qa, qb = a.get("quality_parity"), b.get("quality_parity")
    if qa or qb:
        lines.append("  quality-parity:")
        lines.append(
            f"    A: {_fmt_quality_parity(qa) if qa else _UNRECORDED}"
        )
        lines.append(
            f"    B: {_fmt_quality_parity(qb) if qb else _UNRECORDED}"
        )
    ka, kb = a.get("knobs", {}), b.get("knobs", {})
    # a knob only one run recorded (an older-schema run, or a pre-knob
    # baseline) renders as "(unrecorded)" instead of being dropped — an
    # asymmetric PHOTON_KERNEL_DTYPE is a real config delta, and the
    # `(k in ka) != (k in kb)` term keeps it even when .get() values
    # would coincide (e.g. a knob legitimately recorded as None)
    knob_keys = set(ka) | set(kb)
    knob_diffs = {
        k: (
            ka[k] if k in ka else _UNRECORDED,
            kb[k] if k in kb else _UNRECORDED,
        )
        for k in sorted(knob_keys)
        if (k in ka) != (k in kb) or ka.get(k) != kb.get(k)
    }
    if knob_diffs:
        lines.append("  knob deltas:")
        for k, (va, vb) in knob_diffs.items():
            lines.append(f"    {k}: {va!r} -> {vb!r}")
    return "\n".join(lines)


def latest_run(directory: str) -> str | None:
    """Newest ``run-*.jsonl`` in a telemetry directory (mtime order)."""
    runs = [
        os.path.join(directory, f)
        for f in os.listdir(directory)
        if f.startswith("run-") and f.endswith(".jsonl")
    ]
    return max(runs, key=os.path.getmtime) if runs else None
