"""Run-telemetry subsystem: spans, metrics registry, JSONL sink, exporters.

The TPU-native replacement for the observability the reference got from
Spark's UI/event timeline (SURVEY.md §5.1). Five pieces:

- **spans** (``span("descent/iter", coordinate=cid)``) — nested host-side
  wall-clock spans, thread-correct across the prefetch worker pool;
- **metrics registry** (``metrics.REGISTRY``) — typed counters / gauges /
  histograms / timers, always on, subsuming the legacy stage counters
  (``utils/profiling`` is a compatibility shim over it);
- **JSONL sink** (``configure(telemetry_dir)`` … ``shutdown()``) — one
  run, one schema-versioned file, atomically rotated, single-writer
  under multihost;
- **exporters** — ``obs.export`` renders a run as a Chrome-trace/Perfetto
  JSON next to ``jax.profiler`` device traces; ``obs.report`` summarizes,
  diffs, validates and GATES runs (surfaced as ``photon-ml-tpu report``);
- **analytic device cost** (``obs.devcost``) — per-executable XLA
  ``cost_analysis``/``memory_analysis`` capture on fresh compiles plus
  HBM budget/watermark sampling, feeding the report's roofline table and
  the ``report gate`` regression tripwire.

Everything here is host-side and cheap: with no sink configured, spans
return a shared no-op and event emission is one attribute check, so the
instrumentation stays wired through production paths unconditionally.
"""

from photon_ml_tpu.obs import devcost  # noqa: F401
from photon_ml_tpu.obs import metrics  # noqa: F401
from photon_ml_tpu.obs.devcost import capture as capture_executable_cost  # noqa: F401
from photon_ml_tpu.obs.metrics import REGISTRY  # noqa: F401
from photon_ml_tpu.obs.sink import (  # noqa: F401
    SCHEMA_VERSION,
    TelemetrySink,
    active_sink,
    configure,
    shutdown,
)
from photon_ml_tpu.obs.spans import (  # noqa: F401
    NOOP_SPAN,
    current_span_id,
    emit_event,
    emit_log,
    span,
)

# Compile visibility is part of the ALWAYS-ON half: install the
# jax.monitoring listener at import (no backend init; the callback is a
# cheap no-op between runs) so ``jax.compile_s`` is in every registry
# snapshot — bench telemetry blocks included — even without a sink.
from photon_ml_tpu.obs.sink import _install_jax_monitoring

_install_jax_monitoring()


def enabled() -> bool:
    return active_sink() is not None
