"""Analytic device-cost capture: per-executable XLA cost/memory analysis.

Every perf knob so far (pipeline segments, groups-per-run, prefetch depth,
compaction, kernel dtype) shipped bitwise-parity-tested but BLIND — no
session has had a TPU attached, so no on-device cost number exists for any
of them. XLA's AOT surface closes the gap on any backend: for a jitted
callable, ``fn.lower(*args).compile()`` yields ``cost_analysis()`` (flops,
bytes-accessed) and ``memory_analysis()`` (argument/output/temp/peak
bytes) — hardware-independent ANALYTIC estimates on CPU, real HBM numbers
the moment a chip appears. This module captures those numbers once per
fresh executable and publishes them as schema-versioned
``executable_cost`` telemetry records plus ``devcost.*`` registry gauges,
so the dtype-ladder and groups-per-run sweeps can be compared
analytically today and gated in CI (``photon-ml-tpu report gate``).

Capture discipline (the whole point is to never touch the hot path):

- **Cache-miss only.** A process-wide seen-set keyed by ``(label, knob
  tuple, argument signature)`` mirrors the jit caches it shadows: the
  knob tuple is the retune surface (dtype rung, pipeline segments,
  groups-per-run, …) and the signature is tree structure + shape/dtype
  of every array leaf + repr of every static. A repeat call emits
  NOTHING and costs one tree flatten + the signature-tuple build + a
  set lookup (the knob snapshot is memoized on its raw env/global
  inputs — see ``_knob_items``).
- **Never under a trace.** Wired-through boundaries are called with
  tracers from outer jits/vmaps; any tracer leaf skips capture (the
  enclosing executable is captured at ITS boundary instead).
- **Gated.** Capture runs when a telemetry sink is active, or when
  ``PHOTON_DEVCOST=1`` forces it sink-less (registry gauges only — how
  ``bench.py --quick`` gets cost numbers into its JSON contract).
  ``PHOTON_DEVCOST=0`` forces it off. Cost: the AOT ``lower().compile()``
  is a SECOND compile of the executable (jax exposes no way to reach the
  jit cache's compiled object, and routing production calls through the
  AOT executable would sidestep the dispatch path the bitwise-parity
  tests pin down) — paid once per fresh executable, only on
  capture-enabled runs, recorded honestly as ``capture_s`` in the record
  and the ``devcost.capture_s`` timer. The tier-1 suite pins capture off
  (conftest) for exactly this reason.
- **Never fatal.** Every capture is wrapped; a failure increments
  ``devcost.capture_errors`` and the run proceeds.

The companion samplers here — ``sample_hbm_watermarks`` (called by the
span layer at every root-span exit) and ``record_hbm_budget`` (called by
``ops/streaming.device_hbm_budget_bytes``) — put the RUNTIME memory axis
next to the analytic one: ``bytes_in_use``/``peak_bytes_in_use`` from
``device.memory_stats()`` where the backend exposes them, and an explicit
``available: false`` record where it does not (CPU), so a report reader
can tell "no pressure" from "no instrument".
"""

from __future__ import annotations

import functools
import hashlib
import os
import threading
import time
from typing import Any

from photon_ml_tpu.obs import metrics as _metrics
from photon_ml_tpu.obs import sink as _sink_mod

COST_SCHEMA_VERSION = 1

_lock = threading.Lock()
_seen: set[tuple] = set()
_wrapped: dict = {}
# one-time-per-sink emission guards (a reconfigured sink is a new run and
# gets its own budget/unavailability records)
_budget_sink: Any = None
_wm_unavailable_sink: Any = None
# watermark sampling floor: root spans include per-chunk prefetch-worker
# spans, and memory watermarks at sub-second cadence are noise, not signal
_WM_MIN_INTERVAL_S = 0.5
_last_wm_sample = [float("-inf")]


def reset() -> None:
    """Forget captured executables and one-time emission state (tests)."""
    global _budget_sink, _wm_unavailable_sink
    with _lock:
        _seen.clear()
        _label_totals.clear()
        _budget_sink = None
        _wm_unavailable_sink = None
        _last_wm_sample[0] = float("-inf")


_warned_bad_env = [False]


def capture_enabled() -> bool:
    """Capture gate: ``PHOTON_DEVCOST`` wins (int parse — ``1`` forces
    on sink-less, ``0`` forces off), else capture exactly when a
    telemetry sink is active. Unlike the sibling RETUNE knobs (which
    change MATH and fail strict), a malformed value here degrades to
    capture-off with one warning: this check sits on every wired
    production boundary, and observability misconfiguration must never
    take down the run it observes."""
    env = os.environ.get("PHOTON_DEVCOST")
    if env is not None and env != "":
        try:
            return bool(int(env))
        except ValueError:
            if not _warned_bad_env[0]:
                # lint: waive(conc-unlocked-mutation) benign-race once-flag: worst case is a duplicate warning
                _warned_bad_env[0] = True
                import warnings

                warnings.warn(
                    f"PHOTON_DEVCOST={env!r} is not an int; device-cost "
                    f"capture disabled (use 1/0)",
                    stacklevel=2,
                )
            return False
    return _sink_mod.is_active()


# knob-snapshot memo: ``sink._knob_snapshot`` costs ~30 us (module
# imports, call-time knob readers, strict dtype validation) — too much
# for capture()'s REPEAT path, which runs per eager kernel/scoring call
# while a sink is active. The snapshot is a pure function of the raw env
# vars + module globals below, so it memoizes exactly on them (no TTL —
# a knob flip invalidates immediately). A knob added to _knob_snapshot
# must be added here too; the failure mode of forgetting is one missed
# re-capture on a mid-process flip of only that knob, never a wrong
# number. That wiring is no longer a memory exercise: the lint knob pass
# (photon_ml_tpu/analysis, code knob-devcost-missing) parses this
# function and fails when a snapshot-carried knob is not fingerprinted.
_knob_memo: list = []  # [raw_fingerprint, knobs_dict, sorted_items_tuple]


def _knob_raw_state() -> tuple:
    env = os.environ
    import photon_ml_tpu.ops.prefetch as pf
    import photon_ml_tpu.ops.sparse_tiled as st

    try:
        import sys

        re_mod = sys.modules.get("photon_ml_tpu.game.random_effect")
        re_state = (
            None if re_mod is None
            else (re_mod.COMPACT_EVERY, re_mod.FUSE_BUCKETS,
                  re_mod.RE_COMBINE)
        )
    except Exception:
        re_state = None
    try:
        import sys

        pl_mod = sys.modules.get("photon_ml_tpu.parallel.placement")
        shard_state = (
            None if pl_mod is None
            else (pl_mod.RE_SHARD, pl_mod.RE_SPLIT,
                  pl_mod.REPLAN_IMBALANCE, pl_mod.RE_DEVICE_SPLIT,
                  pl_mod.RE_SPLIT_WEIGHT)
        )
    except Exception:
        shard_state = None
    try:
        import sys

        pj_mod = sys.modules.get("photon_ml_tpu.game.projector")
        project_state = (
            None if pj_mod is None
            else (pj_mod.RE_PROJECT, pj_mod.RE_PROJECT_DIM)
        )
    except Exception:
        project_state = None
    try:
        import sys

        im_mod = sys.modules.get("photon_ml_tpu.data.index_map")
        fe_state = (
            None if im_mod is None
            else (im_mod.FE_SHARD, im_mod.FE_SPLIT_WEIGHT)
        )
    except Exception:
        fe_state = None
    try:
        import sys

        sv_store = sys.modules.get("photon_ml_tpu.serve.store")
        sv_router = sys.modules.get("photon_ml_tpu.serve.router")
        sv_refresh = sys.modules.get("photon_ml_tpu.serve.refresh")
        serve_state = (
            None if sv_store is None else sv_store.SERVE_HOT_BYTES,
            None if sv_router is None
            else (sv_router.SERVE_MAX_BATCH, sv_router.SERVE_MAX_WAIT_MS),
            None if sv_refresh is None else sv_refresh.SERVE_REFRESH_EVERY,
        )
    except Exception:
        serve_state = None
    try:
        import sys

        se_mod = sys.modules.get("photon_ml_tpu.ops.stream_executor")
        stream_state = (
            None if se_mod is None
            else (se_mod.STREAM_EXECUTOR, se_mod.STREAM_PRIORITY,
                  se_mod.STREAM_SHARE)
        )
    except Exception:
        stream_state = None
    return (
        env.get("PHOTON_STREAM_EXECUTOR"),
        env.get("PHOTON_STREAM_PRIORITY"),
        env.get("PHOTON_STREAM_SHARE"),
        env.get("PHOTON_SERVE_HOT_BYTES"),
        env.get("PHOTON_SERVE_MAX_BATCH"),
        env.get("PHOTON_SERVE_MAX_WAIT_MS"),
        env.get("PHOTON_SERVE_REFRESH_EVERY"),
        env.get("PHOTON_PREFETCH_DEPTH"),
        env.get("PHOTON_CHUNK_CACHE_BUDGET"),
        env.get("PHOTON_KERNEL_DTYPE"),
        env.get("PHOTON_RE_COMPACT_EVERY"),
        env.get("PHOTON_RE_FUSE_BUCKETS"),
        env.get("PHOTON_RE_COMBINE"),
        env.get("PHOTON_RE_PROJECT"),
        env.get("PHOTON_RE_PROJECT_DIM"),
        env.get("PHOTON_RE_SHARD"),
        env.get("PHOTON_RE_SPLIT"),
        env.get("PHOTON_RE_REPLAN_IMBALANCE"),
        env.get("PHOTON_RE_DEVICE_SPLIT"),
        env.get("PHOTON_RE_SPLIT_WEIGHT"),
        env.get("PHOTON_FE_SHARD"),
        env.get("PHOTON_FE_SPLIT_WEIGHT"),
        pf.PREFETCH_DEPTH, pf.CHUNK_CACHE_BUDGET,
        len(pf._device_budget_memo),
        st.GROUPS_PER_STEP, st.SEGMENTS_PER_DMA,
        st.GROUPS_PER_RUN, st.PIPELINE_SEGMENTS, st.KERNEL_DTYPE,
        re_state,
        shard_state,
        project_state,
        fe_state,
        serve_state,
        stream_state,
    )


def _knob_items() -> tuple:
    """The knob snapshot as a sorted item tuple (the hashable half of
    every capture key), memoized on the raw knob inputs."""
    fp = _knob_raw_state()
    memo = _knob_memo
    if memo and memo[0] == fp:
        return memo[2]
    knobs = _sink_mod._knob_snapshot()
    items = tuple(sorted(knobs.items()))
    # lint: waive(conc-unlocked-mutation) deliberately lock-free memo: sits on capture()'s repeat path; a racing rewrite recomputes the same value
    _knob_memo[:] = [fp, knobs, items]
    return items


def knob_key() -> dict:
    """The retune surface an executable was compiled under — the same
    knob snapshot a run's ``run_start`` records (dtype rung, pipeline
    segments, groups-per-run, prefetch depth, compaction knobs), so cost
    records key by CONFIGURATION, not by luck."""
    return dict(_knob_items())


def _leaf_descriptors(leaves) -> tuple:
    """Hashable per-leaf signature: shape/dtype for arrays, repr for
    statics — the same information the jit cache keys on. A plain tuple,
    not a digest: tuple hashing is what the repeat (cache-hit) path
    pays, and it must stay cheap (the treedef rides the key directly —
    PyTreeDef is hashable — so structure needs no stringification)."""
    parts = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            parts.append(f"{tuple(leaf.shape)}:{leaf.dtype}")
        else:
            parts.append(repr(leaf))
    return tuple(parts)


def _analyze(compiled) -> tuple[float, float, dict, int | None, bool]:
    """Normalize one ``Compiled``'s analyses across jax versions/backends.
    Returns (flops, bytes_accessed, memory dict, peak_bytes,
    peak_is_estimate). ``cost_analysis`` may be a per-device list; TPU
    exposes a true ``peak_memory_in_bytes`` while CPU only itemizes
    argument/output/temp — there the peak is estimated as their sum and
    flagged, so a reader never mistakes an estimate for a measurement."""
    cost: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        cost = dict(ca or {})
    except Exception:
        pass
    flops = float(cost.get("flops", 0.0) or 0.0)
    bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
    mem: dict = {}
    try:
        ma = compiled.memory_analysis()
        for k in (
            "generated_code_size_in_bytes",
            "argument_size_in_bytes",
            "output_size_in_bytes",
            "alias_size_in_bytes",
            "temp_size_in_bytes",
            "peak_memory_in_bytes",
        ):
            v = getattr(ma, k, None)
            if v is not None:
                mem[k] = int(v)
    except Exception:
        pass
    peak = mem.get("peak_memory_in_bytes")
    peak_is_estimate = False
    if peak is None and mem:
        peak = (
            mem.get("argument_size_in_bytes", 0)
            + mem.get("output_size_in_bytes", 0)
            + mem.get("temp_size_in_bytes", 0)
        )
        peak_is_estimate = True
    return flops, bytes_accessed, mem, peak, peak_is_estimate


def capture(
    label: str,
    fn: Any,
    args: tuple = (),
    kwargs: dict | None = None,
    **extra,
) -> dict | None:
    """Capture ``fn``'s executable cost for ``(args, kwargs)`` if this
    (label, knob tuple, signature) has not been captured before. ``fn``
    must be a jitted callable (``.lower``); call BEFORE (or after — the
    AOT path is independent) invoking it. Returns the record, or None
    when disabled / already seen / called under a trace / on any
    analysis failure."""
    if not capture_enabled():
        return None
    try:
        import jax
        from jax.core import Tracer

        kwargs = kwargs or {}
        leaves, treedef = jax.tree.flatten((args, kwargs))
        if any(isinstance(leaf, Tracer) for leaf in leaves):
            return None
        sig_tuple = _leaf_descriptors(leaves)
        key = (label, _knob_items(), treedef, sig_tuple)
        with _lock:
            if key in _seen:
                return None
            # mark BEFORE compiling: a failing capture must not re-pay
            # the AOT compile on every subsequent call
            _seen.add(key)
        # miss path only from here: materialize the knob dict and the
        # short record-only digest (a readable dedup tag in the JSONL)
        knobs = knob_key()
        sig = hashlib.sha256(
            "|".join(sig_tuple).encode()
        ).hexdigest()[:16]
        t0 = time.perf_counter()
        compiled = fn.lower(*args, **kwargs).compile()
        capture_s = time.perf_counter() - t0
        flops, bytes_accessed, mem, peak, peak_est = _analyze(compiled)
        record = {
            "event": "executable_cost",
            "cost_schema_version": COST_SCHEMA_VERSION,
            "label": label,
            "knobs": knobs,
            "arg_sig": sig,
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "arith_intensity": (
                flops / bytes_accessed if bytes_accessed else None
            ),
            "memory": mem,
            "peak_bytes": peak,
            "peak_is_estimate": peak_est,
            "capture_s": capture_s,
        }
        record.update(extra)
        _publish(record)
        return record
    except Exception:
        try:
            _metrics.REGISTRY.counter_inc("devcost.capture_errors")
        except Exception:
            pass
        return None


# per-label running totals behind the devcost.<label>.* gauges: one label
# can capture several executables (the compaction loop's shrinking fronts,
# several chunk geometries), and a last-write-wins gauge would show only
# the LAST one — blinding the bench-JSON gate path to every earlier
# executable. The gauges therefore carry the SUM of flops/bytes and the
# MAX peak across the label's captures, the same aggregation the
# telemetry-JSONL summarize path applies.
_label_totals: dict[str, list] = {}


def _publish(record: dict) -> None:
    reg = _metrics.REGISTRY
    label = record["label"]
    reg.counter_inc("devcost.captures")
    reg.timer_add("devcost.capture_s", record["capture_s"])
    with _lock:
        tot = _label_totals.setdefault(label, [0.0, 0.0, 0])
        tot[0] += record["flops"]
        tot[1] += record["bytes_accessed"]
        if record["peak_bytes"] is not None:
            tot[2] = max(tot[2], record["peak_bytes"])
        flops_t, bytes_t, peak_t = tot
    reg.gauge_set(f"devcost.{label}.flops", flops_t)
    reg.gauge_set(f"devcost.{label}.bytes_accessed", bytes_t)
    if peak_t:
        reg.gauge_set(f"devcost.{label}.peak_bytes", peak_t)
    from photon_ml_tpu.obs.spans import emit_event

    emit_event(
        "executable_cost",
        **{k: v for k, v in record.items() if k != "event"},
    )


def captured(label_prefix: str, fn: Any) -> Any:
    """A capture-instrumented twin of a jitted callable, MEMOIZED so the
    returned object is identity-stable: callers use these as jit STATIC
    keys (``minimize_fn``/``init_fn`` in ``game/random_effect``), and a
    fresh wrapper per selector call would poison every such cache into
    recompiling. Non-lowerable callables (the host-driven solver twins)
    are returned unchanged."""
    if not hasattr(fn, "lower"):
        return fn
    key = (label_prefix, fn)
    with _lock:
        wrapper = _wrapped.get(key)
    if wrapper is not None:
        return wrapper
    label = f"{label_prefix}.{getattr(fn, '__name__', 'fn')}"

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        capture(label, fn, args, kwargs)
        return fn(*args, **kwargs)

    with _lock:
        # a racing construction keeps the FIRST wrapper (identity must be
        # stable for the process lifetime)
        wrapper = _wrapped.setdefault(key, wrapper)
    return wrapper


# -- runtime memory axis: HBM budget + watermarks ---------------------------


def record_hbm_budget(budget_bytes: float, queried: bool) -> None:
    """Called by ``ops/streaming.device_hbm_budget_bytes`` on every query:
    gauges always (the bench telemetry block reads them), plus ONE
    ``hbm_budget`` event per sink naming which source won — a run on a
    memory-stats-less backend (CPU: ``fallback_default``) is
    distinguishable from a device-quoted one in ``report`` output."""
    global _budget_sink
    try:
        reg = _metrics.REGISTRY
        reg.gauge_set("hbm.budget_bytes", float(budget_bytes))
        reg.gauge_set("hbm.budget_queried", 1.0 if queried else 0.0)
        s = _sink_mod.active_sink()
        if s is not None and s is not _budget_sink:
            _budget_sink = s
            from photon_ml_tpu.obs.spans import emit_event

            emit_event(
                "hbm_budget",
                budget_bytes=float(budget_bytes),
                source="device_memory_stats" if queried else
                       "fallback_default",
            )
    except Exception:
        pass


def sample_hbm_watermarks(root_span: str | None = None) -> dict | None:
    """Sample ``device.memory_stats()`` watermarks across local devices —
    called at every root-span exit while a sink is active (root spans are
    per-fit/per-driver, so this is off the hot path by construction).
    Emits one ``hbm_watermark`` record (``available: false`` ONCE per
    sink on backends without memory stats) and keeps max-across-devices
    gauges; returns the record, or None when nothing was sampled.

    Rate-limited: prefetch WORKER spans are roots in their own threads
    (per-chunk cadence), so samples closer than ``_WM_MIN_INTERVAL_S``
    to the previous one are skipped — ``peak_bytes_in_use`` is a
    process-cumulative watermark, so a skipped sample loses only
    instantaneous ``bytes_in_use`` granularity, never the peak."""
    global _wm_unavailable_sink
    s = _sink_mod.active_sink()
    now = time.monotonic()
    with _lock:
        if now - _last_wm_sample[0] < _WM_MIN_INTERVAL_S:
            return None
        _last_wm_sample[0] = now
    try:
        import jax

        per_device = []
        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                pass
            if stats:
                per_device.append(
                    {
                        "device": str(d.id),
                        "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                        "peak_bytes_in_use": int(
                            stats.get("peak_bytes_in_use", 0)
                        ),
                        "bytes_limit": int(stats.get("bytes_limit", 0)),
                    }
                )
        from photon_ml_tpu.obs.spans import emit_event

        if not per_device:
            if s is not None and s is not _wm_unavailable_sink:
                _wm_unavailable_sink = s
                rec = {"available": False, "root_span": root_span}
                emit_event("hbm_watermark", **rec)
                return rec
            return None
        reg = _metrics.REGISTRY
        in_use = max(d["bytes_in_use"] for d in per_device)
        peak = max(d["peak_bytes_in_use"] for d in per_device)
        reg.gauge_set("hbm.bytes_in_use", float(in_use))
        reg.gauge_set("hbm.peak_bytes_in_use", float(peak))
        rec = {
            "available": True,
            "root_span": root_span,
            "bytes_in_use": in_use,
            "peak_bytes_in_use": peak,
            "devices": per_device,
        }
        if s is not None:
            emit_event("hbm_watermark", **rec)
        return rec
    except Exception:
        return None


# -- host-side layout-pack accounting (tile_cache misses) -------------------


def record_layout_pack(nbytes: int, chunks: int) -> None:
    """Called by ``ops/tile_cache`` on a layout-cache MISS: the packed
    tile-COO streams are the kernel's HBM traffic, so the per-knob packed
    byte total is the analytic half of the dtype ladder's bytes-moved
    claim (f32 12 B/nnz → bf16 6 → int8 4) — published next to the
    executable costs and rendered in the same roofline table."""
    try:
        reg = _metrics.REGISTRY
        reg.counter_inc("devcost.tile_layout.packs")
        reg.counter_inc("devcost.tile_layout.packed_bytes_total", nbytes)
        reg.gauge_set("devcost.tile_layout.packed_bytes", float(nbytes))
        if _sink_mod.is_active():
            from photon_ml_tpu.obs.spans import emit_event

            emit_event(
                "tile_layout_pack",
                nbytes=int(nbytes),
                chunks=int(chunks),
                knobs=knob_key(),
            )
    except Exception:
        pass
