"""Typed process-wide metrics registry (counters, gauges, histograms, timers).

The registry is the always-on half of the telemetry subsystem: instruments
accumulate in plain host memory under one lock whether or not a JSONL sink
is configured, exactly like the stage counters they subsume
(``utils/profiling.py`` is now a thin compatibility shim over the timer
kind here). A snapshot is a plain JSON-serializable dict, so it can ride
in a bench artifact, a telemetry ``run_end`` record, or a test assertion
without translation.

Instrument kinds:

- **Counter** — monotonically accumulating float (``inc``); e.g. prefetch
  cache hit/miss bytes, chunk-cache evictions, streamed chunk counts.
- **Gauge** — last-write-wins value (``set``); e.g. a run's dropped-row
  fraction per grouped-evaluator tag.
- **Histogram** — ``observe`` keeps count/sum/min/max plus log2 bucket
  counts (enough for a sweep to diff step-count distributions without
  unbounded storage); e.g. per-solve L-BFGS/TRON iteration counts.
- **Timer** — accumulating wall seconds + call count, the exact shape the
  legacy ``counter_snapshot`` API exposes (``{"seconds", "calls"}``).

Thread-safe: prefetch workers and the consumer thread hit the same
instruments concurrently. The single lock is a leaf (no instrument ever
acquires another lock), so callers may update from inside their own
critical sections without ordering hazards.
"""

from __future__ import annotations

import math
import threading


class Counter:
    __slots__ = ("value", "calls")

    def __init__(self):
        self.value = 0.0
        self.calls = 0


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class Histogram:
    __slots__ = ("count", "sum", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: dict[int, int] = {}  # log2 bucket index -> count

    def _observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        b = -1 if v <= 0 else int(math.floor(math.log2(v)))
        self.buckets[b] = self.buckets.get(b, 0) + 1


class Timer:
    __slots__ = ("seconds", "calls")

    def __init__(self):
        self.seconds = 0.0
        self.calls = 0


class MetricsRegistry:
    """Name → instrument maps, one lock, JSON-plain snapshots."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._timers: dict[str, Timer] = {}

    # -- writes ------------------------------------------------------------

    def counter_inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            c.value += value
            c.calls += 1

    def gauge_set(self, name: str, value: float) -> None:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            g.value = float(value)

    def histogram_observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            h._observe(float(value))

    def timer_add(self, name: str, seconds: float) -> None:
        with self._lock:
            t = self._timers.get(name)
            if t is None:
                t = self._timers[name] = Timer()
            t.seconds += float(seconds)
            t.calls += 1

    # -- reads -------------------------------------------------------------

    def timer_snapshot(self, prefix: str | None = None) -> dict:
        """``{name: {"seconds", "calls"}}`` — the legacy stage-counter
        shape ``utils/profiling.counter_snapshot`` promises."""
        with self._lock:
            return {
                k: {"seconds": t.seconds, "calls": t.calls}
                for k, t in self._timers.items()
                if prefix is None or k.startswith(prefix)
            }

    def snapshot(self, prefix: str | None = None) -> dict:
        """Every instrument as a JSON-plain dict (bench artifacts, the
        telemetry ``run_end`` record, report tables)."""

        def keep(k):
            return prefix is None or k.startswith(prefix)

        with self._lock:
            return {
                "counters": {
                    k: {"value": c.value, "calls": c.calls}
                    for k, c in self._counters.items() if keep(k)
                },
                "gauges": {
                    k: g.value for k, g in self._gauges.items() if keep(k)
                },
                "histograms": {
                    k: {
                        "count": h.count,
                        "sum": h.sum,
                        "min": None if h.count == 0 else h.min,
                        "max": None if h.count == 0 else h.max,
                        "log2_buckets": {str(b): n for b, n in sorted(h.buckets.items())},
                    }
                    for k, h in self._histograms.items() if keep(k)
                },
                "timers": {
                    k: {"seconds": t.seconds, "calls": t.calls}
                    for k, t in self._timers.items() if keep(k)
                },
            }

    # -- resets ------------------------------------------------------------

    def reset_timers(self, prefix: str | None = None) -> None:
        with self._lock:
            for k in [k for k in self._timers
                      if prefix is None or k.startswith(prefix)]:
                del self._timers[k]

    def reset(self, prefix: str | None = None) -> None:
        with self._lock:
            for m in (self._counters, self._gauges, self._histograms,
                      self._timers):
                for k in [k for k in m
                          if prefix is None or k.startswith(prefix)]:
                    del m[k]


# THE process-wide registry (mirrors the tile-layout and chunk caches:
# module-level singletons shared by every consumer in the process)
REGISTRY = MetricsRegistry()
