"""Buffered JSONL event sink — one run, one schema-versioned file.

Reference parity: the reference leans on Spark's event log / UI timeline
for run observability (SURVEY.md §5.1); this sink is the TPU-native
equivalent: every span, optimizer record, structured warning and metric
snapshot of a run lands as one JSON line in one file that a human or a
sweep script can diff across runs without grepping stderr.

Durability contract: the file on disk is ALWAYS a complete, parseable
run prefix. Buffered records are committed by **atomic rotation** — the
full accumulated content is written to a same-directory temp file,
fsync'd, and renamed over the run file (``utils/atomic_io``, the same
fsync-rename idiom the visit-checkpoint shards use) — so a reader never
observes a torn tail and a crash never shadows a complete file with a
partial one. The rotation threshold grows with the file (bounded at
``_MAX_ROTATE_EVERY``) so total write amplification stays O(n·log n)
rather than O(n²) on long runs. The tradeoff of full-rewrite atomicity
is that the sink holds the run's serialized records in memory and each
commit rewrites the whole file — sized for this framework's runs (span +
per-iteration record volume is a few hundred bytes each; even a
day-long sweep stays in the tens of MB). A workload emitting orders of
magnitude more should thin its per-iteration records, not the spans.

Multihost: process 0 writes the canonical ``run-<id>.jsonl`` — the file
every existing consumer reads unchanged. Under **fleet telemetry**
(``PHOTON_TELEMETRY_FLEET``; defaults to the ``PHOTON_RE_SHARD`` knob,
because the sharded random-effect schedule is exactly the workload whose
phase walls, exchange waits and per-link transfers live on processes
1..N-1) every non-zero process writes its own schema-versioned shard
``run-<id>.p<k>.jsonl`` under the same atomic-rotation durability
contract; ``photon-ml-tpu report fleet`` joins the canonical file and
its shards into one per-process view. With fleet telemetry off (the
default), ``configure`` on a non-zero process returns a disabled
subsystem exactly as before — the same single-writer discipline the
drivers use for models and metrics, byte for byte.

Disabled fast path: when no sink is configured, ``emit`` is a single
attribute check and every ``span()`` returns a shared no-op context
manager — telemetry can stay wired through production paths.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any

from photon_ml_tpu.obs import metrics as _metrics

SCHEMA_VERSION = 1

# rotation cadence: first commit after this many buffered records, then
# proportional to what's already written (amortized near-linear total IO)
_FIRST_ROTATE_EVERY = 128
_MAX_ROTATE_EVERY = 65536


def _json_default(o: Any) -> str:
    return str(o)


def _sanitize(v: Any) -> Any:
    """Strict-JSON-safe record values: Python's json module would happily
    write bare ``NaN``/``Infinity`` (a diverged solve's loss, say), which
    strict parsers — the Perfetto UI, any non-Python consumer — reject
    for the WHOLE file. Non-finite floats become strings, keeping the
    information without breaking the document."""
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "Infinity"
        if v == float("-inf"):
            return "-Infinity"
        return v
    if isinstance(v, dict):
        return {k: _sanitize(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_sanitize(x) for x in v]
    return v


class TelemetrySink:
    """One run's JSONL file. Thread-safe; records are buffered and
    committed by atomic rotation (never an append a crash could tear)."""

    _seq = itertools.count()  # same-second same-process runs stay distinct

    def __init__(
        self,
        directory: str,
        run_id: str | None = None,
        shard_index: int | None = None,
    ):
        os.makedirs(directory, exist_ok=True)
        self.run_id = run_id or (
            time.strftime("%Y%m%dT%H%M%S")
            + f"-{os.getpid()}-{next(self._seq)}"
        )
        self.directory = directory
        # shard_index k > 0: one process's slice of a FLEET run —
        # ``run-<id>.p<k>.jsonl`` next to process 0's canonical
        # ``run-<id>.jsonl`` (which keeps its name so every
        # single-process consumer reads it unchanged)
        self.shard_index = shard_index
        suffix = f".p{shard_index}" if shard_index else ""
        self.path = os.path.join(
            directory, f"run-{self.run_id}{suffix}.jsonl"
        )
        self._lock = threading.Lock()
        self._lines: list[str] = []
        self._pending = 0
        self._rotate_every = _FIRST_ROTATE_EVERY
        self._closed = False

    def emit(self, record: dict) -> None:
        """Buffer one event record (a plain dict; non-JSON values are
        stringified rather than raised — telemetry must never take down
        the run it observes)."""
        line = json.dumps(_sanitize(record), default=_json_default)
        with self._lock:
            if self._closed:
                return
            self._lines.append(line)
            self._pending += 1
            if self._pending >= self._rotate_every:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        from photon_ml_tpu.utils.atomic_io import atomic_replace_bytes

        data = ("\n".join(self._lines) + "\n").encode()
        atomic_replace_bytes(self.directory, self.path, data)
        self._pending = 0
        self._rotate_every = min(
            max(_FIRST_ROTATE_EVERY, len(self._lines)), _MAX_ROTATE_EVERY
        )

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._rotate_locked()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._rotate_locked()
            self._closed = True


# -- the process-wide active sink ------------------------------------------

_ACTIVE: TelemetrySink | None = None
_state_lock = threading.Lock()


def active_sink() -> TelemetrySink | None:
    return _ACTIVE


def is_active() -> bool:
    """Whether a telemetry sink is currently configured (cheap, lock-free
    — consumers use it to gate observability-only host syncs)."""
    return _ACTIVE is not None


def _process_index() -> int:
    # a rejoin-booted process (fresh interpreter, original identity
    # recorded by multihost.bootstrap_rejoin) must shard under its
    # ORIGINAL index — jax.process_index() is 0 there, and a 0-index
    # rejoiner would collide with the true canonical run file
    try:
        from photon_ml_tpu.parallel import multihost as mh

        if mh.rejoin_identity() is not None:
            return int(mh.original_process_index())
    except Exception:
        pass
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def _process_count() -> int:
    try:
        from photon_ml_tpu.parallel import multihost as mh

        if mh.rejoin_identity() is not None:
            return int(mh.original_process_count())
    except Exception:
        pass
    try:
        import jax

        return int(jax.process_count())
    except Exception:
        return 1


def fleet_telemetry_enabled() -> bool:
    """Fleet telemetry knob: ``PHOTON_TELEMETRY_FLEET`` (strict int parse
    like the sibling knobs — a typo fails loudly). Unset, it follows
    ``PHOTON_RE_SHARD``: the sharded random-effect schedule is exactly
    the workload whose telemetry lives on processes 1..N-1, and the
    default keeps every non-sharded multihost run's sink behavior (and
    file layout) bit-for-bit what it was."""
    env = os.environ.get("PHOTON_TELEMETRY_FLEET")
    if env is not None and env != "":
        return int(env) != 0
    try:
        from photon_ml_tpu.parallel.placement import re_shard_enabled

        return re_shard_enabled()
    except Exception:
        return False


def _fleet_run_id() -> str:
    """One run id for every process of a fleet run: process 0 generates
    its usual timestamp id and broadcasts it (the shards must carry the
    SAME ``<id>`` for ``report fleet`` to join them with the canonical
    file). Collective — every process reaches ``configure`` at the same
    program point, the same contract the drivers' multihost init already
    imposes. Callers that need to avoid the collective pass an explicit
    ``run_id`` (the bench harness does)."""
    import numpy as np

    from photon_ml_tpu.parallel.multihost import broadcast_from_host0

    rid = time.strftime("%Y%m%dT%H%M%S") + f"-{os.getpid()}"
    buf = np.zeros(64, np.uint8)
    raw = rid.encode()[:64]
    buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    out = np.asarray(broadcast_from_host0(buf), np.uint8)
    return bytes(out[out != 0]).decode()


def configure(
    telemetry_dir: str | None,
    run_id: str | None = None,
    force_writer: bool | None = None,
) -> str | None:
    """Enable telemetry into ``telemetry_dir`` and return the run file's
    path. ``None`` leaves telemetry disabled (the CLI drivers call this
    unconditionally with their ``--telemetry-dir`` value). Multihost: the
    output process writes the canonical run file; under fleet telemetry
    (``fleet_telemetry_enabled``) every other process writes its own
    ``.p<k>`` shard, otherwise it gets a disabled subsystem unless
    ``force_writer=True``. Re-configuring closes any previous run's sink
    first."""
    global _ACTIVE
    with _state_lock:
        if _ACTIVE is not None:
            _shutdown_locked()
        if telemetry_dir is None:
            return None
        pidx = _process_index()
        fleet = _process_count() > 1 and fleet_telemetry_enabled()
        if fleet and run_id is None and force_writer is None:
            # collective: every process must agree on the shard-join id
            run_id = _fleet_run_id()
        writer = force_writer if force_writer is not None else pidx == 0
        shard_index = None
        if not writer:
            if not fleet:
                return None
            shard_index = pidx
        sink = TelemetrySink(
            telemetry_dir, run_id=run_id, shard_index=shard_index
        )
        record = {
            "event": "run_start",
            "t": time.time(),
            "schema_version": SCHEMA_VERSION,
            "run_id": sink.run_id,
            "pid": os.getpid(),
            "process_index": pidx,
            "knobs": _knob_snapshot(),
            # the registry is PROCESS-cumulative; the baseline lets a
            # reader (obs/report) delta run_end down to THIS run's
            # share when several runs live in one process
            "metrics_baseline": _metrics.REGISTRY.snapshot(),
        }
        if fleet:
            # only fleet runs carry the field: a single-process (or
            # fleet-off) run's file stays byte-for-byte what it was
            record["fleet"] = {"process_count": _process_count()}
        sink.emit(record)
        _ACTIVE = sink
        _install_jax_monitoring()
        return sink.path


def shutdown() -> None:
    """Emit the ``run_end`` record (with the full metrics snapshot), flush
    durably, and disable the sink. Safe to call when already disabled."""
    with _state_lock:
        _shutdown_locked()


def _shutdown_locked() -> None:
    global _ACTIVE
    sink = _ACTIVE
    _ACTIVE = None  # disable emission first: close must not race new spans
    if sink is None:
        return
    record = {
        "event": "run_end",
        "t": time.time(),
        "run_id": sink.run_id,
        "metrics": _metrics.REGISTRY.snapshot(),
    }
    try:
        from photon_ml_tpu.ops import prefetch

        record["chunk_cache"] = prefetch.cache_stats()
    except Exception:
        pass
    try:
        from photon_ml_tpu.ops import stream_executor

        # only when a stream actually rode the arbiter: an executor-off
        # run's run_end record stays key-for-key what it was
        if stream_executor.traffic_seen():
            record["stream_cache"] = stream_executor.cache_stats()
    except Exception:
        pass
    sink.emit(record)
    sink.close()


def _knob_snapshot() -> dict:
    """The retune surface a run executed under (same knobs the bench
    round-trips), so two JSONLs are diffable AS CONFIGURATIONS too."""
    knobs: dict = {}
    try:
        from photon_ml_tpu.ops import prefetch

        knobs["prefetch_depth"] = prefetch.prefetch_depth()
        knobs["chunk_cache_budget_bytes"] = int(
            prefetch.chunk_cache_budget_bytes()
        )
    except Exception:
        pass
    try:
        from photon_ml_tpu.ops import sparse_tiled as st

        knobs["groups_per_step"] = int(st.GROUPS_PER_STEP)
        knobs["segments_per_dma"] = int(st.SEGMENTS_PER_DMA)
        knobs["groups_per_run"] = int(st.GROUPS_PER_RUN)
        knobs["pipeline_segments"] = int(st.PIPELINE_SEGMENTS)
        knobs["kernel_dtype"] = st.kernel_dtype()
    except Exception:
        pass
    try:
        from photon_ml_tpu.game import random_effect as re_mod

        knobs["re_compact_every"] = int(re_mod.compact_every())
        knobs["re_fuse_buckets"] = int(bool(re_mod.fuse_buckets()))
        knobs["re_combine"] = str(re_mod.re_combine_mode())
    except Exception:
        pass
    try:
        from photon_ml_tpu.game import projector

        knobs["re_project"] = str(projector.re_project_mode())
        knobs["re_project_dim"] = int(projector.re_project_dim())
    except Exception:
        pass
    try:
        from photon_ml_tpu.parallel import placement

        knobs["re_shard"] = int(bool(placement.re_shard_enabled()))
        knobs["re_split"] = int(placement.re_split_factor())
        knobs["re_replan_imbalance"] = float(
            placement.replan_imbalance_threshold()
        )
        knobs["re_device_split"] = int(
            bool(placement.re_device_split_enabled())
        )
        knobs["re_split_weight"] = str(placement.re_split_weight())
    except Exception:
        pass
    try:
        from photon_ml_tpu.data import index_map

        knobs["fe_shard"] = int(bool(index_map.fe_shard_enabled()))
        knobs["fe_split_weight"] = str(index_map.fe_split_weight())
    except Exception:
        pass
    try:
        from photon_ml_tpu.serve import refresh as serve_refresh
        from photon_ml_tpu.serve import router as serve_router
        from photon_ml_tpu.serve import store as serve_store

        knobs["serve_hot_bytes"] = int(serve_store.serve_hot_budget_bytes())
        knobs["serve_max_batch"] = int(serve_router.serve_max_batch())
        knobs["serve_max_wait_ms"] = float(serve_router.serve_max_wait_ms())
        knobs["serve_refresh_every"] = int(
            serve_refresh.serve_refresh_every()
        )
    except Exception:
        pass
    try:
        from photon_ml_tpu.ops import stream_executor

        knobs["stream_executor"] = int(
            bool(stream_executor.stream_executor_enabled())
        )
        knobs["stream_priority"] = str(
            stream_executor.stream_priority_spec()
        )
        knobs["stream_share"] = str(stream_executor.stream_share_spec())
    except Exception:
        pass
    return knobs


# -- XLA compile visibility via jax.monitoring ------------------------------
# Registered ONCE per process at obs import (and defensively re-checked in
# configure), never unregistered (jax offers no targeted removal); the
# callbacks consult the active sink so they are cheap no-ops between runs.
# Durations also land in the registry, so compile wall is in every
# snapshot — bench telemetry blocks included — even without a sink.

_jax_monitoring_installed = False


def _on_jax_duration(name: str, secs: float, **kw) -> None:
    try:
        if "backend_compile" in name:
            # the leaf XLA compile phase only: jax nests it inside broader
            # "compile" events, and summing every match double-counts
            _metrics.REGISTRY.timer_add("jax.compile_s", secs)
        sink = _ACTIVE
        if sink is not None:
            sink.emit(
                {"event": "jax_event", "t": time.time(), "name": name,
                 "dur_s": secs}
            )
    except Exception:
        pass  # monitoring must never break compilation


def _install_jax_monitoring() -> None:
    global _jax_monitoring_installed
    if _jax_monitoring_installed:
        return
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_jax_duration)
        _jax_monitoring_installed = True
    except Exception:
        pass  # older jax without monitoring: compile events just absent
