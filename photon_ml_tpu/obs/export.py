"""Chrome-trace / Perfetto export of a telemetry run.

Renders a run's JSONL span records as Chrome trace-event JSON (the
``{"traceEvents": [...]}`` container format), so the host-side span
timeline opens in Perfetto / ``chrome://tracing`` NEXT TO the
``jax.profiler`` device traces the drivers already capture — one tool,
both sides of the host/device boundary.

Mapping: every ``span`` record becomes a complete event (``"ph": "X"``,
micro-second ``ts``/``dur`` relative to ``run_start``); ``log`` and
optimizer records become instant events (``"ph": "i"``) so warnings and
per-iteration markers are visible on the timeline. Thread ids map to
``tid`` with thread-name metadata events, so the prefetch worker pool
renders as separate tracks under one process.

Fleet runs: ``fleet_chrome_trace`` merges every shard of one run —
process 0's canonical file plus the ``.p<k>`` shards — into ONE trace
on a shared time base (``pid`` = process index, with ``process_name``
metadata), so a 2-process exchange schedule reads as two aligned
swim-lane groups on a single timeline. ``export_chrome_trace`` accepts
a run file, a LIST of shard files, or a telemetry directory (all shards
of the newest canonical run).
"""

from __future__ import annotations

import json
import os
from typing import Any


def chrome_trace(
    records: list[dict],
    pid: int | None = None,
    t0: float | None = None,
) -> dict:
    """Chrome trace-event JSON (as a dict) for one run's records.
    ``pid``/``t0`` override the run's own process index / start time —
    the fleet merge pins every shard to one shared time base."""
    for r in records:
        if r.get("event") == "run_start":
            if t0 is None:
                t0 = float(r["t"])
            if pid is None:
                pid = int(r.get("process_index", 0))
            break
    if t0 is None and records:
        t0 = min(float(r["t"]) for r in records if "t" in r)
    t0 = t0 or 0.0
    pid = pid or 0

    events: list[dict[str, Any]] = []
    thread_names: dict[int, str] = {}

    def us(t: float) -> float:
        return max((t - t0) * 1e6, 0.0)

    for r in records:
        kind = r.get("event")
        if kind == "span":
            tid = int(r.get("tid") or 0)
            if r.get("thread") and tid not in thread_names:
                thread_names[tid] = r["thread"]
            ev: dict[str, Any] = {
                "name": r.get("name", "span"),
                "ph": "X",
                "ts": us(float(r["t"])),
                "dur": float(r.get("dur_s", 0.0)) * 1e6,
                "pid": pid,
                "tid": tid,
            }
            args = dict(r.get("attrs") or {})
            args["span_id"] = r.get("span_id")
            if r.get("parent_id") is not None:
                args["parent_id"] = r["parent_id"]
            ev["args"] = args
            events.append(ev)
        elif kind in ("log", "optim_iter", "optim_result", "jax_event",
                      "p2p_send", "p2p_recv", "p2p_heartbeat",
                      "exchange", "exchange_wait"):
            name = (
                r.get("message") if kind == "log" else r.get("name", kind)
            ) or kind
            events.append(
                {
                    "name": str(name)[:120],
                    "ph": "i",
                    "s": "t",
                    "ts": us(float(r["t"])),
                    "pid": pid,
                    "tid": int(r.get("tid") or 0),
                    "args": {
                        k: v
                        for k, v in r.items()
                        if k not in ("event", "t") and _plain(v)
                    },
                }
            )
    for tid, name in thread_names.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": name},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _plain(v) -> bool:
    return isinstance(v, (str, int, float, bool)) or v is None


def fleet_chrome_trace(records_by_shard: list[list[dict]]) -> dict:
    """One merged trace for every shard of a fleet run: a shared time
    base (the earliest shard's ``run_start``), ``pid`` = each shard's
    process index, plus ``process_name`` metadata so the Perfetto UI
    labels the swim-lane groups."""
    t0 = None
    for records in records_by_shard:
        for r in records:
            if r.get("event") == "run_start":
                t = float(r["t"])
                t0 = t if t0 is None else min(t0, t)
                break
    events: list[dict[str, Any]] = []
    for records in records_by_shard:
        pid = 0
        for r in records:
            if r.get("event") == "run_start":
                pid = int(r.get("process_index", 0))
                break
        events.extend(chrome_trace(records, pid=pid, t0=t0)["traceEvents"])
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"process {pid}"},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(
    jsonl_path: str | list[str], out_path: str | None = None
) -> dict:
    """Read a run (file), a fleet run (list of shard files, or a
    telemetry DIRECTORY — all shards of the newest canonical run) and
    return (optionally write) its Chrome trace. A directory or list
    with a single file degrades to the plain single-process trace."""
    from photon_ml_tpu.obs.report import fleet_run_paths, load_run

    if isinstance(jsonl_path, str) and os.path.isdir(jsonl_path):
        jsonl_path = fleet_run_paths(jsonl_path)
    if isinstance(jsonl_path, (list, tuple)):
        if len(jsonl_path) == 1:
            trace = chrome_trace(load_run(jsonl_path[0]))
        else:
            trace = fleet_chrome_trace(
                [load_run(p) for p in jsonl_path]
            )
    else:
        trace = chrome_trace(load_run(jsonl_path))
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(trace, f)
    return trace
