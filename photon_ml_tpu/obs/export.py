"""Chrome-trace / Perfetto export of a telemetry run.

Renders a run's JSONL span records as Chrome trace-event JSON (the
``{"traceEvents": [...]}`` container format), so the host-side span
timeline opens in Perfetto / ``chrome://tracing`` NEXT TO the
``jax.profiler`` device traces the drivers already capture — one tool,
both sides of the host/device boundary.

Mapping: every ``span`` record becomes a complete event (``"ph": "X"``,
micro-second ``ts``/``dur`` relative to ``run_start``); ``log`` and
optimizer records become instant events (``"ph": "i"``) so warnings and
per-iteration markers are visible on the timeline. Thread ids map to
``tid`` with thread-name metadata events, so the prefetch worker pool
renders as separate tracks under one process.
"""

from __future__ import annotations

import json
from typing import Any


def chrome_trace(records: list[dict]) -> dict:
    """Chrome trace-event JSON (as a dict) for one run's records."""
    t0 = None
    pid = 0
    for r in records:
        if r.get("event") == "run_start":
            t0 = float(r["t"])
            pid = int(r.get("process_index", 0))
            break
    if t0 is None and records:
        t0 = min(float(r["t"]) for r in records if "t" in r)
    t0 = t0 or 0.0

    events: list[dict[str, Any]] = []
    thread_names: dict[int, str] = {}

    def us(t: float) -> float:
        return max((t - t0) * 1e6, 0.0)

    for r in records:
        kind = r.get("event")
        if kind == "span":
            tid = int(r.get("tid") or 0)
            if r.get("thread") and tid not in thread_names:
                thread_names[tid] = r["thread"]
            ev: dict[str, Any] = {
                "name": r.get("name", "span"),
                "ph": "X",
                "ts": us(float(r["t"])),
                "dur": float(r.get("dur_s", 0.0)) * 1e6,
                "pid": pid,
                "tid": tid,
            }
            args = dict(r.get("attrs") or {})
            args["span_id"] = r.get("span_id")
            if r.get("parent_id") is not None:
                args["parent_id"] = r["parent_id"]
            ev["args"] = args
            events.append(ev)
        elif kind in ("log", "optim_iter", "optim_result", "jax_event"):
            name = (
                r.get("message") if kind == "log" else r.get("name", kind)
            ) or kind
            events.append(
                {
                    "name": str(name)[:120],
                    "ph": "i",
                    "s": "t",
                    "ts": us(float(r["t"])),
                    "pid": pid,
                    "tid": int(r.get("tid") or 0),
                    "args": {
                        k: v
                        for k, v in r.items()
                        if k not in ("event", "t") and _plain(v)
                    },
                }
            )
    for tid, name in thread_names.items():
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": name},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _plain(v) -> bool:
    return isinstance(v, (str, int, float, bool)) or v is None


def export_chrome_trace(jsonl_path: str, out_path: str | None = None) -> dict:
    """Read a run JSONL and return (optionally write) its Chrome trace."""
    from photon_ml_tpu.obs.report import load_run

    trace = chrome_trace(load_run(jsonl_path))
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(trace, f)
    return trace
