"""Nested host-side spans + structured event emission.

``span("descent/iter", coordinate=cid)`` opens a named wall-clock span;
spans nest through a THREAD-LOCAL stack, so concurrent prefetch worker
threads each build their own span tree instead of inheriting whatever the
consumer thread happened to have open (cross-thread parent leakage would
corrupt every timeline the workers touch). A span record is emitted on
exit as one complete event — name, ids, thread, start time, duration,
attributes — which maps 1:1 onto a Chrome-trace complete event for the
Perfetto exporter.

Disabled fast path: with no active sink, ``span()`` returns one shared
module-level no-op context manager — no object allocation, no stack
touch, no clock read — so spans stay wired through production hot paths
unconditionally.
"""

from __future__ import annotations

import itertools
import threading
import time

from photon_ml_tpu.obs import sink as _sink_mod

# span ids are process-unique; itertools.count is atomic under the GIL
_ids = itertools.count(1)
_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _NoopSpan:
    """Shared do-nothing context manager (the disabled-sink fast path)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "span_id", "parent_id", "t0", "start_unix")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        st = _stack()
        self.parent_id = st[-1].span_id if st else None
        self.span_id = next(_ids)
        st.append(self)
        self.start_unix = time.time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self.t0
        st = _stack()
        # tolerate exotic unwind orders; normal exits pop the top
        if st and st[-1] is self:
            st.pop()
        elif self in st:
            st.remove(self)
        s = _sink_mod.active_sink()
        if s is not None:
            th = threading.current_thread()
            rec = {
                "event": "span",
                "t": self.start_unix,
                "name": self.name,
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                "tid": th.ident,
                "thread": th.name,
                "dur_s": dur,
            }
            if self.attrs:
                rec["attrs"] = self.attrs
            if exc_type is not None:
                rec["error"] = exc_type.__name__
            s.emit(rec)
            if self.parent_id is None:
                # ROOT-span exit: sample device HBM watermarks (per-fit /
                # per-driver cadence — never per-iteration; the sampler is
                # itself sink-gated and never raises)
                try:
                    from photon_ml_tpu.obs import devcost

                    devcost.sample_hbm_watermarks(root_span=self.name)
                except Exception:
                    pass
        return False


def span(name: str, **attrs):
    """A nested wall-clock span; a no-op singleton when telemetry is off."""
    if _sink_mod.active_sink() is None:
        return NOOP_SPAN
    return _Span(name, attrs)


def current_span_id() -> int | None:
    st = getattr(_tls, "stack", None)
    return st[-1].span_id if st else None


def emit_event(event: str, **payload) -> None:
    """Emit one structured record (attributed to the current thread's open
    span, if any). A no-op when telemetry is disabled."""
    s = _sink_mod.active_sink()
    if s is None:
        return
    rec = {"event": event, "t": time.time()}
    sid = current_span_id()
    if sid is not None:
        rec["span_id_ref"] = sid
    rec.update(payload)
    s.emit(rec)


def emit_log(level: str, message: str, fields: dict | None = None) -> None:
    """Structured twin of a PhotonLogger warn/error line (the logger's
    default event hook)."""
    s = _sink_mod.active_sink()
    if s is None:
        return
    rec = {"event": "log", "t": time.time(), "level": level,
           "message": message}
    sid = current_span_id()
    if sid is not None:
        rec["span_id_ref"] = sid
    if fields:
        rec["fields"] = fields
    s.emit(rec)
