"""Per-coordinate down-sampling.

Reference parity: ``photon-api::ml.sampling.{DownSampler,
BinaryClassificationDownSampler, DefaultDownSampler}`` (SURVEY.md §2.2) —
used per-coordinate (especially the fixed effect) to shrink the training set:

- Binary classification: keep ALL positives, Bernoulli-sample negatives at
  ``rate`` and multiply the kept negatives' weights by ``1/rate`` so the
  objective stays an unbiased estimate of the full-data objective.
- Default (regression tasks): uniform Bernoulli sample at ``rate`` with no
  weight correction (matching the reference's plain ``RDD.sample``).

TPU-first note: down-sampling happens on the host at ingest as *row-index
selection*. The selected rows form the coordinate's training batch (a
gather); scoring always uses every row. This replaces the reference's
per-trainModel RDD sample with a seeded, reproducible index computation.
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.types import TaskType


def default_down_sample(
    num_rows: int, rate: float, rng: np.random.Generator
) -> tuple[np.ndarray, np.ndarray | None]:
    """Uniform Bernoulli sample of rows. Returns (rows, weight_scale=None).

    Parity: ``DefaultDownSampler`` — no weight correction.
    """
    if not 0.0 < rate < 1.0:
        raise ValueError(f"down-sampling rate must be in (0, 1), got {rate}")
    keep = rng.uniform(size=num_rows) < rate
    return np.flatnonzero(keep), None


def binary_classification_down_sample(
    labels: np.ndarray, rate: float, rng: np.random.Generator
) -> tuple[np.ndarray, np.ndarray]:
    """Negative down-sampling for imbalanced binary data.

    Keeps every positive (label > 0), samples negatives at ``rate``, and
    returns per-kept-row weight multipliers (1 for positives, 1/rate for
    kept negatives). Parity: ``BinaryClassificationDownSampler``.
    """
    if not 0.0 < rate < 1.0:
        raise ValueError(f"down-sampling rate must be in (0, 1), got {rate}")
    labels = np.asarray(labels)
    positive = labels > 0
    keep = positive | (rng.uniform(size=labels.shape[0]) < rate)
    rows = np.flatnonzero(keep)
    scale = np.where(positive[rows], 1.0, 1.0 / rate).astype(np.float32)
    return rows, scale


def down_sample(
    task: TaskType,
    labels: np.ndarray,
    rate: float,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray | None]:
    """Task-appropriate down-sampling (the reference's driver picks the
    sampler the same way: classification → negative down-sampling, else
    uniform). Returns (row_indices, weight_scale_or_None)."""
    rng = np.random.default_rng(seed)
    if task.is_classification:
        return binary_classification_down_sample(labels, rate, rng)
    return default_down_sample(len(labels), rate, rng)
