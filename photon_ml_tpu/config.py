"""Typed configuration dataclasses.

Reference parity: the spark.ml ``Param``/``ParamMap`` config surface of
``GameTrainingDriver`` / ``GameScoringDriver`` plus the per-coordinate config
objects (``FixedEffectCoordinateConfiguration``,
``RandomEffectCoordinateConfiguration``, ``FeatureShardConfiguration``,
``FixedEffectOptimizationConfiguration``,
``RandomEffectOptimizationConfiguration``) — SURVEY.md §2.2/§2.3/§5.6.

The TPU build replaces scopt+ParamMap with plain dataclasses that round-trip
through JSON (``to_dict`` / ``from_dict``), so a driver invocation is fully
described by one JSON document.
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

from photon_ml_tpu.types import (
    DataValidationType,
    ModelOutputMode,
    NormalizationType,
    OptimizerType,
    RegularizationType,
    TaskType,
    VarianceComputationType,
)


def _to_jsonable(value: Any) -> Any:
    if isinstance(value, enum.Enum):
        return value.value
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return {f.name: _to_jsonable(getattr(value, f.name)) for f in dataclasses.fields(value)}
    if isinstance(value, Mapping):
        return {k: _to_jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_to_jsonable(v) for v in value]
    return value


class _JsonMixin:
    def to_dict(self) -> dict:
        return _to_jsonable(self)

    def replace(self, **kwargs):
        return dataclasses.replace(self, **kwargs)


@dataclass(frozen=True)
class RegularizationContext(_JsonMixin):
    """L1/L2/elastic-net bookkeeping.

    Parity: ``photon-lib::ml.optimization.RegularizationContext``. For
    ELASTIC_NET, ``alpha`` is the L1 fraction: l1 = alpha * weight,
    l2 = (1 - alpha) * weight.
    """

    regularization_type: RegularizationType = RegularizationType.NONE
    alpha: float = 0.5  # elastic-net mixing; only used for ELASTIC_NET

    def l1_weight(self, regularization_weight: float) -> float:
        if self.regularization_type is RegularizationType.L1:
            return regularization_weight
        if self.regularization_type is RegularizationType.ELASTIC_NET:
            return self.alpha * regularization_weight
        return 0.0

    def l2_weight(self, regularization_weight: float) -> float:
        if self.regularization_type is RegularizationType.L2:
            return regularization_weight
        if self.regularization_type is RegularizationType.ELASTIC_NET:
            return (1.0 - self.alpha) * regularization_weight
        return 0.0


@dataclass(frozen=True)
class OptimizerConfig(_JsonMixin):
    """Parity: ``photon-lib::ml.optimization.OptimizerConfig``.

    ``tolerance`` is relative gradient-norm tolerance (converged when
    ||g|| <= tolerance * max(1, ||g0||)), matching Breeze's convergence
    check shape. ``max_iterations`` bounds the device loop trip count.
    """

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 100
    tolerance: float = 1e-7
    # L-BFGS history size (Breeze default m=10 per SURVEY.md §2.1)
    history_length: int = 10
    # Interpolating-backtracking line-search bound. With safeguarded
    # quadratic interpolation (optim/lbfgs.py) a workable step is found in
    # 1-3 refinements; 10 bounds the terminal no-representable-progress
    # iteration without burning 25 full objective passes on it.
    max_line_search_steps: int = 10
    # TRON inner conjugate-gradient iteration bound
    max_cg_iterations: int = 20


@dataclass(frozen=True)
class OptimizationConfig(_JsonMixin):
    """One coordinate's optimization setup: optimizer + regularization +
    down-sampling rate.

    Parity: ``photon-api::ml.optimization.game.FixedEffectOptimizationConfiguration``
    / ``RandomEffectOptimizationConfiguration``.
    """

    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    regularization: RegularizationContext = field(default_factory=RegularizationContext)
    regularization_weight: float = 0.0
    down_sampling_rate: float = 1.0


@dataclass(frozen=True)
class FeatureShardConfig(_JsonMixin):
    """Parity: ``FeatureShardConfiguration`` — which feature bags make up a
    shard, and whether the shard gets an intercept column.
    """

    feature_bags: tuple[str, ...] = ()
    has_intercept: bool = True


@dataclass(frozen=True)
class FixedEffectCoordinateConfig(_JsonMixin):
    """Parity: ``FixedEffectCoordinateConfiguration``."""

    feature_shard_id: str = "global"
    optimization: OptimizationConfig = field(default_factory=OptimizationConfig)


@dataclass(frozen=True)
class RandomEffectCoordinateConfig(_JsonMixin):
    """Parity: ``RandomEffectCoordinateConfiguration``.

    ``random_effect_type`` names the entity-id column (e.g. "userId").
    ``active_data_upper_bound`` reservoir-samples each entity's training rows
    (reference: ``numActiveDataPointsUpperBound``);
    ``features_to_samples_ratio_upper_bound`` prunes per-entity features
    (reference: ``numFeaturesToSamplesRatioUpperBound``).
    """

    random_effect_type: str = "entityId"
    feature_shard_id: str = "per_entity"
    optimization: OptimizationConfig = field(default_factory=OptimizationConfig)
    active_data_upper_bound: int | None = None
    features_to_samples_ratio_upper_bound: float | None = None
    # Shared random projection (reference: ``RandomProjection`` /
    # ``ProjectionMatrix``): project this coordinate's features to the given
    # dimension before the per-entity solves. None = off.
    random_projection_dim: int | None = None
    # TPU-specific: bucket geometry for the batched per-entity solver.
    # Entities are grouped into buckets of padded sample count; None = auto.
    sample_bucket_sizes: tuple[int, ...] | None = None
    # Auto-ladder tuning (ignored when sample_bucket_sizes is set): merge
    # the geometric capacity ladder down toward this many buckets — each
    # bucket is one device program per descent iteration — as long as total
    # padded cells stay under bucket_max_padded_ratio x active samples.
    # Large-d random effects (where padded FLOPs, not program count,
    # dominate) can lower the ratio or raise the target.
    bucket_target_count: int = 4
    bucket_max_padded_ratio: float = 4.0


@dataclass(frozen=True)
class NormalizationConfig(_JsonMixin):
    normalization_type: NormalizationType = NormalizationType.NONE


@dataclass(frozen=True)
class GameTrainingConfig(_JsonMixin):
    """Full GAME training run configuration.

    Parity: the ``GameTrainingDriver`` Param surface (SURVEY.md §2.3):
    coordinate configurations + update sequence + descent iterations + task
    type + normalization + evaluators + output mode + warm start + variance
    + hyperparameter tuning.
    """

    task_type: TaskType = TaskType.LOGISTIC_REGRESSION
    coordinate_update_sequence: tuple[str, ...] = ("fixed",)
    coordinate_descent_iterations: int = 1
    fixed_effect_coordinates: Mapping[str, FixedEffectCoordinateConfig] = field(
        default_factory=dict
    )
    random_effect_coordinates: Mapping[str, RandomEffectCoordinateConfig] = field(
        default_factory=dict
    )
    feature_shards: Mapping[str, FeatureShardConfig] = field(default_factory=dict)
    normalization: NormalizationType = NormalizationType.NONE
    evaluators: tuple[str, ...] = ()
    output_mode: ModelOutputMode = ModelOutputMode.BEST
    variance_computation: VarianceComputationType = VarianceComputationType.NONE
    data_validation: DataValidationType = DataValidationType.VALIDATE_DISABLED
    model_input_dir: str | None = None  # warm start
    # incremental training: the warm-start model additionally acts as a
    # Gaussian MAP prior (per-coordinate means + 1/variance precisions)
    incremental: bool = False
    hyperparameter_tuning_iters: int = 0
    # Per-coordinate regularization-weight lists; the training grid is their
    # cross-product (reference: per-coordinate regularizationWeights in the
    # coordinate configurations drive the GameEstimator grid). Coordinates
    # absent from the map keep their single configured weight.
    regularization_weight_grid: Mapping[str, tuple[float, ...]] = field(
        default_factory=dict
    )

    def coordinate_config(self, cid: str):
        if cid in self.fixed_effect_coordinates:
            return self.fixed_effect_coordinates[cid]
        if cid in self.random_effect_coordinates:
            return self.random_effect_coordinates[cid]
        raise KeyError(f"Unknown coordinate id: {cid!r}")


@dataclass(frozen=True)
class MeshConfig(_JsonMixin):
    """Device-mesh geometry for the distributed runtime.

    The reference's parallelism inventory (SURVEY.md §2.7) needs two logical
    axes: ``data`` (sample sharding for fixed effects — the treeAggregate
    analog) and ``entity`` (entity sharding for random effects). By default
    both map onto all devices (the axes are used by different phases, so one
    physical axis serves both).
    """

    data_axis: str = "data"
    entity_axis: str = "entity"
    # None = use all available devices on the data axis.
    data_axis_size: int | None = None


def _from_dict(cls, d: Mapping[str, Any]):
    """Generic dataclass-from-JSON-dict: only keys present in ``d`` are
    passed, so defaults live in exactly one place (the dataclass), and
    nested dataclasses / enums / tuples are reconstructed from the field's
    type annotation."""
    import typing

    hints = typing.get_type_hints(cls)
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name not in d:
            continue
        v = d[f.name]
        kwargs[f.name] = _convert(hints[f.name], v)
    return cls(**kwargs)


def _convert(tp, v):
    import collections.abc
    import types
    import typing

    origin = typing.get_origin(tp)
    args = typing.get_args(tp)
    if origin is typing.Union or origin is types.UnionType:
        if v is None:
            return None
        non_none = [a for a in args if a is not type(None)]
        return _convert(non_none[0], v)
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return tp(v)
    if dataclasses.is_dataclass(tp):
        return _from_dict(tp, v)
    if origin in (tuple, collections.abc.Sequence) or tp is tuple:
        inner = args[0] if args else str
        return tuple(_convert(inner, x) for x in v)
    if origin in (dict, collections.abc.Mapping):
        val_tp = args[1] if len(args) == 2 else str
        return {k: _convert(val_tp, x) for k, x in v.items()}
    if tp is float:
        return float(v)
    if tp is int:
        return int(v)
    if tp is bool:
        return bool(v)
    return v


def parse_config(d: Mapping[str, Any]) -> GameTrainingConfig:
    """Build a ``GameTrainingConfig`` from a JSON-style dict (inverse of
    ``to_dict``). Keys absent from the dict keep the dataclass defaults."""
    return _from_dict(GameTrainingConfig, d)
