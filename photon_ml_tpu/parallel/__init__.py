"""Distributed runtime: device meshes, sharded objectives, collectives.

Replaces the reference's Spark communication layer (SURVEY.md §2.7):
TorrentBroadcast + treeAggregate become ``lax.psum`` over ICI inside
``shard_map``; the shuffle disappears entirely (entity grouping happens at
ingest — see ``data.entity_index``).
"""

from photon_ml_tpu.parallel.mesh import data_mesh, local_device_count  # noqa: F401
from photon_ml_tpu.parallel.distributed import (  # noqa: F401
    DistributedTrainer,
    shard_batch,
    sharded_minimize,
)
from photon_ml_tpu.parallel.multihost import (  # noqa: F401
    global_batch_from_host_shards,
    host_shard_of_paths,
    initialize_multihost,
    shard_batch_multihost,
)
from photon_ml_tpu.parallel.placement import (  # noqa: F401
    PlacementPlan,
    plan_entity_placement,
    plan_shard_placement,
    re_shard_enabled,
)
