"""Deterministic fault injection for the framed-P2P exchange mesh.

The reference inherits fault tolerance from Spark — task retry and
lineage recovery are exercised daily by real cluster flakiness. Our
JAX/TPU fleet has no such substrate, and real network faults are neither
reproducible nor CI-friendly, so this module makes them DETERMINISTIC: a
seedless fault plan (knob ``PHOTON_FAULT_PLAN``) names exactly which
frame-set of which exchange on which link gets dropped, corrupted,
delayed, or torn down — and the link layer's retry/backoff, the CRC
corruption detection, the heartbeat-to-timeout path and the peer-loss
recovery machinery can each be driven through their full state machines
by host-side tests and the chaos harness (``scripts/chaos_quick.sh``)
with zero real flakiness.

Plan grammar (JSON — a list of fault specs, or ``@/path/to/plan.json``):

    [
      {"op": "drop",    "link": [0, 1], "seq": 2, "tag": "offsets"},
      {"op": "corrupt", "link": [1, 0], "seq": 1},
      {"op": "delay",   "link": [0, 1], "seq": 3, "delay_s": 0.2},
      {"op": "close",   "link": [0, 1], "seq": 4},
      {"op": "kill",    "link": [1, 0], "seq": 2, "exit_code": 137}
    ]

- ``op``: ``drop`` (the frame set is never sent), ``corrupt`` (payload
  bytes are flipped before send — detected by the CRC trailer when
  ``PHOTON_P2P_CRC`` negotiated, by size/row validation otherwise),
  ``delay`` (``delay_s`` sleep before send), ``close`` (the link socket
  is closed instead of sending — the peer sees EOF), ``kill`` (the
  process exits hard at the send boundary — the peer-loss drill),
  ``rejoin`` (the process exits hard AND re-execs itself ``delay_s``
  seconds later as a rejoin boot — the elastic-rejoin drill; needs
  ``PHOTON_REJOIN_CMD``, a JSON argv list naming the command to
  relaunch, because a ``python -c`` worker's own command string is not
  recoverable from ``sys.argv``. The child gets ``PHOTON_REJOIN_BOOT``
  = the dying process's index and an EMPTY fault plan — a rejoined
  process must not re-run the plan that killed it).
- ``link``: ``[src, dst]`` ORIGINAL process indices. Send-side faults
  fire on the ``src`` process; every spec is matched on the side that
  performs the send (the injection boundary is the framed send path,
  where one process can deterministically perturb the wire).
- ``seq``: the per-link frame-set ordinal (the SAME submission-order
  counter the PR-9 telemetry correlation ids use — the k-th frame set
  ever sent on that link), so a plan entry names one exact frame set.
- ``tag`` (optional): additionally require the exchange tag to match
  (e.g. ``offsets``, ``scores``, ``ingest/<cid>``). Omitted = any tag.

Every spec fires AT MOST ONCE (consumed on match), so a retried
exchange's resend goes through clean — exactly the transient-fault
contract the retry layer is tested against. The plan is parsed once per
distinct env value and the no-plan fast path is one ``is None`` check,
so production exchanges pay nothing.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field

VALID_OPS = ("drop", "corrupt", "delay", "close", "kill", "rejoin")


@dataclass
class FaultSpec:
    op: str
    src: int
    dst: int
    seq: int
    tag: str | None = None
    delay_s: float = 0.0
    exit_code: int = 137
    fired: bool = False

    def matches(self, src: int, dst: int, seq: int, tag: str) -> bool:
        if self.fired:
            return False
        if (self.src, self.dst, self.seq) != (src, dst, seq):
            return False
        return self.tag is None or self.tag == tag


@dataclass
class FaultPlan:
    specs: list[FaultSpec] = field(default_factory=list)

    def pop_send_fault(
        self, src: int, dst: int, seq: int, tag: str
    ) -> FaultSpec | None:
        """The (at most one) unfired spec for this frame set, consumed.
        First match in plan order wins — a plan listing two faults for
        one frame set fires them on successive attempts, which is how a
        plan expresses 'fail twice, then succeed'."""
        for s in self.specs:
            if s.matches(src, dst, seq, tag):
                s.fired = True
                return s
        return None

    @property
    def remaining(self) -> int:
        return sum(1 for s in self.specs if not s.fired)


def parse_plan(text: str) -> FaultPlan:
    """Strict parse — a typo'd plan must fail the run loudly, not
    silently chaos-test nothing."""
    if text.startswith("@"):
        with open(text[1:]) as f:
            text = f.read()
    doc = json.loads(text)
    if not isinstance(doc, list):
        raise ValueError(
            f"PHOTON_FAULT_PLAN must be a JSON list of fault specs, got "
            f"{type(doc).__name__}"
        )
    specs: list[FaultSpec] = []
    for i, d in enumerate(doc):
        if not isinstance(d, dict):
            raise ValueError(f"fault spec {i} is not an object: {d!r}")
        unknown = set(d) - {"op", "link", "seq", "tag", "delay_s", "exit_code"}
        if unknown:
            raise ValueError(
                f"fault spec {i}: unknown keys {sorted(unknown)}"
            )
        op = d.get("op")
        if op not in VALID_OPS:
            raise ValueError(
                f"fault spec {i}: op {op!r} not in {VALID_OPS}"
            )
        link = d.get("link")
        if (
            not isinstance(link, (list, tuple)) or len(link) != 2
            or not all(isinstance(x, int) and x >= 0 for x in link)
        ):
            raise ValueError(
                f"fault spec {i}: link must be [src, dst] process "
                f"indices, got {link!r}"
            )
        seq = d.get("seq")
        if not isinstance(seq, int) or seq < 1:
            raise ValueError(
                f"fault spec {i}: seq must be a 1-based frame-set "
                f"ordinal, got {seq!r}"
            )
        if op in ("delay", "rejoin") and not d.get("delay_s"):
            raise ValueError(f"fault spec {i}: {op} requires delay_s > 0")
        specs.append(
            FaultSpec(
                op=op, src=int(link[0]), dst=int(link[1]), seq=seq,
                tag=d.get("tag"), delay_s=float(d.get("delay_s", 0.0)),
                exit_code=int(d.get("exit_code", 137)),
            )
        )
    return FaultPlan(specs=specs)


# parsed-plan cache keyed on the raw env value: call-time knob reads (the
# bench RETUNE idiom) without re-parsing per frame; fired-state lives in
# the cached object, so one process's plan is consumed monotonically
_PLAN_CACHE: dict[str, FaultPlan] = {}


def active_plan() -> FaultPlan | None:
    """The process's fault plan, or None (the production fast path)."""
    env = os.environ.get("PHOTON_FAULT_PLAN")
    if not env:
        return None
    plan = _PLAN_CACHE.get(env)
    if plan is None:
        plan = _PLAN_CACHE[env] = parse_plan(env)
    return plan


def reset() -> None:
    """Forget parsed plans (tests re-arm consumed specs this way)."""
    _PLAN_CACHE.clear()


def _corrupt(buf: bytes) -> bytes:
    """Flip one byte mid-payload — undetectable by length framing,
    guaranteed caught by the CRC trailer."""
    if not buf:
        return buf
    i = len(buf) // 2
    return buf[:i] + bytes([buf[i] ^ 0xFF]) + buf[i + 1:]


def apply_send_fault(
    spec: FaultSpec, frames: list[bytes], sock
) -> tuple[list[bytes] | None, bool]:
    """Apply ``spec`` at the framed send boundary. Returns ``(frames,
    corrupt_wire)``: the frame payloads to send (None = the whole frame
    set is dropped) and whether the link layer should corrupt the FIRST
    frame's bytes on the wire — after any CRC trailer is computed, so
    the corruption models a wire/buffer fault the trailer detects (a
    pre-CRC flip would be faithfully checksummed and sail through,
    which tests nothing). ``close``/``kill`` act on the socket/process
    directly."""
    _emit(spec)
    if spec.op == "drop":
        return None, False
    if spec.op == "delay":
        time.sleep(spec.delay_s)
        return frames, False
    if spec.op == "corrupt":
        return frames, True
    if spec.op == "close":
        try:
            sock.close()
        # lint: waive(except-swallow) the close IS the injected fault; a double-close error is the drill succeeding
        except OSError:
            pass
        # the next sendall on the closed socket raises
        return frames, False
    if spec.op == "kill":
        # flush telemetry? no — a killed process is a killed process;
        # the drill is precisely that its shard ends mid-run and its
        # peers must cope. os._exit skips atexit/finally by design.
        os._exit(spec.exit_code)
    if spec.op == "rejoin":
        _spawn_rejoin_child(spec)
        os._exit(spec.exit_code)
    raise AssertionError(f"unhandled fault op {spec.op!r}")


def _spawn_rejoin_child(spec: FaultSpec) -> None:
    """Launch the delayed re-exec for a ``rejoin`` spec, then let the
    caller hard-exit. The child is a detached ``sh`` that sleeps
    ``delay_s`` and execs the command from ``PHOTON_REJOIN_CMD`` (JSON
    argv) with ``PHOTON_REJOIN_BOOT`` = this process's original index
    and the fault plan CLEARED. stdout/stderr are inherited, so a
    harness reading the dying worker's pipe also captures the
    rejoiner's output — no extra plumbing."""
    import subprocess

    raw = os.environ.get("PHOTON_REJOIN_CMD")
    if not raw:
        raise RuntimeError(
            "fault op 'rejoin' needs PHOTON_REJOIN_CMD (JSON argv list "
            "of the command to relaunch)"
        )
    cmd = json.loads(raw)
    if not isinstance(cmd, list) or not all(isinstance(c, str) for c in cmd):
        raise RuntimeError(
            f"PHOTON_REJOIN_CMD must be a JSON list of strings, got {raw!r}"
        )
    env = dict(os.environ)
    env["PHOTON_REJOIN_BOOT"] = str(spec.src)
    env.pop("PHOTON_FAULT_PLAN", None)
    # sh -c 'sleep N; exec "$0" "$@"' <argv...>: $0/$@ carry the command
    # verbatim (no quoting pitfalls), and the exec replaces the shell so
    # the rejoiner is a direct child of init once this process dies
    subprocess.Popen(
        [
            "/bin/sh", "-c",
            f'sleep {float(spec.delay_s)}; exec "$0" "$@"', *cmd,
        ],
        env=env, start_new_session=True,
    )


def _emit(spec: FaultSpec) -> None:
    """A ``fault_injected`` record in the run's telemetry shard — the
    chaos harness asserts the fault actually fired (except ``kill``,
    whose shard necessarily truncates)."""
    try:
        from photon_ml_tpu.obs.spans import emit_event

        emit_event(
            "fault_injected", op=spec.op, src=spec.src, dst=spec.dst,
            seq=spec.seq, tag=spec.tag,
        )
    # lint: waive(except-swallow) telemetry guard: the fault record must never take down the drill it observes
    except Exception:
        pass


# -- synthetic straggler (the re-planner's fault drill) ----------------------


def straggler_spec() -> tuple[int, float] | None:
    """``PHOTON_RE_STRAGGLER`` = ``"<process>:<delay_s>"`` — the
    deterministic straggler injection for the telemetry-driven
    re-planner (``PHOTON_RE_REPLAN_IMBALANCE``): the named process
    sleeps ``delay_s`` at the start of every streamed random-effect
    bucket-solve visit, so its MEASURED solve wall genuinely inflates
    (the re-plan trigger reads real telemetry, not a faked gauge) while
    the math — and therefore the model, bitwise — is untouched. Strict
    parse, like every fault knob."""
    env = os.environ.get("PHOTON_RE_STRAGGLER")
    if not env:
        return None
    proc, sep, delay = env.partition(":")
    if not sep:
        raise ValueError(
            f"PHOTON_RE_STRAGGLER must be '<process>:<delay_s>', "
            f"got {env!r}"
        )
    return int(proc), float(delay)


def maybe_straggle() -> float:
    """Apply the straggler injection on the named process; returns the
    seconds slept (0.0 on every other process / with the knob unset —
    the production fast path is one env read)."""
    spec = straggler_spec()
    if spec is None:
        return 0.0
    proc, delay = spec
    if delay <= 0.0:
        return 0.0
    import jax

    if jax.process_index() != proc:
        return 0.0
    time.sleep(delay)
    try:
        from photon_ml_tpu.obs.spans import emit_event

        emit_event(
            "fault_injected", op="straggler", src=proc, dst=proc,
            seq=0, tag="re_solve", delay_s=delay,
        )
    # lint: waive(except-swallow) telemetry guard: the straggler record must never take down the visit it delays
    except Exception:
        pass
    return delay
