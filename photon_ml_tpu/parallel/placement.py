"""Skew-aware shard placement for entity-sharded random-effect solves.

The random-effect phase is embarrassingly parallel over entities, so the
scale-out question is pure PLACEMENT: which process/chip owns which
entities (streamed path) or which whole buckets (in-memory path). The
naive rule — ``entity_id % P`` — balances entity COUNT, but under Zipf
traffic the head entities carry orders of magnitude more rows than the
tail, so one shard ends up solving (and receiving, every visit, through
the offset/score exchanges) a large multiple of the mean row load while
the others idle.

``plan_shard_placement`` balances by Σ per-entity rows instead: LPT
(longest-processing-time) greedy — heaviest placement unit first, each
onto the currently-lightest shard. Units may be GROUPS of items that
must land on one shard together: the same bookkeeping PR-5's
``plan_fusion_groups`` uses to fuse same-geometry bucket launches also
drives group-atomic placement, so the launch fusion keeps working per
shard (a fusion group split across shards could no longer concatenate
into one launch anywhere).

Everything here is deterministic pure-host arithmetic on inputs that are
identical on every process (globally-reduced row counts), so every
process computes the SAME plan with zero extra communication.

Knob: ``PHOTON_RE_SHARD`` (env > module global, strict int parse, read
at call time — the bench RETUNE idiom). 0 (default) keeps today's
modular owner rule and exchange schedule bit-for-bit; 1 enables
skew-aware placement and the overlapped exchange schedule in the
consumers that opt in (``game/streaming.py``, ``game/random_effect.py``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Sequence

import numpy as np

# Entity-sharded random-effect solves (placement + overlapped exchange).
# 0 = the pre-sharding schedule bit-for-bit (modular owners, blocking
# exchanges); 1 = skew-aware placement + overlapped P2P exchange.
RE_SHARD = 0

# Sub-bucket placement atoms (PHOTON_RE_SPLIT): 0 (default) keeps the
# bucket-atomic placement bit-for-bit — a whole capacity class is one
# placement unit, so the Zipf tail class pins its owner's combine
# segment and solve load at O(E) no matter how good LPT is. A positive
# value N is the target ATOM COUNT of the split rule
# (``game.data.placement_atoms``): any capacity class whose total row
# weight exceeds total_rows / N is split into contiguous sub-bucket
# atoms of at most that weight (each >= 2 entities — the batched-XLA
# lane floor), so the max-owner load is bounded by
# total/P + max-atom-weight instead of the heaviest class. The rule
# reads ONLY the global bincount and the knob — never the process
# count — so every process (and the single-process reference) derives
# the identical sub-bucket ladder with zero extra communication. Like
# every fleet knob it must be set identically on all processes.
RE_SPLIT = 0

# Device-granularity placement (PHOTON_RE_DEVICE_SPLIT): 0 (default)
# keeps the single-unit-per-process schedule bit-for-bit — a process
# solves all of its owned buckets on its default device, exactly the
# PR-13/PR-15 dispatch. 1 adds a SECOND LPT level: each process's owned
# placement atoms are assigned to its LOCAL devices
# (``plan_device_placement``), the consumers stage each owned bucket on
# its assigned device, and same-device buckets keep fusing through the
# existing permutation bookkeeping (device placement is fusion-group /
# atom atomic, so launch geometry — and bits — are preserved vs the
# single-device schedule; devices execute their queued launches
# asynchronously, which is the intra-host win). Results re-enter the
# canonical matrix through a device-local combine (permutation-only row
# copies) BEFORE the process-level PHOTON_RE_COMBINE transport, which
# is unchanged. Like every fleet knob it must be set identically on all
# processes.
RE_DEVICE_SPLIT = 0

# Placement weight axis (PHOTON_RE_SPLIT_WEIGHT): "rows" (default)
# balances Σ per-entity ROWS — the solve-compute axis, bit-for-bit the
# PR-13 rule. "bytes" balances per-lane WIRE bytes instead (each active
# entity contributes one coefficient/variance/diag segment row to the
# combine, so lane bytes are proportional to LANE COUNT, not row
# count), and the split rule caps BOTH axes so atoms stay bounded in
# compute too. The r09 capture shows why the axis exists: row-balanced
# placement reached 1.04x row balance while the MAX owner's combine
# bytes ran ~2x the mean (the Zipf tail's many tiny entities all carry
# the same per-lane segment cost no matter how few rows they have).
RE_SPLIT_WEIGHT = "rows"

# Telemetry-driven re-planning (PHOTON_RE_REPLAN_IMBALANCE): when the
# MEASURED per-process random-effect solve wall of a descent iteration
# is more imbalanced than this max/mean ratio, the streamed trainer
# re-runs the LPT planner over measured (wall-calibrated) entity costs
# and migrates entities at the next visit boundary — the PR-11
# peer-loss re-plan machinery driven by a telemetry trigger instead of
# a PeerLost. 0 (default) = off; meaningful values are > 1 (e.g. 1.5 =
# re-plan when the slowest shard runs 50% over the mean).
REPLAN_IMBALANCE = 0.0


def re_shard_enabled() -> bool:
    """``PHOTON_RE_SHARD`` (env > module global), strict parse like the
    sibling RE knobs — a typo fails loudly instead of silently benching
    the default schedule."""
    env = os.environ.get("PHOTON_RE_SHARD")
    if env is not None and env != "":
        return int(env) != 0
    return int(RE_SHARD) != 0


def re_split_factor() -> int:
    """``PHOTON_RE_SPLIT`` (env > module global), strict int parse like
    the sibling RE knobs — a typo fails loudly instead of silently
    benching bucket-atomic placement. <= 0 disables (the knob
    convention); a positive value is the split rule's target atom
    count (the per-atom weight cap is total_rows / value)."""
    env = os.environ.get("PHOTON_RE_SPLIT")
    raw = env if (env is not None and env != "") else RE_SPLIT
    return max(int(raw), 0)


def re_device_split_enabled() -> bool:
    """``PHOTON_RE_DEVICE_SPLIT`` (env > module global), strict parse
    like the sibling RE knobs — a typo fails loudly instead of silently
    benching the single-device-per-process schedule."""
    env = os.environ.get("PHOTON_RE_DEVICE_SPLIT")
    if env is not None and env != "":
        return int(env) != 0
    return int(RE_DEVICE_SPLIT) != 0


_SPLIT_WEIGHT_MODES = ("rows", "bytes")


def re_split_weight() -> str:
    """``PHOTON_RE_SPLIT_WEIGHT`` (env > module global), strict
    membership parse — an unknown axis name fails loudly instead of
    silently benching the row-weighted rule. ``rows`` (default)
    balances solve compute; ``bytes`` balances combine wire bytes
    (per-lane segment rows), with the split rule capping both axes."""
    env = os.environ.get("PHOTON_RE_SPLIT_WEIGHT")
    raw = env if (env is not None and env != "") else RE_SPLIT_WEIGHT
    mode = str(raw)
    if mode not in _SPLIT_WEIGHT_MODES:
        raise ValueError(
            f"PHOTON_RE_SPLIT_WEIGHT must be one of "
            f"{_SPLIT_WEIGHT_MODES}, got {mode!r}"
        )
    return mode


def replan_imbalance_threshold() -> float:
    """``PHOTON_RE_REPLAN_IMBALANCE`` (env > module global), strict
    float parse; <= 0 disables (the knob convention). Must be set
    consistently fleet-wide — the re-plan decision is computed from
    allgathered walls on every process and a knob mismatch would
    desync the collectives."""
    env = os.environ.get("PHOTON_RE_REPLAN_IMBALANCE")
    raw = env if (env is not None and env != "") else REPLAN_IMBALANCE
    v = float(raw)
    return v if v > 0.0 else 0.0


@dataclass(frozen=True)
class PlacementPlan:
    """One placement decision: ``owner[i]`` is the shard of item ``i``
    (an entity, or a bucket, depending on the caller's granularity);
    ``loads[s]`` is shard ``s``'s Σ rows under the plan."""

    owner: np.ndarray  # (n_items,) int64
    loads: np.ndarray  # (num_shards,) float64
    num_shards: int

    @property
    def balance(self) -> float:
        """max shard load / mean shard load (1.0 = perfectly even).
        The skew metric the 1.15× acceptance bound is written against;
        0-load plans (no rows anywhere) read as perfectly balanced."""
        mean = float(self.loads.mean()) if len(self.loads) else 0.0
        if mean <= 0.0:
            return 1.0
        return float(self.loads.max()) / mean

    def owned_items(self, shard: int) -> np.ndarray:
        """Ascending item indices owned by ``shard``."""
        return np.flatnonzero(self.owner == shard)


def plan_shard_placement(
    row_counts: Sequence[float] | np.ndarray,
    num_shards: int,
    groups: Sequence[Sequence[int]] | None = None,
    skew_aware: bool = True,
) -> PlacementPlan:
    """Assign items to ``num_shards`` shards, balancing Σ ``row_counts``.

    ``groups`` lists index sets that must be CO-LOCATED (placement is
    group-atomic — e.g. PR-5 fusion groups, so same-geometry launch
    fusion keeps working inside each shard). Unlisted items place as
    singleton groups. ``skew_aware=True`` is LPT greedy: groups by total
    rows descending (ties: first item index ascending — deterministic),
    each onto the lightest shard so far (ties: lowest shard id).
    ``skew_aware=False`` is the naive baseline: round-robin by group
    order — the comparison arm the bench records.

    Deterministic: identical inputs produce the identical plan on every
    process (no RNG, no dict-order dependence).
    """
    counts = np.asarray(row_counts, np.float64)
    if counts.ndim != 1:
        raise ValueError(f"row_counts must be 1-D, got shape {counts.shape}")
    P = int(num_shards)
    if P < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    n = len(counts)
    if groups is None:
        group_list = [[i] for i in range(n)]
    else:
        group_list = [list(g) for g in groups]
        seen = np.zeros(n, bool)
        for g in group_list:
            for i in g:
                if not (0 <= i < n):
                    raise ValueError(f"group member {i} out of range [0, {n})")
                if seen[i]:
                    raise ValueError(f"item {i} appears in two groups")
                seen[i] = True
        # items not named by any group place as singletons, after the
        # explicit groups (stable: ascending index)
        group_list += [[i] for i in np.flatnonzero(~seen)]
    owner = np.zeros(n, np.int64)
    loads = np.zeros(P, np.float64)
    if P == 1 or n == 0:
        return PlacementPlan(owner=owner, loads=_add_loads(loads, counts, owner), num_shards=P)
    totals = [float(counts[g].sum()) for g in group_list]
    if skew_aware:
        # LPT: heaviest group first onto the lightest shard, via a heap
        # keyed (load, shard id) — O(G log P) where a per-group argmin
        # would be O(G·P) Python work (G = entity count on the streamed
        # path). Ties break exactly like np.argmin did: equal loads go
        # to the lowest shard id; the sort key ties break toward the
        # earliest group (its first member's index).
        import heapq

        order = sorted(
            range(len(group_list)),
            key=lambda gi: (-totals[gi], group_list[gi][0] if group_list[gi] else -1),
        )
        heap = [(0.0, s) for s in range(P)]
        for gi in order:
            load, s = heapq.heappop(heap)
            load += totals[gi]
            loads[s] = load
            heapq.heappush(heap, (load, s))
            for i in group_list[gi]:
                owner[i] = s
    else:
        for gi, g in enumerate(group_list):
            s = gi % P
            loads[s] += totals[gi]
            for i in g:
                owner[i] = s
    return PlacementPlan(owner=owner, loads=loads, num_shards=P)


def _add_loads(loads: np.ndarray, counts: np.ndarray, owner: np.ndarray) -> np.ndarray:
    np.add.at(loads, owner, counts)
    return loads


def plan_from_owner(
    owner: np.ndarray,
    row_counts: Sequence[float] | np.ndarray,
    num_shards: int,
) -> PlacementPlan:
    """Reconstruct a ``PlacementPlan`` from an existing owner map + row
    counts (the load definition lives HERE, next to the planner — the
    re-planner and the forced-map shard rebuild both need the old/forced
    plan's loads and must agree with ``plan_shard_placement``'s).

    Validates shape and range instead of silently truncating: an owner
    map that disagrees in length with the row counts, or that names a
    shard outside ``[0, num_shards)``, is a desynced plan (the exact
    failure the deterministic-replication design exists to prevent) and
    must fail loudly with the offending value."""
    owner = np.asarray(owner, np.int64)
    counts = np.asarray(row_counts, np.float64)
    P = int(num_shards)
    if len(owner) != len(counts):
        raise ValueError(
            f"plan_from_owner: owner map length {len(owner)} != "
            f"row_counts length {len(counts)} — the map and the counts "
            "must describe the same items"
        )
    if len(owner) and (owner.min() < 0 or owner.max() >= P):
        bad = owner[(owner < 0) | (owner >= P)][0]
        raise ValueError(
            f"plan_from_owner: owner value {int(bad)} outside "
            f"[0, {P}) — the map names a shard this plan does not have"
        )
    loads = _add_loads(np.zeros(P, np.float64), counts, owner)
    return PlacementPlan(owner=owner, loads=loads, num_shards=P)


def plan_entity_placement(
    entity_row_counts: np.ndarray, num_shards: int, skew_aware: bool = True
) -> PlacementPlan:
    """Entity-granularity placement (the streamed trainer's unit): each
    entity is one atom — all of an entity's rows live at its owner, the
    invariant every per-visit exchange and the per-entity solves rely
    on."""
    return plan_shard_placement(
        entity_row_counts, num_shards, groups=None, skew_aware=skew_aware
    )


def replan_excluding(
    plan: PlacementPlan,
    lost_shards: Sequence[int],
    row_counts: Sequence[float] | np.ndarray,
    survivors: Sequence[int],
    groups: Sequence[Sequence[int]] | None = None,
    skew_aware: bool = True,
) -> tuple[PlacementPlan, np.ndarray]:
    """Re-plan around lost shards (the peer-loss recovery step): run the
    SAME deterministic LPT planner over ``len(survivors)`` shards and
    return ``(new_plan, migrated)`` where ``new_plan.owner`` is in
    SURVIVOR-RANK space (rank = position in the sorted survivor list —
    the degraded group's effective indices) and ``migrated`` flags the
    items whose owner changed between the old plan (original shard ids,
    mapped through the survivor ranks) and the new one.

    Like the original plan, this is pure host arithmetic on globally-
    identical inputs (the allreduced row counts every process already
    holds), so all survivors compute the IDENTICAL new plan with zero
    extra communication — the property that lets recovery re-shard
    without a coordinator.

    With an EMPTY lost set the survivor set may EXPAND past the old
    plan's shard range — the elastic-rejoin signature: re-plan over the
    current shards plus a returned one the degraded plan never had. A
    joining shard has no old items, so every item it receives counts as
    migrated — exactly the entities the re-planner moves back. With a
    non-empty lost set (a genuine degrade) survivors outside the old
    range remain a desynced plan and fail loudly, naming the value."""
    survivors = sorted(int(s) for s in survivors)
    lost = {int(s) for s in lost_shards}
    if set(survivors) & lost:
        raise ValueError(
            f"survivors {survivors} and lost shards {sorted(lost)} overlap"
        )
    if not survivors:
        raise ValueError("no surviving shards to re-plan onto")
    bound = None if not lost else int(plan.num_shards)
    out_of_range = [
        s for s in survivors
        if s < 0 or (bound is not None and s >= bound)
    ]
    if out_of_range:
        raise ValueError(
            f"replan_excluding: survivor {out_of_range[0]} outside the "
            f"old plan's shard range [0, {int(plan.num_shards)}) — the "
            "survivor list and the plan disagree about the topology "
            "(expansion past the range is legal only with an empty "
            "lost set, the rejoin signature)"
        )
    new_plan = plan_shard_placement(
        row_counts, len(survivors), groups=groups, skew_aware=skew_aware
    )
    # old owner (original shard id) -> survivor rank, lost -> -1; sized
    # for an EXPANDED survivor set too (rejoin: survivors the old plan
    # never had simply map no old items)
    rank_of = np.full(
        max(int(plan.num_shards), survivors[-1] + 1), -1, np.int64
    )
    for r, s in enumerate(survivors):
        rank_of[s] = r
    old_ranks = rank_of[plan.owner]
    migrated = old_ranks != new_plan.owner
    return new_plan, migrated


def plan_device_placement(
    row_counts: Sequence[float] | np.ndarray,
    owner: np.ndarray,
    shard: int,
    num_devices: int,
    groups: Sequence[Sequence[int]] | None = None,
    skew_aware: bool = True,
) -> tuple[np.ndarray, PlacementPlan]:
    """The SECOND placement level (``PHOTON_RE_DEVICE_SPLIT``): assign
    the items ``shard`` owns under the process-level ``owner`` map to
    its ``num_devices`` LOCAL devices, with the same deterministic LPT
    rule (and the same group-atomicity contract: ``groups`` lists index
    sets — fusion groups on an unsplit prep — that must stay on ONE
    device, so same-device launch fusion reproduces the single-device
    launch geometry exactly). Returns ``(device, plan)`` where
    ``device[i]`` is item ``i``'s local device ordinal for owned items
    and ``-1`` elsewhere, and ``plan`` is the device-space sub-plan
    (its ``balance`` is the ``re_shard.device_balance`` gauge).

    Group members must be wholly owned or wholly un-owned by ``shard``
    — the process-level plan is group-atomic too, so a straddling group
    is a desynced plan and fails loudly. Like the first level this is
    pure host arithmetic: recomputing it from a SURVIVOR topology's
    owner map (after an in-place degrade re-plan) needs no extra
    communication."""
    counts = np.asarray(row_counts, np.float64)
    owner = np.asarray(owner, np.int64)
    if len(owner) != len(counts):
        raise ValueError(
            f"plan_device_placement: owner map length {len(owner)} != "
            f"row_counts length {len(counts)}"
        )
    D = int(num_devices)
    if D < 1:
        raise ValueError(f"num_devices must be >= 1, got {num_devices}")
    owned = np.flatnonzero(owner == int(shard))
    device = np.full(len(owner), -1, np.int64)
    # owned item index -> dense position in the sub-problem
    pos = np.full(len(owner), -1, np.int64)
    pos[owned] = np.arange(len(owned))
    sub_groups = None
    if groups is not None:
        sub_groups = []
        for g in groups:
            g = list(g)
            mine = [i for i in g if owner[i] == int(shard)]
            if mine and len(mine) != len(g):
                raise ValueError(
                    "plan_device_placement: group "
                    f"{g} straddles the owner boundary of shard "
                    f"{int(shard)} — the process-level plan is "
                    "group-atomic, so a straddling group is a desynced "
                    "plan"
                )
            if mine:
                sub_groups.append([int(pos[i]) for i in mine])
    plan = plan_shard_placement(
        counts[owned], D, groups=sub_groups, skew_aware=skew_aware
    )
    device[owned] = plan.owner
    return device, plan


def record_device_placement_metrics(
    plan: PlacementPlan, prefix: str = "re_shard"
) -> None:
    """Publish the device-level sub-plan's gauges:
    ``re_shard.device_balance`` (max/mean over THIS process's device
    loads — the intra-host twin of ``re_shard.balance``),
    ``re_shard.devices``, and per-device loads
    ``re_shard.device_rows.<d>`` (the per-device rows ``report fleet``
    renders). Pure gauges, published per process like the level-1
    metrics."""
    from photon_ml_tpu.obs.metrics import REGISTRY

    REGISTRY.gauge_set(f"{prefix}.device_balance", plan.balance)
    REGISTRY.gauge_set(f"{prefix}.devices", float(plan.num_shards))
    for d in range(plan.num_shards):
        REGISTRY.gauge_set(
            f"{prefix}.device_rows.{d}", float(plan.loads[d])
        )


def measured_entity_costs(
    entity_row_counts: np.ndarray,
    entity_owner: np.ndarray,
    shard_walls: np.ndarray,
) -> np.ndarray:
    """Per-entity MEASURED costs for a telemetry-driven re-plan: each
    entity's row count scaled by its current owner's measured
    seconds-per-row (``wall_p / Σ rows owned by p``). Entities living
    on a shard that measured slow cost proportionally more, so the LPT
    re-plan spreads them off it — row counts alone would reproduce the
    plan that produced the imbalance. Shards with no rows (or a zero
    wall: clock resolution, or a shard that did no solve work) fall
    back to the mean measured rate, keeping their entities
    row-proportional instead of free (a zero cost would make LPT dump
    every such entity onto one shard).

    Deterministic pure-host arithmetic on globally-identical inputs
    (allreduced row counts, allgathered walls) — every process computes
    the IDENTICAL costs with zero extra communication, the same
    property the original plan and ``replan_excluding`` rely on."""
    counts = np.asarray(entity_row_counts, np.float64)
    owner = np.asarray(entity_owner, np.int64)
    walls = np.asarray(shard_walls, np.float64)
    P = len(walls)
    if len(owner) != len(counts):
        raise ValueError(
            f"measured_entity_costs: owner map length {len(owner)} != "
            f"row_counts length {len(counts)}"
        )
    loads = np.zeros(P, np.float64)
    np.add.at(loads, owner, counts)
    rate = np.zeros(P, np.float64)
    ok = (loads > 0) & (walls > 0)
    rate[ok] = walls[ok] / loads[ok]
    fallback = float(rate[ok].mean()) if ok.any() else 1.0
    rate[~ok] = fallback
    return counts * rate[owner]


def record_projection_metrics(
    lane_dims: Sequence[tuple[int, int]],
    full_dim: int,
    prefix: str = "re_project",
) -> None:
    """Publish the feature-projection payload gauges
    (``PHOTON_RE_PROJECT``): ``re_project.mean_ratio`` — the
    lane-weighted mean solved width over the full width
    (Σ lanes·d_e / Σ lanes·d, the fraction of every byte-denominated
    cost the projection keeps) — and ``re_project.dims_saved_bytes`` —
    the float32 coefficient-row bytes one full combine pass no longer
    ships (Σ lanes·(d − d_e)·4). ``lane_dims`` is one ``(lanes,
    solved_width)`` pair per bucket this process solves. Both consumers
    (in-memory prepare, streamed shard build) publish through HERE so
    the gauge definition can't drift; callers only publish when the
    projection is active, keeping the gauges ABSENT — and the gate tier
    silent — on unprojected runs."""
    from photon_ml_tpu.obs.metrics import REGISTRY

    lanes_total = float(sum(k for k, _ in lane_dims))
    full = lanes_total * float(full_dim)
    kept = float(sum(k * d for k, d in lane_dims))
    ratio = kept / full if full > 0 else 1.0
    REGISTRY.gauge_set(f"{prefix}.mean_ratio", ratio)
    REGISTRY.gauge_set(f"{prefix}.dims_saved_bytes", (full - kept) * 4.0)


def record_placement_metrics(
    plan: PlacementPlan,
    shard: int | None = None,
    prefix: str = "re_shard",
    atoms: int | None = None,
    split_classes: int | None = None,
) -> None:
    """Publish the plan's load gauges through the PR-4 registry:
    ``re_shard.rows`` (THIS shard's Σ rows when ``shard`` is given, else
    the max — the number that bounds the critical path either way),
    ``re_shard.rows_max`` / ``rows_mean``, ``re_shard.balance``
    (max/mean), ``re_shard.shards``, and the placement-granularity
    gauges ``re_shard.atoms`` (how many independently-placeable units
    the plan distributed — defaults to the item count when the caller
    does not group) / ``re_shard.split_classes`` (how many capacity
    classes the ``PHOTON_RE_SPLIT`` rule split; 0 on an unsplit run).
    Pure gauges — safe to call from every process (each publishes its
    own view; only process 0's sink writes)."""
    from photon_ml_tpu.obs.metrics import REGISTRY

    loads = plan.loads
    rows_max = float(loads.max()) if len(loads) else 0.0
    rows_mean = float(loads.mean()) if len(loads) else 0.0
    own = rows_max if shard is None else float(loads[int(shard)])
    REGISTRY.gauge_set(f"{prefix}.rows", own)
    REGISTRY.gauge_set(f"{prefix}.rows_max", rows_max)
    REGISTRY.gauge_set(f"{prefix}.rows_mean", rows_mean)
    REGISTRY.gauge_set(f"{prefix}.balance", plan.balance)
    REGISTRY.gauge_set(f"{prefix}.shards", float(plan.num_shards))
    REGISTRY.gauge_set(
        f"{prefix}.atoms",
        float(len(plan.owner) if atoms is None else atoms),
    )
    REGISTRY.gauge_set(
        f"{prefix}.split_classes", float(split_classes or 0)
    )
