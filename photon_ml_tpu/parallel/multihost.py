"""Multi-host runtime scaffolding.

Reference parity: the reference scales out on a Spark cluster — a driver
plus executors on many hosts, with the cluster manager handling membership
and the shuffle service moving data (SURVEY.md §2.6 Spark-replacement
table). The TPU-native replacement is ``jax.distributed``: every host runs
the SAME program, ``jax.distributed.initialize`` wires the processes into
one runtime, ``jax.devices()`` becomes the GLOBAL device list, and a mesh
built over it spans the whole slice — XLA then routes collectives over
ICI within a host/pod and DCN across pods. No driver, no shuffle: each
host reads its own slice of the input (``host_shard_of_paths``) and
assembles its rows into a globally-sharded array
(``global_batch_from_host_shards``).

Usage (same command on every host, e.g. under GKE/xmanager):

    python -m photon_ml_tpu.cli.train ... --multihost

with the coordinator address/process count/process id taken from the
standard env vars (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
``JAX_PROCESS_ID``) or auto-detected on TPU pods (GCE metadata).
"""

from __future__ import annotations

import os
import time
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Join this process into the multi-host runtime.

    Arguments default to the standard env vars / TPU-pod auto-detection
    (``jax.distributed.initialize`` semantics). Returns a summary dict
    (process index/count, local/global device counts) for logging. Safe to
    call on a single host only when explicit arguments or env vars are set;
    plain single-host runs should simply not call this.
    """
    # resolve the standard env vars ourselves — jax.distributed auto-detects
    # only inside known cluster environments (TPU pods, SLURM, …)
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    # PHOTON_COORD_MAX_MISSING_HEARTBEATS (strict int parse, default =
    # jax's own): how many 10 s heartbeats the coordination service /
    # client tolerate missing before declaring a task dead and FATALing
    # every member. An elastic fleet (PHOTON_DESCENT_DEGRADE /
    # PHOTON_REJOIN) raises it so the repo's own roll-call tier — not
    # the jax coordination service, which cannot degrade in place — is
    # what decides who is dead.
    hb = os.environ.get("PHOTON_COORD_MAX_MISSING_HEARTBEATS")
    if hb is not None and hb != "":
        hb = int(hb)  # strict parse OUTSIDE the init-error rewrap: a
        # typo'd knob must name itself, not masquerade as a cluster
        # configuration problem
    else:
        hb = None
    try:
        if hb is not None:
            # the public initialize() wrapper does not forward the
            # heartbeat options — go through the same State the wrapper
            # drives, with the same must-precede-backends check
            from jax._src import distributed as _jax_distributed
            from jax._src import xla_bridge as _xla_bridge

            if _xla_bridge.backends_are_initialized():
                raise RuntimeError(
                    "initialize_multihost must be called before any JAX "
                    "computations are executed"
                )
            _jax_distributed.global_state.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                service_max_missing_heartbeats=int(hb),
                client_max_missing_heartbeats=int(hb),
            )
        else:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
    except (ValueError, RuntimeError) as e:
        raise RuntimeError(
            "multihost initialization failed — on non-auto-detected "
            "clusters set JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES and "
            "JAX_PROCESS_ID (or pass them explicitly); on a single host, "
            f"drop --multihost. Underlying error: {e}"
        ) from e
    return runtime_summary()


def runtime_summary() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def host_shard_of_paths(paths: Sequence[str]) -> list[str]:
    """The input files THIS host reads: a round-robin slice of the sorted
    path list by process index (the reference's executor partition
    assignment, without a shuffle service). Every path must be visible to
    every host (shared filesystem / object store), but each is read once
    globally."""
    ordered = sorted(paths)
    return ordered[jax.process_index() :: jax.process_count()]


def global_batch_from_host_shards(local_arrays, mesh: Mesh, axis_name: str = "data"):
    """Assemble per-host row blocks into ONE globally row-sharded pytree.

    Each process passes its own ``local_arrays`` (a pytree of host numpy
    arrays with identical structure and per-host row counts that sum to the
    global batch); ``jax.make_array_from_process_local_data`` builds global
    arrays whose addressable shards hold this host's rows — no host ever
    materializes the global batch (SURVEY.md §7: the 1B-row path).
    """
    sharding = NamedSharding(mesh, P(axis_name))

    def to_global(a):
        a = np.asarray(a)
        return jax.make_array_from_process_local_data(sharding, a)

    return jax.tree.map(to_global, local_arrays)


def shard_batch_multihost(local_batch, mesh: Mesh, axis_name: str = "data"):
    """Multi-host twin of ``parallel.distributed.shard_batch``: every host
    contributes ITS OWN rows (from its slice of the input files) and the
    result is one globally row-sharded ``Batch`` — no host ever holds the
    global data.

    Hosts may have unequal row counts; each pads with zero-weight rows
    (inert in the objective) to the global per-host maximum, rounded up so
    the global row count divides the mesh's data axis.
    """
    from jax.experimental import multihost_utils

    from photon_ml_tpu.ops.batch import pad_batch

    n_local = local_batch.num_rows
    counts = multihost_utils.process_allgather(np.asarray([n_local]))
    per_host = int(np.max(counts))
    devs_per_host = max(len(jax.local_devices()), 1)
    per_host = -(-per_host // devs_per_host) * devs_per_host
    local = pad_batch(local_batch, per_host)
    return global_batch_from_host_shards(
        jax.tree.map(np.asarray, local), mesh, axis_name
    )


def is_output_process() -> bool:
    """True on the single process that writes shared outputs (models,
    metrics, checkpoints). All hosts COMPUTE; exactly one host WRITES —
    concurrent writers to shared storage interleave and corrupt files.
    In a degraded group the lowest-ranked SURVIVOR writes (the original
    writer may be the lost peer)."""
    return effective_process_index() == 0


# per-call monotonic barrier suffix: every process calls sync_processes
# at the same program points in the same order, so the counters agree —
# and two overlapping barriers carrying the SAME caller tag (possible
# once the pipelined exchange schedule defers work past a barrier site)
# can no longer alias each other inside the runtime's key-matched
# barrier bookkeeping.
_BARRIER_SEQ = [0]


def sync_processes(tag: str = "photon-ml-barrier") -> None:
    """Barrier across all processes (e.g. before reading files another
    process wrote). No-op on a single process. The wire tag is
    ``{tag}#{n}`` with ``n`` a per-process monotonic call counter
    (identical across processes by the matched-call-order requirement
    every collective already has), so repeated barriers under one caller
    tag are distinct barrier keys. In a degraded group the barrier
    rides the framed-P2P survivor mesh (same tag discipline) — the jax
    barrier would wait on the dead peer forever."""
    if effective_process_count() <= 1:
        return
    _BARRIER_SEQ[0] += 1
    if _DEGRADED is not None:
        _p2p_allgather_obj(f"{tag}#{_BARRIER_SEQ[0]}", tag="barrier")
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(f"{tag}#{_BARRIER_SEQ[0]}")


def broadcast_from_host0(pytree):
    """Every process receives process 0's value of ``pytree`` (host numpy
    leaves; identity on a single process). The pytree STRUCTURE must be
    identical on every process — only leaf values may differ. Used to make
    checkpoint-resume decisions (and restored state) consistent when hosts
    do not share an output filesystem. In a degraded group "host 0" is
    the lowest-ranked SURVIVOR and the broadcast rides the framed-P2P
    survivor mesh."""
    if effective_process_count() <= 1:
        return pytree
    if _DEGRADED is not None:
        rank = effective_process_index()
        views = _p2p_allgather_obj(
            pytree if rank == 0 else None, tag="broadcast0"
        )
        return jax.tree.map(np.asarray, views[0])
    from jax.experimental import multihost_utils

    out = multihost_utils.broadcast_one_to_all(pytree)
    return jax.tree.map(np.asarray, out)


def allgather_row_chunks(arrays, chunk_rows: int, pad_values=None):
    """Chunk-wise all-to-all of per-host row blocks (the TPU-native stand-in
    for the reference's Spark shuffle, done on HOSTS over DCN).

    ``arrays`` is a dict of same-leading-dim host numpy arrays (this host's
    rows). Yields one round at a time: a dict of ``(P, chunk_rows, ...)``
    stacked arrays holding EVERY process's chunk — the receiver filters the
    rows it owns and frees the round before the next, so peak memory is
    O(P · chunk_rows), never O(global rows). Hosts with fewer rows pad
    trailing rounds (``pad_values[k]``, default 0 — pick a sentinel the
    receiver can filter, e.g. -1 entity ids). Every process yields the SAME
    number of rounds (a collective requirement).
    """
    pad_values = dict(pad_values or {})
    keys = list(arrays)
    n_loc = len(arrays[keys[0]]) if keys else 0
    counts = allgather_host(np.asarray([n_loc])).reshape(-1)
    rounds = int(-(-int(counts.max()) // chunk_rows)) if counts.max() else 0
    for r in range(rounds):
        lo = r * chunk_rows
        hi = min(lo + chunk_rows, n_loc)
        chunk = {}
        for k in keys:
            a = np.asarray(arrays[k])
            part = a[lo:hi] if lo < n_loc else a[:0]
            pad = chunk_rows - len(part)
            if pad:
                fill = np.full(
                    (pad,) + a.shape[1:], pad_values.get(k, 0), a.dtype
                )
                part = np.concatenate([part, fill])
            chunk[k] = part
        if _DEGRADED is not None:
            views = _p2p_allgather_obj(chunk, tag="row_chunks")
            yield {
                k: np.stack([v[k] for v in views]) for k in keys
            }
            continue
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(chunk)
        yield {k: np.asarray(v) for k, v in gathered.items()}


# host-collective payload wire formats: a 1-byte kind prefix selects
# how the rest decodes. PICKLE is the original format (arbitrary host
# objects); NDARRAY is the fast path for array-bearing payloads — the
# container skeleton (dicts/lists/tuples with array leaves replaced by
# position markers) plus per-array (dtype, shape) specs pickle small,
# and the array bytes ride RAW after them, so the send side never
# pickles (or copies) a row payload and the recv side reconstructs with
# one ``np.frombuffer`` per array. Values round-trip byte-identically
# (asserted in tests/test_re_combine.py); only the wire encoding
# differs, and both ends of a mesh always run the same build.
_PAYLOAD_PICKLE = 0
_PAYLOAD_NDARRAY = 1


class _NdRef:
    """Skeleton placeholder for the i-th raw array of an NDARRAY-format
    payload (module-level so the pickled skeleton resolves it)."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i

    def __reduce__(self):
        return (_NdRef, (self.i,))


def _encode_host_payload(obj) -> tuple[list, int]:
    """``(wire_parts, total_bytes)`` for one host-collective payload.
    ``wire_parts`` is a list of buffers (bytes / byte-cast memoryviews)
    the sender streams in order — array payloads are zero-copy views of
    the (contiguous) source arrays. Only plain ndarrays of simple
    dtypes take the raw path; object/structured dtypes and ndarray
    subclasses stay pickled (in the skeleton, or — when no raw-able
    array exists at all — as a wholesale PICKLE-format payload)."""
    import pickle
    import struct

    arrays: list[np.ndarray] = []
    shapes: list[tuple] = []

    def strip(x):
        # raw fast path ONLY for plain ndarrays of simple dtypes:
        # subclasses (MaskedArray carries a mask) and structured dtypes
        # (dtype.str is lossy — '|V12' drops the fields) must keep the
        # pickle round-trip the skeleton gives them
        if (
            type(x) is np.ndarray
            and not x.dtype.hasobject
            and x.dtype.names is None
        ):
            # record the ORIGINAL shape: ascontiguousarray promotes 0-d
            # to 1-d, and the decode reshape must undo that
            arrays.append(np.ascontiguousarray(x))
            shapes.append(x.shape)
            return _NdRef(len(arrays) - 1)
        if isinstance(x, dict):
            return {k: strip(v) for k, v in x.items()}
        if isinstance(x, tuple):
            vals = [strip(v) for v in x]
            # preserve tuple subclasses (namedtuples) — the pickle
            # format round-trips them, so this format must too
            return type(x)(*vals) if hasattr(x, "_fields") else tuple(vals)
        if isinstance(x, list):
            return [strip(v) for v in x]
        return x

    skeleton = strip(obj)
    if not arrays:
        raw = bytes([_PAYLOAD_PICKLE]) + pickle.dumps(obj, protocol=4)
        return [raw], len(raw)
    specs = [
        (a.dtype.str, shape) for a, shape in zip(arrays, shapes)
    ]
    head = pickle.dumps((skeleton, specs), protocol=4)
    parts: list = [
        bytes([_PAYLOAD_NDARRAY]) + struct.pack("!q", len(head)) + head
    ]
    total = len(parts[0])
    for a in arrays:
        if a.size == 0:
            continue  # zero-size arrays have no bytes (and memoryview
            # cannot cast shapes with zeros); the spec alone rebuilds them
        m = memoryview(a).cast("B")
        parts.append(m)
        total += len(m)
    return parts, total


def _decode_host_payload(raw: bytes):
    """Inverse of ``_encode_host_payload`` over the received frame
    bytes. Arrays come back as fresh WRITABLE copies — the contract the
    pickle format always gave callers (several mutate results in
    place), and the one copy here replaces the decode copy pickle paid
    anyway."""
    import pickle
    import struct

    kind = raw[0]
    body = memoryview(raw)[1:]
    if kind == _PAYLOAD_PICKLE:
        return pickle.loads(body)
    if kind != _PAYLOAD_NDARRAY:
        raise RuntimeError(
            f"host collective payload: unknown wire format {kind}"
        )
    head_len = struct.unpack("!q", body[:8])[0]
    skeleton, specs = pickle.loads(body[8:8 + head_len])
    offset = 1 + 8 + head_len
    arrays = []
    for dt, shape in specs:
        dtype = np.dtype(dt)
        count = int(np.prod(shape, dtype=np.int64))
        a = np.frombuffer(
            raw, dtype, count=count, offset=offset
        ).reshape(shape).copy()
        offset += count * dtype.itemsize
        arrays.append(a)

    def restore(x):
        if isinstance(x, _NdRef):
            return arrays[x.i]
        if isinstance(x, dict):
            return {k: restore(v) for k, v in x.items()}
        if isinstance(x, tuple):
            vals = [restore(v) for v in x]
            return type(x)(*vals) if hasattr(x, "_fields") else tuple(vals)
        if isinstance(x, list):
            return [restore(v) for v in x]
        return x

    return restore(skeleton)


def _send_frame_parts(sock, parts: list, total: int, crc: bool,
                      peer: int | None = None, tag: str | None = None,
                      heartbeat: float | None = None,
                      corrupt_wire: bool = False) -> None:
    """``_send_frame`` for a multi-buffer payload: one length prefix
    covering the whole frame, each part streamed without concatenation
    (the array fast path's zero-copy send), and — frame protocol v1 —
    one CRC32 trailer computed incrementally over the parts (identical
    to the single-buffer trailer over their concatenation).

    ``corrupt_wire`` (fault injection only) flips a byte of the FIRST
    part on the wire AFTER the trailer is computed — the same
    post-CRC discipline as ``_send_frame``'s."""
    import struct

    wire = parts
    if corrupt_wire and parts:
        from photon_ml_tpu.parallel import faults

        wire = [faults._corrupt(bytes(parts[0])), *parts[1:]]
    _sendall_hb(sock, struct.pack("!q", total), peer, tag, heartbeat)
    for p in wire:
        _sendall_hb(sock, p, peer, tag, heartbeat)
    if crc:
        import zlib

        c = 0
        for p in parts:
            c = zlib.crc32(p, c)
        _sendall_hb(
            sock, struct.pack("!I", c & 0xFFFFFFFF), peer, tag, heartbeat
        )


def _ring_allgather(
    links: dict, ordered_pids: list[int], rank: int, obj,
    tag: str, heartbeat: float | None, stats: dict | None = None,
) -> list:
    """One framed allgather of a host object over an explicit ring:
    ``ordered_pids[rank]`` is this process, links are keyed by ORIGINAL
    pid. The single implementation behind the degraded-group
    collectives, the roll-call agreement round AND the owner-segment
    combine (hand-rolled copies of threaded socket code WILL drift).
    Array-bearing payloads ride the raw-ndarray wire format (no pickle
    copy/overhead per array). Bumps the per-link frame-set counters
    like every framed user, so submission-order correlation stays
    matched. ``stats`` (optional) receives the byte accounting:
    ``payload_bytes`` (this rank's encoded payload), ``bytes_sent``
    (= payload × (P−1), the rotation schedule's send traffic) and
    ``bytes_recv``. Returns the per-rank list."""
    import struct
    import threading

    from photon_ml_tpu.parallel import faults

    protos = links.get("proto", {})
    parts, total = _encode_host_payload(obj)
    P_ = len(ordered_pids)
    own_pid = ordered_pids[rank]
    plan = faults.active_plan()
    out: dict[int, object] = {rank: obj}
    err: list[BaseException] = []

    def send_all():
        try:
            for r in range(1, P_):
                peer_pid = ordered_pids[(rank + r) % P_]
                seq = _next_link_seq("send", peer_pid)
                wire_parts, corrupt_wire = parts, False
                if plan is not None:
                    # the ring collectives are framed users like the
                    # row exchange — the deterministic fault plan can
                    # name their frame sets too (the in-memory combine
                    # is exactly where the descent-degrade drill kills)
                    spec = plan.pop_send_fault(own_pid, peer_pid, seq, tag)
                    if spec is not None:
                        wire_parts, corrupt_wire = faults.apply_send_fault(
                            spec, parts, links["send"][peer_pid]
                        )
                if wire_parts is None:
                    continue  # the frame set was dropped
                _send_frame_parts(
                    links["send"][peer_pid], wire_parts, total,
                    protos.get(peer_pid, 0) >= _FRAME_PROTO_CRC,
                    peer_pid, tag, heartbeat,
                    corrupt_wire=corrupt_wire,
                )
        except BaseException as e:
            e.peer = getattr(e, "peer", peer_pid)
            err.append(e)

    t = threading.Thread(target=send_all)
    t.start()
    bytes_recv = 0
    for r in range(1, P_):
        src_rank = (rank - r) % P_
        src_pid = ordered_pids[src_rank]
        sock = links["recv"][src_pid]
        _next_link_seq("recv", src_pid)
        try:
            n = struct.unpack(
                "!q", _recv_exact(sock, 8, src_pid, tag, heartbeat)
            )[0]
            raw = _recv_frame_payload(
                sock, n, protos.get(src_pid, 0) >= _FRAME_PROTO_CRC,
                src_pid, tag, heartbeat,
            )
        except BaseException as e:
            # name the silent link: the suspected-loss hardening (and
            # the roll call it triggers) wants a peer to start from
            e.peer = getattr(e, "peer", src_pid)
            raise
        bytes_recv += n
        out[src_rank] = _decode_host_payload(raw)
    t.join()
    if err:
        raise err[0]
    if stats is not None:
        stats.update(
            payload_bytes=total,
            bytes_sent=total * (P_ - 1),
            bytes_recv=bytes_recv,
        )
    return [out[r] for r in range(P_)]


def _p2p_allgather_obj(obj, tag: str = "host_collective",
                       drain: bool = True, stats: dict | None = None) -> list:
    """Allgather one host object over the framed-P2P links of the
    CURRENT group — the degraded world's replacement for
    ``multihost_utils.process_allgather`` (which would hang on the dead
    peer), and the transport behind the owner-segment collectives on a
    HEALTHY mesh too. Returns the per-rank list in ascending effective
    rank; a sync collective drains the async queue first, like every
    other synchronous socket user (``drain=False`` is for the exchange
    WORKER itself, which is the queue — draining there would wait on
    its own future).

    A transient link fault here hardens straight into ``PeerLost``
    (peer ``-1`` when the failing link is unknown) — in a DEGRADED
    group always, and on a healthy mesh whenever the reliable mode is
    armed (``PHOTON_P2P_RETRIES`` > 0): these collectives have no
    completion ACK, so a mid-collective retry could desync peers — but
    the failure is symmetric (the teardown kills every peer's links,
    so every peer's collective fails too), and the right recovery is a
    roll call from the caller's handler (the streamed fit, the
    in-place-degrading descent), not an abort. With retries unset the
    healthy-mesh error propagates raw — the pre-elastic behavior
    byte-for-byte."""
    P_ = effective_process_count()
    pid = effective_process_index()
    if P_ <= 1:
        if stats is not None:
            stats.update(payload_bytes=0, bytes_sent=0, bytes_recv=0)
        return [obj]
    if drain:
        drain_async_exchanges()
    try:
        links = _host_links()
        heartbeat = _p2p_heartbeat_s() if _sink_active() else None
        return _ring_allgather(
            links, [_orig_pid(r) for r in range(P_)], pid, obj,
            tag, heartbeat, stats=stats,
        )
    except BaseException as e:
        _reset_host_links()
        if isinstance(e, OSError):
            if _DEGRADED is not None:
                raise PeerLost(
                    getattr(e, "peer", -1),
                    f"degraded-group host collective {tag!r} failed: {e}",
                ) from e
            if _p2p_retries() > 0:
                # suspected loss, not a verdict: the roll call in the
                # caller's recovery path decides whether the peer is
                # really gone (nobody lost -> the handler retries or
                # aborts with the flapped-links message)
                raise PeerLost(
                    getattr(e, "peer", -1),
                    f"host collective {tag!r} failed on the full "
                    f"mesh: {e}",
                ) from e
        raise


def allgather_obj_p2p(obj, tag: str = "host_collective",
                      stats: dict | None = None) -> list:
    """Public synchronous framed-P2P allgather of one host object over
    the current group (healthy or degraded mesh): the owner-segment
    collective the random-effect combine and the diagnostics gather
    ride. Identity on a single process. Must be called collectively (at
    the same program point on every process of the group)."""
    return _p2p_allgather_obj(obj, tag=tag, stats=stats)


def allgather_host(array: np.ndarray) -> np.ndarray:
    """Stack one same-shape host array from every process of the
    CURRENT group: a ``(P_eff, ...)`` array. The jax collective
    normally; the framed-P2P survivor mesh when degraded (the jax
    runtime still counts the dead peer and would hang). Every
    group-shaped reduction in the trainer routes through here so a
    degraded group keeps training."""
    array = np.asarray(array)
    if effective_process_count() <= 1:
        return array[None]
    if _DEGRADED is None:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(array))
    return np.stack(_p2p_allgather_obj(array, tag="allgather_host"))


def _fragment_may_proceed(survivors, group) -> bool:
    """The roll call's split-brain quorum, as a pure predicate (the
    drills in tests/test_faults.py enumerate partitions against it): a
    fragment survives iff it holds a STRICT majority of the group's
    MEMBERS, or exactly half of them including the group's writer (its
    lowest member). Membership counts the CURRENT group only — an
    invited rejoiner in the agreed set is not yet a member, and letting
    it pad a fragment's count would let two fragments (one holding the
    rejoiner, one holding a member majority) both pass. At most one
    fragment of any partition satisfies the predicate."""
    group = sorted(group)
    writer = group[0]
    members = [s for s in survivors if s in group]
    if 2 * len(members) > len(group):
        return True
    return 2 * len(members) == len(group) and writer in members


def roll_call(
    window_s: float | None = None,
    candidates: Sequence[int] | None = None,
    guard_group: Sequence[int] | None = None,
) -> list[int]:
    """Survivor census after a suspected peer loss (the barrier-tagged
    roll call of the recovery tier). Every process that hit
    ``PeerLost`` on the same exchange calls this at the same program
    point (the reliable mode's completion ACK guarantees the failure —
    and therefore the retry exhaustion — is observed by EVERY
    survivor): each rebuilds a mesh over the current group from the
    cached addresses, dropping peers that stay unreachable past the
    window (knob ``PHOTON_ROLLCALL_WINDOW_S``, default 10 s), then
    survivors exchange their reachable sets over the candidate mesh
    and agree on the INTERSECTION — a peer any survivor cannot reach
    is lost for everyone (a half-connected peer cannot participate in
    a full exchange mesh anyway). Returns the sorted surviving
    ORIGINAL process indices (always including this process).

    ``candidates`` widens the census beyond the current group — the
    elastic-rejoin roll call names the current survivors PLUS the
    invited rejoiners, so one roll call can admit a returning process
    and drop a freshly-dead one in the same round. ``guard_group`` is
    the membership set the split-brain quorum is judged against (the
    CURRENT group — a rejoiner is not a member until admitted); it
    defaults to the current group."""
    if window_s is None:
        env = os.environ.get("PHOTON_ROLLCALL_WINDOW_S")
        window_s = float(env) if env else 10.0
    global _HOST_LINKS
    with _LINKS_BUILD_LOCK:
        _reset_host_links()
        pid = _self_pid()
        if guard_group is not None:
            group = sorted(int(p) for p in guard_group)
        elif _DEGRADED is not None:
            group = list(_DEGRADED["survivors"])
        else:
            group = list(range(_world_size()))
        candidates = (
            list(group) if candidates is None
            else sorted(int(p) for p in candidates)
        )
        deadline = time.monotonic() + window_s
        # survivors enter a roll call at times spread across their
        # peers' retry budgets, and each unreachable-candidate removal
        # needs one more agreement pass over the reduced set — so the
        # loop keeps probing past the per-candidate patience window, up
        # to a give-up that extends with every removal, before this
        # process declares itself isolated
        give_up = deadline + window_s
        probe_timeout = max(min(2.0, window_s / 4.0), 0.2)
        survivors = None
        while len(candidates) > 1:
            try:
                links = _build_host_links(candidates, probe_timeout)
            except PeerUnreachable as e:
                if time.monotonic() >= deadline:
                    candidates.remove(e.peer)
                    # the reduced set gets a fresh patience window: its
                    # members may still be probing the removed peer in
                    # their own (later-entered) roll calls
                    deadline = time.monotonic() + window_s
                    give_up = max(give_up, deadline + window_s)
                else:
                    time.sleep(probe_timeout / 2.0)
                continue
            except (OSError, RuntimeError):
                # a build race (two peers mid-rebuild) — retry until
                # the give-up, then give up on the stragglers
                if time.monotonic() >= give_up:
                    break
                time.sleep(probe_timeout / 2.0)
                continue
            _HOST_LINKS = links
            # barrier-tagged agreement round: intersect everyone's view
            try:
                rank = candidates.index(pid)
                views = _ring_allgather(
                    links, candidates, rank, list(candidates),
                    "rollcall", None,
                )
            except OSError:
                # the agreement raced a peer whose OWN build attempt
                # failed after ours succeeded (it tears down the
                # freshly-accepted sockets): rebuild and re-agree
                _reset_host_links()
                if time.monotonic() >= give_up:
                    break
                time.sleep(probe_timeout / 2.0)
                continue
            agreed = set(candidates)
            for v in views:
                agreed &= set(v)
            if pid not in agreed:
                _reset_host_links()
                raise RuntimeError(
                    f"roll call excluded this process ({pid}): survivors "
                    f"agreed on {sorted(agreed)}"
                )
            if agreed != set(candidates):
                # some survivor could not reach a candidate this process
                # could: drop to the intersection and rebuild over it
                # (the excluded peer's own roll call ends with it alone)
                _reset_host_links()
                candidates = sorted(agreed)
                if len(candidates) > 1:
                    _HOST_LINKS = _build_host_links(
                        candidates, _p2p_timeout_s()
                    )
            survivors = sorted(candidates)
            break
        if survivors is None:
            _reset_host_links()
            survivors = [pid]
        # split-brain guard: a roll call has no external arbiter, so a
        # network PARTITION (not a death) would let both halves "agree"
        # on themselves — and both halves' rank-0 would pass
        # is_output_process() and write checkpoints concurrently, the
        # corruption the single-writer rule exists to prevent. A
        # fragment may proceed iff it holds a STRICT majority of the
        # group, or exactly half of it INCLUDING the group's current
        # writer (its lowest member). At most one fragment can satisfy
        # either condition: a strict majority is unique, the writer
        # lives in one fragment, and a strict majority plus an exact
        # half cannot coexist. (The earlier rule let ANY fragment
        # holding the writer proceed — a 1-of-4 writer fragment and the
        # 3-of-4 majority fragment would then BOTH survive a partition,
        # exactly the double-writer scenario the guard exists for; the
        # split-brain drill in tests/test_faults.py pins the fix.)
        if not _fragment_may_proceed(survivors, group):
            _reset_host_links()
            _emit_event(
                "roll_call_abort", survivors=survivors,
                group=list(group),
            )
            raise RuntimeError(
                f"roll call reached only {survivors} of {sorted(group)}: "
                f"a fragment without a strict member majority (or exactly "
                f"half the group including the writer, process "
                f"{min(group)}) must abort rather than risk a split-brain "
                "second writer — restart this process and rejoin"
            )
        _emit_event(
            "roll_call", survivors=survivors,
            lost=[p for p in group if p not in survivors],
        )
        return survivors


def allreduce_sum_host(*arrays: np.ndarray):
    """Sum numpy arrays across ALL processes of the current group
    (returns them unchanged on a single process). Used by the streaming
    objective to combine per-host partial (value, gradient) sums — the
    treeAggregate analog for the out-of-core path."""
    if effective_process_count() <= 1:
        return arrays if len(arrays) > 1 else arrays[0]
    if _DEGRADED is not None:
        gathered = _p2p_allgather_obj(
            tuple(np.asarray(a) for a in arrays), tag="allreduce_sum"
        )
        summed = tuple(
            np.sum(np.stack([g[i] for g in gathered]), axis=0)
            for i in range(len(arrays))
        )
        return summed if len(summed) > 1 else summed[0]
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(arrays)  # each: (P, ...)
    summed = tuple(np.sum(np.asarray(a), axis=0) for a in stacked)
    return summed if len(summed) > 1 else summed[0]


# running counters for the LAST exchange_rows call (tests assert the
# per-visit traffic is O(owned rows), not O(P * rows) — VERDICT r3 weak #5)
LAST_EXCHANGE_STATS: dict = {}

_PROC_MESH = None


def _process_mesh():
    """A 1-D mesh with ONE device per process (each process's first local
    device) — the lane for host-to-host all_to_all exchanges."""
    global _PROC_MESH
    if _PROC_MESH is None:
        from jax.sharding import Mesh

        P_ = jax.process_count()
        by_proc: dict[int, object] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        _PROC_MESH = Mesh(
            np.array([by_proc[p] for p in range(P_)]), ("proc",)
        )
    return _PROC_MESH


_A2A_JIT = None


def _all_to_all_jit():
    """One cached jitted all_to_all program (jit handles shape/dtype
    polymorphism through its own cache; rebuilding the shard_map per call
    would recompile every exchange). Audited for per-call re-trace:
    the mesh object, the shard_map closure and the jit wrapper are all
    process-lifetime singletons, so repeated exchanges with identical
    (shape, dtype) reuse ONE executable — asserted by the cache-growth
    test in tests/test_multihost.py (``_a2a_cache_size``)."""
    global _A2A_JIT
    if _A2A_JIT is None:
        try:  # jax.experimental.shard_map moved in newer jax releases
            from photon_ml_tpu.utils.compat import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        _A2A_JIT = jax.jit(
            shard_map(
                lambda x: jax.lax.all_to_all(
                    x, "proc", split_axis=0, concat_axis=0, tiled=True
                ),
                mesh=_process_mesh(),
                in_specs=P("proc"),
                out_specs=P("proc"),
            )
        )
    return _A2A_JIT


def _a2a_cache_size() -> int:
    """Number of compiled variants behind the cached all_to_all jit —
    the executable-reuse tripwire: coordinate descent re-enters the
    exchange with identical shapes every visit, so this must stay FLAT
    across repeated same-shape calls (growth = a re-trace regression
    that would recompile the exchange every visit)."""
    if _A2A_JIT is None:
        return 0
    try:
        return int(_A2A_JIT._cache_size())
    except AttributeError:  # very old jax: no public cache introspection
        return 0


def exchange_rows(arrays, dest: np.ndarray, tag: str = ""):
    """Deliver row ``i`` of every array to process ``dest[i]`` — the
    point-to-point shuffle the reference does with a Spark exchange.

    Unlike ``allgather_row_chunks`` (every row to EVERY host: O(P·n)
    traffic), this routes each row only to its destination. Two transports,
    chosen per call from the globally-consistent (P, P) bucket-count
    matrix:

    - **Balanced** (padded allocation ≤ 2× payload): one
      ``lax.all_to_all`` over the process mesh — rides ICI on pods, one
      compiled program re-entered when per-visit counts are stable.
      SPMD collectives require UNIFORM (source, dest) block sizes, so
      every bucket pads to the global max — fine when destinations are
      balanced, structurally O(P×payload) under entity skew (one hot
      entity ⇒ one hot owner ⇒ one huge bucket sets every bucket's pad).
    - **Skewed** (padding would exceed 2× payload): a host-side TCP
      point-to-point exchange (``_host_p2p_exchange``) sending each
      bucket EXACTLY — zero padding under any skew, the direct analog of
      the reference's Netty shuffle riding DCN (SURVEY §2.7). Per-host
      traffic is O(rows sent + rows owned) always.

    Returns a dict of received rows (grouped by source process, sources in
    ascending order — every process receives with the same layout rule, so
    the result is deterministic and transport-independent). Single
    process: identity. All processes must call this collectively with the
    same key set. ``tag`` labels the exchange in telemetry (the per-link
    ``p2p_send``/``p2p_recv`` events of the framed transport carry it);
    it never affects routing or results.
    """
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    P_ = effective_process_count()
    if P_ <= 1:
        LAST_EXCHANGE_STATS.update(
            bytes_sent=0, rows_sent=len(dest), padded_rows=len(dest),
            transport="local",
        )
        return arrays
    from jax.experimental import multihost_utils as mhu
    from jax.sharding import PartitionSpec as P

    dest = np.asarray(dest, np.int64)
    order = np.argsort(dest, kind="stable")
    counts = np.bincount(dest, minlength=P_).astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(counts)])
    # every process learns every (source, destination) bucket size — a
    # (P, P) int matrix, negligible next to the row payload
    counts_matrix = allgather_host(counts).reshape(P_, P_)
    maxc = max(int(counts_matrix.max()), 1)

    # transport decision — identical on every process (counts_matrix is):
    # all_to_all allocates P·maxc slots per process against its
    # counts.sum() real rows; beyond 2× padding, go point-to-point.
    # A degraded group ALWAYS goes point-to-point: the all_to_all
    # program spans the full device mesh, dead peer included.
    total_payload = max(int(counts_matrix.sum()), 1)
    if _DEGRADED is not None or P_ * P_ * maxc > 2 * total_payload:
        # one global socket-use order: never interleave with an in-flight
        # worker-thread exchange mid-frame (no-op when none are pending)
        drain_async_exchanges()
        return _host_p2p_exchange(
            arrays, order, starts, counts_matrix, tag=tag
        )

    from photon_ml_tpu.obs import devcost

    mesh = _process_mesh()
    pid = jax.process_index()
    out: dict[str, np.ndarray] = {}
    bytes_sent = 0
    for key in sorted(arrays):
        a = arrays[key]
        feat = a.shape[1:]
        local = np.zeros((P_, maxc) + feat, a.dtype)
        for p in range(P_):
            rows = order[starts[p]:starts[p + 1]]
            local[p, : len(rows)] = a[rows]
        bytes_sent += local.nbytes
        g = mhu.host_local_array_to_global_array(local, mesh, P("proc"))
        swapped = _all_to_all_jit()(g)
        # analytic cost of the exchange-adjacent executable, captured
        # AFTER the collective ran: the capture's AOT compile happens on
        # the sink-holding process only, and doing it before the call
        # would park every peer mid-collective behind that compile. One
        # capture per fresh (shape, dtype) — the devcost layer dedups.
        devcost.capture("multihost.all_to_all", _all_to_all_jit(), (g,))
        recv = np.asarray(
            mhu.global_array_to_host_local_array(swapped, mesh, P("proc"))
        )  # (P, maxc, *feat): slice s = rows from source s
        out[key] = np.concatenate(
            [recv[s, : counts_matrix[s, pid]] for s in range(P_)]
        )
    LAST_EXCHANGE_STATS.update(
        bytes_sent=bytes_sent,
        rows_sent=int(counts.sum()),
        padded_rows=P_ * maxc * len(arrays),
        transport="all_to_all",
    )
    return out


# lazily-built full TCP mesh between processes for the skewed-exchange
# transport: {"send": {peer: socket}, "recv": {peer: socket}}
_HOST_LINKS: dict | None = None

# per-link frame-set sequence counters for TELEMETRY CORRELATION: the
# framed exchange's submission-order invariant (every process issues the
# same exchange sequence at the same program points) means the k-th
# frame-set SENT on link i→j is exactly the k-th frame-set RECEIVED on
# that link at j — so both ends derive the same correlation id
# ``p2p:<src>><dst>#<k>`` with zero extra bytes on the wire, and
# ``report fleet`` joins each link's send/recv events across shard
# files by that id (one-sided wait = recv-start − send-start).
# Incremented UNCONDITIONALLY (not sink-gated): a process whose sink
# activates mid-sequence must still agree with its peers on k.
_LINK_SEQ: dict = {"send": {}, "recv": {}}


def _next_link_seq(direction: str, peer: int) -> int:
    seqs = _LINK_SEQ[direction]
    seqs[peer] = seqs.get(peer, 0) + 1
    return seqs[peer]


def _sink_active() -> bool:
    """Whether telemetry is on (cheap; the exchange hot path must stay
    byte-identical when it is not)."""
    try:
        from photon_ml_tpu.obs import sink as _sink

        return _sink.is_active()
    except Exception:
        return False


def _emit_event(event: str, **payload) -> None:
    try:
        from photon_ml_tpu.obs.spans import emit_event

        emit_event(event, **payload)
    except Exception:
        pass  # telemetry must never take down the exchange it observes


def _reset_host_links() -> None:
    """Close every cached exchange socket and drop THIS process's mesh so
    its next exchange rebuilds from scratch. Called on ANY
    ``_host_p2p_exchange`` error: after a partial send/receive the
    length-prefix framing on the surviving streams is undefined (a retry
    would read payload bytes as a prefix and silently mis-frame
    everything after), so the only safe local state is no mesh at all.
    The reset is per-process by construction (an error such as a size
    mismatch may be raised on one host only); peers discover it FAIL-FAST
    on their next exchange — their sends/receives against the closed
    sockets error instead of mis-framing — which resets them too, so a
    caller-level collective retry converges to a full mesh rebuild."""
    global _HOST_LINKS
    links, _HOST_LINKS = _HOST_LINKS, None
    # correlation counters restart with the mesh: after a teardown both
    # ends rebuild and resynchronize at frame-set 1 (frames lost to the
    # error surface as UNMATCHED send/recv events in ``report fleet`` —
    # the telemetry-health signal, by design)
    _LINK_SEQ["send"] = {}
    _LINK_SEQ["recv"] = {}
    if not links:
        return
    for side in ("send", "recv"):
        for sock in links.get(side, {}).values():
            try:
                sock.close()
            except OSError:
                pass


def _coordinator_address() -> str:
    """The ``jax.distributed`` coordinator address: the standard env var
    when set, else JAX's own distributed global state (the runtime knows
    its coordinator even when it was wired up by pod auto-detection or
    explicit ``initialize`` arguments — the env var is absent on exactly
    those paths)."""
    target = os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    if target:
        return target
    try:
        from jax._src import distributed as _distributed

        return getattr(_distributed.global_state, "coordinator_address", None) or ""
    except Exception:
        return ""


def _is_loopback(ip: str) -> bool:
    return ip.startswith("127.") or ip in ("0.0.0.0", "localhost", "::1")


def _coordinator_is_loopback(host: str) -> bool:
    """True when the coordinator host is loopback — literally, or through
    DNS/hosts resolution (the single-machine harness may pass the
    machine's own hostname, which stock Debian/Ubuntu maps to
    127.0.1.1)."""
    if not host:
        return False
    if _is_loopback(host):
        return True
    import socket

    try:
        return _is_loopback(socket.gethostbyname(host))
    except OSError:
        return False


def _local_ip() -> str:
    """This host's address as peers should dial it. Override with
    ``PHOTON_EXCHANGE_HOST`` to pin a specific NIC. Otherwise discover the
    OUTBOUND interface by UDP-connecting toward the ``jax.distributed``
    coordinator (env var or the runtime's own global state; no packet is
    sent — the kernel just picks the route) —
    ``gethostbyname(gethostname())`` is NOT used because stock
    Debian/Ubuntu ``/etc/hosts`` maps the hostname to 127.0.1.1, which
    would advertise an undialable loopback to remote peers.

    A discovered LOOPBACK address with ``process_count > 1`` under a
    non-loopback (or unknown) coordinator fails FAST: advertising it would
    make every remote peer dial itself and hang the mesh build until the
    300 s socket timeout. A loopback COORDINATOR means every process lives
    on this machine (a remote process could not have reached it), so
    loopback peers are dialable and the single-machine multi-process test
    harness keeps working."""
    explicit = os.environ.get("PHOTON_EXCHANGE_HOST")
    if explicit:
        return explicit
    import socket

    target = _coordinator_address()
    host = target.rsplit(":", 1)[0] if target else ""

    # any non-loopback discovery returns immediately; one loopback result
    # only means THAT probe routed locally (e.g. the coordinator hostname
    # mapped to 127.0.1.1 via /etc/hosts — the later 8.8.8.8 probe still
    # finds the real NIC), so keep probing and fail fast only once EVERY
    # source has come up loopback
    last = "127.0.0.1"
    for probe in filter(None, [host, "8.8.8.8"]):
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect((probe, 53))
                ip = s.getsockname()[0]
        except OSError:
            continue
        if not _is_loopback(ip):
            return ip
        last = ip
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if not _is_loopback(ip):
            return ip
        last = ip
    except OSError:
        pass
    if jax.process_count() > 1 and not _coordinator_is_loopback(host):
        raise RuntimeError(
            f"host exchange address discovery found only loopback {last!r} "
            f"with process_count={jax.process_count()}: remote peers "
            "cannot dial it (the mesh build would hang until the "
            "300 s timeout). Set PHOTON_EXCHANGE_HOST to this host's "
            "reachable address."
        )
    return last


def _p2p_timeout_s() -> float | None:
    """Socket timeout for the host P2P exchange mesh, knob
    ``PHOTON_P2P_TIMEOUT_S`` (seconds; generous default — exchanges move
    real payload over slow DCN links, and a false-positive timeout tears
    the mesh down; ``0`` or negative disables the timeout entirely, the
    usual knob convention, restoring blocking sockets). Applied to EVERY
    socket operation of the mesh — accept, connect, send, recv — so a
    dead or silent peer raises ``socket.timeout`` instead of hanging the
    exchange forever; the error then reaches the existing
    ``_reset_host_links`` teardown and the caller's retry rebuilds the
    mesh."""
    env = os.environ.get("PHOTON_P2P_TIMEOUT_S")
    if env is not None and env != "":
        v = float(env)
        return v if v > 0 else None
    return 300.0


def _configure_link_socket(sock) -> None:
    """Apply the exchange-mesh socket policy: the knob timeout (no socket
    in the mesh may block forever) and TCP_NODELAY (length-prefixed small
    frames must not wait on Nagle)."""
    import socket

    sock.settimeout(_p2p_timeout_s())
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


def _p2p_retries() -> int:
    """Transient-fault retry budget for the framed host P2P exchange,
    knob ``PHOTON_P2P_RETRIES`` (default 0 = the pre-retry behavior:
    any link error tears the mesh down and raises, bit-for-bit). N > 0
    enables the RELIABLE exchange mode: every framed exchange ends with
    a per-link completion ACK (so one process's failure fails every
    process's exchange — the cross-process precondition for a
    consistent collective retry), and a failed exchange is retried up
    to N times through the ``_reset_host_links`` teardown + cached-
    address rebuild path, with exponential backoff
    (``PHOTON_P2P_BACKOFF_S``) between attempts."""
    env = os.environ.get("PHOTON_P2P_RETRIES")
    if env is not None and env != "":
        return max(int(env), 0)
    return 0


def _p2p_backoff_s() -> float:
    """Base backoff between exchange retry attempts, knob
    ``PHOTON_P2P_BACKOFF_S`` (seconds; attempt k sleeps
    ``base * 2**k``, plus a deterministic per-process jitter fraction
    derived from (process index, attempt) — decorrelated across the
    fleet with no RNG state, the seedless discipline the fault plan
    uses)."""
    env = os.environ.get("PHOTON_P2P_BACKOFF_S")
    if env is not None and env != "":
        return max(float(env), 0.0)
    return 0.5


def _retry_backoff_sleep(attempt: int) -> float:
    base = _p2p_backoff_s()
    if base <= 0.0:
        return 0.0
    # deterministic jitter in [0, 0.5): hash of (pid, attempt) — every
    # process backs off a slightly different amount without any RNG
    pid = _self_pid()
    jitter = ((pid * 2654435761 + attempt * 40503) % 512) / 1024.0
    return base * (2.0 ** attempt) * (1.0 + jitter)


def _p2p_crc_enabled() -> bool:
    """``PHOTON_P2P_CRC`` (default 0): advertise frame-protocol v1 at
    mesh build. A link uses the CRC32 integrity trailer only when BOTH
    ends advertised v1 (the hello's spare high bytes carry the version,
    so a v0 peer still reads its pid unchanged) — corruption then
    surfaces as a detected ``LinkCorruption`` instead of a mis-framed
    length prefix downstream. Off = the PR-10 wire format byte-for-
    byte."""
    env = os.environ.get("PHOTON_P2P_CRC")
    if env is not None and env != "":
        return int(env) != 0
    return False


# frame-protocol versions a process can advertise in the mesh hello:
# 0 = length-prefixed frames (the original wire format), 1 = length
# prefix + payload + CRC32 trailer. The hello int packs
# ``pid | (version << 16)`` — version 0 leaves the hello bytes exactly
# the PR-10 wire bytes.
_FRAME_PROTO_CRC = 1


class LinkCorruption(ConnectionError):
    """A framed-P2P payload failed its CRC32 integrity check — the
    frame ARRIVED (framing intact) but its bytes are wrong. A transient
    fault for the retry layer: the mesh tears down and the exchange
    re-runs."""


class PeerUnreachable(ConnectionError):
    """A mesh (re)build could not reach one specific peer (connect
    refused / timed out / accept never arrived). Transient until the
    retry budget exhausts — then it hardens into ``PeerLost``."""

    def __init__(self, peer: int, message: str):
        super().__init__(message)
        self.peer = peer


class PeerLost(ConnectionError):
    """Retries exhausted against a specific peer: the exchange layer
    has given up on reaching it. Callers with a recovery path (the
    streamed GAME trainer) catch this, confirm the loss with a roll
    call, re-plan placement around the dead peer and resume from the
    last checkpoint; callers without one get a clean abort that names
    the peer instead of a 300 s timeout stack."""

    def __init__(self, peer: int, message: str):
        super().__init__(message)
        self.peer = peer


# -- degraded process group (peer-loss recovery) -----------------------------
#
# After a confirmed peer loss the jax collective runtime is unusable
# (every collective would include — and hang on — the dead process), so
# recovery shrinks the world HOST-SIDE: a degraded group names the
# surviving ORIGINAL process indices, every multihost helper in this
# module routes through the framed-P2P survivor mesh (addresses are
# cached from the first build — no collective needed), and
# ``effective_process_index/count`` replace ``jax.process_index/count``
# for group-shaped decisions. The jax runtime itself stays up (device
# compute is process-local); it is simply never asked to cross
# processes again.

_DEGRADED: dict | None = None

# rejoin identity: a process RE-EXEC'D after a loss (the elastic-rejoin
# half, knob PHOTON_REJOIN) cannot re-enter the original
# ``jax.distributed`` cohort — its fresh runtime reports
# ``process_index() == 0`` / ``process_count() == 1``. ``bootstrap_
# rejoin`` records the process's ORIGINAL identity (pid + world size,
# from the persisted mesh-address cache) here, and every identity read
# in this module goes through ``_self_pid``/``_world_size`` so the
# rejoined process keeps speaking the framed-P2P protocol under its
# original name. None on every normally-initialized process — the
# helpers then read the jax runtime exactly as before.
_REJOIN_IDENTITY: dict | None = None


def rejoin_identity() -> dict | None:
    return _REJOIN_IDENTITY


def _self_pid() -> int:
    """This process's ORIGINAL process index (survives a rejoin
    re-exec, where ``jax.process_index()`` resets to 0)."""
    if _REJOIN_IDENTITY is not None:
        return int(_REJOIN_IDENTITY["pid"])
    return jax.process_index()


def _world_size() -> int:
    """The ORIGINAL fleet size (survives a rejoin re-exec, where
    ``jax.process_count()`` resets to 1)."""
    if _REJOIN_IDENTITY is not None:
        return int(_REJOIN_IDENTITY["world"])
    return jax.process_count()


def original_process_index() -> int:
    """Public twin of ``_self_pid`` for consumers outside this module
    (the telemetry sink's shard index, the rejoin drills)."""
    return _self_pid()


def original_process_count() -> int:
    return _world_size()


def degraded_group() -> dict | None:
    return _DEGRADED


def effective_process_count() -> int:
    if _DEGRADED is not None:
        return len(_DEGRADED["survivors"])
    if _REJOIN_IDENTITY is not None:
        # a rejoiner BEFORE admission: group-shaped code must not
        # mistake it for a healthy single-process world (it must not
        # run collectives at all until the rejoin roll call seats it)
        return _world_size()
    return jax.process_count()


def effective_process_index() -> int:
    if _DEGRADED is not None:
        return _DEGRADED["rank"]
    if _REJOIN_IDENTITY is not None:
        return _self_pid()
    return jax.process_index()


def effective_topology() -> tuple:
    """The EFFECTIVE device topology this process computes under, as a
    hashable cache-key component: ``(backend, local device count,
    effective process count)``. The executable caches (``_tiled_apply``'s
    jit statics, the tile-layout cache's tuned-constants key) carry this
    so a degrade-in-place — which changes the effective group without
    restarting the process — can never re-enter an executable compiled
    for the pre-loss topology by shape coincidence, while a SAME-topology
    re-entry (the cheap-abort restart at survivor count, or plain
    repeated visits) hits every cache it already filled: zero growth,
    zero recompiles. Read at CALL time, the same discipline as every
    tuned constant."""
    return (
        jax.default_backend(),
        len(jax.local_devices()),
        effective_process_count(),
    )


def set_degraded_group(survivors) -> None:
    """Shrink this process's world to ``survivors`` (sorted original
    process indices; must include this process). Tears the socket mesh
    down — the next exchange rebuilds it over the survivor set from the
    cached addresses. An EXPANDED group (elastic rejoin) goes through
    here too: even at full original size the group keeps routing over
    the framed-P2P mesh, because a rejoined process's fresh jax runtime
    is not part of the original collective cohort."""
    global _DEGRADED
    survivors = tuple(sorted(int(s) for s in survivors))
    pid = _self_pid()
    if pid not in survivors:
        raise ValueError(
            f"process {pid} cannot join a degraded group {survivors} "
            "that excludes it"
        )
    _reset_host_links()
    if (
        len(survivors) == _world_size()
        and _DEGRADED is None
        and _REJOIN_IDENTITY is None
    ):
        return  # full group = not degraded
    _DEGRADED = {
        "survivors": survivors,
        "rank": survivors.index(pid),
    }
    from photon_ml_tpu.obs.metrics import REGISTRY

    REGISTRY.gauge_set("fleet.survivors", float(len(survivors)))
    _emit_event(
        "degraded_group", survivors=list(survivors),
        rank=_DEGRADED["rank"],
    )


def _orig_pid(rank: int) -> int:
    """Effective rank -> original process index (identity when the
    group is whole)."""
    if _DEGRADED is not None:
        return _DEGRADED["survivors"][rank]
    return rank


def _p2p_heartbeat_s() -> float | None:
    """Blocked-recv heartbeat cadence, knob ``PHOTON_P2P_HEARTBEAT_S``
    (seconds; ``0`` or negative disables). While a framed-P2P recv is
    blocked on a silent peer, the exchange emits one rate-limited
    ``p2p_heartbeat`` telemetry event per interval — so a stuck link is
    visible (with its peer, tag and blocked seconds) in the run's shard
    file long before the ``PHOTON_P2P_TIMEOUT_S`` abort (default 300 s)
    tears the mesh down."""
    env = os.environ.get("PHOTON_P2P_HEARTBEAT_S")
    if env is not None and env != "":
        v = float(env)
        return v if v > 0 else None
    return 5.0


def _recv_exact(sock, n: int, peer: int | None = None,
                tag: str | None = None,
                heartbeat: float | None = None) -> bytes:
    """``heartbeat=None`` (the default, and always when no sink is
    active — callers snapshot that ONCE per exchange) is the plain
    pre-heartbeat recv, byte-identical to the original hot path."""
    if heartbeat is None:
        chunks = []
        while n:
            part = sock.recv(min(n, 1 << 20))
            if not part:
                raise ConnectionError("exchange peer closed the connection")
            chunks.append(part)
            n -= len(part)
        return b"".join(chunks)
    # heartbeat path: poll readiness so a silent peer surfaces in
    # telemetry every ``heartbeat`` seconds; the knob timeout keeps its
    # exact semantics (max SILENCE, the same contract settimeout gives
    # the plain path — the clock resets whenever bytes arrive).
    # selectors (epoll/poll on Linux), NOT select.select: the exchange
    # mesh plus chunk cache plus JAX can push socket fds past
    # FD_SETSIZE (1024), where select() raises — the instrument must
    # never crash an exchange the plain path would have completed.
    import selectors

    timeout_s = _p2p_timeout_s()
    chunks = []
    silent = 0.0
    with selectors.DefaultSelector() as sel:
        sel.register(sock, selectors.EVENT_READ)
        while n:
            t0 = time.perf_counter()
            ready = sel.select(timeout=heartbeat)
            if not ready:
                silent += time.perf_counter() - t0
                _emit_event(
                    "p2p_heartbeat", peer=peer, tag=tag,
                    blocked_s=silent, bytes_remaining=n,
                    direction="recv",
                )
                if timeout_s is not None and silent >= timeout_s:
                    import socket as _socket

                    raise _socket.timeout(
                        f"exchange recv from process {peer} silent for "
                        f"{silent:.1f}s (PHOTON_P2P_TIMEOUT_S)"
                    )
                continue
            part = sock.recv(min(n, 1 << 20))
            if not part:
                raise ConnectionError(
                    "exchange peer closed the connection"
                )
            silent = 0.0
            chunks.append(part)
            n -= len(part)
    return b"".join(chunks)


def _sendall_hb(sock, data: bytes, peer: int | None = None,
                tag: str | None = None,
                heartbeat: float | None = None) -> None:
    """``sendall`` twin of ``_recv_exact``'s heartbeat mode.
    ``heartbeat=None`` (always, when no sink is active) is
    ``sock.sendall`` verbatim — the original hot path. With a
    heartbeat, a send stalled on a full kernel buffer toward a wedged
    peer emits rate-limited ``p2p_heartbeat`` events with ``direction:
    send`` — previously a blocked SEND was invisible until the timeout
    abort (only blocked recvs heartbeated). Timeout semantics mirror
    the plain path's ``settimeout``: max SILENCE, the clock resets
    whenever bytes move."""
    if heartbeat is None:
        sock.sendall(data)
        return
    import selectors

    timeout_s = _p2p_timeout_s()
    view = memoryview(data)
    silent = 0.0
    with selectors.DefaultSelector() as sel:
        sel.register(sock, selectors.EVENT_WRITE)
        while view:
            t0 = time.perf_counter()
            ready = sel.select(timeout=heartbeat)
            if not ready:
                silent += time.perf_counter() - t0
                _emit_event(
                    "p2p_heartbeat", peer=peer, tag=tag,
                    blocked_s=silent, bytes_remaining=len(view),
                    direction="send",
                )
                if timeout_s is not None and silent >= timeout_s:
                    import socket as _socket

                    raise _socket.timeout(
                        f"exchange send to process {peer} blocked for "
                        f"{silent:.1f}s (PHOTON_P2P_TIMEOUT_S)"
                    )
                continue
            sent = sock.send(view)
            if sent == 0:
                raise ConnectionError(
                    "exchange peer closed the connection"
                )
            silent = 0.0
            view = view[sent:]


def _send_frame(sock, payload: bytes, crc: bool,
                peer: int | None = None, tag: str | None = None,
                heartbeat: float | None = None,
                corrupt_wire: bool = False) -> None:
    """One framed payload: 8-byte length prefix + payload, plus (frame
    protocol v1, negotiated per link at mesh build) a CRC32 trailer of
    the payload. The length prefix never counts the trailer, so every
    row-count validation downstream is protocol-independent.

    ``corrupt_wire`` (fault injection only) flips a payload byte AFTER
    the trailer is computed — modelling a wire/buffer fault, which is
    exactly what the trailer exists to catch. A pre-CRC flip would be
    faithfully checksummed and arrive "valid"."""
    import struct

    wire = payload
    if corrupt_wire:
        from photon_ml_tpu.parallel import faults

        wire = faults._corrupt(payload)
    _sendall_hb(sock, struct.pack("!q", len(payload)), peer, tag, heartbeat)
    _sendall_hb(sock, wire, peer, tag, heartbeat)
    if crc:
        import zlib

        _sendall_hb(
            sock, struct.pack("!I", zlib.crc32(payload)),
            peer, tag, heartbeat,
        )


def _recv_frame_payload(sock, n: int, crc: bool,
                        peer: int | None = None, tag: str | None = None,
                        heartbeat: float | None = None) -> bytes:
    """The payload bytes of a frame whose length prefix was already
    read, verifying the v1 CRC trailer when the link negotiated it. A
    mismatch raises ``LinkCorruption`` — a DETECTED transient for the
    retry layer, where the unchecked protocol would have handed
    corrupt rows to the solver (or mis-framed every later exchange)."""
    raw = _recv_exact(sock, n, peer, tag, heartbeat)
    if crc:
        import struct
        import zlib

        want = struct.unpack(
            "!I", _recv_exact(sock, 4, peer, tag, heartbeat)
        )[0]
        got = zlib.crc32(raw)
        if got != want:
            raise LinkCorruption(
                f"exchange frame from process {peer} tag {tag!r}: "
                f"CRC32 mismatch (got {got:#010x}, trailer {want:#010x})"
            )
    return raw


# completion-ACK magic for the reliable exchange mode: one byte per
# link per exchange, confirming the peer finished its WHOLE exchange —
# without it, one process's failure could leave peers believing the
# exchange succeeded, and a later retry would resend frames into
# streams whose counters no longer agree (silent mis-framing)
_ACK_BYTE = b"\xa5"


# addresses from the FIRST mesh build, cached process-wide: {orig_pid:
# (ip_str, port)}. A REBUILD (retry after teardown, survivor mesh after
# a peer loss) reuses them and re-binds this process's own recorded
# port — no collective, so a rebuild is legal from the exchange worker
# thread and from a degraded group the jax runtime can no longer span.
_HOST_ADDRS: dict[int, tuple[str, int]] | None = None

# serializes mesh builds across threads (the exchange worker may rebuild
# mid-retry while the main thread bootstraps an async exchange)
import threading as _threading

_LINKS_BUILD_LOCK = _threading.RLock()


def _hello_int(pid: int) -> int:
    """The mesh hello: the sender's pid, with the advertised frame-
    protocol version in the spare high bytes. Version 0 (CRC knob off)
    leaves the int — and the wire bytes — exactly the original pid."""
    proto = _FRAME_PROTO_CRC if _p2p_crc_enabled() else 0
    return pid | (proto << 16)


def _decode_hello(raw: int) -> tuple[int, int]:
    return raw & 0xFFFF, raw >> 16


def _close_quietly(sock) -> None:
    try:
        sock.close()
    except OSError:
        pass


def _gather_link_addrs() -> dict[int, tuple[str, int]]:
    """First-build address bootstrap over the jax runtime (collective —
    each process allgathers its (IPv4, port) as five small ints; the
    only collective the mesh ever uses) with this process's listener
    already bound. Cached for every later rebuild."""
    import socket

    from jax.experimental import multihost_utils as mhu

    P_ = jax.process_count()
    assert _HOST_ADDRS is not None  # own entry recorded by caller
    ip = np.frombuffer(
        socket.inet_aton(_HOST_ADDRS[jax.process_index()][0]), np.uint8
    ).astype(np.int64)
    port = _HOST_ADDRS[jax.process_index()][1]
    addrs = np.asarray(
        mhu.process_allgather(np.concatenate([ip, [port]]))
    ).reshape(P_, 5)
    return {
        p: (
            socket.inet_ntoa(addrs[p, :4].astype(np.uint8).tobytes()),
            int(addrs[p, 4]),
        )
        for p in range(P_)
    }


def _build_host_links(peers: list[int], timeout_s, srv=None) -> dict:
    """One full-mesh build over ``peers`` (original pids, this process
    included): every ordered pair gets a dedicated unidirectional TCP
    connection, so concurrent sends and receives never share a stream.
    On ANY partial failure the already-established sockets are closed,
    the listener is closed and the acceptor thread is JOINED before the
    error propagates — a half-built mesh must never leak connected
    sockets or a live acceptor into the next rebuild attempt (they
    would accept/deliver stale hellos there and mis-key the mesh).

    Returns ``{"send": {pid: sock}, "recv": {pid: sock},
    "proto": {pid: negotiated version}}``."""
    import socket
    import struct
    import threading

    global _HOST_ADDRS
    pid = _self_pid()
    others = [p for p in peers if p != pid]
    first_build = _HOST_ADDRS is None
    if srv is None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.settimeout(timeout_s)  # accept() must not hang on a dead peer
        # rebuilds bind the RECORDED port (peers dial the cached
        # address); the first build lets the OS pick
        own_port = 0 if first_build else _HOST_ADDRS[pid][1]
        try:
            srv.bind(("0.0.0.0", own_port))
        except OSError:
            srv.close()
            raise
        srv.listen(max(len(peers), 1))
    if first_build:
        _HOST_ADDRS = {pid: (_local_ip(), srv.getsockname()[1])}
        try:
            _HOST_ADDRS = _gather_link_addrs()
        except BaseException:
            _HOST_ADDRS = None
            srv.close()
            raise
        _maybe_persist_mesh_addrs()

    recv_socks: dict[int, socket.socket] = {}
    recv_protos: dict[int, int] = {}
    accept_err: list[BaseException] = []

    def accept_all():
        try:
            for _ in range(len(others)):
                conn, _ = srv.accept()
                _configure_link_socket(conn)
                raw = struct.unpack("!i", _recv_exact(conn, 4))[0]
                src, proto = _decode_hello(raw)
                if src in recv_socks:
                    # a peer re-dialed (its previous build attempt
                    # aborted): the stale socket is dead — replace it
                    _close_quietly(recv_socks[src])
                recv_socks[src] = conn
                recv_protos[src] = proto
        except BaseException as e:
            accept_err.append(e)

    acceptor = threading.Thread(target=accept_all, daemon=True)
    acceptor.start()
    send_socks: dict[int, socket.socket] = {}
    send_protos: dict[int, int] = {}
    try:
        order = sorted(others, key=lambda p: (p - pid) % max(len(peers), 1))
        for peer in order:
            peer_ip, peer_port = _HOST_ADDRS[peer]
            # dial with PATIENCE while our own listener stays up: on a
            # concurrent rebuild both peers race listen-then-dial, and a
            # refused connect only means the peer has not re-listened
            # YET. Abandoning the whole build on first refusal would
            # close our listener too — two rebuilding peers would then
            # livelock, each dialing the other's closed port during the
            # other's backoff sleep. So refusals retry in place until
            # the per-build timeout budget; only then is the peer
            # declared unreachable for this attempt.
            deadline = time.monotonic() + (
                timeout_s if timeout_s is not None else 30.0
            )
            while True:
                try:
                    s = socket.create_connection(
                        (peer_ip, peer_port), timeout=timeout_s
                    )
                    break
                except OSError as e:
                    if time.monotonic() >= deadline:
                        raise PeerUnreachable(
                            peer,
                            f"exchange mesh build: cannot connect to "
                            f"process {peer} at {peer_ip}:{peer_port}: "
                            f"{e}",
                        ) from e
                    time.sleep(0.05)
            _configure_link_socket(s)
            s.sendall(struct.pack("!i", _hello_int(pid)))
            send_socks[peer] = s
        acceptor.join(timeout=timeout_s)
        if acceptor.is_alive() or len(recv_socks) != len(others):
            missing = sorted(set(others) - set(recv_socks))
            err = RuntimeError(
                f"host exchange mesh incomplete: accepted "
                f"{len(recv_socks)} of {len(others)} peers"
                + (f" (missing {missing})" if missing else "")
            )
            if missing:
                # name the lowest missing peer even when several are
                # missing: the retry/roll-call tier only needs ONE
                # suspect to treat the failure as transient-then-
                # PeerLost — a raw RuntimeError here would propagate
                # past the retry loop and crash a survivor that merely
                # raced its peers' own rebuild attempts
                err = PeerUnreachable(missing[0], str(err))
            raise err
    except BaseException:
        # partial-failure cleanup: closing the listener unblocks a
        # still-alive acceptor (accept() raises), so the join below
        # cannot hang; every established socket closes so nothing
        # leaks into the next attempt
        srv.close()
        for s in send_socks.values():
            _close_quietly(s)
        for s in recv_socks.values():
            _close_quietly(s)
        acceptor.join(timeout=timeout_s)
        raise
    srv.close()
    my_proto = _FRAME_PROTO_CRC if _p2p_crc_enabled() else 0
    # per-link negotiation: the CRC trailer rides a link only when BOTH
    # ends advertised it (the send side knows the peer's version from
    # the recv-side hello — the mesh is symmetric, every pair has both
    # links, and each process advertises ONE version to everyone)
    proto = {
        p: min(my_proto, recv_protos.get(p, 0)) for p in others
    }
    return {"send": send_socks, "recv": recv_socks, "proto": proto}


def _host_links() -> dict:
    """The (lazily built) socket mesh for this process's CURRENT group
    — all processes normally, the survivors after a degraded-group
    switch. First build must be called collectively (address
    bootstrap); rebuilds are collective-free (cached addresses)."""
    global _HOST_LINKS
    with _LINKS_BUILD_LOCK:
        if _HOST_LINKS is not None:
            return _HOST_LINKS
        if _DEGRADED is not None:
            peers = list(_DEGRADED["survivors"])
        else:
            peers = list(range(_world_size()))
        _HOST_LINKS = _build_host_links(peers, _p2p_timeout_s())
        return _HOST_LINKS


def _host_p2p_exchange(arrays, order, starts, counts_matrix=None,
                       transport="p2p_host", tag=""):
    """Skew-robust transport for ``exchange_rows``: each (source, dest)
    bucket travels EXACTLY, length-prefixed, over its pair's dedicated TCP
    link — no padding under any skew (an SPMD collective must pad every
    bucket to a uniform size, which costs O(P × payload) when one entity
    dominates). Sends run on a helper thread in rotation order (round r:
    send to pid+r, receive from pid−r) so every process's receiver drains
    concurrently — no cyclic wait. Layout of the result matches the
    all_to_all transport exactly (ascending source, stable within source).

    ANY error tears THIS process's socket mesh down
    (``_reset_host_links``): a partially-drained stream's next bytes are
    payload, not a length prefix, so reusing a survivor would silently
    mis-frame every later exchange. Peers fail fast against the closed
    sockets on their next use and reset themselves, so retries rebuild
    the mesh instead of corrupting data.

    ``PHOTON_P2P_RETRIES`` > 0 makes that retry AUTOMATIC: transient
    link faults (connect refused, recv timeout, peer EOF, CRC
    corruption) are retried here with bounded exponential backoff +
    jitter through the cached-address mesh rebuild — collective-free,
    so the retry is legal from the exchange worker thread too. The
    reliable mode's per-exchange completion ACK guarantees every
    process observes the same exchange outcome, so all peers retry the
    SAME exchange and the rebuilt streams stay frame-matched. When the
    budget exhausts against one unreachable peer, the error hardens
    into ``PeerLost`` — the recovery layer's signal.
    """
    retries = _p2p_retries()
    attempt = 0
    while True:
        try:
            return _host_p2p_exchange_impl(
                arrays, order, starts, counts_matrix, transport, tag
            )
        except BaseException as e:
            # closing the sockets also unblocks a sender thread stuck
            # in sendall against a stalled peer — it errors out + exits
            _reset_host_links()
            transient = isinstance(e, OSError)
            if transient and attempt < retries:
                attempt += 1
                backoff = _retry_backoff_sleep(attempt - 1)
                from photon_ml_tpu.obs.metrics import REGISTRY

                REGISTRY.counter_inc("p2p.retries")
                _emit_event(
                    "p2p_retry", attempt=attempt, max_attempts=retries,
                    tag=tag, error=type(e).__name__,
                    peer=getattr(e, "peer", None), backoff_s=backoff,
                )
                if backoff > 0.0:
                    time.sleep(backoff)
                continue
            if retries and transient:
                from photon_ml_tpu.obs.metrics import REGISTRY

                REGISTRY.counter_inc("p2p.giveups")
                _emit_event(
                    "p2p_giveup", attempts=attempt, tag=tag,
                    error=type(e).__name__,
                    peer=getattr(e, "peer", None),
                )
                if isinstance(e, PeerUnreachable):
                    raise PeerLost(
                        e.peer,
                        f"exchange retries exhausted ({retries}) against "
                        f"unreachable process {e.peer}: {e}",
                    ) from e
            raise


def _host_p2p_exchange_impl(arrays, order, starts, counts_matrix,
                            transport="p2p_host", tag=""):
    """``counts_matrix=None`` is the COLLECTIVE-FREE framing mode (the
    overlapped exchange schedule): each bucket's row count is derived
    from its length prefix instead of a pre-exchanged (P, P) count
    matrix, so the whole exchange is pure sockets — safe to run on the
    exchange worker thread concurrently with main-thread jax
    collectives, whose global ordering a worker-side allgather would
    violate. Frame sizes are validated per key (row-multiple + all keys
    from one source agreeing on the row count)."""
    import struct
    import threading

    from photon_ml_tpu.parallel import faults

    P_ = effective_process_count()
    pid = effective_process_index()
    links = _host_links()
    protos = links.get("proto", {})
    reliable = _p2p_retries() > 0
    plan = faults.active_plan()
    keys = sorted(arrays)
    parts: dict[str, dict[int, np.ndarray]] = {
        k: {pid: np.ascontiguousarray(
            arrays[k][order[starts[pid]:starts[pid + 1]]]
        )}
        for k in keys
    }
    bytes_sent = 0
    send_err: list[BaseException] = []
    # snapshot ONCE per exchange: the env knob and the sink check stay
    # off the per-frame hot path, and a concurrent sink reconfigure
    # cannot flip the recv framing mid-exchange
    telemetry = _sink_active()
    heartbeat = _p2p_heartbeat_s() if telemetry else None

    def send_all():
        nonlocal bytes_sent
        try:
            for r in range(1, P_):
                peer = (pid + r) % P_
                o_pid, o_peer = _orig_pid(pid), _orig_pid(peer)
                sock = links["send"][o_peer]
                crc = protos.get(o_peer, 0) >= _FRAME_PROTO_CRC
                seq = _next_link_seq("send", o_peer)
                t_start = time.time()
                t0 = time.perf_counter()
                rows = order[starts[peer]:starts[peer + 1]]
                bufs = [
                    np.ascontiguousarray(arrays[k][rows]).tobytes()
                    for k in keys
                ]
                corrupt_wire = False
                if plan is not None:
                    spec = plan.pop_send_fault(o_pid, o_peer, seq, tag)
                    if spec is not None:
                        bufs, corrupt_wire = faults.apply_send_fault(
                            spec, bufs, sock
                        )
                peer_bytes = 0
                if bufs is not None:  # None = the frame set was dropped
                    for j, buf in enumerate(bufs):
                        _send_frame(
                            sock, buf, crc, o_peer, tag, heartbeat,
                            corrupt_wire=corrupt_wire and j == 0,
                        )
                        peer_bytes += len(buf)
                bytes_sent += peer_bytes
                if telemetry:
                    # one event per (link, exchange): the frame-set, not
                    # per key — report fleet joins it with the peer's
                    # p2p_recv through the shared correlation id
                    _emit_event(
                        "p2p_send", peer=o_peer,
                        bytes=peer_bytes,
                        rows=int(starts[peer + 1] - starts[peer]),
                        dur_s=time.perf_counter() - t0,
                        t_start=t_start,
                        corr=f"p2p:{o_pid}>{o_peer}#{seq}",
                        tag=tag, transport=transport,
                    )
        except BaseException as e:  # surfaced after join
            send_err.append(e)

    sender = threading.Thread(target=send_all)
    sender.start()
    for r in range(1, P_):
        src = (pid - r) % P_
        o_pid, o_src = _orig_pid(pid), _orig_pid(src)
        sock = links["recv"][o_src]
        crc = protos.get(o_src, 0) >= _FRAME_PROTO_CRC
        seq = _next_link_seq("recv", o_src)
        t_start = time.time()
        t0 = time.perf_counter()
        src_bytes = 0
        src_rows = 0
        n_src: int | None = None  # framed mode: all keys must agree
        for k in keys:
            a = arrays[k]
            row_bytes = a.itemsize * int(
                np.prod(a.shape[1:], dtype=np.int64)
            )
            got = struct.unpack(
                "!q", _recv_exact(sock, 8, o_src, tag, heartbeat)
            )[0]
            if counts_matrix is not None:
                n = int(counts_matrix[src, pid])
                want = n * row_bytes
                if got != want:
                    raise RuntimeError(
                        f"exchange size mismatch from process {o_src} key "
                        f"{k!r}: expected {want} bytes ({n} rows), got {got}"
                    )
            else:
                if row_bytes <= 0 or got % row_bytes:
                    raise RuntimeError(
                        f"exchange frame from process {o_src} key {k!r}: "
                        f"{got} bytes is not a multiple of the "
                        f"{row_bytes}-byte row"
                    )
                n = got // row_bytes
                if n_src is None:
                    n_src = n
                elif n != n_src:
                    raise RuntimeError(
                        f"exchange frames from process {o_src} disagree on "
                        f"row count: key {k!r} carries {n} rows, earlier "
                        f"keys carried {n_src}"
                    )
            raw = _recv_frame_payload(sock, got, crc, o_src, tag, heartbeat)
            src_bytes += got
            src_rows = n
            parts[k][src] = np.frombuffer(raw, a.dtype).reshape(
                (n,) + a.shape[1:]
            ).copy()
        if telemetry:
            _emit_event(
                "p2p_recv", peer=o_src,
                bytes=src_bytes, rows=int(src_rows),
                dur_s=time.perf_counter() - t0,
                t_start=t_start,
                corr=f"p2p:{o_src}>{o_pid}#{seq}",
                tag=tag, transport=transport,
            )
    sender.join()
    if send_err:
        raise send_err[0]
    if reliable:
        # completion-ACK round (reliable mode only — one extra byte per
        # link per exchange, absent from the knob-off wire format): a
        # link's ACK arrives only after its peer finished its WHOLE
        # exchange, so any single failure fails every process's
        # exchange and the collective retry stays frame-matched
        for r in range(1, P_):
            peer = (pid + r) % P_
            _sendall_hb(
                links["send"][_orig_pid(peer)], _ACK_BYTE,
                _orig_pid(peer), tag, heartbeat,
            )
        for r in range(1, P_):
            src = (pid - r) % P_
            o_src = _orig_pid(src)
            got = _recv_exact(
                links["recv"][o_src], 1, o_src, tag, heartbeat
            )
            if got != _ACK_BYTE:
                raise RuntimeError(
                    f"exchange completion ACK from process {o_src} "
                    f"carries {got!r} (stream desync)"
                )
    # this process's send counts: identical to counts_matrix[pid] when a
    # matrix was exchanged, and derivable locally when not (framed mode)
    counts_send = np.diff(starts)
    LAST_EXCHANGE_STATS.update(
        bytes_sent=bytes_sent,
        rows_sent=int(counts_send.sum()),
        # same accounting as the all_to_all branch (allocated row-slots,
        # summed over keys) — here exactly the payload: zero padded slots
        padded_rows=int(counts_send.sum()) * len(arrays),
        transport=transport,
    )
    return {
        k: np.concatenate([parts[k][s] for s in range(P_)]) for k in keys
    }


# -- overlapped (asynchronous) point-to-point exchange ----------------------
#
# The pipelined exchange schedule (PHOTON_RE_SHARD=1): an exchange is
# ISSUED at one program point and JOINED at a later one, with device
# solves / host bookkeeping / jax collectives in between — instead of a
# barrier per coordinate. The exchange body runs on ONE dedicated worker
# thread per process in strict submission order (every process submits
# the same exchange sequence at the same program points, so the socket
# streams stay frame-matched), and it is COLLECTIVE-FREE (framed p2p:
# row counts ride the length prefixes) so a worker-side exchange can
# never interleave a collective against the main thread's.

_EXCHANGE_POOL = None
_EXCHANGE_LOCK = None  # guards the pending list + overlap accounting
_PENDING_EXCHANGES: list = []
_EXCHANGE_TOTALS = {"exchange_s": 0.0, "wait_s": 0.0}


def _exchange_state():
    global _EXCHANGE_POOL, _EXCHANGE_LOCK
    if _EXCHANGE_LOCK is None:
        import threading

        _EXCHANGE_LOCK = threading.Lock()
    if _EXCHANGE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        _EXCHANGE_POOL = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="photon-exchange"
        )
    return _EXCHANGE_POOL, _EXCHANGE_LOCK


def _record_overlap(kind: str, seconds: float) -> None:
    """Cumulative exchange/wait seconds + the derived overlap-ratio
    gauge: the fraction of exchange wall the consumer did NOT block on
    (1.0 = fully hidden behind other work, 0.0 = a barrier schedule).
    Mirrored into the PR-4 registry so the ratio rides every telemetry
    snapshot and ``photon-ml-tpu report``."""
    from photon_ml_tpu.obs.metrics import REGISTRY

    _, lock = _exchange_state()
    with lock:
        _EXCHANGE_TOTALS[kind] += seconds
        wall = _EXCHANGE_TOTALS["exchange_s"]
        wait = _EXCHANGE_TOTALS["wait_s"]
    REGISTRY.timer_add(f"re_exchange.{kind}", seconds)
    # zero wall (the single-process identity path) reads as fully
    # overlapped: there was nothing to wait for — and the gauge must
    # exist on every topology the schedule runs on
    ratio = 1.0 if wall <= 0.0 else max(0.0, min(1.0, 1.0 - wait / wall))
    REGISTRY.gauge_set("re_shard.exchange_overlap_ratio", ratio)


class ExchangeHandle:
    """A pending ``exchange_rows_async``. ``result()`` blocks until the
    exchange lands and returns the received-rows dict (the same layout
    contract as ``exchange_rows``); the blocked seconds are recorded as
    ``re_exchange.wait_s`` against the worker's ``re_exchange.exchange_s``
    for the overlap-ratio gauge (and, with a sink active, emitted as an
    ``exchange_wait`` event so the per-process timeline shows where the
    consumer actually blocked)."""

    def __init__(self, future=None, value=None, tag: str = ""):
        self._future = future
        self._value = value
        self._tag = tag

    @property
    def done(self) -> bool:
        return self._future is None or self._future.done()

    def result(self) -> dict:
        if self._future is None:
            return self._value
        import time as _time

        t0 = _time.perf_counter()
        try:
            out = self._future.result()
        finally:
            waited = _time.perf_counter() - t0
            _record_overlap("wait_s", waited)
            if _sink_active():
                _emit_event(
                    "exchange_wait", tag=self._tag, wait_s=waited
                )
            _, lock = _exchange_state()
            with lock:
                _PENDING_EXCHANGES[:] = [
                    e for e in _PENDING_EXCHANGES
                    if e[0] is not self._future
                ]
        self._future = None
        self._value = out
        return out


def drain_async_exchanges() -> None:
    """Wait for every in-flight async exchange (results stay claimable
    through their handles). A SYNCHRONOUS p2p exchange must not touch
    the sockets while the worker is mid-frame, and submission order is
    the cross-process consistency invariant — so the sync path drains
    first, preserving one global socket-use order.

    A worker exception observed here is RECORDED (``exchange_drain_
    error`` event + ``p2p.exchange_drain_errors`` counter) before being
    left for the owner handle to re-raise — previously it was swallowed
    bare, so a failed background exchange whose handle was never polled
    was invisible in ``report fleet``. A failed entry is dropped from
    the pending list on first observation (the handle keeps its own
    future reference, so ``result()`` still re-raises) — otherwise
    every later drain would re-wait and re-report the same failure."""
    _, lock = _exchange_state()
    with lock:
        pending = list(_PENDING_EXCHANGES)
    for entry in pending:
        f, tag = entry
        try:
            exc = f.exception()  # waits; the owner handle re-raises on
            # result() — this is observation, not consumption
        except Exception as e:
            exc = e
        if exc is not None:
            with lock:
                if entry in _PENDING_EXCHANGES:
                    _PENDING_EXCHANGES.remove(entry)
            from photon_ml_tpu.obs.metrics import REGISTRY

            REGISTRY.counter_inc("p2p.exchange_drain_errors")
            _emit_event(
                "exchange_drain_error", tag=tag,
                error=type(exc).__name__,
                peer=getattr(exc, "peer", None),
            )


def reset_async_exchanges() -> None:
    """Forget every pending async-exchange record without waiting.
    Recovery calls this after a peer loss: the failed attempt's handles
    are abandoned wholesale, and leaving their futures in the pending
    list would make every later drain re-wait and re-report them."""
    _, lock = _exchange_state()
    with lock:
        _PENDING_EXCHANGES.clear()


def confirm_peer_loss(err) -> tuple[list[int], list[int], list[int]]:
    """The loss-confirmation preamble every ``PeerLost`` recovery tier
    shares (the streamed fit's checkpoint re-entry, the in-memory
    descent's in-place degrade): count + emit the suspected loss, drop
    the failed attempt's abandoned async exchanges, roll-call the
    CURRENT group and return ``(group, survivors, lost)`` — an empty
    ``lost`` means every peer answered (a link flap, not a death) and
    the caller should retry rather than degrade. One shared helper so
    the tiers cannot drift on what "confirming a loss" means."""
    from photon_ml_tpu.obs.metrics import REGISTRY

    REGISTRY.counter_inc("fleet.peer_lost")
    _emit_event(
        "peer_lost", peer=int(getattr(err, "peer", -1)), error=str(err)
    )
    reset_async_exchanges()
    dg = degraded_group()
    group = (
        list(dg["survivors"]) if dg is not None
        else list(range(original_process_count()))
    )
    survivors = roll_call()
    lost = sorted(set(group) - set(survivors))
    return group, survivors, lost


def exchange_rows_async(
    arrays, dest: np.ndarray, tag: str = ""
) -> ExchangeHandle:
    """Issue ``exchange_rows`` without blocking: returns a handle whose
    ``result()`` yields the identical received-rows layout. Transport is
    ALWAYS the framed host P2P path (collective-free — the worker thread
    must never run a jax collective; padding-free — the schedule exists
    for the skewed configs where all_to_all padding is pathological).
    The socket mesh is built (collectively) on the CALLING thread at
    first use, so the collective stays in program order. Single process:
    completes inline (identity)."""
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    P_ = effective_process_count()
    if P_ <= 1:
        LAST_EXCHANGE_STATS.update(
            bytes_sent=0, rows_sent=len(dest), padded_rows=len(dest),
            transport="local",
        )
        # inline identity still contributes (zero-wait) overlap samples,
        # so the gauge exists on every topology the schedule runs on
        _record_overlap("exchange_s", 0.0)
        _record_overlap("wait_s", 0.0)
        return ExchangeHandle(value=arrays)
    dest = np.asarray(dest, np.int64)
    order = np.argsort(dest, kind="stable")
    counts = np.bincount(dest, minlength=P_).astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(counts)])
    _host_links()  # collective bootstrap happens HERE, in program order
    pool, lock = _exchange_state()

    def run():
        import time as _time

        t0 = _time.perf_counter()
        try:
            return _host_p2p_exchange(
                arrays, order, starts, counts_matrix=None,
                transport="p2p_host_async", tag=tag,
            )
        finally:
            dur = _time.perf_counter() - t0
            _record_overlap("exchange_s", dur)
            if _sink_active():
                _emit_event("exchange", tag=tag, dur_s=dur)

    fut = pool.submit(run)
    with lock:
        _PENDING_EXCHANGES.append((fut, tag))
    return ExchangeHandle(future=fut, tag=tag)


class ObjCollectiveHandle:
    """A pending ``allgather_obj_p2p_async``. ``result()`` blocks until
    the allgather lands and returns the per-rank list. Unlike
    ``ExchangeHandle`` it records nothing into the ``re_exchange.*``
    overlap accounting — owner-segment callers keep their own
    ``re_combine.*`` books (mixing the two would skew the exchange
    overlap gauge the sharded-solve sweeps gate on)."""

    def __init__(self, future=None, value=None, tag: str = ""):
        self._future = future
        self._value = value
        self._tag = tag

    def result(self) -> list:
        if self._future is None:
            return self._value
        try:
            out = self._future.result()
        finally:
            _, lock = _exchange_state()
            with lock:
                _PENDING_EXCHANGES[:] = [
                    e for e in _PENDING_EXCHANGES
                    if e[0] is not self._future
                ]
        self._future = None
        self._value = out
        return out


def allgather_obj_p2p_async(
    obj, tag: str = "host_collective", stats: dict | None = None
) -> ObjCollectiveHandle:
    """Issue ``allgather_obj_p2p`` on the dedicated exchange worker:
    the frames go on the wire while the caller keeps working (the
    owner-segment combine overlaps its diagnostics readback under the
    coefficient-segment send). Same discipline as
    ``exchange_rows_async``: the mesh bootstrap (collective on first
    use) happens on the CALLING thread in program order, the body runs
    on the single worker in strict submission order, and the pending
    entry keeps every synchronous socket user draining behind it.
    ``stats`` is filled by the worker (byte accounting plus
    ``exchange_s``, the worker-side wall) before the handle resolves.
    Single process: completes inline (identity)."""
    P_ = effective_process_count()
    if P_ <= 1:
        if stats is not None:
            stats.update(
                payload_bytes=0, bytes_sent=0, bytes_recv=0,
                exchange_s=0.0,
            )
        return ObjCollectiveHandle(value=[obj], tag=tag)
    _host_links()  # collective bootstrap HERE, in program order
    pool, lock = _exchange_state()

    def run():
        t0 = time.perf_counter()
        try:
            return _p2p_allgather_obj(
                obj, tag=tag, drain=False, stats=stats
            )
        finally:
            if stats is not None:
                stats["exchange_s"] = time.perf_counter() - t0

    fut = pool.submit(run)
    with lock:
        _PENDING_EXCHANGES.append((fut, tag))
    return ObjCollectiveHandle(future=fut, tag=tag)


# -- elastic rejoin (knob PHOTON_REJOIN) -------------------------------------
#
# The degrade half shrinks the world in place; this half grows it back.
# A process lost to the fleet re-execs (the ``rejoin`` fault spec, or an
# operator restart), reloads its ORIGINAL identity and the cached mesh
# addresses from the persisted mesh cache (knob ``PHOTON_MESH_CACHE``),
# binds its recorded port and WAITS to be invited. The surviving group,
# at a visit boundary, probes the lost peers' cached addresses; a
# listening rejoiner gets an INVITE naming the candidate set, then both
# sides run one barrier-tagged rejoin roll call (``roll_call`` with
# ``candidates`` = survivors + rejoiners, quorum guarded by the CURRENT
# group) and the agreed, expanded group continues over the framed-P2P
# mesh — the jax collective cohort is never re-entered (a fresh runtime
# cannot rejoin it), which is exactly why every group-shaped helper in
# this module routes host-side once degraded.


def rejoin_enabled() -> bool:
    """``PHOTON_REJOIN`` (strict int parse; default 0 = lost peers stay
    lost, today's behavior byte-for-byte)."""
    env = os.environ.get("PHOTON_REJOIN")
    if env is not None and env != "":
        return int(env) != 0
    return False


def rejoin_window_s() -> float:
    """``PHOTON_REJOIN_WINDOW_S`` (seconds, strict float parse; default
    10): how long the fleet lingers for returning peers at the FIRST
    visit boundary after a degrade (and how long a booting rejoiner
    waits for its invite). Later boundaries use instant probes, so a
    peer that never returns costs one connect-refused per boundary."""
    env = os.environ.get("PHOTON_REJOIN_WINDOW_S")
    if env is not None and env != "":
        return max(float(env), 0.0)
    return 10.0


def _mesh_cache_path() -> str | None:
    """``PHOTON_MESH_CACHE``: file path the first mesh build persists
    its ``{pid: (ip, port)}`` table to (atomically), and a rejoin boot
    reloads it from. Unset (default) = nothing is written — the
    pre-rejoin behavior byte-for-byte."""
    return os.environ.get("PHOTON_MESH_CACHE") or None


def _maybe_persist_mesh_addrs() -> None:
    """Persist the freshly-bootstrapped address table for future
    rejoiners. Every process writes (atomic replace — on a shared
    filesystem the copies are identical; on split filesystems each
    host keeps its own). Never fatal: the cache is an enabler for
    rejoin, not a correctness dependency of the healthy path."""
    path = _mesh_cache_path()
    if path is None or _HOST_ADDRS is None:
        return
    try:
        import json

        from photon_ml_tpu.utils.atomic_io import atomic_replace_bytes

        doc = {
            "world": _world_size(),
            "addrs": {
                str(p): [ip, int(port)]
                for p, (ip, port) in sorted(_HOST_ADDRS.items())
            },
        }
        atomic_replace_bytes(
            os.path.dirname(path) or ".", path, json.dumps(doc).encode()
        )
    except Exception:
        _emit_event("mesh_cache_write_failed", path=path)


def bootstrap_rejoin(pid: int | None = None, path: str | None = None) -> dict:
    """Adopt a lost process's ORIGINAL identity in a fresh interpreter:
    load the persisted mesh-address cache, record ``(pid, world)`` as
    this process's identity (``jax.process_index/count`` are 0/1 here —
    the fresh runtime never joined the original cohort), and leave the
    process ready for ``rejoin_wait``. ``pid`` defaults to the
    ``PHOTON_REJOIN_BOOT`` env var the ``rejoin`` fault spec plants in
    the re-exec'd child."""
    global _HOST_ADDRS, _REJOIN_IDENTITY
    import json

    if pid is None:
        env = os.environ.get("PHOTON_REJOIN_BOOT")
        if not env:
            raise RuntimeError(
                "bootstrap_rejoin needs the original process index: pass "
                "pid= or set PHOTON_REJOIN_BOOT"
            )
        pid = int(env)
    path = path or _mesh_cache_path()
    if path is None:
        raise RuntimeError(
            "bootstrap_rejoin needs the persisted mesh cache: set "
            "PHOTON_MESH_CACHE (the same path the original fleet ran "
            "with) or pass path="
        )
    with open(path) as f:
        doc = json.load(f)
    addrs = {
        int(p): (str(ip), int(port))
        for p, (ip, port) in doc["addrs"].items()
    }
    if pid not in addrs:
        raise RuntimeError(
            f"mesh cache {path!r} has no address for process {pid} "
            f"(recorded: {sorted(addrs)})"
        )
    _reset_host_links()
    _HOST_ADDRS = addrs
    _REJOIN_IDENTITY = {"pid": int(pid), "world": int(doc["world"])}
    _emit_event("rejoin_boot", pid=int(pid), world=int(doc["world"]))
    return dict(_REJOIN_IDENTITY)


# hello-int version values reserved for the rejoin rendezvous (the mesh
# frame protocol uses 0/1, so these can never be mistaken for a build
# hello's version — and a rejoiner can tell a roll-call dial from an
# invite and stay out of a build it was not named in)
_HELLO_PROBE = 0x7D
_HELLO_INVITE = 0x7E


def probe_rejoiners(
    lost: Sequence[int], window_s: float = 0.0, poll_s: float = 0.25
) -> list[int]:
    """Which of the ``lost`` original pids are back and listening on
    their recorded mesh address (rank-0 survivor side; the result must
    be broadcast over the group before acting on it — probing is
    per-process I/O, not a collective). A probe is one cheap connect +
    2-word handshake; refused/timed out = not back yet. ``window_s``
    lingers, re-polling every ``poll_s``, until at least one rejoiner
    answers or the window closes."""
    import socket
    import struct

    if _HOST_ADDRS is None:
        return []
    deadline = time.monotonic() + max(window_s, 0.0)
    present: list[int] = []
    while True:
        for p in lost:
            if p in present or p not in _HOST_ADDRS:
                continue
            try:
                with socket.create_connection(
                    _HOST_ADDRS[p], timeout=0.5
                ) as s:
                    s.settimeout(2.0)
                    s.sendall(struct.pack(
                        "!i", _self_pid() | (_HELLO_PROBE << 16)
                    ))
                    if _recv_exact(s, 1) == _ACK_BYTE:
                        present.append(p)
            except OSError:
                continue
        if present or time.monotonic() >= deadline:
            return sorted(present)
        time.sleep(poll_s)


def send_rejoin_invites(
    present: Sequence[int], candidates: Sequence[int],
    survivors: Sequence[int],
) -> list[int]:
    """Deliver the rejoin invitation (candidate set + current survivor
    set — everything a rejoiner needs to enter the SAME roll call the
    survivors are about to run) to each probed-present rejoiner.
    Returns the pids that ACKed; a rejoiner that died between probe and
    invite simply drops out of the roll call like any unreachable
    candidate."""
    import pickle
    import socket
    import struct

    invited: list[int] = []
    payload = pickle.dumps(
        {
            "candidates": [int(c) for c in sorted(candidates)],
            "survivors": [int(s) for s in sorted(survivors)],
        },
        protocol=4,
    )
    for p in present:
        try:
            with socket.create_connection(
                _HOST_ADDRS[p], timeout=2.0
            ) as s:
                s.settimeout(5.0)
                s.sendall(struct.pack(
                    "!i", _self_pid() | (_HELLO_INVITE << 16)
                ))
                s.sendall(struct.pack("!q", len(payload)))
                s.sendall(payload)
                if _recv_exact(s, 1) == _ACK_BYTE:
                    invited.append(int(p))
        except OSError:
            continue
    return invited


def rejoin_wait(window_s: float | None = None) -> dict | None:
    """Rejoiner side of the rendezvous: bind this process's RECORDED
    mesh port, answer probes, and wait up to ``window_s`` for an
    invite. Returns the invite payload (``candidates`` + ``survivors``)
    or None when the window closes uninvited.

    A dial that is NOT a probe/invite — a degrade roll call racing this
    boot reaches the same recorded port — is closed unanswered: the
    rejoiner must not wedge a mesh build it was not named in (the
    build's accept count then falls short and the roll call drops this
    pid for that round; a later boundary re-invites it). The listener
    is closed before returning, so the rejoin roll call can re-bind the
    port."""
    import pickle
    import socket
    import struct

    if _REJOIN_IDENTITY is None or _HOST_ADDRS is None:
        raise RuntimeError(
            "rejoin_wait outside a rejoin boot: call bootstrap_rejoin "
            "first"
        )
    if window_s is None:
        window_s = rejoin_window_s()
    own_port = _HOST_ADDRS[_self_pid()][1]
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        srv.bind(("0.0.0.0", own_port))
        srv.listen(8)
        deadline = time.monotonic() + max(window_s, 0.0)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            srv.settimeout(min(remaining, 1.0))
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            try:
                conn.settimeout(5.0)
                raw = struct.unpack("!i", _recv_exact(conn, 4))[0]
                src, kind = _decode_hello(raw)
                if kind == _HELLO_PROBE:
                    conn.sendall(_ACK_BYTE)
                    continue
                if kind != _HELLO_INVITE:
                    # a mesh/roll-call build dialing our recorded port:
                    # close unanswered (see docstring)
                    continue
                n = struct.unpack("!q", _recv_exact(conn, 8))[0]
                payload = pickle.loads(_recv_exact(conn, n))
                conn.sendall(_ACK_BYTE)
                _emit_event(
                    "rejoin_invited", inviter=int(src),
                    candidates=payload.get("candidates"),
                    survivors=payload.get("survivors"),
                )
                return payload
            except OSError:
                continue
            finally:
                _close_quietly(conn)
    finally:
        _close_quietly(srv)


def allreduce_max_host(*arrays: np.ndarray):
    """Elementwise max across ALL processes of the current group
    (identity on one process). Used by the streamed feature summary for
    min/max statistics (min rides as max of the negation)."""
    if effective_process_count() <= 1:
        return arrays if len(arrays) > 1 else arrays[0]
    if _DEGRADED is not None:
        gathered = _p2p_allgather_obj(
            tuple(np.asarray(a) for a in arrays), tag="allreduce_max"
        )
        maxed = tuple(
            np.max(np.stack([g[i] for g in gathered]), axis=0)
            for i in range(len(arrays))
        )
        return maxed if len(maxed) > 1 else maxed[0]
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(arrays)  # each: (P, ...)
    maxed = tuple(np.max(np.asarray(a), axis=0) for a in stacked)
    return maxed if len(maxed) > 1 else maxed[0]
