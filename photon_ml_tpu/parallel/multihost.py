"""Multi-host runtime scaffolding.

Reference parity: the reference scales out on a Spark cluster — a driver
plus executors on many hosts, with the cluster manager handling membership
and the shuffle service moving data (SURVEY.md §2.6 Spark-replacement
table). The TPU-native replacement is ``jax.distributed``: every host runs
the SAME program, ``jax.distributed.initialize`` wires the processes into
one runtime, ``jax.devices()`` becomes the GLOBAL device list, and a mesh
built over it spans the whole slice — XLA then routes collectives over
ICI within a host/pod and DCN across pods. No driver, no shuffle: each
host reads its own slice of the input (``host_shard_of_paths``) and
assembles its rows into a globally-sharded array
(``global_batch_from_host_shards``).

Usage (same command on every host, e.g. under GKE/xmanager):

    python -m photon_ml_tpu.cli.train ... --multihost

with the coordinator address/process count/process id taken from the
standard env vars (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
``JAX_PROCESS_ID``) or auto-detected on TPU pods (GCE metadata).
"""

from __future__ import annotations

import os
import time
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Join this process into the multi-host runtime.

    Arguments default to the standard env vars / TPU-pod auto-detection
    (``jax.distributed.initialize`` semantics). Returns a summary dict
    (process index/count, local/global device counts) for logging. Safe to
    call on a single host only when explicit arguments or env vars are set;
    plain single-host runs should simply not call this.
    """
    # resolve the standard env vars ourselves — jax.distributed auto-detects
    # only inside known cluster environments (TPU pods, SLURM, …)
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        raise RuntimeError(
            "multihost initialization failed — on non-auto-detected "
            "clusters set JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES and "
            "JAX_PROCESS_ID (or pass them explicitly); on a single host, "
            f"drop --multihost. Underlying error: {e}"
        ) from e
    return runtime_summary()


def runtime_summary() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def host_shard_of_paths(paths: Sequence[str]) -> list[str]:
    """The input files THIS host reads: a round-robin slice of the sorted
    path list by process index (the reference's executor partition
    assignment, without a shuffle service). Every path must be visible to
    every host (shared filesystem / object store), but each is read once
    globally."""
    ordered = sorted(paths)
    return ordered[jax.process_index() :: jax.process_count()]


def global_batch_from_host_shards(local_arrays, mesh: Mesh, axis_name: str = "data"):
    """Assemble per-host row blocks into ONE globally row-sharded pytree.

    Each process passes its own ``local_arrays`` (a pytree of host numpy
    arrays with identical structure and per-host row counts that sum to the
    global batch); ``jax.make_array_from_process_local_data`` builds global
    arrays whose addressable shards hold this host's rows — no host ever
    materializes the global batch (SURVEY.md §7: the 1B-row path).
    """
    sharding = NamedSharding(mesh, P(axis_name))

    def to_global(a):
        a = np.asarray(a)
        return jax.make_array_from_process_local_data(sharding, a)

    return jax.tree.map(to_global, local_arrays)


def shard_batch_multihost(local_batch, mesh: Mesh, axis_name: str = "data"):
    """Multi-host twin of ``parallel.distributed.shard_batch``: every host
    contributes ITS OWN rows (from its slice of the input files) and the
    result is one globally row-sharded ``Batch`` — no host ever holds the
    global data.

    Hosts may have unequal row counts; each pads with zero-weight rows
    (inert in the objective) to the global per-host maximum, rounded up so
    the global row count divides the mesh's data axis.
    """
    from jax.experimental import multihost_utils

    from photon_ml_tpu.ops.batch import pad_batch

    n_local = local_batch.num_rows
    counts = multihost_utils.process_allgather(np.asarray([n_local]))
    per_host = int(np.max(counts))
    devs_per_host = max(len(jax.local_devices()), 1)
    per_host = -(-per_host // devs_per_host) * devs_per_host
    local = pad_batch(local_batch, per_host)
    return global_batch_from_host_shards(
        jax.tree.map(np.asarray, local), mesh, axis_name
    )


def is_output_process() -> bool:
    """True on the single process that writes shared outputs (models,
    metrics, checkpoints). All hosts COMPUTE; exactly one host WRITES —
    concurrent writers to shared storage interleave and corrupt files."""
    return jax.process_index() == 0


# per-call monotonic barrier suffix: every process calls sync_processes
# at the same program points in the same order, so the counters agree —
# and two overlapping barriers carrying the SAME caller tag (possible
# once the pipelined exchange schedule defers work past a barrier site)
# can no longer alias each other inside the runtime's key-matched
# barrier bookkeeping.
_BARRIER_SEQ = [0]


def sync_processes(tag: str = "photon-ml-barrier") -> None:
    """Barrier across all processes (e.g. before reading files another
    process wrote). No-op on a single process. The wire tag is
    ``{tag}#{n}`` with ``n`` a per-process monotonic call counter
    (identical across processes by the matched-call-order requirement
    every collective already has), so repeated barriers under one caller
    tag are distinct barrier keys."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        _BARRIER_SEQ[0] += 1
        multihost_utils.sync_global_devices(f"{tag}#{_BARRIER_SEQ[0]}")


def broadcast_from_host0(pytree):
    """Every process receives process 0's value of ``pytree`` (host numpy
    leaves; identity on a single process). The pytree STRUCTURE must be
    identical on every process — only leaf values may differ. Used to make
    checkpoint-resume decisions (and restored state) consistent when hosts
    do not share an output filesystem."""
    if jax.process_count() <= 1:
        return pytree
    from jax.experimental import multihost_utils

    out = multihost_utils.broadcast_one_to_all(pytree)
    return jax.tree.map(np.asarray, out)


def allgather_row_chunks(arrays, chunk_rows: int, pad_values=None):
    """Chunk-wise all-to-all of per-host row blocks (the TPU-native stand-in
    for the reference's Spark shuffle, done on HOSTS over DCN).

    ``arrays`` is a dict of same-leading-dim host numpy arrays (this host's
    rows). Yields one round at a time: a dict of ``(P, chunk_rows, ...)``
    stacked arrays holding EVERY process's chunk — the receiver filters the
    rows it owns and frees the round before the next, so peak memory is
    O(P · chunk_rows), never O(global rows). Hosts with fewer rows pad
    trailing rounds (``pad_values[k]``, default 0 — pick a sentinel the
    receiver can filter, e.g. -1 entity ids). Every process yields the SAME
    number of rounds (a collective requirement).
    """
    from jax.experimental import multihost_utils

    pad_values = dict(pad_values or {})
    keys = list(arrays)
    n_loc = len(arrays[keys[0]]) if keys else 0
    counts = np.asarray(
        multihost_utils.process_allgather(np.asarray([n_loc]))
    ).reshape(-1)
    rounds = int(-(-int(counts.max()) // chunk_rows)) if counts.max() else 0
    for r in range(rounds):
        lo = r * chunk_rows
        hi = min(lo + chunk_rows, n_loc)
        chunk = {}
        for k in keys:
            a = np.asarray(arrays[k])
            part = a[lo:hi] if lo < n_loc else a[:0]
            pad = chunk_rows - len(part)
            if pad:
                fill = np.full(
                    (pad,) + a.shape[1:], pad_values.get(k, 0), a.dtype
                )
                part = np.concatenate([part, fill])
            chunk[k] = part
        gathered = multihost_utils.process_allgather(chunk)
        yield {k: np.asarray(v) for k, v in gathered.items()}


def allreduce_sum_host(*arrays: np.ndarray):
    """Sum numpy arrays across ALL processes (returns them unchanged on a
    single process). Used by the streaming objective to combine per-host
    partial (value, gradient) sums — the treeAggregate analog for the
    out-of-core path."""
    if jax.process_count() <= 1:
        return arrays if len(arrays) > 1 else arrays[0]
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(arrays)  # each: (P, ...)
    summed = tuple(np.sum(np.asarray(a), axis=0) for a in stacked)
    return summed if len(summed) > 1 else summed[0]


# running counters for the LAST exchange_rows call (tests assert the
# per-visit traffic is O(owned rows), not O(P * rows) — VERDICT r3 weak #5)
LAST_EXCHANGE_STATS: dict = {}

_PROC_MESH = None


def _process_mesh():
    """A 1-D mesh with ONE device per process (each process's first local
    device) — the lane for host-to-host all_to_all exchanges."""
    global _PROC_MESH
    if _PROC_MESH is None:
        from jax.sharding import Mesh

        P_ = jax.process_count()
        by_proc: dict[int, object] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        _PROC_MESH = Mesh(
            np.array([by_proc[p] for p in range(P_)]), ("proc",)
        )
    return _PROC_MESH


_A2A_JIT = None


def _all_to_all_jit():
    """One cached jitted all_to_all program (jit handles shape/dtype
    polymorphism through its own cache; rebuilding the shard_map per call
    would recompile every exchange). Audited for per-call re-trace:
    the mesh object, the shard_map closure and the jit wrapper are all
    process-lifetime singletons, so repeated exchanges with identical
    (shape, dtype) reuse ONE executable — asserted by the cache-growth
    test in tests/test_multihost.py (``_a2a_cache_size``)."""
    global _A2A_JIT
    if _A2A_JIT is None:
        try:  # jax.experimental.shard_map moved in newer jax releases
            from photon_ml_tpu.utils.compat import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        _A2A_JIT = jax.jit(
            shard_map(
                lambda x: jax.lax.all_to_all(
                    x, "proc", split_axis=0, concat_axis=0, tiled=True
                ),
                mesh=_process_mesh(),
                in_specs=P("proc"),
                out_specs=P("proc"),
            )
        )
    return _A2A_JIT


def _a2a_cache_size() -> int:
    """Number of compiled variants behind the cached all_to_all jit —
    the executable-reuse tripwire: coordinate descent re-enters the
    exchange with identical shapes every visit, so this must stay FLAT
    across repeated same-shape calls (growth = a re-trace regression
    that would recompile the exchange every visit)."""
    if _A2A_JIT is None:
        return 0
    try:
        return int(_A2A_JIT._cache_size())
    except AttributeError:  # very old jax: no public cache introspection
        return 0


def exchange_rows(arrays, dest: np.ndarray, tag: str = ""):
    """Deliver row ``i`` of every array to process ``dest[i]`` — the
    point-to-point shuffle the reference does with a Spark exchange.

    Unlike ``allgather_row_chunks`` (every row to EVERY host: O(P·n)
    traffic), this routes each row only to its destination. Two transports,
    chosen per call from the globally-consistent (P, P) bucket-count
    matrix:

    - **Balanced** (padded allocation ≤ 2× payload): one
      ``lax.all_to_all`` over the process mesh — rides ICI on pods, one
      compiled program re-entered when per-visit counts are stable.
      SPMD collectives require UNIFORM (source, dest) block sizes, so
      every bucket pads to the global max — fine when destinations are
      balanced, structurally O(P×payload) under entity skew (one hot
      entity ⇒ one hot owner ⇒ one huge bucket sets every bucket's pad).
    - **Skewed** (padding would exceed 2× payload): a host-side TCP
      point-to-point exchange (``_host_p2p_exchange``) sending each
      bucket EXACTLY — zero padding under any skew, the direct analog of
      the reference's Netty shuffle riding DCN (SURVEY §2.7). Per-host
      traffic is O(rows sent + rows owned) always.

    Returns a dict of received rows (grouped by source process, sources in
    ascending order — every process receives with the same layout rule, so
    the result is deterministic and transport-independent). Single
    process: identity. All processes must call this collectively with the
    same key set. ``tag`` labels the exchange in telemetry (the per-link
    ``p2p_send``/``p2p_recv`` events of the framed transport carry it);
    it never affects routing or results.
    """
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    P_ = jax.process_count()
    if P_ <= 1:
        LAST_EXCHANGE_STATS.update(
            bytes_sent=0, rows_sent=len(dest), padded_rows=len(dest),
            transport="local",
        )
        return arrays
    from jax.experimental import multihost_utils as mhu
    from jax.sharding import PartitionSpec as P

    dest = np.asarray(dest, np.int64)
    order = np.argsort(dest, kind="stable")
    counts = np.bincount(dest, minlength=P_).astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(counts)])
    # every process learns every (source, destination) bucket size — a
    # (P, P) int matrix, negligible next to the row payload
    counts_matrix = np.asarray(
        mhu.process_allgather(counts)
    ).reshape(P_, P_)
    maxc = max(int(counts_matrix.max()), 1)

    # transport decision — identical on every process (counts_matrix is):
    # all_to_all allocates P·maxc slots per process against its
    # counts.sum() real rows; beyond 2× padding, go point-to-point.
    total_payload = max(int(counts_matrix.sum()), 1)
    if P_ * P_ * maxc > 2 * total_payload:
        # one global socket-use order: never interleave with an in-flight
        # worker-thread exchange mid-frame (no-op when none are pending)
        drain_async_exchanges()
        return _host_p2p_exchange(
            arrays, order, starts, counts_matrix, tag=tag
        )

    from photon_ml_tpu.obs import devcost

    mesh = _process_mesh()
    pid = jax.process_index()
    out: dict[str, np.ndarray] = {}
    bytes_sent = 0
    for key in sorted(arrays):
        a = arrays[key]
        feat = a.shape[1:]
        local = np.zeros((P_, maxc) + feat, a.dtype)
        for p in range(P_):
            rows = order[starts[p]:starts[p + 1]]
            local[p, : len(rows)] = a[rows]
        bytes_sent += local.nbytes
        g = mhu.host_local_array_to_global_array(local, mesh, P("proc"))
        swapped = _all_to_all_jit()(g)
        # analytic cost of the exchange-adjacent executable, captured
        # AFTER the collective ran: the capture's AOT compile happens on
        # the sink-holding process only, and doing it before the call
        # would park every peer mid-collective behind that compile. One
        # capture per fresh (shape, dtype) — the devcost layer dedups.
        devcost.capture("multihost.all_to_all", _all_to_all_jit(), (g,))
        recv = np.asarray(
            mhu.global_array_to_host_local_array(swapped, mesh, P("proc"))
        )  # (P, maxc, *feat): slice s = rows from source s
        out[key] = np.concatenate(
            [recv[s, : counts_matrix[s, pid]] for s in range(P_)]
        )
    LAST_EXCHANGE_STATS.update(
        bytes_sent=bytes_sent,
        rows_sent=int(counts.sum()),
        padded_rows=P_ * maxc * len(arrays),
        transport="all_to_all",
    )
    return out


# lazily-built full TCP mesh between processes for the skewed-exchange
# transport: {"send": {peer: socket}, "recv": {peer: socket}}
_HOST_LINKS: dict | None = None

# per-link frame-set sequence counters for TELEMETRY CORRELATION: the
# framed exchange's submission-order invariant (every process issues the
# same exchange sequence at the same program points) means the k-th
# frame-set SENT on link i→j is exactly the k-th frame-set RECEIVED on
# that link at j — so both ends derive the same correlation id
# ``p2p:<src>><dst>#<k>`` with zero extra bytes on the wire, and
# ``report fleet`` joins each link's send/recv events across shard
# files by that id (one-sided wait = recv-start − send-start).
# Incremented UNCONDITIONALLY (not sink-gated): a process whose sink
# activates mid-sequence must still agree with its peers on k.
_LINK_SEQ: dict = {"send": {}, "recv": {}}


def _next_link_seq(direction: str, peer: int) -> int:
    seqs = _LINK_SEQ[direction]
    seqs[peer] = seqs.get(peer, 0) + 1
    return seqs[peer]


def _sink_active() -> bool:
    """Whether telemetry is on (cheap; the exchange hot path must stay
    byte-identical when it is not)."""
    try:
        from photon_ml_tpu.obs import sink as _sink

        return _sink.is_active()
    except Exception:
        return False


def _emit_event(event: str, **payload) -> None:
    try:
        from photon_ml_tpu.obs.spans import emit_event

        emit_event(event, **payload)
    except Exception:
        pass  # telemetry must never take down the exchange it observes


def _reset_host_links() -> None:
    """Close every cached exchange socket and drop THIS process's mesh so
    its next exchange rebuilds from scratch. Called on ANY
    ``_host_p2p_exchange`` error: after a partial send/receive the
    length-prefix framing on the surviving streams is undefined (a retry
    would read payload bytes as a prefix and silently mis-frame
    everything after), so the only safe local state is no mesh at all.
    The reset is per-process by construction (an error such as a size
    mismatch may be raised on one host only); peers discover it FAIL-FAST
    on their next exchange — their sends/receives against the closed
    sockets error instead of mis-framing — which resets them too, so a
    caller-level collective retry converges to a full mesh rebuild."""
    global _HOST_LINKS
    links, _HOST_LINKS = _HOST_LINKS, None
    # correlation counters restart with the mesh: after a teardown both
    # ends rebuild and resynchronize at frame-set 1 (frames lost to the
    # error surface as UNMATCHED send/recv events in ``report fleet`` —
    # the telemetry-health signal, by design)
    _LINK_SEQ["send"] = {}
    _LINK_SEQ["recv"] = {}
    if not links:
        return
    for side in ("send", "recv"):
        for sock in links.get(side, {}).values():
            try:
                sock.close()
            except OSError:
                pass


def _coordinator_address() -> str:
    """The ``jax.distributed`` coordinator address: the standard env var
    when set, else JAX's own distributed global state (the runtime knows
    its coordinator even when it was wired up by pod auto-detection or
    explicit ``initialize`` arguments — the env var is absent on exactly
    those paths)."""
    target = os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    if target:
        return target
    try:
        from jax._src import distributed as _distributed

        return getattr(_distributed.global_state, "coordinator_address", None) or ""
    except Exception:
        return ""


def _is_loopback(ip: str) -> bool:
    return ip.startswith("127.") or ip in ("0.0.0.0", "localhost", "::1")


def _coordinator_is_loopback(host: str) -> bool:
    """True when the coordinator host is loopback — literally, or through
    DNS/hosts resolution (the single-machine harness may pass the
    machine's own hostname, which stock Debian/Ubuntu maps to
    127.0.1.1)."""
    if not host:
        return False
    if _is_loopback(host):
        return True
    import socket

    try:
        return _is_loopback(socket.gethostbyname(host))
    except OSError:
        return False


def _local_ip() -> str:
    """This host's address as peers should dial it. Override with
    ``PHOTON_EXCHANGE_HOST`` to pin a specific NIC. Otherwise discover the
    OUTBOUND interface by UDP-connecting toward the ``jax.distributed``
    coordinator (env var or the runtime's own global state; no packet is
    sent — the kernel just picks the route) —
    ``gethostbyname(gethostname())`` is NOT used because stock
    Debian/Ubuntu ``/etc/hosts`` maps the hostname to 127.0.1.1, which
    would advertise an undialable loopback to remote peers.

    A discovered LOOPBACK address with ``process_count > 1`` under a
    non-loopback (or unknown) coordinator fails FAST: advertising it would
    make every remote peer dial itself and hang the mesh build until the
    300 s socket timeout. A loopback COORDINATOR means every process lives
    on this machine (a remote process could not have reached it), so
    loopback peers are dialable and the single-machine multi-process test
    harness keeps working."""
    explicit = os.environ.get("PHOTON_EXCHANGE_HOST")
    if explicit:
        return explicit
    import socket

    target = _coordinator_address()
    host = target.rsplit(":", 1)[0] if target else ""

    # any non-loopback discovery returns immediately; one loopback result
    # only means THAT probe routed locally (e.g. the coordinator hostname
    # mapped to 127.0.1.1 via /etc/hosts — the later 8.8.8.8 probe still
    # finds the real NIC), so keep probing and fail fast only once EVERY
    # source has come up loopback
    last = "127.0.0.1"
    for probe in filter(None, [host, "8.8.8.8"]):
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect((probe, 53))
                ip = s.getsockname()[0]
        except OSError:
            continue
        if not _is_loopback(ip):
            return ip
        last = ip
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if not _is_loopback(ip):
            return ip
        last = ip
    except OSError:
        pass
    if jax.process_count() > 1 and not _coordinator_is_loopback(host):
        raise RuntimeError(
            f"host exchange address discovery found only loopback {last!r} "
            f"with process_count={jax.process_count()}: remote peers "
            "cannot dial it (the mesh build would hang until the "
            "300 s timeout). Set PHOTON_EXCHANGE_HOST to this host's "
            "reachable address."
        )
    return last


def _p2p_timeout_s() -> float | None:
    """Socket timeout for the host P2P exchange mesh, knob
    ``PHOTON_P2P_TIMEOUT_S`` (seconds; generous default — exchanges move
    real payload over slow DCN links, and a false-positive timeout tears
    the mesh down; ``0`` or negative disables the timeout entirely, the
    usual knob convention, restoring blocking sockets). Applied to EVERY
    socket operation of the mesh — accept, connect, send, recv — so a
    dead or silent peer raises ``socket.timeout`` instead of hanging the
    exchange forever; the error then reaches the existing
    ``_reset_host_links`` teardown and the caller's retry rebuilds the
    mesh."""
    env = os.environ.get("PHOTON_P2P_TIMEOUT_S")
    if env is not None and env != "":
        v = float(env)
        return v if v > 0 else None
    return 300.0


def _configure_link_socket(sock) -> None:
    """Apply the exchange-mesh socket policy: the knob timeout (no socket
    in the mesh may block forever) and TCP_NODELAY (length-prefixed small
    frames must not wait on Nagle)."""
    import socket

    sock.settimeout(_p2p_timeout_s())
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


def _p2p_heartbeat_s() -> float | None:
    """Blocked-recv heartbeat cadence, knob ``PHOTON_P2P_HEARTBEAT_S``
    (seconds; ``0`` or negative disables). While a framed-P2P recv is
    blocked on a silent peer, the exchange emits one rate-limited
    ``p2p_heartbeat`` telemetry event per interval — so a stuck link is
    visible (with its peer, tag and blocked seconds) in the run's shard
    file long before the ``PHOTON_P2P_TIMEOUT_S`` abort (default 300 s)
    tears the mesh down."""
    env = os.environ.get("PHOTON_P2P_HEARTBEAT_S")
    if env is not None and env != "":
        v = float(env)
        return v if v > 0 else None
    return 5.0


def _recv_exact(sock, n: int, peer: int | None = None,
                tag: str | None = None,
                heartbeat: float | None = None) -> bytes:
    """``heartbeat=None`` (the default, and always when no sink is
    active — callers snapshot that ONCE per exchange) is the plain
    pre-heartbeat recv, byte-identical to the original hot path."""
    if heartbeat is None:
        chunks = []
        while n:
            part = sock.recv(min(n, 1 << 20))
            if not part:
                raise ConnectionError("exchange peer closed the connection")
            chunks.append(part)
            n -= len(part)
        return b"".join(chunks)
    # heartbeat path: poll readiness so a silent peer surfaces in
    # telemetry every ``heartbeat`` seconds; the knob timeout keeps its
    # exact semantics (max SILENCE, the same contract settimeout gives
    # the plain path — the clock resets whenever bytes arrive).
    # selectors (epoll/poll on Linux), NOT select.select: the exchange
    # mesh plus chunk cache plus JAX can push socket fds past
    # FD_SETSIZE (1024), where select() raises — the instrument must
    # never crash an exchange the plain path would have completed.
    import selectors

    timeout_s = _p2p_timeout_s()
    chunks = []
    silent = 0.0
    with selectors.DefaultSelector() as sel:
        sel.register(sock, selectors.EVENT_READ)
        while n:
            t0 = time.perf_counter()
            ready = sel.select(timeout=heartbeat)
            if not ready:
                silent += time.perf_counter() - t0
                _emit_event(
                    "p2p_heartbeat", peer=peer, tag=tag,
                    blocked_s=silent, bytes_remaining=n,
                )
                if timeout_s is not None and silent >= timeout_s:
                    import socket as _socket

                    raise _socket.timeout(
                        f"exchange recv from process {peer} silent for "
                        f"{silent:.1f}s (PHOTON_P2P_TIMEOUT_S)"
                    )
                continue
            part = sock.recv(min(n, 1 << 20))
            if not part:
                raise ConnectionError(
                    "exchange peer closed the connection"
                )
            silent = 0.0
            chunks.append(part)
            n -= len(part)
    return b"".join(chunks)


def _host_links() -> dict:
    """Build (once) the P×P socket mesh: every ordered pair (i → j) gets a
    dedicated unidirectional TCP connection, so concurrent sends and
    receives never share a stream. Address discovery bootstraps over the
    existing ``jax.distributed`` runtime: each process allgathers its
    (IPv4, port) as five small ints — the only use of a collective here.
    Must be called collectively."""
    global _HOST_LINKS
    if _HOST_LINKS is not None:
        return _HOST_LINKS
    import socket
    import struct
    import threading

    from jax.experimental import multihost_utils as mhu

    timeout_s = _p2p_timeout_s()
    P_ = jax.process_count()
    pid = jax.process_index()
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.settimeout(timeout_s)  # accept() must not hang on a dead peer
    srv.bind(("0.0.0.0", 0))
    srv.listen(P_)
    port = srv.getsockname()[1]
    ip = np.frombuffer(
        socket.inet_aton(_local_ip()), np.uint8
    ).astype(np.int64)
    addrs = np.asarray(
        mhu.process_allgather(np.concatenate([ip, [port]]))
    ).reshape(P_, 5)

    recv_socks: dict[int, socket.socket] = {}

    def accept_all():
        for _ in range(P_ - 1):
            conn, _ = srv.accept()
            _configure_link_socket(conn)
            src = struct.unpack("!i", _recv_exact(conn, 4))[0]
            recv_socks[src] = conn

    acceptor = threading.Thread(target=accept_all, daemon=True)
    acceptor.start()
    send_socks: dict[int, socket.socket] = {}
    for r in range(1, P_):
        peer = (pid + r) % P_
        peer_ip = socket.inet_ntoa(
            addrs[peer, :4].astype(np.uint8).tobytes()
        )
        s = socket.create_connection(
            (peer_ip, int(addrs[peer, 4])), timeout=timeout_s
        )
        _configure_link_socket(s)
        s.sendall(struct.pack("!i", pid))
        send_socks[peer] = s
    acceptor.join(timeout=timeout_s)
    if len(recv_socks) != P_ - 1:
        raise RuntimeError(
            f"host exchange mesh incomplete: accepted {len(recv_socks)} "
            f"of {P_ - 1} peers"
        )
    srv.close()
    _HOST_LINKS = {"send": send_socks, "recv": recv_socks}
    return _HOST_LINKS


def _host_p2p_exchange(arrays, order, starts, counts_matrix=None,
                       transport="p2p_host", tag=""):
    """Skew-robust transport for ``exchange_rows``: each (source, dest)
    bucket travels EXACTLY, length-prefixed, over its pair's dedicated TCP
    link — no padding under any skew (an SPMD collective must pad every
    bucket to a uniform size, which costs O(P × payload) when one entity
    dominates). Sends run on a helper thread in rotation order (round r:
    send to pid+r, receive from pid−r) so every process's receiver drains
    concurrently — no cyclic wait. Layout of the result matches the
    all_to_all transport exactly (ascending source, stable within source).

    ANY error tears THIS process's socket mesh down
    (``_reset_host_links``): a partially-drained stream's next bytes are
    payload, not a length prefix, so reusing a survivor would silently
    mis-frame every later exchange. Peers fail fast against the closed
    sockets on their next use and reset themselves, so retries rebuild
    the mesh instead of corrupting data.
    """
    try:
        return _host_p2p_exchange_impl(
            arrays, order, starts, counts_matrix, transport, tag
        )
    except BaseException:
        # closing the sockets also unblocks a sender thread stuck in
        # sendall against a stalled peer — it errors out and exits
        _reset_host_links()
        raise


def _host_p2p_exchange_impl(arrays, order, starts, counts_matrix,
                            transport="p2p_host", tag=""):
    """``counts_matrix=None`` is the COLLECTIVE-FREE framing mode (the
    overlapped exchange schedule): each bucket's row count is derived
    from its length prefix instead of a pre-exchanged (P, P) count
    matrix, so the whole exchange is pure sockets — safe to run on the
    exchange worker thread concurrently with main-thread jax
    collectives, whose global ordering a worker-side allgather would
    violate. Frame sizes are validated per key (row-multiple + all keys
    from one source agreeing on the row count)."""
    import struct
    import threading

    P_ = jax.process_count()
    pid = jax.process_index()
    links = _host_links()
    keys = sorted(arrays)
    parts: dict[str, dict[int, np.ndarray]] = {
        k: {pid: np.ascontiguousarray(
            arrays[k][order[starts[pid]:starts[pid + 1]]]
        )}
        for k in keys
    }
    bytes_sent = 0
    send_err: list[BaseException] = []
    # snapshot ONCE per exchange: the env knob and the sink check stay
    # off the per-frame hot path, and a concurrent sink reconfigure
    # cannot flip the recv framing mid-exchange
    telemetry = _sink_active()
    heartbeat = _p2p_heartbeat_s() if telemetry else None

    def send_all():
        nonlocal bytes_sent
        try:
            for r in range(1, P_):
                peer = (pid + r) % P_
                sock = links["send"][peer]
                seq = _next_link_seq("send", peer)
                t_start = time.time()
                t0 = time.perf_counter()
                peer_bytes = 0
                for k in keys:
                    rows = order[starts[peer]:starts[peer + 1]]
                    buf = np.ascontiguousarray(arrays[k][rows]).tobytes()
                    sock.sendall(struct.pack("!q", len(buf)))
                    sock.sendall(buf)
                    peer_bytes += len(buf)
                bytes_sent += peer_bytes
                if telemetry:
                    # one event per (link, exchange): the frame-set, not
                    # per key — report fleet joins it with the peer's
                    # p2p_recv through the shared correlation id
                    _emit_event(
                        "p2p_send", peer=peer,
                        bytes=peer_bytes,
                        rows=int(starts[peer + 1] - starts[peer]),
                        dur_s=time.perf_counter() - t0,
                        t_start=t_start,
                        corr=f"p2p:{pid}>{peer}#{seq}",
                        tag=tag, transport=transport,
                    )
        except BaseException as e:  # surfaced after join
            send_err.append(e)

    sender = threading.Thread(target=send_all)
    sender.start()
    for r in range(1, P_):
        src = (pid - r) % P_
        sock = links["recv"][src]
        seq = _next_link_seq("recv", src)
        t_start = time.time()
        t0 = time.perf_counter()
        src_bytes = 0
        src_rows = 0
        n_src: int | None = None  # framed mode: all keys must agree
        for k in keys:
            a = arrays[k]
            row_bytes = a.itemsize * int(
                np.prod(a.shape[1:], dtype=np.int64)
            )
            got = struct.unpack(
                "!q", _recv_exact(sock, 8, src, tag, heartbeat)
            )[0]
            if counts_matrix is not None:
                n = int(counts_matrix[src, pid])
                want = n * row_bytes
                if got != want:
                    raise RuntimeError(
                        f"exchange size mismatch from process {src} key "
                        f"{k!r}: expected {want} bytes ({n} rows), got {got}"
                    )
            else:
                if row_bytes <= 0 or got % row_bytes:
                    raise RuntimeError(
                        f"exchange frame from process {src} key {k!r}: "
                        f"{got} bytes is not a multiple of the "
                        f"{row_bytes}-byte row"
                    )
                n = got // row_bytes
                if n_src is None:
                    n_src = n
                elif n != n_src:
                    raise RuntimeError(
                        f"exchange frames from process {src} disagree on "
                        f"row count: key {k!r} carries {n} rows, earlier "
                        f"keys carried {n_src}"
                    )
            raw = _recv_exact(sock, got, src, tag, heartbeat)
            src_bytes += got
            src_rows = n
            parts[k][src] = np.frombuffer(raw, a.dtype).reshape(
                (n,) + a.shape[1:]
            ).copy()
        if telemetry:
            _emit_event(
                "p2p_recv", peer=src,
                bytes=src_bytes, rows=int(src_rows),
                dur_s=time.perf_counter() - t0,
                t_start=t_start,
                corr=f"p2p:{src}>{pid}#{seq}",
                tag=tag, transport=transport,
            )
    sender.join()
    if send_err:
        raise send_err[0]
    # this process's send counts: identical to counts_matrix[pid] when a
    # matrix was exchanged, and derivable locally when not (framed mode)
    counts_send = np.diff(starts)
    LAST_EXCHANGE_STATS.update(
        bytes_sent=bytes_sent,
        rows_sent=int(counts_send.sum()),
        # same accounting as the all_to_all branch (allocated row-slots,
        # summed over keys) — here exactly the payload: zero padded slots
        padded_rows=int(counts_send.sum()) * len(arrays),
        transport=transport,
    )
    return {
        k: np.concatenate([parts[k][s] for s in range(P_)]) for k in keys
    }


# -- overlapped (asynchronous) point-to-point exchange ----------------------
#
# The pipelined exchange schedule (PHOTON_RE_SHARD=1): an exchange is
# ISSUED at one program point and JOINED at a later one, with device
# solves / host bookkeeping / jax collectives in between — instead of a
# barrier per coordinate. The exchange body runs on ONE dedicated worker
# thread per process in strict submission order (every process submits
# the same exchange sequence at the same program points, so the socket
# streams stay frame-matched), and it is COLLECTIVE-FREE (framed p2p:
# row counts ride the length prefixes) so a worker-side exchange can
# never interleave a collective against the main thread's.

_EXCHANGE_POOL = None
_EXCHANGE_LOCK = None  # guards the pending list + overlap accounting
_PENDING_EXCHANGES: list = []
_EXCHANGE_TOTALS = {"exchange_s": 0.0, "wait_s": 0.0}


def _exchange_state():
    global _EXCHANGE_POOL, _EXCHANGE_LOCK
    if _EXCHANGE_LOCK is None:
        import threading

        _EXCHANGE_LOCK = threading.Lock()
    if _EXCHANGE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        _EXCHANGE_POOL = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="photon-exchange"
        )
    return _EXCHANGE_POOL, _EXCHANGE_LOCK


def _record_overlap(kind: str, seconds: float) -> None:
    """Cumulative exchange/wait seconds + the derived overlap-ratio
    gauge: the fraction of exchange wall the consumer did NOT block on
    (1.0 = fully hidden behind other work, 0.0 = a barrier schedule).
    Mirrored into the PR-4 registry so the ratio rides every telemetry
    snapshot and ``photon-ml-tpu report``."""
    from photon_ml_tpu.obs.metrics import REGISTRY

    _, lock = _exchange_state()
    with lock:
        _EXCHANGE_TOTALS[kind] += seconds
        wall = _EXCHANGE_TOTALS["exchange_s"]
        wait = _EXCHANGE_TOTALS["wait_s"]
    REGISTRY.timer_add(f"re_exchange.{kind}", seconds)
    # zero wall (the single-process identity path) reads as fully
    # overlapped: there was nothing to wait for — and the gauge must
    # exist on every topology the schedule runs on
    ratio = 1.0 if wall <= 0.0 else max(0.0, min(1.0, 1.0 - wait / wall))
    REGISTRY.gauge_set("re_shard.exchange_overlap_ratio", ratio)


class ExchangeHandle:
    """A pending ``exchange_rows_async``. ``result()`` blocks until the
    exchange lands and returns the received-rows dict (the same layout
    contract as ``exchange_rows``); the blocked seconds are recorded as
    ``re_exchange.wait_s`` against the worker's ``re_exchange.exchange_s``
    for the overlap-ratio gauge (and, with a sink active, emitted as an
    ``exchange_wait`` event so the per-process timeline shows where the
    consumer actually blocked)."""

    def __init__(self, future=None, value=None, tag: str = ""):
        self._future = future
        self._value = value
        self._tag = tag

    @property
    def done(self) -> bool:
        return self._future is None or self._future.done()

    def result(self) -> dict:
        if self._future is None:
            return self._value
        import time as _time

        t0 = _time.perf_counter()
        try:
            out = self._future.result()
        finally:
            waited = _time.perf_counter() - t0
            _record_overlap("wait_s", waited)
            if _sink_active():
                _emit_event(
                    "exchange_wait", tag=self._tag, wait_s=waited
                )
            _, lock = _exchange_state()
            with lock:
                if self._future in _PENDING_EXCHANGES:
                    _PENDING_EXCHANGES.remove(self._future)
        self._future = None
        self._value = out
        return out


def drain_async_exchanges() -> None:
    """Wait for every in-flight async exchange (results stay claimable
    through their handles). A SYNCHRONOUS p2p exchange must not touch
    the sockets while the worker is mid-frame, and submission order is
    the cross-process consistency invariant — so the sync path drains
    first, preserving one global socket-use order."""
    _, lock = _exchange_state()
    with lock:
        pending = list(_PENDING_EXCHANGES)
    for f in pending:
        try:
            f.exception()  # waits; the owner handle re-raises on result()
        except Exception:
            pass


def exchange_rows_async(
    arrays, dest: np.ndarray, tag: str = ""
) -> ExchangeHandle:
    """Issue ``exchange_rows`` without blocking: returns a handle whose
    ``result()`` yields the identical received-rows layout. Transport is
    ALWAYS the framed host P2P path (collective-free — the worker thread
    must never run a jax collective; padding-free — the schedule exists
    for the skewed configs where all_to_all padding is pathological).
    The socket mesh is built (collectively) on the CALLING thread at
    first use, so the collective stays in program order. Single process:
    completes inline (identity)."""
    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    P_ = jax.process_count()
    if P_ <= 1:
        LAST_EXCHANGE_STATS.update(
            bytes_sent=0, rows_sent=len(dest), padded_rows=len(dest),
            transport="local",
        )
        # inline identity still contributes (zero-wait) overlap samples,
        # so the gauge exists on every topology the schedule runs on
        _record_overlap("exchange_s", 0.0)
        _record_overlap("wait_s", 0.0)
        return ExchangeHandle(value=arrays)
    dest = np.asarray(dest, np.int64)
    order = np.argsort(dest, kind="stable")
    counts = np.bincount(dest, minlength=P_).astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(counts)])
    _host_links()  # collective bootstrap happens HERE, in program order
    pool, lock = _exchange_state()

    def run():
        import time as _time

        t0 = _time.perf_counter()
        try:
            return _host_p2p_exchange(
                arrays, order, starts, counts_matrix=None,
                transport="p2p_host_async", tag=tag,
            )
        finally:
            dur = _time.perf_counter() - t0
            _record_overlap("exchange_s", dur)
            if _sink_active():
                _emit_event("exchange", tag=tag, dur_s=dur)

    fut = pool.submit(run)
    with lock:
        _PENDING_EXCHANGES.append(fut)
    return ExchangeHandle(future=fut, tag=tag)


def allreduce_max_host(*arrays: np.ndarray):
    """Elementwise max across ALL processes (identity on one process).
    Used by the streamed feature summary for min/max statistics (min rides
    as max of the negation)."""
    if jax.process_count() <= 1:
        return arrays if len(arrays) > 1 else arrays[0]
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(arrays)  # each: (P, ...)
    maxed = tuple(np.max(np.asarray(a), axis=0) for a in stacked)
    return maxed if len(maxed) > 1 else maxed[0]
