"""Sample-sharded distributed training.

Reference parity: ``photon-api::ml.function.glm.DistributedGLMLossFunction``
+ ``DistributedOptimizationProblem`` (SURVEY.md §2.2, §2.7 item 1): the
reference broadcasts coefficients driver→executors, folds per-partition
gradient sums, and treeAggregates back to a driver-resident Breeze loop —
one cluster round-trip per objective evaluation (1 + #CG for TRON).

TPU-native redesign: the *entire optimizer* runs SPMD inside ``shard_map``
over the ``data`` mesh axis. Every device holds a row shard of the batch and
a replicated copy of the coefficients; the objective's partial sums meet in
a single ``lax.psum`` over ICI per evaluation. Broadcast and aggregation
collapse into that one collective, and the optimizer loop itself never
leaves the device — there is no driver in the loop at all.

The solve entry point is one module-level jitted function keyed on static
(optimizer, loss, config, mesh) — re-entered, never recompiled, across
regularization sweeps and coordinate-descent iterations.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.config import OptimizerConfig
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops.batch import Batch, pad_batch
from photon_ml_tpu.ops.glm import make_objective
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim.common import OptimizationResult, select_minimize_fn
from photon_ml_tpu.utils import compat

Array = jnp.ndarray


def shard_batch(batch: Batch, mesh: Mesh, axis_name: str = "data") -> Batch:
    """Place a host-global batch row-sharded over the mesh's data axis.

    Rows are padded with zero-weight samples up to a multiple of the axis
    size (static-shape requirement); padding is inert in the objective.
    """
    n_dev = mesh.shape[axis_name]
    n = batch.num_rows
    target = -(-n // n_dev) * n_dev
    batch = pad_batch(batch, target)
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def _densify_sharded(batch, mesh: Mesh, axis_name: str = "data"):
    """Densify a sparse batch whose dense form fits the MESH's HBM but not
    one chip's: row-shard the sparse arrays first, then scatter each
    device's own (n/P, d) block under ``shard_map`` — the full (n, d)
    matrix never exists on any single device."""
    from photon_ml_tpu.ops.batch import densify

    batch = shard_batch(batch, mesh, axis_name)
    fn = jax.jit(
        compat.shard_map(
            densify,
            mesh=mesh,
            in_specs=P(axis_name),
            out_specs=P(axis_name),
        )
    )
    return fn(batch)


@partial(
    jax.jit,
    static_argnames=(
        "minimize_fn",
        "loss",
        "config",
        "intercept_index",
        "axis_name",
        "mesh",
        "use_l1",
        "fused",
        "data_hints",
    ),
)
def _sharded_solve(
    batch: Batch,
    w0: Array,
    l2_weight: Array,
    l1_weight: Array,
    norm: NormalizationContext | None,
    prior,  # GaussianPrior | None (replicated pytree)
    *,
    minimize_fn: Callable,
    loss: PointwiseLoss,
    config: OptimizerConfig,
    intercept_index: int | None,
    axis_name: str,
    mesh: Mesh,
    use_l1: bool,
    fused: bool = False,
    data_hints: tuple[bool, bool] = (False, False),
) -> OptimizationResult:
    def solve(local_batch, w0, l2w, l1w, norm_, prior_):
        # ``fused``/``data_hints`` are decided OUTSIDE the shard_map (the
        # local batch here is a tracer, so in-place auto-detection would
        # always say no); inside, the Pallas kernels see the per-device
        # row shard with concrete shapes.
        obj = make_objective(
            local_batch,
            loss,
            l2_weight=l2w,
            norm=norm_,
            intercept_index=intercept_index,
            axis_name=axis_name,
            fused=fused,
            data_hints=data_hints,
            prior=prior_,
        )
        kwargs = {"l1_weight": l1w} if use_l1 else {}
        return minimize_fn(obj, w0, config, **kwargs)

    return compat.shard_map(
        solve,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(batch, w0, l2_weight, l1_weight, norm, prior)


def sharded_minimize(
    minimize_fn: Callable[..., OptimizationResult],
    batch: Batch,
    w0: Array,
    config: OptimizerConfig,
    mesh: Mesh,
    loss: PointwiseLoss,
    l2_weight: float | Array = 0.0,
    norm: NormalizationContext | None = None,
    intercept_index: int | None = None,
    axis_name: str = "data",
    l1_weight: float | Array | None = None,
    fused: bool | None = None,
    prior=None,
    **minimize_kwargs,
) -> OptimizationResult:
    """Run a device-resident optimizer over a row-sharded batch.

    ``minimize_fn`` is one of ``lbfgs_minimize`` / ``owlqn_minimize`` /
    ``tron_minimize`` — the *same* functions used single-device; the
    objective they see simply carries ``axis_name`` so its partial sums
    psum over the mesh (the twin structure of SURVEY.md §4, collapsed to
    one code path).

    ``fused=None`` auto-enables the one-pass Pallas kernels (TPU, dense
    batch, supported shapes) — decided here on the concrete global batch
    because inside ``shard_map`` only tracers are visible.
    """
    from photon_ml_tpu.ops.glm import _constant_hints, auto_fused

    if "l1_weight" in minimize_kwargs:
        l1_weight = minimize_kwargs.pop("l1_weight")
    if minimize_kwargs:
        raise TypeError(f"unsupported kwargs: {sorted(minimize_kwargs)}")

    # the framework's FULL ingest layout decision, on the mesh path too
    # (VERDICT r4 missing #4: the mesh trainer lowered high-dim sparse
    # shards through the known-slow XLA gather/scatter fallback): densify
    # when the dense matrix fits the budget; re-block genuinely
    # high-dimensional sparse data into per-shard tile-COO kernels --
    # sparse_tiled.py's own multi-device recipe (shard rows first, one
    # tile-COO per shard, psum reduces)
    from photon_ml_tpu.ops.batch import SparseBatch, maybe_densify

    if isinstance(batch, SparseBatch):
        from photon_ml_tpu.ops.sparse_tiled import (
            supports_tiling,
            tile_sparse_batch_sharded,
        )
        from photon_ml_tpu.ops.streaming import device_hbm_budget_bytes

        # densify when the dense matrix fits the MESH's total HBM — but
        # never materialize more than one chip's worth on one chip: over
        # one-chip budget, the rows are sharded first and each device
        # scatters only its own (n/P, d) block
        n_dev = mesh.shape[axis_name]
        one_chip = device_hbm_budget_bytes()
        dense_bytes = batch.num_rows * batch.num_features * 4
        if dense_bytes <= one_chip:
            batch = maybe_densify(batch, one_chip)
        elif dense_bytes <= one_chip * n_dev:
            batch = _densify_sharded(batch, mesh, axis_name)
        if isinstance(batch, SparseBatch) and supports_tiling(batch):
            stacked, _ = tile_sparse_batch_sharded(
                batch, mesh.shape[axis_name]
            )
            sharding = NamedSharding(mesh, P(axis_name))
            stacked = jax.tree.map(
                lambda a: jax.device_put(a, sharding), stacked
            )
            use_l1 = l1_weight is not None
            return _sharded_tiled_solve(
                stacked,
                w0,
                jnp.asarray(l2_weight, jnp.float32),
                jnp.asarray(0.0 if l1_weight is None else l1_weight, jnp.float32),
                norm,
                prior,
                minimize_fn=minimize_fn,
                loss=loss,
                config=config,
                intercept_index=intercept_index,
                axis_name=axis_name,
                mesh=mesh,
                use_l1=use_l1,
            )

    if fused is None:
        fused = auto_fused(batch)
    data_hints = _constant_hints(batch) if fused else (False, False)
    n_before = batch.num_rows
    batch = shard_batch(batch, mesh, axis_name)
    if batch.num_rows != n_before:
        # sharding padded zero-WEIGHT rows in: the all-ones hint no longer
        # holds (the padding must stay inert through the weight mask)
        data_hints = (data_hints[0], False)
    use_l1 = l1_weight is not None
    return _sharded_solve(
        batch,
        w0,
        jnp.asarray(l2_weight, jnp.float32),
        jnp.asarray(0.0 if l1_weight is None else l1_weight, jnp.float32),
        norm,
        prior,
        minimize_fn=minimize_fn,
        loss=loss,
        config=config,
        intercept_index=intercept_index,
        axis_name=axis_name,
        mesh=mesh,
        use_l1=use_l1,
        fused=bool(fused),
        data_hints=tuple(data_hints),
    )


@partial(
    jax.jit,
    static_argnames=(
        "minimize_fn",
        "loss",
        "config",
        "intercept_index",
        "axis_name",
        "mesh",
        "use_l1",
    ),
)
def _sharded_tiled_solve(
    stacked: Any,
    w0: Array,
    l2_weight: Array,
    l1_weight: Array,
    norm: NormalizationContext | None,
    prior,
    *,
    minimize_fn: Callable,
    loss: PointwiseLoss,
    config: OptimizerConfig,
    intercept_index: int | None,
    axis_name: str,
    mesh: Mesh,
    use_l1: bool,
) -> OptimizationResult:
    '''The tiled twin of ``_sharded_solve``: ``stacked`` is a
    ``TiledSparseBatch``-shaped pytree with a leading device axis
    (``tile_sparse_batch_sharded``); each device drops its unit leading
    axis to recover the local per-shard tile-COO batch, and the
    objective's partial sums meet in the same single psum per
    evaluation.'''

    def solve(stacked_local, w0, l2w, l1w, norm_, prior_):
        local_batch = jax.tree.map(lambda a: a[0], stacked_local)
        obj = make_objective(
            local_batch,
            loss,
            l2_weight=l2w,
            norm=norm_,
            intercept_index=intercept_index,
            axis_name=axis_name,
            prior=prior_,
        )
        kwargs = {"l1_weight": l1w} if use_l1 else {}
        return minimize_fn(obj, w0, config, **kwargs)

    return compat.shard_map(
        solve,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked, w0, l2_weight, l1_weight, norm, prior)


@dataclass(frozen=True)
class DistributedTrainer:
    """Binds a mesh + optimizer choice into a ``train(batch, w0)`` call —
    the ergonomic equivalent of the reference's
    ``DistributedOptimizationProblem`` (objective + optimizer +
    regularization + normalization bound together)."""

    mesh: Mesh
    config: OptimizerConfig
    loss: PointwiseLoss
    l2_weight: float = 0.0
    l1_weight: float = 0.0
    norm: NormalizationContext | None = None
    intercept_index: int | None = None
    axis_name: str = "data"

    def train(self, batch: Batch, w0: Array) -> OptimizationResult:
        fn, kwargs = select_minimize_fn(self.config, self.l1_weight)
        return sharded_minimize(
            fn,
            batch,
            w0,
            self.config,
            self.mesh,
            self.loss,
            l2_weight=self.l2_weight,
            norm=self.norm,
            intercept_index=self.intercept_index,
            axis_name=self.axis_name,
            **kwargs,
        )
