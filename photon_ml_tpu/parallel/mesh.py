"""Mesh construction helpers.

The framework uses one logical mesh with a ``data`` axis (sample sharding —
the reference's executor data parallelism) and, for random effects, an
``entity`` view of the same devices (entity sharding — the reference's
``RandomEffectDatasetPartitioner``). On multi-host TPU slices the mesh spans
all hosts (``jax.devices()`` is global under ``jax.distributed``), so the
same code scales from 1 chip to a pod: XLA routes the psums over ICI/DCN.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def local_device_count() -> int:
    return len(jax.devices())


def data_mesh(
    num_devices: int | None = None, axis_name: str = "data", devices=None
) -> Mesh:
    """A 1-D mesh over all (or the first ``num_devices``) devices."""
    devs = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))
