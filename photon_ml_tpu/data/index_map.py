"""Feature index maps: (name, term) → dense column index.

Reference parity: ``photon-client::ml.index.{IndexMap, DefaultIndexMap,
PalDBIndexMap, PalDBIndexMapBuilder}`` and the feature-key convention of
``AvroDataReader`` (feature key = name + INTERCEPT/DELIMITER + term)
(SURVEY.md §2.3).

The reference needs PalDB because JVM executors memory-map 10⁷–10⁸ string
keys off-heap. Here the map lives once on the TPU-VM host; storage is a
sorted string array + offsets persisted as ``.npz`` (mmap-loadable), with
hash-based lookup via numpy ``searchsorted`` over hashed keys for bulk
translation — no per-key Python dict overhead on the bulk path.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Mapping

import numpy as np

# The reference separates feature name and term with a special delimiter and
# uses a reserved key for the intercept (Constants.INTERCEPT_KEY).
DELIMITER = "\x01"
INTERCEPT_KEY = "(INTERCEPT)"

# Feature-range-sharded fixed-effect solves (PHOTON_FE_SHARD): 0 (default)
# keeps the replicated-coefficient fixed-effect path bit-for-bit — every
# process packs, caches and solves the full [0, d) feature space. 1
# partitions the global feature space into P contiguous ranges
# (``plan_feature_ranges``) and each process holds ONLY its range: packed
# tile-COO streams, chunk-cache residency and the optimizer's coefficient
# vector all shrink to ~1/P. The partition rule reads ONLY the global
# per-feature nnz histogram and the process count — deterministic pure-host
# arithmetic on inputs identical everywhere, so every process derives the
# same boundaries with zero communication (the placement.py discipline).
# Like every fleet knob it must be set identically on all processes.
FE_SHARD = 0

# Range-split weight axis (PHOTON_FE_SPLIT_WEIGHT): "nnz" (default) places
# boundaries on the per-feature NNZ prefix sum — real feature frequency is
# Zipf just like entity traffic, so a hot dense block would otherwise pin
# one shard's packed bytes at a large multiple of the mean. "width" splits
# the index space uniformly (the naive rule, kept for A/B).
FE_SPLIT_WEIGHT = "nnz"

_FE_SPLIT_WEIGHT_MODES = ("nnz", "width")


def fe_shard_enabled() -> bool:
    """``PHOTON_FE_SHARD`` (env > module global), strict parse like the
    sibling fleet knobs — a typo fails loudly instead of silently benching
    the replicated path."""
    env = os.environ.get("PHOTON_FE_SHARD")
    if env is not None and env != "":
        return int(env) != 0
    return int(FE_SHARD) != 0


def fe_split_weight() -> str:
    """``PHOTON_FE_SPLIT_WEIGHT`` (env > module global), strict membership
    parse — an unknown axis fails loudly instead of silently benching the
    default split."""
    env = os.environ.get("PHOTON_FE_SPLIT_WEIGHT")
    raw = env if (env is not None and env != "") else FE_SPLIT_WEIGHT
    mode = str(raw)
    if mode not in _FE_SPLIT_WEIGHT_MODES:
        raise ValueError(
            f"PHOTON_FE_SPLIT_WEIGHT must be one of {_FE_SPLIT_WEIGHT_MODES}, "
            f"got {mode!r}")
    return mode


@dataclass(frozen=True)
class FeatureRangePlan:
    """A contiguous partition of the global feature space [0, d) into
    ``num_ranges`` half-open ranges ``[boundaries[p], boundaries[p+1])``.

    Ranges are DISJOINT and cover [0, d) exactly once, so per-range
    gradient/coefficient segments concatenate back to the full vector
    exactly (no arithmetic — the x+0.0-exact combine argument does not
    even need to apply; it is pure concatenation)."""

    boundaries: tuple[int, ...]  # num_ranges + 1 ascending ints, [0]=0, [-1]=d
    weights: tuple[float, ...]   # per-range weight (nnz or width)

    @property
    def num_ranges(self) -> int:
        return len(self.boundaries) - 1

    @property
    def num_features(self) -> int:
        return int(self.boundaries[-1])

    @property
    def balance(self) -> float:
        """max/mean per-range weight — the r12 gate's nnz-balance metric."""
        w = np.asarray(self.weights, dtype=np.float64)
        mean = float(w.mean()) if len(w) else 0.0
        return float(w.max() / mean) if mean > 0 else 1.0

    def range_of(self, pid: int) -> tuple[int, int]:
        return int(self.boundaries[pid]), int(self.boundaries[pid + 1])


def plan_feature_ranges(
    weights: np.ndarray,
    num_ranges: int,
    mode: str | None = None,
) -> FeatureRangePlan:
    """Partition [0, d) into ``num_ranges`` contiguous ranges.

    ``weights`` is the GLOBAL per-feature weight histogram (nnz counts
    under the default axis) — identical on every process, so the plan is
    too. Boundaries sit where the weight prefix sum crosses k·total/P
    (the contiguous analogue of placement.py's LPT: contiguity is forced
    by the range representation, so the optimal split is the balanced
    prefix cut, no greedy bin-packing needed). Zero-weight features are
    still owned by exactly one range — coverage of [0, d) is structural,
    not weight-dependent. ``mode`` defaults to ``fe_split_weight()``."""
    w = np.asarray(weights, dtype=np.float64).ravel()
    d = len(w)
    p = int(num_ranges)
    if p <= 0:
        raise ValueError(f"num_ranges must be positive, got {num_ranges}")
    if d < p:
        raise ValueError(f"cannot split {d} features into {p} ranges")
    mode = fe_split_weight() if mode is None else mode
    if mode not in _FE_SPLIT_WEIGHT_MODES:
        raise ValueError(
            f"feature split mode must be one of {_FE_SPLIT_WEIGHT_MODES}, "
            f"got {mode!r}")
    if mode == "width" or float(w.sum()) <= 0.0:
        # uniform index split (also the degenerate all-zero-weight case)
        cuts = [round(k * d / p) for k in range(p + 1)]
    else:
        prefix = np.concatenate([[0.0], np.cumsum(w)])
        total = float(prefix[-1])
        cuts = [0]
        for k in range(1, p):
            target = k * total / p
            pos = int(np.searchsorted(prefix, target))
            # pick the neighbour closer to the target
            if pos > 0 and (pos > d or
                            target - prefix[pos - 1] <= prefix[pos] - target):
                pos = pos - 1
            # monotone + leave room for the remaining p-k cuts
            pos = min(max(pos, cuts[-1] + 1), d - (p - k))
            cuts.append(pos)
        cuts.append(d)
    bounds = tuple(int(c) for c in cuts)
    range_w = tuple(float(w[lo:hi].sum()) for lo, hi in zip(bounds, bounds[1:]))
    return FeatureRangePlan(boundaries=bounds, weights=range_w)


def feature_key(name: str, term: str = "") -> str:
    return f"{name}{DELIMITER}{term}" if term else name


@dataclass
class IndexMap:
    """Immutable feature-key → index map with O(log n) numpy bulk lookup."""

    _keys: np.ndarray  # sorted unicode array
    _indices: np.ndarray  # int64, index of each sorted key

    @classmethod
    def build(cls, keys: Iterable[str], add_intercept: bool = False) -> "IndexMap":
        """Assign dense ids 0..d-1 in first-seen order (deterministic).
        The intercept, when requested, always gets the LAST index — matching
        the convention used across the framework (intercept_index = d-1)."""
        seen: dict[str, int] = {}
        for k in keys:
            if k == INTERCEPT_KEY:
                continue
            if k not in seen:
                seen[k] = len(seen)
        if add_intercept:
            seen[INTERCEPT_KEY] = len(seen)
        arr = np.array(list(seen.keys()), dtype=np.str_)
        idx = np.array(list(seen.values()), dtype=np.int64)
        order = np.argsort(arr)
        return cls(_keys=arr[order], _indices=idx[order])

    @property
    def size(self) -> int:
        return len(self._keys)

    @property
    def intercept_index(self) -> int | None:
        pos = np.searchsorted(self._keys, INTERCEPT_KEY)
        if pos < len(self._keys) and self._keys[pos] == INTERCEPT_KEY:
            return int(self._indices[pos])
        return None

    def get(self, key: str, default: int = -1) -> int:
        pos = np.searchsorted(self._keys, key)
        if pos < len(self._keys) and self._keys[pos] == key:
            return int(self._indices[pos])
        return default

    def __contains__(self, key: str) -> bool:
        return self.get(key) >= 0

    def __len__(self) -> int:
        return self.size

    def lookup_all(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized lookup: unknown keys map to -1 (callers drop them, the
        reference does the same for features absent from the index)."""
        keys = np.asarray(keys, dtype=np.str_)
        # widen to a common itemsize: casting queries DOWN to the stored
        # width would truncate long unseen keys onto shorter stored ones
        width = max(self._keys.dtype.itemsize, keys.dtype.itemsize) // 4
        keys = keys.astype(f"<U{width}")
        stored = self._keys.astype(f"<U{width}")
        pos = np.searchsorted(stored, keys)
        pos = np.clip(pos, 0, len(stored) - 1)
        found = stored[pos] == keys
        return np.where(found, self._indices[pos], -1)

    def items(self) -> Iterator[tuple[str, int]]:
        for k, i in zip(self._keys, self._indices):
            yield str(k), int(i)

    def keys_for(self, indices) -> list[str]:
        """Reverse lookup (index → feature key) for a FEW indices: one
        vectorized O(d) integer membership test selects just the matching
        entries — no d-sized string allocation, no Python-dict inversion —
        so reporting paths resolve a handful of top features out of 10⁷+
        cheaply. Unknown indices resolve to their decimal string."""
        indices = np.asarray(indices, dtype=np.int64)
        mask = np.isin(self._indices, indices)
        found = {
            int(i): str(k)
            for i, k in zip(self._indices[mask], self._keys[mask])
        }
        return [found.get(int(j), str(int(j))) for j in indices]

    # -- persistence (PalDB-store equivalent: one mmap-able npz per shard) ----
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path if path.endswith(".npz") else path + ".npz",
                 keys=self._keys, indices=self._indices)

    @classmethod
    def load(cls, path: str) -> "IndexMap":
        z = np.load(path if path.endswith(".npz") else path + ".npz",
                    allow_pickle=False)
        return cls(_keys=z["keys"], _indices=z["indices"])
