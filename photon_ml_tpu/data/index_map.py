"""Feature index maps: (name, term) → dense column index.

Reference parity: ``photon-client::ml.index.{IndexMap, DefaultIndexMap,
PalDBIndexMap, PalDBIndexMapBuilder}`` and the feature-key convention of
``AvroDataReader`` (feature key = name + INTERCEPT/DELIMITER + term)
(SURVEY.md §2.3).

The reference needs PalDB because JVM executors memory-map 10⁷–10⁸ string
keys off-heap. Here the map lives once on the TPU-VM host; storage is a
sorted string array + offsets persisted as ``.npz`` (mmap-loadable), with
hash-based lookup via numpy ``searchsorted`` over hashed keys for bulk
translation — no per-key Python dict overhead on the bulk path.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Mapping

import numpy as np

# The reference separates feature name and term with a special delimiter and
# uses a reserved key for the intercept (Constants.INTERCEPT_KEY).
DELIMITER = "\x01"
INTERCEPT_KEY = "(INTERCEPT)"


def feature_key(name: str, term: str = "") -> str:
    return f"{name}{DELIMITER}{term}" if term else name


@dataclass
class IndexMap:
    """Immutable feature-key → index map with O(log n) numpy bulk lookup."""

    _keys: np.ndarray  # sorted unicode array
    _indices: np.ndarray  # int64, index of each sorted key

    @classmethod
    def build(cls, keys: Iterable[str], add_intercept: bool = False) -> "IndexMap":
        """Assign dense ids 0..d-1 in first-seen order (deterministic).
        The intercept, when requested, always gets the LAST index — matching
        the convention used across the framework (intercept_index = d-1)."""
        seen: dict[str, int] = {}
        for k in keys:
            if k == INTERCEPT_KEY:
                continue
            if k not in seen:
                seen[k] = len(seen)
        if add_intercept:
            seen[INTERCEPT_KEY] = len(seen)
        arr = np.array(list(seen.keys()), dtype=np.str_)
        idx = np.array(list(seen.values()), dtype=np.int64)
        order = np.argsort(arr)
        return cls(_keys=arr[order], _indices=idx[order])

    @property
    def size(self) -> int:
        return len(self._keys)

    @property
    def intercept_index(self) -> int | None:
        pos = np.searchsorted(self._keys, INTERCEPT_KEY)
        if pos < len(self._keys) and self._keys[pos] == INTERCEPT_KEY:
            return int(self._indices[pos])
        return None

    def get(self, key: str, default: int = -1) -> int:
        pos = np.searchsorted(self._keys, key)
        if pos < len(self._keys) and self._keys[pos] == key:
            return int(self._indices[pos])
        return default

    def __contains__(self, key: str) -> bool:
        return self.get(key) >= 0

    def __len__(self) -> int:
        return self.size

    def lookup_all(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized lookup: unknown keys map to -1 (callers drop them, the
        reference does the same for features absent from the index)."""
        keys = np.asarray(keys, dtype=np.str_)
        # widen to a common itemsize: casting queries DOWN to the stored
        # width would truncate long unseen keys onto shorter stored ones
        width = max(self._keys.dtype.itemsize, keys.dtype.itemsize) // 4
        keys = keys.astype(f"<U{width}")
        stored = self._keys.astype(f"<U{width}")
        pos = np.searchsorted(stored, keys)
        pos = np.clip(pos, 0, len(stored) - 1)
        found = stored[pos] == keys
        return np.where(found, self._indices[pos], -1)

    def items(self) -> Iterator[tuple[str, int]]:
        for k, i in zip(self._keys, self._indices):
            yield str(k), int(i)

    def keys_for(self, indices) -> list[str]:
        """Reverse lookup (index → feature key) for a FEW indices: one
        vectorized O(d) integer membership test selects just the matching
        entries — no d-sized string allocation, no Python-dict inversion —
        so reporting paths resolve a handful of top features out of 10⁷+
        cheaply. Unknown indices resolve to their decimal string."""
        indices = np.asarray(indices, dtype=np.int64)
        mask = np.isin(self._indices, indices)
        found = {
            int(i): str(k)
            for i, k in zip(self._indices[mask], self._keys[mask])
        }
        return [found.get(int(j), str(int(j))) for j in indices]

    # -- persistence (PalDB-store equivalent: one mmap-able npz per shard) ----
    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path if path.endswith(".npz") else path + ".npz",
                 keys=self._keys, indices=self._indices)

    @classmethod
    def load(cls, path: str) -> "IndexMap":
        z = np.load(path if path.endswith(".npz") else path + ".npz",
                    allow_pickle=False)
        return cls(_keys=z["keys"], _indices=z["indices"])
