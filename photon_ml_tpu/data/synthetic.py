"""Synthetic dataset generators for tests and benchmarks.

Parity role: ``photon-test-utils::GameTestUtils`` / ``CommonTestUtils``
dataset builders (SURVEY.md §2.5) — plus the benchmark configs of
BASELINE.md need reproducible data at arbitrary scale.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from photon_ml_tpu.ops.batch import DenseBatch, dense_batch_from_numpy
from photon_ml_tpu.types import TaskType


def synthetic_glm_data(
    rng: np.random.Generator,
    n: int,
    d: int,
    task: TaskType = TaskType.LOGISTIC_REGRESSION,
    noise: float = 0.1,
    add_intercept: bool = True,
    dtype=np.float32,
) -> tuple[DenseBatch, int | None, np.ndarray]:
    """Dense GLM problem with known ground-truth weights.

    Returns (batch, intercept_index, w_true).
    """
    X = rng.normal(size=(n, d)).astype(dtype)
    intercept_index = None
    if add_intercept:
        X = np.concatenate([X, np.ones((n, 1), dtype)], axis=1)
        intercept_index = d
    w_true = (rng.normal(size=X.shape[1]) * 0.5).astype(dtype)
    margin = X @ w_true
    if task is TaskType.LOGISTIC_REGRESSION or task is TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-margin))).astype(dtype)
    elif task is TaskType.LINEAR_REGRESSION:
        y = (margin + rng.normal(scale=noise, size=n)).astype(dtype)
    elif task is TaskType.POISSON_REGRESSION:
        y = rng.poisson(np.exp(np.clip(margin, -10, 3))).astype(dtype)
    else:  # pragma: no cover
        raise ValueError(task)
    return dense_batch_from_numpy(X, y, dtype=dtype), intercept_index, w_true


@dataclass(frozen=True)
class GameSyntheticData:
    """Columnar GAME dataset: global features + per-entity memberships.

    ``entity_ids[name]`` maps each sample to an int entity id in
    [0, num_entities[name]); ``entity_features[name]`` is the feature matrix
    for that random effect's shard (here shared with the fixed shard for
    simplicity); ``w_fixed`` / ``w_entity[name]`` are the generating
    coefficients.
    """

    X: np.ndarray  # (n, d_fixed) fixed-effect shard
    y: np.ndarray
    entity_ids: dict[str, np.ndarray]  # name → (n,) int32
    entity_X: dict[str, np.ndarray]  # name → (n, d_re) per-effect shard
    w_fixed: np.ndarray
    w_entity: dict[str, np.ndarray]  # name → (num_entities, d_re)
    intercept_index: int


def synthetic_game_data(
    rng: np.random.Generator,
    n: int,
    d_fixed: int,
    effects: dict[str, tuple[int, int]],
    task: TaskType = TaskType.LOGISTIC_REGRESSION,
    entity_scale: float = 1.0,
    skew: float = 1.5,
    dtype=np.float32,
) -> GameSyntheticData:
    """GLMix-style data: score = fixed(x) + Σ_e w_e[entity_e(i)]·x_e.

    ``effects`` maps effect name → (num_entities, d_re). Entity membership
    follows a Zipf-ish power law (``skew``) so entity sizes are realistically
    imbalanced — the hard case for the reference's per-entity grouping and
    for our bucketed batching.
    """
    X = rng.normal(size=(n, d_fixed)).astype(dtype)
    X = np.concatenate([X, np.ones((n, 1), dtype)], axis=1)
    intercept_index = d_fixed
    w_fixed = (rng.normal(size=d_fixed + 1) * 0.5).astype(dtype)
    margin = X @ w_fixed

    entity_ids: dict[str, np.ndarray] = {}
    entity_X: dict[str, np.ndarray] = {}
    w_entity: dict[str, np.ndarray] = {}
    for name, (num_entities, d_re) in effects.items():
        probs = (1.0 / np.arange(1, num_entities + 1) ** skew)
        probs /= probs.sum()
        ids = rng.choice(num_entities, size=n, p=probs).astype(np.int32)
        Xe = rng.normal(size=(n, d_re)).astype(dtype)
        We = (rng.normal(size=(num_entities, d_re)) * entity_scale).astype(dtype)
        margin = margin + np.sum(We[ids] * Xe, axis=1)
        entity_ids[name] = ids
        entity_X[name] = Xe
        w_entity[name] = We

    if task is TaskType.LOGISTIC_REGRESSION:
        y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-margin))).astype(dtype)
    elif task is TaskType.LINEAR_REGRESSION:
        y = (margin + rng.normal(scale=0.1, size=n)).astype(dtype)
    elif task is TaskType.POISSON_REGRESSION:
        y = rng.poisson(np.exp(np.clip(margin, -10, 3))).astype(dtype)
    else:  # pragma: no cover
        raise ValueError(task)
    return GameSyntheticData(
        X=X,
        y=y,
        entity_ids=entity_ids,
        entity_X=entity_X,
        w_fixed=w_fixed,
        w_entity=w_entity,
        intercept_index=intercept_index,
    )
