"""Pre-training data validation.

Reference parity: ``photon-client::ml.data.DataValidators`` (SURVEY.md §2.3):
finite-ness checks on features/labels/offsets/weights and per-task label
domain checks (binary labels for logistic/hinge, non-negative for Poisson),
with modes VALIDATE_FULL / VALIDATE_SAMPLE / VALIDATE_DISABLED.

Host-side numpy: validation runs at ingest, before data is shipped to
device (shipping bad rows and detecting NaNs after a compiled step is the
expensive way to find out).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from photon_ml_tpu.types import DataValidationType, TaskType

_SAMPLE_FRACTION = 0.1
_MIN_SAMPLE = 1024


class DataValidationError(ValueError):
    """Raised when input data fails validation."""


def _sample_rows(n: int, mode: DataValidationType, seed: int) -> np.ndarray | slice:
    if mode is DataValidationType.VALIDATE_FULL:
        return slice(None)
    k = max(_MIN_SAMPLE, int(n * _SAMPLE_FRACTION))
    if k >= n:
        return slice(None)
    return np.random.default_rng(seed).choice(n, size=k, replace=False)


def _check_finite(name: str, a: np.ndarray) -> None:
    if not np.isfinite(a).all():
        bad = int((~np.isfinite(a)).sum())
        raise DataValidationError(f"{name}: {bad} non-finite value(s)")


def validate_labels(labels: np.ndarray, task: TaskType) -> None:
    """Per-task label domain checks (parity with the reference's validators)."""
    _check_finite("labels", labels)
    if task.is_classification:
        if not np.isin(labels, (0.0, 1.0)).all():
            raise DataValidationError(
                f"{task.value} requires binary labels in {{0, 1}}; "
                f"found values outside that set"
            )
    elif task is TaskType.POISSON_REGRESSION:
        if (labels < 0).any():
            raise DataValidationError("POISSON_REGRESSION requires non-negative labels")


def validate_arrays(
    task: TaskType,
    labels: np.ndarray,
    features: Mapping[str, np.ndarray] | np.ndarray,
    offsets: np.ndarray | None = None,
    weights: np.ndarray | None = None,
    mode: DataValidationType = DataValidationType.VALIDATE_FULL,
    seed: int = 0,
) -> None:
    """Validate host arrays before batching. Raises ``DataValidationError``.

    ``features`` may be one array or a mapping of shard → array (dense
    values or sparse value arrays — any ndarray is checked for finiteness).
    """
    if mode is DataValidationType.VALIDATE_DISABLED:
        return
    labels = np.asarray(labels)
    rows = _sample_rows(labels.shape[0], mode, seed)
    validate_labels(labels[rows], task)
    feats = features if isinstance(features, Mapping) else {"features": features}
    for sid, f in feats.items():
        _check_finite(f"features[{sid}]", np.asarray(f)[rows])
    if offsets is not None:
        _check_finite("offsets", np.asarray(offsets)[rows])
    if weights is not None:
        w = np.asarray(weights)[rows]
        _check_finite("weights", w)
        if (w < 0).any():
            raise DataValidationError("weights must be non-negative")


def validate_game_batch(batch, task: TaskType, mode: DataValidationType, seed: int = 0) -> None:
    """Validate a built ``GameBatch`` (host transfer of the checked columns).

    Sparse shards check their value arrays (indices are ingest-produced ints).
    """
    if mode is DataValidationType.VALIDATE_DISABLED:
        return
    from photon_ml_tpu.game.data import DenseFeatures

    feats = {
        sid: np.asarray(f.X if isinstance(f, DenseFeatures) else f.values)
        for sid, f in batch.features.items()
    }
    validate_arrays(
        task,
        np.asarray(batch.labels),
        feats,
        offsets=np.asarray(batch.offsets),
        weights=np.asarray(batch.weights),
        mode=mode,
        seed=seed,
    )
