"""LIBSVM format reader → padded device batches.

Used by benchmark config A (a9a logistic — BASELINE.md). The reference
reads Avro, but its test fixtures and the baseline configs are
LIBSVM-shaped; this reader produces either a ``SparseBatch`` (padded
per-row index/value pairs) or a ``DenseBatch``.

Host-side validation: feature indices are bound-checked here because the
device kernels clamp out-of-range gathers silently (XLA semantics).
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.ops.batch import DenseBatch, SparseBatch, dense_batch_from_numpy


def parse_libsvm(
    path: str, zero_based: bool = False
) -> tuple[np.ndarray, list[np.ndarray], list[np.ndarray]]:
    """Parse a LIBSVM file. Returns (labels, per-row index arrays, per-row
    value arrays). Labels -1/+1 are mapped to 0/1."""
    labels: list[float] = []
    rows_idx: list[np.ndarray] = []
    rows_val: list[np.ndarray] = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            y = float(parts[0])
            idx = np.empty(len(parts) - 1, np.int64)
            val = np.empty(len(parts) - 1, np.float32)
            for j, tok in enumerate(parts[1:]):
                k, v = tok.split(":")
                idx[j] = int(k)
                val[j] = float(v)
            if not zero_based:
                idx -= 1
            if len(idx) and idx.min() < 0:
                raise ValueError(f"{path}:{line_no}: negative feature index")
            labels.append(y)
            rows_idx.append(idx)
            rows_val.append(val)
    y = np.asarray(labels, np.float32)
    uniq = np.unique(y)
    if set(uniq.tolist()) <= {-1.0, 1.0}:
        y = (y + 1.0) / 2.0  # -1/+1 → 0/1
    return y, rows_idx, rows_val


def _feature_dim(
    rows_idx: list[np.ndarray], num_features: int | None, add_intercept: bool
) -> tuple[int, int, int | None]:
    """Shared index-derivation/bounds-check for both packing paths.
    Returns (d_raw, d_total, intercept_index); the intercept always gets
    the LAST column."""
    max_idx = max((int(r.max()) for r in rows_idx if len(r)), default=-1)
    d_raw = num_features if num_features is not None else max_idx + 1
    if max_idx >= d_raw:
        raise ValueError(f"feature index {max_idx} out of range for num_features={d_raw}")
    intercept_index = d_raw if add_intercept else None
    return d_raw, d_raw + (1 if add_intercept else 0), intercept_index


def to_padded_sparse(
    labels: np.ndarray,
    rows_idx: list[np.ndarray],
    rows_val: list[np.ndarray],
    num_features: int | None = None,
    add_intercept: bool = True,
    pad_to_multiple: int = 8,
) -> tuple[SparseBatch, int | None]:
    """Pack ragged rows into fixed-width (n, k) index/value arrays.

    k = max row nnz (+1 for the intercept column, which is appended as the
    last feature id). Padding entries are (0, 0.0) — inert by construction.
    Returns (batch, intercept_index).
    """
    import jax.numpy as jnp

    n = len(rows_idx)
    d_raw, d, intercept_index = _feature_dim(rows_idx, num_features, add_intercept)
    k = max((len(r) for r in rows_idx), default=0) + (1 if add_intercept else 0)
    k = max(k, 1)
    k = -(-k // pad_to_multiple) * pad_to_multiple
    idx = np.zeros((n, k), np.int32)
    val = np.zeros((n, k), np.float32)
    for i, (ri, rv) in enumerate(zip(rows_idx, rows_val)):
        m = len(ri)
        idx[i, :m] = ri
        val[i, :m] = rv
        if add_intercept:
            idx[i, m] = intercept_index
            val[i, m] = 1.0
    batch = SparseBatch(
        indices=jnp.asarray(idx),
        values=jnp.asarray(val),
        labels=jnp.asarray(labels, jnp.float32),
        offsets=jnp.zeros((n,), jnp.float32),
        weights=jnp.ones((n,), jnp.float32),
        num_features=d,
    )
    return batch, intercept_index


def read_libsvm(
    path: str,
    num_features: int | None = None,
    dense: bool = False,
    add_intercept: bool = True,
    zero_based: bool = False,
):
    """Read a LIBSVM file into a device batch.

    Returns (batch, intercept_index). ``dense=True`` materializes the full
    (n, d) matrix — appropriate when d is modest (e.g. a9a's 123 features);
    sparse keeps padded (n, k) pairs.
    """
    labels, rows_idx, rows_val = parse_libsvm(path, zero_based=zero_based)
    if not dense:
        return to_padded_sparse(
            labels, rows_idx, rows_val, num_features=num_features, add_intercept=add_intercept
        )
    n = len(rows_idx)
    d_raw, d, intercept_index = _feature_dim(rows_idx, num_features, add_intercept)
    X = np.zeros((n, d), np.float32)
    for i, (ri, rv) in enumerate(zip(rows_idx, rows_val)):
        # accumulate duplicate indices (the sparse path's scatter-add does)
        np.add.at(X[i], ri, rv)
    if intercept_index is not None:
        X[:, intercept_index] = 1.0
    return dense_batch_from_numpy(X, labels), intercept_index
