"""Host-side data layer: readers, index maps, summaries, batching.

Reference parity: ``photon-client``'s IO layer (SURVEY.md §2.3) — the Avro
``DataReader``, ``IndexMap``/``PalDBIndexMap``, feature summarization — plus
a LIBSVM reader for the benchmark configs. The TPU redesign does all
grouping/sorting once at ingest on the host (replacing Spark's runtime
shuffle) and hands the device fixed-shape, padded blocks.
"""

from photon_ml_tpu.data.index_map import IndexMap  # noqa: F401
from photon_ml_tpu.data.libsvm import read_libsvm  # noqa: F401
from photon_ml_tpu.data.summary import FeatureSummary, summarize  # noqa: F401
from photon_ml_tpu.data.synthetic import (  # noqa: F401
    synthetic_game_data,
    synthetic_glm_data,
)
