"""Feature summarization: per-feature statistics feeding normalization.

Reference parity: ``photon-api::ml.stat.BasicStatisticalSummary`` (means,
variances, min/max via Spark) and its use in building a
``NormalizationContext`` (SURVEY.md §2.2); also the
``FeatureSummarizationResultAvro`` output of the legacy driver (§5.5).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass

import numpy as np

from photon_ml_tpu.normalization import NormalizationContext, build_normalization
from photon_ml_tpu.ops.batch import Batch, DenseBatch
from photon_ml_tpu.types import NormalizationType


@dataclass(frozen=True)
class FeatureSummary:
    """Weighted per-feature statistics over a dataset."""

    mean: np.ndarray
    variance: np.ndarray
    min: np.ndarray
    max: np.ndarray
    max_magnitude: np.ndarray
    num_nonzeros: np.ndarray
    count: int

    def to_json(self) -> str:
        d = {k: (v.tolist() if isinstance(v, np.ndarray) else v) for k, v in asdict(self).items()}
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "FeatureSummary":
        d = json.loads(s)
        return cls(
            mean=np.asarray(d["mean"]),
            variance=np.asarray(d["variance"]),
            min=np.asarray(d["min"]),
            max=np.asarray(d["max"]),
            max_magnitude=np.asarray(d["max_magnitude"]),
            num_nonzeros=np.asarray(d["num_nonzeros"]),
            count=d["count"],
        )

    def normalization(
        self, norm_type: NormalizationType, intercept_index: int | None = None
    ) -> NormalizationContext:
        return build_normalization(
            norm_type, self.mean, self.variance, self.max_magnitude, intercept_index
        )


def summarize(batch: Batch) -> FeatureSummary:
    """Compute weighted feature statistics on host (numpy — ingest-time op).

    Sparse semantics match the reference: zero entries participate in the
    moments (a sparse feature's mean includes its implicit zeros).
    """
    if isinstance(batch, DenseBatch):
        X = np.asarray(batch.X, np.float64)
    else:
        n = batch.num_rows
        X = np.zeros((n, batch.num_features), np.float64)
        idx = np.asarray(batch.indices)
        val = np.asarray(batch.values, np.float64)
        rows = np.repeat(np.arange(n), idx.shape[1])
        # scatter-add so duplicate (row, col) pairs accumulate like the device path
        np.add.at(X, (rows, idx.ravel()), val.ravel())
    w = np.asarray(batch.weights, np.float64)
    total = w.sum()
    if total <= 0:
        raise ValueError("summarize: total sample weight is zero")
    mean = (w[:, None] * X).sum(0) / total
    var = (w[:, None] * (X - mean) ** 2).sum(0) / total
    active = w > 0
    Xa = X[active]
    return FeatureSummary(
        mean=mean,
        variance=var,
        min=Xa.min(0) if Xa.size else np.zeros(X.shape[1]),
        max=Xa.max(0) if Xa.size else np.zeros(X.shape[1]),
        max_magnitude=np.abs(Xa).max(0) if Xa.size else np.zeros(X.shape[1]),
        num_nonzeros=(Xa != 0).sum(0).astype(np.int64),
        count=int(active.sum()),
    )
