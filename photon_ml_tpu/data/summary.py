"""Feature summarization: per-feature statistics feeding normalization.

Reference parity: ``photon-api::ml.stat.BasicStatisticalSummary`` (means,
variances, min/max via Spark) and its use in building a
``NormalizationContext`` (SURVEY.md §2.2); also the
``FeatureSummarizationResultAvro`` output of the legacy driver (§5.5).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass

import numpy as np

from photon_ml_tpu.normalization import NormalizationContext, build_normalization
from photon_ml_tpu.ops.batch import Batch, DenseBatch
from photon_ml_tpu.types import NormalizationType


@dataclass(frozen=True)
class FeatureSummary:
    """Weighted per-feature statistics over a dataset."""

    mean: np.ndarray
    variance: np.ndarray
    min: np.ndarray
    max: np.ndarray
    max_magnitude: np.ndarray
    num_nonzeros: np.ndarray
    count: int

    def to_json(self) -> str:
        d = {k: (v.tolist() if isinstance(v, np.ndarray) else v) for k, v in asdict(self).items()}
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "FeatureSummary":
        d = json.loads(s)
        return cls(
            mean=np.asarray(d["mean"]),
            variance=np.asarray(d["variance"]),
            min=np.asarray(d["min"]),
            max=np.asarray(d["max"]),
            max_magnitude=np.asarray(d["max_magnitude"]),
            num_nonzeros=np.asarray(d["num_nonzeros"]),
            count=d["count"],
        )

    def normalization(
        self, norm_type: NormalizationType, intercept_index: int | None = None
    ) -> NormalizationContext:
        return build_normalization(
            norm_type, self.mean, self.variance, self.max_magnitude, intercept_index
        )


def summarize(batch: Batch) -> FeatureSummary:
    """Compute weighted feature statistics on host (numpy — ingest-time op).

    Sparse semantics match the reference: zero entries participate in the
    moments (a sparse feature's mean includes its implicit zeros).
    """
    if isinstance(batch, DenseBatch):
        X = np.asarray(batch.X, np.float64)
    else:
        n = batch.num_rows
        X = np.zeros((n, batch.num_features), np.float64)
        idx = np.asarray(batch.indices)
        val = np.asarray(batch.values, np.float64)
        rows = np.repeat(np.arange(n), idx.shape[1])
        # scatter-add so duplicate (row, col) pairs accumulate like the device path
        np.add.at(X, (rows, idx.ravel()), val.ravel())
    w = np.asarray(batch.weights, np.float64)
    total = w.sum()
    if total <= 0:
        raise ValueError("summarize: total sample weight is zero")
    mean = (w[:, None] * X).sum(0) / total
    var = (w[:, None] * (X - mean) ** 2).sum(0) / total
    active = w > 0
    Xa = X[active]
    return FeatureSummary(
        mean=mean,
        variance=var,
        min=Xa.min(0) if Xa.size else np.zeros(X.shape[1]),
        max=Xa.max(0) if Xa.size else np.zeros(X.shape[1]),
        max_magnitude=np.abs(Xa).max(0) if Xa.size else np.zeros(X.shape[1]),
        num_nonzeros=(Xa != 0).sum(0).astype(np.int64),
        count=int(active.sum()),
    )


def shard_normalization_context(
    summary: FeatureSummary,
    norm_type: NormalizationType,
    shard_id: str,
    intercept_index: int | None,
    log=None,
) -> NormalizationContext:
    """Shared per-shard context policy for the GAME trainers (in-memory
    estimator AND streamed): a shard with no intercept cannot absorb the
    shift term on the output model, so STANDARDIZATION degrades to
    scale-only for that shard (logged, not silent)."""
    if intercept_index is None and norm_type is NormalizationType.STANDARDIZATION:
        norm_type = NormalizationType.SCALE_WITH_STANDARD_DEVIATION
        if log is not None:
            log(
                f"shard {shard_id!r} has no intercept: STANDARDIZATION "
                f"degraded to SCALE_WITH_STANDARD_DEVIATION (shifts need "
                f"an intercept to absorb on the output model)"
            )
    return summary.normalization(norm_type, intercept_index)


def summarize_chunks(
    chunks, num_features: int, cross_process: bool = False
) -> FeatureSummary:
    """Streamed twin of ``summarize``: weighted feature statistics over
    uniform host chunk dicts (``ops.streaming`` builders /
    ``AvroDataReader.iter_batch_chunks``) without materializing the dense
    matrix — one O(d) accumulator pass per chunk. Feeds the out-of-core
    drivers' normalization contexts (reference: the summary/normalization
    stage of ``photon-client::ml.Driver``, SURVEY.md §2.2 — the reference
    computes these on its only, distributed path).

    Semantics match ``summarize`` exactly: implicit zeros participate in
    the moments and min/max; padded rows (weight 0) are inert; duplicate
    (row, col) pairs accumulate before squaring. ``cross_process=True``
    reduces the accumulators across hosts (each host passes only its own
    chunks) so every process returns the GLOBAL summary.
    """
    d = num_features
    w_total = 0.0
    n_active = 0
    s1 = np.zeros(d, np.float64)  # Σ w x
    s2 = np.zeros(d, np.float64)  # Σ w x²
    nnz = np.zeros(d, np.int64)
    vmin = np.full(d, np.inf)
    vmax = np.full(d, -np.inf)
    n_present = np.zeros(d, np.int64)  # active rows where feature explicit

    for chunk in chunks:
        w = np.asarray(chunk["weights"], np.float64)
        active = w > 0
        w_total += w.sum()
        n_active += int(active.sum())
        if "X" in chunk:
            X = np.asarray(chunk["X"], np.float64)
            s1 += (w[:, None] * X).sum(0)
            s2 += (w[:, None] * X * X).sum(0)
            Xa = X[active]
            if Xa.size:
                vmin = np.minimum(vmin, Xa.min(0))
                vmax = np.maximum(vmax, Xa.max(0))
                nnz += (Xa != 0).sum(0)
            n_present += int(active.sum())
        else:
            idx = np.asarray(chunk["indices"], np.int64)
            val = np.asarray(chunk["values"], np.float64)
            n, k = idx.shape
            rows = np.repeat(np.arange(n, dtype=np.int64), k)
            flat_c = idx.ravel()
            flat_v = val.ravel()
            # accumulate duplicates per (row, col) BEFORE squaring, like the
            # dense scatter-add path; padding slots (value 0) drop out of
            # nnz/min/max via keep, and contribute 0 to the moments anyway
            key = rows * d + flat_c
            uniq, inv = np.unique(key, return_inverse=True)
            summed = np.zeros(len(uniq), np.float64)
            np.add.at(summed, inv, flat_v)
            explicit = np.zeros(len(uniq), np.bool_)
            np.bitwise_or.at(explicit, inv, flat_v != 0.0)
            urows = (uniq // d).astype(np.int64)
            ucols = (uniq % d).astype(np.int64)
            keep = explicit  # at least one real (nonzero-valued) slot
            summed, urows, ucols = summed[keep], urows[keep], ucols[keep]
            uw = w[urows]
            np.add.at(s1, ucols, uw * summed)
            np.add.at(s2, ucols, uw * summed * summed)
            a = active[urows]
            if a.any():
                np.minimum.at(vmin, ucols[a], summed[a])
                np.maximum.at(vmax, ucols[a], summed[a])
                np.add.at(nnz, ucols[a], (summed[a] != 0).astype(np.int64))
                np.add.at(n_present, ucols[a], 1)

    if cross_process:
        from photon_ml_tpu.parallel.multihost import (
            allreduce_max_host,
            allreduce_sum_host,
        )

        w_total_a, n_active_a, s1, s2, nnz_f, n_present_f = (
            allreduce_sum_host(
                np.asarray([w_total]), np.asarray([float(n_active)]), s1, s2,
                nnz.astype(np.float64),
                n_present.astype(np.float64),
            )
        )
        w_total = float(w_total_a[0])
        n_active = int(n_active_a[0])
        nnz = nnz_f.astype(np.int64)
        n_present = n_present_f.astype(np.int64)
        (vmax, neg_vmin) = allreduce_max_host(vmax, -vmin)
        vmin = -neg_vmin

    if w_total <= 0:
        raise ValueError("summarize: total sample weight is zero")
    # implicit zeros: a feature absent from some active row has 0 as a
    # min/max candidate; absent-row weight contributes 0 to the moments
    has_implicit = n_present < n_active
    vmin = np.where(n_present == 0, 0.0, np.where(has_implicit, np.minimum(vmin, 0.0), vmin))
    vmax = np.where(n_present == 0, 0.0, np.where(has_implicit, np.maximum(vmax, 0.0), vmax))
    mean = s1 / w_total
    # E[w x²]/W − mean² (matches the dense two-pass variance algebraically;
    # f64 accumulators keep it stable at ingest scale)
    var = np.maximum(s2 / w_total - mean * mean, 0.0)
    return FeatureSummary(
        mean=mean,
        variance=var,
        min=vmin,
        max=vmax,
        max_magnitude=np.maximum(np.abs(vmin), np.abs(vmax)),
        num_nonzeros=nnz,
        count=n_active,
    )
