"""GAME transformer: score a dataset with a trained model.

Reference parity: ``photon-api::ml.transformers.GameTransformer`` (SURVEY.md
§2.2, §3.3): fixed effect scored via a broadcast dot-product, random effects
via a join on entity id, contributions summed (+ link function for
predictions), optional evaluation of the scored data.

TPU-first: there is no broadcast and no join. The fixed-effect coefficient
vector is device-resident; each random-effect model is an (E, d) matrix, so
per-sample scoring is a gather + row-dot — the reference's shuffle/join
boundary becomes an HBM gather.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp

from photon_ml_tpu.evaluation import EvaluationResults, evaluate_all
from photon_ml_tpu.game.data import GameBatch
from photon_ml_tpu.game.models import GameModel

Array = jnp.ndarray


class GameTransformer:
    """Scores ``GameBatch``es with a ``GameModel``."""

    def __init__(self, model: GameModel, logger: Callable[[str], None] | None = None):
        self.model = model
        self._log = logger or (lambda msg: None)

    def transform(self, batch: GameBatch) -> Array:
        """Raw scores: Σ coordinate contributions + data offsets (the
        reference's ``ModelDataScores``)."""
        return self.model.score(batch)

    def predict(self, batch: GameBatch) -> Array:
        """Mean response (inverse link applied to the raw score)."""
        return self.model.predict(batch)

    def transform_with_evaluation(
        self, batch: GameBatch, evaluators: Sequence[str]
    ) -> tuple[Array, EvaluationResults]:
        """Score and evaluate in one pass (parity: scoring driver's optional
        evaluation of scored data). Evaluators consume RAW scores — loss
        metrics re-apply the pointwise loss to the margin."""
        scores = self.transform(batch)
        results = evaluate_all(
            list(evaluators),
            scores,
            batch.labels,
            batch.weights,
            group_ids=batch.host_id_tags(),
        )
        self._log(f"scoring evaluation: {results}")
        return scores, results
