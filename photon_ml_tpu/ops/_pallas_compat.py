"""Version-compat shims for Pallas-TPU API drift.

JAX renamed the Pallas TPU parameter/memory-space types between releases
(``TPUCompilerParams``/``TPUMemorySpace`` in the 0.4.x line became
``CompilerParams``/``MemorySpace`` later). The kernels in ``fused.py`` and
``sparse_tiled.py`` must import-compile on both spellings — the seed
regression was a module-level ``pltpu.CompilerParams`` that raised
``AttributeError`` at import on the installed JAX, taking every test that
transitively imports the fused kernels down with it. All Pallas-TPU
call sites resolve the names through this module instead of touching
``pltpu`` attributes directly.
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu


def _first_attr(*names):
    for name in names:
        obj = getattr(pltpu, name, None)
        if obj is not None:
            return obj
    raise AttributeError(
        f"installed jax.experimental.pallas.tpu exposes none of {names}"
    )


# The params dataclass: new spelling first so behavior tracks the
# installed JAX once it drops the TPU prefix.
_CompilerParams = _first_attr("CompilerParams", "TPUCompilerParams")
_MemorySpace = _first_attr("MemorySpace", "TPUMemorySpace")

# Memory-space constant for ``pl.BlockSpec(memory_space=...)`` — ANY keeps
# an operand in HBM for manual DMA.
ANY = _MemorySpace.ANY


def compiler_params(**kwargs):
    """Build the TPU compiler-params object under either spelling."""
    return _CompilerParams(**kwargs)
