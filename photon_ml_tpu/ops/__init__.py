"""Numeric kernels: pointwise losses, GLM objectives, segment reductions."""
