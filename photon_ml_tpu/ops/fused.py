"""One-pass fused GLM evaluation kernels (Pallas on TPU).

Why this exists: the GLM objective is HBM-bandwidth bound — at training
shapes the feature matrix ``X`` dwarfs everything else, so wall-clock is
set by how many times ``X`` streams from HBM per optimizer iteration and
by how well the streaming overlaps compute. These kernels tile ``X`` over
rows and, per tile resident in VMEM, compute margins (MXU), the pointwise
loss and its derivatives (VPU), and the transposed gradient contraction
(MXU) before moving on — ``X`` streams from HBM exactly ONCE per
evaluation:

- ``fused_value_grad``: (Σ w·l, Xᵀr, Σr) in one pass.
- ``fused_hvp``: (Xᵀ(d2·(Xv)), Σ d2·(Xv)) in one pass — margins and
  ``X·v`` come from the same resident tile via one (d, 2) MXU dot.

Combined with the L-BFGS line search evaluating ``value_and_grad`` per
trial (``optim/lbfgs.py``), a typical accepted step costs ONE X read
instead of the XLA path's margins pass + gradient pass + line-search
value pass.

Hardware subtlety that shapes the code: per-row vectors (labels, offsets,
weights) enter the kernel as ``(bn, 1)`` column blocks, and a column block
pads to 128 VMEM lanes — 128x its HBM footprint. Three such aux inputs,
double-buffered, evict the budget that the ``X`` tile wants (bigger tiles
= better DMA/compute overlap; measured ~1.5x between bn=2048 and
bn=4096). So aux inputs are OPTIONAL at trace time: callers pass
``offsets=None`` / ``weights=None`` when they are identically 0 / 1 (the
ingest layer's common case, detected once per objective construction),
and ``_block_rows`` picks the largest power-of-two row tile whose
X-double-buffer + aux padding fits the VMEM budget.

Reference parity note: this replaces the per-partition fold inside the
reference's ``photon-api::ml.function.ValueAndGradientAggregator`` /
``HessianVectorAggregator`` (SURVEY.md §2.2) with a hand-scheduled TPU
kernel; the reduction across devices stays the objective's single
``lax.psum``.

Semantics match ``GLMObjective`` exactly:
- zero-weight rows contribute exactly 0 (padding can hold any values),
- bfloat16 feature storage keeps bf16 MXU operands with float32
  accumulation (the vector operand is cast to bf16, like
  ``DenseBatch._mm``).

The kernels run in interpreter mode off-TPU, so CPU tests exercise the
identical code path the TPU runs compiled.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops import _pallas_compat

Array = jnp.ndarray

# VMEM budget for pipelined inputs (X double-buffer + padded aux blocks).
# The chip has ~16 MB; leave headroom for accumulators and control.
_VMEM_BUDGET = 14 * 1024 * 1024
_LANE_PAD_BYTES = 128 * 4  # one aux row pads to a full 128-lane f32 line
_MIN_BLOCK_ROWS = 256  # covers the bf16 (16, 128) min tile with headroom
_MAX_BLOCK_ROWS = 8192


def supports_fused(n: int, d: int, dtype) -> bool:
    """Static gate: shapes/dtypes the kernels handle efficiently.

    d must be lane-aligned (the (1, d) accumulator and (bn, d) tiles are
    laid out in 128-wide lanes) and a minimum row tile plus the worst-case
    three aux inputs must fit the VMEM budget — very high-d problems
    belong to the sparse path.
    """
    if dtype not in (jnp.float32, jnp.bfloat16):
        return False
    if d % 128 != 0:
        return False
    return _block_rows(n, d, jnp.dtype(dtype).itemsize, naux=3) is not None


def _block_rows(n: int, d: int, itemsize: int, naux: int) -> int | None:
    """Largest power-of-two row tile whose double-buffered X block plus
    ``naux`` lane-padded aux blocks fit the VMEM budget (None if even the
    minimum tile does not fit)."""
    best = None
    bn = _MIN_BLOCK_ROWS
    while bn <= _MAX_BLOCK_ROWS:
        need = 2 * bn * (d * itemsize + naux * _LANE_PAD_BYTES)
        if need > _VMEM_BUDGET:
            break
        best = bn
        if bn >= n:
            break
        bn *= 2
    return best


def _row_mask(i, bn: int, n: int):
    rows = jax.lax.broadcasted_iota(jnp.int32, (bn, 1), 0) + i * bn
    return rows < n


def _split_refs(refs, has_off: bool, has_wt: bool):
    """(x, y, off|None, wt|None, rest...) from the positional ref list."""
    x_ref, y_ref = refs[0], refs[1]
    k = 2
    off_ref = wt_ref = None
    if has_off:
        off_ref = refs[k]
        k += 1
    if has_wt:
        wt_ref = refs[k]
        k += 1
    return (x_ref, y_ref, off_ref, wt_ref) + tuple(refs[k:])


def _vg_kernel(*refs, loss, n, bn, masked, has_off, has_wt):
    x_ref, y_ref, off_ref, wt_ref, u_ref, c_ref, val_ref, g_ref, rs_ref = (
        _split_refs(refs, has_off, has_wt)
    )
    i = pl.program_id(0)
    x = x_ref[...]
    # MXU f32 dots default to a single bf16 pass in Mosaic; request full
    # f32 precision when the data is stored f32 (bf16 storage keeps the
    # fast single pass — that is its point).
    prec = (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    mask = _row_mask(i, bn, n) if masked else None
    if masked:
        # Out-of-range tile rows hold unspecified values; zero them so the
        # contraction below cannot pick up Inf/NaN garbage through 0·x.
        x = jnp.where(mask, x, jnp.zeros_like(x))
    m = jnp.dot(x, u_ref[...].astype(x.dtype),
                preferred_element_type=jnp.float32, precision=prec)
    m = m - c_ref[...]
    if has_off:
        m = m + off_ref[...]
    y = y_ref[...]
    lv = loss.value(m, y)
    r = loss.d1(m, y)
    if has_wt:
        wt = wt_ref[...]
        if masked:
            wt = jnp.where(mask, wt, 0.0)
        lv = jnp.where(wt != 0.0, wt * lv, 0.0)
        r = jnp.where(wt != 0.0, wt * r, 0.0)
    elif masked:
        lv = jnp.where(mask, lv, 0.0)
        r = jnp.where(mask, r, 0.0)
    # Each tile writes its OWN output slot; partials are tree-reduced in
    # f32 outside the kernel. A single running accumulator would add tile
    # partials sequentially, whose O(grid)·eps rounding is enough to stall
    # the optimizer's Armijo test near convergence (observed on-chip).
    g_ref[...] = jax.lax.dot_general(
        r.astype(x.dtype), x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    ).reshape(g_ref.shape)
    val_ref[...] = jnp.sum(lv).reshape(val_ref.shape)
    rs_ref[...] = jnp.sum(r).reshape(rs_ref.shape)


def _col_spec(bn):
    return pl.BlockSpec((bn, 1), lambda i: (i, 0))


def _const_spec(shape):
    return pl.BlockSpec(shape, lambda i: (0, 0))


def _part_spec(shape):
    """Per-tile output slot: tile i writes leading-index block i. The slot
    is a leading length-1 axis so the last two dims satisfy the TPU block
    rules exactly (they equal the overall array dims)."""
    return pl.BlockSpec((1,) + shape, lambda i: (i, 0, 0))


def _prep(X, labels, offsets, weights):
    """Shared wrapper setup: tile sizing and the X + aux-column input lists
    (one copy, so value_grad and hvp can never diverge in tiling/specs)."""
    n, d = X.shape
    itemsize = jnp.dtype(X.dtype).itemsize
    has_off, has_wt = offsets is not None, weights is not None
    naux = 1 + int(has_off) + int(has_wt)
    bn = _block_rows(n, d, itemsize, naux)
    if bn is None:
        raise ValueError(f"no VMEM-feasible tile for (n={n}, d={d})")
    grid = pl.cdiv(n, bn)
    masked = (n % bn) != 0

    col = lambda a: a.astype(jnp.float32).reshape(n, 1)
    ins = [X, col(labels)]
    in_specs = [pl.BlockSpec((bn, d), lambda i: (i, 0)), _col_spec(bn)]
    if has_off:
        ins.append(col(offsets))
        in_specs.append(_col_spec(bn))
    if has_wt:
        ins.append(col(weights))
        in_specs.append(_col_spec(bn))
    return n, d, bn, grid, masked, has_off, has_wt, ins, in_specs


# Mosaic's default 16MB scoped-vmem cap undercounts the transpose staging
# for the reverse contraction; the chip has more physical VMEM than the cap.
_COMPILER_PARAMS = _pallas_compat.compiler_params(
    dimension_semantics=("arbitrary",),
    vmem_limit_bytes=32 * 1024 * 1024,
)


def fused_value_grad(X, labels, offsets, weights, u, c, *, loss,
                     interpret=False):
    """One X-read (Σᵢ wᵢ·l(mᵢ, yᵢ), Xᵀr, Σᵢ rᵢ) with r = w·l'(m, y) and
    margins m = X@u + offsets − c. ``offsets=None`` means identically 0,
    ``weights=None`` identically 1 (fewer VMEM-padded aux streams → larger
    X tiles). Returns float32 (val, grad, r_sum)."""
    n, d, bn, grid, masked, has_off, has_wt, ins, in_specs = _prep(
        X, labels, offsets, weights
    )
    ins += [u.reshape(d, 1).astype(jnp.float32),
            jnp.asarray(c, jnp.float32).reshape(1, 1)]
    in_specs += [_const_spec((d, 1)), _const_spec((1, 1))]

    kernel = functools.partial(
        _vg_kernel, loss=loss, n=n, bn=bn, masked=masked,
        has_off=has_off, has_wt=has_wt,
    )
    val, g, rs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[_part_spec((1, 1)), _part_spec((1, d)), _part_spec((1, 1))],
        out_shape=[
            jax.ShapeDtypeStruct((grid, 1, 1), jnp.float32),
            jax.ShapeDtypeStruct((grid, 1, d), jnp.float32),
            jax.ShapeDtypeStruct((grid, 1, 1), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(*ins)
    return jnp.sum(val), jnp.sum(g, axis=(0, 1)), jnp.sum(rs)


def _hvp_kernel(*refs, loss, n, bn, masked, has_off, has_wt):
    x_ref, y_ref, off_ref, wt_ref, u_ref, v_ref, sc_ref, hv_ref, qs_ref = (
        _split_refs(refs, has_off, has_wt)
    )
    i = pl.program_id(0)
    x = x_ref[...]
    prec = (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    mask = _row_mask(i, bn, n) if masked else None
    if masked:
        x = jnp.where(mask, x, jnp.zeros_like(x))
    uv = jnp.concatenate([u_ref[...], v_ref[...]], axis=1).astype(x.dtype)
    muv = jnp.dot(x, uv, preferred_element_type=jnp.float32,
                  precision=prec)  # (bn, 2)
    m = muv[:, 0:1] - sc_ref[0:1, 0:1]
    if has_off:
        m = m + off_ref[...]
    mv = muv[:, 1:2] - sc_ref[0:1, 1:2]
    d2 = loss.d2(m, y_ref[...])
    if has_wt:
        wt = wt_ref[...]
        if masked:
            wt = jnp.where(mask, wt, 0.0)
        d2 = jnp.where(wt != 0.0, wt * d2, 0.0)
    elif masked:
        d2 = jnp.where(mask, d2, 0.0)
    q = d2 * mv
    # per-tile partials, reduced outside (see _vg_kernel)
    hv_ref[...] = jax.lax.dot_general(
        q.astype(x.dtype), x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    ).reshape(hv_ref.shape)
    qs_ref[...] = jnp.sum(q).reshape(qs_ref.shape)


def fused_hvp(X, labels, offsets, weights, u, v, c, cv, *, loss,
              interpret=False):
    """One X-read Gauss-Newton Hv: (Xᵀq, Σq) with q = w·l''(m, y)·(Xv − cv)
    and m = X@u + offsets − c. ``offsets``/``weights`` may be None as in
    ``fused_value_grad``. Returns float32 (hv, q_sum)."""
    n, d, bn, grid, masked, has_off, has_wt, ins, in_specs = _prep(
        X, labels, offsets, weights
    )
    sc = jnp.stack([jnp.asarray(c, jnp.float32),
                    jnp.asarray(cv, jnp.float32)]).reshape(1, 2)
    ins += [u.reshape(d, 1).astype(jnp.float32),
            v.reshape(d, 1).astype(jnp.float32), sc]
    in_specs += [_const_spec((d, 1)), _const_spec((d, 1)), _const_spec((1, 2))]

    kernel = functools.partial(
        _hvp_kernel, loss=loss, n=n, bn=bn, masked=masked,
        has_off=has_off, has_wt=has_wt,
    )
    hv, qs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[_part_spec((1, d)), _part_spec((1, 1))],
        out_shape=[
            jax.ShapeDtypeStruct((grid, 1, d), jnp.float32),
            jax.ShapeDtypeStruct((grid, 1, 1), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(*ins)
    return jnp.sum(hv, axis=(0, 1)), jnp.sum(qs)
