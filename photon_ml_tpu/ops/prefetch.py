"""Bounded-depth host-ingest prefetch pipeline + device-resident chunk cache.

The streamed consumers (``ops/streaming.py`` chunk objectives and scorer,
``game/streaming.py`` bucket ingest and visit scoring,
``supervised/cross_validation.py`` fold ingest) all share one critical-path
shape: a host-side *preparation* step per work item — feature slicing,
tile-COO layout build/cache lookup, host staging, ``device_put`` — followed
by device compute, repeated serially item after item, pass after pass.
Input-pipeline overlap (tf.data-style) is the standard fix: prepare item
``i+k`` on background worker threads while the device computes item ``i``.

Two invariants make the overlap safe to turn on by default:

- **Preparation only is reordered.** Workers produce *inputs* (host arrays
  staged, device buffers transferred); every kernel call and every
  accumulation happens on the consumer thread in the original item order,
  so all outputs are bitwise identical to the synchronous schedule (float
  summation order is untouched). ``PHOTON_PREFETCH_DEPTH=0`` restores the
  synchronous code path bit-for-bit (callers branch to their unchanged
  pre-prefetch loop).
- **Errors propagate, never deadlock.** A worker exception is re-raised in
  the consumer when that item's turn comes; remaining queued work is
  cancelled. The worker pool is process-wide and task-independent (no task
  ever waits on another task), so there is no lock-ordering to get wrong.

On top of the pipeline sits a process-wide **device-resident chunk cache**
(LRU, modeled on ``ops/tile_cache.py``): the streamed optimizers re-stage
the IDENTICAL chunk sequence on every objective pass — L-BFGS/TRON make
tens of passes over the same host arrays — so passes 2..N should replay
already-resident device buffers instead of re-paying ``device_put``. The
cache is byte-budgeted against ``device_hbm_budget_bytes`` (the same
query the streaming decision rule uses) and keyed by host-array STORAGE
identity (data pointer + layout, made safe by holding a reference to the
host array — a held array's address can never be reused by the allocator,
the ``_FP_MEMO`` argument in ``ops/streaming.py``). Entries evicted from
the device tier spill to a host-staged tier: the prepared host arrays are
retained so a later re-entry pays one ``device_put``, never a re-pack.
Cached host arrays are treated as immutable — the same contract the
tile-layout cache already imposes on indices/values. Lifecycle: entries
for discarded datasets age out by LRU as new traffic arrives (both tiers
are budget-bounded, so a dead objective can pin at most the budgets, not
grow without bound); a long-running driver that swaps datasets and wants
the memory back eagerly calls ``clear_cache()``.

Knobs (``RETUNE_ENV``/call-time-read discipline, like the kernel
constants): ``PHOTON_PREFETCH_DEPTH`` (default 2; 0 = synchronous) and
``PHOTON_CHUNK_CACHE_BUDGET`` (bytes; default = the queried device
budget). The environment override is read at call time so child bench
processes and tests retune without import-order games.

Observability: the pipeline's stages report wall-seconds through
``utils/profiling.py`` stage counters — ``prefetch.host_pack_s`` (host
preparation inside workers), ``prefetch.device_put_s`` (transfer calls),
``prefetch.consumer_wait_s`` (time the CONSUMER blocked waiting for a
prepared item — the un-hidden remainder; ~0 means the pipeline fully hid
preparation) — so the overlap is observable, not asserted.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterator

import numpy as np

from photon_ml_tpu.obs.metrics import REGISTRY as _REGISTRY
from photon_ml_tpu.utils import profiling

# -- knobs (module globals read at CALL time; env override wins) ----------

PREFETCH_DEPTH = 2  # items prepared ahead of the consumer; 0 = synchronous
CHUNK_CACHE_BUDGET = None  # bytes; None = a minority fraction of HBM
# host-staged spill tier budget: evicted device entries keep their prepared
# host arrays up to this many bytes (re-entry pays a device_put, not a
# re-pack); numpy host RAM is the cheap tier
HOST_SPILL_BUDGET = None  # bytes; None = same as the device budget
# the chunk tier's default share of device HBM: deliberately a MINORITY
# fraction — the streamed paths run precisely when the dataset EXCEEDS the
# 0.75-fraction residency budget, so the cache must leave the bulk of HBM
# for kernels, coefficients and XLA scratch (the pre-cache path kept at
# most two chunks resident). When the chunk working set exceeds this, hits
# degrade toward plain per-pass transfers — never toward an allocation
# failure.
_DEFAULT_HBM_FRACTION = 0.25
# bytes_limit never changes mid-process: memoize the backend query so the
# per-array hot path (budget checks under the cache lock) costs a list
# read, not a device call
_device_budget_memo: list = []


def prefetch_depth() -> int:
    """The pipeline depth, read at CALL time (env wins over the module
    global, so bench child processes and tests retune without touching
    import order)."""
    env = os.environ.get("PHOTON_PREFETCH_DEPTH")
    if env is not None and env != "":
        return max(int(env), 0)
    return max(int(PREFETCH_DEPTH), 0)


def chunk_cache_budget_bytes() -> int:
    """Device-tier byte budget, read at CALL time (env > module global >
    memoized ``_DEFAULT_HBM_FRACTION`` of the queried device limit)."""
    env = os.environ.get("PHOTON_CHUNK_CACHE_BUDGET")
    if env is not None and env != "":
        return max(int(env), 0)
    if CHUNK_CACHE_BUDGET is not None:
        return max(int(CHUNK_CACHE_BUDGET), 0)
    if not _device_budget_memo:
        from photon_ml_tpu.ops.streaming import device_hbm_budget_bytes

        # ``default`` is the no-memory-stats fallback (CPU test backends)
        # and is NOT scaled by ``fraction`` — pass the already-scaled value
        # lint: waive(conc-unlocked-mutation) memoize-once of an immutable backend quote: racing appends store the same value and only [0] is read
        _device_budget_memo.append(int(device_hbm_budget_bytes(
            default=2e9, fraction=_DEFAULT_HBM_FRACTION,
        )))
    return _device_budget_memo[0]


def host_spill_budget_bytes() -> int:
    if HOST_SPILL_BUDGET is not None:
        return max(int(HOST_SPILL_BUDGET), 0)
    return chunk_cache_budget_bytes()


# -- the bounded-depth pipeline -------------------------------------------

# One process-wide worker pool, lazily built: tasks are independent
# preparations (no task waits on a task), so sharing a pool across
# concurrent streams cannot deadlock; per-call pools would pay thread
# creation on every optimizer pass.
_pool_lock = threading.Lock()
_pool: ThreadPoolExecutor | None = None


def _worker_pool() -> ThreadPoolExecutor:
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(
                max_workers=min(8, (os.cpu_count() or 2)),
                thread_name_prefix="photon-prefetch",
            )
        return _pool


# per-thread exclusion so host_pack_s and device_put_s stay DISJOINT: a
# prepare callable usually ends in a transfer, and nesting the put timer
# inside the pack timer would double-count it (the stage split would sum
# past worker wall time and misattribute transfer cost as pack cost)
_stage_tls = threading.local()


def timed_device_put(a):
    """``jax.device_put`` accounted under ``prefetch.device_put_s`` and
    EXCLUDED from any enclosing ``_timed_prepare`` pack time. Use for
    transfers inside prepare callables that bypass the chunk cache."""
    import time

    import jax

    t0 = time.perf_counter()
    try:
        return jax.device_put(a)
    finally:
        dt = time.perf_counter() - t0
        profiling.add_seconds("prefetch.device_put_s", dt)
        if hasattr(_stage_tls, "put_s"):
            _stage_tls.put_s += dt


def _timed_prepare(prepare: Callable[[int], Any], i: int) -> Any:
    import time

    t0 = time.perf_counter()
    _stage_tls.put_s = 0.0
    try:
        return prepare(i)
    finally:
        dt = time.perf_counter() - t0 - _stage_tls.put_s
        del _stage_tls.put_s
        profiling.add_seconds("prefetch.host_pack_s", max(dt, 0.0))


def prefetch_iter(
    num_items: int,
    prepare: Callable[[int], Any],
    depth: int | None = None,
) -> Iterator[Any]:
    """Yield ``prepare(0..num_items-1)`` IN ORDER, preparing up to
    ``depth`` items ahead on worker threads. ``depth=None`` reads the
    knob; ``depth<=0`` runs fully synchronously (no threads touched).
    A preparation error re-raises at that item's turn; queued later items
    are cancelled (already-running ones finish and are dropped)."""
    if depth is None:
        depth = prefetch_depth()
    if threading.current_thread().name.startswith("photon-prefetch"):
        # a pool worker consuming a NESTED pipeline would block on pool
        # tasks while occupying a pool slot — with enough such waiters the
        # pool starves. No consumer nests today; degrade to synchronous so
        # one never can.
        depth = 0
    if depth <= 0 or num_items <= 1:
        for i in range(num_items):
            yield prepare(i)
        return
    pool = _worker_pool()
    futs: deque = deque()
    nxt = 0
    try:
        while nxt < num_items and len(futs) < depth:
            futs.append(pool.submit(_timed_prepare, prepare, nxt))
            nxt += 1
        while futs:
            f = futs.popleft()
            with profiling.stage_timer("prefetch.consumer_wait_s"):
                out = f.result()  # re-raises a worker exception here
            if nxt < num_items:
                futs.append(pool.submit(_timed_prepare, prepare, nxt))
                nxt += 1
            yield out
    finally:
        for f in futs:  # consumer bailed (error or early close): drop tail
            f.cancel()


# -- the device-resident chunk cache --------------------------------------
# PER-ARRAY granularity: a GAME coordinate visit swaps only the residual
# offsets column of each chunk — per-array keys re-transfer exactly the
# changed column while labels/weights/features replay resident buffers.

_cache_lock = threading.Lock()
# key -> (host_ref, staged_ref, device_array, dev_nbytes, host_nbytes);
# insertion order = LRU. ``host_ref`` is the caller's original array (it
# OWNS the data-pointer key: holding it makes the key safe); ``staged_ref``
# is the transfer-dtype twin actually shipped (identical to host_ref on the
# f32 rung).
_device_tier: "OrderedDict[tuple, tuple]" = OrderedDict()
_device_bytes = 0
# aggregate HOST RAM pinned by device-resident entries (each entry's
# host_ref keeps a view's whole base alive): bounded against the host
# spill budget, so many small device entries can never pin unbounded
# host memory between them — the pre-ladder guarantee, kept in aggregate
_device_host_bytes = 0
# key -> (host_ref, staged_ref, host_nbytes): spilled entries (refs
# retained so a re-entry pays one device_put — never a re-slice/re-pack —
# and so the data-pointer key stays safe)
_host_tier: "OrderedDict[tuple, tuple]" = OrderedDict()
_host_bytes = 0
_cache_stats = {
    "device_hits": 0, "host_hits": 0, "misses": 0, "evictions": 0,
}

# Raw (un-tiled) streamed feature arrays packed at the transfer dtype:
# under the PHOTON_KERNEL_DTYPE precision ladder (ops/sparse_tiled), the
# tile-COO consumers already move their packed slabs at the storage dtype;
# these are the remaining fat columns of raw chunk dicts. Both reduced
# rungs transfer bf16 here (int8's symmetric scales exist only inside the
# packed tile layouts; a raw operand has no tile to carry them on) —
# labels/offsets/weights stay f32, so the f32 rung is byte-identical to
# the pre-ladder path.
_PACK_KEYS = ("values", "X")


def transfer_dtype() -> str:
    """The raw-chunk transfer rung derived from the kernel-dtype knob at
    CALL time: 'f32' (identity) or 'bf16'."""
    from photon_ml_tpu.ops.sparse_tiled import kernel_dtype

    return "f32" if kernel_dtype() == "f32" else "bf16"


def _pack_for_transfer(a: np.ndarray):
    """One feature array → its bf16 transfer twin (f32 inputs only; other
    dtypes pass through untouched)."""
    import ml_dtypes

    return a.astype(ml_dtypes.bfloat16) if a.dtype == np.float32 else a


def pack_host_chunk(host_tree: dict) -> dict:
    """Pack a prepared host chunk's feature arrays at the ladder's
    transfer dtype (no-op on the f32 rung). The synchronous depth-0
    streamed path uses this directly; the cached path packs per-array on
    cache miss so repeat passes key on the caller's ORIGINAL storage."""
    if transfer_dtype() == "f32":
        return host_tree
    return {
        k: _pack_for_transfer(np.asarray(v)) if k in _PACK_KEYS else v
        for k, v in host_tree.items()
    }


def _storage_key(a: np.ndarray) -> tuple:
    ai = a.__array_interface__
    return (ai["data"], a.shape, ai["strides"], str(a.dtype))


def _evict_over_budget_locked() -> None:
    global _device_bytes, _device_host_bytes, _host_bytes
    budget = chunk_cache_budget_bytes()
    host_budget = host_spill_budget_bytes()
    while _device_tier and (
        _device_bytes > budget or _device_host_bytes > host_budget
    ):
        key, (host_ref, staged, _dev, nb_dev, nb_host) = (
            _device_tier.popitem(last=False)
        )
        _device_bytes -= nb_dev
        _device_host_bytes -= nb_host
        _cache_stats["evictions"] += 1
        _REGISTRY.counter_inc("prefetch.cache.evictions")
        # spill: keep the staged host twin so re-entry is one device_put,
        # never a re-slice/re-pack upstream
        if key not in _host_tier:
            _host_bytes += nb_host
        _host_tier[key] = (host_ref, staged, nb_host)
        _host_tier.move_to_end(key)
    while _host_tier and _host_bytes > host_budget:
        _, (_ref, _staged, nb) = _host_tier.popitem(last=False)
        _host_bytes -= nb


def _cached_put_one(name, a):
    """One host array → its device-resident twin, through the LRU."""
    global _device_bytes, _device_host_bytes, _host_bytes
    a = np.asarray(a)
    tdt = transfer_dtype()
    packs = tdt != "f32" and name in _PACK_KEYS and a.dtype == np.float32
    # the transfer dtype is part of the key for packed arrays: a bf16-rung
    # entry must never serve an f32 pass (or vice versa) after the knob
    # toggles mid-process — same never-by-luck rule as the kernel caches
    key = _storage_key(a) + ((tdt,) if packs else ())
    staged = None
    with _cache_lock:
        hit = _device_tier.get(key)
        if hit is not None:
            _device_tier.move_to_end(key)
            _cache_stats["device_hits"] += 1
            # registry twins of the stats (hit/miss BYTES: the transfer
            # traffic the cache saved/paid — what a sweep actually diffs;
            # counted at the DEVICE size, i.e. post-pack dtype)
            _REGISTRY.counter_inc("prefetch.cache.hit_bytes", hit[3])
            return hit[2]
        spilled = _host_tier.pop(key, None)
        if spilled is not None:
            _host_bytes -= spilled[2]
            _cache_stats["host_hits"] += 1
            _REGISTRY.counter_inc(
                "prefetch.cache.host_hit_bytes", int(spilled[1].nbytes)
            )
            staged = spilled[1]
        else:
            _cache_stats["misses"] += 1
    if staged is None:
        staged = _pack_for_transfer(a) if packs else a
        # registry counters take their own lock — no cache state touched
        _REGISTRY.counter_inc("prefetch.cache.miss_bytes", int(staged.nbytes))
    # transfer OUTSIDE the lock (the expensive part; concurrent misses for
    # the same key both transfer — last insert wins, both correct)
    dev = timed_device_put(staged)
    # the DEVICE tier charges what the entry actually holds in HBM — the
    # post-pack device array's nbytes (a bf16 pass fits ~2x the chunks of
    # an f32 pass under the same budget, and a view's device copy is just
    # the slice). What the entry pins in HOST RAM (a view's whole base —
    # see _pinned_nbytes) is bounded separately against the HOST budget:
    # a few-KB slice of a base larger than the spill budget never caches,
    # so holding its ref can never pin unbounded host RAM past both
    # budgets (the pre-ladder guarantee, kept).
    nb_dev = int(dev.nbytes)
    nb_host = _pinned_nbytes(a) + (int(staged.nbytes) if staged is not a else 0)
    with _cache_lock:
        if (
            nb_dev <= chunk_cache_budget_bytes()
            and nb_host <= host_spill_budget_bytes()
        ):  # over-budget on either axis: never pinned
            prev = _device_tier.pop(key, None)
            if prev is not None:
                _device_bytes -= prev[3]
                _device_host_bytes -= prev[4]
            _device_tier[key] = (a, staged, dev, nb_dev, nb_host)
            _device_bytes += nb_dev
            _device_host_bytes += nb_host
            _device_tier.move_to_end(key)
            _evict_over_budget_locked()
    return dev


def _pinned_nbytes(a: np.ndarray) -> int:
    """A HOST-tier entry's budget charge: what holding the reference
    actually PINS. A numpy VIEW keeps its whole base array alive, so
    charging the slice's own nbytes would let a few-KB entry pin a
    multi-GB dataset past the spill budget; views are charged at their
    base's size (conservative — a base larger than the budget simply
    never spills, degrading to plain per-pass transfers)."""
    base = a.base
    if isinstance(base, np.ndarray):
        return int(base.nbytes)
    return int(a.nbytes)


def cached_device_put(host_tree: dict) -> dict:
    """Device-resident arrays for a prepared host chunk (dict of numpy
    arrays) through the process-wide per-array cache: a repeat pass over
    the SAME host storage returns already-resident device buffers
    (optimizer passes 2..N skip the transfer entirely), and a per-visit
    offsets swap re-transfers only the offsets column. Feature arrays
    (``values``/``X``) transfer at the precision ladder's storage dtype
    (``pack_host_chunk``), so a bf16 pass halves both the HBM footprint
    and the host→device traffic of raw chunks. Thread-safe — prefetch
    workers for different chunks race here by design. Keyed by the
    CALLER's storage identity (+ transfer dtype for packed arrays), so
    cached arrays must not be mutated in place (the framework never does;
    fresh arrays per visit get fresh keys)."""
    return {k: _cached_put_one(k, v) for k, v in host_tree.items()}


def cache_stats() -> dict:
    with _cache_lock:
        return dict(
            _cache_stats,
            device_entries=len(_device_tier),
            device_bytes=_device_bytes,
            device_host_pinned_bytes=_device_host_bytes,
            host_entries=len(_host_tier),
            host_bytes=_host_bytes,
        )


def clear_cache() -> None:
    global _device_bytes, _device_host_bytes, _host_bytes
    with _cache_lock:
        _device_tier.clear()
        _host_tier.clear()
        _device_bytes = 0
        _device_host_bytes = 0
        _host_bytes = 0
        for k in _cache_stats:
            _cache_stats[k] = 0
