"""One streaming executor for every streamed consumer in the package.

The scale story (PAPER.md §: #samples via streaming) grew as four
hand-wired copies of the same source → prepare → device-window → consume
loop — ``StreamingGLMObjective._stream``, both ``stream_scores``, the
``StreamedGameTrainer`` bucket/visit ingest, CV fold ingest — plus the
serving/refresh streams of PR 19. Each copy wires the prefetch pool and
the chunk cache separately, so no two streams can share a byte of HBM
budget or a prepared chunk, and a latency-critical stream cannot ask a
background stream to get out of the way. This module is the ONE pipeline
they all ride when ``PHOTON_STREAM_EXECUTOR=1``:

- **Registration** (:func:`register`): each consumer declares a name,
  a scheduling priority and (optionally) a share of the chunk-cache
  budget. The registration owns the telemetry surface — the executor
  emits ``stream/<name>`` spans and per-consumer counters
  (``stream.<name>.items`` / ``.wait_s`` / ``.hit_bytes`` /
  ``.miss_bytes`` / ``.yields`` and the ``.charged_bytes`` gauge), so a
  ported consumer that silently drops its stream span fails the
  telemetry-surface lint, not review.
- **Scheduling** (:func:`stream`): the same bounded-depth pipeline as
  ``prefetch.prefetch_iter`` (same worker pool, same in-order yield, same
  error propagation), except the effective depth is re-read on every
  submission: while a strictly higher-priority stream is active
  (:func:`active_stream` — the serve window marks itself active while it
  scores), a lower-priority stream submits at depth 1, yielding its
  prefetch slots to the critical path. Scheduling touches PREPARATION
  ONLY — kernel calls and accumulation stay on the consumer thread in
  item order (the PR-3 contract), so outputs are bitwise identical at
  any priority interleaving.
- **Arbitration** (:func:`cached_device_put`): one process-wide
  multi-tenant chunk cache. Entries are keyed by chunk CONTENT
  fingerprint × pack dtype × fe_range — not by host storage identity —
  so a validation stream replaying training chunks through a different
  loader (fresh host arrays, identical bytes) re-uses the resident
  device buffers instead of re-transferring its own copy. Every
  consumer holding an entry is charged its full byte size; a consumer
  exceeding its budget share releases ITS least-recently-used holds
  first (a shared entry stays device-resident until the LAST holder
  releases — the refcount rule), so one stream's pressure can never
  evict a neighbor's working set before its own.

``PHOTON_STREAM_EXECUTOR=0`` (the default) is wired OUT of every
consumer: each keeps its pre-executor branch verbatim — same transfer
counters, same span tree, bitwise outputs.

Knobs (env > module global, read at CALL time, strict parse):
``PHOTON_STREAM_EXECUTOR`` (flag), ``PHOTON_STREAM_PRIORITY``
(spec: ``name=int,...`` overriding per-consumer priorities) and
``PHOTON_STREAM_SHARE`` (spec: ``name=fraction,...`` capping a
consumer's charged bytes at that fraction of the chunk-cache budget;
unlisted consumers are capped only by the whole budget).

Accounting (BYTES, through the PR-4 registry): constant-named
``stream.cache.hit_bytes`` / ``stream.cache.shared_hit_bytes`` (hits on
entries ANOTHER consumer admitted — the cross-stream dedup the X_stream
bench measures) / ``stream.cache.miss_bytes`` (actual transfer traffic)
/ ``stream.cache.evictions``, plus the per-consumer wildcard family
above. All rendered by ``report summarize``'s stream section.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Iterator

import numpy as np

from photon_ml_tpu.obs import span
from photon_ml_tpu.obs.metrics import REGISTRY
from photon_ml_tpu.utils import profiling

# -- knobs (module globals read at CALL time; env override wins) ----------

STREAM_EXECUTOR = 0  # 1 = route ported consumers through the executor
STREAM_PRIORITY = ""  # spec "name=int,...": per-consumer priority override
STREAM_SHARE = ""  # spec "name=frac,...": per-consumer budget-share cap


def stream_executor_enabled() -> bool:
    """The executor toggle, read at CALL time (env > module global).
    Off (the default) keeps every ported consumer on its pre-executor
    branch bit-for-bit."""
    env = os.environ.get("PHOTON_STREAM_EXECUTOR")
    if env is not None and env != "":
        return bool(int(env))
    return bool(int(STREAM_EXECUTOR))


def stream_priority_spec() -> str:
    """Raw ``name=int,...`` priority-override spec (env > module
    global). Parsed strictly by :func:`priority_of` — a malformed entry
    raises, naming the value (never silently default)."""
    env = os.environ.get("PHOTON_STREAM_PRIORITY")
    if env is not None:
        return env
    return str(STREAM_PRIORITY)


def stream_share_spec() -> str:
    """Raw ``name=fraction,...`` budget-share spec (env > module
    global); fractions are of the chunk-cache byte budget
    (``prefetch.chunk_cache_budget_bytes``)."""
    env = os.environ.get("PHOTON_STREAM_SHARE")
    if env is not None:
        return env
    return str(STREAM_SHARE)


def _parse_spec(spec: str, knob: str, cast) -> dict:
    out: dict = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, sep, raw = item.partition("=")
        if not sep or not name.strip():
            raise ValueError(
                f"{knob}: malformed entry {item!r} — expected "
                f"'consumer=value[,consumer=value...]'"
            )
        out[name.strip()] = cast(raw.strip())
    return out


#: default scheduling priorities for the consumers this PR ports —
#: serving preempts everything, refresh yields to everything; the four
#: training-side streams share the middle band (they never overlap in
#: the current drivers, so relative order among them is inert).
_DEFAULT_PRIORITY = {
    "serve": 100,
    "objective": 50,
    "scores": 50,
    "re_gather": 50,
    "re_scores": 50,
    "cv": 50,
    "refresh": 10,
}
_FALLBACK_PRIORITY = 50

# -- consumer registration -------------------------------------------------

_reg_lock = threading.Lock()
_registered: dict[str, int] = {}  # name -> registration-time priority
# name -> nesting count of live streams / active windows (re-entrant)
_active: dict[str, int] = {}


def register(name: str, priority: int | None = None) -> None:
    """Declare a stream consumer (idempotent). ``priority`` defaults to
    the consumer's entry in the default table; the env spec wins over
    both at call time."""
    with _reg_lock:
        if priority is not None:
            _registered[name] = int(priority)
        else:
            _registered.setdefault(
                name, _DEFAULT_PRIORITY.get(name, _FALLBACK_PRIORITY)
            )


def priority_of(name: str) -> int:
    """Effective priority: env/global spec > registration > default
    table > fallback. Read at CALL time like every knob."""
    overrides = _parse_spec(stream_priority_spec(), "PHOTON_STREAM_PRIORITY", int)
    if name in overrides:
        return int(overrides[name])
    with _reg_lock:
        if name in _registered:
            return _registered[name]
    return _DEFAULT_PRIORITY.get(name, _FALLBACK_PRIORITY)


def share_fraction(name: str) -> float:
    """This consumer's cap on charged cache bytes, as a fraction of the
    chunk-cache budget; 1.0 (no per-consumer cap) when unlisted."""
    shares = _parse_spec(stream_share_spec(), "PHOTON_STREAM_SHARE", float)
    frac = float(shares.get(name, 1.0))
    if not 0.0 < frac <= 1.0:
        raise ValueError(
            f"PHOTON_STREAM_SHARE: share for {name!r} must be in (0, 1], "
            f"got {frac}"
        )
    return frac


class active_stream:
    """Mark ``name`` active for the scheduler's duration checks — the
    serve window wraps its scoring in this so concurrently-running
    lower-priority streams yield their prefetch slots. Re-entrant."""

    def __init__(self, name: str) -> None:
        self.name = name

    def __enter__(self) -> "active_stream":
        register(self.name)
        with _reg_lock:
            _active[self.name] = _active.get(self.name, 0) + 1
        return self

    def __exit__(self, *exc) -> None:
        with _reg_lock:
            n = _active.get(self.name, 0) - 1
            if n <= 0:
                _active.pop(self.name, None)
            else:
                _active[self.name] = n


def _higher_priority_active(name: str) -> bool:
    mine = priority_of(name)
    with _reg_lock:
        others = [n for n in _active if n != name]
    return any(priority_of(n) > mine for n in others)


# -- the scheduled stream --------------------------------------------------


def stream(
    name: str,
    num_items: int,
    prepare: Callable[[int], Any],
    depth: int | None = None,
) -> Iterator[Any]:
    """Yield ``prepare(0..num_items-1)`` IN ORDER through the executor:
    the prefetch worker pool prepares up to ``depth`` items ahead
    (knob default), re-checking on every submission whether a strictly
    higher-priority stream is active — if so this stream tops up to
    depth 1 only (its slots yield to the critical path; ``.yields``
    counts the deferrals). Consume order is always item order, so
    scheduling can never change a consumer's outputs. Error semantics
    are ``prefetch_iter``'s: a worker exception re-raises at that item's
    turn and the queued tail is cancelled."""
    from photon_ml_tpu.ops import prefetch

    register(name)
    if num_items <= 0:
        return
    base = prefetch.prefetch_depth() if depth is None else max(int(depth), 0)
    if threading.current_thread().name.startswith("photon-prefetch"):
        base = 0  # nested-consumer guard, same rule as prefetch_iter
    REGISTRY.counter_inc("stream.streams")
    REGISTRY.counter_inc(f"stream.{name}.items", num_items)
    with active_stream(name), span(f"stream/{name}", items=num_items):
        if base <= 0 or num_items <= 1:
            for i in range(num_items):
                yield prepare(i)
            return
        pool = prefetch._worker_pool()
        from collections import deque

        futs: deque = deque()
        nxt = 0

        def _top_up() -> None:
            nonlocal nxt
            eff = base
            if _higher_priority_active(name):
                eff = min(base, 1)
            limited = False
            while nxt < num_items and len(futs) < eff:
                futs.append(
                    pool.submit(prefetch._timed_prepare, prepare, nxt)
                )
                nxt += 1
            if nxt < num_items and eff < base and len(futs) >= eff:
                limited = True
            if limited:
                REGISTRY.counter_inc(f"stream.{name}.yields")

        try:
            _top_up()
            while futs:
                f = futs.popleft()
                t0 = time.perf_counter()
                with profiling.stage_timer("prefetch.consumer_wait_s"):
                    out = f.result()  # re-raises a worker exception here
                REGISTRY.timer_add(
                    f"stream.{name}.wait_s", time.perf_counter() - t0
                )
                _top_up()
                yield out
        finally:
            for f in futs:  # consumer bailed: drop the prepared tail
                f.cancel()


# -- the multi-tenant chunk-cache arbiter ----------------------------------

# content-fingerprint memo keyed by host STORAGE identity: repeat passes
# over unchanged arrays must not re-hash chunk bytes. Holding the array
# reference makes the data-pointer key safe (a held array's address can
# never be reused by the allocator — the ops/streaming _FP_MEMO argument).
_fp_lock = threading.Lock()
_FP_MEMO_CAP = 4096
_fp_memo: "OrderedDict[tuple, tuple]" = OrderedDict()  # skey -> (ref, digest)


def _content_fingerprint(a: np.ndarray) -> bytes:
    from photon_ml_tpu.ops import prefetch

    skey = prefetch._storage_key(a)
    with _fp_lock:
        hit = _fp_memo.get(skey)
        if hit is not None:
            _fp_memo.move_to_end(skey)
            return hit[1]
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((str(a.dtype), a.shape)).encode())
    h.update(np.ascontiguousarray(a).tobytes())
    digest = h.digest()
    with _fp_lock:
        _fp_memo[skey] = (a, digest)
        while len(_fp_memo) > _FP_MEMO_CAP:
            _fp_memo.popitem(last=False)
    return digest


class _Entry:
    __slots__ = ("dev", "nbytes", "holders", "admitted_by")

    def __init__(self, dev, nbytes: int, admitted_by: str) -> None:
        self.dev = dev
        self.nbytes = int(nbytes)
        self.holders: "OrderedDict[str, None]" = OrderedDict()
        self.admitted_by = admitted_by


_arb_lock = threading.Lock()
_entries: "OrderedDict[tuple, _Entry]" = OrderedDict()  # global LRU
_total_bytes = 0
_charges: dict[str, int] = {}  # consumer -> charged bytes
_holder_lru: dict[str, "OrderedDict[tuple, None]"] = {}
_arb_stats = {"hits": 0, "shared_hits": 0, "misses": 0, "evictions": 0}
_saw_traffic = False


def _share_bytes(name: str) -> int:
    from photon_ml_tpu.ops import prefetch

    budget = prefetch.chunk_cache_budget_bytes()
    return int(budget * share_fraction(name))


def _release_locked(name: str, key: tuple) -> None:
    """Drop ``name``'s hold on ``key``; the entry leaves the device only
    when its LAST holder releases (the shared-entry refcount rule)."""
    global _total_bytes
    e = _entries.get(key)
    if e is None or name not in e.holders:
        return
    del e.holders[name]
    _charges[name] = _charges.get(name, 0) - e.nbytes
    _holder_lru.get(name, OrderedDict()).pop(key, None)
    if not e.holders:
        del _entries[key]
        _total_bytes -= e.nbytes
        _arb_stats["evictions"] += 1
        REGISTRY.counter_inc("stream.cache.evictions")


def _enforce_locked(name: str) -> None:
    """Budget enforcement after an admission/hold by ``name``: first the
    per-consumer share (release ``name``'s own LRU holds — a neighbor's
    entries are untouched), then the global budget (walk the global LRU,
    releasing EVERY holder of the victim)."""
    from photon_ml_tpu.ops import prefetch

    share = _share_bytes(name)
    lru = _holder_lru.setdefault(name, OrderedDict())
    while _charges.get(name, 0) > share and lru:
        _release_locked(name, next(iter(lru)))
    budget = prefetch.chunk_cache_budget_bytes()
    while _total_bytes > budget and _entries:
        victim = next(iter(_entries))
        for h in list(_entries[victim].holders):
            _release_locked(h, victim)


def _hold_locked(name: str, key: tuple, e: _Entry) -> None:
    if name not in e.holders:
        e.holders[name] = None
        _charges[name] = _charges.get(name, 0) + e.nbytes
    lru = _holder_lru.setdefault(name, OrderedDict())
    lru[key] = None
    lru.move_to_end(key)
    _entries.move_to_end(key)


def _arb_put_one(name: str, arr_name: str, a, context) -> Any:
    """One host array → its device twin through the shared arbiter."""
    global _total_bytes
    from photon_ml_tpu.ops import prefetch

    a = np.asarray(a)
    tdt = prefetch.transfer_dtype()
    packs = (
        tdt != "f32"
        and arr_name in prefetch._PACK_KEYS
        and a.dtype == np.float32
    )
    # pack dtype is part of the key exactly like the PR-3 cache: a
    # bf16-rung entry must never serve an f32 pass after a mid-process
    # knob toggle
    key = (_content_fingerprint(a), tdt if packs else "raw", context)
    with _arb_lock:
        e = _entries.get(key)
        if e is not None:
            _arb_stats["hits"] += 1
            REGISTRY.counter_inc("stream.cache.hit_bytes", e.nbytes)
            if name not in e.holders:
                _arb_stats["shared_hits"] += 1
                REGISTRY.counter_inc(
                    "stream.cache.shared_hit_bytes", e.nbytes
                )
            REGISTRY.counter_inc(f"stream.{name}.hit_bytes", e.nbytes)
            _hold_locked(name, key, e)
            _enforce_locked(name)
            dev = e.dev
            charged = _charges.get(name, 0)
            REGISTRY.gauge_set(f"stream.{name}.charged_bytes", charged)
            return dev
        _arb_stats["misses"] += 1
    # transfer OUTSIDE the lock (the expensive part; concurrent misses
    # for the same key both transfer — last insert wins, both correct)
    staged = prefetch._pack_for_transfer(a) if packs else a
    dev = prefetch.timed_device_put(staged)
    nbytes = int(dev.nbytes)
    REGISTRY.counter_inc("stream.cache.miss_bytes", nbytes)
    REGISTRY.counter_inc(f"stream.{name}.miss_bytes", nbytes)
    with _arb_lock:
        e = _entries.get(key)
        if e is None:
            e = _Entry(dev, nbytes, name)
            _entries[key] = e
            _total_bytes += nbytes
        _hold_locked(name, key, e)
        _enforce_locked(name)
        dev = e.dev
        REGISTRY.gauge_set(
            f"stream.{name}.charged_bytes", _charges.get(name, 0)
        )
    return dev


def cached_device_put(
    name: str, host_tree: dict, context: Any = None
) -> dict:
    """Device-resident arrays for a prepared host chunk through the
    MULTI-TENANT arbiter: entries key on content fingerprint × pack
    dtype × ``context`` (the fe_range under feature sharding), so a
    second stream replaying the same chunk CONTENT — even through fresh
    host arrays — re-uses the resident buffers, charged to both
    holders. Thread-safe; prefetch workers for different chunks race
    here by design."""
    global _saw_traffic
    _saw_traffic = True
    register(name)
    return {
        k: _arb_put_one(name, k, v, context) for k, v in host_tree.items()
    }


def cache_stats() -> dict:
    """Arbiter snapshot for the telemetry sink's ``run_end`` record —
    per-consumer charges next to the aggregate, mirroring
    ``prefetch.cache_stats()``."""
    with _arb_lock:
        return dict(
            _arb_stats,
            entries=len(_entries),
            bytes=_total_bytes,
            charges={k: v for k, v in sorted(_charges.items()) if v},
        )


def traffic_seen() -> bool:
    """True once any stream routed through the arbiter this process —
    the sink's gate for embedding ``stream_cache`` stats (executor-off
    runs keep their run_end record key-for-key unchanged)."""
    return _saw_traffic


def clear() -> None:
    """Drop every arbiter entry, charge and fingerprint memo (tests and
    bench arms; the worker pool and registrations survive)."""
    global _total_bytes, _saw_traffic
    with _arb_lock:
        _entries.clear()
        _charges.clear()
        _holder_lru.clear()
        _total_bytes = 0
        for k in _arb_stats:
            _arb_stats[k] = 0
        _saw_traffic = False
    with _fp_lock:
        _fp_memo.clear()
    with _reg_lock:
        _active.clear()
