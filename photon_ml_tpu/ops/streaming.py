"""Out-of-core GLM training: chunked host→device objective evaluation.

Reference parity: the reference streams arbitrarily large datasets through
Spark partitions — each L-BFGS/TRON iteration broadcasts coefficients and
treeAggregates per-partition (value, gradient) sums back to the driver
(``photon-api::ml.function.glm.DistributedGLMLossFunction``, SURVEY.md
§2.2, §7 hard parts: "Streaming 1B rows through host RAM with
double-buffering").

TPU-native redesign: when a dataset exceeds device HBM, the batch lives in
host RAM as a list of uniform-shape chunks; each objective evaluation
streams chunks through the device, accumulating partial (value, gradient)
sums on device. Transfers are double-buffered — chunk ``i+1``'s
``device_put`` is issued before chunk ``i``'s compute is consumed, so the
DMA overlaps the matmuls (JAX dispatch is asynchronous). The per-chunk
kernel is ONE compiled program re-entered for every chunk of every
iteration (uniform chunk shapes are a hard requirement for that).

The optimizers driving this are host-side L-BFGS and TRON
(``optim.host_lbfgs`` / ``optim.host_tron``): the device-resident
``lax.while_loop`` optimizers cannot stream host data from inside a
compiled loop, so the loop structure intentionally mirrors the reference's
driver-resident Breeze loop — one streamed pass per value+gradient
evaluation (plus one per CG step for TRON). For data that fits HBM, the fully
device-resident optimizers in ``photon_ml_tpu.optim`` remain the fast
path; ``fits_in_memory`` below is the decision rule.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops.batch import Batch, DenseBatch, SparseBatch
from photon_ml_tpu.ops.glm import make_objective
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jnp.ndarray


def chunk_batch(batch_arrays: dict, chunk_rows: int) -> list[dict]:
    """Split host arrays (a dict of same-leading-dim numpy arrays) into
    uniform ``chunk_rows``-row chunks; the last chunk is padded with
    zero-weight rows so every chunk compiles to the same program."""
    n = len(batch_arrays["labels"])
    chunks = []
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        chunk = {k: v[lo:hi] for k, v in batch_arrays.items()}
        pad = chunk_rows - (hi - lo)
        if pad:
            for k, v in chunk.items():
                fill = np.zeros((pad,) + v.shape[1:], v.dtype)
                chunk[k] = np.concatenate([v, fill])
            # padded rows carry weight 0 → inert in the objective
            chunk["weights"][hi - lo:] = 0.0
        chunks.append(chunk)
    return chunks


def dense_chunks(
    X: np.ndarray,
    labels: np.ndarray,
    chunk_rows: int,
    offsets: np.ndarray | None = None,
    weights: np.ndarray | None = None,
) -> list[dict]:
    n = X.shape[0]
    return chunk_batch(
        {
            "X": X,
            "labels": labels,
            "offsets": np.zeros(n, X.dtype) if offsets is None else offsets,
            "weights": np.ones(n, X.dtype) if weights is None else weights,
        },
        chunk_rows,
    )


def sparse_chunks(
    indices: np.ndarray,
    values: np.ndarray,
    labels: np.ndarray,
    chunk_rows: int,
    offsets: np.ndarray | None = None,
    weights: np.ndarray | None = None,
) -> list[dict]:
    n = indices.shape[0]
    return chunk_batch(
        {
            "indices": indices,
            "values": values,
            "labels": labels,
            "offsets": np.zeros(n, values.dtype) if offsets is None else offsets,
            "weights": np.ones(n, values.dtype) if weights is None else weights,
        },
        chunk_rows,
    )


def _to_batch(chunk: dict, num_features: int | None) -> Batch:
    if "X" in chunk:
        return DenseBatch(
            X=chunk["X"], labels=chunk["labels"],
            offsets=chunk["offsets"], weights=chunk["weights"],
        )
    return SparseBatch(
        indices=chunk["indices"], values=chunk["values"], labels=chunk["labels"],
        offsets=chunk["offsets"], weights=chunk["weights"],
        num_features=num_features,
    )


def _fe_nnz_histogram(chunks: Sequence[dict], num_features: int) -> np.ndarray:
    """Global per-feature nnz counts over sparse chunk dicts (padded
    zero-value slots excluded — they never pack or contribute). Under
    feature-range sharding rows are replicated, so the LOCAL histogram is
    the global one and every process derives the identical partition."""
    nnz = np.zeros(num_features, np.int64)
    for c in chunks:
        idx = np.asarray(c["indices"]).ravel()
        val = np.asarray(c["values"]).ravel()
        live = idx[val != 0.0]
        if live.size:
            nnz += np.bincount(live, minlength=num_features)
    return nnz


def _fe_restrict_chunks(
    chunks: Sequence[dict], lo: int, hi: int
) -> tuple[list[dict], int]:
    """Column-restrict sparse chunk dicts to the feature range [lo, hi):
    out-of-range entries zero out (index 0, value 0 — inert in both matvec
    directions), in-range indices shift by -lo, and every chunk compacts
    to ONE common per-row width (kept entries first, stable order) so the
    restricted chunks stay uniform-shape for the one-kernel discipline —
    and so the raw host→device stream shrinks with the range, not just
    the packed tile-COO stream. labels/offsets/weights are SHARED with
    the input chunks (same storage: per-pass streaming sees live values,
    and the prefetch chunk cache keys keep hitting)."""
    keeps = []
    k_max = 1
    for c in chunks:
        idx = np.asarray(c["indices"])
        val = np.asarray(c["values"])
        keep = (idx >= lo) & (idx < hi) & (val != 0.0)
        if keep.size:
            k_max = max(k_max, int(keep.sum(axis=1).max()))
        keeps.append(keep)
    out = []
    for c, keep in zip(chunks, keeps):
        idx = np.asarray(c["indices"])
        val = np.asarray(c["values"])
        order = np.argsort(~keep, axis=1, kind="stable")
        idx_loc = np.take_along_axis(
            np.where(keep, idx - lo, 0).astype(idx.dtype), order, axis=1
        )[:, :k_max]
        val_loc = np.take_along_axis(
            np.where(keep, val, 0.0).astype(val.dtype), order, axis=1
        )[:, :k_max]
        out.append(dict(
            c,
            indices=np.ascontiguousarray(idx_loc),
            values=np.ascontiguousarray(val_loc),
        ))
    return out, k_max


def device_hbm_budget_bytes(
    default: float = 8e9, fraction: float = 0.75, device=None
) -> float:
    """The HBM budget for dataset residency, QUERIED from the device
    (``memory_stats()['bytes_limit']`` scaled by ``fraction`` to leave room
    for coefficients, optimizer state and XLA scratch). Falls back to
    ``default`` on backends that expose no memory stats (e.g. CPU).

    Which source won is recorded (``hbm.budget_bytes`` /
    ``hbm.budget_queried`` gauges + a one-per-run ``hbm_budget`` event):
    a fallback-budget run on a memory-stats-less backend is
    distinguishable from a device-quoted one in ``report`` output."""
    queried = None
    try:
        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats() or {}
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if limit:
            queried = fraction * float(limit)
    except Exception:
        pass
    budget = default if queried is None else queried
    from photon_ml_tpu.obs import devcost

    devcost.record_hbm_budget(budget, queried is not None)
    return budget


def fits_in_memory(num_rows: int, num_features: int, itemsize: int = 4,
                   hbm_budget_bytes: float | None = None) -> bool:
    """Decision rule between the device-resident fast path and streaming.
    ``hbm_budget_bytes=None`` queries the device (``device_hbm_budget_bytes``)."""
    if hbm_budget_bytes is None:
        hbm_budget_bytes = device_hbm_budget_bytes()
    return num_rows * num_features * itemsize <= hbm_budget_bytes


@dataclass
class StreamingGLMObjective:
    """GLM objective over host-resident chunks (uniform shapes).

    Exposes the same ``value`` / ``value_and_grad`` contract as
    ``GLMObjective``, so ``host_lbfgs_minimize`` (or any host-driven
    optimizer) consumes it directly. Per-chunk math reuses ``GLMObjective``
    with the L2 term stripped (added once at the end); per-chunk
    normalization-space gradients sum correctly because
    ``grad_to_model_space`` is linear in its (g_raw, r_sum) inputs.
    """

    chunks: Sequence[dict]  # host numpy chunk dicts (uniform shapes)
    loss: PointwiseLoss
    num_features: int
    l2_weight: float = 0.0
    intercept_index: int | None = None
    norm: NormalizationContext | None = None
    # multi-host: sum partial (value, grad) across ALL processes per
    # evaluation (each host streams only its own chunks — the treeAggregate
    # analog). The L2 term is added once, AFTER the cross-process sum.
    cross_process: bool = False
    # incremental training: (d,) Gaussian MAP prior in the SOLVER's
    # coefficient space (normalized space when ``norm`` is set — build via
    # ``GaussianPrior.from_coefficients``, same as the device objective).
    # The regularizer becomes 0.5·λ₂·Σ maskⱼ·precⱼ·(wⱼ−μⱼ)²; plain L2 is
    # the μ=0, prec=1 default. Like the L2 term, the prior lands ONCE
    # outside the per-chunk stream (it does not depend on the data).
    prior_mean: Array | None = None
    prior_precision: Array | None = None
    # tile-COO chunk kernels for SPARSE chunks (VERDICT r4 missing #4: the
    # streamed objective lowered its sparse chunks through the known-slow
    # XLA gather/scatter path). None = auto: tile on TPU when the chunks
    # are sparse and high-dimensional (the same rule as the in-memory
    # ingest decision). Layouts build ONCE from the first chunks'
    # indices/values and live on device; a later ``chunks`` swap must
    # preserve indices/values (the GAME trainer's per-visit swap only
    # changes offsets — a fingerprint check rejects anything else).
    tile_sparse: bool | None = None
    # feature-range sharding (PHOTON_FE_SHARD): None = follow the knob
    # (sparse chunks only); True/False force it per objective (the GAME
    # trainer passes False — its entity axis is already sharded, mixed
    # entity×feature sharding is future work). When active, this process
    # holds ONLY its contiguous feature range [lo, hi): restricted
    # column-sliced chunks, a (hi-lo,) coefficient/gradient contract
    # toward the optimizer, and ONE fixed-ascending-range-order margin
    # reduction per streamed pass. Requires replicated rows across
    # processes (every process streams ALL rows; the win is the feature
    # axis) — the complement of ``cross_process`` row sharding, and
    # mutually exclusive with it.
    fe_shard: bool | None = None

    def __post_init__(self):
        if not self.chunks and not self.cross_process:
            raise ValueError("streaming objective needs at least one chunk")
        mask = jnp.ones((self.num_features,), jnp.float32)
        if self.intercept_index is not None:
            mask = mask.at[self.intercept_index].set(0.0)
        # public: the host OWL-QN twin applies scalar L1 over this mask,
        # exactly like the device objective's reg_mask contract (the
        # LOCAL range slice under feature-range sharding)
        self.reg_mask = mask
        if self.prior_mean is not None:
            self.prior_mean = jnp.asarray(self.prior_mean, jnp.float32)
        if self.prior_precision is not None:
            self.prior_precision = jnp.asarray(self.prior_precision, jnp.float32)
        self._tile_layouts = None
        self._tile_meta = None
        self._tile_fingerprints = None
        self._fe_plan = None
        self._fe_range = None  # (pid, lo, hi, P) when sharded
        self._fe_chunks = None
        self._fe_dim = self.num_features
        from photon_ml_tpu.ops.sparse_tiled import auto_tile_streaming

        sparse = bool(self.chunks) and "indices" in self.chunks[0]
        from photon_ml_tpu.data.index_map import fe_shard_enabled

        want_fe = (
            self.fe_shard
            if self.fe_shard is not None
            else (sparse and fe_shard_enabled())
        )
        if want_fe:
            self._init_fe_shard(sparse)
        want_tiling = (
            self.tile_sparse
            if self.tile_sparse is not None
            else auto_tile_streaming(sparse, self.num_features)
        )
        if want_tiling and sparse:
            self._build_tile_layouts()
        if self._fe_range is not None:
            self._build_fe_kernels()

        def chunk_value_grad(batch: Batch, w: Array):
            obj = make_objective(
                batch, self.loss, l2_weight=0.0, norm=self.norm,
                intercept_index=self.intercept_index,
            )
            return obj.value_and_grad(w)

        def chunk_value(batch: Batch, w: Array):
            obj = make_objective(
                batch, self.loss, l2_weight=0.0, norm=self.norm,
                intercept_index=self.intercept_index,
            )
            return obj.value(w)

        def chunk_hvp(batch: Batch, wv: tuple[Array, Array]):
            obj = make_objective(
                batch, self.loss, l2_weight=0.0, norm=self.norm,
                intercept_index=self.intercept_index,
            )
            return obj.hvp(wv[0], wv[1])

        def chunk_hessian_diag(batch: Batch, w: Array):
            obj = make_objective(
                batch, self.loss, l2_weight=0.0, norm=self.norm,
                intercept_index=self.intercept_index,
            )
            return obj.hessian_diag(w)

        def chunk_hessian(batch: Batch, w: Array):
            from photon_ml_tpu.ops.batch import SparseBatch, densify

            if isinstance(batch, SparseBatch):
                # FULL variance only runs under the d-bound, where a
                # chunk-rows × d dense view is small; densifying per chunk
                # keeps ONE hessian implementation
                batch = densify(batch)
            obj = make_objective(
                batch, self.loss, l2_weight=0.0, norm=self.norm,
                intercept_index=self.intercept_index,
            )
            return obj.hessian(w)

        # ONE compiled kernel per contract, re-entered for every chunk
        self._chunk_vg = jax.jit(chunk_value_grad)
        self._chunk_v = jax.jit(chunk_value)
        self._chunk_hvp = jax.jit(chunk_hvp)
        self._chunk_hd = jax.jit(chunk_hessian_diag)
        self._chunk_h = jax.jit(chunk_hessian)

    def _build_tile_layouts(self):
        """Tile every sparse chunk ONCE (host transform): per-chunk
        write-slab-major layouts, padded to a common stream length so one
        compiled kernel serves every chunk, staged to device where they
        stay for the whole objective lifetime (only labels/offsets/weights
        ride the per-pass host→device stream — the packed index/value
        streams replace the raw indices/values entirely). The per-chunk
        pack goes through the PROCESS-WIDE layout cache
        (``ops/tile_cache``): a rebuilt objective over the same data —
        GAME trainers rebuild per fit, drivers per sweep — reuses the
        packed streams instead of re-sorting every nonzero."""
        from photon_ml_tpu.ops import tile_cache
        from photon_ml_tpu.ops.batch import SparseBatch
        from photon_ml_tpu.ops.sparse_tiled import pad_chunks_to_common_groups

        tbs = []
        fps = []
        # under feature-range sharding the layouts pack the RESTRICTED
        # column-sliced chunks (zeroed out-of-range entries drop at pack
        # time, so the packed streams genuinely shrink to ~range nnz) and
        # the range identity joins both the cache key and the batch meta
        for c in (self._fe_chunks if self._fe_chunks is not None
                  else self.chunks):
            sb = SparseBatch(
                indices=c["indices"], values=c["values"], labels=c["labels"],
                offsets=c["offsets"], weights=c["weights"],
                num_features=self._fe_dim,
            )
            fp = self._chunk_fingerprint(c)
            tbs.append(
                tile_cache.tiled_layout_for(
                    sb, keep_empty_chunks=True,
                    # same hash serves the swap guard (structure) and the
                    # cache key (structure + feature width) — computed once
                    fingerprint=(fp[0], self._fe_dim, fp[1], fp[2]),
                    fe_range=self._fe_range,
                )
            )
            fps.append(fp)
        layouts = pad_chunks_to_common_groups(tbs)
        ref = tbs[0]
        self._tile_layouts = [
            tuple(layouts[j][i] for j in range(len(ref.chunks)))
            for i in range(len(tbs))
        ]
        self._tile_meta = (
            ref.num_rows_real, ref.n_pad_total, ref.d_pad_total
        )
        self._tile_fingerprints = fps

    def _init_fe_shard(self, sparse: bool) -> None:
        """Partition the feature space and restrict this process to its
        range (PHOTON_FE_SHARD). The plan reads ONLY the global per-feature
        nnz histogram and the effective process count — deterministic
        pure-host arithmetic on inputs identical on every process (rows are
        replicated under this mode), so every process derives the same
        boundaries with zero communication. The regularizer surfaces
        (reg_mask, priors) slice to the range: the ranges are DISJOINT, so
        local quadratic terms sum to the global regularizer exactly."""
        from photon_ml_tpu.data.index_map import plan_feature_ranges
        from photon_ml_tpu.obs.metrics import REGISTRY
        from photon_ml_tpu.parallel.multihost import (
            effective_process_count,
            effective_process_index,
        )

        if not sparse:
            raise ValueError(
                "PHOTON_FE_SHARD requires sparse chunks (dense chunks fit "
                "one chip's HBM by construction)"
            )
        if self.cross_process:
            raise ValueError(
                "PHOTON_FE_SHARD shards the FEATURE axis over replicated "
                "rows; cross_process shards rows — the two are mutually "
                "exclusive on one objective"
            )
        if self.norm is not None:
            raise NotImplementedError(
                "PHOTON_FE_SHARD supports identity normalization only "
                "(norm=None): normalization shifts couple all ranges "
                "through the margin correction"
            )
        p_count = effective_process_count()
        pid = effective_process_index()
        plan = plan_feature_ranges(
            _fe_nnz_histogram(self.chunks, self.num_features), p_count
        )
        lo, hi = plan.range_of(pid)
        self._fe_plan = plan
        self._fe_range = (pid, lo, hi, p_count)
        self._fe_dim = hi - lo
        self._fe_chunks, _ = _fe_restrict_chunks(self.chunks, lo, hi)
        self.reg_mask = self.reg_mask[lo:hi]
        if self.prior_mean is not None:
            self.prior_mean = self.prior_mean[lo:hi]
        if self.prior_precision is not None:
            self.prior_precision = self.prior_precision[lo:hi]
        REGISTRY.gauge_set("fe_shard.ranges", float(p_count))
        REGISTRY.gauge_set("fe_shard.width", float(self._fe_dim))
        REGISTRY.gauge_set("fe_shard.nnz_local", float(plan.weights[pid]))
        REGISTRY.gauge_set("fe_shard.nnz_balance", float(plan.balance))

    def _build_fe_kernels(self) -> None:
        """The sharded per-chunk programs (ONE compiled kernel per
        contract, re-entered for every chunk — the same discipline as the
        replicated kernels). Phase A computes the range-local partial
        matvec(s); phase B re-streams the chunks against the COMBINED
        margins, which ride as one device array sliced per chunk (chunk
        shapes are uniform, so the chunk index is the only per-chunk
        value and stays a traced scalar)."""
        loss = self.loss
        n_chunk = int(np.asarray(self.chunks[0]["labels"]).shape[0])

        def weighted(batch, x):
            wts = batch.weights
            return jnp.where(wts != 0.0, wts * x, 0.0)

        def m_at(full, i):
            return jax.lax.dynamic_slice(full, (i * n_chunk,), (n_chunk,))

        def fe_margin(batch, ws):
            return jnp.stack([batch.matvec(w) for w in ws])

        def fe_value(batch, mi):
            m = m_at(mi[0][0], mi[1]) + batch.offsets
            return jnp.sum(weighted(batch, loss.value(m, batch.labels)))

        def fe_value_grad(batch, mi):
            m = m_at(mi[0][0], mi[1]) + batch.offsets
            val = jnp.sum(weighted(batch, loss.value(m, batch.labels)))
            r = weighted(batch, loss.d1(m, batch.labels))
            return val, batch.rmatvec(r)

        def fe_hvp(batch, mi):
            m = m_at(mi[0][0], mi[1]) + batch.offsets
            q = weighted(batch, loss.d2(m, batch.labels)) * m_at(mi[0][1], mi[1])
            return batch.rmatvec(q)

        def fe_hessian_diag(batch, mi):
            m = m_at(mi[0][0], mi[1]) + batch.offsets
            return batch.rmatvec_sq(
                weighted(batch, loss.d2(m, batch.labels))
            )

        self._fe_k_m = jax.jit(fe_margin)
        self._fe_k_v = jax.jit(fe_value)
        self._fe_k_vg = jax.jit(fe_value_grad)
        self._fe_k_hvp = jax.jit(fe_hvp)
        self._fe_k_hd = jax.jit(fe_hessian_diag)

    def _fe_combine_margins(self, ws: tuple, l2_w=None):
        """Phase A of a sharded evaluation: stream the range-local partial
        matvec(s) over the restricted chunks, then ONE cross-range
        reduction in FIXED ASCENDING RANGE ORDER (``allreduce_sum_host``
        allgathers and sums in process order — psum-equivalent under a
        healthy mesh, the framed-P2P raw-ndarray codec when degraded), so
        every process holds bit-identical combined margins. ``l2_w``
        piggybacks the local regularizer scalar on the same collective —
        a sharded pass costs exactly one margin-sized reduction."""
        from photon_ml_tpu.parallel.multihost import allreduce_sum_host

        ws = tuple(jnp.asarray(w) for w in ws)
        parts = self._stream(
            ws, self._fe_k_m, lambda acc, out: acc + [np.asarray(out)], [],
            devcost_fn=self._fe_k_m, devcost_label="streaming.fe_margins",
        )
        partial = np.concatenate(parts, axis=1)
        if l2_w is None:
            return jnp.asarray(allreduce_sum_host(partial))
        l2_local = np.asarray(self._l2_term(jnp.asarray(l2_w)), np.float32)
        m, l2 = allreduce_sum_host(partial, l2_local)
        return jnp.asarray(m), jnp.asarray(l2)

    @property
    def fe_active(self) -> bool:
        """True when this objective's coefficient contract is a
        feature-range shard (w, gradients and curvature vectors are the
        LOCAL (hi-lo,) segment; values and line-search scalars are
        global)."""
        return self._fe_range is not None

    def fe_slice(self, w_full) -> np.ndarray:
        """This process's range segment of a full-space vector (warm
        starts, priors already sliced at build)."""
        _pid, lo, hi, _p = self._fe_range
        return np.asarray(w_full)[lo:hi]

    def fe_gather(self, w_local) -> np.ndarray:
        """EXACT full-space assembly of per-range segments: an ascending-
        range-order allgather + concatenation — pure data movement, no
        arithmetic, so the assembled vector is bitwise the segments.
        Collective (framed-P2P: segments are variable-width); identity at
        a single range."""
        w_local = np.asarray(w_local)
        if self._fe_range[3] <= 1:
            return w_local
        from photon_ml_tpu.parallel.multihost import allgather_obj_p2p

        parts = allgather_obj_p2p(w_local, tag="fe_gather")
        return np.concatenate([np.asarray(p) for p in parts])

    def fe_dot(self, a, b) -> float:
        """Global inner product of two range-local vectors: local dot,
        then a scalar all-reduce — the ONLY wire traffic the optimizers'
        line searches add. Every process receives the identical sum
        (fixed-order reduction), so host-side control flow stays in
        lockstep."""
        from photon_ml_tpu.parallel.multihost import allreduce_sum_host

        local = np.asarray(
            np.dot(np.asarray(a, np.float64), np.asarray(b, np.float64))
        )
        return float(allreduce_sum_host(local))

    @staticmethod
    def _chunk_fingerprint(chunk: dict) -> tuple:
        # one hash serves both the swap guard and (widened with the
        # feature count) the process-wide layout cache key
        from photon_ml_tpu.ops import tile_cache

        return tile_cache.structure_fingerprint(
            chunk["indices"], chunk["values"]
        )

    @staticmethod
    def _same_storage(a, b) -> bool:
        """True when ``a`` and ``b`` are numpy arrays over the SAME memory
        (identical object, or fresh views of one base with the same data
        pointer/shape/strides). The GAME trainer re-slices its feature
        arrays every visit — each swap passes NEW view objects over
        unchanged storage, so a plain ``is`` check would re-hash the whole
        design matrix once per coordinate visit."""
        if a is b:
            return True
        if not (isinstance(a, np.ndarray) and isinstance(b, np.ndarray)):
            return False
        ai, bi = a.__array_interface__, b.__array_interface__
        return (
            ai["data"] == bi["data"]
            and ai["shape"] == bi["shape"]
            and ai["strides"] == bi["strides"]
            and a.dtype == b.dtype
        )

    def __setattr__(self, name, value):
        if (
            name == "chunks"
            and getattr(self, "_fe_chunks", None) is not None
        ):
            # the restricted column slices (and the plan they were cut
            # by) were derived from the PREVIOUS chunks; no caller swaps
            # chunks on a sharded objective today (the GAME trainer opts
            # out with fe_shard=False), so refuse loudly instead of
            # silently re-deriving a possibly different plan
            raise ValueError(
                "chunk swap under feature-range sharding (PHOTON_FE_SHARD); "
                "rebuild the StreamingGLMObjective"
            )
        if (
            name == "chunks"
            and getattr(self, "_tile_layouts", None) is not None
        ):
            # the cached layouts were built from the PREVIOUS chunks'
            # indices/values; a swap may only change labels/offsets/weights
            # (the GAME trainer's per-visit residual swap). Same-storage
            # check first: the common swap re-slices the same arrays, and
            # the byte-exact hash is only worth paying for fresh storage.
            old_chunks = getattr(self, "chunks", None)
            for i, c in enumerate(value):
                prev = (
                    old_chunks[i]
                    if old_chunks is not None and i < len(old_chunks)
                    else None
                )
                if (
                    prev is not None
                    and self._same_storage(c.get("indices"), prev.get("indices"))
                    and self._same_storage(c.get("values"), prev.get("values"))
                ):
                    continue
                if (
                    i >= len(self._tile_fingerprints)
                    or self._chunk_fingerprint(c) != self._tile_fingerprints[i]
                ):
                    raise ValueError(
                        "chunk swap changed indices/values under cached "
                        "tile-COO layouts; rebuild the StreamingGLMObjective"
                    )
            if len(value) != len(self._tile_fingerprints):
                raise ValueError(
                    "chunk swap changed the chunk count under cached "
                    "tile-COO layouts; rebuild the StreamingGLMObjective"
                )
        object.__setattr__(self, name, value)

    def _chunk_batch(self, cur: dict, i: int) -> Batch:
        if self._tile_layouts is not None:
            from photon_ml_tpu.ops.sparse_tiled import TiledSparseBatch

            num_rows_real, n_pad, d_pad = self._tile_meta
            return TiledSparseBatch(
                chunks=self._tile_layouts[i],
                labels=cur["labels"], offsets=cur["offsets"],
                weights=cur["weights"],
                num_features=self._fe_dim,
                num_rows_real=num_rows_real,
                n_pad_total=n_pad, d_pad_total=d_pad,
                fe_range=self._fe_range,
            )
        return _to_batch(cur, self._fe_dim)

    def _stream(self, params, kernel: Callable, accumulate: Callable, init,
                devcost_fn=None, devcost_label: str | None = None,
                params_for: Callable | None = None):
        """Host→device chunk pipeline. Default (``PHOTON_PREFETCH_DEPTH``
        > 0): a bounded-depth background pipeline (``ops/prefetch``)
        prepares chunk ``i+k`` — host staging + ``device_put`` through the
        process-wide device-resident chunk cache, so optimizer passes 2..N
        replay already-resident buffers — on worker threads while the
        device computes chunk ``i``. Kernel calls and accumulation stay on
        THIS thread in chunk order, so outputs are bitwise identical to
        the synchronous schedule. Depth 0 restores the pre-prefetch
        double-buffered path bit-for-bit: the NEXT chunk's transfer is
        issued before the CURRENT chunk's compute result is consumed, so
        DMA overlaps compute (async dispatch). ``params`` is passed to
        ``kernel`` verbatim (an array or a tuple of arrays). Tiled chunks
        stream only labels/offsets/weights (the packed nonzero streams are
        device-resident).

        ``devcost_fn``/``devcost_label`` name the jitted per-chunk program
        for analytic cost capture (``obs/devcost``) — chunks are
        uniform-shape, so the FIRST chunk's signature covers every chunk
        of every pass, and the capture dedup means passes 2..N emit
        nothing.

        ``params_for`` (feature-range sharding's phase B) supplies
        PER-CHUNK params (chunk index → params) instead of the shared
        ``params`` — the combined margins ride as one device array and
        each chunk's kernel slices its rows by index."""
        slim = (
            (lambda c: {k: c[k] for k in ("labels", "offsets", "weights")})
            if self._tile_layouts is not None
            else (lambda c: c)
        )
        # under feature-range sharding the stream serves the RESTRICTED
        # column-sliced chunks; their labels/offsets/weights are the SAME
        # storage as self.chunks', so live per-pass values still ride
        src = self._fe_chunks if self._fe_chunks is not None else self.chunks
        acc = init
        if not src:
            return acc
        from photon_ml_tpu.obs import devcost
        from photon_ml_tpu.obs.metrics import REGISTRY
        from photon_ml_tpu.ops import prefetch

        # registry counters (one update per PASS, not per chunk: the
        # telemetry write must never show up on the chunk critical path)
        REGISTRY.counter_inc("stream.passes")
        REGISTRY.counter_inc("stream.chunks", len(src))

        from photon_ml_tpu.ops import stream_executor

        if stream_executor.stream_executor_enabled():
            # executor path: same pool, same in-order consume; device
            # residency rides the MULTI-TENANT arbiter keyed by chunk
            # CONTENT × pack dtype × fe_range, so a validation stream
            # replaying these chunks re-uses the resident buffers
            def prepare_x(i):
                return stream_executor.cached_device_put(
                    "objective", slim(src[i]), context=self._fe_range
                )

            for i, cur in enumerate(
                stream_executor.stream("objective", len(src), prepare_x)
            ):
                b = self._chunk_batch(cur, i)
                p_i = params_for(i) if params_for is not None else params
                if i == 0 and devcost_fn is not None:
                    devcost.capture(devcost_label, devcost_fn, (b, p_i))
                out = kernel(b, p_i)
                acc = accumulate(acc, out)
            return acc

        depth = prefetch.prefetch_depth()
        if depth <= 0:
            # pack_host_chunk: raw feature columns transfer at the
            # precision ladder's storage dtype here too (identity on the
            # f32 rung, so depth 0 stays the pre-prefetch path bit-for-bit)
            nxt = jax.device_put(prefetch.pack_host_chunk(slim(src[0])))
            for i in range(len(src)):
                cur = nxt
                if i + 1 < len(src):
                    nxt = jax.device_put(
                        prefetch.pack_host_chunk(slim(src[i + 1]))
                    )
                b = self._chunk_batch(cur, i)
                p_i = params_for(i) if params_for is not None else params
                if i == 0 and devcost_fn is not None:
                    devcost.capture(devcost_label, devcost_fn, (b, p_i))
                out = kernel(b, p_i)
                acc = accumulate(acc, out)
            return acc

        def prepare(i):
            return prefetch.cached_device_put(slim(src[i]))

        for i, cur in enumerate(
            prefetch.prefetch_iter(len(src), prepare, depth)
        ):
            b = self._chunk_batch(cur, i)
            p_i = params_for(i) if params_for is not None else params
            if i == 0 and devcost_fn is not None:
                devcost.capture(devcost_label, devcost_fn, (b, p_i))
            out = kernel(b, p_i)
            acc = accumulate(acc, out)
        return acc

    def _reg_delta(self, w: Array) -> Array:
        from photon_ml_tpu.ops.glm import reg_delta

        return reg_delta(w, self.prior_mean, self.prior_precision)

    def _reg_curvature(self, like: Array) -> Array:
        from photon_ml_tpu.ops.glm import reg_curvature

        return reg_curvature(like, self.prior_mean, self.prior_precision)

    def _l2_term(self, w: Array) -> Array:
        from photon_ml_tpu.ops.glm import reg_term

        return reg_term(
            jnp.asarray(w), jnp.float32(self.l2_weight), self.reg_mask,
            self.prior_mean, self.prior_precision,
        )

    def value(self, w: Array) -> Array:
        if self._fe_range is not None:
            return self._fe_value(w)
        total = self._stream(
            jnp.asarray(w), self._chunk_v, lambda acc, v: acc + v,
            jnp.float32(0.0),
            devcost_fn=self._chunk_v, devcost_label="streaming.chunk_value",
        )
        if self.cross_process:
            from photon_ml_tpu.parallel.multihost import allreduce_sum_host

            total = jnp.asarray(allreduce_sum_host(np.asarray(total)))
        return total + self._l2_term(jnp.asarray(w))

    def hvp(self, w: Array, v: Array) -> Array:
        """Gauss-Newton Hessian-vector product, streamed — TRON's CG inner
        loop costs one full-data pass per step, exactly the reference's
        treeAggregate accounting (SURVEY §2.1 TRON row)."""
        if self._fe_range is not None:
            return self._fe_hvp(w, v)
        w = jnp.asarray(w)
        v = jnp.asarray(v)
        init = jnp.zeros((self.num_features,), jnp.float32)
        hv = self._stream(
            (w, v),
            lambda batch, wv: self._chunk_hvp(batch, wv),
            lambda acc, out: acc + out,
            init,
            devcost_fn=self._chunk_hvp, devcost_label="streaming.chunk_hvp",
        )
        if self.cross_process:
            from photon_ml_tpu.parallel.multihost import allreduce_sum_host

            hv = jnp.asarray(allreduce_sum_host(np.asarray(hv)))
        return hv + (
            jnp.float32(self.l2_weight) * self.reg_mask
            * self._reg_curvature(v) * v
        )

    def hessian_diag(self, w: Array) -> Array:
        """diag(H), streamed — VarianceComputationType.SIMPLE at the
        solution costs one extra full-data pass (the in-memory formula is
        linear in the per-chunk data sums, so chunk partials add; the L2
        term lands once, after the cross-process sum)."""
        if self._fe_range is not None:
            return self._fe_hessian_diag(w)
        w = jnp.asarray(w)
        init = jnp.zeros((self.num_features,), jnp.float32)
        diag = self._stream(
            w,
            lambda batch, wi: self._chunk_hd(batch, wi),
            lambda acc, out: acc + out,
            init,
            devcost_fn=self._chunk_hd,
            devcost_label="streaming.chunk_hessian_diag",
        )
        if self.cross_process:
            from photon_ml_tpu.parallel.multihost import allreduce_sum_host

            diag = jnp.asarray(allreduce_sum_host(np.asarray(diag)))
        return diag + (
            jnp.float32(self.l2_weight) * self.reg_mask
            * self._reg_curvature(diag)
        )

    # d-bound on the streamed FULL Hessian: the (d, d) f32 accumulator is
    # d²·4 bytes ON DEVICE for the whole pass (8192 → 256 MB) and the host
    # inverts it afterwards — FULL variance is a small-to-mid-d feature in
    # the reference too (it inverts d×d on the driver)
    FULL_HESSIAN_MAX_D = 8192

    def hessian(self, w: Array) -> Array:
        """Full (d, d) Hessian at ``w``, streamed — FULL variance at the
        solution is ONE extra pass accumulating the per-chunk d×d Gram
        contractions (Σ Zᵀ(d2·Z), linear in the chunks, exactly like the
        streamed gradient), then a host-side inverse by the caller. The
        d-bound keeps the accumulator a bounded device buffer; beyond it
        FULL is refused eagerly with the limit in the message."""
        if self._fe_range is not None:
            raise NotImplementedError(
                "FULL variance is not supported under feature-range "
                "sharding (PHOTON_FE_SHARD) — the d×d Hessian couples all "
                "ranges; use SIMPLE variances"
            )
        if self._tile_layouts is not None:
            raise NotImplementedError(
                "FULL variance is not supported with tile-COO streamed "
                "chunks (the raw per-chunk indices are not retained); "
                "build the objective with tile_sparse=False or use SIMPLE"
            )
        if self.num_features > self.FULL_HESSIAN_MAX_D:
            raise NotImplementedError(
                f"streamed FULL variance supports d <= "
                f"{self.FULL_HESSIAN_MAX_D} (the dense d×d Hessian "
                f"accumulator would be {self.num_features}² floats); use "
                f"SIMPLE variances at this width"
            )
        w = jnp.asarray(w)
        init = jnp.zeros(
            (self.num_features, self.num_features), jnp.float32
        )
        h = self._stream(
            w,
            lambda batch, wi: self._chunk_h(batch, wi),
            lambda acc, out: acc + out,
            init,
            devcost_fn=self._chunk_h,
            devcost_label="streaming.chunk_hessian",
        )
        if self.cross_process:
            from photon_ml_tpu.parallel.multihost import allreduce_sum_host

            h = jnp.asarray(allreduce_sum_host(np.asarray(h)))
        return h + jnp.diag(
            jnp.float32(self.l2_weight) * self.reg_mask
            * self._reg_curvature(self.reg_mask)
        )

    def stream_scores(self, w: Array, num_rows: int) -> np.ndarray:
        """Margins (X·w, no offsets) over this objective's chunks, trimmed
        to ``num_rows`` — through the SAME device-resident tile-COO
        layouts the solve used when they exist (the GAME trainer scores
        every coordinate visit; re-running those scores through the XLA
        gather path forfeited the kernel the visit just trained on), else
        the plain per-chunk matvec.

        Under feature-range sharding ``w`` is the LOCAL range segment and
        the returned scores are the COMBINED full margins (identical on
        every process — the fixed-ascending-range-order reduction)."""
        if not self.chunks:
            return np.zeros(num_rows, np.float32)
        if self._fe_range is not None:
            m = self._fe_combine_margins((jnp.asarray(w),))
            return np.asarray(m[0])[:num_rows]
        w = jnp.asarray(w)
        from photon_ml_tpu.ops import prefetch

        depth = prefetch.prefetch_depth()
        # the one module-level scoring program (shared with the module
        # scorer below): objectives are rebuilt per GAME fit / per sweep,
        # and a per-objective jit would re-compile scoring on every
        # rebuild instead of re-entering the process-wide cache
        if depth <= 0:
            # raw (un-tiled) chunks score at the ladder's transfer dtype,
            # like the streamed objective's depth-0 path; tiled chunks
            # only consume labels/offsets/weights here (identity pack)
            pack = (
                (lambda c: c)
                if self._tile_layouts is not None
                else prefetch.pack_host_chunk
            )
            outs = [
                np.asarray(_score_matvec(self._chunk_batch(pack(c), i), w))
                for i, c in enumerate(self.chunks)
            ]
            return np.concatenate(outs)[:num_rows]

        from photon_ml_tpu.ops import stream_executor

        if stream_executor.stream_executor_enabled():

            def prepare_x(i):
                c = self.chunks[i]
                if self._tile_layouts is not None:
                    c = {k: c[k] for k in ("labels", "offsets", "weights")}
                return self._chunk_batch(
                    stream_executor.cached_device_put("scores", c), i
                )

            outs = [
                np.asarray(_score_matvec(b, w))
                for b in stream_executor.stream(
                    "scores", len(self.chunks), prepare_x, depth
                )
            ]
            return np.concatenate(outs)[:num_rows]

        def prepare(i):
            # stage through the device-resident chunk cache: per-visit
            # GAME scoring re-transfers only the columns that changed
            c = self.chunks[i]
            if self._tile_layouts is not None:
                c = {k: c[k] for k in ("labels", "offsets", "weights")}
            return self._chunk_batch(prefetch.cached_device_put(c), i)

        outs = [
            np.asarray(_score_matvec(b, w))
            for b in prefetch.prefetch_iter(len(self.chunks), prepare, depth)
        ]
        return np.concatenate(outs)[:num_rows]

    def value_and_grad(self, w: Array) -> tuple[Array, Array]:
        if self._fe_range is not None:
            return self._fe_value_and_grad(w)
        w = jnp.asarray(w)
        init = (jnp.float32(0.0), jnp.zeros((self.num_features,), jnp.float32))
        v, g = self._stream(
            w, self._chunk_vg,
            lambda acc, out: (acc[0] + out[0], acc[1] + out[1]),
            init,
            devcost_fn=self._chunk_vg,
            devcost_label="streaming.chunk_value_grad",
        )
        if self.cross_process:
            from photon_ml_tpu.parallel.multihost import allreduce_sum_host

            v, g = allreduce_sum_host(np.asarray(v), np.asarray(g))
            v, g = jnp.asarray(v), jnp.asarray(g)
        g = g + jnp.float32(self.l2_weight) * self.reg_mask * self._reg_delta(w)
        return v + self._l2_term(w), g

    # -- feature-range-sharded consumers (PHOTON_FE_SHARD) -------------------
    # Every evaluation is two streamed passes: phase A computes the
    # range-local partial matvec(s) and ONE fixed-ascending-range-order
    # reduction assembles the full margins (identical bits everywhere);
    # phase B derives the contract from the combined margins. The data
    # value is a full-data sum every process computes identically (no
    # second collective); gradient/curvature contractions are DISJOINT
    # range segments — the local slice IS this process's result, exact by
    # construction (pure concatenation reassembles the full vector, no
    # combine arithmetic at all). The regularizer terms are elementwise
    # over local slices of mask/priors, equally exact; only the L2 VALUE
    # scalar crosses the wire, piggybacked on the phase-A reduction.

    def _fe_value(self, w: Array) -> Array:
        w = jnp.asarray(w)
        m, l2 = self._fe_combine_margins((w,), l2_w=w)
        total = self._stream(
            None, self._fe_k_v, lambda acc, v: acc + v, jnp.float32(0.0),
            devcost_fn=self._fe_k_v,
            devcost_label="streaming.fe_chunk_value",
            params_for=lambda i: (m, jnp.int32(i)),
        )
        return total + l2

    def _fe_value_and_grad(self, w: Array) -> tuple[Array, Array]:
        w = jnp.asarray(w)
        m, l2 = self._fe_combine_margins((w,), l2_w=w)
        init = (jnp.float32(0.0), jnp.zeros((self._fe_dim,), jnp.float32))
        v, g = self._stream(
            None, self._fe_k_vg,
            lambda acc, out: (acc[0] + out[0], acc[1] + out[1]), init,
            devcost_fn=self._fe_k_vg,
            devcost_label="streaming.fe_chunk_value_grad",
            params_for=lambda i: (m, jnp.int32(i)),
        )
        g = g + jnp.float32(self.l2_weight) * self.reg_mask * self._reg_delta(w)
        return v + l2, g

    def _fe_hvp(self, w: Array, v: Array) -> Array:
        # BOTH partial matvecs (margins of w, direction image of v) stack
        # into one phase-A stream and one reduction
        w = jnp.asarray(w)
        v = jnp.asarray(v)
        m2 = self._fe_combine_margins((w, v))
        hv = self._stream(
            None, self._fe_k_hvp, lambda acc, out: acc + out,
            jnp.zeros((self._fe_dim,), jnp.float32),
            devcost_fn=self._fe_k_hvp,
            devcost_label="streaming.fe_chunk_hvp",
            params_for=lambda i: (m2, jnp.int32(i)),
        )
        return hv + (
            jnp.float32(self.l2_weight) * self.reg_mask
            * self._reg_curvature(v) * v
        )

    def _fe_hessian_diag(self, w: Array) -> Array:
        w = jnp.asarray(w)
        m = self._fe_combine_margins((w,))
        diag = self._stream(
            None, self._fe_k_hd, lambda acc, out: acc + out,
            jnp.zeros((self._fe_dim,), jnp.float32),
            devcost_fn=self._fe_k_hd,
            devcost_label="streaming.fe_chunk_hessian_diag",
            params_for=lambda i: (m, jnp.int32(i)),
        )
        return diag + (
            jnp.float32(self.l2_weight) * self.reg_mask
            * self._reg_curvature(diag)
        )


@functools.partial(jax.jit, static_argnames=("constants",))
def _score_matvec_keyed(b, wi, constants):
    return b.matvec(wi)


def _score_matvec(b, wi):
    """The one scoring program, re-entered across objectives/visits. The
    tuned kernel constants ride along as a STATIC key: a nested jit's
    statics are resolved at the OUTER trace, so without this a
    PIPELINE_SEGMENTS / SEGMENT_BATCHED toggle (which reshapes nothing)
    would silently re-enter the stale executable — the same
    never-by-luck rule as ``_tiled_apply`` itself. Analytic cost capture
    shadows the same key (constants are part of the signature), so a
    fresh scoring executable's flops/bytes land in telemetry once."""
    from photon_ml_tpu.obs import devcost
    from photon_ml_tpu.ops import tile_cache

    constants = tile_cache.tuned_constants()
    devcost.capture(
        "streaming.score_matvec", _score_matvec_keyed, (b, wi),
        {"constants": constants},
    )
    return _score_matvec_keyed(b, wi, constants=constants)


# bounded storage-identity memo for chunk structure fingerprints: the
# per-visit GAME scorer passes fresh chunk DICTS over unchanged storage,
# and re-hashing every chunk's full index/value bytes per visit costs
# O(data) host sha256 just to look up an already-cached layout. Entries
# hold references (that is what makes the data-pointer comparison safe —
# a freed-and-reused address can never alias a live held array).
# Lock-guarded: prefetch workers fingerprint different chunks concurrently.
import threading as _threading

_FP_MEMO: list = []
_FP_MEMO_CAP = 16
_FP_MEMO_LOCK = _threading.Lock()


def _chunk_structure_fingerprint(indices, values) -> tuple:
    from photon_ml_tpu.ops import tile_cache

    same = StreamingGLMObjective._same_storage
    with _FP_MEMO_LOCK:
        for i, (pi, pv, fp) in enumerate(_FP_MEMO):
            if same(indices, pi) and same(values, pv):
                _FP_MEMO.append(_FP_MEMO.pop(i))
                return fp
    fp = tile_cache.structure_fingerprint(indices, values)  # outside the lock
    with _FP_MEMO_LOCK:
        # racing misses for the same chunk both hash; only ONE may insert,
        # or duplicates would consume memo capacity and evict live entries
        for pi, pv, _pf in _FP_MEMO:
            if same(indices, pi) and same(values, pv):
                return fp
        _FP_MEMO.append((indices, values, fp))
        del _FP_MEMO[:-_FP_MEMO_CAP]
    return fp


def stream_scores(
    chunks: Sequence[dict],
    w: np.ndarray,
    num_rows: int,
    num_features: int | None = None,
    tile_sparse: bool | None = None,
) -> np.ndarray:
    """Margins over all chunks (scoring an out-of-core dataset), trimmed to
    the dataset's true ``num_rows`` (the last chunk is padded).

    ``tile_sparse=None`` applies the streamed objective's auto rule: on
    TPU, genuinely high-dimensional sparse chunks score through tile-COO
    layouts from the PROCESS-WIDE cache (``ops/tile_cache``) — per-visit
    GAME validation scoring packs each chunk once and hits the cache every
    visit after, instead of re-running XLA's latency-bound gather."""
    if not chunks:
        return np.zeros(num_rows, np.float32)  # 0-row host shard
    from photon_ml_tpu.ops.sparse_tiled import auto_tile_streaming

    sparse = "indices" in chunks[0]
    from photon_ml_tpu.data.index_map import fe_shard_enabled

    if sparse and num_features is not None and fe_shard_enabled():
        return _stream_scores_fe(
            chunks, w, num_rows, num_features, tile_sparse
        )
    want_tiling = (
        tile_sparse
        if tile_sparse is not None
        else auto_tile_streaming(sparse, num_features)
    )
    w = jnp.asarray(w)

    def prepare(i):
        c = chunks[i]
        if not (want_tiling and sparse):
            # raw chunks score at the ladder's transfer dtype (identity
            # on the f32 rung); tiled chunks keep their f32 values — the
            # layout builder owns their storage-precision conversion
            c = prefetch.pack_host_chunk(c)
        b = _to_batch(c, num_features)
        if want_tiling and sparse:
            from photon_ml_tpu.ops import tile_cache

            # storage-identity memo: per-visit calls pass fresh chunk
            # dicts over unchanged arrays, and a cache HIT must not cost
            # a full re-hash of the chunk's index/value bytes
            shape, h_idx, h_val = _chunk_structure_fingerprint(
                c["indices"], c["values"]
            )
            b = tile_cache.tiled_layout_for(
                b, keep_empty_chunks=True,
                fingerprint=(shape, num_features, h_idx, h_val),
            )
        return b

    from photon_ml_tpu.ops import prefetch, stream_executor

    if stream_executor.stream_executor_enabled():
        # tiled chunks keep the tile_cache prepare verbatim (the layout
        # cache already owns their device residency); raw chunks ride
        # the multi-tenant arbiter so a replay of the training stream's
        # chunk CONTENT re-uses resident buffers
        if want_tiling and sparse:
            prepare_x = prepare
        else:

            def prepare_x(i):
                return _to_batch(
                    stream_executor.cached_device_put("scores", chunks[i]),
                    num_features,
                )

        outs = [
            np.asarray(_score_matvec(b, w))
            for b in stream_executor.stream("scores", len(chunks), prepare_x)
        ]
        return np.concatenate(outs)[:num_rows]

    # background prefetch prepares chunk i+k's batch (fingerprint memo +
    # layout-cache lookup — the host-pack cost) while the device scores
    # chunk i; depth 0 degenerates to the synchronous per-chunk loop.
    # Scoring/readback stays on this thread in chunk order.
    outs = [
        np.asarray(_score_matvec(b, w))
        for b in prefetch.prefetch_iter(len(chunks), prepare)
    ]
    return np.concatenate(outs)[:num_rows]


def _stream_scores_fe(
    chunks: Sequence[dict],
    w: np.ndarray,
    num_rows: int,
    num_features: int,
    tile_sparse: bool | None,
) -> np.ndarray:
    """Module scorer under PHOTON_FE_SHARD: ``w`` is the FULL coefficient
    vector; each process scores its feature range's partial matvec over
    column-restricted chunks and ONE fixed-ascending-range-order reduction
    assembles the full margins (identical on every process). COLLECTIVE —
    every process of the group must call it at the same point. The plan
    re-derives from the chunk nnz histogram (deterministic, the same rule
    the objective used), so scoring hits the layouts the solve packed."""
    from photon_ml_tpu.data.index_map import plan_feature_ranges
    from photon_ml_tpu.parallel.multihost import (
        allreduce_sum_host,
        effective_process_count,
        effective_process_index,
    )
    from photon_ml_tpu.ops import prefetch
    from photon_ml_tpu.ops.sparse_tiled import auto_tile_streaming

    p_count = effective_process_count()
    pid = effective_process_index()
    plan = plan_feature_ranges(
        _fe_nnz_histogram(chunks, num_features), p_count
    )
    lo, hi = plan.range_of(pid)
    restricted, _k = _fe_restrict_chunks(chunks, lo, hi)
    d_local = hi - lo
    fe_range = (pid, lo, hi, p_count)
    want_tiling = (
        tile_sparse
        if tile_sparse is not None
        else auto_tile_streaming(True, num_features)
    )
    w_loc = jnp.asarray(np.asarray(w)[lo:hi])

    def prepare(i):
        c = restricted[i]
        if not want_tiling:
            c = prefetch.pack_host_chunk(c)
        b = _to_batch(c, d_local)
        if want_tiling:
            from photon_ml_tpu.ops import tile_cache

            shape, h_idx, h_val = _chunk_structure_fingerprint(
                c["indices"], c["values"]
            )
            b = tile_cache.tiled_layout_for(
                b, keep_empty_chunks=True,
                fingerprint=(shape, d_local, h_idx, h_val),
                fe_range=fe_range,
            )
        return b

    from photon_ml_tpu.ops import stream_executor

    if stream_executor.stream_executor_enabled():
        if want_tiling:
            prepare_x = prepare
        else:

            def prepare_x(i):
                # fe_range rides the arbiter key: a column-restricted
                # chunk must never alias another range's resident entry
                return _to_batch(
                    stream_executor.cached_device_put(
                        "scores", restricted[i], context=fe_range
                    ),
                    d_local,
                )

        outs = [
            np.asarray(_score_matvec(b, w_loc))
            for b in stream_executor.stream(
                "scores", len(restricted), prepare_x
            )
        ]
    else:
        outs = [
            np.asarray(_score_matvec(b, w_loc))
            for b in prefetch.prefetch_iter(len(restricted), prepare)
        ]
    partial = np.concatenate(outs)
    return np.asarray(allreduce_sum_host(partial))[:num_rows]
