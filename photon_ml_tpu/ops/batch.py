"""Device-resident sample batches for GLM training.

Reference parity: the role of ``photon-api::ml.data.LabeledPoint`` /
``LocalDataset`` (label, features, offset, weight per sample — SURVEY.md
§2.2), redesigned columnar for TPU:

- ``DenseBatch``: features as one ``(n, d)`` matrix — margins and gradient
  contractions are single MXU matmuls. Used when d is modest (after feature
  sharding / projection) or data is naturally dense.
- ``SparseBatch``: features as padded per-row ``(n, k)`` (index, value)
  pairs — the TPU-native CSR replacement (static shapes; XLA cannot tile
  ragged rows). Margins are gathers + row sums; gradients are scatter-adds
  (``.at[].add``) which XLA lowers to sorted segment sums. Padding uses
  index 0 with value 0, which contributes exactly 0 to every contraction,
  so no masking is needed in the kernels.

Both carry ``weights`` that double as the padding row mask (padded rows get
weight 0), so one code path handles ragged data under fixed shapes. The
objective forces zero-weight rows to contribute exactly 0 (``jnp.where``, not
``0 * x``), so padded rows may hold arbitrary — even loss-overflowing —
values without poisoning the sums.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

Array = jnp.ndarray


@partial(jax.tree_util.register_dataclass, data_fields=["X", "labels", "offsets", "weights"], meta_fields=[])
@dataclass(frozen=True)
class DenseBatch:
    """Columnar batch with dense features.

    X: (n, d) float; labels/offsets/weights: (n,) float.
    Padded rows must have weights == 0 (and any finite values elsewhere).
    """

    X: Array
    labels: Array
    offsets: Array
    weights: Array

    @property
    def num_features(self) -> int:
        return self.X.shape[-1]

    @property
    def num_rows(self) -> int:
        return self.X.shape[0]

    def _mm(self, A: Array, v: Array) -> Array:
        """Matmul honoring bf16 storage: when ``X`` is kept bfloat16 (half
        the HBM traffic — the usual bottleneck), feed the MXU bf16 operands
        but accumulate float32; otherwise use plain promotion semantics."""
        if A.dtype == jnp.bfloat16:
            return jnp.matmul(
                A, v.astype(jnp.bfloat16), preferred_element_type=jnp.float32
            )
        return A @ v

    def matvec(self, w: Array) -> Array:
        """Margins X @ w — one MXU matmul."""
        return self._mm(self.X, w)

    def rmatvec(self, r: Array) -> Array:
        """Gradient contraction Xᵀ @ r — one MXU matmul."""
        return self._mm(self.X.T, r)

    def rmatvec_sq(self, r: Array) -> Array:
        """(X ⊙ X)ᵀ @ r — Hessian diagonal: Σ_i r_i x_ij²."""
        return self._mm((self.X * self.X).T, r)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["indices", "values", "labels", "offsets", "weights"],
    meta_fields=["num_features"],
)
@dataclass(frozen=True)
class SparseBatch:
    """Columnar batch with padded sparse rows.

    indices: (n, k) int32 feature ids, padded with 0.
    values:  (n, k) float feature values, padded with 0.0.
    num_features: static feature-space dimension d.
    """

    indices: Array
    values: Array
    labels: Array
    offsets: Array
    weights: Array
    num_features: int = field(metadata=dict(static=True))

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    def matvec(self, w: Array) -> Array:
        return jnp.sum(self.values * w[self.indices], axis=-1)

    def rmatvec(self, r: Array) -> Array:
        contrib = self.values * r[:, None]  # (n, k)
        return jnp.zeros((self.num_features,), dtype=contrib.dtype).at[self.indices].add(contrib)

    def rmatvec_sq(self, r: Array) -> Array:
        contrib = self.values * self.values * r[:, None]
        return jnp.zeros((self.num_features,), dtype=contrib.dtype).at[self.indices].add(contrib)


Batch = DenseBatch | SparseBatch


def dense_batch_from_numpy(
    X: np.ndarray,
    labels: np.ndarray,
    offsets: np.ndarray | None = None,
    weights: np.ndarray | None = None,
    dtype=jnp.float32,
) -> DenseBatch:
    n = X.shape[0]
    return DenseBatch(
        X=jnp.asarray(X, dtype=dtype),
        labels=jnp.asarray(labels, dtype=dtype),
        offsets=jnp.zeros((n,), dtype) if offsets is None else jnp.asarray(offsets, dtype),
        weights=jnp.ones((n,), dtype) if weights is None else jnp.asarray(weights, dtype),
    )


def densify(batch: SparseBatch, dtype=jnp.float32) -> DenseBatch:
    """One-time scatter of a ``SparseBatch`` into a dense ``(n, d)`` matrix.

    TPU-first rationale: XLA's vector gather/scatter runs at ~10⁸ elem/s on
    TPU regardless of table size (no SparseCore path in vanilla XLA), so a
    sparse solve pays that latency-bound cost on EVERY objective pass. The
    dense layout pays one scatter at ingest and then every pass is an MXU
    matmul at HBM bandwidth — orders of magnitude faster whenever ``n·d``
    fits the memory budget. ``dtype=bfloat16`` halves the HBM traffic;
    contractions still accumulate in float32 (see ``DenseBatch.matvec``).
    """
    n, k = batch.indices.shape
    d = batch.num_features
    rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32)[:, None], k, axis=1)
    X = jnp.zeros((n, d), dtype).at[rows, batch.indices].add(
        batch.values.astype(dtype)
    )
    return DenseBatch(
        X=X, labels=batch.labels, offsets=batch.offsets, weights=batch.weights
    )


def maybe_densify(
    batch: Batch,
    hbm_budget_bytes: float = 6e9,
    dtype=jnp.float32,
) -> Batch:
    """Densify a sparse batch when the dense matrix fits ``hbm_budget_bytes``
    (leave dense batches and over-budget sparse batches unchanged)."""
    if not isinstance(batch, SparseBatch):
        return batch
    dense_bytes = batch.num_rows * batch.num_features * jnp.dtype(dtype).itemsize
    if dense_bytes > hbm_budget_bytes:
        return batch
    return densify(batch, dtype)


def optimize_batch_layout(
    batch: Batch,
    hbm_budget_bytes: float = 6e9,
    dtype=jnp.float32,
) -> Batch:
    """The framework's full ingest layout decision for a single-device GLM
    solve: densify when the dense matrix fits the HBM budget (MXU matmuls
    beat everything at modest d), otherwise re-block genuinely
    high-dimensional sparse data into the tile-COO Pallas layout
    (``ops/sparse_tiled.py`` — ~9x over the XLA gather/scatter path), and
    leave everything else unchanged."""
    out = maybe_densify(batch, hbm_budget_bytes, dtype)
    if isinstance(out, SparseBatch):
        from photon_ml_tpu.ops import tile_cache
        from photon_ml_tpu.ops.sparse_tiled import supports_tiling

        if supports_tiling(out):
            # process-wide layout cache: identical sparsity structure
            # (re-ingested data, repeated fits) never re-packs
            return tile_cache.tiled_layout_for(out)
    return out


def pad_batch(batch: Batch, target_rows: int) -> Batch:
    """Pad a batch to ``target_rows`` rows with zero-weight rows (static-shape
    requirement for sharding: row count must divide the data axis)."""
    n = batch.num_rows
    if n == target_rows:
        return batch
    if n > target_rows:
        raise ValueError(f"batch has {n} rows > target {target_rows}")
    pad = target_rows - n
    pad1 = lambda a: jnp.concatenate([a, jnp.zeros((pad,) + a.shape[1:], a.dtype)])
    if isinstance(batch, DenseBatch):
        return DenseBatch(
            X=pad1(batch.X),
            labels=pad1(batch.labels),
            offsets=pad1(batch.offsets),
            weights=pad1(batch.weights),
        )
    return SparseBatch(
        indices=pad1(batch.indices),
        values=pad1(batch.values),
        labels=pad1(batch.labels),
        offsets=pad1(batch.offsets),
        weights=pad1(batch.weights),
        num_features=batch.num_features,
    )
