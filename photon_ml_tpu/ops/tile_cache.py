"""Process-wide tile-COO layout cache.

Packing a ``SparseBatch`` into the write-slab-major tile-COO layout
(``ops/sparse_tiled.py``) is a host-side sort + scatter over every nonzero
— cheap next to a full solve, but it was being re-paid for IDENTICAL
sparsity structure all over the system: every ``StreamingGLMObjective``
re-tiled its chunks even when a previous objective over the same data had
already done so (GAME trainers rebuild objectives per fit; drivers rebuild
them per sweep), and every cross-validation invocation re-tiled its fold
subsets from scratch. The compiled kernel executable was similarly
re-specialized per call site.

This module is the one shared answer: a process-wide LRU keyed by

    (sparsity fingerprint, chunking mode, tuned kernel constants)

where the fingerprint hashes the nonzero STRUCTURE (indices/values bytes,
shape, feature count) and the tuned constants are the module-level
GROUPS_PER_STEP / SEGMENTS_PER_DMA / GROUPS_PER_RUN / SEGMENT_BATCHED /
PIPELINE_SEGMENTS knobs read at call time — a retune invalidates by key,
never by luck.
Only the layout (the ``_TileChunk`` tuple + pad metadata) is cached;
labels/offsets/weights always come from the caller's batch, so GAME
coordinate visits that only swap residual offsets hit the cache by
construction. Executable reuse is the other half: ``_tiled_apply`` keys
its jit cache on the same tuned constants, so any two cache entries with
equal stream shapes re-enter one compiled kernel.

Thread-safe — the ``ops/prefetch`` pipeline's workers hit this cache
CONCURRENTLY (per-chunk layout lookups race by design; hammer-tested in
``tests/test_prefetch.py``): every LRU mutation, eviction and hit/miss
bookkeeping happens under the one module lock, with only the expensive
pack itself outside it. Bounded by BOTH entry count (``capacity()``, LRU)
and total packed-stream bytes (``byte_budget()``, maintained as a running
total so eviction never re-walks the table) — the entries pin
device-resident streams, so an entry cap alone would let a handful of
billion-nonzero layouts hold multiple GB of HBM for the process lifetime.
``clear()`` drops everything (tests, or to release device memory eagerly).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

_DEFAULT_CAPACITY = 32
# total packed-stream bytes the cache may pin across entries: an A2-scale
# layout (both directions) is ~0.5 GB, so the default holds a few large
# layouts or many small ones and evicts LRU beyond that — worst case a
# re-pack, never an OOM
_DEFAULT_BYTE_BUDGET = 2 * 1024**3

_lock = threading.Lock()
_entries: "OrderedDict[tuple, object]" = OrderedDict()
_entry_bytes: dict = {}
_total_bytes = 0
_stats = {"hits": 0, "misses": 0}
_capacity = _DEFAULT_CAPACITY
_byte_budget = _DEFAULT_BYTE_BUDGET


def tuned_constants() -> tuple:
    """The kernel-shaping constants, read at CALL time (the same
    discipline as the layout builder: import-time capture breaks
    retuning)."""
    import photon_ml_tpu.ops.sparse_tiled as st

    return (
        st.GROUP,
        st.SLAB,
        st.GROUPS_PER_STEP,
        st.SEGMENTS_PER_DMA,
        st.GROUPS_PER_RUN,
        bool(st.SEGMENT_BATCHED),
        # the pipeline schedule does not reshape the layout, but it keys
        # here anyway so a toggle can NEVER reuse a stale entry (the same
        # never-by-luck rule as the stream-shaping constants; the cost of
        # a spurious miss is one re-pack, the cost of a stale hit under a
        # future layout-coupled schedule would be silent garbage)
        bool(st.PIPELINE_SEGMENTS),
        # the precision rung RESHAPES the packed streams (f32 i32x3 /
        # bf16 i16x3 / int8 i32x1 + scales): a stale hit across a toggle
        # would hand the kernel streams of the wrong width
        st.kernel_dtype(),
        # effective device topology: a degrade-in-place shrinks the
        # group without restarting the process, and entries whose
        # device-resident streams predate the loss must miss by key —
        # while a same-topology re-entry hits everything it already
        # packed (the cheap-abort zero-growth contract)
        _effective_topology(),
    )


def _effective_topology() -> tuple:
    from photon_ml_tpu.parallel.multihost import effective_topology

    return effective_topology()


def structure_fingerprint(indices, values) -> tuple:
    """Byte-exact hash of the nonzero structure alone (shape + index and
    value bytes) — the streamed objective's swap guard uses exactly this
    (labels/offsets/weights are deliberately absent: the GAME trainer's
    per-visit residual swap keeps the same layout)."""
    idx = np.ascontiguousarray(np.asarray(indices))
    val = np.ascontiguousarray(np.asarray(values, np.float32))
    return (
        idx.shape,
        hashlib.sha256(idx.tobytes()).hexdigest(),
        hashlib.sha256(val.tobytes()).hexdigest(),
    )


def sparsity_fingerprint(indices, values, num_features: int) -> tuple:
    """The full cache key half: structure + the feature-space width the
    layout pads to."""
    shape, h_idx, h_val = structure_fingerprint(indices, values)
    return (shape, int(num_features), h_idx, h_val)


def stats() -> dict:
    with _lock:
        return dict(
            _stats,
            entries=len(_entries),
            bytes=_total_bytes,
        )


def capacity() -> int:
    return _capacity


def byte_budget() -> int:
    return _byte_budget


def _evict_over_limits_locked() -> None:
    global _total_bytes
    while _entries and (
        len(_entries) > _capacity or _total_bytes > _byte_budget
    ):
        key, _ = _entries.popitem(last=False)
        _total_bytes -= _entry_bytes.pop(key, 0)


def set_capacity(n: int) -> None:
    global _capacity
    with _lock:
        _capacity = max(int(n), 1)
        _evict_over_limits_locked()


def set_byte_budget(n: int) -> None:
    global _byte_budget
    with _lock:
        _byte_budget = max(int(n), 0)
        _evict_over_limits_locked()


def clear() -> None:
    global _total_bytes
    with _lock:
        _entries.clear()
        _entry_bytes.clear()
        _total_bytes = 0
        _stats["hits"] = 0
        _stats["misses"] = 0


def _chunks_nbytes(chunks) -> int:
    total = 0
    for c in chunks:
        for arrays in (c.m_arrays, c.g_arrays):
            total += sum(int(a.nbytes) for a in arrays)
    return total


def tiled_layout_for(batch, keep_empty_chunks: bool = False,
                     fingerprint: tuple | None = None,
                     fe_range: tuple | None = None):
    """A ``TiledSparseBatch`` for ``batch``, reusing the cached layout when
    an identical sparsity structure was already packed under the current
    tuned constants. The returned batch ALWAYS carries the caller's
    labels/offsets/weights (only the packed streams are shared).
    ``fingerprint`` lets callers that already hashed the chunk (the
    streamed objective's swap guard) skip the second hash. ``fe_range``
    is the feature-range identity ((pid, lo, hi, P)) of a range-sliced
    batch under PHOTON_FE_SHARD — it joins the cache key (a re-plan or
    P change invalidates by key, never by luck) and rides the built
    batch as its static ``fe_range`` meta field."""
    import photon_ml_tpu.ops.sparse_tiled as st

    if fingerprint is None:
        fingerprint = sparsity_fingerprint(
            batch.indices, batch.values, batch.num_features
        )
    key = (fingerprint, bool(keep_empty_chunks), fe_range, tuned_constants())
    with _lock:
        cached = _entries.get(key)
        if cached is not None:
            _entries.move_to_end(key)
            _stats["hits"] += 1
    if cached is not None:
        # only the layout is cached — never the first caller's per-row
        # arrays (which a stored full batch would pin alive)
        chunks, num_rows_real, n_pad_total, d_pad_total = cached
        return st.TiledSparseBatch(
            chunks=chunks,
            labels=batch.labels,
            offsets=batch.offsets,
            weights=batch.weights,
            num_features=batch.num_features,
            num_rows_real=num_rows_real,
            n_pad_total=n_pad_total,
            d_pad_total=d_pad_total,
            fe_range=fe_range,
        )
    # build OUTSIDE the lock (packing is the expensive part) through the
    # module attribute, so instrumented/monkeypatched builders see misses
    # (and keep the plain one-arg call shape they expect)
    if fe_range is not None:
        tb = st.tile_sparse_batch(
            batch, keep_empty_chunks=keep_empty_chunks, fe_range=fe_range
        )
    elif keep_empty_chunks:
        tb = st.tile_sparse_batch(batch, keep_empty_chunks=True)
    else:
        tb = st.tile_sparse_batch(batch)
    nbytes = _chunks_nbytes(tb.chunks)
    global _total_bytes
    with _lock:
        _stats["misses"] += 1
        # devcost accounting: once per PACK that produced a NEW resident
        # entry (concurrent misses on one key both pack, but only the
        # first insert records — a doubled packed-bytes total would
        # inflate the analytic bytes-moved record the dtype ladder's
        # claim rests on). Over-budget layouts are never pinned, so each
        # re-request genuinely re-packs and records again — that repeat
        # IS the real host work/traffic of running over budget.
        prev = _entry_bytes.pop(key, None)
        record_pack = prev is None
        if nbytes <= _byte_budget:  # over-budget layouts are never pinned
            if prev is not None:  # concurrent miss already inserted this key
                _total_bytes -= prev
            _entries[key] = (
                tb.chunks, tb.num_rows_real, tb.n_pad_total, tb.d_pad_total
            )
            _entry_bytes[key] = nbytes
            _total_bytes += nbytes
            _entries.move_to_end(key)
            _evict_over_limits_locked()
        elif prev is not None:
            # key was resident but the REBUILT layout is over budget
            # (budget shrank): drop the stale entry
            _total_bytes -= prev
            _entries.pop(key, None)
    if record_pack:
        from photon_ml_tpu.obs import devcost

        devcost.record_layout_pack(nbytes=nbytes, chunks=len(tb.chunks))
    return tb
