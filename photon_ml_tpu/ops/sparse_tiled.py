"""Tile-COO sparse GLM kernels: MXU/VPU-bound high-dimensional sparse ops.

Why this exists (SURVEY.md §7 hard parts, "Sparse features on TPU";
VERDICT r2 missing #3): XLA lowers the padded-sparse ``SparseBatch``
margins/gradient to element-at-a-time dynamic gathers and scatters —
~6e7 elem/s on TPU, latency-bound, which put the high-dimensional sparse
config BELOW one CPU core. The reference's platform (Breeze on JVM) does
these as cache-friendly CSR loops; beating it needs the sparse pass to run
out of VMEM at vector rates.

Design — the doubly-blocked "tile-COO" layout, built ONCE at ingest:

- The weight vector lives in VMEM as a (d/128, 128) table; the per-row
  residual vector as an (n/128, 128) table. Both fit VMEM for the shapes
  this path serves (d up to ~2M, n up to ~4M per kernel call).
- Every nonzero is assigned to a CELL = (row-slab, col-slab) where a slab
  is 1024 consecutive rows/cols = an (8, 128) block of the corresponding
  table. Nonzeros are sorted by cell and each cell padded to a multiple of
  GROUP=128 (zero-valued fillers pointing at the cell's corner).
- A GROUP (128 nonzeros, one vector-register row) therefore shares ONE
  w-table slab and ONE m-table slab. Per group, the kernels do only
  vector-rate work:
    * table READ:  slab = table[cb*8 : cb*8+8] (dynamic slice);
      per-lane gather ``take_along_axis(slab, lane, 1)`` pulls the wanted
      lane from ALL 8 sublanes; an 8-way iota-compare select keeps the
      right sublane. (Mosaic's TPU gather is lane/8-sublane scoped — this
      structure is exactly what the hardware supports.)
    * table WRITE: contributions become an (8,128) slab update through a
      one-hot matmul (A = contribution masked by sub-index; B = lane
      one-hot; MXU at HIGHEST precision), accumulated into a VMEM scratch
      of the whole output table, written out once at the last grid step.
- margins (``matvec``) reads the w-table and writes the m-table; the
  gradient (``rmatvec``) reads the r-table and writes the g-table — SAME
  nonzero arrays, mirrored roles, two kernels.

Measured on a v5e chip at the A2 shape (n=2^19, k=32, d=2^17): ~18 ms per
margins pass vs ~130 ms for the XLA gather path (7x), padding overhead
1.24x; a full value+grad pass runs both kernels plus XLA elementwise work.

``TiledSparseBatch`` is a drop-in ``Batch``: ``GLMObjective`` consumes it
through ``matvec``/``rmatvec``/``rmatvec_sq`` unchanged. Off-TPU the
kernels run in Pallas interpreter mode, so CPU tests exercise the exact
code path the TPU compiles. Single-device by design: under a mesh, shard
rows first and build one tile-COO per shard (the objective's psum handles
the reduction).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jnp.ndarray

GROUP = 128  # nonzeros per group: one vreg row, shares one cell
GROUPS_PER_TILE = 8  # groups per grid step
SLAB = 1024  # rows/cols per slab: an (8, 128) block of a table


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def build_tiled_coo(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray, n_pad: int, d_pad: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sort nonzeros by (row-slab, col-slab) cell and pad each cell to a
    GROUP multiple (vectorized — no Python per-cell loop). Returns the
    (M,) tiled rows/cols/vals with zero-valued fillers aimed at each
    cell's corner (they contribute exactly 0 to every kernel)."""
    rows = np.asarray(rows, np.int32)
    cols = np.asarray(cols, np.int32)
    vals = np.asarray(vals, np.float32)
    ncs = d_pad // SLAB
    cell = (rows // SLAB).astype(np.int64) * ncs + (cols // SLAB)
    order = np.argsort(cell, kind="stable")
    rows, cols, vals, cell = rows[order], cols[order], vals[order], cell[order]
    uniq, start, counts = np.unique(cell, return_index=True, return_counts=True)
    padded = (-(-counts // GROUP) * GROUP).astype(np.int64)
    out_start = np.concatenate([[0], np.cumsum(padded)])
    M = int(out_start[-1])
    M_pad = -(-M // (GROUP * GROUPS_PER_TILE)) * (GROUP * GROUPS_PER_TILE)

    # initialize with per-cell corner fillers, then scatter the real nnz
    corner_r = ((uniq // ncs) * SLAB).astype(np.int32)
    corner_c = ((uniq % ncs) * SLAB).astype(np.int32)
    out_rows = np.zeros(M_pad, np.int32)
    out_cols = np.zeros(M_pad, np.int32)
    out_vals = np.zeros(M_pad, np.float32)
    out_rows[:M] = np.repeat(corner_r, padded)
    out_cols[:M] = np.repeat(corner_c, padded)
    within = np.arange(len(cell), dtype=np.int64) - np.repeat(start, counts)
    pos = np.repeat(out_start[:-1], counts) + within
    out_rows[pos] = rows
    out_cols[pos] = cols
    out_vals[pos] = vals
    return out_rows, out_cols, out_vals


def _tables(n_pad: int, d_pad: int) -> tuple[int, int]:
    return n_pad // 128, d_pad // 128


def _tile_kernel(
    rows_ref, cols_ref, val_ref, src_ref, out_ref, acc_scratch,
    *, n_tiles, transpose,
):
    """One grid step = GROUPS_PER_TILE groups. ``transpose=False``:
    margins (read w by col, write m by row). ``transpose=True``: gradient
    (read r by row, write g by col)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    iota8 = jax.lax.broadcasted_iota(jnp.int32, (8, GROUP), 0)
    iota128 = jax.lax.broadcasted_iota(jnp.int32, (GROUP, GROUP), 1)
    for s in range(GROUPS_PER_TILE):
        row = rows_ref[s, :]
        col = cols_ref[s, :]
        read_idx = row if transpose else col
        write_idx = col if transpose else row
        # every nonzero of a group shares its cell: slab ids are scalars
        read_slab = (rows_ref[s, 0] if transpose else cols_ref[s, 0]) // SLAB
        write_slab = (cols_ref[s, 0] if transpose else rows_ref[s, 0]) // SLAB

        lane_r = read_idx & 127
        sub_r = (read_idx >> 7) & 7
        slab = src_ref[pl.ds(pl.multiple_of(read_slab * 8, 8), 8), :]
        gathered = jnp.take_along_axis(
            slab, jnp.broadcast_to(lane_r[None, :], (8, GROUP)), axis=1
        )
        sel = (iota8 == sub_r[None, :]).astype(jnp.float32)
        src_vals = jnp.sum(gathered * sel, axis=0)  # (GROUP,)
        p = val_ref[s, :] * src_vals

        lane_w = write_idx & 127
        sub_w = (write_idx >> 7) & 7
        A = jnp.where(iota8 == sub_w[None, :], p[None, :], 0.0)  # (8,GROUP)
        B = (iota128 == lane_w[:, None]).astype(jnp.float32)  # (GROUP,128)
        Ms = jnp.dot(
            A, B, preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        idx = pl.ds(pl.multiple_of(write_slab * 8, 8), 8)
        acc_scratch[idx, :] = acc_scratch[idx, :] + Ms

    @pl.when(t == n_tiles - 1)
    def _():
        out_ref[...] = acc_scratch[...]


@functools.partial(
    jax.jit, static_argnames=("n_pad", "d_pad", "transpose")
)
def _tiled_apply(trows, tcols, tvals, src, n_pad, d_pad, transpose):
    """margins (transpose=False): src = w (d_pad,) -> (n_pad,).
    gradient (transpose=True): src = r (n_pad,) -> (d_pad,)."""
    M = trows.shape[0] * GROUP
    n_tiles = M // (GROUP * GROUPS_PER_TILE)
    nrs, ncs128 = _tables(n_pad, d_pad)
    src_shape = (ncs128, 128) if not transpose else (nrs, 128)
    out_shape = (nrs, 128) if not transpose else (ncs128, 128)
    f = pl.pallas_call(
        functools.partial(
            _tile_kernel, n_tiles=n_tiles, transpose=transpose
        ),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((GROUPS_PER_TILE, GROUP), lambda i: (i, 0)),
            pl.BlockSpec((GROUPS_PER_TILE, GROUP), lambda i: (i, 0)),
            pl.BlockSpec((GROUPS_PER_TILE, GROUP), lambda i: (i, 0)),
            pl.BlockSpec(src_shape, lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec(out_shape, lambda i: (0, 0)),
        scratch_shapes=[pltpu.VMEM(out_shape, jnp.float32)],
        out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=_interpret(),
    )
    return f(trows, tcols, tvals, src.reshape(src_shape)).reshape(-1)


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "trows", "tcols", "tvals", "tvals_sq", "labels", "offsets", "weights",
    ],
    meta_fields=["num_features", "num_rows_real"],
)
@dataclass(frozen=True)
class TiledSparseBatch:
    """Drop-in ``Batch`` whose margins/gradient run the tile-COO Pallas
    kernels. ``labels``/``offsets``/``weights`` are (n,) with the ORIGINAL
    row indexing (the kernels scatter/gather by original row id).

    Build with ``tile_sparse_batch`` — it handles table padding (n to a
    SLAB multiple, d to a SLAB multiple) and precomputes the squared
    values for ``rmatvec_sq`` (Hessian diagonal).
    """

    trows: Array  # (M/GROUP, GROUP) int32 tiled row ids
    tcols: Array  # (M/GROUP, GROUP) int32 tiled col ids
    tvals: Array  # (M/GROUP, GROUP) f32 values (0 on fillers)
    tvals_sq: Array  # (M/GROUP, GROUP) f32 squared values
    labels: Array
    offsets: Array
    weights: Array
    num_features: int = field(metadata=dict(static=True))
    num_rows_real: int = field(metadata=dict(static=True))

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    @property
    def _n_pad(self) -> int:
        return -(-self.num_rows // SLAB) * SLAB

    @property
    def _d_pad(self) -> int:
        return -(-self.num_features // SLAB) * SLAB

    def _pad_src_d(self, w: Array) -> Array:
        d = self.num_features
        return w if d == self._d_pad else jnp.pad(w, (0, self._d_pad - d))

    def _pad_src_n(self, r: Array) -> Array:
        n = self.num_rows
        return r if n == self._n_pad else jnp.pad(r, (0, self._n_pad - n))

    def matvec(self, w: Array) -> Array:
        m = _tiled_apply(
            self.trows, self.tcols, self.tvals, self._pad_src_d(w),
            self._n_pad, self._d_pad, transpose=False,
        )
        return m[: self.num_rows]

    def rmatvec(self, r: Array) -> Array:
        g = _tiled_apply(
            self.trows, self.tcols, self.tvals, self._pad_src_n(r),
            self._n_pad, self._d_pad, transpose=True,
        )
        return g[: self.num_features]

    def rmatvec_sq(self, r: Array) -> Array:
        g = _tiled_apply(
            self.trows, self.tcols, self.tvals_sq, self._pad_src_n(r),
            self._n_pad, self._d_pad, transpose=True,
        )
        return g[: self.num_features]


def tile_sparse_batch(batch) -> TiledSparseBatch:
    """Build a ``TiledSparseBatch`` from a padded-sparse ``SparseBatch``
    (host-side one-time transform; zero-valued padding slots are dropped
    before tiling)."""
    indices = np.asarray(batch.indices)
    values = np.asarray(batch.values)
    n, k = indices.shape
    rows = np.repeat(np.arange(n, dtype=np.int32), k)
    cols = indices.reshape(-1).astype(np.int32)
    vals = values.reshape(-1).astype(np.float32)
    keep = vals != 0.0
    rows, cols, vals = rows[keep], cols[keep], vals[keep]
    n_pad = -(-n // SLAB) * SLAB
    d_pad = -(-batch.num_features // SLAB) * SLAB
    trows, tcols, tvals = build_tiled_coo(rows, cols, vals, n_pad, d_pad)
    shape2 = (-1, GROUP)
    return TiledSparseBatch(
        trows=jnp.asarray(trows.reshape(shape2)),
        tcols=jnp.asarray(tcols.reshape(shape2)),
        tvals=jnp.asarray(tvals.reshape(shape2)),
        tvals_sq=jnp.asarray((tvals * tvals).reshape(shape2)),
        labels=batch.labels,
        offsets=batch.offsets,
        weights=batch.weights,
        num_features=batch.num_features,
        num_rows_real=n,
    )


# The kernels hold the FULL row table (margins output / r source) and col
# table (w source / gradient output) in VMEM: each costs 4 bytes/row|col
# for the block input plus the same again for the accumulation scratch.
# Bound the accepted shapes well inside the ~100 MB VMEM limit.
_MAX_TABLE_ROWS = 1 << 22  # 4M rows -> 2 x 16 MB (out block + scratch)
_MAX_TABLE_COLS = 1 << 21  # 2M cols -> 2 x 8 MB


def supports_tiling(batch) -> bool:
    """Static gate: shapes the tile-COO path handles well — a genuinely
    sparse high-dimensional problem (the dense path beats it otherwise)
    small enough that both VMEM-resident tables fit (beyond the bounds,
    the XLA gather/scatter path is slow but correct; chunk rows and sum
    partial gradients to stay inside them)."""
    from photon_ml_tpu.ops.batch import SparseBatch

    return (
        isinstance(batch, SparseBatch)
        and batch.num_features >= 4096
        and SLAB <= batch.num_rows <= _MAX_TABLE_ROWS
        and batch.num_features <= _MAX_TABLE_COLS
        # an all-padding batch tiles to 0 groups, and a 0-group kernel is
        # not compilable (s32[0,128] operand) — the XLA path handles it
        and bool(np.any(np.asarray(batch.values) != 0))
    )
