"""Tile-COO sparse GLM kernels: MXU/VPU-bound high-dimensional sparse ops.

Why this exists (SURVEY.md §7 hard parts, "Sparse features on TPU";
VERDICT r2 missing #3): XLA lowers the padded-sparse ``SparseBatch``
margins/gradient to element-at-a-time dynamic gathers and scatters —
~6e7 elem/s on TPU, latency-bound, which put the high-dimensional sparse
config BELOW one CPU core. The reference's platform (Breeze on JVM) does
these as cache-friendly CSR loops; beating it needs the sparse pass to run
out of VMEM at vector rates.

Design — write-slab-major tile-COO, built ONCE at ingest:

- The source vector (w for margins, r for the gradient) lives in VMEM as a
  (len/128, 128) table; so does the output (m / g), accumulated in a VMEM
  scratch and written out at the last grid step.
- Every nonzero is assigned to a CELL = (write-slab, read-slab) where a
  slab is 1024 consecutive outputs/inputs = an (8, 128) block of the
  corresponding table. Nonzeros are sorted by cell (write-slab major) and
  each cell padded to a whole number of GROUPS_PER_RUN-group RUNS of
  GROUP=128 nonzeros (zero-valued fillers) — consecutive groups of one
  cell read ONE source slab, so the kernel loads each shared slab once
  per run and batches the gather over the whole run (the r5 ablation's
  per-group skeleton floor, hoisted; see GROUPS_PER_RUN).
- Each WRITE SLAB's nonzeros are further padded to a multiple of
  GROUPS_PER_STEP groups, so one grid step processes GROUPS_PER_STEP
  groups that ALL write to the same (8, 128) output slab. Per group the
  kernel does only vector-rate work:
    * read:  slab = src[rslab] (one (8,128) dynamic slice; slab id comes
      from an SMEM-prefetched per-group array, not a vector lane read);
      ``take_along_axis(slab, lane, 1)`` pulls the wanted lane from all 8
      sublanes, an 8-way iota-compare select keeps the right sublane —
      exactly Mosaic's lane/8-sublane gather scope.
    * write: contributions are staged into an A matrix (8, G*128) masked
      by output sublane, and a TRANSPOSED one-hot B_T (128, G*128) with
      B_T[l, j] = (l == lane(j)). Building B transposed keeps the lane
      indices in the LANE dimension (the straightforward (G*128, 128)
      one-hot needs a lane->sublane transpose per group — measured ~2x
      slower end to end).
- One ``dot_general`` contracts A and B_T over their last dims: a single
  (8, G*128) x (128, G*128) -> (8, 128) MXU call scatters ALL of the
  step's nonzeros into the shared write slab (one matmul per G groups vs
  one per group in the first design — matmul issue count was the round-3
  bottleneck). B_T is exactly representable in bf16, and A is split into
  hi+mid+lo bf16 terms (Dekker-style, 24 mantissa bits), so the scatter
  runs at the MXU's bf16 rate while staying f32-exact (three passes
  instead of six for HIGHEST-f32).
- The one-hot operands stage per SEGMENT, not per group
  (``SEGMENT_BATCHED``, the r5 kernel): the r5 ablation (see the note at
  the kernel) measured the read gather as fully hidden and the per-group
  A/B_T staging as the cost center; batching the staging bought 1.41x on
  the margins direction (30.3 -> 21.4 ms on the A2 shapes, same relay
  session). Known open asymmetry: the gradient direction (write=col)
  runs ~3x the margins direction on identical group counts, invariant to
  read-table size (row chunking), staging mode, and MXU term count — the
  next profiling step needs per-op visibility inside the kernel that the
  dev relay cannot provide.
- margins (``matvec``) and gradient (``rmatvec``) each get their OWN
  layout — write=row/read=col and write=col/read=row respectively — the
  one-time ingest cost buys both directions their batched write slab.

``TiledSparseBatch`` is a drop-in ``Batch``: ``GLMObjective`` consumes it
through ``matvec``/``rmatvec``/``rmatvec_sq`` unchanged. Off-TPU the
kernels run in Pallas interpreter mode, so CPU tests exercise the exact
code path the TPU compiles. Shapes beyond the single-kernel VMEM bounds
are split into row/col chunks, each its own kernel call, with partial
outputs concatenated (rows) or summed (cols). Single-device by design:
under a mesh, shard rows first and build one tile-COO per shard (the
objective's psum handles the reduction).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops import _pallas_compat

Array = jnp.ndarray

GROUP = 128  # nonzeros per group: one vreg row, shares one (write, read) cell
# 32-group segments halve the number of sequential (matmul + accumulate)
# steps chained onto each write slab — measured 1.83x on the gradient
# direction (66.8 -> 36.6 ms on the A2 shapes, same session, parity
# intact; the margins direction is insensitive) at +1.4% stream padding.
# The DMA step stays at 128 groups (16K nnz per fetch).
GROUPS_PER_STEP = 32  # groups per SEGMENT: all share ONE write slab
SEGMENTS_PER_DMA = 4  # segments per DMA step (128 groups = 16K nnz per fetch)
# Slab-RUN batching (the r5 addendum's recorded next lever): consecutive
# groups of one cell read the SAME source slab, so the builder pads every
# cell to whole runs of GROUPS_PER_RUN groups and the kernel loads the
# shared slab ONCE per run, gathering/staging all of the run's nonzeros in
# batched ops instead of per group. Bigger runs amortize more of the
# per-group skeleton but pad scattered cells harder (a cell always pads to
# a whole run): at the A2 shapes cells average ~2 groups, so 2 is the
# padding-neutral default — retune per workload like the two constants
# above (must divide GROUPS_PER_STEP).
GROUPS_PER_RUN = 2  # groups per slab RUN: all read ONE source slab
# Software pipeline across SEGMENTS (the r6 addendum's recorded next
# kernel lever): phase 1 (VPU gather/select/product) and phase 2 (scatter
# staging + MXU contraction) of one segment touch disjoint scratch, so the
# kernel double-buffers ``p_scratch`` (two segment slots) and issues
# segment s+1's phase 1 BEFORE segment s's phase 2 — the VPU gather stream
# of one segment overlaps the MXU dots of the previous one, hiding
# whichever side is shorter. The skew carries across the DMA-step
# boundary too (the last segment of step t overlaps the first segment of
# step t+1, composing with the double-buffered DMA). 0 restores the
# straight-line schedule bit-for-bit (same per-phase math, same
# accumulation order — the parity tests assert bitwise equality); retune
# from the environment via PHOTON_PIPELINE_SEGMENTS (bench.py RETUNE_ENV).
PIPELINE_SEGMENTS = 1  # 1 = skewed segment schedule, 0 = straight-line
SLAB = 1024  # outputs/inputs per slab: an (8, 128) block of a table
# Precision ladder for the PACKED SLAB STORAGE and the gathered source
# operand (ROADMAP "Mixed-precision sparse-tiled kernels"): A2 is
# HBM-bound, so after pipelining/prefetch/caching hid latency, the next
# raw-speed lever is to move fewer bytes. The rungs change STORAGE only —
# the MXU contraction always accumulates in f32 through the existing
# 3-term Dekker split, and ``p_scratch``/``acc_scratch`` stay f32:
#
#   f32  — today's layout bit-for-bit (12 B/nnz: full i32 write/read
#          indices + f32 value bits). The BITWISE-parity anchor: knob
#          unset and knob=f32 reproduce the pre-ladder kernels exactly
#          (asserted with assert_array_equal across all four streamed
#          consumers).
#   bf16 — the same three streams at HALF width (6 B/nnz): the kernel
#          only ever consumes the low 10 bits of each index (lane +
#          sublane; the slab id rides the SMEM wslab/rslab/rrun streams),
#          so indices narrow to within-slab i16 offsets and values store
#          as bf16 bits in i16. Gathered source slabs are cast to bf16
#          too; products upcast to f32 before accumulation.
#   int8 — ONE i32 stream (4 B/nnz): write-offset(10) | read-offset(10)
#          | symmetric-int8 value(8), with per-CELL scale factors (one
#          (write-slab, read-slab) tile shares one scale, carried per
#          aligned RUN in the scalar-prefetched ``srun`` stream so the
#          kernel pays one SMEM read per run). Dequantized to f32 at
#          gather time; accumulation unchanged.
#
# bf16/int8 are NOT bitwise rungs — they gate on model-quality parity
# (AUC/RMSE deltas in the bench ``telemetry`` block, per BASELINE's
# "never report speed without a parity check" protocol). The dtype is a
# static key of the ``_tiled_apply`` jit cache, the tile-layout cache and
# the shared scoring program: toggling recompiles, never reuses. Retune
# from the environment via PHOTON_KERNEL_DTYPE (bench.py RETUNE_ENV).
KERNEL_DTYPE = "f32"  # storage rung: "f32" (parity anchor) | "bf16" | "int8"
KERNEL_DTYPES = ("f32", "bf16", "int8")


def validate_kernel_dtype(value) -> str:
    """Strict knob parse (the sibling PHOTON_RE_* knobs parse strict ints;
    a typo'd dtype must fail loudly, not fall back to f32 and silently
    bench the wrong rung)."""
    v = str(value).strip().lower()
    if v not in KERNEL_DTYPES:
        raise ValueError(
            f"PHOTON_KERNEL_DTYPE={value!r} is not a known precision rung; "
            f"valid rungs: {', '.join(KERNEL_DTYPES)}"
        )
    return v


def kernel_dtype() -> str:
    """The active storage rung, read at CALL time (env wins over the
    module global — the same discipline as the layout-shaping constants:
    an import-time capture would let layouts and kernel disagree)."""
    env = os.environ.get("PHOTON_KERNEL_DTYPE")
    if env is not None and env != "":
        return validate_kernel_dtype(env)
    return validate_kernel_dtype(KERNEL_DTYPE)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@dataclass(frozen=True)
class _Layout:
    """One direction's write-slab-major tiling (host numpy).

    ``packed`` interleaves the three per-nonzero streams — write index,
    read index, value bits — as (M/GROUP, 3, GROUP) int32, so the kernel
    fetches ONE contiguous block per DMA step. Measured on v5e: issuing
    one DMA per array per step capped the stream at ~20 GB/s (per-DMA
    issue/wait overhead ~1.5 us dominates 64 KB transfers); the packed
    single-DMA layout with 128-group steps is what made the stream cheap
    enough for the compute to be the limit again."""

    packed: np.ndarray  # (M/GROUP, S, GROUP) storage-dtype streams; f32:
    # S=3 int32 [write, read, val bits] (the pre-ladder layout verbatim),
    # bf16: S=3 int16 [write off10, read off10, bf16 bits], int8: S=1
    # int32 [write off10 | read off10 << 10 | symmetric q8 << 20]
    wslab: np.ndarray  # (M/(GROUP*GROUPS_PER_STEP),) int32: per-segment slab
    rslab: np.ndarray  # (M/GROUP,) int32 read slab id per group
    rrun: np.ndarray  # (M/(GROUP*GROUPS_PER_RUN),) int32: per-RUN read slab
    srun: np.ndarray  # (M/(GROUP*GROUPS_PER_RUN),) f32: per-RUN dequant
    # scale (each run is single-cell, so this carries the per-CELL int8
    # symmetric scale; all-ones for the f32/bf16 rungs, never read there)


def detect_slab_runs(rslab: np.ndarray) -> np.ndarray:
    """Run-length metadata over a per-group read-slab stream: maximal runs
    of consecutive groups reading one slab, as an (n_runs, 3) int64 array
    of [start group, length, slab id]. This is the host-side view the
    fixed-size ``GROUPS_PER_RUN`` blocks are carved from (the kernel
    consumes the aligned ``rrun`` stream; this helper backs builder
    assertions, tests and padding diagnostics)."""
    r = np.asarray(rslab, np.int64)
    if not len(r):
        return np.zeros((0, 3), np.int64)
    starts = np.flatnonzero(np.concatenate([[True], r[1:] != r[:-1]]))
    lengths = np.diff(np.concatenate([starts, [len(r)]]))
    return np.stack([starts, lengths, r[starts]], axis=1)


def build_write_major_layout(
    write_idx: np.ndarray,
    read_idx: np.ndarray,
    vals: np.ndarray,
    write_pad: int,
    read_pad: int,
    groups_per_step: int | None = None,
    groups_per_run: int | None = None,
    storage: str | None = None,
) -> _Layout:
    """Sort nonzeros by (write-slab, read-slab) cell, pad each cell to a
    whole number of ``groups_per_run``-group RUNS (every group of a cell
    reads the cell's slab, so an aligned run is single-slab by
    construction), then pad each write slab's group count to a multiple
    of ``groups_per_step`` (all vectorized — no Python per-cell loop).
    Fillers carry value 0 (they contribute exactly 0 through any slab).

    ``groups_per_step=None``/``groups_per_run=None``/``storage=None``
    read the module's GROUPS_PER_STEP / GROUPS_PER_RUN / kernel_dtype()
    at CALL time — a default-arg capture froze the import-time value, so
    layouts built after retuning the constant silently disagreed with
    the kernel consuming them (garbage outputs, caught by a parity
    probe). ``storage`` selects the packed-stream precision rung (see
    KERNEL_DTYPE): the f32 layout is the pre-ladder layout verbatim;
    bf16/int8 narrow the streams and must be consumed by a kernel
    compiled for the same rung (the jit/layout caches key on it)."""
    if groups_per_step is None:
        groups_per_step = GROUPS_PER_STEP
    if groups_per_run is None:
        groups_per_run = GROUPS_PER_RUN
    if storage is None:
        storage = kernel_dtype()
    else:
        storage = validate_kernel_dtype(storage)
    if groups_per_step % groups_per_run:
        raise ValueError(
            f"GROUPS_PER_RUN={groups_per_run} must divide "
            f"GROUPS_PER_STEP={groups_per_step}: segments are carved into "
            f"whole aligned runs"
        )
    w = np.asarray(write_idx, np.int32)
    r = np.asarray(read_idx, np.int32)
    v = np.asarray(vals, np.float32)
    nws = write_pad // SLAB
    nrs = read_pad // SLAB
    ws_of = (w // SLAB).astype(np.int64)
    cell = ws_of * nrs + (r // SLAB)
    order = np.argsort(cell, kind="stable")
    w, r, v, cell = w[order], r[order], v[order], cell[order]

    uniq, start, counts = np.unique(cell, return_index=True, return_counts=True)
    run_nnz = GROUP * groups_per_run
    pc = (-(-counts // run_nnz) * run_nnz).astype(np.int64)  # padded cell nnz
    cell_ws = (uniq // nrs).astype(np.int64)
    cell_rs = (uniq % nrs).astype(np.int32)

    # write-slab blocks: sum of padded cell counts, padded to SEGMENT
    # multiple (a segment = groups_per_step groups sharing one write slab)
    step_nnz = groups_per_step * GROUP
    nnz_per_ws = np.zeros(nws, np.int64)
    np.add.at(nnz_per_ws, cell_ws, pc)
    ws_padded = -(-nnz_per_ws // step_nnz) * step_nnz  # empty slabs -> 0
    ws_out_start = np.concatenate([[0], np.cumsum(ws_padded)])
    M = int(ws_out_start[-1])
    # tail: the stream must divide into whole DMA steps — append filler
    # SEGMENTS (write slab 0, value 0: they accumulate exactly 0)
    dma_nnz = step_nnz * SEGMENTS_PER_DMA
    M_total = max(-(-M // dma_nnz) * dma_nnz, dma_nnz)

    # each cell's output offset: write-slab base + within-slab running sum
    pc_excl = np.cumsum(pc) - pc
    uws, uws_first, uws_ncells = np.unique(
        cell_ws, return_index=True, return_counts=True
    )
    within_ws = pc_excl - np.repeat(pc_excl[uws_first], uws_ncells)
    cell_out = ws_out_start[cell_ws] + within_ws

    # init with per-write-slab corner fillers, then scatter the real nnz
    out_w = np.zeros(M_total, np.int32)
    out_w[:M] = np.repeat(
        (np.arange(nws, dtype=np.int64) * SLAB), ws_padded
    ).astype(np.int32)
    out_r = np.zeros(M_total, np.int32)
    out_v = np.zeros(M_total, np.float32)
    within_cell = np.arange(len(cell), dtype=np.int64) - np.repeat(start, counts)
    pos = np.repeat(cell_out, counts) + within_cell
    out_w[pos] = w
    out_r[pos] = r
    out_v[pos] = v

    # per-group read slab: a cell's groups all read its slab; filler groups
    # (write-slab/tail padding) read slab 0 — their values are all 0
    n_groups = M_total // GROUP
    rslab = np.zeros(n_groups, np.int32)
    gc = (pc // GROUP).astype(np.int64)  # groups per cell
    gc_excl = np.cumsum(gc) - gc
    gpos = (
        np.repeat(cell_out // GROUP, gc)
        + np.arange(int(gc.sum()), dtype=np.int64)
        - np.repeat(gc_excl, gc)
    )
    rslab[gpos] = np.repeat(cell_rs, gc)

    wslab = (out_w[::step_nnz] // SLAB).astype(np.int32)
    # per-run read slab: cells pad to whole runs and write-slab/tail
    # fillers (rslab 0) start run-aligned, so every aligned block is
    # single-slab — the invariant the kernel's once-per-run load rests on
    blocks = rslab.reshape(-1, groups_per_run)
    assert (blocks == blocks[:, :1]).all(), "slab run crosses a run block"
    rrun = np.ascontiguousarray(blocks[:, 0])
    n_runs = n_groups // groups_per_run
    srun = np.ones(n_runs, np.float32)
    if storage == "f32":
        packed = np.stack(
            [
                out_w.reshape(n_groups, GROUP),
                out_r.reshape(n_groups, GROUP),
                out_v.view(np.int32).reshape(n_groups, GROUP),
            ],
            axis=1,
        )
    elif storage == "bf16":
        import ml_dtypes

        # the kernel consumes only the within-slab offset (lane + sublane
        # = low 10 bits; slab ids ride the SMEM streams), so both index
        # streams narrow to i16 and the value stream stores bf16 bits —
        # the same three streams at exactly half width
        packed = np.stack(
            [
                (out_w % SLAB).astype(np.int16).reshape(n_groups, GROUP),
                (out_r % SLAB).astype(np.int16).reshape(n_groups, GROUP),
                out_v.astype(ml_dtypes.bfloat16).view(np.int16).reshape(
                    n_groups, GROUP
                ),
            ],
            axis=1,
        )
    else:  # int8: one i32 stream [w off10 | r off10 << 10 | q8 << 20]
        out_q = np.zeros(M_total, np.int64)
        if len(uniq):
            # symmetric per-CELL scale: every nonzero of a (write-slab,
            # read-slab) tile quantizes against the tile's |v| max, and
            # every aligned run of the cell carries that scale in srun
            # (fillers are q=0, inert under any scale)
            amax = np.maximum.reduceat(np.abs(v), start)
            cell_scale = (amax / 127.0).astype(np.float32)
            cell_scale[cell_scale == 0.0] = 1.0
            q = np.clip(
                np.rint(v / np.repeat(cell_scale, counts)), -127, 127
            ).astype(np.int64)
            out_q[pos] = q
            runs_per_cell = (pc // run_nnz).astype(np.int64)
            rpc_excl = np.cumsum(runs_per_cell) - runs_per_cell
            rpos = (
                np.repeat(cell_out // run_nnz, runs_per_cell)
                + np.arange(int(runs_per_cell.sum()), dtype=np.int64)
                - np.repeat(rpc_excl, runs_per_cell)
            )
            srun[rpos] = np.repeat(cell_scale, runs_per_cell)
        packed = (
            (out_w.astype(np.int64) % SLAB)
            | ((out_r.astype(np.int64) % SLAB) << 10)
            | ((out_q & 0xFF) << 20)
        ).astype(np.int32).reshape(n_groups, 1, GROUP)
    return _Layout(
        packed=packed, wslab=wslab, rslab=rslab, rrun=rrun, srun=srun
    )


# r5 ablation on the A2 shapes (n=2^19, d=2^17, k=32; one chunk,
# 21.2M padded nnz; relay session of 2026-07-31, ms/matvec):
#   full 30.3 | single-matmul 25.7 | no-B_T-build 22.1 | no-A-staging
#   20.4 | no-gather 31.2
# i.e. the READ gather is fully hidden behind the scatter pipeline, and
# the cost is the per-group staging of the one-hot operands (A ~33%,
# B_T ~27%, Dekker's two extra matmuls ~15%). SEGMENT_BATCHED stages
# whole segments instead: ONE relayout of the packed block to a
# (1, seg_nnz) row per stream, one batched one-hot compare per segment,
# matmul operands built as
# VALUES (no a/bt VMEM scratch round-trip), one batched one-hot build
# per segment instead of ``groups`` per-group ones. The r6 follow-up (the
# retuned-state ablation's recorded lever) batches PHASE 1 the same way:
# skeleton loads/bitcast hoist per segment and the source slab loads once
# per GROUPS_PER_RUN-group run — see _tile_kernel_seg.
SEGMENT_BATCHED = True


def _decode_packed(load, storage):
    """Phase 1's packed-stream decode, shared by BOTH kernels (one copy
    of the per-rung bit layout — a drifted duplicate would let phase 1
    and phase 2 disagree on offsets and produce silent garbage).
    ``load(stream)`` returns one packed stream's 2-D block, so each rung
    loads ONLY the streams it consumes; returns ``(rd, vals)`` — i32
    within-slab read offsets and f32 values (RAW q for int8: the per-run
    scale is applied by the caller, after the optional square
    decision)."""
    if storage == "int8":
        pk = load(0)
        rd = (pk >> 10) & 1023
        q = (pk >> 20) & 255
        return rd, (q - ((q & 128) << 1)).astype(jnp.float32)
    rd = load(1)
    if storage == "bf16":
        return rd.astype(jnp.int32), pltpu.bitcast(
            load(2), jnp.bfloat16
        ).astype(jnp.float32)
    return rd, pltpu.bitcast(load(2), jnp.float32)


def _decode_write_offsets(wr, storage):
    """Phase 2's write-stream decode, shared by both kernels: normalize
    the per-rung storage to i32 within-slab write offsets."""
    if storage == "bf16":
        return wr.astype(jnp.int32)
    if storage == "int8":
        return wr & 1023  # low 10 bits of the single packed stream
    return wr


def _run_segment_schedule(dma, phase1, phase2, *, n_steps, segs, pipeline):
    """The per-step segment loop shared by BOTH kernels, expressed over
    their ``dma(slot, t)`` / ``phase1(buf_slot, t, s2, p_slot)`` /
    ``phase2(buf_slot, t, s2, p_slot)`` callables — one copy of the DMA
    pairing and slot-parity logic, so the two kernels cannot diverge.

    ``pipeline`` selects the skewed schedule (see PIPELINE_SEGMENTS):
    prologue runs segment 0's phase 1; each steady-state iteration issues
    segment s+1's phase 1 (VPU gather stream) before segment s's phase 2
    (MXU contraction stream), crossing the DMA-step boundary at a step's
    last segment by waiting the already-in-flight next fetch mid-step.
    Every DMA semaphore is started and waited exactly once on either
    schedule; the straight-line schedule is the pre-pipeline loop
    verbatim (phase 1 then phase 2 per segment, slot 0 only)."""
    dma(0, 0).start()

    if pipeline:
        dma(0, 0).wait()
        phase1(0, 0, 0, 0)

        def step(t, carry):
            slot = jax.lax.rem(t, 2)
            nxt = jax.lax.rem(t + 1, 2)

            # start the next fetch first (its pk_buf slot was last read by
            # the previous iteration's trailing phase 2, already issued by
            # this sequential core), so it overlaps this whole step
            @pl.when(t + 1 < n_steps)
            def _():
                dma(nxt, t + 1).start()

            for s2 in range(segs):
                sg = t * segs + s2  # global segment index
                cur_p = jax.lax.rem(sg, 2)
                nxt_p = jax.lax.rem(sg + 1, 2)
                # skew: the NEXT segment's phase 1 issues before THIS
                # segment's phase 2 — disjoint p_scratch slots, so the
                # gather stream and the MXU stream have no dependency
                if s2 + 1 < segs:
                    phase1(slot, t, s2 + 1, nxt_p)
                else:
                    @pl.when(t + 1 < n_steps)
                    def _():
                        # cross-step handoff: wait the already-in-flight
                        # next fetch and pipeline its first segment
                        # against this step's last contraction
                        dma(nxt, t + 1).wait()
                        phase1(nxt, t + 1, 0, nxt_p)
                phase2(slot, t, s2, cur_p)
            return carry
    else:
        def step(t, carry):
            slot = jax.lax.rem(t, 2)
            nxt = jax.lax.rem(t + 1, 2)

            @pl.when(t + 1 < n_steps)
            def _():
                dma(nxt, t + 1).start()

            dma(slot, t).wait()

            for s2 in range(segs):
                phase1(slot, t, s2, 0)
                phase2(slot, t, s2, 0)
            return carry

    jax.lax.fori_loop(0, n_steps, step, 0)


def _tile_kernel_seg(
    wslab_ref, rslab_ref, rrun_ref, srun_ref, packed_hbm, src_ref, out_ref,
    acc_scratch, p_scratch, pk_buf, dma_sem,
    *, n_steps, groups, segs, run_groups, square_vals, pipeline, storage,
):
    """Segment-batched kernel with slab-RUN phase 1 (see SEGMENT_BATCHED
    note): the per-group skeleton the r5 retuned-state ablation measured
    as the floor (packed-buffer loads, value bitcast, p-scratch store,
    ~135 ns per 128-nnz group) hoists to ONE batched load/bitcast per
    segment, and the source slab loads once per ``run_groups``-group RUN
    (the layout builder guarantees aligned runs are single-slab), with the
    gather/sublane-select/product batched over the whole run. Phase 2 is
    the whole-segment scatter staging + 3-term Dekker bf16 MXU
    contraction.

    ``pipeline`` selects the SOFTWARE-PIPELINED segment schedule (see
    PIPELINE_SEGMENTS): ``p_scratch`` carries two segment slots and the
    loop is skewed — prologue runs segment 0's phase 1, each steady-state
    iteration issues segment s+1's phase 1 (VPU gather stream) before
    segment s's phase 2 (MXU contraction stream), and at the step boundary
    the NEXT step's DMA is waited mid-step so its first segment's phase 1
    overlaps the last segment's phase 2. Both schedules run identical
    per-phase math in identical accumulation order, so outputs are
    BIT-IDENTICAL (asserted by the parity tests).

    ``storage`` selects the packed-stream precision rung (KERNEL_DTYPE):
    only phase 1's stream decode changes — f32 reproduces the pre-ladder
    decode verbatim (the bitwise anchor), bf16 widens i16 offsets and
    bitcasts bf16 value bits, int8 unpacks the single i32 stream and
    dequantizes by the per-run SMEM scale (``srun_ref``). Products land
    in f32 ``p_scratch`` either way, and phase 2's Dekker-split f32 MXU
    accumulation is IDENTICAL across rungs."""
    step_groups = segs * groups
    seg_nnz = groups * GROUP
    run_nnz = run_groups * GROUP
    seg_runs = groups // run_groups
    step_runs = step_groups // run_groups
    # int32 iota: this hardware supports no narrower iota (8- and 16-bit
    # both rejected by Mosaic) — the win here is the batching, not density
    iota8_run = jax.lax.broadcasted_iota(jnp.int32, (8, run_nnz), 0)
    iota8_seg = jax.lax.broadcasted_iota(jnp.int32, (8, seg_nnz), 0)
    iota_sub_seg = jax.lax.broadcasted_iota(jnp.int32, (GROUP, seg_nnz), 0)
    acc_scratch[...] = jnp.zeros_like(acc_scratch)

    def dma(slot, t):
        return pltpu.make_async_copy(
            packed_hbm.at[pl.ds(t * step_groups, step_groups)],
            pk_buf.at[slot],
            dma_sem.at[slot],
        )

    def phase1(buf_slot, t, s2, p_slot):
        """Batched gather/sublane-select/product of segment (t, s2) from
        ``pk_buf[buf_slot]`` into ``p_scratch[p_slot]``."""
        g0 = s2 * groups
        # per-group skeleton, hoisted: one packed-buffer load per
        # stream and one value decode for the WHOLE segment
        rd_all, vals_all = _decode_packed(
            lambda s: pk_buf[buf_slot, g0:g0 + groups, s, :], storage
        )  # (groups, GROUP) each
        lane_all = rd_all & 127
        sub_all = (rd_all >> 7) & 7
        if square_vals and storage != "int8":
            # int8 squares AFTER dequantization (below): (q·s)² needs the
            # per-run scale, and scale² must not leak into the raw q
            vals_all = vals_all * vals_all
        for b in range(seg_runs):
            gb = b * run_groups
            # ONE shared-slab load per run; the gather pulls all of
            # the run's nonzeros from it in one batched op
            rslab = rrun_ref[t * step_runs + s2 * seg_runs + b]
            slab = src_ref[pl.ds(pl.multiple_of(rslab * 8, 8), 8), :]
            lanes = lane_all[gb:gb + run_groups, :].reshape(1, run_nnz)
            gathered = jnp.take_along_axis(
                slab, jnp.broadcast_to(lanes, (8, run_nnz)), axis=1
            )
            if storage != "f32":
                # the gathered operand is stored bf16 (the other half of
                # the bytes-moved win); upcast BEFORE the product so the
                # accumulation chain is f32 end to end
                gathered = gathered.astype(jnp.float32)
            sub_r = sub_all[gb:gb + run_groups, :].reshape(1, run_nnz)
            sel = (
                iota8_run == jnp.broadcast_to(sub_r, (8, run_nnz))
            ).astype(jnp.float32)
            src_vals = jnp.sum(gathered * sel, axis=0)  # (run_nnz,)
            v = vals_all[gb:gb + run_groups, :]
            if storage == "int8":
                v = v * srun_ref[t * step_runs + s2 * seg_runs + b]
                if square_vals:
                    v = v * v
            p_scratch[p_slot, gb:gb + run_groups, :] = (
                v * src_vals.reshape(run_groups, GROUP)
            )

    def phase2(buf_slot, t, s2, p_slot):
        """Whole-segment scatter staging + MXU contraction of segment
        (t, s2), reading phase 1's products from ``p_scratch[p_slot]``:
        one relayout per stream, int8 one-hot compares, operands as
        values."""
        g0 = s2 * groups
        wr = _decode_write_offsets(
            pk_buf[buf_slot, g0:g0 + groups, 0, :], storage
        )  # (groups, GROUP)
        wr_row = wr.reshape(1, seg_nnz)
        lane_w = wr_row & 127
        sub_w = (wr_row >> 7) & 7
        p_row = p_scratch[p_slot].reshape(1, seg_nnz)
        # explicit broadcasts + mask-multiply: the implicit (1, n) ->
        # (8, n) broadcast inside compare/select trips a Mosaic
        # "invalid relayout" on the i1 mask
        mask8 = iota8_seg == jnp.broadcast_to(sub_w, (8, seg_nnz))
        a = (
            jnp.broadcast_to(p_row, (8, seg_nnz))
            * mask8.astype(jnp.float32)
        )
        a_hi = a.astype(jnp.bfloat16)
        rem = a - a_hi.astype(jnp.float32)
        a_mid = rem.astype(jnp.bfloat16)
        a_lo = (rem - a_mid.astype(jnp.float32)).astype(jnp.bfloat16)
        bt = (
            iota_sub_seg == jnp.broadcast_to(lane_w, (GROUP, seg_nnz))
        ).astype(jnp.bfloat16)
        dims = (((1,), (1,)), ((), ()))
        ms = (
            jax.lax.dot_general(
                a_hi, bt, dims, preferred_element_type=jnp.float32
            )
            + jax.lax.dot_general(
                a_mid, bt, dims, preferred_element_type=jnp.float32
            )
            + jax.lax.dot_general(
                a_lo, bt, dims, preferred_element_type=jnp.float32
            )
        )
        ws = wslab_ref[t * segs + s2]
        idx = pl.ds(pl.multiple_of(ws * 8, 8), 8)
        acc_scratch[idx, :] = acc_scratch[idx, :] + ms

    _run_segment_schedule(
        dma, phase1, phase2, n_steps=n_steps, segs=segs, pipeline=pipeline
    )
    out_ref[...] = acc_scratch[...]


def _tile_kernel(
    wslab_ref, rslab_ref, rrun_ref, srun_ref, packed_hbm, src_ref, out_ref,
    acc_scratch, a_scratch, bt_scratch, p_scratch, pk_buf, dma_sem,
    *, n_steps, groups, segs, run_groups, square_vals, pipeline, storage,
):
    """Single-launch kernel: a ``fori_loop`` over DMA steps, each step
    fetching ``segs * groups`` groups in ONE double-buffered DMA and
    running ``segs`` segment scatters (one batched MXU call per segment,
    whose groups all write one output slab). ``rrun_ref`` rides along for
    prefetch-signature parity with the segment-batched kernel; this
    per-group variant reads the per-group ``rslab_ref`` stream.

    The phase split mirrors ``_tile_kernel_seg``: phase 1 is the per-group
    gather/select/product into ``p_scratch`` (two slots under
    ``pipeline`` — see PIPELINE_SEGMENTS), phase 2 the per-group one-hot
    staging + per-segment MXU contraction, so the same skewed schedule
    overlaps adjacent segments' VPU and MXU streams here too. ``storage``
    (KERNEL_DTYPE) changes only the per-group stream decode, exactly as in
    the segment-batched kernel; accumulation stays f32 on every rung."""
    step_groups = segs * groups
    step_runs = step_groups // run_groups
    iota8 = jax.lax.broadcasted_iota(jnp.int32, (8, GROUP), 0)
    iota_sub = jax.lax.broadcasted_iota(jnp.int32, (GROUP, GROUP), 0)
    acc_scratch[...] = jnp.zeros_like(acc_scratch)

    def dma(slot, t):
        return pltpu.make_async_copy(
            packed_hbm.at[pl.ds(t * step_groups, step_groups)],
            pk_buf.at[slot],
            dma_sem.at[slot],
        )

    def phase1(buf_slot, t, s2, p_slot):
        """Per-group gather/sublane-select/product of segment (t, s2)
        into ``p_scratch[p_slot]``."""
        for gi in range(groups):
            g = s2 * groups + gi
            # (1, GROUP) 2-D blocks (Mosaic's bitcast scope), squeezed
            # after the shared decode
            rd, vals = _decode_packed(
                lambda s: pk_buf[buf_slot, g:g + 1, s, :], storage
            )
            rd, vals = rd[0, :], vals[0, :]
            lane_r = rd & 127
            sub_r = (rd >> 7) & 7
            rslab = rslab_ref[t * step_groups + g]
            slab = src_ref[pl.ds(pl.multiple_of(rslab * 8, 8), 8), :]
            gathered = jnp.take_along_axis(
                slab, jnp.broadcast_to(lane_r[None, :], (8, GROUP)), axis=1
            )
            if storage != "f32":
                gathered = gathered.astype(jnp.float32)
            sel = (iota8 == sub_r[None, :]).astype(jnp.float32)
            src_vals = jnp.sum(gathered * sel, axis=0)  # (GROUP,)
            if storage == "int8":
                vals = vals * srun_ref[t * step_runs + g // run_groups]
            if square_vals:
                # Hessian-diagonal contraction (rmatvec_sq) squares the
                # values in-register — no second packed stream needed
                # (int8: after dequantization, so the square carries s²)
                vals = vals * vals
            p_scratch[p_slot, gi, :] = vals * src_vals

    def phase2(buf_slot, t, s2, p_slot):
        """Per-group one-hot staging + one MXU scatter for segment
        (t, s2), reading phase 1's products from ``p_scratch[p_slot]``."""
        for gi in range(groups):
            g = s2 * groups + gi
            p = p_scratch[p_slot, gi, :]
            wr = _decode_write_offsets(pk_buf[buf_slot, g, 0, :], storage)
            lane_w = wr & 127
            sub_w = (wr >> 7) & 7
            cols = pl.ds(g * GROUP, GROUP)
            a_scratch[:, cols] = jnp.where(
                iota8 == sub_w[None, :], p[None, :], 0.0
            )
            # TRANSPOSED one-hot: lane indices stay in the lane dim
            bt_scratch[:, cols] = (
                iota_sub == lane_w[None, :]
            ).astype(jnp.bfloat16)

        # one MXU scatter per segment: contract over the nnz dimension.
        # B_T is exact in bf16; A splits into hi+mid+lo bf16 terms
        # (Dekker style, each residual exactly representable -> 24
        # mantissa bits), so three bf16 passes reproduce the f32
        # product (vs six for HIGHEST f32)
        seg_cols = pl.ds(s2 * groups * GROUP, groups * GROUP)
        a = a_scratch[:, seg_cols]
        a_hi = a.astype(jnp.bfloat16)
        rem = a - a_hi.astype(jnp.float32)
        a_mid = rem.astype(jnp.bfloat16)
        a_lo = (rem - a_mid.astype(jnp.float32)).astype(jnp.bfloat16)
        bt = bt_scratch[:, seg_cols]
        dims = (((1,), (1,)), ((), ()))
        ms = (
            jax.lax.dot_general(
                a_hi, bt, dims, preferred_element_type=jnp.float32
            )
            + jax.lax.dot_general(
                a_mid, bt, dims, preferred_element_type=jnp.float32
            )
            + jax.lax.dot_general(
                a_lo, bt, dims, preferred_element_type=jnp.float32
            )
        )
        ws = wslab_ref[t * segs + s2]
        idx = pl.ds(pl.multiple_of(ws * 8, 8), 8)
        acc_scratch[idx, :] = acc_scratch[idx, :] + ms

    _run_segment_schedule(
        dma, phase1, phase2, n_steps=n_steps, segs=segs, pipeline=pipeline
    )
    out_ref[...] = acc_scratch[...]


@functools.partial(
    jax.jit,
    static_argnames=(
        "out_pad", "src_pad", "square_vals",
        "groups", "segs", "run_groups", "seg_batched", "pipeline",
        "storage", "interpret", "topology",
    ),
)
def _tiled_apply_jit(
    layout_arrays, src, out_pad, src_pad, square_vals,
    groups, segs, run_groups, seg_batched, pipeline, storage, interpret,
    topology=None,
):
    packed, wslab, rslab, rrun, srun = layout_arrays
    step_groups = segs * groups
    n_steps = int(packed.shape[0]) // step_groups
    src_shape = (src_pad // 128, 128)
    out_shape = (out_pad // 128, 128)
    src_mat = src.reshape(src_shape)
    if storage != "f32":
        # the gathered operand stores bf16 under both reduced rungs (the
        # source vector changes per call, so per-call int8 quantization
        # would buy nothing); products upcast to f32 inside phase 1
        src_mat = src_mat.astype(jnp.bfloat16)
    # packed-stream shape/dtype per rung (must match the layout builder):
    # f32 (.., 3, GROUP) i32 | bf16 (.., 3, GROUP) i16 | int8 (.., 1,
    # GROUP) i32 — a layout built under one rung fails loudly under a
    # kernel compiled for another (the caches key on the rung, so the
    # only way there is hand-assembling mismatched pieces)
    n_streams = 1 if storage == "int8" else 3
    buf_dtype = jnp.int16 if storage == "bf16" else jnp.int32
    # p_scratch: phase 1's per-segment products. The pipelined schedule
    # double-buffers it (segment s+1's phase 1 writes one slot while
    # segment s's phase 2 drains the other); straight-line needs one slot.
    p_slots = 2 if pipeline else 1
    if seg_batched:
        kernel = functools.partial(
            _tile_kernel_seg, n_steps=n_steps, groups=groups, segs=segs,
            run_groups=run_groups, square_vals=square_vals,
            pipeline=pipeline, storage=storage,
        )
        scratch = [
            pltpu.VMEM(out_shape, jnp.float32),
            pltpu.VMEM((p_slots, groups, GROUP), jnp.float32),  # p_scratch
            pltpu.VMEM((2, step_groups, n_streams, GROUP), buf_dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ]
    else:
        kernel = functools.partial(
            _tile_kernel, n_steps=n_steps, groups=groups, segs=segs,
            run_groups=run_groups, square_vals=square_vals,
            pipeline=pipeline, storage=storage,
        )
        scratch = [
            pltpu.VMEM(out_shape, jnp.float32),
            pltpu.VMEM((8, step_groups * GROUP), jnp.float32),
            pltpu.VMEM((GROUP, step_groups * GROUP), jnp.bfloat16),
            pltpu.VMEM((p_slots, groups, GROUP), jnp.float32),  # p_scratch
            pltpu.VMEM((2, step_groups, n_streams, GROUP), buf_dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ]
    f = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=_pallas_compat.ANY),
                pl.BlockSpec(src_shape, lambda i, *_: (0, 0)),
            ],
            out_specs=pl.BlockSpec(out_shape, lambda i, *_: (0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
        compiler_params=_pallas_compat.compiler_params(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=120 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return f(wslab, rslab, rrun, srun, packed, src_mat).reshape(-1)


def _tiled_apply(layout_arrays, src, out_pad, src_pad, square_vals=False):
    """Run one direction's kernel: src (src_pad,) -> out (out_pad,).

    The tuned constants enter the jitted call as STATIC arguments, read
    from the module at CALL time: they are part of the executable's cache
    key, so a retune after a compile can never silently reuse a stale
    executable whose argument shapes happen to coincide (e.g. swapping
    GROUPS_PER_STEP=32/SEGMENTS_PER_DMA=4 for 16/8 keeps every stream
    shape identical while changing the kernel's segment carve). This is
    also what makes the compiled kernel a PROCESS-WIDE executable cache:
    any layout with the same stream shapes and constants — across
    streaming chunks, GAME visits and CV folds — re-enters the same
    compiled program. PIPELINE_SEGMENTS and the KERNEL_DTYPE storage rung
    are part of the same static key: toggling either mid-process
    recompiles, never reuses.

    Analytic cost capture (``obs/devcost``) shadows the same key: an
    eager call whose (knob tuple, stream signature) is fresh captures the
    kernel executable's XLA flops/bytes once — calls under an outer
    trace (the optimizer/scoring jits) skip, and THAT enclosing
    executable is captured at its own boundary instead."""
    from photon_ml_tpu.parallel.multihost import effective_topology

    args = (
        layout_arrays, src, out_pad, src_pad, square_vals,
        GROUPS_PER_STEP, SEGMENTS_PER_DMA, GROUPS_PER_RUN, SEGMENT_BATCHED,
        bool(PIPELINE_SEGMENTS), kernel_dtype(), _interpret(),
        # effective topology rides as a static key: a degrade-in-place
        # must never re-enter a pre-loss executable by shape coincidence,
        # and a same-topology re-entry compiles nothing new
        effective_topology(),
    )
    from photon_ml_tpu.obs import devcost

    devcost.capture("sparse_tiled.tiled_apply", _tiled_apply_jit, args)
    return _tiled_apply_jit(*args)


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["m_arrays", "g_arrays"],
    meta_fields=["row_start", "col_start", "n_pad", "d_pad"],
)
@dataclass(frozen=True)
class _TileChunk:
    """One (row-range x col-range) kernel chunk: both direction layouts."""

    m_arrays: tuple  # margins: (packed, wslab, rslab, rrun, srun), write=row
    g_arrays: tuple  # gradient: same five streams, write=col
    row_start: int = field(metadata=dict(static=True))
    col_start: int = field(metadata=dict(static=True))
    n_pad: int = field(metadata=dict(static=True))
    d_pad: int = field(metadata=dict(static=True))

    def matvec_part(self, w_full: Array) -> Array:
        w = jax.lax.dynamic_slice(w_full, (self.col_start,), (self.d_pad,))
        return _tiled_apply(self.m_arrays, w, self.n_pad, self.d_pad)

    def rmatvec_part(self, r_full: Array, squared: bool) -> Array:
        r = jax.lax.dynamic_slice(r_full, (self.row_start,), (self.n_pad,))
        return _tiled_apply(
            self.g_arrays, r, self.d_pad, self.n_pad, square_vals=squared
        )


@functools.partial(
    jax.tree_util.register_dataclass,
    data_fields=["chunks", "labels", "offsets", "weights"],
    meta_fields=["num_features", "num_rows_real", "n_pad_total", "d_pad_total",
                 "fe_range"],
)
@dataclass(frozen=True)
class TiledSparseBatch:
    """Drop-in ``Batch`` whose margins/gradient run the tile-COO Pallas
    kernels. ``labels``/``offsets``/``weights`` are (n,) with the ORIGINAL
    row indexing. Build with ``tile_sparse_batch``; shapes beyond one
    kernel's VMEM bounds arrive as multiple row/col chunks."""

    chunks: tuple  # tuple[_TileChunk, ...]
    labels: Array
    offsets: Array
    weights: Array
    num_features: int = field(metadata=dict(static=True))
    num_rows_real: int = field(metadata=dict(static=True))
    n_pad_total: int = field(metadata=dict(static=True))
    d_pad_total: int = field(metadata=dict(static=True))
    # Feature-range identity under PHOTON_FE_SHARD: (pid, lo, hi, P) when
    # this batch's columns are the [lo, hi) slice of the global feature
    # space, else None. STATIC (a meta field) so the range id + boundaries
    # ride every jit key that takes the batch — the dtype-ladder
    # discipline: a re-plan invalidates by key, never by luck.
    fe_range: tuple | None = field(default=None, metadata=dict(static=True))

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    def matvec(self, w: Array) -> Array:
        d = self.num_features
        w_pad = w if d == self.d_pad_total else jnp.pad(w, (0, self.d_pad_total - d))
        m = jnp.zeros((self.n_pad_total,), jnp.float32)
        for c in self.chunks:
            m = jax.lax.dynamic_update_slice(
                m,
                jax.lax.dynamic_slice(m, (c.row_start,), (c.n_pad,))
                + c.matvec_part(w_pad),
                (c.row_start,),
            )
        return m[: self.num_rows]

    def _rmatvec(self, r: Array, squared: bool) -> Array:
        n = self.num_rows
        r_pad = r if n == self.n_pad_total else jnp.pad(r, (0, self.n_pad_total - n))
        g = jnp.zeros((self.d_pad_total,), jnp.float32)
        for c in self.chunks:
            g = jax.lax.dynamic_update_slice(
                g,
                jax.lax.dynamic_slice(g, (c.col_start,), (c.d_pad,))
                + c.rmatvec_part(r_pad, squared),
                (c.col_start,),
            )
        return g[: self.num_features]

    def rmatvec(self, r: Array) -> Array:
        return self._rmatvec(r, squared=False)

    def rmatvec_sq(self, r: Array) -> Array:
        return self._rmatvec(r, squared=True)


# A chunk holds four tables in VMEM across its two kernels: the src block,
# the out block, and the f32 accumulation scratch (out-sized), plus the
# staged A/B_T step matrices. Bound each chunk's table sizes well inside
# the ~128 MB VMEM limit; bigger problems are built as multiple chunks.
_MAX_TABLE_ROWS = 1 << 22  # 4M rows -> out block + scratch = 2 x 16 MB
_MAX_TABLE_COLS = 1 << 21  # 2M cols -> 2 x 8 MB


def _build_chunk(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
    row_start: int, col_start: int, n_pad: int, d_pad: int,
) -> _TileChunk:
    storage = kernel_dtype()  # ONE call-time read for both directions
    m = build_write_major_layout(rows, cols, vals, n_pad, d_pad,
                                 storage=storage)
    g = build_write_major_layout(cols, rows, vals, d_pad, n_pad,
                                 storage=storage)
    as_j = lambda lay: tuple(
        jnp.asarray(a)
        for a in (lay.packed, lay.wslab, lay.rslab, lay.rrun, lay.srun)
    )
    return _TileChunk(
        m_arrays=as_j(m),
        g_arrays=as_j(g),
        row_start=row_start,
        col_start=col_start,
        n_pad=n_pad,
        d_pad=d_pad,
    )


def tile_sparse_batch(batch, keep_empty_chunks: bool = False,
                      fe_range: tuple | None = None) -> TiledSparseBatch:
    """Build a ``TiledSparseBatch`` from a padded-sparse ``SparseBatch``
    (host-side one-time transform; zero-valued padding slots are dropped
    before tiling). Shapes beyond the per-kernel VMEM bounds are split
    into row/col chunks along SLAB-aligned boundaries.

    ``keep_empty_chunks`` keeps nonzero-free chunks instead of skipping
    them — the per-device-shard builder needs every shard to carry the
    SAME chunk structure so the stacked pytrees line up under shard_map.
    """
    indices = np.asarray(batch.indices)
    values = np.asarray(batch.values)
    n, k = indices.shape
    d = batch.num_features
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = indices.reshape(-1).astype(np.int64)
    vals = values.reshape(-1).astype(np.float32)
    keep = vals != 0.0
    rows, cols, vals = rows[keep], cols[keep], vals[keep]

    n_pad_total = -(-n // SLAB) * SLAB
    d_pad_total = -(-d // SLAB) * SLAB
    n_row_chunks = -(-n_pad_total // _MAX_TABLE_ROWS)
    n_col_chunks = -(-d_pad_total // _MAX_TABLE_COLS)
    chunks = []
    for rc in range(n_row_chunks):
        r0 = rc * _MAX_TABLE_ROWS
        r1 = min(r0 + _MAX_TABLE_ROWS, n_pad_total)
        in_r = (rows >= r0) & (rows < r1)
        for cc in range(n_col_chunks):
            c0 = cc * _MAX_TABLE_COLS
            c1 = min(c0 + _MAX_TABLE_COLS, d_pad_total)
            m = in_r & (cols >= c0) & (cols < c1)
            if (
                n_row_chunks * n_col_chunks > 1
                and not keep_empty_chunks
                and not m.any()
            ):
                continue
            chunks.append(
                _build_chunk(
                    rows[m] - r0, cols[m] - c0, vals[m],
                    row_start=r0, col_start=c0,
                    n_pad=r1 - r0, d_pad=c1 - c0,
                )
            )
    return TiledSparseBatch(
        chunks=tuple(chunks),
        labels=batch.labels,
        offsets=batch.offsets,
        weights=batch.weights,
        num_features=d,
        num_rows_real=n,
        n_pad_total=n_pad_total,
        d_pad_total=d_pad_total,
        fe_range=fe_range,
    )


# Beyond these totals the chunk count (each chunk = 2 kernel compiles)
# stops paying for itself against the streamed/sharded paths.
_MAX_TOTAL_ROWS = 1 << 25  # 32M rows = 8 row chunks
_MAX_TOTAL_COLS = 1 << 23  # 8M cols = 4 col chunks


def tiling_economical_features(num_features: int) -> bool:
    """The feature-dimension half of the tiling gate, shared with the
    streamed objective's auto rule (one decision, two ingest paths —
    duplicating it let the streamed rule drop the upper cap): genuinely
    high-dimensional, but within the chunk-count economy ceiling."""
    return 4096 <= num_features <= _MAX_TOTAL_COLS


def auto_tile_streaming(sparse: bool, num_features: int | None) -> bool:
    """The streamed paths' ONE auto rule for tile-COO chunk kernels — the
    chunked objective and the module scorer both call this (a drifted
    copy would tile shapes the other path no longer tiles): sparse
    chunks, genuinely high-dimensional, on a real TPU (interpret-mode
    tiling is test-only and opts in explicitly via tile_sparse=True)."""
    return (
        bool(sparse)
        and num_features is not None
        and tiling_economical_features(num_features)
        and jax.default_backend() == "tpu"
    )


def supports_tiling(batch) -> bool:
    """Static gate: shapes the tile-COO path handles well — a genuinely
    sparse high-dimensional problem (the dense path beats it otherwise).
    Shapes beyond one kernel's VMEM bounds are row/col-chunked, so the
    ceiling here is the chunk-count economy, not VMEM."""
    from photon_ml_tpu.ops.batch import SparseBatch

    return (
        isinstance(batch, SparseBatch)
        and tiling_economical_features(batch.num_features)
        and SLAB <= batch.num_rows <= _MAX_TOTAL_ROWS
        # an all-padding batch tiles to 0 groups, and a 0-group kernel is
        # not compilable (s32[0,128] operand) — the XLA path handles it
        and bool(np.any(np.asarray(batch.values) != 0))
    )


def _pad_layout_groups(arrays: tuple, target_groups: int) -> tuple:
    """Extend one direction's (packed, wslab, rslab, rrun, srun) stream
    with filler segments up to ``target_groups`` groups. Fillers use the
    builder's tail convention — write slab 0, read slab 0, value 0, scale
    1 — and contribute exactly 0; ``target_groups`` must be a
    whole-DMA-step multiple (every built stream already is, so the max
    over shards is too), and a DMA step is a whole number of runs."""
    packed, wslab, rslab, rrun, srun = arrays
    n_groups = packed.shape[0]  # packed is (n_groups, S, GROUP)
    if n_groups == target_groups:
        return arrays
    add = target_groups - n_groups
    packed = jnp.concatenate(
        [packed, jnp.zeros((add,) + packed.shape[1:], packed.dtype)]
    )
    rslab = jnp.concatenate([rslab, jnp.zeros((add,), rslab.dtype)])
    segs = add // GROUPS_PER_STEP
    wslab = jnp.concatenate([wslab, jnp.zeros((segs,), wslab.dtype)])
    runs = add // GROUPS_PER_RUN
    rrun = jnp.concatenate([rrun, jnp.zeros((runs,), rrun.dtype)])
    srun = jnp.concatenate([srun, jnp.ones((runs,), srun.dtype)])
    return (packed, wslab, rslab, rrun, srun)


def pad_chunks_to_common_groups(tbs: list) -> list[list]:
    """Pad every ``TiledSparseBatch`` in ``tbs`` (identical chunk
    structure) so that chunk j's streams have the SAME group count across
    all batches — the shared prerequisite for stacking per-shard layouts
    under ``shard_map`` and for serving every streamed chunk with one
    compiled kernel. Returns ``out[j][i]`` = batch i's padded chunk j."""
    n_chunks = len(tbs[0].chunks)
    assert all(len(tb.chunks) == n_chunks for tb in tbs)
    out = []
    for j in range(n_chunks):
        targets = {
            side: max(
                getattr(tb.chunks[j], side)[0].shape[0] for tb in tbs
            )
            for side in ("m_arrays", "g_arrays")
        }
        out.append(
            [
                _TileChunk(
                    m_arrays=_pad_layout_groups(
                        tb.chunks[j].m_arrays, targets["m_arrays"]
                    ),
                    g_arrays=_pad_layout_groups(
                        tb.chunks[j].g_arrays, targets["g_arrays"]
                    ),
                    row_start=tb.chunks[j].row_start,
                    col_start=tb.chunks[j].col_start,
                    n_pad=tb.chunks[j].n_pad,
                    d_pad=tb.chunks[j].d_pad,
                )
                for tb in tbs
            ]
        )
    return out


def tile_sparse_batch_sharded(batch, n_dev: int):
    """Per-device tile-COO for a row-sharded mesh solve — the module
    docstring's own multi-device recipe ("shard rows first and build one
    tile-COO per shard; the objective's psum handles the reduction"),
    implemented as a host-side ingest transform:

    - rows pad to an ``n_dev`` multiple and split into ``n_dev``
      contiguous shards (equal row counts → identical chunk structure);
    - each shard tiles independently (``keep_empty_chunks`` so the chunk
      lists line up), streams pad to the max group count across shards;
    - every array leaf stacks on a LEADING DEVICE AXIS. The result is a
      ``TiledSparseBatch``-shaped pytree whose leaves are (n_dev, ...) —
      shard it with ``PartitionSpec(axis)`` and drop the unit leading axis
      inside ``shard_map`` to recover each device's local batch.

    Returns (stacked_batch, rows_per_shard).
    """
    from photon_ml_tpu.ops.batch import pad_batch

    n = batch.num_rows
    rows_per_shard = -(-n // n_dev)
    batch = pad_batch(batch, rows_per_shard * n_dev)
    shards = [
        jax.tree.map(
            lambda a: a[i * rows_per_shard:(i + 1) * rows_per_shard], batch
        )
        for i in range(n_dev)
    ]
    tbs = [tile_sparse_batch(sh, keep_empty_chunks=True) for sh in shards]
    ref = tbs[0]
    padded = pad_chunks_to_common_groups(tbs)

    stacked_chunks = []
    for j in range(len(ref.chunks)):
        stacked_chunks.append(
            _TileChunk(
                m_arrays=tuple(
                    jnp.stack([c.m_arrays[i] for c in padded[j]])
                    for i in range(5)
                ),
                g_arrays=tuple(
                    jnp.stack([c.g_arrays[i] for c in padded[j]])
                    for i in range(5)
                ),
                row_start=ref.chunks[j].row_start,
                col_start=ref.chunks[j].col_start,
                n_pad=ref.chunks[j].n_pad,
                d_pad=ref.chunks[j].d_pad,
            )
        )
    stacked = TiledSparseBatch(
        chunks=tuple(stacked_chunks),
        labels=jnp.stack([tb.labels for tb in tbs]),
        offsets=jnp.stack([tb.offsets for tb in tbs]),
        weights=jnp.stack([tb.weights for tb in tbs]),
        num_features=ref.num_features,
        num_rows_real=ref.num_rows_real,
        n_pad_total=ref.n_pad_total,
        d_pad_total=ref.d_pad_total,
    )
    return stacked, rows_per_shard
