"""Pointwise GLM losses: ``l(margin, label)`` with first and second
derivatives with respect to the margin.

Reference parity: ``photon-api::ml.function.glm.PointwiseLossFunction`` and
its implementations ``LogisticLossFunction``, ``SquaredLossFunction``,
``PoissonLossFunction``, plus the smoothed hinge loss used by
``DistributedSmoothedHingeLossFunction`` (SURVEY.md §2.2).

Design: each loss is a namespace of three pure jnp functions
(``value``, ``d1``, ``d2``) over (margin, label) arrays. The GLM objective
calls them inside one fused pass so XLA fuses loss + reduction into the
matmul epilogue. All math is elementwise (VPU); the surrounding matmuls
(margins, gradient contractions) hit the MXU.

Conventions (matching the reference):
- margin = w·x + offset
- logistic labels are 0/1; loss = log(1 + exp(-margin)) for y=1, i.e.
  softplus(-sign * margin) with sign = 2y - 1 (numerically stable form).
- Poisson uses the log link: loss = exp(margin) - y * margin.
- squared loss = 0.5 * (margin - y)^2.
- smoothed hinge (Rennie & Srebro): labels 0/1 mapped to ±1; piecewise
  quadratic smoothing of the hinge on z = sign * margin.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp

from photon_ml_tpu.types import TaskType

Array = jnp.ndarray


@dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise loss with derivatives w.r.t. the margin.

    ``value``/``d1``/``d2`` map (margin, label) elementwise. ``mean`` is the
    inverse link (prediction from margin), used by model classes for scoring.
    """

    name: str
    value: Callable[[Array, Array], Array]
    d1: Callable[[Array, Array], Array]
    d2: Callable[[Array, Array], Array]
    mean: Callable[[Array], Array]


# --- logistic -----------------------------------------------------------------
def _logistic_value(margin: Array, label: Array) -> Array:
    sign = 2.0 * label - 1.0
    return jax.nn.softplus(-sign * margin)


def _logistic_d1(margin: Array, label: Array) -> Array:
    # d/dm [softplus(-s m)] = -s * sigmoid(-s m) = sigmoid(m) - y   (for y in {0,1})
    return jax.nn.sigmoid(margin) - label


def _logistic_d2(margin: Array, label: Array) -> Array:
    p = jax.nn.sigmoid(margin)
    return p * (1.0 - p)


logistic_loss = PointwiseLoss(
    name="logistic",
    value=_logistic_value,
    d1=_logistic_d1,
    d2=_logistic_d2,
    mean=jax.nn.sigmoid,
)


# --- squared ------------------------------------------------------------------
squared_loss = PointwiseLoss(
    name="squared",
    value=lambda m, y: 0.5 * (m - y) ** 2,
    d1=lambda m, y: m - y,
    d2=lambda m, y: jnp.ones_like(m),
    mean=lambda m: m,
)


# --- poisson ------------------------------------------------------------------
poisson_loss = PointwiseLoss(
    name="poisson",
    value=lambda m, y: jnp.exp(m) - y * m,
    d1=lambda m, y: jnp.exp(m) - y,
    d2=lambda m, y: jnp.exp(m),
    mean=jnp.exp,
)


# --- smoothed hinge -----------------------------------------------------------
def _smoothed_hinge_pieces(margin: Array, label: Array):
    sign = 2.0 * label - 1.0
    z = sign * margin
    return sign, z


def _smoothed_hinge_value(margin: Array, label: Array) -> Array:
    # Rennie & Srebro smooth hinge on z = s*m:
    #   z <= 0      : 0.5 - z
    #   0 < z < 1   : 0.5 * (1 - z)^2
    #   z >= 1      : 0
    _, z = _smoothed_hinge_pieces(margin, label)
    return jnp.where(z <= 0.0, 0.5 - z, jnp.where(z < 1.0, 0.5 * (1.0 - z) ** 2, 0.0))


def _smoothed_hinge_d1(margin: Array, label: Array) -> Array:
    sign, z = _smoothed_hinge_pieces(margin, label)
    dz = jnp.where(z <= 0.0, -1.0, jnp.where(z < 1.0, z - 1.0, 0.0))
    return sign * dz  # chain rule through z = s*m (s^2 = 1)


def _smoothed_hinge_d2(margin: Array, label: Array) -> Array:
    _, z = _smoothed_hinge_pieces(margin, label)
    return jnp.where((z > 0.0) & (z < 1.0), 1.0, 0.0)


smoothed_hinge_loss = PointwiseLoss(
    name="smoothed_hinge",
    value=_smoothed_hinge_value,
    d1=_smoothed_hinge_d1,
    d2=_smoothed_hinge_d2,
    # SVM "mean" = raw margin (decision value), thresholded by callers
    mean=lambda m: m,
)


LOSSES: dict[str, PointwiseLoss] = {
    loss.name: loss
    for loss in (logistic_loss, squared_loss, poisson_loss, smoothed_hinge_loss)
}


def loss_for_task(task: TaskType) -> PointwiseLoss:
    """Select the pointwise loss for a task type (parity with how the
    reference binds ``TaskType`` → ``PointwiseLossFunction``)."""
    return {
        TaskType.LOGISTIC_REGRESSION: logistic_loss,
        TaskType.LINEAR_REGRESSION: squared_loss,
        TaskType.POISSON_REGRESSION: poisson_loss,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: smoothed_hinge_loss,
    }[task]
