"""GLM objective: fused value / gradient / Hessian-vector kernels.

Reference parity: this module replaces the reference's entire objective
stack — ``photon-lib::ml.function.{ObjectiveFunction,DiffFunction,
TwiceDiffFunction}``, ``photon-api::ml.function.glm.DistributedGLMLossFunction``
and ``SingleNodeGLMLossFunction``, and the aggregators
(``ValueAndGradientAggregator``, ``HessianVectorAggregator``,
``HessianMatrixAggregator``, ``HessianDiagonalAggregator``) — SURVEY.md §2.2.

TPU-first design (vs the reference's broadcast + per-partition fold +
treeAggregate):

- One fused pass per evaluation: margins (MXU matmul) → pointwise loss
  derivatives (VPU, fused by XLA) → gradient contraction (MXU matmul).
- **The distributed and single-node objectives are the same code.** The
  ``axis_name`` field selects the twin (SURVEY.md §4 "twin structure"): when
  set, the objective is being traced inside ``shard_map`` over a mesh axis
  and partial sums are reduced with ``lax.psum`` over ICI — the reference's
  driver→executor broadcast *and* executor→driver treeAggregate both
  collapse into that one collective, and the optimizer loop stays on device.
- Loss semantics match the reference: objective = Σ_i weight_i·l(margin_i, y_i)
  (+ 0.5·λ₂·‖w‖² over regularized coordinates). Sums, not means, so
  regularization weights mean the same thing as in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.normalization import NormalizationContext, no_normalization
from photon_ml_tpu.ops.batch import Batch, DenseBatch
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.types import VarianceComputationType

Array = jnp.ndarray


def reg_delta(w: Array, prior_mean, prior_precision) -> Array:
    """prec·(w − μ) — the (L2 or Gaussian-MAP) regularizer's gradient
    direction; w itself for plain L2. The ONE home for this math: both the
    device objective and the streamed twin delegate here, so the MAP policy
    cannot diverge between the paths."""
    if prior_mean is None:
        return w
    prec = jnp.ones_like(w) if prior_precision is None else prior_precision
    return prec * (w - prior_mean)


def reg_curvature(like: Array, prior_mean, prior_precision) -> Array:
    """The regularizer's diagonal curvature scale (prec, or ones)."""
    if prior_mean is None or prior_precision is None:
        return jnp.ones_like(like)
    return prior_precision


def reg_term(w: Array, l2_weight, reg_mask, prior_mean, prior_precision) -> Array:
    """0.5·λ₂·Σ maskⱼ·precⱼ·(wⱼ−μⱼ)² (μ=0, prec=1 for plain L2)."""
    delta = w if prior_mean is None else w - prior_mean
    prec = reg_curvature(w, prior_mean, prior_precision)
    return 0.5 * l2_weight * jnp.sum(reg_mask * prec * delta * delta)


def _interpret_fused() -> bool:
    """Pallas kernels run compiled on TPU, interpreter-mode elsewhere (the
    CPU test suite exercises the identical program)."""
    return jax.default_backend() != "tpu"


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["batch", "norm", "l2_weight", "reg_mask", "prior_mean",
                 "prior_precision"],
    meta_fields=["loss", "axis_name", "fused", "offsets_zero", "weights_one"],
)
@dataclass(frozen=True)
class GLMObjective:
    """Value/gradient/Hv contracts consumed by the optimizers.

    Fields:
      batch     — the (local shard of the) training data.
      norm      — normalization applied inside evaluation (never to data).
      l2_weight — scalar λ₂ (array so regularization grids don't recompile).
      reg_mask  — (d,) 0/1 mask of regularized coordinates (intercept → 0).
      loss      — pointwise loss namespace (static).
      axis_name — mesh axis to psum over, or None for single-node (static).
      fused     — use the one-pass Pallas kernels (``ops/fused.py``) for
                  value_and_grad/hvp on dense batches (static; ``X`` streams
                  from HBM once per evaluation instead of 2-3 times).
      offsets_zero / weights_one — static data hints (detected once at
                  construction): constant-0 offsets / constant-1 weights
                  let the fused kernels skip those VMEM-padded aux streams
                  and run larger X tiles.
      prior_mean / prior_precision — optional (d,) Gaussian prior for
                  incremental training: the regularizer becomes
                  0.5·λ₂·Σ maskⱼ·precⱼ·(wⱼ−μⱼ)², i.e. a MAP update toward
                  the previous model (reference: Photon-ML's incremental
                  learning uses the prior model's means/variances the same
                  way; plain L2 is the μ=0, prec=1 special case).
    """

    batch: Batch
    norm: NormalizationContext
    l2_weight: Array
    reg_mask: Array
    loss: PointwiseLoss
    axis_name: str | None = None
    fused: bool = False
    offsets_zero: bool = False
    weights_one: bool = False
    prior_mean: Array | None = None
    prior_precision: Array | None = None

    # -- collective hook (identity when single-node) --------------------------
    def _reduce(self, x):
        if self.axis_name is None:
            return x
        return lax.psum(x, self.axis_name)

    def _weighted(self, x: Array) -> Array:
        """weights * x, with zero-weight rows forced to exactly 0 so padding
        can never poison the sums (0 * inf would be NaN — e.g. an overflowed
        poisson loss on a padded row)."""
        w = self.batch.weights
        return jnp.where(w != 0.0, w * x, 0.0)

    # -- margins --------------------------------------------------------------
    def margins(self, w: Array) -> Array:
        u, c = self.norm.to_effective(w)
        return self.batch.matvec(u) - c + self.batch.offsets

    # -- regularizer (plain L2 or Gaussian prior) ------------------------------
    def _reg_delta(self, w: Array) -> Array:
        return reg_delta(w, self.prior_mean, self.prior_precision)

    def _reg_curvature(self, like: Array) -> Array:
        return reg_curvature(like, self.prior_mean, self.prior_precision)

    # -- objective contracts ---------------------------------------------------
    def _l2_term(self, w: Array) -> Array:
        return reg_term(
            w, self.l2_weight, self.reg_mask, self.prior_mean,
            self.prior_precision,
        )

    @property
    def one_pass_value_grad(self) -> bool:
        """Line-search policy hint for the optimizers: evaluate
        value_and_grad at every TRIAL point (instead of value-only trials
        plus a separate gradient pass at acceptance). True when (a) the
        fused dense kernel makes value_and_grad cost one X read anyway, or
        (b) the tile-COO sparse kernels make the typical one-trial
        iteration cheaper that way (margins+grad = 2 kernel passes beats
        margins-trial + margins+grad = 3)."""
        from photon_ml_tpu.ops.sparse_tiled import TiledSparseBatch

        return self.fused or isinstance(self.batch, TiledSparseBatch)

    def value(self, w: Array) -> Array:
        m = self.margins(w)
        local = jnp.sum(self._weighted(self.loss.value(m, self.batch.labels)))
        return self._reduce(local) + self._l2_term(w)

    # -- margin-state API (Newton's hot loop) ----------------------------------
    # Margins are affine in w, so a solver can carry m = margins(w) in its
    # loop state (updating it as m + t·dm after a line search) and derive
    # value/grad/Hessian from the STORED margins — one matvec per iteration
    # (the direction's) instead of re-deriving margins inside every
    # contract. ``optim.newton`` uses these when present; the generic
    # value/grad contracts above stay the interface for everything else.

    def direction_margins(self, p: Array) -> Array:
        """d margins / d t along direction p (no offset term)."""
        u_p, c_p = self.norm.to_effective(p)
        return self.batch.matvec(u_p) - c_p

    def value_and_grad_from_margins(self, m: Array, w: Array) -> tuple[Array, Array]:
        """``value_and_grad(w)`` given m = margins(w) — saves the forward
        matvec; the gradient contraction still reads the data once."""
        lv = self.loss.value(m, self.batch.labels)
        r = self._weighted(self.loss.d1(m, self.batch.labels))
        local = (jnp.sum(self._weighted(lv)), self.batch.rmatvec(r), jnp.sum(r))
        val, g_raw, r_sum = self._reduce(local)
        g = (self.norm.grad_to_model_space(g_raw, r_sum)
             + self.l2_weight * self.reg_mask * self._reg_delta(w))
        return val + self._l2_term(w), g

    def hessian_from_margins(self, m: Array, w: Array) -> Array:
        """``hessian(w)`` given m = margins(w) (dense batches only)."""
        if not isinstance(self.batch, DenseBatch):
            raise NotImplementedError(
                "full Hessian requires a DenseBatch; use hessian_diag or hvp"
            )
        d2 = self._weighted(self.loss.d2(m, self.batch.labels))
        Z = (self.batch.X - self.norm.shifts) * self.norm.factors
        h = self._reduce(Z.T @ (d2[:, None] * Z))
        return h + jnp.diag(self.l2_weight * self.reg_mask * self._reg_curvature(self.reg_mask))

    def ray_values_from_margins(
        self, m: Array, dm: Array, w: Array, p: Array, ts: Array
    ) -> Array:
        """``ray_values`` given m = margins(w) and dm = direction_margins(p)
        — the whole Armijo ladder with NO matvec at all."""
        y = self.batch.labels

        def at(t):
            return jnp.sum(self._weighted(self.loss.value(m + t * dm, y)))

        data = self._reduce(jax.vmap(at)(ts))
        return data + self._reg_ray(w, p, ts)

    def _reg_ray(self, w: Array, p: Array, ts: Array) -> Array:
        """0.5·λ·Σ mask·prec·(δ + t·p)² for every t (δ = w − μ, or w)."""
        delta = w if self.prior_mean is None else w - self.prior_mean
        prec = self._reg_curvature(w)
        q0 = jnp.sum(self.reg_mask * prec * delta * delta)
        q1 = jnp.sum(self.reg_mask * prec * delta * p)
        q2 = jnp.sum(self.reg_mask * prec * p * p)
        return 0.5 * self.l2_weight * (q0 + 2.0 * ts * q1 + ts * ts * q2)

    def ray_values(self, w: Array, p: Array, ts: Array) -> Array:
        """Objective at ``w + t·p`` for every t in ``ts`` — data is read
        ONCE regardless of len(ts).

        Margins are affine in w (``to_effective`` is linear), so
        m(t) = m(w) + t·dm with one extra matvec for dm; each trial is then
        an elementwise loss reduction over precomputed margins, and the
        quadratic regularizer expands analytically in t. Newton's Armijo
        ladder uses this: the naive ``vmap`` over trial points paid K full
        X-reads per iteration (profiled: the dominant cost of bench config
        E's per-entity solves after the solver itself went custom-call-free).
        """
        return self.ray_values_from_margins(
            self.margins(w), self.direction_margins(p), w, p, ts
        )

    def value_and_grad(self, w: Array) -> tuple[Array, Array]:
        if self.fused and isinstance(self.batch, DenseBatch):
            from photon_ml_tpu.ops.fused import fused_value_grad

            u, c = self.norm.to_effective(w)
            local = fused_value_grad(
                self.batch.X, self.batch.labels,
                None if self.offsets_zero else self.batch.offsets,
                None if self.weights_one else self.batch.weights,
                u, c, loss=self.loss,
                interpret=_interpret_fused(),
            )
        else:
            return self.value_and_grad_from_margins(self.margins(w), w)
        val, g_raw, r_sum = self._reduce(local)
        g = (self.norm.grad_to_model_space(g_raw, r_sum)
             + self.l2_weight * self.reg_mask * self._reg_delta(w))
        return val + self._l2_term(w), g

    def grad(self, w: Array) -> Array:
        return self.value_and_grad(w)[1]

    def hvp(self, w: Array, v: Array) -> Array:
        """Gauss-Newton/Hessian-vector product H·v = AᵀDA·v + λ₂·v (A = the
        normalized design matrix, D = diag(weight·d2)). One forward matmul +
        one reverse matmul; for TRON's CG loop this is the hot kernel."""
        v_eff = self.norm.factors * v
        if self.fused and isinstance(self.batch, DenseBatch):
            from photon_ml_tpu.ops.fused import fused_hvp

            u, c = self.norm.to_effective(w)
            local = fused_hvp(
                self.batch.X, self.batch.labels,
                None if self.offsets_zero else self.batch.offsets,
                None if self.weights_one else self.batch.weights,
                u, v_eff, c,
                jnp.dot(self.norm.shifts, v_eff), loss=self.loss,
                interpret=_interpret_fused(),
            )
        else:
            m = self.margins(w)
            d2 = self._weighted(self.loss.d2(m, self.batch.labels))
            mv = self.batch.matvec(v_eff) - jnp.dot(self.norm.shifts, v_eff)
            q = d2 * mv
            local = (self.batch.rmatvec(q), jnp.sum(q))
        hv_raw, q_sum = self._reduce(local)
        hv = self.norm.grad_to_model_space(hv_raw, q_sum)
        return hv + self.l2_weight * self.reg_mask * self._reg_curvature(v) * v

    def hessian_diag(self, w: Array) -> Array:
        """diag(H) — for VarianceComputationType.SIMPLE.

        diag_j = f_j² [ Σ d2ᵢxᵢⱼ² − 2 s_j Σ d2ᵢxᵢⱼ + s_j² Σ d2ᵢ ] + λ₂·mask.
        """
        m = self.margins(w)
        d2 = self._weighted(self.loss.d2(m, self.batch.labels))
        local = (self.batch.rmatvec_sq(d2), self.batch.rmatvec(d2), jnp.sum(d2))
        sq, lin, tot = self._reduce(local)
        f, s = self.norm.factors, self.norm.shifts
        diag = f * f * (sq - 2.0 * s * lin + s * s * tot)
        return diag + self.l2_weight * self.reg_mask * self._reg_curvature(diag)

    def hessian(self, w: Array) -> Array:
        """Full (d, d) Hessian — for VarianceComputationType.FULL. Dense
        batches only (FULL variance is a small-d feature in the reference
        too: it inverts a d×d matrix on the driver)."""
        return self.hessian_from_margins(self.margins(w), w)




@partial(
    jax.tree_util.register_dataclass,
    data_fields=["means", "variances"],
    meta_fields=["min_variance"],
)
@dataclass(frozen=True)
class GaussianPrior:
    """Informative Gaussian prior for incremental training (MAP update).

    Built from a previously-trained model's coefficient means and
    variances: the new fit is pulled toward ``means`` with per-coordinate
    strength 1/variance (relative to the L2 weight λ₂). Reference:
    Photon-ML's incremental learning consumes the prior model's
    ``BayesianLinearModelAvro`` means/variances the same way (SURVEY.md §2.3
    Model IO; warm start + prior = incremental retraining).

    Registered as a pytree so it can cross ``jit``/``shard_map`` boundaries
    (the sharded fixed-effect solve passes it as a replicated argument).
    """

    means: Array
    variances: Array | None = None
    min_variance: float = 1e-6

    @property
    def precisions(self) -> Array | None:
        """1/variance, with NON-POSITIVE variances treated as UNINFORMATIVE
        (precision 1, i.e. plain-L2 strength). Model loaders zero-fill
        variances for features absent from the saved record and for padded
        new entities — clamping those zeros to min_variance would give them
        near-infinite precision and freeze them at the prior mean forever;
        the reference gives missing prior features a default variance of 1
        for exactly this reason."""
        if self.variances is None:
            return None
        v = jnp.asarray(self.variances, jnp.float32)
        return jnp.where(v > 0.0, 1.0 / jnp.maximum(v, self.min_variance), 1.0)

    @classmethod
    def from_coefficients(cls, means, variances, norm=None) -> "GaussianPrior":
        """Build the prior IN THE SOLVER'S SPACE from original-feature-space
        model coefficients: means map through the normalization, variances
        through the inverse of the output map var_out = f²·var_norm. The
        single home for this transform (GLM sweep, GAME fixed effect, and
        the per-entity lanes all route through it); handles (d,) vectors
        and (E, d) per-entity matrices alike."""
        mu = jnp.asarray(means, jnp.float32)
        if norm is not None:
            f = norm.model_from_original_space
            mu = jax.vmap(f)(mu) if mu.ndim == 2 else f(mu)
        var = None
        if variances is not None:
            var = jnp.asarray(variances, jnp.float32)
            if norm is not None:
                var = var / (norm.factors**2)
        return cls(means=mu, variances=var)


def compute_variances(
    obj: GLMObjective, w: Array, variance_type: VarianceComputationType
) -> Array | None:
    """Coefficient variances from the Hessian at the optimum.

    Parity: ``photon-api::ml.optimization.VarianceComputationType`` — SIMPLE
    inverts the Hessian diagonal; FULL takes the diagonal of the full
    Hessian inverse. Shared by the GLM sweep and the GAME fixed-effect
    coordinate (one implementation, one set of numerical guards).
    """
    if variance_type is VarianceComputationType.NONE:
        return None
    if variance_type is VarianceComputationType.SIMPLE:
        return 1.0 / jnp.maximum(obj.hessian_diag(w), 1e-12)
    H = obj.hessian(w)
    d = H.shape[0]
    Hinv = jnp.linalg.inv(H + 1e-9 * jnp.eye(d, dtype=H.dtype))
    return jnp.diag(Hinv)


def make_objective(
    batch: Batch,
    loss: PointwiseLoss,
    l2_weight: float | Array = 0.0,
    norm: NormalizationContext | None = None,
    intercept_index: int | None = None,
    axis_name: str | None = None,
    fused: bool | None = None,
    data_hints: tuple[bool, bool] | None = None,
    prior: "GaussianPrior | None" = None,
) -> GLMObjective:
    """Convenience constructor. ``intercept_index`` is excluded from L2
    regularization (and from normalization if ``norm`` is built with it).

    ``fused=None`` auto-enables the one-pass Pallas kernels on TPU for
    dense batches with supported shapes (``ops/fused.py``); pass
    ``False``/``True`` to force (``True`` off-TPU runs the kernels in
    interpreter mode — correct but slow, for tests). Set the environment
    variable ``PHOTON_DISABLE_FUSED=1`` to veto auto-enabling.

    ``data_hints`` = (offsets all zero, weights all one), for callers that
    know their device-resident data (host numpy arrays are auto-detected
    for free). The hints let the fused kernels drop those aux streams.

    ``prior`` switches the regularizer from plain L2 to a Gaussian MAP
    prior (incremental training): 0.5·λ₂·Σ maskⱼ·precⱼ·(wⱼ−μⱼ)²."""
    d = batch.num_features
    if norm is None:
        norm = no_normalization(d, intercept_index)
    mask = jnp.ones((d,), jnp.float32)
    if intercept_index is not None:
        mask = mask.at[intercept_index].set(0.0)
    if fused is None:
        fused = auto_fused(batch)
    offsets_zero = weights_one = False
    if fused:
        offsets_zero, weights_one = (
            data_hints if data_hints is not None else _constant_hints(batch)
        )
    return GLMObjective(
        batch=batch,
        norm=norm,
        l2_weight=jnp.asarray(l2_weight, jnp.float32),
        reg_mask=mask,
        loss=loss,
        axis_name=axis_name,
        fused=bool(fused),
        offsets_zero=offsets_zero,
        weights_one=weights_one,
        prior_mean=None if prior is None else jnp.asarray(prior.means, jnp.float32),
        prior_precision=None if prior is None else prior.precisions,
    )


def fused_disabled() -> bool:
    """``PHOTON_DISABLE_FUSED`` veto for :func:`auto_fused`, strict int
    parse like every sibling knob. The previous truthiness read made
    ``PHOTON_DISABLE_FUSED=0`` DISABLE fusion — ``"0"`` is a truthy
    string — which is exactly the inversion the lint knob pass now
    rejects repo-wide (``knob-truthy-parse``)."""
    import os

    env = os.environ.get("PHOTON_DISABLE_FUSED")
    if env is not None and env != "":
        return int(env) != 0
    return False


def auto_fused(batch: Batch) -> bool:
    """Should this (concrete) batch use the one-pass Pallas kernels?
    True on TPU for dense, lane-aligned, VMEM-feasible shapes. Callers that
    construct objectives inside a transform (``shard_map``, ``vmap``) must
    decide BEFORE entering it — under a transform X is a tracer and this
    returns False (pallas under vmap batching rules is untested; under
    ``shard_map`` pass the pre-computed answer through a static arg, as
    ``parallel/distributed.py`` does with per-device row counts)."""
    from photon_ml_tpu.ops.fused import supports_fused

    return (
        isinstance(batch, DenseBatch)
        and not isinstance(batch.X, jax.core.Tracer)
        and jax.default_backend() == "tpu"
        and not fused_disabled()
        and supports_fused(batch.num_rows, batch.num_features, batch.X.dtype)
    )


def _constant_hints(batch: Batch) -> tuple[bool, bool]:
    """(offsets all 0, weights all 1) — static data hints for the fused
    kernels. Only HOST numpy arrays are inspected (a free scan): checking a
    device array would force a blocking device→host sync per objective
    construction, which call sites like the coordinate-descent loop pay
    every iteration. Callers holding device arrays that know their data
    pass ``data_hints`` to ``make_objective`` instead."""
    import numpy as np

    def _is_const(x, value) -> bool:
        return isinstance(x, np.ndarray) and bool(np.all(x == value))

    return _is_const(batch.offsets, 0.0), _is_const(batch.weights, 1.0)
