"""Generic byte-budgeted LRU — the chunk cache's accounting, reusable.

``ops/prefetch.py`` grew a device-resident chunk cache whose useful core
is not chunk-specific at all: an ordered map of entries with a byte cost,
a budget read at call time, hit/miss/eviction accounting in BYTES, and
the two invariants the prefetch tests pin down — an entry larger than the
whole budget is never pinned (it is simply not admitted), and eviction
walks strictly least-recently-used until the budget holds. The serving
subsystem needs exactly that machinery for a different payload (per-entity
model coefficient shards instead of data chunks), so this module lifts the
accounting into a standalone class both granularities can state their
contracts against.

Deliberately metric-agnostic: callers wire the ``on_hit``/``on_miss``/
``on_evict`` hooks to their own CONSTANT-named registry counters (the
telemetry-surface lint wants literal emission names at the call site —
``prefetch.cache.*`` for chunks, ``serve.hot.*`` for model shards), so the
generic tier never emits under a computed name.

Thread-safety: all mutating operations take the instance lock; hooks are
called OUTSIDE the lock (a hook that re-enters the cache must not
deadlock, and registry counters need no ordering guarantees).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Hashable

_NOOP: Callable[[int], None] = lambda nbytes: None


class ByteBudgetLRU:
    """Byte-budgeted LRU map of ``key -> (value, nbytes)``.

    ``budget_fn`` is read at CALL time on every admission (the repo's
    knob discipline: env-driven retunes must take effect without
    rebuilding the cache). ``get`` refreshes recency on hit; ``put``
    admits the entry and then evicts least-recently-used entries until
    the budget holds again. An entry whose ``nbytes`` exceeds the whole
    budget is never admitted (the chunk cache's no-pin rule: one
    over-budget item must not wipe the working set and then pin itself).
    """

    def __init__(
        self,
        budget_fn: Callable[[], int],
        on_hit: Callable[[int], None] = _NOOP,
        on_miss: Callable[[int], None] = _NOOP,
        on_evict: Callable[[int], None] = _NOOP,
    ) -> None:
        self._budget_fn = budget_fn
        self._on_hit = on_hit
        self._on_miss = on_miss
        self._on_evict = on_evict
        self._entries: OrderedDict[Hashable, tuple[Any, int]] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    # -- queries ------------------------------------------------------------
    @property
    def bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._entries

    def get(self, key: Hashable):
        """The cached value (recency refreshed, hit hook in entry bytes),
        or None. A miss here fires NO hook — only ``put`` knows the byte
        cost of what was missing, so the miss hook fires at admission."""
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                return None
            self._entries.move_to_end(key)
            value, nbytes = hit
        self._on_hit(nbytes)
        return value

    # -- mutation -----------------------------------------------------------
    def put(self, key: Hashable, value: Any, nbytes: int) -> Any:
        """Admit ``key`` (miss hook fires in ``nbytes``), evicting LRU
        entries over budget. Returns ``value`` so the miss path reads
        ``cache.put(k, build(), n)``. Re-putting an existing key replaces
        its entry in place (bytes re-accounted, recency refreshed)."""
        nbytes = int(nbytes)
        evicted: list[int] = []
        budget = max(int(self._budget_fn()), 0)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            # over-budget single entry: never admitted, never pinned
            if nbytes <= budget:
                self._entries[key] = (value, nbytes)
                self._bytes += nbytes
                while self._bytes > budget and self._entries:
                    k_old, (_, b_old) = self._entries.popitem(last=False)
                    self._bytes -= b_old
                    evicted.append(b_old)
        self._on_miss(nbytes)
        for b in evicted:
            self._on_evict(b)
        return value

    def drop(self, key: Hashable) -> None:
        """Remove one entry if present (no hooks — invalidation is not an
        eviction; refresh publishes replace stale shards through here)."""
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes}
