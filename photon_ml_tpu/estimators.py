"""GAME estimator: grid fit + model selection.

Reference parity: ``photon-api::ml.estimators.GameEstimator`` (SURVEY.md
§2.2, §3.1): ``fit(data, validationData, configurations)`` returns one
``(GameModel, Option[EvaluationResults], configuration)`` per optimization
configuration in the grid; the driver selects the best by the primary
validation evaluator.

TPU-first notes:
- All ingest-time work that does not depend on the optimization
  configuration — data validation, per-shard normalization statistics,
  entity grouping/bucketing (the reference's shuffle) — happens ONCE per
  ``fit`` and is shared across the whole grid.
- Each grid entry re-enters the same compiled device programs (the
  geometry — shapes, bucket capacities, mesh — is identical across the
  grid; only λ and co. change, and those are traced scalars).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, Mapping, Sequence

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from photon_ml_tpu.config import (
    GameTrainingConfig,
    OptimizationConfig,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.data.validation import validate_game_batch
from photon_ml_tpu.data.summary import summarize
from photon_ml_tpu.evaluation import EvaluationResults, evaluate_all, make_evaluator
from photon_ml_tpu.game.coordinate import (
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.data import (
    EntityBuckets,
    EntityGrouping,
    GameBatch,
    bucket_entities,
    group_by_entity,
)
from photon_ml_tpu.game.descent import CoordinateDescent, CoordinateDescentResult
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.sampling import down_sample
from photon_ml_tpu.types import NormalizationType, TaskType

Array = jnp.ndarray

# One grid entry: per-coordinate optimization configurations.
GameOptimizationConfiguration = Mapping[str, OptimizationConfig]


_DEFAULT_EVALUATORS = {
    TaskType.LOGISTIC_REGRESSION: ("AUC",),
    TaskType.LINEAR_REGRESSION: ("RMSE",),
    TaskType.POISSON_REGRESSION: ("POISSON_LOSS",),
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: ("AUC",),
}


@dataclass(frozen=True)
class GameResult:
    """One grid entry's outcome (parity: the reference's ``GameResult``
    triple (model, evaluations, configuration))."""

    model: GameModel
    evaluation: EvaluationResults | None
    configuration: dict[str, OptimizationConfig]
    descent: CoordinateDescentResult


def build_configuration_grid(
    config: GameTrainingConfig,
) -> list[dict[str, OptimizationConfig]]:
    """Cross-product of per-coordinate regularization-weight lists
    (``config.regularization_weight_grid``); coordinates without a list keep
    their single configured weight. Parity: the reference's grid over
    ``GameOptimizationConfiguration``s."""
    import dataclasses
    import itertools

    cids = list(config.coordinate_update_sequence)
    unknown = set(config.regularization_weight_grid) - set(cids)
    if unknown:
        raise ValueError(
            f"regularization_weight_grid names unknown coordinate(s) {sorted(unknown)}; "
            f"update sequence is {cids}"
        )
    axes: list[list[OptimizationConfig]] = []
    for cid in cids:
        base = config.coordinate_config(cid).optimization
        weights = config.regularization_weight_grid.get(cid)
        if weights:
            axes.append(
                [dataclasses.replace(base, regularization_weight=float(w)) for w in weights]
            )
        else:
            axes.append([base])
    return [dict(zip(cids, combo)) for combo in itertools.product(*axes)]


# GameTrainingConfig fields that do NOT change the optimization trajectory:
# excluded from the checkpoint fingerprint so benign reruns (extending the
# iteration count — the canonical resume-and-extend workflow — changing
# evaluators, output mode, …) still resume instead of retraining from zero.
_NON_TRAJECTORY_CONFIG_FIELDS = (
    "coordinate_descent_iterations",
    "evaluators",
    "output_mode",
    "hyperparameter_tuning_iters",
    "model_input_dir",  # the warm-start model itself is hashed by value
)


def _fingerprint_base(
    config: GameTrainingConfig,
    batch: GameBatch,
    seed: int,
    initial_model: GameModel | None,
) -> dict:
    """The grid-invariant part of the checkpoint-resume fingerprint: the
    trajectory-affecting ``GameTrainingConfig`` fields, the estimator seed,
    a value hash of the warm-start model, and a cheap data signature.
    Computed once per ``fit``; each grid entry folds in only its own
    per-coordinate optimization configs. A checkpoint written under any
    different setup must not be silently resumed."""
    import hashlib

    warm = None
    if initial_model is not None:
        warm = {
            cid: hashlib.sha256(
                np.ascontiguousarray(np.asarray(sub.coefficient_means)).tobytes()
            ).hexdigest()
            for cid, sub in sorted(initial_model.models.items())
        }
    # Cheap value digest of the data: catches regenerated/changed datasets
    # that happen to keep the same geometry.
    from photon_ml_tpu.checkpoint import batch_digest

    data_digest = batch_digest(batch.labels, batch.weights)
    cfg_dict = config.to_dict()
    for key in _NON_TRAJECTORY_CONFIG_FIELDS:
        cfg_dict.pop(key, None)
    return {
        "training_config": cfg_dict,
        "seed": seed,
        "initial_model": warm,
        "data": {
            "num_rows": batch.num_rows,
            "digest": data_digest,
            "shards": {
                sid: feats.num_features for sid, feats in sorted(batch.features.items())
            },
        },
    }


def _fit_fingerprint(
    base: dict, configuration: GameOptimizationConfiguration
) -> str:
    import hashlib

    payload = dict(
        base,
        configuration={cid: oc.to_dict() for cid, oc in configuration.items()},
    )
    blob = json.dumps(payload, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()


class GameEstimator:
    """Fits GAME models over a grid of optimization configurations.

    ``intercept_indices`` maps feature-shard id → intercept column (or
    None); shards absent from the mapping are treated as intercept-free.
    """

    def __init__(
        self,
        config: GameTrainingConfig,
        mesh: Mesh | None = None,
        intercept_indices: Mapping[str, int | None] | None = None,
        logger: Callable[[str], None] | None = None,
        seed: int = 0,
    ):
        self.config = config
        self.mesh = mesh
        self.intercept_indices = dict(intercept_indices or {})
        self._log = logger or (lambda msg: None)
        self.seed = seed

    # -- ingest-time preparation (config-grid independent) ------------------

    def _normalization_contexts(self, batch: GameBatch) -> dict[str, NormalizationContext]:
        """Per-shard normalization from feature summaries (reference:
        ``BasicStatisticalSummary`` → ``NormalizationContext`` per shard) —
        for EVERY shard in the update sequence, random-effect shards
        included (their per-entity solves apply the shard's context inside
        the objective, like the fixed effect's)."""
        if self.config.normalization is NormalizationType.NONE:
            return {}
        contexts: dict[str, NormalizationContext] = {}
        shard_ids = {
            c.feature_shard_id for c in self.config.fixed_effect_coordinates.values()
        } | {
            c.feature_shard_id
            for c in self.config.random_effect_coordinates.values()
        }
        from photon_ml_tpu.data.summary import shard_normalization_context

        for sid in shard_ids:
            contexts[sid] = shard_normalization_context(
                summarize(batch.batch_for(sid)),
                self.config.normalization,
                sid,
                self.intercept_indices.get(sid),
                log=self._log,
            )
        return contexts

    def _entity_layouts(
        self, batch: GameBatch
    ) -> dict[str, tuple[EntityGrouping, EntityBuckets, int]]:
        """Group + bucket each random-effect coordinate's entities (the
        ingest-time replacement for the reference's group-by-entity shuffle)."""
        layouts: dict[str, tuple[EntityGrouping, EntityBuckets, int]] = {}
        for cid, cfg in self.config.random_effect_coordinates.items():
            ids = np.asarray(batch.id_tags[cfg.random_effect_type])
            num_entities = int(ids.max()) + 1 if len(ids) else 0
            grouping = group_by_entity(
                ids,
                num_entities=num_entities,
                active_upper_bound=cfg.active_data_upper_bound,
                seed=self.seed,
            )
            buckets = bucket_entities(
                grouping,
                cfg.sample_bucket_sizes,
                target_buckets=cfg.bucket_target_count,
                max_padded_ratio=cfg.bucket_max_padded_ratio,
            )
            layouts[cid] = (grouping, buckets, num_entities)
        return layouts

    def _build_coordinates(
        self,
        batch: GameBatch,
        configuration: GameOptimizationConfiguration,
        norm_contexts: Mapping[str, NormalizationContext],
        entity_layouts: Mapping[str, tuple[EntityGrouping, EntityBuckets, int]],
        re_coordinate_cache: dict[str, RandomEffectCoordinate] | None = None,
        prior_model: "GameModel | None" = None,
    ) -> dict[str, Coordinate]:
        """``re_coordinate_cache`` (when given) shares each random-effect
        coordinate's prepared bucket tensors across grid entries — only the
        optimization config is swapped per entry, so the staged device
        buffers are gathered once per ``fit``, not once per grid entry."""
        coordinates: dict[str, Coordinate] = {}
        task = self.config.task_type
        for cid in self.config.coordinate_update_sequence:
            opt = configuration[cid]
            coord_cfg = self.config.coordinate_config(cid)
            if isinstance(coord_cfg, RandomEffectCoordinateConfig):
                if re_coordinate_cache is not None and cid in re_coordinate_cache:
                    coordinates[cid] = re_coordinate_cache[cid].with_config(opt)
                    continue
                grouping, buckets, num_entities = entity_layouts[cid]
                projector = None
                if coord_cfg.random_projection_dim is not None:
                    from photon_ml_tpu.game.projector import RandomProjector

                    projector = RandomProjector.build(
                        batch.features[coord_cfg.feature_shard_id].num_features,
                        coord_cfg.random_projection_dim,
                        seed=self.seed,
                    )
                coord = RandomEffectCoordinate(
                    coordinate_id=cid,
                    batch=batch,
                    feature_shard_id=coord_cfg.feature_shard_id,
                    random_effect_type=coord_cfg.random_effect_type,
                    config=opt,
                    grouping=grouping,
                    buckets=buckets,
                    task_type=task,
                    num_entities=num_entities,
                    intercept_index=self.intercept_indices.get(coord_cfg.feature_shard_id),
                    normalization=norm_contexts.get(coord_cfg.feature_shard_id),
                    variance_computation=self.config.variance_computation,
                    mesh=self.mesh,
                    features_to_samples_ratio=coord_cfg.features_to_samples_ratio_upper_bound,
                    projector=projector,
                    prior_model=(
                        None if prior_model is None else prior_model.models.get(cid)
                    ),
                )
                if re_coordinate_cache is not None:
                    re_coordinate_cache[cid] = coord
                coordinates[cid] = coord
            else:
                train_rows = None
                weight_scale = None
                if opt.down_sampling_rate < 1.0:
                    rows, scale = down_sample(
                        task,
                        np.asarray(batch.labels),
                        opt.down_sampling_rate,
                        seed=self.seed,
                    )
                    train_rows = jnp.asarray(rows, jnp.int32)
                    weight_scale = None if scale is None else jnp.asarray(scale)
                coordinates[cid] = FixedEffectCoordinate(
                    coordinate_id=cid,
                    batch=batch,
                    feature_shard_id=coord_cfg.feature_shard_id,
                    config=opt,
                    task_type=task,
                    intercept_index=self.intercept_indices.get(coord_cfg.feature_shard_id),
                    normalization=norm_contexts.get(coord_cfg.feature_shard_id),
                    variance_computation=self.config.variance_computation,
                    mesh=self.mesh,
                    train_rows=train_rows,
                    train_weight_scale=weight_scale,
                    prior_model=(
                        None if prior_model is None else prior_model.models.get(cid)
                    ),
                )
        return coordinates

    # -- fit ----------------------------------------------------------------

    def _evaluator_specs(self) -> tuple[str, ...]:
        return tuple(self.config.evaluators) or _DEFAULT_EVALUATORS[self.config.task_type]

    def fit(
        self,
        batch: GameBatch,
        validation_batch: GameBatch | None = None,
        configurations: Sequence[GameOptimizationConfiguration] | None = None,
        initial_model: GameModel | None = None,
        checkpoint_dir: str | None = None,
    ) -> list[GameResult]:
        """Train one GAME model per grid configuration.

        ``configurations`` defaults to ``build_configuration_grid(self.config)``
        — the cross-product of ``regularization_weight_grid`` (a single
        configuration when no weight lists are set). ``initial_model``
        warm-starts every grid entry (reference: ``modelInputDirectory``).
        """
        cfg = self.config
        validate_game_batch(batch, cfg.task_type, cfg.data_validation, self.seed)
        if validation_batch is not None:
            validate_game_batch(
                validation_batch, cfg.task_type, cfg.data_validation, self.seed
            )

        if configurations is None:
            configurations = build_configuration_grid(cfg)

        norm_contexts = self._normalization_contexts(batch)
        entity_layouts = self._entity_layouts(batch)
        specs = self._evaluator_specs()
        fingerprint_base = (
            None
            if checkpoint_dir is None
            else _fingerprint_base(cfg, batch, self.seed, initial_model)
        )

        results: list[GameResult] = []
        re_coordinate_cache: dict[str, RandomEffectCoordinate] = {}
        for i, configuration in enumerate(configurations):
            self._log(f"grid entry {i + 1}/{len(configurations)}: {configuration}")
            coordinates = self._build_coordinates(
                batch, configuration, norm_contexts, entity_layouts,
                re_coordinate_cache=re_coordinate_cache,
                prior_model=initial_model if cfg.incremental else None,
            )
            descent = CoordinateDescent(
                coordinates,
                batch,
                cfg.task_type,
                validation_batch=validation_batch,
                evaluators=specs if validation_batch is not None else (),
                logger=self._log,
                mesh=self.mesh,
            )
            cd_result = descent.run(
                cfg.coordinate_update_sequence,
                cfg.coordinate_descent_iterations,
                initial_model=initial_model,
                checkpoint_dir=(
                    None
                    if checkpoint_dir is None
                    else f"{checkpoint_dir}/config-{i:04d}"
                ),
                checkpoint_fingerprint=(
                    None
                    if fingerprint_base is None
                    else _fit_fingerprint(fingerprint_base, configuration)
                ),
            )
            evaluation = None
            if validation_batch is not None:
                scores = cd_result.model.score(validation_batch)
                evaluation = evaluate_all(
                    specs,
                    scores,
                    validation_batch.labels,
                    validation_batch.weights,
                    group_ids=validation_batch.host_id_tags(),
                    mesh=self.mesh,
                )
                self._log(f"grid entry {i + 1}: validation {evaluation}")
            results.append(
                GameResult(
                    model=cd_result.model,
                    evaluation=evaluation,
                    configuration=dict(configuration),
                    descent=cd_result,
                )
            )
        return results

    def select_best(self, results: Sequence[GameResult]) -> GameResult:
        """Pick the grid entry with the best primary validation metric
        (parity: the driver's model selection). Falls back to the first
        result when nothing was evaluated."""
        specs = self._evaluator_specs()
        primary = make_evaluator(specs[0])
        best = None
        for r in results:
            if r.evaluation is None:
                continue
            if best is None or primary.better(r.evaluation.primary, best.evaluation.primary):
                best = r
        return best if best is not None else results[0]
