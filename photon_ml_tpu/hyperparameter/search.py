"""Hyperparameter search strategies.

Reference parity: ``photon-lib::ml.hyperparameter.{GaussianProcessSearch,
RandomSearch}`` and the driver's tuning loop (SURVEY.md §3.4): seed with the
grid observations, then repeatedly (fit GP → argmax EI over a Sobol
candidate pool → full retrain → observe).

API: ``observe(x, y)`` feeds results; ``suggest()`` proposes the next point
in the original (possibly log-scaled) coordinate space. Internally
everything lives in the unit cube and is MINIMIZED (larger-is-better
metrics are negated by the caller — see ``tune`` in drivers).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from photon_ml_tpu.hyperparameter.criteria import expected_improvement
from photon_ml_tpu.hyperparameter.gp import GaussianProcessEstimator
from photon_ml_tpu.hyperparameter.sobol import sobol_sequence


@dataclass(frozen=True)
class SearchRange:
    """One dimension's range. ``log_scale`` searches in log space (the right
    space for regularization weights — the reference tunes log-λ too)."""

    lo: float
    hi: float
    log_scale: bool = False

    def to_unit(self, v: np.ndarray) -> np.ndarray:
        if self.log_scale:
            return (np.log(v) - np.log(self.lo)) / (np.log(self.hi) - np.log(self.lo))
        return (v - self.lo) / (self.hi - self.lo)

    def from_unit(self, u: np.ndarray) -> np.ndarray:
        if self.log_scale:
            return np.exp(np.log(self.lo) + u * (np.log(self.hi) - np.log(self.lo)))
        return self.lo + u * (self.hi - self.lo)


class _SearchBase:
    def __init__(self, ranges: Sequence[SearchRange], seed: int = 0):
        if not ranges:
            raise ValueError("search needs at least one dimension")
        self.ranges = list(ranges)
        self.seed = seed
        self._X: list[np.ndarray] = []  # unit-cube points
        self._y: list[float] = []  # minimized objective

    @property
    def num_dims(self) -> int:
        return len(self.ranges)

    def _to_unit(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        return np.array([r.to_unit(x[i]) for i, r in enumerate(self.ranges)])

    def _from_unit(self, u: np.ndarray) -> np.ndarray:
        return np.array([r.from_unit(u[i]) for i, r in enumerate(self.ranges)])

    def observe(self, x: np.ndarray, y: float) -> None:
        """Record an evaluated point (original space) and its objective
        (lower is better)."""
        self._X.append(np.clip(self._to_unit(x), 0.0, 1.0))
        self._y.append(float(y))

    @property
    def best(self) -> tuple[np.ndarray, float]:
        i = int(np.argmin(self._y))
        return self._from_unit(self._X[i]), self._y[i]


class RandomSearch(_SearchBase):
    """Quasi-random (Sobol) search — the reference's baseline strategy."""

    def __init__(self, ranges: Sequence[SearchRange], seed: int = 0):
        super().__init__(ranges, seed)
        self._draw = 0

    def suggest(self) -> np.ndarray:
        u = sobol_sequence(self._draw + 1, self.num_dims, seed=self.seed)[-1]
        self._draw += 1
        return self._from_unit(u)


class GaussianProcessSearch(_SearchBase):
    """GP + EI search (the reference's Bayesian strategy).

    The first ``num_init`` suggestions are Sobol seeds; afterwards each
    suggestion fits the GP to all observations and maximizes expected
    improvement over a fresh Sobol candidate pool.
    """

    def __init__(
        self,
        ranges: Sequence[SearchRange],
        seed: int = 0,
        num_init: int = 4,
        candidate_pool_size: int = 512,
        estimator: GaussianProcessEstimator | None = None,
    ):
        super().__init__(ranges, seed)
        self.num_init = num_init
        self.candidate_pool_size = candidate_pool_size
        self.estimator = estimator or GaussianProcessEstimator(seed=seed)
        self._draw = 0

    def suggest(self) -> np.ndarray:
        self._draw += 1
        if len(self._y) < self.num_init:
            u = sobol_sequence(self._draw, self.num_dims, seed=self.seed)[-1]
            return self._from_unit(u)
        model = self.estimator.fit(np.stack(self._X), np.asarray(self._y))
        pool = sobol_sequence(
            self.candidate_pool_size, self.num_dims, seed=self.seed + self._draw
        )
        mean, std = model.predict(pool)
        ei = expected_improvement(mean, std, best=float(np.min(self._y)))
        return self._from_unit(pool[int(np.argmax(ei))])
