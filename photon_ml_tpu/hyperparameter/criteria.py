"""Acquisition criteria.

Reference parity: ``photon-lib::ml.hyperparameter.criteria.
ExpectedImprovement`` — EI for MINIMIZATION (metrics are converted so lower
is better before the search sees them).
"""

from __future__ import annotations

import numpy as np
from scipy.stats import norm


def expected_improvement(
    mean: np.ndarray, std: np.ndarray, best: float, xi: float = 0.0
) -> np.ndarray:
    """EI(z) = (best − μ − ξ)·Φ(u) + σ·φ(u), u = (best − μ − ξ)/σ.

    Larger is better (more expected reduction below the incumbent).
    """
    std = np.maximum(std, 1e-12)
    imp = best - mean - xi
    u = imp / std
    return imp * norm.cdf(u) + std * norm.pdf(u)
