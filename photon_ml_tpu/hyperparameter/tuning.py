"""The driver's hyperparameter auto-tuning loop.

Reference parity: SURVEY.md §3.4 — after the grid fit, the driver seeds a
``GaussianProcessSearch`` with (config vector, validation metric)
observations and iterates: fit GP → argmax EI over Sobol candidates → full
distributed retrain → append observation.

The tuned vector is each coordinate's regularization weight (log scale),
matching the reference's tuning target.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from photon_ml_tpu.estimators import GameEstimator, GameResult
from photon_ml_tpu.evaluation import make_evaluator
from photon_ml_tpu.game.data import GameBatch
from photon_ml_tpu.hyperparameter.search import GaussianProcessSearch, SearchRange

# log-λ search box (the reference's tuner works on a comparable range)
_DEFAULT_RANGE = SearchRange(lo=1e-4, hi=1e4, log_scale=True)


def gp_tune_weights(
    cids: Sequence[str],
    prior: Sequence[tuple[dict, float]],
    num_iterations: int,
    evaluate,
    larger_is_better: bool,
    seed: int = 0,
) -> None:
    """The GP→EI→refit loop over per-coordinate regularization weights,
    decoupled from the data path: ``prior`` holds (weights-by-cid, primary
    metric) observations; ``evaluate(weights_by_cid, iteration) -> primary``
    performs one full refit. Shared by the in-memory estimator loop and
    the out-of-core streamed driver (same search, same range, same
    observation algebra)."""
    sign = -1.0 if larger_is_better else 1.0  # search minimizes
    search = GaussianProcessSearch(
        ranges=[_DEFAULT_RANGE] * len(cids), seed=seed, num_init=0
    )
    for weights, y in prior:
        x = np.array(
            [
                np.clip(weights[cid], _DEFAULT_RANGE.lo, _DEFAULT_RANGE.hi)
                for cid in cids
            ]
        )
        search.observe(x, sign * y)
    for it in range(num_iterations):
        x = search.suggest()
        y = evaluate({cid: float(x[i]) for i, cid in enumerate(cids)}, it)
        search.observe(x, sign * y)


def tune_game_hyperparameters(
    estimator: GameEstimator,
    batch: GameBatch,
    validation_batch: GameBatch,
    prior_results: Sequence[GameResult],
    num_iterations: int,
    seed: int = 0,
) -> list[GameResult]:
    """Run ``num_iterations`` Bayesian-tuning refits; returns the new
    results (caller appends them to the grid results for final selection)."""
    cfg = estimator.config
    cids = list(cfg.coordinate_update_sequence)
    specs = estimator._evaluator_specs()
    primary = make_evaluator(specs[0])

    prior = [
        (
            {
                cid: r.configuration[cid].regularization_weight
                for cid in cids
            },
            r.evaluation.primary,
        )
        for r in prior_results
        if r.evaluation is not None
    ]
    results: list[GameResult] = []

    def evaluate(weights: dict, _it: int) -> float:
        configuration = {
            cid: dataclasses.replace(
                cfg.coordinate_config(cid).optimization,
                regularization_weight=weights[cid],
            )
            for cid in cids
        }
        fit = estimator.fit(
            batch, validation_batch, configurations=[configuration]
        )[0]
        results.append(fit)
        return fit.evaluation.primary

    gp_tune_weights(
        cids, prior, num_iterations, evaluate, primary.larger_is_better,
        seed=seed,
    )
    return results
