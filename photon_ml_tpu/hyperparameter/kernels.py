"""Stationary GP covariance kernels.

Reference parity: ``photon-lib::ml.hyperparameter.estimators.kernels``
(Matern-5/2 — the reference's default for hyperparameter surfaces, after
Snoek et al.'s "Practical Bayesian Optimization" — and RBF), with amplitude,
per-dimension length scales (ARD), and observation noise.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import numpy as np

_SQRT5 = np.sqrt(5.0)


@dataclass(frozen=True)
class StationaryKernel:
    """amplitude² · k(r/lengthscale) + noise²·I (on the diagonal).

    ``lengthscales`` broadcasts: scalar or (d,) ARD.
    """

    amplitude: float = 1.0
    lengthscales: np.ndarray | float = 1.0
    noise: float = 1e-4

    def _scaled_sqdist(self, X: np.ndarray, Z: np.ndarray) -> np.ndarray:
        ls = np.asarray(self.lengthscales, np.float64)
        Xs, Zs = X / ls, Z / ls
        d2 = (
            np.sum(Xs * Xs, 1)[:, None]
            + np.sum(Zs * Zs, 1)[None, :]
            - 2.0 * Xs @ Zs.T
        )
        return np.maximum(d2, 0.0)

    def _base(self, r2: np.ndarray) -> np.ndarray:  # pragma: no cover (abstract)
        raise NotImplementedError

    def __call__(self, X: np.ndarray, Z: np.ndarray | None = None) -> np.ndarray:
        """Covariance matrix; noise is added only on the X==Z diagonal."""
        X = np.atleast_2d(np.asarray(X, np.float64))
        same = Z is None
        Z = X if same else np.atleast_2d(np.asarray(Z, np.float64))
        K = self.amplitude**2 * self._base(self._scaled_sqdist(X, Z))
        if same:
            K = K + (self.noise**2 + 1e-10) * np.eye(len(X))
        return K

    def with_params(self, log_params: np.ndarray) -> "StationaryKernel":
        """Rebuild from log-space parameter vector
        [log amplitude, log noise, log lengthscale...] — the slice sampler's
        coordinate space."""
        p = np.exp(np.asarray(log_params, np.float64))
        ls = p[2] if len(p) == 3 else p[2:]
        return replace(self, amplitude=p[0], noise=p[1], lengthscales=ls)

    def log_params(self, num_dims: int, ard: bool = True) -> np.ndarray:
        ls = np.broadcast_to(
            np.asarray(self.lengthscales, np.float64), (num_dims if ard else 1,)
        )
        return np.log(np.concatenate([[self.amplitude, self.noise], ls]))


@dataclass(frozen=True)
class RBF(StationaryKernel):
    """Squared-exponential: k(r²) = exp(-r²/2)."""

    def _base(self, r2: np.ndarray) -> np.ndarray:
        return np.exp(-0.5 * r2)


@dataclass(frozen=True)
class Matern52(StationaryKernel):
    """Matérn-5/2: (1 + √5 r + 5r²/3)·exp(-√5 r)."""

    def _base(self, r2: np.ndarray) -> np.ndarray:
        r = np.sqrt(r2)
        return (1.0 + _SQRT5 * r + (5.0 / 3.0) * r2) * np.exp(-_SQRT5 * r)
