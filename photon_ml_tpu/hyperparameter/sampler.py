"""Univariate slice sampling with stepping-out, applied coordinate-wise.

Reference parity: ``photon-lib::ml.hyperparameter.sampler.SliceSampler`` —
used to sample GP kernel hyperparameters from their (log) marginal-likelihood
posterior instead of point-optimizing them (Neal 2003; Snoek et al. 2012).
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def _slice_sample_1d(
    x0: np.ndarray,
    dim: int,
    log_density: Callable[[np.ndarray], float],
    rng: np.random.Generator,
    width: float,
    max_steps_out: int = 8,
) -> np.ndarray:
    """One slice-sampling update of coordinate ``dim``."""
    x0 = np.asarray(x0, np.float64)
    f0 = log_density(x0)
    log_y = f0 + np.log(rng.uniform(1e-12, 1.0))

    # step out
    u = rng.uniform()
    lo = x0[dim] - width * u
    hi = lo + width
    def density_at(v: float) -> float:
        x = x0.copy()
        x[dim] = v
        return log_density(x)
    for _ in range(max_steps_out):
        if density_at(lo) <= log_y:
            break
        lo -= width
    for _ in range(max_steps_out):
        if density_at(hi) <= log_y:
            break
        hi += width

    # shrink
    for _ in range(64):
        v = rng.uniform(lo, hi)
        if density_at(v) > log_y:
            x1 = x0.copy()
            x1[dim] = v
            return x1
        if v < x0[dim]:
            lo = v
        else:
            hi = v
    return x0  # shrunk to nothing — keep the current point


def slice_sample(
    x0: np.ndarray,
    log_density: Callable[[np.ndarray], float],
    num_samples: int,
    rng: np.random.Generator,
    width: float = 1.0,
    burn_in: int = 0,
    thin: int = 1,
) -> np.ndarray:
    """Draw ``num_samples`` points from ``exp(log_density)`` by cycling
    coordinate-wise slice updates. Returns (num_samples, d)."""
    x = np.asarray(x0, np.float64).copy()
    out = []
    total = burn_in + num_samples * thin
    for i in range(total):
        for dim in range(len(x)):
            x = _slice_sample_1d(x, dim, log_density, rng, width)
        if i >= burn_in and (i - burn_in) % thin == 0:
            out.append(x.copy())
    return np.stack(out[:num_samples])
