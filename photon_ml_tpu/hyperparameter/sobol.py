"""Sobol quasi-random sequences.

Reference parity: ``photon-lib::ml.hyperparameter.SobolSequence`` — used to
seed the search and to draw the candidate pool the acquisition function is
maximized over. Delegates to scipy's direction-number implementation
(scrambled Owen variant), which replaces the reference's hand-rolled tables.
"""

from __future__ import annotations

import numpy as np
from scipy.stats import qmc


def sobol_sequence(num_points: int, num_dims: int, seed: int = 0) -> np.ndarray:
    """``num_points`` scrambled-Sobol points in [0, 1)^num_dims.

    Sobol balance properties hold for power-of-2 sample counts, so the draw
    is padded up to the next power of two and truncated — the kept prefix
    is still a valid (scrambled) Sobol sequence, and scipy's balance
    warning never fires."""
    if num_points <= 0:
        return np.zeros((0, num_dims))
    sampler = qmc.Sobol(d=num_dims, scramble=True, seed=seed)
    pow2 = 1 << (num_points - 1).bit_length()
    return sampler.random(pow2)[:num_points]
