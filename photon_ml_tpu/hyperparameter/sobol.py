"""Sobol quasi-random sequences.

Reference parity: ``photon-lib::ml.hyperparameter.SobolSequence`` — used to
seed the search and to draw the candidate pool the acquisition function is
maximized over. Delegates to scipy's direction-number implementation
(scrambled Owen variant), which replaces the reference's hand-rolled tables.
"""

from __future__ import annotations

import numpy as np
from scipy.stats import qmc


def sobol_sequence(num_points: int, num_dims: int, seed: int = 0) -> np.ndarray:
    """``num_points`` scrambled-Sobol points in [0, 1)^num_dims."""
    sampler = qmc.Sobol(d=num_dims, scramble=True, seed=seed)
    return sampler.random(num_points)
