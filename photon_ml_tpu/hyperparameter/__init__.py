"""Bayesian hyperparameter auto-tuning.

Reference parity: ``photon-lib::ml.hyperparameter.*`` (SURVEY.md §2.1) —
``GaussianProcessSearch`` (GP surrogate + expected improvement),
``RandomSearch``, ``GaussianProcessEstimator``/``GaussianProcessModel``,
``criteria.ExpectedImprovement``, kernels (``Matern52``, ``RBF``),
``SobolSequence``, ``sampler.SliceSampler``.

Host-side numpy throughout: the search runs on the driver between full
distributed retrains (§3.4), so its cost is noise next to one refit — no
reason to jit it.
"""

from photon_ml_tpu.hyperparameter.kernels import Matern52, RBF, StationaryKernel  # noqa: F401
from photon_ml_tpu.hyperparameter.gp import (  # noqa: F401
    GaussianProcessEstimator,
    GaussianProcessModel,
)
from photon_ml_tpu.hyperparameter.criteria import expected_improvement  # noqa: F401
from photon_ml_tpu.hyperparameter.sobol import sobol_sequence  # noqa: F401
from photon_ml_tpu.hyperparameter.sampler import slice_sample  # noqa: F401
from photon_ml_tpu.hyperparameter.search import (  # noqa: F401
    GaussianProcessSearch,
    RandomSearch,
    SearchRange,
)
