"""Gaussian-process regression for hyperparameter surfaces.

Reference parity: ``photon-lib::ml.hyperparameter.estimators.
{GaussianProcessEstimator, GaussianProcessModel}`` — GP regression whose
kernel hyperparameters are *slice-sampled* from the marginal likelihood
(not point-optimized), with predictions averaged over the sampled kernels
(Snoek et al. 2012, the design the reference follows).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
from scipy.linalg import cho_factor, cho_solve

from photon_ml_tpu.hyperparameter.kernels import Matern52, StationaryKernel
from photon_ml_tpu.hyperparameter.sampler import slice_sample


@dataclass(frozen=True)
class GaussianProcessModel:
    """GP posterior over observed (X, y), marginalized over kernel samples.

    ``predict`` returns (mean, std) averaged over the kernel posterior:
    mean = E[mean_k], var = E[var_k + mean_k²] − mean² (law of total
    variance — matching the reference's prediction averaging).
    """

    X: np.ndarray  # (n, d)
    y: np.ndarray  # (n,) — centered internally
    kernels: tuple[StationaryKernel, ...]
    y_mean: float

    def predict(self, Z: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        Z = np.atleast_2d(np.asarray(Z, np.float64))
        means, variances = [], []
        yc = self.y - self.y_mean
        for k in self.kernels:
            K = k(self.X)
            factor = cho_factor(K, lower=True)
            alpha = cho_solve(factor, yc)
            Kzx = k(Z, self.X)
            mu = Kzx @ alpha
            v = cho_solve(factor, Kzx.T)
            var = np.maximum(
                np.diag(k(Z, Z)) + k.noise**2 - np.sum(Kzx * v.T, axis=1), 1e-12
            )
            means.append(mu + self.y_mean)
            variances.append(var)
        M = np.stack(means)
        V = np.stack(variances)
        mean = M.mean(0)
        var = (V + M * M).mean(0) - mean * mean
        return mean, np.sqrt(np.maximum(var, 1e-12))


def _log_marginal_likelihood(
    X: np.ndarray, yc: np.ndarray, kernel: StationaryKernel
) -> float:
    try:
        K = kernel(X)
        factor = cho_factor(K, lower=True)
    except np.linalg.LinAlgError:
        return -np.inf
    alpha = cho_solve(factor, yc)
    logdet = 2.0 * np.sum(np.log(np.diag(factor[0])))
    return float(-0.5 * yc @ alpha - 0.5 * logdet - 0.5 * len(yc) * np.log(2 * np.pi))


@dataclass(frozen=True)
class GaussianProcessEstimator:
    """Fits a ``GaussianProcessModel`` by slice-sampling kernel
    hyperparameters (amplitude, noise, per-dim lengthscales) from the
    marginal likelihood with a weak log-normal prior."""

    kernel: StationaryKernel = Matern52()
    num_kernel_samples: int = 8
    burn_in: int = 16
    seed: int = 0

    def fit(self, X: np.ndarray, y: np.ndarray) -> GaussianProcessModel:
        X = np.atleast_2d(np.asarray(X, np.float64))
        y = np.asarray(y, np.float64)
        y_mean = float(y.mean())
        yc = y - y_mean
        rng = np.random.default_rng(self.seed)

        def log_density(log_params: np.ndarray) -> float:
            # weak log-normal prior keeps amplitude/noise/lengthscales sane
            prior = -0.5 * np.sum((log_params / 3.0) ** 2)
            return _log_marginal_likelihood(X, yc, self.kernel.with_params(log_params)) + prior

        x0 = self.kernel.log_params(X.shape[1])
        samples = slice_sample(
            x0, log_density, self.num_kernel_samples, rng, width=1.0, burn_in=self.burn_in
        )
        kernels = tuple(self.kernel.with_params(s) for s in samples)
        return GaussianProcessModel(X=X, y=y, kernels=kernels, y_mean=y_mean)
