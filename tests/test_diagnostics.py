"""Model diagnostics reports (JSON + self-contained HTML).

Reference parity: the reference's historical model-diagnostics subsystem
(HTML reports off training artifacts) — SURVEY.md checklist item 7."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.types import TaskType, VarianceComputationType


def _write_libsvm(path, rng, n, w):
    lines = []
    for _ in range(n):
        x = rng.normal(size=w.shape[0])
        y = 1 if rng.uniform() < 1 / (1 + np.exp(-x @ w)) else -1
        feats = " ".join(f"{j + 1}:{x[j]:.5f}" for j in range(w.shape[0]))
        lines.append(f"{y} {feats}")
    with open(path, "w") as f:
        f.write("\n".join(lines))


def test_coefficient_summary_resolves_names():
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.diagnostics import coefficient_summary

    imap = IndexMap.build(["age\x01", "income\x01log", "clicks\x01"])
    means = np.array([0.5, -2.0, 0.0])
    c = coefficient_summary(means, variances=np.array([0.1, 0.2, 0.3]), index_map=imap)
    assert c["num_features"] == 3
    assert c["num_nonzero"] == 2
    # top feature is the largest |weight| and carries its resolved name
    top = c["top_features"][0]
    assert abs(top["weight"]) == 2.0 and isinstance(top["feature"], str)
    assert len(c["top_features"]) == 2  # zeros excluded
    assert c["has_variances"]


def test_glm_driver_writes_diagnostics(tmp_path, rng):
    from photon_ml_tpu.cli import train_glm

    path = str(tmp_path / "train.libsvm")
    _write_libsvm(path, rng, 300, np.array([1.0, -2.0, 0.5]))
    out = str(tmp_path / "out")
    train_glm.run(
        TaskType.LOGISTIC_REGRESSION,
        [path],
        out,
        validation_data=[path],
        weights=[0.1, 1.0],
        variance_computation=VarianceComputationType.SIMPLE,
        diagnostics=True,
    )
    with open(os.path.join(out, "diagnostics.json")) as f:
        report = json.load(f)
    assert report["kind"] == "glm_sweep"
    assert report["best_regularization_weight"] in (0.1, 1.0)
    assert len(report["entries"]) == 2
    e = report["entries"][0]
    assert e["optimizer"]["iterations"] >= 1
    assert e["optimizer"]["loss_history"][0] >= e["optimizer"]["loss_history"][-1]
    assert e["validation"]["AUC"] > 0.6
    assert e["coefficients"]["top_features"], "expected resolved top features"
    html_text = open(os.path.join(out, "diagnostics.html")).read()
    assert "<svg" in html_text and "top features" in html_text
    assert str(report["best_regularization_weight"]) in html_text


def test_game_diagnostics_report(rng):
    from photon_ml_tpu.config import (
        FixedEffectCoordinateConfig,
        GameTrainingConfig,
        OptimizationConfig,
        RandomEffectCoordinateConfig,
    )
    from photon_ml_tpu.config import OptimizerConfig
    from photon_ml_tpu.diagnostics import game_diagnostics, write_html
    from photon_ml_tpu.estimators import GameEstimator
    from photon_ml_tpu.game import make_game_batch

    n, d, E, dr = 200, 4, 6, 2
    X = rng.normal(size=(n, d)).astype(np.float32)
    Xr = rng.normal(size=(n, dr)).astype(np.float32)
    ids = rng.integers(0, E, size=n).astype(np.int32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    batch = make_game_batch(y, {"g": X, "r": Xr}, id_tags={"uid": ids})
    cfg = GameTrainingConfig(
        task_type=TaskType.LOGISTIC_REGRESSION,
        coordinate_update_sequence=("fixed", "user"),
        coordinate_descent_iterations=1,
        fixed_effect_coordinates={
            "fixed": FixedEffectCoordinateConfig(
                feature_shard_id="g",
                optimization=OptimizationConfig(
                    optimizer=OptimizerConfig(max_iterations=20)
                ),
            )
        },
        random_effect_coordinates={
            "user": RandomEffectCoordinateConfig(
                feature_shard_id="r",
                random_effect_type="uid",
                optimization=OptimizationConfig(
                    optimizer=OptimizerConfig(max_iterations=20)
                ),
            )
        },
    )
    results = GameEstimator(cfg).fit(batch)
    report = game_diagnostics(results, config=cfg)
    assert report["kind"] == "game" and len(report["grid"]) == 1
    coords = report["grid"][0]["coordinates"]
    assert coords["fixed"]["type"] == "fixed_effect"
    assert coords["user"]["type"] == "random_effect"
    assert coords["user"]["num_entities"] == E
    assert coords["fixed"]["per_iteration"], "fixed coordinate tracker missing"
    json.dumps(report)  # must be JSON-serializable

    out = os.path.join(os.path.dirname(__file__), "..", ".tmp_diag.html")
    try:
        write_html(report, out)
        assert "coordinate" in open(out).read()
    finally:
        if os.path.exists(out):
            os.remove(out)
