"""Host-side tests for the owner-segment combine + telemetry-driven
re-planning (ISSUE 12): the raw-ndarray host-collective codec, the ring
allgather schedule over real sockets, owner-segment packing/offsets and
disjoint-row reassembly (empty owner / single bucket / V=None edges),
the PHOTON_RE_COMBINE / PHOTON_RE_REPLAN_IMBALANCE / PHOTON_RE_STRAGGLER
knob parses, measured-cost re-planning, and the report/gate surface for
``re_combine.*`` / ``re_replan.*``. The cross-process bitwise/byte
assertions live in the slow gloo harness (tests/test_multihost.py)."""

from __future__ import annotations

import json
import socket
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.parallel import multihost as mh

from collections import namedtuple

_Pt = namedtuple("_Pt", "a b")  # module-level: pickles by reference


class TestHostPayloadCodec:
    """Raw-ndarray wire format: byte-identical values, no pickle per
    array, writable results (the pickle contract)."""

    def roundtrip(self, obj):
        parts, total = mh._encode_host_payload(obj)
        raw = b"".join(bytes(p) for p in parts)
        assert len(raw) == total
        return mh._decode_host_payload(raw)

    def test_array_container_roundtrip_bitwise(self):
        obj = {
            "f32": np.arange(12, dtype=np.float32).reshape(3, 4),
            "f64": np.linspace(0, 1, 7),
            "i64": np.array([-(2**62), 2**62], np.int64),
            "bool": np.array([True, False]),
            "nested": [
                (np.float32(1.5), np.zeros((2, 0, 3), np.float32)),
                {"k": np.arange(5, dtype=np.int32)},
            ],
            "scalar": 7,
            "s": "text",
        }
        back = self.roundtrip(obj)
        np.testing.assert_array_equal(back["f32"], obj["f32"])
        assert back["f32"].dtype == np.float32
        np.testing.assert_array_equal(back["f64"], obj["f64"])
        np.testing.assert_array_equal(back["i64"], obj["i64"])
        np.testing.assert_array_equal(back["bool"], obj["bool"])
        assert back["nested"][0][1].shape == (2, 0, 3)
        np.testing.assert_array_equal(
            back["nested"][1]["k"], obj["nested"][1]["k"]
        )
        assert back["scalar"] == 7 and back["s"] == "text"

    def test_arrays_come_back_writable(self):
        back = self.roundtrip([np.arange(4, dtype=np.float32)])
        assert back[0].flags.writeable
        back[0][0] = 9.0  # the pickle format allowed in-place writes

    def test_non_contiguous_input(self):
        a = np.arange(20, dtype=np.float32).reshape(4, 5)[:, ::2]
        back = self.roundtrip({"a": a})
        np.testing.assert_array_equal(back["a"], a)

    def test_zero_dim_array_keeps_shape(self):
        # ascontiguousarray promotes 0-d to 1-d; the spec must record
        # the ORIGINAL shape so peers see () like the sender's own rank
        back = self.roundtrip({"x": np.array(3.5), "y": np.arange(2)})
        assert back["x"].shape == ()
        assert float(back["x"]) == 3.5

    def test_namedtuple_survives_array_format(self):
        back = self.roundtrip({"p": _Pt(a=np.arange(2), b=1)})
        assert back["p"].a.tolist() == [0, 1] and back["p"].b == 1
        assert isinstance(back["p"], _Pt)

    def test_structured_dtype_and_subclass_keep_pickle_path(self):
        # structured dtypes (dtype.str is lossy) and ndarray subclasses
        # (MaskedArray carries a mask) must round-trip via pickle even
        # when a plain array rides the raw format alongside them
        rec = np.zeros(3, dtype=[("a", "i4"), ("b", "f8")])
        rec["a"] = [1, 2, 3]
        masked = np.ma.masked_array([1.0, 2.0], mask=[False, True])
        back = self.roundtrip(
            {"rec": rec, "m": masked, "plain": np.arange(4)}
        )
        assert back["rec"].dtype.names == ("a", "b")
        np.testing.assert_array_equal(back["rec"]["a"], [1, 2, 3])
        assert isinstance(back["m"], np.ma.MaskedArray)
        assert back["m"].mask.tolist() == [False, True]
        np.testing.assert_array_equal(back["plain"], np.arange(4))

    def test_no_array_payload_falls_back_to_pickle(self):
        parts, _ = mh._encode_host_payload({"x": 1, "y": ("z", None)})
        assert bytes(parts[0])[0] == mh._PAYLOAD_PICKLE
        assert self.roundtrip({"x": 1}) == {"x": 1}

    def test_object_dtype_array_falls_back_to_pickle(self):
        oarr = np.array([{"k": 1}, None], dtype=object)
        parts, _ = mh._encode_host_payload([oarr])
        assert bytes(parts[0])[0] == mh._PAYLOAD_PICKLE
        back = self.roundtrip([oarr])
        assert back[0][0] == {"k": 1} and back[0][1] is None

    def test_array_payload_uses_raw_format(self):
        parts, _ = mh._encode_host_payload(np.arange(3))
        assert bytes(parts[0])[0] == mh._PAYLOAD_NDARRAY

    def test_unknown_wire_format_raises(self):
        with pytest.raises(RuntimeError, match="unknown wire format"):
            mh._decode_host_payload(b"\x7fjunk")


def _pair_links():
    """Two in-process 'ranks' wired with real sockets: links dicts in
    the exact shape ``_ring_allgather`` consumes."""
    a01, b01 = socket.socketpair()  # 0 -> 1
    a10, b10 = socket.socketpair()  # 1 -> 0
    links0 = {"send": {1: a01}, "recv": {1: b10}, "proto": {}}
    links1 = {"send": {0: a10}, "recv": {0: b01}, "proto": {}}
    return links0, links1, (a01, b01, a10, b10)


class TestRingAllgather:
    """The ring schedule over real sockets (single process, two
    threads): per-rank ordering, array payloads, byte stats."""

    def test_two_rank_ring_and_stats(self):
        links0, links1, socks = _pair_links()
        obj0 = {"w": np.arange(6, dtype=np.float32), "who": 0}
        obj1 = {"w": np.arange(8, dtype=np.float64) * 2, "who": 1}
        out = {}
        stats0, stats1 = {}, {}

        def run1():
            out[1] = mh._ring_allgather(
                links1, [0, 1], 1, obj1, "t", None, stats=stats1
            )

        t = threading.Thread(target=run1)
        t.start()
        out[0] = mh._ring_allgather(
            links0, [0, 1], 0, obj0, "t", None, stats=stats0
        )
        t.join()
        for sock in socks:
            sock.close()
        for rank in (0, 1):
            views = out[rank]
            assert views[0]["who"] == 0 and views[1]["who"] == 1
            np.testing.assert_array_equal(views[0]["w"], obj0["w"])
            np.testing.assert_array_equal(views[1]["w"], obj1["w"])
            assert views[1]["w"].dtype == np.float64
        # stats: one peer -> bytes_sent == payload, recv == peer payload
        assert stats0["bytes_sent"] == stats0["payload_bytes"]
        assert stats0["bytes_recv"] == stats1["payload_bytes"]
        assert stats1["bytes_recv"] == stats0["payload_bytes"]

    def test_single_process_identity_paths(self):
        st = {}
        assert mh.allgather_obj_p2p("x", stats=st) == ["x"]
        assert st == {"payload_bytes": 0, "bytes_sent": 0, "bytes_recv": 0}
        st2 = {}
        h = mh.allgather_obj_p2p_async({"a": 1}, stats=st2)
        assert h.result() == [{"a": 1}]
        assert st2["exchange_s"] == 0.0


# -- owner-segment packing / reassembly --------------------------------------


def _fake_prepared(ent_lists, owners):
    from photon_ml_tpu.game.random_effect import PreparedBucket

    return [
        PreparedBucket(
            entity_ids=np.asarray(ents, np.int64), ids=None, static=None,
            row_idx=None, mask=None, num_real=len(ents), owner=owner,
        )
        for ents, owner in zip(ent_lists, owners)
    ]


def _simulate_combine(ent_lists, owners, P, d=3, with_v=True, seed=0):
    """Emulate the cross-process segment flow host-side: every rank
    packs from its own (partially-solved) matrices, then one rank
    applies all views — compared against the owner-truth reference."""
    from photon_ml_tpu.game import random_effect as re_mod

    rng = np.random.default_rng(seed)
    E = 1 + max((max(e) for e in ent_lists if len(e)), default=0)
    prepared = _fake_prepared(ent_lists, owners)
    # owner-truth: each bucket's rows/diag as solved by its owner
    truth_W = rng.normal(size=(E, d)).astype(np.float32)
    truth_V = rng.normal(size=(E, d)).astype(np.float32) if with_v else None
    truth_diag = [
        (
            rng.normal(size=len(e)).astype(np.float32),
            rng.integers(1, 9, size=len(e)).astype(np.int32),
            rng.integers(0, 3, size=len(e)).astype(np.int32),
        )
        for e in ent_lists
    ]
    wv_views, diag_views = [], []
    per_rank_state = {}
    for rank in range(P):
        owned = [i for i, o in enumerate(owners) if o == rank]
        # this rank's local matrices: correct only on its owned rows
        W_h = np.zeros((E, d), np.float32)
        V_h = np.zeros((E, d), np.float32) if with_v else None
        for i in owned:
            W_h[ent_lists[i]] = truth_W[ent_lists[i]]
            if V_h is not None:
                V_h[ent_lists[i]] = truth_V[ent_lists[i]]
        wv_views.append(
            re_mod._pack_wv_segments(prepared, W_h, V_h, owned)
        )
        diag_views.append(
            re_mod._pack_diag_segments([truth_diag[i] for i in owned])
        )
        per_rank_state[rank] = (W_h, V_h)
    # round-trip every view through the wire codec (what the ring does)
    def wire(v):
        parts, total = mh._encode_host_payload(v)
        return mh._decode_host_payload(b"".join(bytes(p) for p in parts))

    wv_views = [wire(v) for v in wv_views]
    diag_views = [wire(v) for v in diag_views]
    results = {}
    for rank in range(P):
        W_h, V_h = per_rank_state[rank]
        diag = [
            truth_diag[i] if owners[i] == rank else None
            for i in range(len(ent_lists))
        ]
        diag = re_mod._apply_owner_segments(
            prepared, W_h, V_h, diag, wv_views, diag_views, rank
        )
        results[rank] = (W_h, V_h, diag)
    return truth_W, truth_V, truth_diag, results


class TestOwnerSegments:
    def test_disjoint_reassembly_three_ranks(self):
        ents = [[0, 3], [1, 4, 6], [2], [5, 7]]
        owners = [0, 1, 1, 2]
        tw, tv, td, results = _simulate_combine(ents, owners, P=3)
        for rank, (W_h, V_h, diag) in results.items():
            np.testing.assert_array_equal(W_h, tw)
            np.testing.assert_array_equal(V_h, tv)
            for i, e in enumerate(ents):
                f, it, r = diag[i]
                np.testing.assert_array_equal(
                    np.asarray(f, np.float32), td[i][0]
                )
                np.testing.assert_array_equal(np.asarray(it), td[i][1])
                np.testing.assert_array_equal(np.asarray(r), td[i][2])
                if owners[i] != rank:
                    # non-owned diag arrives as the allreduce arm's
                    # dtypes exactly (f32 / i32 / i32 device arrays)
                    assert f.dtype == jnp.float32
                    assert it.dtype == jnp.int32 and r.dtype == jnp.int32

    def test_empty_owner_edge(self):
        # rank 1 owns nothing: ships empty segments, receives everything
        ents = [[0, 1], [2, 3]]
        owners = [0, 0]
        tw, tv, _, results = _simulate_combine(ents, owners, P=2)
        W_h, V_h, _ = results[1]
        np.testing.assert_array_equal(W_h, tw)
        np.testing.assert_array_equal(V_h, tv)

    def test_single_bucket_and_v_none(self):
        ents = [[0, 1, 2]]
        owners = [1]
        tw, tv, _, results = _simulate_combine(
            ents, owners, P=2, with_v=False
        )
        assert tv is None
        W_h, V_h, diag = results[0]
        assert V_h is None
        np.testing.assert_array_equal(W_h, tw)
        assert diag[0][0].dtype == jnp.float32

    def test_duplicate_owner_detected(self):
        from photon_ml_tpu.game import random_effect as re_mod

        prepared = _fake_prepared([[0], [1]], [0, 1])
        W_h = np.zeros((2, 2), np.float32)
        wv = [
            {"buckets": np.array([0, 1]),
             "W": np.zeros((2, 2), np.float32)},
            {"buckets": np.array([1]),
             "W": np.zeros((1, 2), np.float32)},
        ]
        dg = [
            {"F": np.zeros(2), "I": np.zeros(2, np.int64),
             "R": np.zeros(2, np.int64)},
            {"F": np.zeros(1), "I": np.zeros(1, np.int64),
             "R": np.zeros(1, np.int64)},
        ]
        with pytest.raises(RuntimeError, match="two owners"):
            re_mod._apply_owner_segments(
                prepared, W_h, None, [None, None], wv, dg, 0
            )

    def test_missing_owner_detected(self):
        from photon_ml_tpu.game import random_effect as re_mod

        prepared = _fake_prepared([[0], [1]], [0, 1])
        W_h = np.zeros((2, 2), np.float32)
        wv = [{"buckets": np.array([0]),
               "W": np.zeros((1, 2), np.float32)}]
        dg = [{"F": np.zeros(1), "I": np.zeros(1, np.int64),
               "R": np.zeros(1, np.int64)}]
        with pytest.raises(RuntimeError, match="no owner"):
            re_mod._apply_owner_segments(
                prepared, W_h, None, [None, None], wv, dg, 0
            )

    def test_pack_matches_allreduce_dtype_flow(self):
        """The segment payload's F/I/R dtypes are the dense arm's
        accumulator dtypes (f64/i64) — the float32 cast at reassembly
        is then bit-for-bit the allreduce arm's."""
        from photon_ml_tpu.game import random_effect as re_mod

        diag = [(np.float32([1.5]), np.int32([3]), np.int32([1]))]
        p = re_mod._pack_diag_segments(diag)
        assert p["F"].dtype == np.float64
        assert p["I"].dtype == np.int64 and p["R"].dtype == np.int64


class TestGatherUnaddressable:
    def test_single_process_reassembles_from_local_shards(self):
        from photon_ml_tpu.game.random_effect import (
            _gather_refs_host,
            _gather_unaddressable,
        )

        x = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
        (full,) = _gather_unaddressable([x])
        np.testing.assert_array_equal(full, np.asarray(x))
        refs = [(x[:, 0], jnp.arange(3, dtype=jnp.int32),
                 jnp.zeros(3, jnp.int32))]
        host = _gather_refs_host(refs)
        np.testing.assert_array_equal(host[0][0], np.asarray(x[:, 0]))


# -- knobs -------------------------------------------------------------------


class TestKnobs:
    def test_re_combine_default_and_env(self, monkeypatch):
        from photon_ml_tpu.game import random_effect as re_mod

        monkeypatch.delenv("PHOTON_RE_COMBINE", raising=False)
        assert re_mod.re_combine_mode() == "allreduce"
        monkeypatch.setenv("PHOTON_RE_COMBINE", "segments")
        assert re_mod.re_combine_mode() == "segments"
        monkeypatch.setenv("PHOTON_RE_COMBINE", "ring")
        with pytest.raises(ValueError, match="PHOTON_RE_COMBINE"):
            re_mod.re_combine_mode()

    def test_re_combine_module_global(self, monkeypatch):
        from photon_ml_tpu.game import random_effect as re_mod

        monkeypatch.delenv("PHOTON_RE_COMBINE", raising=False)
        monkeypatch.setattr(re_mod, "RE_COMBINE", "segments")
        assert re_mod.re_combine_mode() == "segments"

    def test_replan_threshold(self, monkeypatch):
        from photon_ml_tpu.parallel import placement

        monkeypatch.delenv("PHOTON_RE_REPLAN_IMBALANCE", raising=False)
        assert placement.replan_imbalance_threshold() == 0.0
        monkeypatch.setenv("PHOTON_RE_REPLAN_IMBALANCE", "1.4")
        assert placement.replan_imbalance_threshold() == 1.4
        monkeypatch.setenv("PHOTON_RE_REPLAN_IMBALANCE", "-1")
        assert placement.replan_imbalance_threshold() == 0.0
        monkeypatch.setenv("PHOTON_RE_REPLAN_IMBALANCE", "fast")
        with pytest.raises(ValueError):
            placement.replan_imbalance_threshold()

    def test_straggler_spec(self, monkeypatch):
        from photon_ml_tpu.parallel import faults

        monkeypatch.delenv("PHOTON_RE_STRAGGLER", raising=False)
        assert faults.straggler_spec() is None
        assert faults.maybe_straggle() == 0.0
        monkeypatch.setenv("PHOTON_RE_STRAGGLER", "1:0.25")
        assert faults.straggler_spec() == (1, 0.25)
        # this test runs as process 0 -> no sleep
        assert faults.maybe_straggle() == 0.0
        monkeypatch.setenv("PHOTON_RE_STRAGGLER", "nope")
        with pytest.raises(ValueError, match="PHOTON_RE_STRAGGLER"):
            faults.straggler_spec()

    def test_straggler_sleeps_on_named_process(self, monkeypatch):
        from photon_ml_tpu.parallel import faults

        monkeypatch.setenv("PHOTON_RE_STRAGGLER", "0:0.01")
        slept = faults.maybe_straggle()
        assert slept == 0.01


class TestMeasuredCosts:
    def test_straggler_shard_inflates_its_entities(self):
        from photon_ml_tpu.parallel.placement import measured_entity_costs

        counts = np.array([10, 10, 10, 10])
        owner = np.array([0, 0, 1, 1])
        walls = np.array([1.0, 3.0])  # shard 1 measured 3x slower
        costs = measured_entity_costs(counts, owner, walls)
        np.testing.assert_allclose(costs, [0.5, 0.5, 1.5, 1.5])

    def test_zero_wall_falls_back_to_mean_rate(self):
        from photon_ml_tpu.parallel.placement import measured_entity_costs

        counts = np.array([10, 10])
        owner = np.array([0, 1])
        costs = measured_entity_costs(counts, owner, np.array([2.0, 0.0]))
        # shard 1's rate falls back to shard 0's (the only measured one)
        np.testing.assert_allclose(costs, [2.0, 2.0])

    def test_replan_excluding_healthy_fleet_migrates(self):
        from photon_ml_tpu.parallel.placement import (
            PlacementPlan,
            measured_entity_costs,
            replan_excluding,
        )

        counts = np.array([8, 8, 8, 8])
        owner = np.array([0, 0, 0, 1])  # imbalanced by construction
        loads = np.array([24.0, 8.0])
        plan = PlacementPlan(owner=owner, loads=loads, num_shards=2)
        costs = measured_entity_costs(
            counts, owner, np.array([3.0, 1.0])
        )
        new_plan, migrated = replan_excluding(
            plan, [], costs, survivors=range(2)
        )
        assert migrated.sum() > 0
        assert new_plan.balance < plan.balance


# -- report / gate surface ---------------------------------------------------


def _write_shard(d, pidx, shard, extra_records=(), counters=None,
                 gauges=None, timers=None, knobs=None, fleet=2):
    from photon_ml_tpu.obs.sink import TelemetrySink

    t0 = 1000.0
    s = TelemetrySink(d, run_id="RC", shard_index=shard)
    s.emit({"event": "run_start", "t": t0, "schema_version": 1,
            "run_id": "RC", "pid": pidx, "process_index": pidx,
            "knobs": knobs or {}, "fleet": {"process_count": fleet},
            "metrics_baseline": {}})
    s.emit({"event": "span", "t": t0 + 0.1, "name": "descent/iter",
            "span_id": 1, "parent_id": None, "tid": 1, "thread": "M",
            "dur_s": 1.0})
    for r in extra_records:
        s.emit(dict(r, t=t0 + 0.5))
    s.emit({"event": "run_end", "t": t0 + 2.0, "run_id": "RC",
            "metrics": {"counters": counters or {}, "gauges": gauges or {},
                        "histograms": {}, "timers": timers or {}}})
    s.close()
    return s.path


class TestReportSurface:
    COUNTERS = {
        "re_combine.exchanges": {"value": 2.0},
        "re_combine.bytes_sent": {"value": 4096.0},
        "re_replan.checks": {"value": 1.0},
        "re_replan.count": {"value": 1.0},
        "re_replan.migrations": {"value": 12.0},
    }
    TIMERS = {
        "re_combine.exchange_s": {"seconds": 0.5, "count": 2},
        "re_combine.wait_s": {"seconds": 0.1, "count": 2},
    }
    REPLAN_EVENT = {
        "event": "re_replan", "iteration": 0, "coordinate": "per_entity",
        "imbalance": 2.5, "threshold": 1.3, "migrated": 12,
        "old_balance": 2.1, "new_balance": 1.1,
    }

    def test_summary_blocks_and_gate_metrics(self, tmp_path):
        from photon_ml_tpu.obs.report import (
            format_summary,
            gate_metrics_from_summary,
            summarize_run,
        )

        p = _write_shard(
            str(tmp_path), 0, None, extra_records=[self.REPLAN_EVENT],
            counters=self.COUNTERS, timers=self.TIMERS,
            gauges={"re_replan.last_imbalance": 2.5},
            knobs={"re_combine": "segments"},
        )
        s = summarize_run(p)
        assert s["re_combine"]["bytes_sent"] == 4096.0
        assert s["re_combine"]["mode"] == "segments"
        assert s["re_combine"]["exchange_s"] == 0.5
        assert s["re_replan"]["migrations"] == 12.0
        assert s["re_replan"]["events"][0]["coordinate"] == "per_entity"
        m = gate_metrics_from_summary(s)
        assert m["re_combine/bytes_sent"] == 4096.0
        assert m["re_replan/migrations"] == 12.0
        txt = format_summary(s)
        assert "re-combine:" in txt and "re-plan:" in txt

    def test_summary_without_combine_has_no_new_keys(self, tmp_path):
        from photon_ml_tpu.obs.report import summarize_run

        p = _write_shard(str(tmp_path), 0, None)
        s = summarize_run(p)
        assert "re_combine" not in s and "re_replan" not in s

    def test_gate_tiers(self):
        from photon_ml_tpu.obs.report import (
            DEFAULT_GATE_THRESHOLDS,
            resolve_threshold,
        )

        assert resolve_threshold(
            "re_combine/bytes_sent", DEFAULT_GATE_THRESHOLDS
        ) == {"rel": 0.05}
        assert resolve_threshold(
            "re_replan/migrations", DEFAULT_GATE_THRESHOLDS
        ) == {"rel": 0.0, "abs": 0.0}

    def test_gate_fails_on_byte_and_migration_regressions(self):
        from photon_ml_tpu.obs.report import gate_run

        base = {"re_combine/bytes_sent": 1000.0,
                "re_replan/migrations": 0.0}
        ok, _ = gate_run(dict(base), base)
        assert not ok
        fail_bytes, _ = gate_run(
            {"re_combine/bytes_sent": 1100.0,
             "re_replan/migrations": 0.0}, base
        )
        assert any(
            f["metric"] == "re_combine/bytes_sent" for f in fail_bytes
        )
        fail_mig, _ = gate_run(
            {"re_combine/bytes_sent": 1000.0,
             "re_replan/migrations": 1.0}, base
        )
        assert any(
            f["metric"] == "re_replan/migrations" for f in fail_mig
        )

    def test_fleet_merge_and_gate_metrics(self, tmp_path):
        from photon_ml_tpu.obs.report import (
            fleet_run_paths,
            format_fleet,
            gate_metrics_from_fleet,
            summarize_fleet,
        )

        _write_shard(
            str(tmp_path), 0, None, extra_records=[self.REPLAN_EVENT],
            counters=self.COUNTERS, timers=self.TIMERS,
            knobs={"re_combine": "segments"},
        )
        _write_shard(
            str(tmp_path), 1, 1,
            counters={
                "re_combine.exchanges": {"value": 2.0},
                "re_combine.bytes_sent": {"value": 1024.0},
                "re_replan.migrations": {"value": 12.0},
            },
            knobs={"re_combine": "segments"},
        )
        fs = summarize_fleet(fleet_run_paths(str(tmp_path)))
        assert fs["re_combine"]["bytes_sent_total"] == 5120.0
        assert fs["re_combine"]["per_process"] == {"0": 4096.0,
                                                   "1": 1024.0}
        assert fs["replans"][0]["migrated"] == 12
        m = gate_metrics_from_fleet(fs)
        assert m["re_combine/bytes_sent"] == 5120.0
        assert m["re_replan/migrations"] == 12.0
        txt = format_fleet(fs)
        assert "re-combine:" in txt and "re-plan:" in txt


class TestBenchKnobParse:
    def test_retune_env_maps_carry_new_knobs(self):
        import bench

        assert bench.RETUNE_ENV_RE["PHOTON_RE_COMBINE"] == "RE_COMBINE"
        assert (
            bench.RETUNE_ENV_SHARD["PHOTON_RE_REPLAN_IMBALANCE"]
            == "REPLAN_IMBALANCE"
        )

    def test_apply_retune_env_parses_string_and_float(self, monkeypatch):
        import bench
        from photon_ml_tpu.game import random_effect as re_mod
        from photon_ml_tpu.parallel import placement

        monkeypatch.setenv("PHOTON_RE_COMBINE", "segments")
        monkeypatch.setenv("PHOTON_RE_REPLAN_IMBALANCE", "1.25")
        monkeypatch.setattr(re_mod, "RE_COMBINE", "allreduce")
        monkeypatch.setattr(placement, "REPLAN_IMBALANCE", 0.0)
        bench._apply_retune_env()
        assert re_mod.RE_COMBINE == "segments"
        assert placement.REPLAN_IMBALANCE == 1.25

    def test_apply_retune_env_rejects_bad_mode(self, monkeypatch):
        import bench

        monkeypatch.setenv("PHOTON_RE_COMBINE", "broadcast")
        with pytest.raises(ValueError, match="PHOTON_RE_COMBINE"):
            bench._apply_retune_env()

    def test_r08_sizes_are_zipf_with_real_entity_count(self):
        import bench

        sizes = bench._multichip_r08_sizes(1024)
        assert len(sizes) == 1024
        assert sizes.min() >= 1 and sizes[0] > sizes[-1]
        # Zipf(~1): roughly constant row mass per capacity octave —
        # the property that makes the bucket ladder's classes (the
        # placement atoms) carry comparable loads
        head = sizes[sizes >= 64].sum()
        tail = sizes[sizes < 4].sum()
        assert head > 0 and tail > 0

    def test_knob_snapshot_carries_combine_and_replan(self, monkeypatch):
        from photon_ml_tpu.obs.sink import _knob_snapshot

        monkeypatch.setenv("PHOTON_RE_COMBINE", "segments")
        monkeypatch.setenv("PHOTON_RE_REPLAN_IMBALANCE", "1.5")
        k = _knob_snapshot()
        assert k["re_combine"] == "segments"
        assert k["re_replan_imbalance"] == 1.5
