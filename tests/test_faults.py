"""Host-side tests for the fault-tolerance layer (ISSUE 11): the
deterministic fault plan, the CRC32 frame protocol, the retry/backoff
wrapper with its PeerLost hardening, the blocked-send heartbeat, the
drain-error telemetry satellite, and the degraded-group helpers. The
end-to-end 2-process chaos drills live in test_multihost.py (slow,
gloo-loopback); everything here runs in-process on fake sockets."""

import socket
import struct
import zlib

import numpy as np
import pytest

import photon_ml_tpu.parallel.faults as faults
import photon_ml_tpu.parallel.multihost as mh


@pytest.fixture(autouse=True)
def _clean_fault_plans():
    faults.reset()
    yield
    faults.reset()


class FrameSock:
    """Replays pre-framed bytes on recv; records sends."""

    def __init__(self, frames=(), crc=False):
        self.buf = b"".join(
            struct.pack("!q", len(f)) + f
            + (struct.pack("!I", zlib.crc32(f)) if crc else b"")
            for f in frames
        )
        self.sent: list[bytes] = []
        self.closed = False

    def recv(self, n):
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def sendall(self, data):
        if self.closed:
            raise OSError("socket closed")
        self.sent.append(bytes(data))

    def close(self):
        self.closed = True


class TestFaultPlanGrammar:
    def test_parse_valid_plan(self):
        plan = faults.parse_plan(
            '[{"op": "drop", "link": [0, 1], "seq": 2, "tag": "offsets"},'
            ' {"op": "delay", "link": [1, 0], "seq": 1, "delay_s": 0.01}]'
        )
        assert plan.remaining == 2
        assert plan.specs[0].op == "drop"
        assert (plan.specs[0].src, plan.specs[0].dst) == (0, 1)

    def test_parse_from_file(self, tmp_path):
        p = tmp_path / "plan.json"
        p.write_text('[{"op": "close", "link": [0, 1], "seq": 1}]')
        plan = faults.parse_plan(f"@{p}")
        assert plan.specs[0].op == "close"

    @pytest.mark.parametrize(
        "bad",
        [
            '{"op": "drop"}',  # not a list
            '[{"op": "explode", "link": [0, 1], "seq": 1}]',  # bad op
            '[{"op": "drop", "link": [0], "seq": 1}]',  # bad link
            '[{"op": "drop", "link": [0, 1], "seq": 0}]',  # bad seq
            '[{"op": "drop", "link": [0, 1], "seq": 1, "x": 1}]',  # key
            '[{"op": "delay", "link": [0, 1], "seq": 1}]',  # no delay_s
        ],
    )
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            faults.parse_plan(bad)

    def test_specs_fire_once_and_match_tag(self):
        plan = faults.parse_plan(
            '[{"op": "drop", "link": [0, 1], "seq": 1, "tag": "offsets"}]'
        )
        assert plan.pop_send_fault(0, 1, 1, "scores") is None
        assert plan.pop_send_fault(0, 2, 1, "offsets") is None
        spec = plan.pop_send_fault(0, 1, 1, "offsets")
        assert spec is not None and spec.op == "drop"
        # consumed: the retried frame set goes through clean
        assert plan.pop_send_fault(0, 1, 1, "offsets") is None
        assert plan.remaining == 0

    def test_two_specs_one_frame_set_fire_on_successive_attempts(self):
        plan = faults.parse_plan(
            '[{"op": "drop", "link": [0, 1], "seq": 1},'
            ' {"op": "drop", "link": [0, 1], "seq": 1}]'
        )
        assert plan.pop_send_fault(0, 1, 1, "") is not None
        assert plan.pop_send_fault(0, 1, 1, "") is not None
        assert plan.pop_send_fault(0, 1, 1, "") is None

    def test_active_plan_caches_and_no_plan_is_none(self, monkeypatch):
        monkeypatch.delenv("PHOTON_FAULT_PLAN", raising=False)
        assert faults.active_plan() is None
        monkeypatch.setenv(
            "PHOTON_FAULT_PLAN",
            '[{"op": "drop", "link": [0, 1], "seq": 1}]',
        )
        plan = faults.active_plan()
        assert plan is not None
        assert faults.active_plan() is plan  # cached (fired state sticks)
        with pytest.raises(ValueError):
            monkeypatch.setenv("PHOTON_FAULT_PLAN", '{"op": "x"}')
            faults.active_plan()


class TestFrameProtocol:
    def _recv_frame(self, sock, crc):
        n = struct.unpack("!q", mh._recv_exact(sock, 8))[0]
        return mh._recv_frame_payload(sock, n, crc)

    def test_crc_roundtrip(self):
        payload = np.arange(7, dtype=np.float32).tobytes()
        sock = FrameSock()
        mh._send_frame(sock, payload, crc=True)
        # wire: length prefix + payload + 4-byte trailer
        assert b"".join(sock.sent) == (
            struct.pack("!q", len(payload)) + payload
            + struct.pack("!I", zlib.crc32(payload))
        )
        echo = FrameSock([payload], crc=True)
        assert self._recv_frame(echo, crc=True) == payload

    def test_crc_off_wire_bytes_identical_to_plain_framing(self):
        payload = b"abcdef"
        sock = FrameSock()
        mh._send_frame(sock, payload, crc=False)
        assert b"".join(sock.sent) == struct.pack("!q", 6) + payload

    def test_corruption_detected(self):
        payload = b"x" * 64
        bad = faults._corrupt(payload)
        assert bad != payload and len(bad) == len(payload)
        wire = FrameSock()
        wire.buf = (
            struct.pack("!q", len(bad)) + bad
            + struct.pack("!I", zlib.crc32(payload))  # trailer of GOOD
        )
        with pytest.raises(mh.LinkCorruption):
            self._recv_frame(wire, crc=True)

    def test_hello_negotiation(self, monkeypatch):
        monkeypatch.delenv("PHOTON_P2P_CRC", raising=False)
        assert mh._hello_int(3) == 3  # knob off: the PR-10 hello verbatim
        monkeypatch.setenv("PHOTON_P2P_CRC", "1")
        raw = mh._hello_int(3)
        assert mh._decode_hello(raw) == (3, mh._FRAME_PROTO_CRC)
        # a v0 receiver's mask still reads the right pid
        assert raw & 0xFFFF == 3


class TestKnobsOffWireIdentity:
    def test_exchange_wire_bytes_identical_to_pre_retry_protocol(
        self, monkeypatch
    ):
        """The acceptance anchor: with no fault plan and every knob
        unset, the framed exchange puts EXACTLY the PR-10 bytes on the
        wire — 8-byte length prefix + payload per key, no CRC trailer,
        no completion ACK — asserted byte-for-byte on a captured fake
        link."""
        import jax

        for k in ("PHOTON_P2P_CRC", "PHOTON_P2P_RETRIES",
                  "PHOTON_FAULT_PLAN", "PHOTON_P2P_HEARTBEAT_S"):
            monkeypatch.delenv(k, raising=False)
        payload_in = np.arange(2, dtype=np.float32).tobytes()
        links = {
            "send": {1: FrameSock()},
            "recv": {1: FrameSock([payload_in])},
        }
        monkeypatch.setattr(mh, "_HOST_LINKS", links)
        monkeypatch.setattr(mh, "_host_links", lambda: links)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        monkeypatch.setattr(mh, "_LINK_SEQ", {"send": {}, "recv": {}})
        arrays = {"v": np.arange(4, dtype=np.float32)}
        order = np.arange(4, dtype=np.int64)
        starts = np.asarray([0, 2, 4], np.int64)
        out = mh._host_p2p_exchange(arrays, order, starts, None, tag="t")
        np.testing.assert_array_equal(
            out["v"], np.concatenate([arrays["v"][:2], [0.0, 1.0]])
        )
        expect = arrays["v"][2:4].tobytes()
        assert b"".join(links["send"][1].sent) == (
            struct.pack("!q", len(expect)) + expect
        )
        # and the peer's stream was drained exactly — no trailing ACK
        # read attempt against the recv link
        assert links["recv"][1].buf == b""


class TestSendFaults:
    def test_drop_returns_none(self):
        spec = faults.FaultSpec(op="drop", src=0, dst=1, seq=1)
        bufs, corrupt = faults.apply_send_fault(
            spec, [b"abc"], FrameSock()
        )
        assert bufs is None and not corrupt

    def test_corrupt_is_a_wire_fault_the_crc_catches(self):
        """The corrupt op flags WIRE corruption: the frame payloads are
        untouched (the CRC trailer is computed over them), and the link
        layer flips bytes after checksumming — so the receiver's CRC
        check fires. A pre-CRC flip would be faithfully checksummed and
        arrive 'valid' (the original injection bug this test pins)."""
        spec = faults.FaultSpec(op="corrupt", src=0, dst=1, seq=1)
        bufs, corrupt = faults.apply_send_fault(
            spec, [b"aaaa", b"bbbb"], FrameSock()
        )
        assert bufs == [b"aaaa", b"bbbb"] and corrupt
        payload = b"x" * 32
        sock = FrameSock()
        mh._send_frame(sock, payload, crc=True, corrupt_wire=True)
        wire = b"".join(sock.sent)
        sent_payload = wire[8:-4]
        trailer = struct.unpack("!I", wire[-4:])[0]
        assert sent_payload != payload  # wire bytes flipped...
        assert trailer == zlib.crc32(payload)  # ...after checksumming
        assert zlib.crc32(sent_payload) != trailer  # receiver detects

    def test_close_closes_socket(self):
        sock = FrameSock()
        spec = faults.FaultSpec(op="close", src=0, dst=1, seq=1)
        bufs, corrupt = faults.apply_send_fault(spec, [b"abc"], sock)
        assert sock.closed and bufs == [b"abc"] and not corrupt
        with pytest.raises(OSError):
            sock.sendall(b"x")  # the natural error path fires next

    def test_delay_sleeps(self):
        import time

        spec = faults.FaultSpec(
            op="delay", src=0, dst=1, seq=1, delay_s=0.05
        )
        t0 = time.perf_counter()
        faults.apply_send_fault(spec, [b"abc"], FrameSock())
        assert time.perf_counter() - t0 >= 0.04


class TestRetryWrapper:
    def _call(self, monkeypatch, attempts_needed, error, retries):
        calls = {"n": 0}

        def impl(*a, **k):
            calls["n"] += 1
            if calls["n"] <= attempts_needed:
                raise error
            return {"ok": calls["n"]}

        monkeypatch.setattr(mh, "_host_p2p_exchange_impl", impl)
        monkeypatch.setattr(mh, "_reset_host_links", lambda: None)
        monkeypatch.setenv("PHOTON_P2P_RETRIES", str(retries))
        monkeypatch.setenv("PHOTON_P2P_BACKOFF_S", "0")
        return calls, lambda: mh._host_p2p_exchange(
            {}, np.zeros(0, np.int64), np.zeros(1, np.int64), tag="t"
        )

    def test_transient_fault_retried_to_success(self, monkeypatch):
        from photon_ml_tpu.obs.metrics import REGISTRY

        before = (
            REGISTRY.snapshot().get("counters", {})
            .get("p2p.retries", {}).get("value", 0.0)
        )
        calls, run = self._call(
            monkeypatch, 2, ConnectionError("reset"), retries=3
        )
        assert run() == {"ok": 3}
        assert calls["n"] == 3
        after = (
            REGISTRY.snapshot().get("counters", {})
            .get("p2p.retries", {}).get("value", 0.0)
        )
        assert after - before == 2

    def test_knob_off_raises_immediately(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 1, ConnectionError("reset"), retries=0
        )
        with pytest.raises(ConnectionError):
            run()
        assert calls["n"] == 1  # the pre-retry behavior bit-for-bit

    def test_exhaustion_raises_original_error(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 10, socket.timeout("silent"), retries=2
        )
        with pytest.raises((socket.timeout, TimeoutError)):
            run()
        assert calls["n"] == 3  # 1 + 2 retries

    def test_unreachable_peer_hardens_into_peer_lost(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 10, mh.PeerUnreachable(1, "refused"), retries=2
        )
        with pytest.raises(mh.PeerLost) as ei:
            run()
        assert ei.value.peer == 1

    def test_non_transient_error_never_retried(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 10, RuntimeError("size mismatch"), retries=5
        )
        with pytest.raises(RuntimeError):
            run()
        assert calls["n"] == 1

    def test_corruption_is_transient(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 1, mh.LinkCorruption("crc"), retries=1
        )
        assert run() == {"ok": 2}

    def test_retry_events_ride_the_sink(self, tmp_path, monkeypatch):
        import photon_ml_tpu.obs as obs

        path = obs.configure(str(tmp_path / "tel"), run_id="retry")
        try:
            calls, run = self._call(
                monkeypatch, 1, mh.LinkCorruption("crc"), retries=1
            )
            run()
            calls2, run2 = self._call(
                monkeypatch, 10, mh.PeerUnreachable(1, "x"), retries=1
            )
            with pytest.raises(mh.PeerLost):
                run2()
        finally:
            obs.shutdown()
        from photon_ml_tpu.obs.report import load_run

        records = load_run(path)
        retries = [r for r in records if r["event"] == "p2p_retry"]
        giveups = [r for r in records if r["event"] == "p2p_giveup"]
        assert len(retries) == 2 and len(giveups) == 1
        assert retries[0]["error"] == "LinkCorruption"
        assert retries[0]["tag"] == "t"
        assert retries[0]["attempt"] == 1
        assert giveups[0]["error"] == "PeerUnreachable"
        assert giveups[0]["peer"] == 1

    def test_backoff_deterministic_and_exponential(self, monkeypatch):
        monkeypatch.setenv("PHOTON_P2P_BACKOFF_S", "0.25")
        a0, a1 = mh._retry_backoff_sleep(0), mh._retry_backoff_sleep(1)
        assert a0 == mh._retry_backoff_sleep(0)  # deterministic
        assert 0.25 <= a0 < 0.375  # base * (1 + jitter<0.5)
        assert a1 >= 2 * 0.25  # exponential


class TestSendHeartbeat:
    def test_plain_path_is_sendall(self):
        sock = FrameSock()
        mh._sendall_hb(sock, b"abc")
        assert sock.sent == [b"abc"]

    def test_blocked_send_emits_direction_send_heartbeats(
        self, tmp_path, monkeypatch
    ):
        import photon_ml_tpu.obs as obs

        monkeypatch.setenv("PHOTON_P2P_TIMEOUT_S", "0.25")
        path = obs.configure(str(tmp_path / "tel"), run_id="hb")
        a, b = socket.socketpair()
        try:
            # fill a's kernel buffer so the next send blocks on the
            # never-draining peer
            a.setblocking(False)
            try:
                while True:
                    a.send(b"x" * 65536)
            except BlockingIOError:
                pass
            a.setblocking(True)
            with pytest.raises((socket.timeout, TimeoutError)):
                mh._sendall_hb(
                    a, b"y" * (1 << 22), peer=1, tag="scores",
                    heartbeat=0.05,
                )
        finally:
            obs.shutdown()
            a.close()
            b.close()
        from photon_ml_tpu.obs.report import load_run

        beats = [
            r for r in load_run(path) if r["event"] == "p2p_heartbeat"
        ]
        assert len(beats) >= 2
        assert all(r["direction"] == "send" for r in beats)
        assert all(r["peer"] == 1 and r["tag"] == "scores" for r in beats)
        assert beats[-1]["blocked_s"] >= beats[0]["blocked_s"]

    def test_blocking_mode_heartbeats_without_timeout(
        self, tmp_path, monkeypatch
    ):
        """Satellite: PHOTON_P2P_TIMEOUT_S<=0 (blocking sockets) still
        honors heartbeats — the recv polls and emits, and only data
        ends the wait (no spurious timeout raise)."""
        import threading
        import time

        import photon_ml_tpu.obs as obs

        monkeypatch.setenv("PHOTON_P2P_TIMEOUT_S", "0")
        path = obs.configure(str(tmp_path / "tel"), run_id="hb0")
        a, b = socket.socketpair()
        payload = b"z" * 8

        def late_send():
            time.sleep(0.3)
            b.sendall(payload)

        t = threading.Thread(target=late_send)
        t.start()
        try:
            got = mh._recv_exact(a, 8, peer=1, tag="offsets",
                                 heartbeat=0.05)
            assert got == payload
        finally:
            t.join()
            obs.shutdown()
            a.close()
            b.close()
        beats = [
            r for r in load_run_path(path)
            if r["event"] == "p2p_heartbeat"
        ]
        assert len(beats) >= 2  # beat while blocked, then delivered


def load_run_path(path):
    from photon_ml_tpu.obs.report import load_run

    return load_run(path)


class TestDrainErrorTelemetry:
    def test_drain_records_worker_exception(self, tmp_path, monkeypatch):
        import photon_ml_tpu.obs as obs

        pool, lock = mh._exchange_state()
        path = obs.configure(str(tmp_path / "tel"), run_id="drain")
        try:
            fut = pool.submit(self._boom)
            with lock:
                mh._PENDING_EXCHANGES.append((fut, "offsets"))
            mh.drain_async_exchanges()
        finally:
            obs.shutdown()
            mh.reset_async_exchanges()
        records = load_run_path(path)
        errs = [
            r for r in records if r["event"] == "exchange_drain_error"
        ]
        assert len(errs) == 1
        assert errs[0]["tag"] == "offsets"
        assert errs[0]["error"] == "PeerUnreachable"
        assert errs[0]["peer"] == 1

    @staticmethod
    def _boom():
        raise mh.PeerUnreachable(1, "refused")

    def test_reset_clears_pending(self):
        pool, lock = mh._exchange_state()
        fut = pool.submit(lambda: None)
        with lock:
            mh._PENDING_EXCHANGES.append((fut, "t"))
        mh.reset_async_exchanges()
        with lock:
            assert not mh._PENDING_EXCHANGES


class TestDegradedGroup:
    @pytest.fixture(autouse=True)
    def _restore(self):
        yield
        mh._DEGRADED = None

    def test_single_survivor_world(self, monkeypatch):
        monkeypatch.setattr(
            mh, "_DEGRADED", {"survivors": (0,), "rank": 0}
        )
        assert mh.effective_process_count() == 1
        assert mh.effective_process_index() == 0
        assert mh.is_output_process()
        # group-shaped helpers collapse to identities — no jax
        # collective (which would hang on the dead peer) is touched
        mh.sync_processes("after-loss")
        assert mh.allreduce_sum_host(np.asarray([3.0])) == [3.0]
        out = mh.exchange_rows(
            {"v": np.arange(3.0)}, np.zeros(3, np.int64)
        )
        np.testing.assert_array_equal(out["v"], np.arange(3.0))
        assert mh.LAST_EXCHANGE_STATS["transport"] == "local"
        tree = mh.broadcast_from_host0({"a": np.ones(2)})
        np.testing.assert_array_equal(tree["a"], np.ones(2))

    def test_rank_mapping(self, monkeypatch):
        monkeypatch.setattr(
            mh, "_DEGRADED", {"survivors": (0, 2, 3), "rank": 1}
        )
        assert mh.effective_process_count() == 3
        assert mh.effective_process_index() == 1
        assert mh._orig_pid(0) == 0
        assert mh._orig_pid(1) == 2
        assert mh._orig_pid(2) == 3
        assert not mh.is_output_process()

    def test_set_degraded_group_requires_membership(self, monkeypatch):
        import jax

        monkeypatch.setattr(jax, "process_index", lambda: 0)
        with pytest.raises(ValueError):
            mh.set_degraded_group([1, 2])


class TestMeshBuildCleanup:
    """Satellites: a partial mesh-build failure must close everything
    and leave the port rebindable, and ``_reset_host_links`` after a
    mid-frame error must leave no listening socket behind."""

    def test_partial_build_closes_sockets_and_joins_acceptor(
        self, monkeypatch
    ):
        import threading

        import jax

        monkeypatch.setattr(jax, "process_count", lambda: 3)
        monkeypatch.setattr(jax, "process_index", lambda: 0)

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        # peer addresses: freshly freed ports nothing listens on, so
        # every connect is refused
        monkeypatch.setattr(
            mh, "_HOST_ADDRS",
            {0: ("127.0.0.1", free_port()), 1: ("127.0.0.1", free_port()),
             2: ("127.0.0.1", free_port())},
        )
        threads_before = {
            t.ident for t in threading.enumerate() if t.is_alive()
        }
        with pytest.raises(mh.PeerUnreachable):
            mh._build_host_links([0, 1, 2], timeout_s=0.5)
        # no acceptor thread survives the failed build
        leaked = [
            t for t in threading.enumerate()
            if t.is_alive() and t.ident not in threads_before
        ]
        assert not leaked
        # and the recorded port is immediately rebindable: the failed
        # build closed its listener (regression guard for the leaked-
        # listener half of the satellite)
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        s.close()

    def test_rebuild_binds_recorded_port_immediately(self, monkeypatch):
        """After a teardown (mid-frame error path), rebuilding must be
        able to bind the SAME recorded port at once — a leaked listener
        would make bind fail with EADDRINUSE."""
        import jax

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        probe2 = socket.socket()
        probe2.bind(("127.0.0.1", 0))
        dead_port = probe2.getsockname()[1]
        probe2.close()
        monkeypatch.setattr(
            mh, "_HOST_ADDRS",
            {0: ("127.0.0.1", port), 1: ("127.0.0.1", dead_port)},
        )
        monkeypatch.setattr(mh, "_HOST_LINKS", None)
        for _ in range(2):  # two successive failed builds: no leak
            with pytest.raises((mh.PeerUnreachable, OSError)):
                mh._build_host_links([0, 1], timeout_s=0.3)
        mh._reset_host_links()
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", port))  # bind succeeds immediately
        s.close()


class TestReplanExcluding:
    def test_replan_matches_direct_plan_and_flags_migrations(self):
        from photon_ml_tpu.parallel.placement import (
            plan_entity_placement,
            replan_excluding,
        )

        rng = np.random.default_rng(0)
        counts = rng.integers(1, 100, size=32).astype(np.float64)
        plan4 = plan_entity_placement(counts, 4)
        new_plan, migrated = replan_excluding(
            plan4, lost_shards=[2], row_counts=counts,
            survivors=[0, 1, 3],
        )
        # the re-plan IS the deterministic 3-shard plan: every survivor
        # computes it identically with zero communication
        direct = plan_entity_placement(counts, 3)
        np.testing.assert_array_equal(new_plan.owner, direct.owner)
        # everything the dead shard owned migrated somewhere
        assert migrated[plan4.owner == 2].all()
        # migration flags compare via survivor ranks: 3 (rank 2) != 2
        rank_of = {0: 0, 1: 1, 3: 2}
        for i, m in enumerate(migrated):
            old = plan4.owner[i]
            expect = (
                old == 2 or rank_of[int(old)] != int(new_plan.owner[i])
            )
            assert bool(m) == expect, i

    def test_replan_rejects_overlap_and_empty(self):
        from photon_ml_tpu.parallel.placement import (
            plan_entity_placement,
            replan_excluding,
        )

        plan = plan_entity_placement(np.ones(4), 2)
        with pytest.raises(ValueError):
            replan_excluding(plan, [0], np.ones(4), survivors=[0, 1])
        with pytest.raises(ValueError):
            replan_excluding(plan, [0, 1], np.ones(4), survivors=[])


class TestCheckpointFingerprintCollection:
    def test_load_accepts_any_listed_fingerprint(self, tmp_path):
        import jax.numpy as jnp

        from photon_ml_tpu.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )
        from photon_ml_tpu.game.models import GameModel, RandomEffectModel
        from photon_ml_tpu.types import TaskType

        model = GameModel(
            models={
                "re": RandomEffectModel(
                    coefficients=jnp.ones((2, 3)),
                    variances=None,
                    random_effect_type="eid",
                    feature_shard_id="r",
                    task_type=TaskType.LOGISTIC_REGRESSION,
                )
            },
            task_type=TaskType.LOGISTIC_REGRESSION,
        )
        save_checkpoint(
            str(tmp_path), model, next_iteration=2, fingerprint="pre-loss"
        )
        # the degraded layout's own fingerprint alone: rejected
        assert load_checkpoint(str(tmp_path), fingerprint="degraded") is None
        # recovery passes BOTH: accepted, resumes at the stored iteration
        ck = load_checkpoint(
            str(tmp_path), fingerprint=("degraded", "pre-loss")
        )
        assert ck is not None and ck.next_iteration == 2
        # plain string still works (the pre-existing contract)
        assert load_checkpoint(str(tmp_path), fingerprint="pre-loss") is not None
