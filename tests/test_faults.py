"""Host-side tests for the fault-tolerance layer (ISSUE 11): the
deterministic fault plan, the CRC32 frame protocol, the retry/backoff
wrapper with its PeerLost hardening, the blocked-send heartbeat, the
drain-error telemetry satellite, and the degraded-group helpers. The
end-to-end 2-process chaos drills live in test_multihost.py (slow,
gloo-loopback); everything here runs in-process on fake sockets."""

import socket
import struct
import zlib

import numpy as np
import pytest

import photon_ml_tpu.parallel.faults as faults
import photon_ml_tpu.parallel.multihost as mh


@pytest.fixture(autouse=True)
def _clean_fault_plans():
    faults.reset()
    yield
    faults.reset()


class FrameSock:
    """Replays pre-framed bytes on recv; records sends."""

    def __init__(self, frames=(), crc=False):
        self.buf = b"".join(
            struct.pack("!q", len(f)) + f
            + (struct.pack("!I", zlib.crc32(f)) if crc else b"")
            for f in frames
        )
        self.sent: list[bytes] = []
        self.closed = False

    def recv(self, n):
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def sendall(self, data):
        if self.closed:
            raise OSError("socket closed")
        self.sent.append(bytes(data))

    def close(self):
        self.closed = True


class TestFaultPlanGrammar:
    def test_parse_valid_plan(self):
        plan = faults.parse_plan(
            '[{"op": "drop", "link": [0, 1], "seq": 2, "tag": "offsets"},'
            ' {"op": "delay", "link": [1, 0], "seq": 1, "delay_s": 0.01}]'
        )
        assert plan.remaining == 2
        assert plan.specs[0].op == "drop"
        assert (plan.specs[0].src, plan.specs[0].dst) == (0, 1)

    def test_parse_from_file(self, tmp_path):
        p = tmp_path / "plan.json"
        p.write_text('[{"op": "close", "link": [0, 1], "seq": 1}]')
        plan = faults.parse_plan(f"@{p}")
        assert plan.specs[0].op == "close"

    @pytest.mark.parametrize(
        "bad",
        [
            '{"op": "drop"}',  # not a list
            '[{"op": "explode", "link": [0, 1], "seq": 1}]',  # bad op
            '[{"op": "drop", "link": [0], "seq": 1}]',  # bad link
            '[{"op": "drop", "link": [0, 1], "seq": 0}]',  # bad seq
            '[{"op": "drop", "link": [0, 1], "seq": 1, "x": 1}]',  # key
            '[{"op": "delay", "link": [0, 1], "seq": 1}]',  # no delay_s
        ],
    )
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            faults.parse_plan(bad)

    def test_specs_fire_once_and_match_tag(self):
        plan = faults.parse_plan(
            '[{"op": "drop", "link": [0, 1], "seq": 1, "tag": "offsets"}]'
        )
        assert plan.pop_send_fault(0, 1, 1, "scores") is None
        assert plan.pop_send_fault(0, 2, 1, "offsets") is None
        spec = plan.pop_send_fault(0, 1, 1, "offsets")
        assert spec is not None and spec.op == "drop"
        # consumed: the retried frame set goes through clean
        assert plan.pop_send_fault(0, 1, 1, "offsets") is None
        assert plan.remaining == 0

    def test_two_specs_one_frame_set_fire_on_successive_attempts(self):
        plan = faults.parse_plan(
            '[{"op": "drop", "link": [0, 1], "seq": 1},'
            ' {"op": "drop", "link": [0, 1], "seq": 1}]'
        )
        assert plan.pop_send_fault(0, 1, 1, "") is not None
        assert plan.pop_send_fault(0, 1, 1, "") is not None
        assert plan.pop_send_fault(0, 1, 1, "") is None

    def test_active_plan_caches_and_no_plan_is_none(self, monkeypatch):
        monkeypatch.delenv("PHOTON_FAULT_PLAN", raising=False)
        assert faults.active_plan() is None
        monkeypatch.setenv(
            "PHOTON_FAULT_PLAN",
            '[{"op": "drop", "link": [0, 1], "seq": 1}]',
        )
        plan = faults.active_plan()
        assert plan is not None
        assert faults.active_plan() is plan  # cached (fired state sticks)
        with pytest.raises(ValueError):
            monkeypatch.setenv("PHOTON_FAULT_PLAN", '{"op": "x"}')
            faults.active_plan()


class TestFrameProtocol:
    def _recv_frame(self, sock, crc):
        n = struct.unpack("!q", mh._recv_exact(sock, 8))[0]
        return mh._recv_frame_payload(sock, n, crc)

    def test_crc_roundtrip(self):
        payload = np.arange(7, dtype=np.float32).tobytes()
        sock = FrameSock()
        mh._send_frame(sock, payload, crc=True)
        # wire: length prefix + payload + 4-byte trailer
        assert b"".join(sock.sent) == (
            struct.pack("!q", len(payload)) + payload
            + struct.pack("!I", zlib.crc32(payload))
        )
        echo = FrameSock([payload], crc=True)
        assert self._recv_frame(echo, crc=True) == payload

    def test_crc_off_wire_bytes_identical_to_plain_framing(self):
        payload = b"abcdef"
        sock = FrameSock()
        mh._send_frame(sock, payload, crc=False)
        assert b"".join(sock.sent) == struct.pack("!q", 6) + payload

    def test_corruption_detected(self):
        payload = b"x" * 64
        bad = faults._corrupt(payload)
        assert bad != payload and len(bad) == len(payload)
        wire = FrameSock()
        wire.buf = (
            struct.pack("!q", len(bad)) + bad
            + struct.pack("!I", zlib.crc32(payload))  # trailer of GOOD
        )
        with pytest.raises(mh.LinkCorruption):
            self._recv_frame(wire, crc=True)

    def test_hello_negotiation(self, monkeypatch):
        monkeypatch.delenv("PHOTON_P2P_CRC", raising=False)
        assert mh._hello_int(3) == 3  # knob off: the PR-10 hello verbatim
        monkeypatch.setenv("PHOTON_P2P_CRC", "1")
        raw = mh._hello_int(3)
        assert mh._decode_hello(raw) == (3, mh._FRAME_PROTO_CRC)
        # a v0 receiver's mask still reads the right pid
        assert raw & 0xFFFF == 3


class TestKnobsOffWireIdentity:
    def test_exchange_wire_bytes_identical_to_pre_retry_protocol(
        self, monkeypatch
    ):
        """The acceptance anchor: with no fault plan and every knob
        unset, the framed exchange puts EXACTLY the PR-10 bytes on the
        wire — 8-byte length prefix + payload per key, no CRC trailer,
        no completion ACK — asserted byte-for-byte on a captured fake
        link."""
        import jax

        for k in ("PHOTON_P2P_CRC", "PHOTON_P2P_RETRIES",
                  "PHOTON_FAULT_PLAN", "PHOTON_P2P_HEARTBEAT_S"):
            monkeypatch.delenv(k, raising=False)
        payload_in = np.arange(2, dtype=np.float32).tobytes()
        links = {
            "send": {1: FrameSock()},
            "recv": {1: FrameSock([payload_in])},
        }
        monkeypatch.setattr(mh, "_HOST_LINKS", links)
        monkeypatch.setattr(mh, "_host_links", lambda: links)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        monkeypatch.setattr(mh, "_LINK_SEQ", {"send": {}, "recv": {}})
        arrays = {"v": np.arange(4, dtype=np.float32)}
        order = np.arange(4, dtype=np.int64)
        starts = np.asarray([0, 2, 4], np.int64)
        out = mh._host_p2p_exchange(arrays, order, starts, None, tag="t")
        np.testing.assert_array_equal(
            out["v"], np.concatenate([arrays["v"][:2], [0.0, 1.0]])
        )
        expect = arrays["v"][2:4].tobytes()
        assert b"".join(links["send"][1].sent) == (
            struct.pack("!q", len(expect)) + expect
        )
        # and the peer's stream was drained exactly — no trailing ACK
        # read attempt against the recv link
        assert links["recv"][1].buf == b""


class TestSendFaults:
    def test_drop_returns_none(self):
        spec = faults.FaultSpec(op="drop", src=0, dst=1, seq=1)
        bufs, corrupt = faults.apply_send_fault(
            spec, [b"abc"], FrameSock()
        )
        assert bufs is None and not corrupt

    def test_corrupt_is_a_wire_fault_the_crc_catches(self):
        """The corrupt op flags WIRE corruption: the frame payloads are
        untouched (the CRC trailer is computed over them), and the link
        layer flips bytes after checksumming — so the receiver's CRC
        check fires. A pre-CRC flip would be faithfully checksummed and
        arrive 'valid' (the original injection bug this test pins)."""
        spec = faults.FaultSpec(op="corrupt", src=0, dst=1, seq=1)
        bufs, corrupt = faults.apply_send_fault(
            spec, [b"aaaa", b"bbbb"], FrameSock()
        )
        assert bufs == [b"aaaa", b"bbbb"] and corrupt
        payload = b"x" * 32
        sock = FrameSock()
        mh._send_frame(sock, payload, crc=True, corrupt_wire=True)
        wire = b"".join(sock.sent)
        sent_payload = wire[8:-4]
        trailer = struct.unpack("!I", wire[-4:])[0]
        assert sent_payload != payload  # wire bytes flipped...
        assert trailer == zlib.crc32(payload)  # ...after checksumming
        assert zlib.crc32(sent_payload) != trailer  # receiver detects

    def test_close_closes_socket(self):
        sock = FrameSock()
        spec = faults.FaultSpec(op="close", src=0, dst=1, seq=1)
        bufs, corrupt = faults.apply_send_fault(spec, [b"abc"], sock)
        assert sock.closed and bufs == [b"abc"] and not corrupt
        with pytest.raises(OSError):
            sock.sendall(b"x")  # the natural error path fires next

    def test_delay_sleeps(self):
        import time

        spec = faults.FaultSpec(
            op="delay", src=0, dst=1, seq=1, delay_s=0.05
        )
        t0 = time.perf_counter()
        faults.apply_send_fault(spec, [b"abc"], FrameSock())
        assert time.perf_counter() - t0 >= 0.04


class TestRetryWrapper:
    def _call(self, monkeypatch, attempts_needed, error, retries):
        calls = {"n": 0}

        def impl(*a, **k):
            calls["n"] += 1
            if calls["n"] <= attempts_needed:
                raise error
            return {"ok": calls["n"]}

        monkeypatch.setattr(mh, "_host_p2p_exchange_impl", impl)
        monkeypatch.setattr(mh, "_reset_host_links", lambda: None)
        monkeypatch.setenv("PHOTON_P2P_RETRIES", str(retries))
        monkeypatch.setenv("PHOTON_P2P_BACKOFF_S", "0")
        return calls, lambda: mh._host_p2p_exchange(
            {}, np.zeros(0, np.int64), np.zeros(1, np.int64), tag="t"
        )

    def test_transient_fault_retried_to_success(self, monkeypatch):
        from photon_ml_tpu.obs.metrics import REGISTRY

        before = (
            REGISTRY.snapshot().get("counters", {})
            .get("p2p.retries", {}).get("value", 0.0)
        )
        calls, run = self._call(
            monkeypatch, 2, ConnectionError("reset"), retries=3
        )
        assert run() == {"ok": 3}
        assert calls["n"] == 3
        after = (
            REGISTRY.snapshot().get("counters", {})
            .get("p2p.retries", {}).get("value", 0.0)
        )
        assert after - before == 2

    def test_knob_off_raises_immediately(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 1, ConnectionError("reset"), retries=0
        )
        with pytest.raises(ConnectionError):
            run()
        assert calls["n"] == 1  # the pre-retry behavior bit-for-bit

    def test_exhaustion_raises_original_error(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 10, socket.timeout("silent"), retries=2
        )
        with pytest.raises((socket.timeout, TimeoutError)):
            run()
        assert calls["n"] == 3  # 1 + 2 retries

    def test_unreachable_peer_hardens_into_peer_lost(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 10, mh.PeerUnreachable(1, "refused"), retries=2
        )
        with pytest.raises(mh.PeerLost) as ei:
            run()
        assert ei.value.peer == 1

    def test_non_transient_error_never_retried(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 10, RuntimeError("size mismatch"), retries=5
        )
        with pytest.raises(RuntimeError):
            run()
        assert calls["n"] == 1

    def test_corruption_is_transient(self, monkeypatch):
        calls, run = self._call(
            monkeypatch, 1, mh.LinkCorruption("crc"), retries=1
        )
        assert run() == {"ok": 2}

    def test_retry_events_ride_the_sink(self, tmp_path, monkeypatch):
        import photon_ml_tpu.obs as obs

        path = obs.configure(str(tmp_path / "tel"), run_id="retry")
        try:
            calls, run = self._call(
                monkeypatch, 1, mh.LinkCorruption("crc"), retries=1
            )
            run()
            calls2, run2 = self._call(
                monkeypatch, 10, mh.PeerUnreachable(1, "x"), retries=1
            )
            with pytest.raises(mh.PeerLost):
                run2()
        finally:
            obs.shutdown()
        from photon_ml_tpu.obs.report import load_run

        records = load_run(path)
        retries = [r for r in records if r["event"] == "p2p_retry"]
        giveups = [r for r in records if r["event"] == "p2p_giveup"]
        assert len(retries) == 2 and len(giveups) == 1
        assert retries[0]["error"] == "LinkCorruption"
        assert retries[0]["tag"] == "t"
        assert retries[0]["attempt"] == 1
        assert giveups[0]["error"] == "PeerUnreachable"
        assert giveups[0]["peer"] == 1

    def test_backoff_deterministic_and_exponential(self, monkeypatch):
        monkeypatch.setenv("PHOTON_P2P_BACKOFF_S", "0.25")
        a0, a1 = mh._retry_backoff_sleep(0), mh._retry_backoff_sleep(1)
        assert a0 == mh._retry_backoff_sleep(0)  # deterministic
        assert 0.25 <= a0 < 0.375  # base * (1 + jitter<0.5)
        assert a1 >= 2 * 0.25  # exponential


class TestSendHeartbeat:
    def test_plain_path_is_sendall(self):
        sock = FrameSock()
        mh._sendall_hb(sock, b"abc")
        assert sock.sent == [b"abc"]

    def test_blocked_send_emits_direction_send_heartbeats(
        self, tmp_path, monkeypatch
    ):
        import photon_ml_tpu.obs as obs

        monkeypatch.setenv("PHOTON_P2P_TIMEOUT_S", "0.25")
        path = obs.configure(str(tmp_path / "tel"), run_id="hb")
        a, b = socket.socketpair()
        try:
            # fill a's kernel buffer so the next send blocks on the
            # never-draining peer
            a.setblocking(False)
            try:
                while True:
                    a.send(b"x" * 65536)
            except BlockingIOError:
                pass
            a.setblocking(True)
            with pytest.raises((socket.timeout, TimeoutError)):
                mh._sendall_hb(
                    a, b"y" * (1 << 22), peer=1, tag="scores",
                    heartbeat=0.05,
                )
        finally:
            obs.shutdown()
            a.close()
            b.close()
        from photon_ml_tpu.obs.report import load_run

        beats = [
            r for r in load_run(path) if r["event"] == "p2p_heartbeat"
        ]
        assert len(beats) >= 2
        assert all(r["direction"] == "send" for r in beats)
        assert all(r["peer"] == 1 and r["tag"] == "scores" for r in beats)
        assert beats[-1]["blocked_s"] >= beats[0]["blocked_s"]

    def test_blocking_mode_heartbeats_without_timeout(
        self, tmp_path, monkeypatch
    ):
        """Satellite: PHOTON_P2P_TIMEOUT_S<=0 (blocking sockets) still
        honors heartbeats — the recv polls and emits, and only data
        ends the wait (no spurious timeout raise)."""
        import threading
        import time

        import photon_ml_tpu.obs as obs

        monkeypatch.setenv("PHOTON_P2P_TIMEOUT_S", "0")
        path = obs.configure(str(tmp_path / "tel"), run_id="hb0")
        a, b = socket.socketpair()
        payload = b"z" * 8

        def late_send():
            time.sleep(0.3)
            b.sendall(payload)

        t = threading.Thread(target=late_send)
        t.start()
        try:
            got = mh._recv_exact(a, 8, peer=1, tag="offsets",
                                 heartbeat=0.05)
            assert got == payload
        finally:
            t.join()
            obs.shutdown()
            a.close()
            b.close()
        beats = [
            r for r in load_run_path(path)
            if r["event"] == "p2p_heartbeat"
        ]
        assert len(beats) >= 2  # beat while blocked, then delivered


def load_run_path(path):
    from photon_ml_tpu.obs.report import load_run

    return load_run(path)


class TestDrainErrorTelemetry:
    def test_drain_records_worker_exception(self, tmp_path, monkeypatch):
        import photon_ml_tpu.obs as obs

        pool, lock = mh._exchange_state()
        path = obs.configure(str(tmp_path / "tel"), run_id="drain")
        try:
            fut = pool.submit(self._boom)
            with lock:
                mh._PENDING_EXCHANGES.append((fut, "offsets"))
            mh.drain_async_exchanges()
        finally:
            obs.shutdown()
            mh.reset_async_exchanges()
        records = load_run_path(path)
        errs = [
            r for r in records if r["event"] == "exchange_drain_error"
        ]
        assert len(errs) == 1
        assert errs[0]["tag"] == "offsets"
        assert errs[0]["error"] == "PeerUnreachable"
        assert errs[0]["peer"] == 1

    @staticmethod
    def _boom():
        raise mh.PeerUnreachable(1, "refused")

    def test_reset_clears_pending(self):
        pool, lock = mh._exchange_state()
        fut = pool.submit(lambda: None)
        with lock:
            mh._PENDING_EXCHANGES.append((fut, "t"))
        mh.reset_async_exchanges()
        with lock:
            assert not mh._PENDING_EXCHANGES


class TestDegradedGroup:
    @pytest.fixture(autouse=True)
    def _restore(self):
        yield
        mh._DEGRADED = None

    def test_single_survivor_world(self, monkeypatch):
        monkeypatch.setattr(
            mh, "_DEGRADED", {"survivors": (0,), "rank": 0}
        )
        assert mh.effective_process_count() == 1
        assert mh.effective_process_index() == 0
        assert mh.is_output_process()
        # group-shaped helpers collapse to identities — no jax
        # collective (which would hang on the dead peer) is touched
        mh.sync_processes("after-loss")
        assert mh.allreduce_sum_host(np.asarray([3.0])) == [3.0]
        out = mh.exchange_rows(
            {"v": np.arange(3.0)}, np.zeros(3, np.int64)
        )
        np.testing.assert_array_equal(out["v"], np.arange(3.0))
        assert mh.LAST_EXCHANGE_STATS["transport"] == "local"
        tree = mh.broadcast_from_host0({"a": np.ones(2)})
        np.testing.assert_array_equal(tree["a"], np.ones(2))

    def test_rank_mapping(self, monkeypatch):
        monkeypatch.setattr(
            mh, "_DEGRADED", {"survivors": (0, 2, 3), "rank": 1}
        )
        assert mh.effective_process_count() == 3
        assert mh.effective_process_index() == 1
        assert mh._orig_pid(0) == 0
        assert mh._orig_pid(1) == 2
        assert mh._orig_pid(2) == 3
        assert not mh.is_output_process()

    def test_set_degraded_group_requires_membership(self, monkeypatch):
        import jax

        monkeypatch.setattr(jax, "process_index", lambda: 0)
        with pytest.raises(ValueError):
            mh.set_degraded_group([1, 2])


class TestMeshBuildCleanup:
    """Satellites: a partial mesh-build failure must close everything
    and leave the port rebindable, and ``_reset_host_links`` after a
    mid-frame error must leave no listening socket behind."""

    def test_partial_build_closes_sockets_and_joins_acceptor(
        self, monkeypatch
    ):
        import threading

        import jax

        monkeypatch.setattr(jax, "process_count", lambda: 3)
        monkeypatch.setattr(jax, "process_index", lambda: 0)

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        # peer addresses: freshly freed ports nothing listens on, so
        # every connect is refused
        monkeypatch.setattr(
            mh, "_HOST_ADDRS",
            {0: ("127.0.0.1", free_port()), 1: ("127.0.0.1", free_port()),
             2: ("127.0.0.1", free_port())},
        )
        threads_before = {
            t.ident for t in threading.enumerate() if t.is_alive()
        }
        with pytest.raises(mh.PeerUnreachable):
            mh._build_host_links([0, 1, 2], timeout_s=0.5)
        # no acceptor thread survives the failed build
        leaked = [
            t for t in threading.enumerate()
            if t.is_alive() and t.ident not in threads_before
        ]
        assert not leaked
        # and the recorded port is immediately rebindable: the failed
        # build closed its listener (regression guard for the leaked-
        # listener half of the satellite)
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        s.close()

    def test_rebuild_binds_recorded_port_immediately(self, monkeypatch):
        """After a teardown (mid-frame error path), rebuilding must be
        able to bind the SAME recorded port at once — a leaked listener
        would make bind fail with EADDRINUSE."""
        import jax

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        probe2 = socket.socket()
        probe2.bind(("127.0.0.1", 0))
        dead_port = probe2.getsockname()[1]
        probe2.close()
        monkeypatch.setattr(
            mh, "_HOST_ADDRS",
            {0: ("127.0.0.1", port), 1: ("127.0.0.1", dead_port)},
        )
        monkeypatch.setattr(mh, "_HOST_LINKS", None)
        for _ in range(2):  # two successive failed builds: no leak
            with pytest.raises((mh.PeerUnreachable, OSError)):
                mh._build_host_links([0, 1], timeout_s=0.3)
        mh._reset_host_links()
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", port))  # bind succeeds immediately
        s.close()


class TestReplanExcluding:
    def test_replan_matches_direct_plan_and_flags_migrations(self):
        from photon_ml_tpu.parallel.placement import (
            plan_entity_placement,
            replan_excluding,
        )

        rng = np.random.default_rng(0)
        counts = rng.integers(1, 100, size=32).astype(np.float64)
        plan4 = plan_entity_placement(counts, 4)
        new_plan, migrated = replan_excluding(
            plan4, lost_shards=[2], row_counts=counts,
            survivors=[0, 1, 3],
        )
        # the re-plan IS the deterministic 3-shard plan: every survivor
        # computes it identically with zero communication
        direct = plan_entity_placement(counts, 3)
        np.testing.assert_array_equal(new_plan.owner, direct.owner)
        # everything the dead shard owned migrated somewhere
        assert migrated[plan4.owner == 2].all()
        # migration flags compare via survivor ranks: 3 (rank 2) != 2
        rank_of = {0: 0, 1: 1, 3: 2}
        for i, m in enumerate(migrated):
            old = plan4.owner[i]
            expect = (
                old == 2 or rank_of[int(old)] != int(new_plan.owner[i])
            )
            assert bool(m) == expect, i

    def test_replan_rejects_overlap_and_empty(self):
        from photon_ml_tpu.parallel.placement import (
            plan_entity_placement,
            replan_excluding,
        )

        plan = plan_entity_placement(np.ones(4), 2)
        with pytest.raises(ValueError):
            replan_excluding(plan, [0], np.ones(4), survivors=[0, 1])
        with pytest.raises(ValueError):
            replan_excluding(plan, [0, 1], np.ones(4), survivors=[])


class TestCheckpointFingerprintCollection:
    def test_load_accepts_any_listed_fingerprint(self, tmp_path):
        import jax.numpy as jnp

        from photon_ml_tpu.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )
        from photon_ml_tpu.game.models import GameModel, RandomEffectModel
        from photon_ml_tpu.types import TaskType

        model = GameModel(
            models={
                "re": RandomEffectModel(
                    coefficients=jnp.ones((2, 3)),
                    variances=None,
                    random_effect_type="eid",
                    feature_shard_id="r",
                    task_type=TaskType.LOGISTIC_REGRESSION,
                )
            },
            task_type=TaskType.LOGISTIC_REGRESSION,
        )
        save_checkpoint(
            str(tmp_path), model, next_iteration=2, fingerprint="pre-loss"
        )
        # the degraded layout's own fingerprint alone: rejected
        assert load_checkpoint(str(tmp_path), fingerprint="degraded") is None
        # recovery passes BOTH: accepted, resumes at the stored iteration
        ck = load_checkpoint(
            str(tmp_path), fingerprint=("degraded", "pre-loss")
        )
        assert ck is not None and ck.next_iteration == 2
        # plain string still works (the pre-existing contract)
        assert load_checkpoint(str(tmp_path), fingerprint="pre-loss") is not None


# -- ISSUE 14: in-place degrade + elastic rejoin (host-side units) -----------


class TestRejoinSpecGrammar:
    def test_parse_valid_rejoin_spec(self):
        plan = faults.parse_plan(
            '[{"op": "rejoin", "link": [3, 0], "seq": 5, '
            '"tag": "offsets", "delay_s": 2.0}]'
        )
        assert plan.specs[0].op == "rejoin"
        assert plan.specs[0].delay_s == 2.0

    def test_rejoin_requires_delay(self):
        with pytest.raises(ValueError, match="rejoin requires delay_s"):
            faults.parse_plan('[{"op": "rejoin", "link": [1, 0], "seq": 1}]')

    def test_spawn_requires_cmd_env(self, monkeypatch):
        monkeypatch.delenv("PHOTON_REJOIN_CMD", raising=False)
        spec = faults.FaultSpec(op="rejoin", src=1, dst=0, seq=1, delay_s=0.1)
        with pytest.raises(RuntimeError, match="PHOTON_REJOIN_CMD"):
            faults._spawn_rejoin_child(spec)

    def test_spawn_rejects_non_list_cmd(self, monkeypatch):
        monkeypatch.setenv("PHOTON_REJOIN_CMD", '"not-a-list"')
        spec = faults.FaultSpec(op="rejoin", src=1, dst=0, seq=1, delay_s=0.1)
        with pytest.raises(RuntimeError, match="JSON list"):
            faults._spawn_rejoin_child(spec)

    def test_spawn_child_env_and_argv(self, monkeypatch):
        import json as _json
        import subprocess

        captured = {}

        def fake_popen(argv, env=None, start_new_session=None, **kw):
            captured.update(
                argv=argv, env=env, start_new_session=start_new_session
            )
            class _P:  # noqa: N801
                pass
            return _P()

        monkeypatch.setenv(
            "PHOTON_REJOIN_CMD", _json.dumps(["python", "-c", "w", "arg"])
        )
        monkeypatch.setenv("PHOTON_FAULT_PLAN", "[]")
        monkeypatch.setattr(subprocess, "Popen", fake_popen)
        spec = faults.FaultSpec(op="rejoin", src=3, dst=0, seq=1, delay_s=1.5)
        faults._spawn_rejoin_child(spec)
        # the relaunch sleeps then execs the command verbatim
        assert captured["argv"][:2] == ["/bin/sh", "-c"]
        assert "sleep 1.5" in captured["argv"][2]
        assert captured["argv"][3:] == ["python", "-c", "w", "arg"]
        # the child adopts the dying process's identity and must NOT
        # re-run the plan that killed it
        assert captured["env"]["PHOTON_REJOIN_BOOT"] == "3"
        assert "PHOTON_FAULT_PLAN" not in captured["env"]
        assert captured["start_new_session"] is True


class TestSplitBrainQuorum:
    """The roll-call split-brain predicate, enumerated. The satellite's
    named case — the exact-half fragment WITHOUT the writer — must
    abort; probing partitions also found the writer-minority bug (a
    1-of-4 writer fragment AND the 3-of-4 majority fragment both passed
    the old rule), fixed by requiring majority-or-half-with-writer."""

    def test_exact_half_without_writer_aborts(self):
        assert not mh._fragment_may_proceed([2, 3], [0, 1, 2, 3])

    def test_exact_half_with_writer_proceeds(self):
        assert mh._fragment_may_proceed([0, 1], [0, 1, 2, 3])
        # the 2-process kill drill's shape: one survivor holding the
        # writer is exactly half of a 2-group
        assert mh._fragment_may_proceed([0], [0, 1])
        assert not mh._fragment_may_proceed([1], [0, 1])

    def test_writer_minority_aborts(self):
        # the found bug: the old rule passed ANY fragment with the writer
        assert not mh._fragment_may_proceed([0], [0, 1, 2, 3])
        assert mh._fragment_may_proceed([1, 2, 3], [0, 1, 2, 3])

    def test_at_most_one_fragment_of_any_partition_proceeds(self):
        import itertools

        group = [0, 1, 2, 3]
        for r in range(len(group) + 1):
            for frag in itertools.combinations(group, r):
                other = [p for p in group if p not in frag]
                assert not (
                    mh._fragment_may_proceed(list(frag), group)
                    and mh._fragment_may_proceed(other, group)
                ), (frag, other)

    def test_rejoiner_does_not_pad_quorum(self):
        # survivors include an admitted-candidate NON-member (pid 9):
        # membership, not raw size, is what counts
        assert not mh._fragment_may_proceed([2, 9], [0, 1, 2, 3])
        assert mh._fragment_may_proceed([0, 1, 2, 9], [0, 1, 2])

    def test_expanded_rejoin_set_proceeds(self):
        assert mh._fragment_may_proceed([0, 1, 2, 3], [0, 1, 2])


class TestRingAllgatherFaultInjection:
    """The deterministic fault plan now reaches the ring collectives
    (the in-memory combine's transport) — a corrupt spec must surface
    as a DETECTED LinkCorruption on the CRC-negotiated link."""

    def _pair_links(self, crc=True):
        a01, b01 = socket.socketpair()
        a10, b10 = socket.socketpair()
        proto = {"proto": {0: 1, 1: 1}} if crc else {"proto": {}}
        links0 = {"send": {1: a01}, "recv": {1: b10}, **proto}
        links1 = {"send": {0: a10}, "recv": {0: b01}, **proto}
        return links0, links1, (a01, b01, a10, b10)

    def test_corrupt_spec_detected_by_crc(self, monkeypatch):
        import threading

        monkeypatch.setitem(mh._LINK_SEQ, "send", {})
        monkeypatch.setitem(mh._LINK_SEQ, "recv", {})
        monkeypatch.setenv(
            "PHOTON_FAULT_PLAN",
            '[{"op": "corrupt", "link": [0, 1], "seq": 1, "tag": "ring"}]',
        )
        faults.reset()
        links0, links1, socks = self._pair_links(crc=True)
        errs = {}

        def run1():
            try:
                mh._ring_allgather(
                    links1, [0, 1], 1,
                    {"w": np.arange(4, dtype=np.float32)}, "ring", None,
                )
            except BaseException as e:
                errs[1] = e

        t = threading.Thread(target=run1)
        t.start()
        try:
            mh._ring_allgather(
                links0, [0, 1], 0,
                {"w": np.ones(4, dtype=np.float32)}, "ring", None,
            )
        except BaseException as e:
            errs[0] = e
        t.join()
        for s in socks:
            s.close()
        assert isinstance(errs.get(1), mh.LinkCorruption), errs
        # the recv error names the silent/corrupt link's peer
        assert getattr(errs[1], "peer", None) == 0
        plan = faults.active_plan()
        assert plan.remaining == 0  # the spec fired exactly once

    def test_delay_spec_passes_payload_through(self, monkeypatch):
        import threading

        monkeypatch.setitem(mh._LINK_SEQ, "send", {})
        monkeypatch.setitem(mh._LINK_SEQ, "recv", {})
        monkeypatch.setenv(
            "PHOTON_FAULT_PLAN",
            '[{"op": "delay", "link": [0, 1], "seq": 1, "delay_s": 0.05}]',
        )
        faults.reset()
        links0, links1, socks = self._pair_links(crc=False)
        out = {}

        def run1():
            out[1] = mh._ring_allgather(
                links1, [0, 1], 1, {"w": np.arange(2.0)}, "ring", None
            )

        t = threading.Thread(target=run1)
        t.start()
        out[0] = mh._ring_allgather(
            links0, [0, 1], 0, {"w": np.ones(2)}, "ring", None
        )
        t.join()
        for s in socks:
            s.close()
        np.testing.assert_array_equal(out[1][0]["w"], np.ones(2))
        assert faults.active_plan().remaining == 0


class TestHealthyMeshPeerLostHardening:
    """With retries armed, a failed host collective on the FULL mesh
    hardens into PeerLost (the descent-degrade / fit-recovery signal);
    with retries unset it propagates raw — the pre-elastic behavior."""

    def _degraded_none(self, monkeypatch):
        monkeypatch.setattr(mh, "_DEGRADED", None)

    def test_hardens_with_retries_armed(self, monkeypatch):
        import jax

        self._degraded_none(monkeypatch)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        monkeypatch.setenv("PHOTON_P2P_RETRIES", "2")

        def boom():
            e = ConnectionError("link down")
            e.peer = 1
            raise e

        monkeypatch.setattr(mh, "_host_links", boom)
        with pytest.raises(mh.PeerLost) as ei:
            mh._p2p_allgather_obj("x", tag="combine")
        assert ei.value.peer == 1

    def test_raw_error_without_retries(self, monkeypatch):
        import jax

        self._degraded_none(monkeypatch)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        monkeypatch.delenv("PHOTON_P2P_RETRIES", raising=False)

        def boom():
            raise ConnectionError("link down")

        monkeypatch.setattr(mh, "_host_links", boom)
        with pytest.raises(ConnectionError):
            mh._p2p_allgather_obj("x", tag="combine")


class TestMeshCacheAndRejoinBootstrap:
    @pytest.fixture(autouse=True)
    def _restore_identity(self):
        yield
        mh._REJOIN_IDENTITY = None

    def test_persist_and_bootstrap_roundtrip(self, tmp_path, monkeypatch):
        path = str(tmp_path / "mesh.json")
        monkeypatch.setenv("PHOTON_MESH_CACHE", path)
        monkeypatch.setattr(
            mh, "_HOST_ADDRS",
            {0: ("127.0.0.1", 4100), 1: ("127.0.0.1", 4101),
             2: ("10.0.0.3", 4102)},
        )
        import jax

        monkeypatch.setattr(jax, "process_count", lambda: 3)
        mh._maybe_persist_mesh_addrs()
        # a fresh interpreter (simulated: cleared globals) adopts its
        # original identity from the cache
        monkeypatch.setattr(mh, "_HOST_ADDRS", None)
        ident = mh.bootstrap_rejoin(pid=2, path=path)
        assert ident == {"pid": 2, "world": 3}
        assert mh._HOST_ADDRS[2] == ("10.0.0.3", 4102)
        assert mh.original_process_index() == 2
        assert mh.original_process_count() == 3
        # pre-admission a rejoiner reports its original identity, so it
        # can never mistake itself for a healthy 1-process world (or
        # the writer, unless it really was process 0)
        assert mh.effective_process_index() == 2
        assert mh.effective_process_count() == 3
        assert not mh.is_output_process()

    def test_bootstrap_rejects_unknown_pid(self, tmp_path, monkeypatch):
        path = str(tmp_path / "mesh.json")
        monkeypatch.setenv("PHOTON_MESH_CACHE", path)
        monkeypatch.setattr(mh, "_HOST_ADDRS", {0: ("127.0.0.1", 4100)})
        import jax

        monkeypatch.setattr(jax, "process_count", lambda: 1)
        mh._maybe_persist_mesh_addrs()
        with pytest.raises(RuntimeError, match="no address for process 7"):
            mh.bootstrap_rejoin(pid=7, path=path)

    def test_bootstrap_requires_cache_path(self, monkeypatch):
        monkeypatch.delenv("PHOTON_MESH_CACHE", raising=False)
        with pytest.raises(RuntimeError, match="PHOTON_MESH_CACHE"):
            mh.bootstrap_rejoin(pid=1)

    def test_sink_shard_index_follows_rejoin_identity(
        self, tmp_path, monkeypatch
    ):
        from photon_ml_tpu.obs import sink as obs_sink

        path = str(tmp_path / "mesh.json")
        monkeypatch.setenv("PHOTON_MESH_CACHE", path)
        monkeypatch.setattr(
            mh, "_HOST_ADDRS",
            {0: ("127.0.0.1", 4100), 1: ("127.0.0.1", 4101)},
        )
        import jax

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        mh._maybe_persist_mesh_addrs()
        mh.bootstrap_rejoin(pid=1, path=path)
        assert obs_sink._process_index() == 1
        assert obs_sink._process_count() == 2


class TestRejoinRendezvous:
    """The probe → invite → wait handshake over real loopback sockets,
    single process: the rejoiner answers probes, ignores a stray mesh
    hello (the degrade-roll-call race), and returns the invite."""

    @pytest.fixture(autouse=True)
    def _restore_identity(self):
        saved = mh._HOST_ADDRS
        yield
        mh._REJOIN_IDENTITY = None
        mh._HOST_ADDRS = saved

    def test_probe_invite_wait_roundtrip(self):
        import threading

        srv_probe = socket.socket()
        srv_probe.bind(("127.0.0.1", 0))
        port = srv_probe.getsockname()[1]
        srv_probe.close()
        mh._HOST_ADDRS = {
            0: ("127.0.0.1", 1), 3: ("127.0.0.1", port),
        }
        mh._REJOIN_IDENTITY = {"pid": 3, "world": 4}
        out = {}

        def wait():
            out["invite"] = mh.rejoin_wait(window_s=10.0)

        t = threading.Thread(target=wait)
        t.start()
        try:
            # a stray NON-invite dial first (a racing roll-call build):
            # the waiter must ignore it and keep listening
            deadline = __import__("time").monotonic() + 5
            while True:
                try:
                    s = socket.create_connection(
                        ("127.0.0.1", port), timeout=0.5
                    )
                    break
                except OSError:
                    if __import__("time").monotonic() > deadline:
                        raise
            s.sendall(struct.pack("!i", 0 | (1 << 16)))  # mesh hello v1
            s.close()
            # now the real probe + invite (the survivor side's calls)
            mh._REJOIN_IDENTITY = None  # act as survivor pid 0 for send
            import jax

            present = []
            deadline = __import__("time").monotonic() + 5
            while not present:
                present = mh.probe_rejoiners([3], window_s=0.0)
                if __import__("time").monotonic() > deadline:
                    break
            assert present == [3]
            invited = mh.send_rejoin_invites(
                present, candidates=[0, 1, 3], survivors=[0, 1]
            )
            assert invited == [3]
        finally:
            t.join(timeout=10)
        assert out["invite"] == {
            "candidates": [0, 1, 3], "survivors": [0, 1]
        }

    def test_wait_times_out_uninvited(self):
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        srv.close()
        mh._HOST_ADDRS = {2: ("127.0.0.1", port)}
        mh._REJOIN_IDENTITY = {"pid": 2, "world": 3}
        assert mh.rejoin_wait(window_s=0.2) is None

    def test_probe_refused_is_absent(self):
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        srv.close()  # nothing listens
        mh._HOST_ADDRS = {1: ("127.0.0.1", port)}
        assert mh.probe_rejoiners([1], window_s=0.0) == []


class TestExpandedReplan:
    def test_empty_lost_set_may_expand(self):
        from photon_ml_tpu.parallel.placement import (
            plan_entity_placement,
            replan_excluding,
        )

        counts = np.asarray([5.0, 4.0, 3.0, 2.0, 1.0, 1.0])
        plan3 = plan_entity_placement(counts, 3)
        new_plan, migrated = replan_excluding(
            plan3, [], counts, survivors=range(4)
        )
        direct = plan_entity_placement(counts, 4)
        np.testing.assert_array_equal(new_plan.owner, direct.owner)
        # everything the joining shard received counts as migrated back
        joined = new_plan.owner == 3
        assert joined.any() and migrated[joined].all()

    def test_non_empty_lost_still_validates_range(self):
        from photon_ml_tpu.parallel.placement import (
            plan_entity_placement,
            replan_excluding,
        )

        plan = plan_entity_placement(np.ones(4), 2)
        with pytest.raises(ValueError, match="survivor 5 outside"):
            replan_excluding(plan, [0], np.ones(4), survivors=[1, 5])


class TestDescentDegradeKnob:
    def test_default_off_and_strict_parse(self, monkeypatch):
        from photon_ml_tpu.game import descent

        monkeypatch.delenv("PHOTON_DESCENT_DEGRADE", raising=False)
        assert not descent.descent_degrade_enabled()
        monkeypatch.setenv("PHOTON_DESCENT_DEGRADE", "1")
        assert descent.descent_degrade_enabled()
        monkeypatch.setenv("PHOTON_DESCENT_DEGRADE", "yes")
        with pytest.raises(ValueError):
            descent.descent_degrade_enabled()

    def test_rejoin_knobs_strict_parse(self, monkeypatch):
        monkeypatch.setenv("PHOTON_REJOIN", "zz")
        with pytest.raises(ValueError):
            mh.rejoin_enabled()
        monkeypatch.setenv("PHOTON_REJOIN", "1")
        assert mh.rejoin_enabled()
        monkeypatch.setenv("PHOTON_REJOIN_WINDOW_S", "2.5")
        assert mh.rejoin_window_s() == 2.5


class _FakeReCoord:
    """Minimal coordinate for descent-level drills: deterministic solve
    (coefficients = a pure function of the offsets), REAL
    RandomEffectModel outputs so checkpointing works, and an optional
    injected PeerLost at the n-th train call."""

    coordinate_id = "c"

    def __init__(self, n_rows, fail_at_call=None, fail_always=False):
        import jax.numpy as jnp

        self.n = n_rows
        self.calls = 0
        self.fail_at_call = fail_at_call
        self.fail_always = fail_always
        self._jnp = jnp

    def train(self, offsets, initial=None):
        from photon_ml_tpu.game.models import RandomEffectModel
        from photon_ml_tpu.types import TaskType

        self.calls += 1
        if self.fail_always or (
            self.fail_at_call is not None and self.calls == self.fail_at_call
        ):
            if not self.fail_always:
                self.fail_at_call = None  # fire once
            raise mh.PeerLost(1, "injected descent loss")
        jnp = self._jnp
        w = jnp.mean(offsets) * 0.5 + 1.0
        sub = RandomEffectModel(
            coefficients=jnp.full((2, 3), w),
            variances=None,
            random_effect_type="eid",
            feature_shard_id="r",
            task_type=TaskType.LOGISTIC_REGRESSION,
        )
        return sub, {"call": self.calls}

    def score(self, sub):
        jnp = self._jnp
        return jnp.full((self.n,), jnp.mean(sub.coefficients))


def _tiny_descent(coord):
    import jax.numpy as jnp

    from photon_ml_tpu.game.data import GameBatch
    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.types import TaskType

    n = 4
    batch = GameBatch(
        labels=jnp.zeros(n), offsets=jnp.zeros(n),
        weights=jnp.ones(n), features={}, id_tags={},
    )
    return CoordinateDescent(
        coordinates={"c": coord}, batch=batch,
        task_type=TaskType.LOGISTIC_REGRESSION,
    )


class TestDescentDegradeInPlace:
    """The PHOTON_DESCENT_DEGRADE handler at the unit level: knob-off
    keeps the abort message, knob-on rolls back to the start-of-
    iteration snapshot, shrinks the group and finishes run() with a
    result BITWISE equal to an uninterrupted run; an all-alive roll
    call retries the iteration with a bounded budget."""

    def _arm(self, monkeypatch, survivors, world=2):
        calls = {"degraded": None}
        monkeypatch.setattr(mh, "roll_call", lambda **kw: list(survivors))
        monkeypatch.setattr(mh, "original_process_count", lambda: world)
        monkeypatch.setattr(mh, "degraded_group", lambda: None)
        monkeypatch.setattr(
            mh, "set_degraded_group",
            lambda s: calls.__setitem__("degraded", list(s)),
        )
        monkeypatch.setattr(mh, "reset_async_exchanges", lambda: None)
        return calls

    def test_knob_off_keeps_abort_message(self, monkeypatch):
        monkeypatch.delenv("PHOTON_DESCENT_DEGRADE", raising=False)
        cd = _tiny_descent(_FakeReCoord(4, fail_at_call=2))
        with pytest.raises(RuntimeError, match="cannot degrade in place"):
            cd.run(["c"], 3)

    def test_degrades_in_place_and_matches_clean_run(self, monkeypatch):
        import numpy as np

        monkeypatch.setenv("PHOTON_DESCENT_DEGRADE", "1")
        calls = self._arm(monkeypatch, survivors=[0], world=2)
        clean = _tiny_descent(_FakeReCoord(4)).run(["c"], 3)
        faulted_coord = _FakeReCoord(4, fail_at_call=2)
        res = _tiny_descent(faulted_coord).run(["c"], 3)
        # run() returned normally, the group shrank, and the
        # interrupted iteration was rolled back + re-run: one extra
        # train call, same results bitwise
        assert calls["degraded"] == [0]
        assert faulted_coord.calls == 4  # 3 iterations + 1 rolled back
        np.testing.assert_array_equal(
            np.asarray(res.model.models["c"].coefficients),
            np.asarray(clean.model.models["c"].coefficients),
        )
        np.testing.assert_array_equal(
            np.asarray(res.training_scores["c"]),
            np.asarray(clean.training_scores["c"]),
        )
        # trackers rolled back: exactly one per completed iteration
        assert [t["call"] for t in res.trackers["c"]] == [1, 3, 4]

    def test_flap_retries_are_bounded(self, monkeypatch):
        monkeypatch.setenv("PHOTON_DESCENT_DEGRADE", "1")
        # roll call finds everyone alive -> iteration retried, bounded
        self._arm(monkeypatch, survivors=[0, 1], world=2)
        cd = _tiny_descent(_FakeReCoord(4, fail_always=True))
        with pytest.raises(RuntimeError, match="links flapped"):
            cd.run(["c"], 2)

    def test_mesh_blocker_falls_back_to_abort(self, monkeypatch):
        monkeypatch.setenv("PHOTON_DESCENT_DEGRADE", "1")
        self._arm(monkeypatch, survivors=[0], world=2)
        coord = _FakeReCoord(4, fail_at_call=1)
        coord._degrade_blocker = lambda: "coordinate 'c' spans the mesh"
        cd = _tiny_descent(coord)
        with pytest.raises(RuntimeError, match="cannot degrade in place"):
            cd.run(["c"], 2)

    def test_mesh_blocker_still_retries_a_flap(self, monkeypatch):
        # review-found: the degradability gate must run only after the
        # roll call CONFIRMS a loss — a link flap needs no degradation,
        # so a mesh-spanning coordinate must not turn it into the abort
        monkeypatch.setenv("PHOTON_DESCENT_DEGRADE", "1")
        self._arm(monkeypatch, survivors=[0, 1], world=2)  # all alive
        coord = _FakeReCoord(4, fail_at_call=1)
        coord._degrade_blocker = lambda: "coordinate 'c' spans the mesh"
        cd = _tiny_descent(coord)
        res = cd.run(["c"], 2)  # the flap is absorbed, run completes
        assert coord.calls == 3  # failed call + retried it-0 + it-1
        assert len(res.trackers["c"]) == 2

    def test_validation_mesh_blocks_degrade(self, monkeypatch):
        # review-found: per-visit validation scores/evaluates over the
        # DESCENT-level device mesh — the dead process's devices cannot
        # leave it in-process any more than a coordinate's can, so a
        # confirmed loss must abort even when every coordinate degrades
        monkeypatch.setenv("PHOTON_DESCENT_DEGRADE", "1")
        self._arm(monkeypatch, survivors=[0], world=2)
        cd = _tiny_descent(_FakeReCoord(4, fail_at_call=1))
        cd.mesh = object()
        cd.validation_batch = cd.batch
        cd.evaluators = ["AUC"]
        with pytest.raises(RuntimeError, match="cannot degrade in place"):
            cd.run(["c"], 2)


class TestDescentResumeFingerprints:
    """The descent checkpoint-resume satellite: ``run`` accepts a
    fingerprint COLLECTION, so a pre-loss layout's checkpoint resumes
    under a degraded layout's differing fingerprint."""

    def _save_pre_loss(self, d, batch):
        import numpy as np

        from photon_ml_tpu.checkpoint import batch_digest, save_checkpoint
        from photon_ml_tpu.game.models import GameModel
        from photon_ml_tpu.types import TaskType

        digest = batch_digest(batch.labels, batch.weights)
        save_checkpoint(
            str(d),
            GameModel(models={}, task_type=TaskType.LOGISTIC_REGRESSION),
            next_iteration=1,
            fingerprint="pre-loss-layout",
            scores={"c": np.zeros(4, np.float32)},
            total=np.zeros(4, np.float32),
            data_digest=digest,
        )

    def test_resume_collection_accepts_pre_loss_checkpoint(self, tmp_path):
        coord = _FakeReCoord(4)
        cd = _tiny_descent(coord)
        self._save_pre_loss(tmp_path, cd.batch)
        cd.run(
            ["c"], 2, checkpoint_dir=str(tmp_path),
            checkpoint_fingerprint="degraded-layout",
            resume_fingerprints=["pre-loss-layout"],
        )
        assert coord.calls == 1  # resumed at iteration 1 of 2

    def test_without_collection_restarts_from_scratch(self, tmp_path):
        coord = _FakeReCoord(4)
        cd = _tiny_descent(coord)
        self._save_pre_loss(tmp_path, cd.batch)
        cd.run(
            ["c"], 2, checkpoint_dir=str(tmp_path),
            checkpoint_fingerprint="degraded-layout",
        )
        assert coord.calls == 2  # fingerprint mismatch -> full retrain

    def test_peek_fingerprint_reads_without_arrays(self, tmp_path):
        from photon_ml_tpu.checkpoint import peek_fingerprint

        coord = _FakeReCoord(4)
        cd = _tiny_descent(coord)
        assert peek_fingerprint(str(tmp_path)) is None
        self._save_pre_loss(tmp_path, cd.batch)
        assert peek_fingerprint(str(tmp_path)) == "pre-loss-layout"


class TestEagerCheckpointFreshness:
    """Review-found regression: the eager visit loop's checkpoint must
    carry the CURRENT iteration's model/total — after the body moved
    into ``_run_one_iteration`` (the degrade transaction), a closure
    over ``_run_inner``'s bindings read the PREVIOUS iteration's model,
    so every checkpoint paired fresh scores with a stale model."""

    def test_resume_matches_uninterrupted_run(self, tmp_path):
        clean = _tiny_descent(_FakeReCoord(4)).run(["c"], 3)
        cd = _tiny_descent(_FakeReCoord(4))
        cd.run(
            ["c"], 2, checkpoint_dir=str(tmp_path),
            checkpoint_fingerprint="f",
        )
        resumed = _tiny_descent(_FakeReCoord(4)).run(
            ["c"], 3, checkpoint_dir=str(tmp_path),
            checkpoint_fingerprint="f",
        )
        np.testing.assert_array_equal(
            np.asarray(resumed.model.models["c"].coefficients),
            np.asarray(clean.model.models["c"].coefficients),
        )
        np.testing.assert_array_equal(
            np.asarray(resumed.training_scores["c"]),
            np.asarray(clean.training_scores["c"]),
        )


class TestRejoinRollCallShrinks:
    """Review-found regression: a rejoin roll call that DROPS a
    survivor (the probed rejoiner vanished and a survivor died between
    probe and roll call) must re-plan + resume like a degrade — the
    in-flight visit's shard plans are keyed on the old rank mapping."""

    def _trainer(self):
        from photon_ml_tpu.config import (
            GameTrainingConfig,
            OptimizationConfig,
            OptimizerConfig,
            RandomEffectCoordinateConfig,
            RegularizationContext,
        )
        from photon_ml_tpu.game.streaming import StreamedGameTrainer
        from photon_ml_tpu.types import (
            RegularizationType,
            TaskType,
            VarianceComputationType,
        )

        opt = OptimizationConfig(
            optimizer=OptimizerConfig(max_iterations=2, tolerance=1e-9),
            regularization=RegularizationContext(RegularizationType.L2),
            regularization_weight=1.0,
        )
        cfg = GameTrainingConfig(
            task_type=TaskType.LOGISTIC_REGRESSION,
            coordinate_update_sequence=("per_entity",),
            coordinate_descent_iterations=1,
            fixed_effect_coordinates={},
            random_effect_coordinates={
                "per_entity": RandomEffectCoordinateConfig(
                    random_effect_type="eid", feature_shard_id="r",
                    optimization=opt,
                )
            },
            variance_computation=VarianceComputationType.SIMPLE,
        )
        return StreamedGameTrainer(
            cfg, chunk_rows=64, multihost=True,
            num_entities={"eid": 4}, sharded_checkpoints=False,
        )

    def test_dropped_survivor_forces_replan_resume(self, monkeypatch):
        from photon_ml_tpu.game.streaming import _RejoinResume
        from photon_ml_tpu.obs.metrics import REGISTRY

        trainer = self._trainer()
        trainer._last_fingerprint = "pre-shrink"
        trainer._last_row_base = 7
        monkeypatch.setenv("PHOTON_REJOIN", "1")
        monkeypatch.setattr(
            mh, "degraded_group",
            lambda: {"survivors": (0, 1, 2), "rank": 0},
        )
        monkeypatch.setattr(mh, "original_process_count", lambda: 4)
        monkeypatch.setattr(mh, "rejoin_window_s", lambda: 0.0)
        monkeypatch.setattr(mh, "effective_process_index", lambda: 0)
        monkeypatch.setattr(mh, "effective_process_count", lambda: 3)
        monkeypatch.setattr(mh, "probe_rejoiners", lambda lost, w: [3])
        monkeypatch.setattr(mh, "broadcast_from_host0", lambda x: x)
        monkeypatch.setattr(
            mh, "send_rejoin_invites", lambda *a, **kw: [3]
        )
        degraded_to = []
        monkeypatch.setattr(
            mh, "set_degraded_group", lambda s: degraded_to.append(list(s))
        )
        # survivor 2 AND the probed rejoiner both die before the roll
        # call: the agreed group shrinks past the current survivor set
        monkeypatch.setattr(mh, "roll_call", lambda **kw: [0, 1])
        before = (
            REGISTRY.snapshot()
            .get("counters", {})
            .get("fleet.recoveries", {})
            .get("value", 0.0)
        )
        with pytest.raises(_RejoinResume):
            trainer._maybe_admit_rejoin({}, iteration=0, ci=0)
        assert degraded_to == [[0, 1]]
        assert "pre-shrink" in trainer.resume_fingerprints
        # the foreign-resume row base re-anchors to the layout that
        # wrote any mid-degrade checkpoint, like _prepare_recovery's
        assert trainer.resume_row_base == 7
        after = (
            REGISTRY.snapshot()
            .get("counters", {})
            .get("fleet.recoveries", {})
            .get("value", 0.0)
        )
        assert after == before + 1.0

    def test_admitted_rejoin_reanchors_row_base(self, monkeypatch):
        from photon_ml_tpu.game.streaming import _RejoinResume

        trainer = self._trainer()
        trainer._last_fingerprint = "degraded-layout"
        trainer._last_row_base = 11
        monkeypatch.setenv("PHOTON_REJOIN", "1")
        monkeypatch.setattr(
            mh, "degraded_group",
            lambda: {"survivors": (0, 1, 2), "rank": 0},
        )
        monkeypatch.setattr(mh, "original_process_count", lambda: 4)
        monkeypatch.setattr(mh, "original_process_index", lambda: 0)
        monkeypatch.setattr(mh, "rejoin_window_s", lambda: 0.0)
        monkeypatch.setattr(mh, "effective_process_index", lambda: 0)
        monkeypatch.setattr(mh, "effective_process_count", lambda: 3)
        monkeypatch.setattr(mh, "probe_rejoiners", lambda lost, w: [3])
        monkeypatch.setattr(mh, "broadcast_from_host0", lambda x: x)
        monkeypatch.setattr(
            mh, "send_rejoin_invites", lambda *a, **kw: [3]
        )
        monkeypatch.setattr(mh, "set_degraded_group", lambda s: None)
        monkeypatch.setattr(mh, "roll_call", lambda **kw: [0, 1, 2, 3])
        monkeypatch.setattr(
            mh, "allgather_obj_p2p",
            lambda payload, tag=None, **kw: [payload, None, None, None],
        )
        with pytest.raises(_RejoinResume):
            trainer._maybe_admit_rejoin({}, iteration=1, ci=0)
        # the survivor accepts its own broadcast allow-list AND
        # re-anchors the foreign row base to the degraded layout that
        # wrote any mid-degrade checkpoint
        assert "degraded-layout" in trainer.resume_fingerprints
        assert trainer.resume_row_base == 11

    def test_admit_and_drop_in_one_round_roots_at_live_survivor(
        self, monkeypatch
    ):
        # review-found: roll_call supports admitting a rejoiner and
        # dropping a freshly-dead survivor in ONE round — the ctrl
        # exchange must root at the lowest LIVE survivor, not at the
        # stale survivor list's minimum (a dead process), which raised
        # ValueError('0 is not in list') fleet-wide
        from photon_ml_tpu.game.streaming import _RejoinResume

        trainer = self._trainer()
        trainer._last_fingerprint = "degraded-layout"
        trainer._last_row_base = 5
        monkeypatch.setenv("PHOTON_REJOIN", "1")
        monkeypatch.setattr(
            mh, "degraded_group",
            lambda: {"survivors": (0, 1, 2), "rank": 1},
        )
        monkeypatch.setattr(mh, "original_process_count", lambda: 4)
        monkeypatch.setattr(mh, "original_process_index", lambda: 1)
        monkeypatch.setattr(mh, "rejoin_window_s", lambda: 0.0)
        monkeypatch.setattr(mh, "effective_process_index", lambda: 1)
        monkeypatch.setattr(mh, "effective_process_count", lambda: 3)
        monkeypatch.setattr(mh, "probe_rejoiners", lambda lost, w: [])
        monkeypatch.setattr(
            mh, "broadcast_from_host0",
            lambda x: np.asarray([3], np.int64),
        )
        monkeypatch.setattr(
            mh, "send_rejoin_invites", lambda *a, **kw: [3]
        )
        monkeypatch.setattr(mh, "set_degraded_group", lambda s: None)
        # process 0 dies between the probe broadcast and the roll call:
        # the agreed group admits 3 AND drops 0 in the same round
        monkeypatch.setattr(mh, "roll_call", lambda **kw: [1, 2, 3])
        sent = {}

        def fake_allgather(payload, tag=None, **kw):
            sent["payload"] = payload
            return [payload, None, None]

        monkeypatch.setattr(mh, "allgather_obj_p2p", fake_allgather)
        with pytest.raises(_RejoinResume):
            trainer._maybe_admit_rejoin({}, iteration=2, ci=0)
        # we (pid 1) are the lowest LIVE survivor, so we rooted the
        # ctrl payload; the anchors registered locally too
        assert sent["payload"]["fingerprints"] == ["degraded-layout"]
        assert "degraded-layout" in trainer.resume_fingerprints
        assert trainer.resume_row_base == 5

    def test_vanished_rejoiner_alone_keeps_training(self, monkeypatch):
        trainer = self._trainer()
        monkeypatch.setenv("PHOTON_REJOIN", "1")
        monkeypatch.setattr(
            mh, "degraded_group",
            lambda: {"survivors": (0, 1, 2), "rank": 0},
        )
        monkeypatch.setattr(mh, "original_process_count", lambda: 4)
        monkeypatch.setattr(mh, "rejoin_window_s", lambda: 0.0)
        monkeypatch.setattr(mh, "effective_process_index", lambda: 0)
        monkeypatch.setattr(mh, "effective_process_count", lambda: 3)
        monkeypatch.setattr(mh, "probe_rejoiners", lambda lost, w: [3])
        monkeypatch.setattr(mh, "broadcast_from_host0", lambda x: x)
        monkeypatch.setattr(
            mh, "send_rejoin_invites", lambda *a, **kw: [3]
        )
        monkeypatch.setattr(mh, "set_degraded_group", lambda s: None)
        # the roll call re-agrees on exactly the current group: the
        # vanished rejoiner costs nothing, training continues in place
        monkeypatch.setattr(mh, "roll_call", lambda **kw: [0, 1, 2])
        assert trainer._maybe_admit_rejoin({}, iteration=0, ci=0) is None
