"""Test harness setup.

Parity with the reference's test strategy (SURVEY.md §4): the reference runs
distributed code in local-mode Spark; we run collective code on a virtual
8-device CPU mesh via ``xla_force_host_platform_device_count``, so every
``shard_map``/psum code path executes in CI without TPU hardware. Must run
before the first ``import jax`` anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Analytic device-cost capture (obs/devcost) AOT-compiles every fresh
# executable a second time while a telemetry sink is active. The tier-1
# suite sits NEAR its wall-clock budget (1260 s — see ROADMAP's tier-1
# line), so the suite pins capture OFF and
# tests that exercise it (tests/test_devcost.py) opt back in by clearing
# or overriding this variable.
os.environ.setdefault("PHOTON_DEVCOST", "0")
# Double precision in tests: finite-difference derivative checks need it.
os.environ["JAX_ENABLE_X64"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache (tier-1 runtime, measured on the 1-core
# CI box): the suite's dominant idiom is "reference arm vs knob arm,
# asserted bitwise", which compiles the SAME HLO two or more times per
# test — and the suite is compile-dominated, not execution-dominated (a
# warm cache cuts representative modules ~57%; intra-run dedupe alone cuts
# them ~18% cold). The cache key is content-addressed over the HLO and the
# jax/XLA versions, so a code change is a clean miss, never a stale hit,
# and a cache hit returns byte-identical executables — bitwise parity
# assertions are unaffected. min-compile-time 0 matters: the duplicate
# mass is many SMALL programs, which the 1 s default would skip.
# ``setdefault`` so an outer environment (or a test of the cache itself)
# still wins; gloo loopback worker subprocesses inherit the dir and dedupe
# their identical per-process programs against it too.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(
        __import__("tempfile").gettempdir(), "photon_xla_test_cache"
    ),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# The environment's sitecustomize registers an 'axon' TPU-relay PJRT plugin in
# every interpreter and forces jax_platforms=axon via jax.config (so env vars
# set here are too late). Initializing that backend blocks on the relay
# socket, hanging the whole suite. Undo both before the first backend init:
import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", "cpu")
# sitecustomize imported jax before this file ran, so the cache env vars
# set above were bound too late for THIS process — re-apply them through
# jax.config (reading the env so an outer override still wins). Worker
# subprocesses run sitecustomize after the env is set, so env alone
# suffices there.
jax.config.update(
    "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
)
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
)
jax.config.update(
    "jax_persistent_cache_min_entry_size_bytes",
    int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]),
)
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)

import numpy as np
import pytest


def pytest_collection_modifyitems(config, items):
    """Run ``kernel``-marked tests (the Pallas interpret-mode parity
    block — the suite's biggest time cost) LAST, preserving relative
    order on both sides of the split. On a box where the tier-1
    wall-clock budget truncates the run, the cut then lands on kernel
    parity coverage (selectable separately via ``-m kernel``) instead of
    on unrelated tests mid-suite; on a fast box every test still runs."""
    items.sort(key=lambda it: it.get_closest_marker("kernel") is not None)


# Tier-1 runtime guard (the suite sits NEAR its wall-clock budget —
# 1260 s, see ROADMAP's tier-1 line): every
# kernel-marked test must trace its Pallas kernels at retuned-DOWN
# constants — interpret-mode cost scales with the DMA-step carve, and one
# test silently instantiating default-size tiles (GROUPS_PER_STEP=32 x
# SEGMENTS_PER_DMA=4 = 16K-nnz steps) costs ~an order of magnitude more
# than the 8x2 test discipline. Collection cannot see what a test will
# build, so the fixture below (a) RETUNES kernel-marked tests down to the
# 8x2 carve by default (tests may monkeypatch further; the layout builder
# and kernel read the constants at call time, so both sides track), and
# (b) wraps the layout builder to fail AT THE BUILD, with an actionable
# message, if a test restores a default-size carve. Run the tier-1
# command with ``--durations=15`` (see ROADMAP) to spot runtime creep.
_KERNEL_TEST_MAX_STEP_NNZ = 8 * 2 * 128  # the retuned-down 8x2 carve


@pytest.fixture(autouse=True)
def _kernel_test_constants_guard(request):
    if request.node.get_closest_marker("kernel") is None:
        yield
        return
    import photon_ml_tpu.ops.sparse_tiled as st

    orig_build = st.build_write_major_layout
    orig_constants = (st.GROUPS_PER_STEP, st.SEGMENTS_PER_DMA)
    st.GROUPS_PER_STEP, st.SEGMENTS_PER_DMA = 8, 2

    def guarded(*args, **kwargs):
        # groups_per_step is parameter #6 of build_write_major_layout —
        # resolve positional and keyword spellings alike, or a positional
        # call would silently bypass the guard
        gps = kwargs.get("groups_per_step")
        if gps is None and len(args) > 5:
            gps = args[5]
        if gps is None:
            gps = st.GROUPS_PER_STEP
        step_nnz = gps * st.SEGMENTS_PER_DMA * st.GROUP
        if step_nnz > _KERNEL_TEST_MAX_STEP_NNZ:
            pytest.fail(
                f"kernel-marked test built a tile layout at default-size "
                f"constants (GROUPS_PER_STEP={gps} x SEGMENTS_PER_DMA="
                f"{st.SEGMENTS_PER_DMA} = {step_nnz}-nnz DMA steps > "
                f"{_KERNEL_TEST_MAX_STEP_NNZ}). Interpret-mode kernel cost "
                f"scales with the step carve and the tier-1 suite sits "
                f"near its wall-clock budget: keep the retuned-down constants "
                f"this fixture installs (or monkeypatch smaller), or drop "
                f"the kernel marker if no kernel is traced."
            )
        return orig_build(*args, **kwargs)

    st.build_write_major_layout = guarded
    try:
        yield
    finally:
        st.build_write_major_layout = orig_build
        st.GROUPS_PER_STEP, st.SEGMENTS_PER_DMA = orig_constants


@pytest.fixture
def rng():
    return np.random.default_rng(42)
