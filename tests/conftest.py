"""Test harness setup.

Parity with the reference's test strategy (SURVEY.md §4): the reference runs
distributed code in local-mode Spark; we run collective code on a virtual
8-device CPU mesh via ``xla_force_host_platform_device_count``, so every
``shard_map``/psum code path executes in CI without TPU hardware. Must run
before the first ``import jax`` anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Double precision in tests: finite-difference derivative checks need it.
os.environ["JAX_ENABLE_X64"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize registers an 'axon' TPU-relay PJRT plugin in
# every interpreter and forces jax_platforms=axon via jax.config (so env vars
# set here are too late). Initializing that backend blocks on the relay
# socket, hanging the whole suite. Undo both before the first backend init:
import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", "cpu")
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)

import numpy as np
import pytest


def pytest_collection_modifyitems(config, items):
    """Run ``kernel``-marked tests (the Pallas interpret-mode parity
    block — the suite's biggest time cost) LAST, preserving relative
    order on both sides of the split. On a box where the tier-1
    wall-clock budget truncates the run, the cut then lands on kernel
    parity coverage (selectable separately via ``-m kernel``) instead of
    on unrelated tests mid-suite; on a fast box every test still runs."""
    items.sort(key=lambda it: it.get_closest_marker("kernel") is not None)


@pytest.fixture
def rng():
    return np.random.default_rng(42)
