"""Feature-range-sharded fixed-effect solves (PHOTON_FE_SHARD).

Coverage tiers, cheapest first (tier-1 sits near its wall-clock budget):

- partitioner property tests — pure host arithmetic on
  ``data/index_map.plan_feature_ranges`` (coverage/disjointness,
  determinism, weight modes, pathological histograms, strict knob parse);
- ``_fe_restrict_chunks`` structural properties — the per-range chunk
  restriction partitions the live nonzeros exactly and SHARES
  label/offset/weight storage with the originals;
- knob-off bitwise identity — ``PHOTON_FE_SHARD=0`` and unset produce
  byte-identical results across all four streamed consumers (objective
  contracts, both optimizers, method + module scoring), and the P=1
  sharded path (identity restriction) matches the replicated path
  bitwise on padding-free chunks;
- gloo loopback parity at P∈{2, 4} — sharded coefficients/objective/
  scores match the single-process reference per the stated contract
  (gradient segments exact; margins under the fixed-ascending-range
  reduction reassociate in f32), with both process groups spawned
  CONCURRENTLY so the suite pays one jax-import wall, not two;
- one kernel-marked tiled test — an ``fe_range`` column-sliced layout's
  matvec/rmatvec against the dense partial, under the 8x2 retuned carve
  the conftest fixture installs.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.config import OptimizerConfig
from photon_ml_tpu.data.index_map import (
    FeatureRangePlan,
    fe_shard_enabled,
    fe_split_weight,
    plan_feature_ranges,
)
from photon_ml_tpu.ops.losses import logistic_loss
from photon_ml_tpu.ops.streaming import (
    StreamingGLMObjective,
    _fe_nnz_histogram,
    _fe_restrict_chunks,
    _to_batch,
    stream_scores,
)
from photon_ml_tpu.optim.host_lbfgs import host_lbfgs_minimize
from photon_ml_tpu.optim.host_tron import host_tron_minimize


def _zipf_hist(d: int, draws: int = 200_000, a: float = 1.3) -> np.ndarray:
    rng = np.random.default_rng(7)
    idx = (rng.zipf(a, size=draws).astype(np.int64) - 1) % d
    return np.bincount(idx, minlength=d).astype(np.int64)


class TestPlanFeatureRanges:
    def test_cover_and_disjoint_on_zipf(self):
        hist = _zipf_hist(4096)
        for p in (1, 2, 3, 4, 7):
            plan = plan_feature_ranges(hist, p)
            b = plan.boundaries
            assert b[0] == 0 and b[-1] == 4096
            assert list(b) == sorted(b)
            # strictly ascending: every range nonempty even where the
            # histogram is zero (coverage is structural)
            assert all(hi > lo for lo, hi in zip(b, b[1:]))
            assert plan.num_ranges == p
            # per-range weights partition the histogram total exactly
            assert sum(plan.weights) == float(hist.sum())

    def test_deterministic_and_pid_independent(self):
        """The rule reads ONLY (histogram, P): repeated calls agree, and
        no per-process input exists — ``range_of(pid)`` just indexes the
        one shared boundary tuple (how every process derives the same
        partition with zero communication)."""
        hist = _zipf_hist(1024)
        a = plan_feature_ranges(hist, 4)
        b = plan_feature_ranges(hist.copy(), 4)
        assert a == b
        ranges = [a.range_of(pid) for pid in range(4)]
        assert ranges == sorted(ranges)
        assert [lo for lo, _ in ranges] == list(a.boundaries[:-1])

    def test_nnz_balance_on_zipf_meets_the_r12_gate(self):
        """The prefix cut on an r12-shaped Zipf histogram lands inside the
        acceptance bound (nnz balance ≤ 1.15x at P∈{2,4}) — the committed
        MULTICHIP_r12.json numbers are not a lucky draw."""
        hist = _zipf_hist(100_000, draws=500_000)
        for p in (2, 4):
            assert plan_feature_ranges(hist, p).balance <= 1.15

    def test_width_mode_splits_uniformly(self):
        hist = _zipf_hist(1000)
        plan = plan_feature_ranges(hist, 4, mode="width")
        assert plan.boundaries == (0, 250, 500, 750, 1000)

    def test_zero_weights_fall_back_to_uniform(self):
        plan = plan_feature_ranges(np.zeros(100), 4)
        assert plan.boundaries == (0, 25, 50, 75, 100)
        assert plan.balance == 1.0

    def test_all_weight_in_one_column_still_covers(self):
        """A single hot column carrying ALL the weight: contiguity caps
        what any split can do — the hot range owns everything — but the
        plan must stay a legal cover with nonempty ranges, not collapse."""
        hist = np.zeros(64)
        hist[40] = 1e6
        plan = plan_feature_ranges(hist, 4)
        b = plan.boundaries
        assert b[0] == 0 and b[-1] == 64
        assert all(hi > lo for lo, hi in zip(b, b[1:]))
        assert sum(plan.weights) == 1e6
        assert plan.balance == pytest.approx(4.0)

    def test_rejects_bad_inputs(self):
        hist = np.ones(8)
        with pytest.raises(ValueError, match="positive"):
            plan_feature_ranges(hist, 0)
        with pytest.raises(ValueError, match="cannot split"):
            plan_feature_ranges(np.ones(3), 4)
        with pytest.raises(ValueError, match="split mode"):
            plan_feature_ranges(hist, 2, mode="rows")


class TestKnobParsing:
    def test_fe_shard_env_wins_and_strict_parses(self, monkeypatch):
        import photon_ml_tpu.data.index_map as im

        monkeypatch.setattr(im, "FE_SHARD", 0)
        monkeypatch.delenv("PHOTON_FE_SHARD", raising=False)
        assert fe_shard_enabled() is False
        monkeypatch.setenv("PHOTON_FE_SHARD", "1")
        assert fe_shard_enabled() is True
        monkeypatch.setenv("PHOTON_FE_SHARD", "0")
        assert fe_shard_enabled() is False
        # module global is the env-less fallback (bench retune surface)
        monkeypatch.delenv("PHOTON_FE_SHARD")
        monkeypatch.setattr(im, "FE_SHARD", 1)
        assert fe_shard_enabled() is True
        # strict parse: a typo fails loudly, never benches the default
        monkeypatch.setenv("PHOTON_FE_SHARD", "yes")
        with pytest.raises(ValueError):
            fe_shard_enabled()

    def test_fe_split_weight_strict_membership(self, monkeypatch):
        monkeypatch.delenv("PHOTON_FE_SPLIT_WEIGHT", raising=False)
        assert fe_split_weight() == "nnz"
        monkeypatch.setenv("PHOTON_FE_SPLIT_WEIGHT", "width")
        assert fe_split_weight() == "width"
        monkeypatch.setenv("PHOTON_FE_SPLIT_WEIGHT", "bytes")
        with pytest.raises(ValueError, match="PHOTON_FE_SPLIT_WEIGHT"):
            fe_split_weight()


def _make_chunks(rng, n_chunks=3, n=64, d=96, k=5, pad_zeros=False):
    """Sparse chunk dicts with Zipf-skewed columns. ``pad_zeros`` plants
    zero-value slots (excluded from the histogram and inert in matvecs)."""
    chunks = []
    for _ in range(n_chunks):
        idx = ((rng.zipf(1.4, size=(n, k)).astype(np.int64) - 1) % d).astype(
            np.int32
        )
        val = rng.standard_normal((n, k)).astype(np.float32)
        val = np.where(val == 0.0, np.float32(0.5), val)  # all-live default
        if pad_zeros:
            val[:, -1] = 0.0
        chunks.append({
            "indices": idx,
            "values": val,
            "labels": (rng.uniform(size=n) < 0.5).astype(np.float32),
            "offsets": rng.standard_normal(n).astype(np.float32) * 0.1,
            "weights": np.ones(n, np.float32),
        })
    return chunks


class TestRestrictChunks:
    def test_partitions_live_nnz_exactly(self, rng):
        d = 96
        chunks = _make_chunks(rng, pad_zeros=True)
        hist = _fe_nnz_histogram(chunks, d)
        assert hist.sum() == sum(
            int((c["values"] != 0.0).sum()) for c in chunks
        )
        plan = plan_feature_ranges(hist, 3)
        per_range_nnz = 0
        dense_sum = np.zeros((len(chunks), 64, d), np.float64)
        for pid in range(3):
            lo, hi = plan.range_of(pid)
            restricted, k_max = _fe_restrict_chunks(chunks, lo, hi)
            assert k_max <= chunks[0]["values"].shape[1]
            for ci, r in enumerate(restricted):
                live = r["values"] != 0.0
                per_range_nnz += int(live.sum())
                # shifted-local indices stay inside [0, hi-lo)
                assert r["indices"][live].min(initial=0) >= 0
                assert r["indices"][live].max(initial=0) < hi - lo
                # per-row arrays SHARE storage (the prefetch chunk-cache
                # and per-visit residual-swap contract)
                for key in ("labels", "offsets", "weights"):
                    assert r[key] is chunks[ci][key]
                np.add.at(
                    dense_sum[ci],
                    (np.arange(64)[:, None], r["indices"] + lo),
                    np.where(live, r["values"], 0.0),
                )
        assert per_range_nnz == int(hist.sum())
        # densified per-range restrictions reassemble the original matrix
        dense_ref = np.zeros_like(dense_sum)
        for ci, c in enumerate(chunks):
            np.add.at(
                dense_ref[ci],
                (np.arange(64)[:, None], c["indices"]),
                np.where(c["values"] != 0.0, c["values"], 0.0),
            )
        np.testing.assert_array_equal(dense_sum, dense_ref)

    def test_identity_range_is_bitwise_on_padding_free_chunks(self, rng):
        chunks = _make_chunks(rng)
        restricted, k_max = _fe_restrict_chunks(chunks, 0, 96)
        assert k_max == chunks[0]["values"].shape[1]
        for r, c in zip(restricted, chunks):
            np.testing.assert_array_equal(r["indices"], c["indices"])
            np.testing.assert_array_equal(r["values"], c["values"])


class TestTileCacheFeRangeKey:
    def test_fe_range_joins_the_layout_cache_key(self, rng):
        """Two layouts over the SAME sparsity structure but different
        ``fe_range`` identities must occupy distinct cache entries — a
        re-plan or P change invalidates by key, never by luck."""
        from photon_ml_tpu.ops import tile_cache

        chunks = _make_chunks(rng, n_chunks=1)
        b = _to_batch(chunks[0], 96)
        tile_cache.clear()
        before = tile_cache.stats()
        tb0 = tile_cache.tiled_layout_for(b, fe_range=None)
        tb1 = tile_cache.tiled_layout_for(b, fe_range=(0, 0, 96, 2))
        stats = tile_cache.stats()
        assert stats["misses"] - before["misses"] == 2
        assert stats["entries"] >= 2
        assert tb0.fe_range is None and tb1.fe_range == (0, 0, 96, 2)
        # repeat lookups hit, per key
        tile_cache.tiled_layout_for(b, fe_range=(0, 0, 96, 2))
        assert tile_cache.stats()["hits"] - before["hits"] >= 1
        tile_cache.clear()


def _consume_all(obj, w_local, w_probe_local, n_rows):
    """Every streamed contract at one probe point, as host numpy."""
    v, g = obj.value_and_grad(jnp.asarray(w_local, jnp.float32))
    hv = obj.hvp(
        jnp.asarray(w_local, jnp.float32),
        jnp.asarray(w_probe_local, jnp.float32),
    )
    hd = obj.hessian_diag(jnp.asarray(w_local, jnp.float32))
    sc = obj.stream_scores(jnp.asarray(w_local, jnp.float32), num_rows=n_rows)
    return (
        np.asarray(v), np.asarray(g), np.asarray(hv), np.asarray(hd),
        np.asarray(sc),
    )


class TestKnobOffBitwise:
    """``PHOTON_FE_SHARD=0`` and unset are byte-identical across all four
    streamed consumers; the P=1 sharded path (identity restriction on
    padding-free chunks) matches them bitwise too — same per-chunk
    arithmetic, margins combined through the identity reduction."""

    def _objective(self, chunks, d):
        return StreamingGLMObjective(
            chunks=chunks, loss=logistic_loss, num_features=d,
            l2_weight=0.25, tile_sparse=False,
        )

    def test_off_and_unset_and_p1_shard_agree_bitwise(self, rng, monkeypatch):
        d, n_rows = 96, 3 * 64
        chunks = _make_chunks(rng)
        w = rng.standard_normal(d).astype(np.float32) * 0.1
        vp = rng.standard_normal(d).astype(np.float32)
        w0 = np.zeros(d, np.float32)

        monkeypatch.delenv("PHOTON_FE_SHARD", raising=False)
        obj = self._objective(chunks, d)
        assert obj.fe_active is False
        ref = _consume_all(obj, w, vp, n_rows)
        res_ref = host_lbfgs_minimize(
            obj, w0, OptimizerConfig(max_iterations=4, tolerance=1e-12)
        )
        tron_ref = host_tron_minimize(
            obj, w0, OptimizerConfig(max_iterations=3, tolerance=1e-12)
        )
        mod_ref = stream_scores(
            chunks, w, num_rows=n_rows, num_features=d, tile_sparse=False
        )

        for knob in ("0", "1"):
            monkeypatch.setenv("PHOTON_FE_SHARD", knob)
            obj2 = self._objective(chunks, d)
            assert obj2.fe_active is (knob == "1")
            got = _consume_all(
                obj2,
                obj2.fe_slice(w) if obj2.fe_active else w,
                obj2.fe_slice(vp) if obj2.fe_active else vp,
                n_rows,
            )
            gather = obj2.fe_gather if obj2.fe_active else (lambda x: x)
            np.testing.assert_array_equal(got[0], ref[0], err_msg=knob)
            for gi in (1, 2, 3):  # grad/hvp/hessian_diag segments
                np.testing.assert_array_equal(
                    gather(got[gi]), ref[gi], err_msg=knob
                )
            np.testing.assert_array_equal(got[4], ref[4], err_msg=knob)
            res = host_lbfgs_minimize(
                obj2,
                obj2.fe_slice(w0) if obj2.fe_active else w0,
                OptimizerConfig(max_iterations=4, tolerance=1e-12),
            )
            np.testing.assert_array_equal(
                gather(np.asarray(res.w)), np.asarray(res_ref.w),
                err_msg=knob,
            )
            assert int(res.iterations) == int(res_ref.iterations)
            tron = host_tron_minimize(
                obj2,
                obj2.fe_slice(w0) if obj2.fe_active else w0,
                OptimizerConfig(max_iterations=3, tolerance=1e-12),
            )
            np.testing.assert_array_equal(
                gather(np.asarray(tron.w)), np.asarray(tron_ref.w),
                err_msg=knob,
            )
            mod = stream_scores(
                chunks, w, num_rows=n_rows, num_features=d, tile_sparse=False
            )
            np.testing.assert_array_equal(mod, np.asarray(mod_ref), err_msg=knob)

    def test_p1_shard_padded_chunks_match_numerically(self, rng, monkeypatch):
        """Zero-value padding compacts away under restriction (a shorter
        per-row width, not the replicated path's layout), so the identity
        claim weakens to numerical agreement — but stays tight: the same
        nonzeros sum in the same row order."""
        d, n_rows = 96, 3 * 64
        chunks = _make_chunks(rng, pad_zeros=True)
        w = rng.standard_normal(d).astype(np.float32) * 0.1
        vp = rng.standard_normal(d).astype(np.float32)
        monkeypatch.delenv("PHOTON_FE_SHARD", raising=False)
        ref = _consume_all(self._objective(chunks, d), w, vp, n_rows)
        monkeypatch.setenv("PHOTON_FE_SHARD", "1")
        obj = self._objective(chunks, d)
        got = _consume_all(obj, obj.fe_slice(w), obj.fe_slice(vp), n_rows)
        np.testing.assert_allclose(got[0], ref[0], rtol=1e-6)
        for gi in (1, 2, 3):
            np.testing.assert_allclose(
                obj.fe_gather(got[gi]), ref[gi], rtol=1e-5, atol=1e-6
            )
        np.testing.assert_allclose(got[4], ref[4], rtol=1e-5, atol=1e-6)

    def test_fe_shard_rejects_dense_cross_process_and_norm(
        self, rng, monkeypatch
    ):
        monkeypatch.setenv("PHOTON_FE_SHARD", "1")
        X = rng.standard_normal((8, 4)).astype(np.float32)
        dense = [{
            "X": X,
            "labels": np.ones(8, np.float32),
            "offsets": np.zeros(8, np.float32),
            "weights": np.ones(8, np.float32),
        }]
        # the env knob auto-rule silently skips dense chunks (they fit one
        # chip's HBM by construction); only FORCING fe_shard raises
        assert StreamingGLMObjective(
            chunks=dense, loss=logistic_loss, num_features=4,
        ).fe_active is False
        with pytest.raises(ValueError, match="sparse"):
            StreamingGLMObjective(
                chunks=dense, loss=logistic_loss, num_features=4,
                fe_shard=True,
            )
        chunks = _make_chunks(rng, n_chunks=1)
        with pytest.raises(ValueError, match="cross_process"):
            StreamingGLMObjective(
                chunks=chunks, loss=logistic_loss, num_features=96,
                cross_process=True, tile_sparse=False,
            )


# -- gloo loopback parity (P∈{2,4}) -----------------------------------------
# Replicated rows, PHOTON_FE_SHARD=1: every process holds one feature
# range; coefficients/objective/scores must match the single-process
# reference computed IN-PROCESS by the parent (spawning a P=1 worker
# would buy nothing — the replicated path has no collectives).

_FE_WORKER = textwrap.dedent(
    """
    import json, os, sys
    coordinator, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    os.environ["PHOTON_FE_SHARD"] = "1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    import numpy as np
    from photon_ml_tpu.parallel.multihost import initialize_multihost
    initialize_multihost(coordinator, num_processes=nproc, process_id=pid)

    import jax.numpy as jnp
    from photon_ml_tpu.config import OptimizerConfig
    from photon_ml_tpu.ops.losses import logistic_loss
    from photon_ml_tpu.ops.streaming import (
        StreamingGLMObjective, stream_scores,
    )
    from photon_ml_tpu.optim.host_lbfgs import host_lbfgs_minimize
    from photon_ml_tpu.optim.host_tron import host_tron_minimize

    # the SAME deterministic dataset as the parent (rows replicated:
    # every process streams all rows, the win is the feature axis)
    rng = np.random.default_rng(1218)
    d, n, k = 96, 64, 5
    chunks = []
    for _ in range(3):
        idx = ((rng.zipf(1.4, size=(n, k)).astype(np.int64) - 1) % d
               ).astype(np.int32)
        val = rng.standard_normal((n, k)).astype(np.float32)
        val = np.where(val == 0.0, np.float32(0.5), val)
        chunks.append({
            "indices": idx, "values": val,
            "labels": (rng.uniform(size=n) < 0.5).astype(np.float32),
            "offsets": rng.standard_normal(n).astype(np.float32) * 0.1,
            "weights": np.ones(n, np.float32),
        })
    w_probe = (rng.standard_normal(d) * 0.1).astype(np.float32)
    n_rows = 3 * n

    obj = StreamingGLMObjective(
        chunks=chunks, loss=logistic_loss, num_features=d,
        l2_weight=0.25, tile_sparse=False,
    )
    assert obj.fe_active
    wp = obj.fe_slice(w_probe)
    v, g = obj.value_and_grad(jnp.asarray(wp, jnp.float32))
    g_full = obj.fe_gather(np.asarray(g))
    res = host_lbfgs_minimize(
        obj, obj.fe_slice(np.zeros(d, np.float32)),
        OptimizerConfig(max_iterations=4, tolerance=1e-12),
    )
    w_lbfgs = obj.fe_gather(np.asarray(res.w))
    tron = host_tron_minimize(
        obj, obj.fe_slice(np.zeros(d, np.float32)),
        OptimizerConfig(max_iterations=3, tolerance=1e-12),
    )
    w_tron = obj.fe_gather(np.asarray(tron.w))
    sc_method = obj.stream_scores(np.asarray(res.w), num_rows=n_rows)
    sc_module = stream_scores(
        chunks, w_lbfgs, num_rows=n_rows, num_features=d, tile_sparse=False,
    )
    from photon_ml_tpu.obs.metrics import REGISTRY
    gauges = {
        key: val for key, val in
        REGISTRY.snapshot().get("gauges", {}).items()
        if key.startswith("fe_shard.")
    }
    print("RESULT " + json.dumps({
        "pid": pid,
        "probe_value": float(v),
        "grad": np.asarray(g_full, np.float64).tolist(),
        "w_lbfgs": np.asarray(w_lbfgs, np.float64).tolist(),
        "iters_lbfgs": int(res.iterations),
        "value_lbfgs": float(res.value),
        "w_tron": np.asarray(w_tron, np.float64).tolist(),
        "scores_method": np.asarray(sc_method, np.float64).tolist(),
        "scores_module": np.asarray(sc_module, np.float64).tolist(),
        "gauges": gauges,
    }))
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_fe_workers(nproc: int) -> list:
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PHOTON_FE_SHARD")
    }
    return [
        subprocess.Popen(
            [sys.executable, "-c", _FE_WORKER, coordinator,
             str(pid), str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in range(nproc)
    ]


def _collect_fe_workers(procs, nproc: int) -> dict:
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err[-4000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                results[r["pid"]] = r
    assert set(results) == set(range(nproc))
    return results


def test_fe_shard_loopback_parity_matches_single_process(monkeypatch):
    d, n, n_rows = 96, 64, 3 * 64
    # the P=2 and P=4 groups launch together and ride out the jax-import
    # wall concurrently while the parent computes the reference
    groups = {nproc: _spawn_fe_workers(nproc) for nproc in (2, 4)}

    rng = np.random.default_rng(1218)
    chunks = _make_chunks(rng)  # identical draw order to the worker
    w_probe = (rng.standard_normal(d) * 0.1).astype(np.float32)
    monkeypatch.delenv("PHOTON_FE_SHARD", raising=False)
    obj = StreamingGLMObjective(
        chunks=chunks, loss=logistic_loss, num_features=d,
        l2_weight=0.25, tile_sparse=False,
    )
    v_ref, g_ref = obj.value_and_grad(jnp.asarray(w_probe, jnp.float32))
    res_ref = host_lbfgs_minimize(
        obj, np.zeros(d, np.float32),
        OptimizerConfig(max_iterations=4, tolerance=1e-12),
    )
    tron_ref = host_tron_minimize(
        obj, np.zeros(d, np.float32),
        OptimizerConfig(max_iterations=3, tolerance=1e-12),
    )
    sc_ref = np.asarray(
        obj.stream_scores(jnp.asarray(res_ref.w), num_rows=n_rows)
    )

    for nproc, procs in groups.items():
        got = _collect_fe_workers(procs, nproc)
        r0 = got[0]
        for pid, r in got.items():
            tag = f"nproc={nproc} pid={pid}"
            # every process reports IDENTICAL assembled results (the
            # fixed-order reduction makes the combined bits lockstep)
            for field in (
                "probe_value", "grad", "w_lbfgs", "iters_lbfgs",
                "value_lbfgs", "w_tron", "scores_method", "scores_module",
            ):
                assert r[field] == r0[field], tag
            # telemetry rides every process; widths/nnz partition the
            # global feature space and live-nnz total exactly
            assert r["gauges"]["fe_shard.ranges"] == float(nproc), tag
            assert r["gauges"]["fe_shard.nnz_balance"] >= 1.0, tag
        assert sum(
            r["gauges"]["fe_shard.width"] for r in got.values()
        ) == float(d)
        assert sum(r["gauges"]["fe_shard.nnz_local"] for r in got.values()
                   ) == float(sum(int((c["values"] != 0).sum())
                                  for c in chunks))
        # parity vs the single-process reference: gradient segments are
        # exact by construction; values/coefficients/scores sit behind
        # the f32 fixed-order margin reduction (reassociation only)
        tag = f"nproc={nproc}"
        np.testing.assert_allclose(
            r0["probe_value"], float(v_ref), rtol=1e-6, err_msg=tag
        )
        np.testing.assert_allclose(
            r0["grad"], np.asarray(g_ref, np.float64), rtol=1e-5,
            atol=1e-6, err_msg=tag,
        )
        np.testing.assert_allclose(
            r0["w_lbfgs"], np.asarray(res_ref.w, np.float64), rtol=1e-4,
            atol=1e-5, err_msg=tag,
        )
        # TRON's CG inner loop compounds the per-evaluation f32 margin
        # reassociation across hvp calls, so the truncated third iterate
        # sits a few e-4 off the reference (both converge to one optimum)
        np.testing.assert_allclose(
            r0["w_tron"], np.asarray(tron_ref.w, np.float64), rtol=2e-3,
            atol=5e-4, err_msg=tag,
        )
        np.testing.assert_allclose(
            r0["scores_method"], sc_ref, rtol=1e-4, atol=1e-5, err_msg=tag
        )
        np.testing.assert_allclose(
            r0["scores_module"], sc_ref, rtol=1e-4, atol=1e-5, err_msg=tag
        )


@pytest.mark.kernel
def test_fe_range_tiled_matvec_matches_dense_partial(rng):
    """A column-sliced ``fe_range`` layout through the tile-COO kernel (at
    the conftest-installed 8x2 carve): matvec/rmatvec against the dense
    partial over [lo, hi) — the sharded solve's phase A/B kernels consume
    exactly this batch shape. No collectives: one process, one range."""
    from photon_ml_tpu.ops.sparse_tiled import tile_sparse_batch

    d, n, k = 1024, 256, 4
    idx = ((rng.zipf(1.4, size=(n, k)).astype(np.int64) - 1) % d).astype(
        np.int32
    )
    val = rng.standard_normal((n, k)).astype(np.float32)
    val = np.where(val == 0.0, np.float32(0.5), val)
    chunk = {
        "indices": idx, "values": val,
        "labels": np.zeros(n, np.float32),
        "offsets": np.zeros(n, np.float32),
        "weights": np.ones(n, np.float32),
    }
    hist = _fe_nnz_histogram([chunk], d)
    plan = plan_feature_ranges(hist, 2)
    dense = np.zeros((n, d), np.float64)
    np.add.at(dense, (np.arange(n)[:, None], idx), val.astype(np.float64))
    for pid in range(2):
        lo, hi = plan.range_of(pid)
        restricted, _k = _fe_restrict_chunks([chunk], lo, hi)
        b = _to_batch(restricted[0], hi - lo)
        tb = tile_sparse_batch(b, fe_range=(pid, lo, hi, 2))
        assert tb.fe_range == (pid, lo, hi, 2)
        w = rng.standard_normal(hi - lo).astype(np.float32)
        r = rng.standard_normal(n).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(tb.matvec(jnp.asarray(w))),
            dense[:, lo:hi] @ w.astype(np.float64),
            rtol=2e-3, atol=2e-3,
        )
        np.testing.assert_allclose(
            np.asarray(tb.rmatvec(jnp.asarray(r))),
            dense[:, lo:hi].T @ r.astype(np.float64),
            rtol=2e-3, atol=2e-3,
        )
