"""Skew-aware shard placement (``parallel/placement``) + the
placement-aware bucket machinery it drives.

All host-side/unmarked (ROADMAP tier-1 discipline: the planner is pure
numpy; the few solver-backed parity tests run tiny geometries). The
multi-process acceptance harness (2/4-process loopback, bitwise vs
single process) lives in ``tests/test_multihost.py`` behind the ``slow``
marker.
"""

import numpy as np
import pytest

from photon_ml_tpu.parallel.placement import (
    PlacementPlan,
    plan_entity_placement,
    plan_shard_placement,
    re_shard_enabled,
    record_placement_metrics,
)


def _zipf_sizes(E: int = 64, base: float = 300.0, alpha: float = 1.1):
    return np.maximum((base / (1 + np.arange(E)) ** alpha).astype(np.int64), 2)


class TestPlanner:
    def test_zipf_64_entities_4_shards_meets_balance_bound(self):
        """The acceptance bound: LPT ≤ 1.15× max/mean where round-robin
        loses a full shard to the head entities (≥ 1.5×)."""
        sizes = _zipf_sizes()
        sk = plan_entity_placement(sizes, 4)
        rr = plan_entity_placement(sizes, 4, skew_aware=False)
        assert sk.balance <= 1.15, sk.loads
        assert rr.balance >= 1.5, rr.loads
        assert sk.balance < rr.balance

    def test_uniform_rows_balance_exactly(self):
        plan = plan_entity_placement(np.full(64, 7), 4)
        assert plan.balance == 1.0
        assert np.bincount(plan.owner, minlength=4).tolist() == [16] * 4

    def test_loads_match_owner_assignment(self):
        sizes = _zipf_sizes(32)
        plan = plan_entity_placement(sizes, 4)
        for s in range(4):
            assert plan.loads[s] == sizes[plan.owned_items(s)].sum()

    def test_single_item_and_more_shards_than_items(self):
        plan = plan_shard_placement([10.0], 4)
        assert plan.owner.tolist() == [0]
        assert plan.loads.tolist() == [10.0, 0.0, 0.0, 0.0]
        assert plan.balance == 4.0  # one loaded shard over mean/4

    def test_empty_items(self):
        plan = plan_shard_placement([], 3)
        assert len(plan.owner) == 0 and plan.balance == 1.0

    def test_single_shard_degenerates(self):
        sizes = _zipf_sizes(16)
        plan = plan_entity_placement(sizes, 1)
        assert set(plan.owner.tolist()) == {0}
        assert plan.loads[0] == sizes.sum()

    def test_group_atomic_assignment(self):
        """Fusion groups place WHOLE: every member shares one owner, and
        group totals (not member counts) drive the balance."""
        rows = [50, 1, 1, 1, 40, 30, 20, 10]
        groups = [[0, 1], [2, 3], [4], [5], [6, 7]]
        plan = plan_shard_placement(rows, 3, groups=groups)
        for g in groups:
            assert len({int(plan.owner[i]) for i in g}) == 1, (g, plan.owner)
        # LPT over group totals [51, 2, 40, 30, 30]: 51|40|30 then the
        # second 30 joins the lightest shard (30→60), then 2 joins 40
        assert sorted(plan.loads.tolist()) == [42.0, 51.0, 60.0]

    def test_unlisted_items_become_singletons(self):
        plan = plan_shard_placement([5, 5, 5, 5], 2, groups=[[1, 2]])
        assert int(plan.owner[1]) == int(plan.owner[2])
        assert plan.loads.sum() == 20.0

    def test_group_validation(self):
        with pytest.raises(ValueError, match="two groups"):
            plan_shard_placement([1, 2], 2, groups=[[0], [0]])
        with pytest.raises(ValueError, match="out of range"):
            plan_shard_placement([1, 2], 2, groups=[[5]])
        with pytest.raises(ValueError, match="num_shards"):
            plan_shard_placement([1.0], 0)
        with pytest.raises(ValueError, match="1-D"):
            plan_shard_placement(np.ones((2, 2)), 2)

    def test_deterministic_including_ties(self):
        rows = [3, 3, 3, 3, 3, 3]  # all-tie: order must still be fixed
        a = plan_shard_placement(rows, 3)
        b = plan_shard_placement(rows, 3)
        np.testing.assert_array_equal(a.owner, b.owner)
        sizes = _zipf_sizes(48)
        np.testing.assert_array_equal(
            plan_entity_placement(sizes, 4).owner,
            plan_entity_placement(sizes, 4).owner,
        )

    def test_round_robin_is_group_order(self):
        plan = plan_shard_placement([9, 1, 9, 1], 2, skew_aware=False)
        assert plan.owner.tolist() == [0, 1, 0, 1]
        assert plan.balance == pytest.approx(18.0 / 10.0)

    def test_record_placement_metrics_gauges(self):
        from photon_ml_tpu.obs.metrics import REGISTRY

        plan = plan_entity_placement(_zipf_sizes(16), 4)
        record_placement_metrics(plan, shard=2)
        snap = REGISTRY.snapshot("re_shard.")
        g = snap["gauges"]
        assert g["re_shard.shards"] == 4.0
        assert g["re_shard.rows"] == float(plan.loads[2])
        assert g["re_shard.rows_max"] == float(plan.loads.max())
        assert g["re_shard.balance"] == pytest.approx(plan.balance)


class TestKnob:
    def test_default_off(self, monkeypatch):
        monkeypatch.delenv("PHOTON_RE_SHARD", raising=False)
        assert re_shard_enabled() is False

    def test_env_wins_and_parses_strictly(self, monkeypatch):
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        assert re_shard_enabled() is True
        monkeypatch.setenv("PHOTON_RE_SHARD", "0")
        assert re_shard_enabled() is False
        monkeypatch.setenv("PHOTON_RE_SHARD", "yes")
        with pytest.raises(ValueError):
            re_shard_enabled()

    def test_module_global_fallback(self, monkeypatch):
        import photon_ml_tpu.parallel.placement as pl

        monkeypatch.delenv("PHOTON_RE_SHARD", raising=False)
        monkeypatch.setattr(pl, "RE_SHARD", 1)
        assert re_shard_enabled() is True


class TestCapacityClasses:
    """``game.data.capacity_classes`` must reproduce ``bucket_entities``'s
    per-entity capacities exactly — including the greedy merge — so a
    shard bucketing only ITS entities against the global ladder gives
    every entity the same geometry the single-process run gave it."""

    @pytest.mark.parametrize("seed", [0, 3, 7])
    def test_matches_bucket_entities_implicit_ladder(self, seed):
        from photon_ml_tpu.game.data import (
            bucket_entities,
            capacity_classes,
            group_by_entity,
        )

        rng = np.random.default_rng(seed)
        E = 40
        sizes = np.maximum(
            rng.zipf(1.6, size=E) % 97, 1
        ).astype(np.int64)
        ids = np.repeat(np.arange(E), sizes)
        grouping = group_by_entity(ids, num_entities=E)
        buckets = bucket_entities(grouping)
        caps, pops = capacity_classes(grouping.active_counts)
        assert caps == buckets.capacities
        assert pops == tuple(len(e) for e in buckets.entity_ids)

    def test_subset_bucketing_reproduces_capacities(self):
        from photon_ml_tpu.game.data import (
            bucket_entities,
            capacity_classes,
            group_by_entity,
        )

        sizes = _zipf_sizes(24, base=60.0)
        ids = np.repeat(np.arange(24), sizes)
        grouping = group_by_entity(ids, num_entities=24)
        caps, _ = capacity_classes(grouping.active_counts)
        # capacity of each entity under the GLOBAL ladder
        global_cap = {}
        full = bucket_entities(grouping, capacities=caps)
        for ent_b, rows_b in zip(full.entity_ids, full.row_indices):
            for e in ent_b:
                global_cap[int(e)] = rows_b.shape[1]
        # bucket an arbitrary SUBSET against the same explicit ladder:
        # every entity keeps its capacity (the sharded-prep invariant)
        subset = np.arange(0, 24, 3)
        keep = np.isin(ids, subset)
        sub_ids = np.searchsorted(subset, ids[keep])  # dense local ids
        sub_grouping = group_by_entity(sub_ids, num_entities=len(subset))
        sub = bucket_entities(sub_grouping, capacities=caps)
        for ent_b, rows_b in zip(sub.entity_ids, sub.row_indices):
            for e_local in ent_b:
                e = int(subset[int(e_local)])
                assert rows_b.shape[1] == global_cap[e], e

    def test_explicit_capacities_and_empty(self):
        from photon_ml_tpu.game.data import capacity_classes

        caps, pops = capacity_classes(
            np.asarray([3, 9, 17]), capacities=(4, 16, 32)
        )
        assert caps == (4, 16, 32)
        assert pops == (1, 1, 1)
        assert capacity_classes(np.zeros(5, np.int64)) == ((), ())
        with pytest.raises(ValueError, match="largest bucket capacity"):
            capacity_classes(np.asarray([100]), capacities=(4, 16))


class TestValidation:
    """plan_from_owner / replan_excluding fail LOUDLY on desynced
    inputs (they used to truncate/ignore silently): a mismatched owner
    map or an out-of-range survivor is a fleet-desync bug, and the
    error names the offending value."""

    def test_plan_from_owner_rejects_length_mismatch(self):
        from photon_ml_tpu.parallel.placement import plan_from_owner

        with pytest.raises(ValueError, match=r"length 3 != .*length 2"):
            plan_from_owner(np.array([0, 1, 0]), np.array([5.0, 5.0]), 2)

    def test_plan_from_owner_rejects_out_of_range_owner(self):
        from photon_ml_tpu.parallel.placement import plan_from_owner

        with pytest.raises(ValueError, match=r"owner value 7"):
            plan_from_owner(np.array([0, 7]), np.array([5.0, 5.0]), 2)
        with pytest.raises(ValueError, match=r"owner value -1"):
            plan_from_owner(np.array([0, -1]), np.array([5.0, 5.0]), 2)

    def test_plan_from_owner_valid_roundtrip(self):
        from photon_ml_tpu.parallel.placement import plan_from_owner

        plan = plan_from_owner(np.array([1, 0, 1]), [2.0, 3.0, 4.0], 2)
        assert plan.loads.tolist() == [3.0, 6.0]

    def test_replan_rejects_out_of_range_survivor(self):
        from photon_ml_tpu.parallel.placement import replan_excluding

        plan = plan_entity_placement(np.ones(4), 2)
        with pytest.raises(ValueError, match=r"survivor 5 outside"):
            replan_excluding(plan, [0], np.ones(4), survivors=[1, 5])

    def test_measured_costs_reject_length_mismatch(self):
        from photon_ml_tpu.parallel.placement import measured_entity_costs

        with pytest.raises(ValueError, match="length"):
            measured_entity_costs(
                np.ones(4), np.zeros(3, np.int64), np.ones(2)
            )


class TestSplitKnob:
    def test_default_off(self, monkeypatch):
        from photon_ml_tpu.parallel.placement import re_split_factor

        monkeypatch.delenv("PHOTON_RE_SPLIT", raising=False)
        assert re_split_factor() == 0

    def test_env_wins_and_parses_strictly(self, monkeypatch):
        from photon_ml_tpu.parallel.placement import re_split_factor

        monkeypatch.setenv("PHOTON_RE_SPLIT", "16")
        assert re_split_factor() == 16
        monkeypatch.setenv("PHOTON_RE_SPLIT", "-3")
        assert re_split_factor() == 0  # <= 0 disables, knob convention
        monkeypatch.setenv("PHOTON_RE_SPLIT", "lots")
        with pytest.raises(ValueError):
            re_split_factor()

    def test_module_global_fallback(self, monkeypatch):
        import photon_ml_tpu.parallel.placement as pl

        monkeypatch.delenv("PHOTON_RE_SPLIT", raising=False)
        monkeypatch.setattr(pl, "RE_SPLIT", 8)
        assert pl.re_split_factor() == 8


def _zipf_active(E: int, seed: int = 0, alpha: float = 0.9):
    """Zipf row counts over the whole entity range (the r08/r09 bench
    shape: constant row mass per capacity octave, population doubling
    toward the tail — the distribution whose tail class motivates the
    split rule)."""
    rng = np.random.default_rng(seed)
    base = np.maximum(
        ((E / (1.0 + np.arange(E))) ** alpha).astype(np.int64), 1
    )
    return np.maximum(base + rng.integers(0, 3, size=E), 1)


class TestSplitRule:
    """The PHOTON_RE_SPLIT sub-bucket atom ladder
    (``game.data.placement_atoms`` / ``split_entity_buckets``): pure
    deterministic arithmetic on the global bincount, never the process
    count."""

    def test_atoms_partition_classes_in_order(self):
        from photon_ml_tpu.game.data import capacity_classes, placement_atoms

        counts = _zipf_active(256)
        atoms, atom_caps, n_split = placement_atoms(counts, split=16)
        caps, pops = capacity_classes(counts)
        # atoms refine the class ladder: concatenating same-capacity
        # atoms in order reproduces each class's ascending member list
        by_cap: dict[int, list] = {}
        for a, c in zip(atoms, atom_caps):
            by_cap.setdefault(c, []).append(a)
        assert set(by_cap) == set(caps)
        active = np.flatnonzero(counts > 0)
        for c, pop in zip(caps, pops):
            merged = np.concatenate(by_cap[c])
            assert len(merged) == pop
            assert (np.diff(merged) > 0).all()  # ascending, no dup
        assert n_split >= 1  # the Zipf tail class split
        # every SPLIT class's atoms respect the >= 2-entity lane floor
        # (a 1-entity atom is legal only as a whole 1-entity class —
        # the batch-1 launch the unsplit run would also have made)
        for c, group in by_cap.items():
            if len(group) > 1:
                assert all(len(a) >= 2 for a in group), (c, group)

    def test_split_zero_is_identity(self):
        from photon_ml_tpu.game.data import capacity_classes, placement_atoms

        counts = _zipf_active(128)
        atoms, atom_caps, n_split = placement_atoms(counts, split=0)
        caps, pops = capacity_classes(counts)
        assert n_split == 0
        assert atom_caps == caps
        assert tuple(len(a) for a in atoms) == pops

    @pytest.mark.parametrize("seed", [0, 3, 7])
    def test_deterministic_and_process_count_independent(self, seed):
        """Same global bincount ⇒ identical ladder, full stop: the rule
        never reads the process count, so P ∈ {1, 2, 4} (or any other
        fleet size) derive the same atoms — the PR-8 bitwise
        invariant's placement analog."""
        from photon_ml_tpu.game.data import placement_atoms

        counts = _zipf_active(192, seed=seed)
        ref_atoms, ref_caps, ref_split = placement_atoms(counts, split=12)
        for P in (1, 2, 4):
            # plan over the atoms at this fleet size — the ladder the
            # plan consumed must be byte-identical to the reference
            atoms, caps, n_split = placement_atoms(counts, split=12)
            assert caps == ref_caps and n_split == ref_split
            for a, r in zip(atoms, ref_atoms):
                np.testing.assert_array_equal(a, r)
            plan = plan_shard_placement(
                counts, P, groups=[list(a) for a in atoms]
            )
            # atoms are indivisible placement units
            for a in atoms:
                assert len({int(plan.owner[i]) for i in a}) == 1

    def test_split_entity_buckets_matches_placement_atoms(self):
        """The two split sites (streamed owner map, in-memory prepared
        buckets) derive the SAME ladder from the same population — the
        shared ``_split_runs`` kernel, asserted end to end."""
        from photon_ml_tpu.game.data import (
            bucket_entities,
            group_by_entity,
            placement_atoms,
            split_entity_buckets,
        )

        counts = _zipf_active(96)
        ids = np.repeat(np.arange(96), counts)
        grouping = group_by_entity(ids, num_entities=96)
        buckets = bucket_entities(grouping)
        split_b, parents, n_split_b = split_entity_buckets(buckets, 12)
        atoms, atom_caps, n_split_a = placement_atoms(
            grouping.active_counts, split=12
        )
        assert n_split_a == n_split_b >= 1
        assert len(split_b.entity_ids) == len(atoms)
        assert split_b.capacities == atom_caps
        for ent_b, a in zip(split_b.entity_ids, atoms):
            np.testing.assert_array_equal(np.sort(ent_b), np.sort(a))
        # parents index the ORIGINAL bucket list, contiguously in order
        assert parents is not None
        assert sorted(set(parents)) == list(range(len(buckets.entity_ids)))

    def test_split_entity_buckets_knob_off_identity(self):
        from photon_ml_tpu.game.data import (
            bucket_entities,
            group_by_entity,
            split_entity_buckets,
        )

        ids = np.repeat(np.arange(16), _zipf_active(16))
        buckets = bucket_entities(group_by_entity(ids, num_entities=16))
        same, parents, n_split = split_entity_buckets(buckets, 0)
        assert same is buckets and parents is None and n_split == 0

    @pytest.mark.parametrize("seed", [1, 5, 9, 13])
    def test_lpt_quality_bound_under_atom_cap(self, seed):
        """Property: LPT over the atom ladder meets the cap-adjusted
        greedy bound max_load <= total/P + max_atom_weight on random
        Zipf shapes — the guarantee that makes max-owner load O(E/P)
        once no atom exceeds the cap."""
        from photon_ml_tpu.game.data import placement_atoms

        rng = np.random.default_rng(seed)
        E = int(rng.integers(64, 512))
        counts = _zipf_active(E, seed=seed, alpha=float(rng.uniform(0.7, 1.2)))
        split = int(rng.integers(8, 33))
        atoms, _, _ = placement_atoms(counts, split=split)
        atom_w = np.array([counts[a].sum() for a in atoms], np.float64)
        for P in (2, 4, 8):
            plan = plan_shard_placement(
                counts, P, groups=[list(a) for a in atoms]
            )
            bound = counts.sum() / P + atom_w.max()
            assert plan.loads.max() <= bound + 1e-9, (
                P, plan.loads, atom_w.max()
            )

    def test_record_placement_metrics_atom_gauges(self):
        from photon_ml_tpu.obs.metrics import REGISTRY

        plan = plan_entity_placement(_zipf_sizes(16), 4)
        record_placement_metrics(plan, shard=1, atoms=5, split_classes=2)
        g = REGISTRY.snapshot("re_shard.")["gauges"]
        assert g["re_shard.atoms"] == 5.0
        assert g["re_shard.split_classes"] == 2.0
        record_placement_metrics(plan, shard=1)
        g = REGISTRY.snapshot("re_shard.")["gauges"]
        assert g["re_shard.atoms"] == 16.0  # defaults to the item count
        assert g["re_shard.split_classes"] == 0.0


class TestLaneFloorBitwise:
    """The sharded path's lane floor: a 1-real-lane launch padded with
    one all-masked dummy lane must give the real entity BITWISE the
    result it gets inside a larger batch (the batched XLA lowering),
    because that is what the single-process run produced for it."""

    def test_padded_single_lane_matches_batched_lane(self):
        import jax.numpy as jnp

        from photon_ml_tpu.config import OptimizerConfig
        from photon_ml_tpu.game.data import DenseFeatures, gather_bucket
        from photon_ml_tpu.game.random_effect import solve_bucket_lanes
        from photon_ml_tpu.ops.losses import loss_for_task
        from photon_ml_tpu.optim.common import select_minimize_fn
        from photon_ml_tpu.types import TaskType, VarianceComputationType

        rng = np.random.default_rng(5)
        k, C, d = 3, 8, 3
        X = rng.normal(size=(k * C, d)).astype(np.float32)
        y = (rng.uniform(size=k * C) < 0.5).astype(np.float32)
        offs = np.zeros(k * C, np.float32)
        wgt = np.ones(k * C, np.float32)
        rows = np.arange(k * C).reshape(k, C)
        feats = DenseFeatures(X=X)
        cfg = OptimizerConfig(max_iterations=6, tolerance=1e-9)
        loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
        minimize_fn, extra = select_minimize_fn(cfg, 0.0)
        common = dict(
            minimize_fn=minimize_fn, loss=loss, config=cfg,
            intercept_index=None,
            variance_computation=VarianceComputationType.SIMPLE,
            **extra,
        )
        l2 = jnp.asarray(1.0, jnp.float32)

        batched = solve_bucket_lanes(
            gather_bucket(feats, y, offs, wgt, rows),
            jnp.zeros((k, d), jnp.float32), l2, None, None, None, **common
        )
        # entity 0 alone + one dummy lane whose rows are all -1 (masked)
        rows_pad = np.stack([rows[0], np.full(C, -1, rows.dtype)])
        padded = solve_bucket_lanes(
            gather_bucket(feats, y, offs, wgt, rows_pad),
            jnp.zeros((2, d), jnp.float32), l2, None, None, None, **common
        )
        for b_out, p_out in zip(batched, padded):
            np.testing.assert_array_equal(
                np.asarray(b_out)[0], np.asarray(p_out)[0]
            )


class TestOwnedBucketMode:
    """PHOTON_RE_SHARD=1 under a (single-process) mesh: owned-bucket prep
    keeps lanes fully addressable — bitwise-identical to the unsharded
    solve, with the PR-5 compaction/fusion knobs now LEGAL under the
    mesh (the lifted gate) and the legacy knob-off schedule untouched."""

    @pytest.fixture()
    def problem(self):
        import jax.numpy as jnp

        from photon_ml_tpu.config import OptimizerConfig
        from photon_ml_tpu.game import bucket_entities, group_by_entity
        from photon_ml_tpu.game.data import DenseFeatures
        from photon_ml_tpu.ops.losses import loss_for_task
        from photon_ml_tpu.types import TaskType, VarianceComputationType

        rng = np.random.default_rng(11)
        n, E, d = 96, 12, 3
        ids = rng.integers(0, E, size=n).astype(np.int32)
        kwargs = dict(
            labels=(rng.uniform(size=n) < 0.5).astype(np.float32),
            offsets=np.zeros(n, np.float32),
            weights=np.ones(n, np.float32),
            buckets=bucket_entities(group_by_entity(ids, num_entities=E)),
            num_entities=E,
            loss=loss_for_task(TaskType.LOGISTIC_REGRESSION),
            config=OptimizerConfig(max_iterations=6, tolerance=1e-9),
            l2_weight=1.0,
            variance_computation=VarianceComputationType.SIMPLE,
        )
        feats = DenseFeatures(
            X=jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        )
        return feats, kwargs

    def test_owned_mesh_solve_is_bitwise(self, problem, monkeypatch):
        from photon_ml_tpu.game.random_effect import train_random_effects
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        ref = train_random_effects(feats, **kwargs)
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        got = train_random_effects(feats, mesh=data_mesh(), **kwargs)
        np.testing.assert_array_equal(
            np.asarray(got.coefficients), np.asarray(ref.coefficients)
        )
        np.testing.assert_array_equal(
            np.asarray(got.variances), np.asarray(ref.variances)
        )
        np.testing.assert_array_equal(got.iterations, ref.iterations)

    def test_gate_lift_compaction_fusion_apply_under_mesh(
        self, problem, monkeypatch
    ):
        """With the knob on, PHOTON_RE_COMPACT_EVERY/FUSE_BUCKETS run
        under a mesh (they were gated off before) — still bitwise."""
        from photon_ml_tpu.game.random_effect import train_random_effects
        from photon_ml_tpu.obs.metrics import REGISTRY
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        ref = train_random_effects(feats, **kwargs)
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        monkeypatch.setenv("PHOTON_RE_FUSE_BUCKETS", "1")
        monkeypatch.setenv("PHOTON_RE_COMPACT_EVERY", "2")

        def launches():
            return (
                REGISTRY.snapshot("re_solve.")["counters"]
                .get("re_solve.launches", {})
                .get("value", 0.0)
            )

        before = launches()
        got = train_random_effects(feats, mesh=data_mesh(), **kwargs)
        np.testing.assert_array_equal(
            np.asarray(got.coefficients), np.asarray(ref.coefficients)
        )
        np.testing.assert_array_equal(
            np.asarray(got.variances), np.asarray(ref.variances)
        )
        # the compacted chunk schedule actually ran (multiple launches
        # per fused unit), i.e. the knobs were NOT silently gated off
        assert launches() > before

    def test_split_owned_mesh_solve_is_bitwise(self, problem, monkeypatch):
        """PHOTON_RE_SPLIT under the owned-bucket mesh: sub-bucket atoms
        re-concatenate per owner (one process here owns everything, so
        the launch geometry — and the model, bit for bit — is exactly
        the unsplit run's), warm starts and per-entity priors included."""
        import jax.numpy as jnp

        from photon_ml_tpu.game.random_effect import train_random_effects
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        ref = train_random_effects(feats, **kwargs)
        W = np.asarray(ref.coefficients)
        V = np.asarray(ref.variances)
        ref2 = train_random_effects(
            feats,
            initial_coefficients=jnp.asarray(W),
            prior_coefficients=jnp.asarray(W),
            prior_variances=jnp.asarray(V),
            **kwargs,
        )
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        monkeypatch.setenv("PHOTON_RE_SPLIT", "6")
        got = train_random_effects(feats, mesh=data_mesh(), **kwargs)
        np.testing.assert_array_equal(np.asarray(got.coefficients), W)
        np.testing.assert_array_equal(np.asarray(got.variances), V)
        np.testing.assert_array_equal(got.iterations, ref.iterations)
        # the warm+prior lanes remap through the sub-bucket permutation
        got2 = train_random_effects(
            feats,
            mesh=data_mesh(),
            initial_coefficients=jnp.asarray(W),
            prior_coefficients=jnp.asarray(W),
            prior_variances=jnp.asarray(V),
            **kwargs,
        )
        np.testing.assert_array_equal(
            np.asarray(got2.coefficients), np.asarray(ref2.coefficients)
        )
        np.testing.assert_array_equal(
            np.asarray(got2.variances), np.asarray(ref2.variances)
        )

    def test_split_knob_off_reproduces_owner_map_and_launches(
        self, problem, monkeypatch
    ):
        """PHOTON_RE_SPLIT=0 is the PR-12 schedule bit for bit: no
        parent markers, the SAME owner map the legacy capacity-keyed
        plan produces, and the legacy one-launch-per-bucket counter."""
        from photon_ml_tpu.game.random_effect import (
            _plan_bucket_owners,
            prepare_buckets,
            train_random_effects,
        )
        from photon_ml_tpu.obs.metrics import REGISTRY
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        monkeypatch.delenv("PHOTON_RE_SPLIT", raising=False)
        prepared = prepare_buckets(
            feats, kwargs["labels"], kwargs["weights"], kwargs["buckets"],
            data_mesh(),
        )
        assert all(pb.parent is None for pb in prepared)
        legacy = _plan_bucket_owners(kwargs["buckets"])
        np.testing.assert_array_equal(
            [pb.owner for pb in prepared], np.asarray(legacy)
        )

        def launches():
            return (
                REGISTRY.snapshot("re_solve.")["counters"]
                .get("re_solve.launches", {})
                .get("value", 0.0)
            )

        before = launches()
        train_random_effects(feats, mesh=data_mesh(), **kwargs)
        assert launches() - before == len(kwargs["buckets"].entity_ids)

    def test_split_prepared_buckets_carry_parents_and_owner_atoms(
        self, problem, monkeypatch
    ):
        """Split prep: heavy classes appear as >= 2-lane sub-buckets
        with parent markers, entity ids still partition, and the
        placement gauges record the finer granularity."""
        from photon_ml_tpu.game.random_effect import prepare_buckets
        from photon_ml_tpu.obs.metrics import REGISTRY
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        monkeypatch.setenv("PHOTON_RE_SPLIT", "6")
        prepared = prepare_buckets(
            feats, kwargs["labels"], kwargs["weights"], kwargs["buckets"],
            data_mesh(),
        )
        assert len(prepared) > len(kwargs["buckets"].entity_ids)
        assert all(pb.parent is not None for pb in prepared)
        split_parents = {
            pb.parent for pb in prepared
            if sum(q.parent == pb.parent for q in prepared) > 1
        }
        assert split_parents  # at least one class actually split
        for pb in prepared:
            if pb.parent in split_parents:
                assert pb.num_real >= 2  # the lane floor
        all_ids = np.concatenate([pb.entity_ids for pb in prepared])
        assert len(all_ids) == len(np.unique(all_ids))
        g = REGISTRY.snapshot("re_shard.")["gauges"]
        assert g["re_shard.atoms"] == float(len(prepared))
        assert g["re_shard.split_classes"] >= 1.0

    def test_knob_off_mesh_keeps_lane_sharded_schedule(
        self, problem, monkeypatch
    ):
        """Knob off: prepare_buckets still lane-shards over the mesh and
        assigns no owners — the legacy schedule, counter for counter."""
        from photon_ml_tpu.game.random_effect import prepare_buckets
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        monkeypatch.delenv("PHOTON_RE_SHARD", raising=False)
        prepared = prepare_buckets(
            feats, kwargs["labels"], kwargs["weights"], kwargs["buckets"],
            data_mesh(),
        )
        assert all(pb.owner is None for pb in prepared)
        # lanes padded to divide the 8-device mesh axis
        assert all(
            pb.static.labels.shape[0] % 8 == 0 for pb in prepared
        )
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        owned = prepare_buckets(
            feats, kwargs["labels"], kwargs["weights"], kwargs["buckets"],
            data_mesh(),
        )
        assert all(pb.owner == 0 for pb in owned)  # single process owns all
        assert all(
            pb.static.labels.shape[0] == pb.num_real for pb in owned
        )

    def test_device_split_owned_mesh_solve_is_bitwise(
        self, problem, monkeypatch
    ):
        """PHOTON_RE_DEVICE_SPLIT under the owned-bucket mesh (the test
        process runs 8 forced CPU devices): per-device dispatch with the
        device-local combine is bitwise the knob-off solve — and on the
        unsplit prep the fusion-group-atomic device plan keeps the
        launch schedule counter for counter."""
        from photon_ml_tpu.game.random_effect import train_random_effects
        from photon_ml_tpu.obs.metrics import REGISTRY
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")

        def launches():
            return (
                REGISTRY.snapshot("re_solve.")["counters"]
                .get("re_solve.launches", {})
                .get("value", 0.0)
            )

        b0 = launches()
        ref = train_random_effects(feats, mesh=data_mesh(), **kwargs)
        ref_launches = launches() - b0
        monkeypatch.setenv("PHOTON_RE_DEVICE_SPLIT", "1")
        b1 = launches()
        got = train_random_effects(feats, mesh=data_mesh(), **kwargs)
        np.testing.assert_array_equal(
            np.asarray(got.coefficients), np.asarray(ref.coefficients)
        )
        np.testing.assert_array_equal(
            np.asarray(got.variances), np.asarray(ref.variances)
        )
        np.testing.assert_array_equal(got.iterations, ref.iterations)
        assert launches() - b1 == ref_launches
        g = REGISTRY.snapshot("re_shard.")["gauges"]
        assert g["re_shard.devices"] >= 2.0
        assert g["re_shard.device_balance"] >= 1.0

    def test_device_split_atoms_warm_prior_and_bytes_weight_bitwise(
        self, problem, monkeypatch
    ):
        """Device placement over sub-bucket atoms (independent atom
        placement, per-owner-AND-device re-concatenation) with warm
        starts and per-entity MAP priors, plus the bytes weight axis —
        all bitwise vs the knob-off run."""
        import jax.numpy as jnp

        from photon_ml_tpu.game.random_effect import train_random_effects
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        monkeypatch.setenv("PHOTON_RE_SPLIT", "6")
        cold = train_random_effects(feats, mesh=data_mesh(), **kwargs)
        W = np.asarray(cold.coefficients)
        V = np.asarray(cold.variances)
        warm_kwargs = dict(
            initial_coefficients=jnp.asarray(W),
            prior_coefficients=jnp.asarray(W),
            prior_variances=jnp.asarray(V),
        )
        ref = train_random_effects(
            feats, mesh=data_mesh(), **warm_kwargs, **kwargs
        )
        monkeypatch.setenv("PHOTON_RE_DEVICE_SPLIT", "1")
        got = train_random_effects(
            feats, mesh=data_mesh(), **warm_kwargs, **kwargs
        )
        np.testing.assert_array_equal(
            np.asarray(got.coefficients), np.asarray(ref.coefficients)
        )
        np.testing.assert_array_equal(
            np.asarray(got.variances), np.asarray(ref.variances)
        )
        # the bytes weight axis changes WHERE atoms go, never the model
        monkeypatch.setenv("PHOTON_RE_SPLIT_WEIGHT", "bytes")
        got2 = train_random_effects(
            feats, mesh=data_mesh(), **warm_kwargs, **kwargs
        )
        np.testing.assert_array_equal(
            np.asarray(got2.coefficients), np.asarray(ref.coefficients)
        )
        np.testing.assert_array_equal(
            np.asarray(got2.variances), np.asarray(ref.variances)
        )

    def test_device_plan_rederives_from_survivor_topology(
        self, problem, monkeypatch
    ):
        """Degrade drill: after an in-place degrade the owner map plans
        over the SURVIVOR group and the device level re-derives from
        this process's survivor rank — pure host arithmetic, with no
        input besides the effective topology."""
        import jax

        import photon_ml_tpu.parallel.multihost as mh
        from photon_ml_tpu.game.random_effect import (
            _plan_bucket_devices,
            _plan_bucket_owners,
        )

        feats, kwargs = problem
        buckets = kwargs["buckets"]
        monkeypatch.setenv("PHOTON_RE_DEVICE_SPLIT", "1")

        # healthy 4-process fleet, this process is original pid 2
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        monkeypatch.setattr(jax, "process_index", lambda: 2)
        owners_h = np.asarray(_plan_bucket_owners(buckets))
        dev_h = np.asarray(_plan_bucket_devices(buckets, None, owners_h))
        assert np.all(dev_h[owners_h == 2] >= 0)
        assert np.all(dev_h[owners_h != 2] == -1)

        # degrade in place: pid 1 lost, survivors (0, 2, 3) — this
        # process's EFFECTIVE rank is 1, the owner map re-plans over 3
        # shards, and the device plan follows the survivor topology
        monkeypatch.setattr(
            mh, "_DEGRADED", {"survivors": (0, 2, 3), "rank": 1}
        )
        owners_d = np.asarray(_plan_bucket_owners(buckets))
        assert int(owners_d.max()) <= 2  # planned over 3 survivors
        dev_d = np.asarray(_plan_bucket_devices(buckets, None, owners_d))
        assert np.all(dev_d[owners_d == 1] >= 0)
        assert np.all(dev_d[owners_d != 1] == -1)
        # the two plans disagree about this process's owned set — the
        # device level really did recompute, not reuse
        assert set(np.flatnonzero(owners_h == 2)) != set(
            np.flatnonzero(owners_d == 1)
        )


class TestDevicePlacementPlanner:
    """The second-level LPT (``plan_device_placement``): one shard's
    owned items onto its local devices — same determinism, balance and
    group-atomicity contracts as the process level, pure numpy."""

    def test_unowned_items_get_minus_one(self):
        from photon_ml_tpu.parallel.placement import plan_device_placement

        device, plan = plan_device_placement(
            [5.0, 7.0, 9.0, 11.0], np.array([0, 1, 0, 1]), 1, 2
        )
        assert device[0] == -1 and device[2] == -1
        assert set(device[[1, 3]].tolist()) <= {0, 1}
        assert plan.loads.sum() == 18.0

    def test_owned_partition_complete_and_deterministic(self):
        from photon_ml_tpu.parallel.placement import plan_device_placement

        sizes = _zipf_sizes(48)
        owner = plan_entity_placement(sizes, 3).owner
        d1, p1 = plan_device_placement(sizes, owner, 2, 4)
        d2, _ = plan_device_placement(sizes, owner, 2, 4)
        np.testing.assert_array_equal(d1, d2)
        owned = np.flatnonzero(owner == 2)
        assert np.all(d1[owned] >= 0) and np.all(d1[owned] < 4)
        assert np.all(d1[np.flatnonzero(owner != 2)] == -1)
        for dev in range(4):
            assert p1.loads[dev] == sizes[np.flatnonzero(d1 == dev)].sum()

    def test_balance_bound_at_atom_granularity(self):
        """The acceptance bound one level down: at ATOM granularity
        (max item weight capped at total/16, the split rule's job) the
        intra-host LPT meets the same 1.15x max/mean bound on every
        shard — whole-bucket granularity can't (the Zipf head entity
        alone exceeds a device's fair share)."""
        from photon_ml_tpu.parallel.placement import plan_device_placement

        sizes = _zipf_sizes(64)
        cap = sizes.sum() / 16
        atoms: list[float] = []
        for s in sizes.astype(np.float64):
            while s > cap:
                atoms.append(cap)
                s -= cap
            atoms.append(s)
        owner = plan_entity_placement(np.asarray(atoms), 2).owner
        for shard in range(2):
            _, plan = plan_device_placement(atoms, owner, shard, 4)
            assert plan.balance <= 1.15, plan.loads

    def test_group_members_stay_on_one_device(self):
        from photon_ml_tpu.parallel.placement import plan_device_placement

        device, _ = plan_device_placement(
            [10.0] * 6, np.zeros(6, np.int64), 0, 4,
            groups=[[0, 1, 2], [3, 4, 5]],
        )
        assert len({int(device[i]) for i in (0, 1, 2)}) == 1
        assert len({int(device[i]) for i in (3, 4, 5)}) == 1

    def test_straddling_group_raises(self):
        from photon_ml_tpu.parallel.placement import plan_device_placement

        with pytest.raises(ValueError, match="straddles"):
            plan_device_placement(
                [5.0, 5.0], np.array([0, 1]), 0, 2, groups=[[0, 1]]
            )

    def test_validation(self):
        from photon_ml_tpu.parallel.placement import plan_device_placement

        with pytest.raises(ValueError, match="num_devices"):
            plan_device_placement([1.0], np.zeros(1, np.int64), 0, 0)
        with pytest.raises(ValueError, match="length"):
            plan_device_placement([1.0, 2.0], np.zeros(1, np.int64), 0, 2)

    def test_record_device_placement_metrics_gauges(self):
        from photon_ml_tpu.obs.metrics import REGISTRY
        from photon_ml_tpu.parallel.placement import (
            plan_device_placement,
            record_device_placement_metrics,
        )

        sizes = _zipf_sizes(32)
        owner = plan_entity_placement(sizes, 2).owner
        _, plan = plan_device_placement(sizes, owner, 0, 4)
        record_device_placement_metrics(plan)
        g = REGISTRY.snapshot("re_shard.")["gauges"]
        assert g["re_shard.device_balance"] == plan.balance
        assert g["re_shard.devices"] == 4.0
        for d in range(4):
            assert g[f"re_shard.device_rows.{d}"] == float(plan.loads[d])


class TestDeviceSplitKnob:
    def test_default_off(self, monkeypatch):
        from photon_ml_tpu.parallel.placement import re_device_split_enabled

        monkeypatch.delenv("PHOTON_RE_DEVICE_SPLIT", raising=False)
        assert re_device_split_enabled() is False

    def test_env_wins_and_parses_strictly(self, monkeypatch):
        from photon_ml_tpu.parallel.placement import re_device_split_enabled

        monkeypatch.setenv("PHOTON_RE_DEVICE_SPLIT", "1")
        assert re_device_split_enabled() is True
        monkeypatch.setenv("PHOTON_RE_DEVICE_SPLIT", "0")
        assert re_device_split_enabled() is False
        monkeypatch.setenv("PHOTON_RE_DEVICE_SPLIT", "yes")
        with pytest.raises(ValueError):
            re_device_split_enabled()

    def test_module_global_fallback(self, monkeypatch):
        import photon_ml_tpu.parallel.placement as pl

        monkeypatch.delenv("PHOTON_RE_DEVICE_SPLIT", raising=False)
        monkeypatch.setattr(pl, "RE_DEVICE_SPLIT", 1)
        assert pl.re_device_split_enabled() is True

    def test_weight_default_and_strict_enum(self, monkeypatch):
        from photon_ml_tpu.parallel.placement import re_split_weight

        monkeypatch.delenv("PHOTON_RE_SPLIT_WEIGHT", raising=False)
        assert re_split_weight() == "rows"
        monkeypatch.setenv("PHOTON_RE_SPLIT_WEIGHT", "bytes")
        assert re_split_weight() == "bytes"
        monkeypatch.setenv("PHOTON_RE_SPLIT_WEIGHT", "lanes")
        with pytest.raises(ValueError):
            re_split_weight()

    def test_weight_module_global_fallback(self, monkeypatch):
        import photon_ml_tpu.parallel.placement as pl

        monkeypatch.delenv("PHOTON_RE_SPLIT_WEIGHT", raising=False)
        monkeypatch.setattr(pl, "RE_SPLIT_WEIGHT", "bytes")
        assert pl.re_split_weight() == "bytes"
