"""Skew-aware shard placement (``parallel/placement``) + the
placement-aware bucket machinery it drives.

All host-side/unmarked (ROADMAP tier-1 discipline: the planner is pure
numpy; the few solver-backed parity tests run tiny geometries). The
multi-process acceptance harness (2/4-process loopback, bitwise vs
single process) lives in ``tests/test_multihost.py`` behind the ``slow``
marker.
"""

import numpy as np
import pytest

from photon_ml_tpu.parallel.placement import (
    PlacementPlan,
    plan_entity_placement,
    plan_shard_placement,
    re_shard_enabled,
    record_placement_metrics,
)


def _zipf_sizes(E: int = 64, base: float = 300.0, alpha: float = 1.1):
    return np.maximum((base / (1 + np.arange(E)) ** alpha).astype(np.int64), 2)


class TestPlanner:
    def test_zipf_64_entities_4_shards_meets_balance_bound(self):
        """The acceptance bound: LPT ≤ 1.15× max/mean where round-robin
        loses a full shard to the head entities (≥ 1.5×)."""
        sizes = _zipf_sizes()
        sk = plan_entity_placement(sizes, 4)
        rr = plan_entity_placement(sizes, 4, skew_aware=False)
        assert sk.balance <= 1.15, sk.loads
        assert rr.balance >= 1.5, rr.loads
        assert sk.balance < rr.balance

    def test_uniform_rows_balance_exactly(self):
        plan = plan_entity_placement(np.full(64, 7), 4)
        assert plan.balance == 1.0
        assert np.bincount(plan.owner, minlength=4).tolist() == [16] * 4

    def test_loads_match_owner_assignment(self):
        sizes = _zipf_sizes(32)
        plan = plan_entity_placement(sizes, 4)
        for s in range(4):
            assert plan.loads[s] == sizes[plan.owned_items(s)].sum()

    def test_single_item_and_more_shards_than_items(self):
        plan = plan_shard_placement([10.0], 4)
        assert plan.owner.tolist() == [0]
        assert plan.loads.tolist() == [10.0, 0.0, 0.0, 0.0]
        assert plan.balance == 4.0  # one loaded shard over mean/4

    def test_empty_items(self):
        plan = plan_shard_placement([], 3)
        assert len(plan.owner) == 0 and plan.balance == 1.0

    def test_single_shard_degenerates(self):
        sizes = _zipf_sizes(16)
        plan = plan_entity_placement(sizes, 1)
        assert set(plan.owner.tolist()) == {0}
        assert plan.loads[0] == sizes.sum()

    def test_group_atomic_assignment(self):
        """Fusion groups place WHOLE: every member shares one owner, and
        group totals (not member counts) drive the balance."""
        rows = [50, 1, 1, 1, 40, 30, 20, 10]
        groups = [[0, 1], [2, 3], [4], [5], [6, 7]]
        plan = plan_shard_placement(rows, 3, groups=groups)
        for g in groups:
            assert len({int(plan.owner[i]) for i in g}) == 1, (g, plan.owner)
        # LPT over group totals [51, 2, 40, 30, 30]: 51|40|30 then the
        # second 30 joins the lightest shard (30→60), then 2 joins 40
        assert sorted(plan.loads.tolist()) == [42.0, 51.0, 60.0]

    def test_unlisted_items_become_singletons(self):
        plan = plan_shard_placement([5, 5, 5, 5], 2, groups=[[1, 2]])
        assert int(plan.owner[1]) == int(plan.owner[2])
        assert plan.loads.sum() == 20.0

    def test_group_validation(self):
        with pytest.raises(ValueError, match="two groups"):
            plan_shard_placement([1, 2], 2, groups=[[0], [0]])
        with pytest.raises(ValueError, match="out of range"):
            plan_shard_placement([1, 2], 2, groups=[[5]])
        with pytest.raises(ValueError, match="num_shards"):
            plan_shard_placement([1.0], 0)
        with pytest.raises(ValueError, match="1-D"):
            plan_shard_placement(np.ones((2, 2)), 2)

    def test_deterministic_including_ties(self):
        rows = [3, 3, 3, 3, 3, 3]  # all-tie: order must still be fixed
        a = plan_shard_placement(rows, 3)
        b = plan_shard_placement(rows, 3)
        np.testing.assert_array_equal(a.owner, b.owner)
        sizes = _zipf_sizes(48)
        np.testing.assert_array_equal(
            plan_entity_placement(sizes, 4).owner,
            plan_entity_placement(sizes, 4).owner,
        )

    def test_round_robin_is_group_order(self):
        plan = plan_shard_placement([9, 1, 9, 1], 2, skew_aware=False)
        assert plan.owner.tolist() == [0, 1, 0, 1]
        assert plan.balance == pytest.approx(18.0 / 10.0)

    def test_record_placement_metrics_gauges(self):
        from photon_ml_tpu.obs.metrics import REGISTRY

        plan = plan_entity_placement(_zipf_sizes(16), 4)
        record_placement_metrics(plan, shard=2)
        snap = REGISTRY.snapshot("re_shard.")
        g = snap["gauges"]
        assert g["re_shard.shards"] == 4.0
        assert g["re_shard.rows"] == float(plan.loads[2])
        assert g["re_shard.rows_max"] == float(plan.loads.max())
        assert g["re_shard.balance"] == pytest.approx(plan.balance)


class TestKnob:
    def test_default_off(self, monkeypatch):
        monkeypatch.delenv("PHOTON_RE_SHARD", raising=False)
        assert re_shard_enabled() is False

    def test_env_wins_and_parses_strictly(self, monkeypatch):
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        assert re_shard_enabled() is True
        monkeypatch.setenv("PHOTON_RE_SHARD", "0")
        assert re_shard_enabled() is False
        monkeypatch.setenv("PHOTON_RE_SHARD", "yes")
        with pytest.raises(ValueError):
            re_shard_enabled()

    def test_module_global_fallback(self, monkeypatch):
        import photon_ml_tpu.parallel.placement as pl

        monkeypatch.delenv("PHOTON_RE_SHARD", raising=False)
        monkeypatch.setattr(pl, "RE_SHARD", 1)
        assert re_shard_enabled() is True


class TestCapacityClasses:
    """``game.data.capacity_classes`` must reproduce ``bucket_entities``'s
    per-entity capacities exactly — including the greedy merge — so a
    shard bucketing only ITS entities against the global ladder gives
    every entity the same geometry the single-process run gave it."""

    @pytest.mark.parametrize("seed", [0, 3, 7])
    def test_matches_bucket_entities_implicit_ladder(self, seed):
        from photon_ml_tpu.game.data import (
            bucket_entities,
            capacity_classes,
            group_by_entity,
        )

        rng = np.random.default_rng(seed)
        E = 40
        sizes = np.maximum(
            rng.zipf(1.6, size=E) % 97, 1
        ).astype(np.int64)
        ids = np.repeat(np.arange(E), sizes)
        grouping = group_by_entity(ids, num_entities=E)
        buckets = bucket_entities(grouping)
        caps, pops = capacity_classes(grouping.active_counts)
        assert caps == buckets.capacities
        assert pops == tuple(len(e) for e in buckets.entity_ids)

    def test_subset_bucketing_reproduces_capacities(self):
        from photon_ml_tpu.game.data import (
            bucket_entities,
            capacity_classes,
            group_by_entity,
        )

        sizes = _zipf_sizes(24, base=60.0)
        ids = np.repeat(np.arange(24), sizes)
        grouping = group_by_entity(ids, num_entities=24)
        caps, _ = capacity_classes(grouping.active_counts)
        # capacity of each entity under the GLOBAL ladder
        global_cap = {}
        full = bucket_entities(grouping, capacities=caps)
        for ent_b, rows_b in zip(full.entity_ids, full.row_indices):
            for e in ent_b:
                global_cap[int(e)] = rows_b.shape[1]
        # bucket an arbitrary SUBSET against the same explicit ladder:
        # every entity keeps its capacity (the sharded-prep invariant)
        subset = np.arange(0, 24, 3)
        keep = np.isin(ids, subset)
        sub_ids = np.searchsorted(subset, ids[keep])  # dense local ids
        sub_grouping = group_by_entity(sub_ids, num_entities=len(subset))
        sub = bucket_entities(sub_grouping, capacities=caps)
        for ent_b, rows_b in zip(sub.entity_ids, sub.row_indices):
            for e_local in ent_b:
                e = int(subset[int(e_local)])
                assert rows_b.shape[1] == global_cap[e], e

    def test_explicit_capacities_and_empty(self):
        from photon_ml_tpu.game.data import capacity_classes

        caps, pops = capacity_classes(
            np.asarray([3, 9, 17]), capacities=(4, 16, 32)
        )
        assert caps == (4, 16, 32)
        assert pops == (1, 1, 1)
        assert capacity_classes(np.zeros(5, np.int64)) == ((), ())
        with pytest.raises(ValueError, match="largest bucket capacity"):
            capacity_classes(np.asarray([100]), capacities=(4, 16))


class TestLaneFloorBitwise:
    """The sharded path's lane floor: a 1-real-lane launch padded with
    one all-masked dummy lane must give the real entity BITWISE the
    result it gets inside a larger batch (the batched XLA lowering),
    because that is what the single-process run produced for it."""

    def test_padded_single_lane_matches_batched_lane(self):
        import jax.numpy as jnp

        from photon_ml_tpu.config import OptimizerConfig
        from photon_ml_tpu.game.data import DenseFeatures, gather_bucket
        from photon_ml_tpu.game.random_effect import solve_bucket_lanes
        from photon_ml_tpu.ops.losses import loss_for_task
        from photon_ml_tpu.optim.common import select_minimize_fn
        from photon_ml_tpu.types import TaskType, VarianceComputationType

        rng = np.random.default_rng(5)
        k, C, d = 3, 8, 3
        X = rng.normal(size=(k * C, d)).astype(np.float32)
        y = (rng.uniform(size=k * C) < 0.5).astype(np.float32)
        offs = np.zeros(k * C, np.float32)
        wgt = np.ones(k * C, np.float32)
        rows = np.arange(k * C).reshape(k, C)
        feats = DenseFeatures(X=X)
        cfg = OptimizerConfig(max_iterations=6, tolerance=1e-9)
        loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
        minimize_fn, extra = select_minimize_fn(cfg, 0.0)
        common = dict(
            minimize_fn=minimize_fn, loss=loss, config=cfg,
            intercept_index=None,
            variance_computation=VarianceComputationType.SIMPLE,
            **extra,
        )
        l2 = jnp.asarray(1.0, jnp.float32)

        batched = solve_bucket_lanes(
            gather_bucket(feats, y, offs, wgt, rows),
            jnp.zeros((k, d), jnp.float32), l2, None, None, None, **common
        )
        # entity 0 alone + one dummy lane whose rows are all -1 (masked)
        rows_pad = np.stack([rows[0], np.full(C, -1, rows.dtype)])
        padded = solve_bucket_lanes(
            gather_bucket(feats, y, offs, wgt, rows_pad),
            jnp.zeros((2, d), jnp.float32), l2, None, None, None, **common
        )
        for b_out, p_out in zip(batched, padded):
            np.testing.assert_array_equal(
                np.asarray(b_out)[0], np.asarray(p_out)[0]
            )


class TestOwnedBucketMode:
    """PHOTON_RE_SHARD=1 under a (single-process) mesh: owned-bucket prep
    keeps lanes fully addressable — bitwise-identical to the unsharded
    solve, with the PR-5 compaction/fusion knobs now LEGAL under the
    mesh (the lifted gate) and the legacy knob-off schedule untouched."""

    @pytest.fixture()
    def problem(self):
        import jax.numpy as jnp

        from photon_ml_tpu.config import OptimizerConfig
        from photon_ml_tpu.game import bucket_entities, group_by_entity
        from photon_ml_tpu.game.data import DenseFeatures
        from photon_ml_tpu.ops.losses import loss_for_task
        from photon_ml_tpu.types import TaskType, VarianceComputationType

        rng = np.random.default_rng(11)
        n, E, d = 96, 12, 3
        ids = rng.integers(0, E, size=n).astype(np.int32)
        kwargs = dict(
            labels=(rng.uniform(size=n) < 0.5).astype(np.float32),
            offsets=np.zeros(n, np.float32),
            weights=np.ones(n, np.float32),
            buckets=bucket_entities(group_by_entity(ids, num_entities=E)),
            num_entities=E,
            loss=loss_for_task(TaskType.LOGISTIC_REGRESSION),
            config=OptimizerConfig(max_iterations=6, tolerance=1e-9),
            l2_weight=1.0,
            variance_computation=VarianceComputationType.SIMPLE,
        )
        feats = DenseFeatures(
            X=jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        )
        return feats, kwargs

    def test_owned_mesh_solve_is_bitwise(self, problem, monkeypatch):
        from photon_ml_tpu.game.random_effect import train_random_effects
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        ref = train_random_effects(feats, **kwargs)
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        got = train_random_effects(feats, mesh=data_mesh(), **kwargs)
        np.testing.assert_array_equal(
            np.asarray(got.coefficients), np.asarray(ref.coefficients)
        )
        np.testing.assert_array_equal(
            np.asarray(got.variances), np.asarray(ref.variances)
        )
        np.testing.assert_array_equal(got.iterations, ref.iterations)

    def test_gate_lift_compaction_fusion_apply_under_mesh(
        self, problem, monkeypatch
    ):
        """With the knob on, PHOTON_RE_COMPACT_EVERY/FUSE_BUCKETS run
        under a mesh (they were gated off before) — still bitwise."""
        from photon_ml_tpu.game.random_effect import train_random_effects
        from photon_ml_tpu.obs.metrics import REGISTRY
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        ref = train_random_effects(feats, **kwargs)
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        monkeypatch.setenv("PHOTON_RE_FUSE_BUCKETS", "1")
        monkeypatch.setenv("PHOTON_RE_COMPACT_EVERY", "2")

        def launches():
            return (
                REGISTRY.snapshot("re_solve.")["counters"]
                .get("re_solve.launches", {})
                .get("value", 0.0)
            )

        before = launches()
        got = train_random_effects(feats, mesh=data_mesh(), **kwargs)
        np.testing.assert_array_equal(
            np.asarray(got.coefficients), np.asarray(ref.coefficients)
        )
        np.testing.assert_array_equal(
            np.asarray(got.variances), np.asarray(ref.variances)
        )
        # the compacted chunk schedule actually ran (multiple launches
        # per fused unit), i.e. the knobs were NOT silently gated off
        assert launches() > before

    def test_knob_off_mesh_keeps_lane_sharded_schedule(
        self, problem, monkeypatch
    ):
        """Knob off: prepare_buckets still lane-shards over the mesh and
        assigns no owners — the legacy schedule, counter for counter."""
        from photon_ml_tpu.game.random_effect import prepare_buckets
        from photon_ml_tpu.parallel import data_mesh

        feats, kwargs = problem
        monkeypatch.delenv("PHOTON_RE_SHARD", raising=False)
        prepared = prepare_buckets(
            feats, kwargs["labels"], kwargs["weights"], kwargs["buckets"],
            data_mesh(),
        )
        assert all(pb.owner is None for pb in prepared)
        # lanes padded to divide the 8-device mesh axis
        assert all(
            pb.static.labels.shape[0] % 8 == 0 for pb in prepared
        )
        monkeypatch.setenv("PHOTON_RE_SHARD", "1")
        owned = prepare_buckets(
            feats, kwargs["labels"], kwargs["weights"], kwargs["buckets"],
            data_mesh(),
        )
        assert all(pb.owner == 0 for pb in owned)  # single process owns all
        assert all(
            pb.static.labels.shape[0] == pb.num_real for pb in owned
        )
