"""Native mmap index store tests: build/open/lookup/bulk/iterate, parity
with the pure-Python IndexMap, and persistence across handles."""

import numpy as np
import pytest

from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.native import NativeIndexStore, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain for the native store"
)


@pytest.fixture
def store_path(tmp_path):
    return str(tmp_path / "features.pidx")


class TestNativeIndexStore:
    def test_build_get_missing(self, store_path):
        s = NativeIndexStore.build(store_path, [("alpha", 0), ("beta", 1), ("g\x01us", 2)])
        assert s.size == 3
        assert s.get("alpha") == 0
        assert s.get("g\x01us") == 2
        assert s.get("nope") == -1
        assert "beta" in s and "nope" not in s

    def test_bulk_lookup(self, store_path):
        n = 5000
        items = [(f"feat_{i}\x01term_{i % 7}", i) for i in range(n)]
        s = NativeIndexStore.build(store_path, items)
        keys = [k for k, _ in items] + ["missing_1", "missing_2"]
        out = s.lookup_all(keys)
        np.testing.assert_array_equal(out[:n], np.arange(n))
        np.testing.assert_array_equal(out[n:], [-1, -1])

    def test_persistence_across_handles(self, store_path):
        NativeIndexStore.build(store_path, [("x", 7)]).close()
        s2 = NativeIndexStore(store_path)
        assert s2.get("x") == 7

    def test_items_roundtrip(self, store_path):
        items = {f"k{i}": i for i in range(100)}
        s = NativeIndexStore.build(store_path, items.items())
        assert dict(s.items()) == items

    def test_duplicate_key_rejected(self, store_path):
        with pytest.raises(ValueError, match="duplicate"):
            NativeIndexStore.build(store_path, [("a", 0), ("a", 1)])

    def test_parity_with_python_index_map(self, store_path, rng):
        keys = [f"name_{i}\x01term_{rng.integers(0, 5)}" for i in range(1000)]
        imap = IndexMap.build(keys, add_intercept=True)
        s = NativeIndexStore.build(store_path, imap.items())
        assert s.size == imap.size
        queries = np.array(keys[::7] + ["zzz_unknown"])
        np.testing.assert_array_equal(s.lookup_all(queries), imap.lookup_all(queries))

    def test_empty_store(self, store_path):
        s = NativeIndexStore.build(store_path, [])
        assert s.size == 0
        assert s.get("anything") == -1

    def test_unicode_keys(self, store_path):
        s = NativeIndexStore.build(store_path, [("héllo", 1), ("日本語", 2)])
        assert s.get("héllo") == 1
        assert s.get("日本語") == 2
